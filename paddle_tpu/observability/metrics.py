"""Metrics registry: counters, gauges, bucketed histograms, events.

One registry for every number the stack used to keep as ad-hoc
attributes (`eng.sync_wait_s`, `RetryStats`, chaos firing counters,
watchdog retirements). Design constraints:

- **bucketed histograms**, not sample lists: a serving process
  observing TTFT per request for days must hold O(buckets), not
  O(requests). Percentiles are linear interpolation inside the bucket
  containing the rank — exact to within one bucket's width (asserted
  against numpy quantiles in tests/test_observability.py);
- **thread-safe** (one lock per instrument): DataLoader workers,
  engine step threads, and the watchdog's abandoned workers all emit;
- **cheap when off**: the module-level `get_metrics()` is None unless
  FLAGS_metrics / PADDLE_TPU_METRICS armed it — instrumentation sites
  hold the result and do one `is None` check;
- three export surfaces: `snapshot()` (one nested dict), `emit_jsonl`
  (append one JSON line per snapshot — scrape-free logging), and
  `prometheus_text` (text exposition format 0.0.4 for a scrape
  endpoint).

Default latency buckets span 100us..60s exponentially — wide enough
for TTFT over a tunneled chip and tight enough (x2 steps) that a
bucket-interpolated p99 is a usable SLO number.
"""
from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Dict, List, Optional

__all__ = ["Counter", "Gauge", "Histogram", "MetricsRegistry",
           "get_metrics", "enable", "disable",
           "DEFAULT_LATENCY_BUCKETS_S"]

# 1e-4 .. 51.2s in x2 steps (+inf overflow bucket is implicit)
DEFAULT_LATENCY_BUCKETS_S = tuple(1e-4 * 2 ** i for i in range(20))


class Counter:
    """Monotonic counter."""

    __slots__ = ("name", "doc", "_lock", "_value")

    def __init__(self, name: str, doc: str = ""):
        self.name = name
        self.doc = doc
        self._lock = threading.Lock()
        self._value = 0.0

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self._value += n

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Gauge:
    """Point-in-time value (pool occupancy, queue depth)."""

    __slots__ = ("name", "doc", "_lock", "_value")

    def __init__(self, name: str, doc: str = ""):
        self.name = name
        self.doc = doc
        self._lock = threading.Lock()
        self._value = 0.0

    def set(self, v: float) -> None:
        with self._lock:
            self._value = float(v)

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Histogram:
    """Fixed-boundary bucketed histogram with interpolated percentiles.

    `bounds` are the UPPER edges of the finite buckets (ascending); one
    +inf overflow bucket rides at the end. `percentile(q)` walks the
    cumulative counts to the bucket containing rank q and interpolates
    linearly inside it (the overflow bucket reports its lower edge —
    there is no upper edge to interpolate toward; `max` is exact and
    tracked separately).
    """

    __slots__ = ("name", "doc", "bounds", "_lock", "counts", "count",
                 "sum", "min", "max")

    def __init__(self, name: str, doc: str = "",
                 bounds=DEFAULT_LATENCY_BUCKETS_S):
        bounds = tuple(float(b) for b in bounds)
        if not bounds or any(b2 <= b1 for b1, b2
                             in zip(bounds, bounds[1:])):
            raise ValueError(
                f"histogram bounds must be non-empty ascending, got "
                f"{bounds}")
        self.name = name
        self.doc = doc
        self.bounds = bounds
        self._lock = threading.Lock()
        self.counts = [0] * (len(bounds) + 1)  # +1: overflow (+inf)
        self.count = 0
        self.sum = 0.0
        self.min = None
        self.max = None

    def observe(self, v: float) -> None:
        v = float(v)
        # bisect over a ~20-entry tuple: fast enough, no numpy import
        lo, hi = 0, len(self.bounds)
        while lo < hi:
            mid = (lo + hi) // 2
            if v <= self.bounds[mid]:
                hi = mid
            else:
                lo = mid + 1
        with self._lock:
            self.counts[lo] += 1
            self.count += 1
            self.sum += v
            if self.min is None or v < self.min:
                self.min = v
            if self.max is None or v > self.max:
                self.max = v

    def _percentile_from(self, counts, count, vmin, vmax, q):
        """Percentile over a lock-consistent copy of the state
        (`bounds` is immutable, so only the mutables are copied)."""
        if not count:
            return None
        rank = q / 100.0 * count
        cum = 0
        for i, c in enumerate(counts):
            if not c:
                continue
            if cum + c >= rank:
                lo = self.bounds[i - 1] if i > 0 else 0.0
                if i < len(self.bounds):
                    hi = self.bounds[i]
                else:  # overflow bucket: no upper edge to interpolate
                    # toward — report its lower edge (clamped up to
                    # the exact min when ALL mass overflowed); only
                    # the terminal rank earns the exact max. Returning
                    # max for mid ranks would report p50 == max
                    # whenever the mass exceeds the top bound.
                    if rank >= count:
                        return vmax
                    return max(lo, vmin if vmin is not None else lo)
                frac = (rank - cum) / c
                return lo + (hi - lo) * min(max(frac, 0.0), 1.0)
            cum += c
        return vmax  # pragma: no cover - rank <= count always

    def _state(self):
        with self._lock:
            return list(self.counts), self.count, self.sum, \
                self.min, self.max

    def percentile(self, q: float) -> Optional[float]:
        """q in [0, 100]. None when empty."""
        counts, count, _, vmin, vmax = self._state()
        return self._percentile_from(counts, count, vmin, vmax, q)

    def percentiles(self, qs=(50, 90, 99)) -> Dict[str, Optional[float]]:
        counts, count, _, vmin, vmax = self._state()
        return {f"p{q:g}": self._percentile_from(counts, count, vmin,
                                                 vmax, q) for q in qs}

    def summary(self, qs=(50, 90, 99)) -> dict:
        """count/sum/min/max/mean + percentiles from ONE consistent
        read — a scrape racing `observe()` must not report a count
        that disagrees with the sum/percentiles next to it."""
        counts, count, s, vmin, vmax = self._state()
        out = {"count": count, "sum": s, "min": vmin, "max": vmax,
               "mean": s / count if count else None}
        for q in qs:
            out[f"p{q:g}"] = self._percentile_from(counts, count, vmin,
                                                   vmax, q)
        return out

    @property
    def mean(self) -> Optional[float]:
        with self._lock:
            return self.sum / self.count if self.count else None


class MetricsRegistry:
    """Name-keyed instruments + a bounded structured-event log.

    ::

        m = MetricsRegistry()
        m.counter("requests").inc()
        m.histogram("ttft_s").observe(0.12)
        m.event("watchdog.retire", slot=3, phase="decode")
        m.snapshot()   # one nested dict
    """

    MAX_EVENTS = 4096

    def __init__(self, max_events: int = MAX_EVENTS):
        self._lock = threading.Lock()
        self._counters: Dict[str, Counter] = {}
        self._gauges: Dict[str, Gauge] = {}
        self._histograms: Dict[str, Histogram] = {}
        self._events = deque(maxlen=max_events)
        self._t0 = time.time() - time.perf_counter()

    # -- instrument access (get-or-create, stable across threads) ------
    def counter(self, name: str, doc: str = "") -> Counter:
        with self._lock:
            c = self._counters.get(name)
            if c is None:
                c = self._counters[name] = Counter(name, doc)
            return c

    def gauge(self, name: str, doc: str = "") -> Gauge:
        with self._lock:
            g = self._gauges.get(name)
            if g is None:
                g = self._gauges[name] = Gauge(name, doc)
            return g

    def histogram(self, name: str, doc: str = "",
                  bounds=DEFAULT_LATENCY_BUCKETS_S) -> Histogram:
        with self._lock:
            h = self._histograms.get(name)
            if h is None:
                h = self._histograms[name] = Histogram(name, doc, bounds)
            return h

    def event(self, name: str, **fields) -> None:
        """Structured event (bounded log): resilience telemetry —
        chaos faults, watchdog retirements, retry give-ups — lands
        here with a wall-clock timestamp."""
        ev = {"event": name, "t": self._t0 + time.perf_counter()}
        if fields:
            ev.update(fields)
        with self._lock:
            self._events.append(ev)

    def events(self, name: Optional[str] = None) -> List[dict]:
        with self._lock:
            evs = list(self._events)
        return evs if name is None else [e for e in evs
                                         if e["event"] == name]

    # -- export ---------------------------------------------------------
    def snapshot(self) -> dict:
        """Everything as one nested dict (bench rows embed a subset)."""
        with self._lock:
            counters = {n: c.value for n, c in self._counters.items()}
            gauges = {n: g.value for n, g in self._gauges.items()}
            hists = list(self._histograms.items())
            n_events = len(self._events)
        out_h = {n: h.summary() for n, h in hists}
        return {"counters": counters, "gauges": gauges,
                "histograms": out_h, "n_events": n_events}

    def emit_jsonl(self, path, extra: Optional[dict] = None) -> None:
        """Append one snapshot as a JSON line (path or open file)."""
        doc = {"ts": time.time(), **(extra or {}), **self.snapshot()}
        line = json.dumps(doc) + "\n"
        if hasattr(path, "write"):
            path.write(line)
        else:
            with open(path, "a") as f:
                f.write(line)

    def prometheus_text(self, prefix: str = "paddle_tpu") -> str:
        """Prometheus text exposition format 0.0.4 (counters, gauges,
        and cumulative-bucket histograms with +Inf, _sum, _count)."""
        def san(n):
            return "".join(ch if ch.isalnum() or ch == "_" else "_"
                           for ch in n)

        lines = []
        with self._lock:
            counters = list(self._counters.items())
            gauges = list(self._gauges.items())
            hists = list(self._histograms.items())
        for n, c in counters:
            fq = f"{prefix}_{san(n)}_total"
            if c.doc:
                lines.append(f"# HELP {fq} {c.doc}")
            lines.append(f"# TYPE {fq} counter")
            lines.append(f"{fq} {c.value}")
        for n, g in gauges:
            fq = f"{prefix}_{san(n)}"
            if g.doc:
                lines.append(f"# HELP {fq} {g.doc}")
            lines.append(f"# TYPE {fq} gauge")
            lines.append(f"{fq} {g.value}")
        for n, h in hists:
            fq = f"{prefix}_{san(n)}"
            if h.doc:
                lines.append(f"# HELP {fq} {h.doc}")
            lines.append(f"# TYPE {fq} histogram")
            with h._lock:
                cum = 0
                for bound, cnt in zip(h.bounds, h.counts):
                    cum += cnt
                    lines.append(f'{fq}_bucket{{le="{bound:g}"}} {cum}')
                cum += h.counts[-1]
                lines.append(f'{fq}_bucket{{le="+Inf"}} {cum}')
                lines.append(f"{fq}_sum {h.sum}")
                lines.append(f"{fq}_count {h.count}")
        return "\n".join(lines) + "\n"


# -- global registry, armed by FLAGS_metrics / PADDLE_TPU_METRICS ------
_global: Optional[MetricsRegistry] = None
_resolved = False


def _resolve_from_flags():
    global _global
    try:
        from ..framework.flags import flag

        on = bool(flag("metrics"))
    except Exception:
        on = str(os.environ.get("PADDLE_TPU_METRICS", "")).lower() in (
            "1", "true", "yes", "on")
    if on:
        _global = MetricsRegistry()


def enable() -> MetricsRegistry:
    global _global, _resolved
    _resolved = True
    _global = MetricsRegistry()
    return _global


def disable() -> None:
    global _global, _resolved
    _global, _resolved = None, True


def get_metrics() -> Optional[MetricsRegistry]:
    """The armed global registry, or None (the disabled fast path —
    hold the result, check `is None` once per site). Like
    `trace.get_tracer`, the flag is re-read on every unarmed call so
    `set_flags({'metrics': True})` after first use still arms the
    registry; explicit `enable()`/`disable()` latches (`_resolved`)."""
    if _global is None and not _resolved:
        _resolve_from_flags()
    return _global
