"""Host-side span tracing with Perfetto/chrome://tracing export.

The one trace emitter the stack shares (ISSUE 8). Three writers used to
coexist — `profiler.Profiler.export`, `parallel/pipeline_viz.
save_chrome_trace`, and ad-hoc bench timing — each with its own JSON
assembly; they now all emit through `write_chrome_trace` here, and live
host spans are recorded by ONE `Tracer`:

- **monotonic-clock spans** (`time.perf_counter_ns`) in a bounded ring
  buffer (`collections.deque(maxlen=...)`): a long-serving engine can
  trace forever without growing memory — old spans fall off the back;
- **nested spans, per-thread tracks**: spans are chrome "X" complete
  events keyed by thread id, so Perfetto renders nesting per track from
  timestamp containment; `set_thread_name` labels the track;
- **structured instant events** (`instant`) and counter series
  (`counter`) for point-in-time facts (retire, eviction, chaos fault,
  watchdog retirement);
- **device bridging**: `span(..., device=True)` also enters
  `jax.profiler.TraceAnnotation` and `step_span` wraps
  `jax.profiler.StepTraceAnnotation`, so host spans align with the
  XPlane device trace when `jax.profiler.start_trace` is live (view
  both in Perfetto/TensorBoard on one timeline);
- **trace-safety guard** (lint rule TPU602): a span/instant emitted
  while jax is TRACING a program would bake a host callback — and a
  per-execution host round-trip — into the compiled artifact. Like
  `resilience.checkpoint`'s TPU601 trace guard, the recorder raises
  `TraceUnderJitError` at trace time instead; the static analyzer's
  TPU602 rule catches emitters smuggled in via explicit callbacks.

Activation: `FLAGS_trace` / `PADDLE_TPU_TRACE=<path>` arms the global
tracer and `export_global()` (atexit-registered on first use) writes
the chrome-trace JSON to `<path>`. When the flag is empty the module
functions are a single `is None` check — the disabled fast path
allocates nothing and is unmeasurable next to a device dispatch
(asserted by tests/test_observability.py and the `bench_continuous
--trace` overhead summary).
"""
from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Optional

__all__ = ["Tracer", "TraceUnderJitError", "write_chrome_trace",
           "merge_chrome_traces", "get_tracer", "enable", "disable",
           "span", "instant", "export_global"]


class TraceUnderJitError(RuntimeError):
    """A trace span/instant was emitted while jax was tracing a program
    (lint rule TPU602): the emitter would compile into the jitted
    artifact as a host callback and stall the device every execution.
    Trace on the HOST between dispatches, never inside traced code."""


def _under_jit() -> bool:
    """True when jax is mid-trace. Cheap (one C call) and import-lazy:
    a pure-host process that never imports jax never pays for it."""
    import sys

    jax = sys.modules.get("jax")
    if jax is None:
        return False
    try:
        return not jax.core.trace_state_clean()
    except Exception:  # pragma: no cover - very old/new jax
        return False


def write_chrome_trace(events, path: str, *, metadata: Optional[dict] = None,
                       display_time_unit: Optional[str] = None) -> str:
    """THE chrome://tracing / Perfetto JSON writer (JSON Object Format:
    {"traceEvents": [...]}). `profiler.Profiler.export` and
    `parallel.pipeline_viz.save_chrome_trace` both emit through here —
    one schema implementation, their output paths/filenames unchanged."""
    doc = {"traceEvents": list(events)}
    if display_time_unit:
        doc["displayTimeUnit"] = display_time_unit
    if metadata:
        doc["metadata"] = metadata
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "w") as f:
        json.dump(doc, f)
    return path


def merge_chrome_traces(paths, out: Optional[str] = None, *,
                        labels=None) -> dict:
    """Merge per-worker chrome traces into ONE Perfetto JSON document
    (the ROADMAP cross-host trace-merge follow-up, ISSUE 17).

    Every input file becomes one PROCESS in the merged timeline: its
    events are re-stamped ``pid=i`` (in-process fleet workers all share
    the real pid — without the re-stamp their tracks would interleave
    into one unreadable process) and a ``process_name`` metadata row
    names the track (``labels[i]`` or the file's basename). Wall-clock
    ``ts`` values are left untouched: all workers of one serving group
    share a clock, so cross-worker causality (kill -> requeue ->
    re-prefill) reads directly off the merged view. Returns the merged
    document; also writes it when `out` is given."""
    paths = list(paths)
    merged: list = []
    meta: dict = {"merged_from": []}
    for i, p in enumerate(paths):
        with open(p) as f:
            doc = json.load(f)
        if isinstance(doc, list):       # bare event-array form
            doc = {"traceEvents": doc}
        events = doc.get("traceEvents") or []
        label = labels[i] if labels and i < len(labels) else None
        if label is None:
            label = os.path.splitext(os.path.basename(p))[0]
        merged.append({"name": "process_name", "ph": "M", "pid": i,
                       "tid": 0, "args": {"name": label}})
        for ev in events:
            ev = dict(ev)
            ev["pid"] = i
            merged.append(ev)
        meta["merged_from"].append({"pid": i, "label": label,
                                    "path": str(p)})
        for k, v in (doc.get("metadata") or {}).items():
            meta.setdefault(k, v)
    doc = {"traceEvents": merged, "metadata": meta}
    if out:
        write_chrome_trace(merged, out, metadata=meta)
    return doc


class _SpanHandle:
    """Context manager for one live span (created only when tracing is
    ON — the disabled path never reaches here)."""

    __slots__ = ("tracer", "name", "args", "t0", "_ann")

    def __init__(self, tracer: "Tracer", name: str, args: dict,
                 device: bool):
        self.tracer = tracer
        self.name = name
        self.args = args
        self.t0 = 0
        self._ann = None
        if device:
            try:
                import jax

                self._ann = jax.profiler.TraceAnnotation(name)
            except Exception:  # pragma: no cover - no jax / no profiler
                self._ann = None

    def __enter__(self):
        if _under_jit():
            raise TraceUnderJitError(
                f"span {self.name!r} opened while jax is tracing a "
                "program: the emitter would compile into the jitted "
                "artifact (lint rule TPU602); trace on the host "
                "between dispatches instead")
        if self._ann is not None:
            self._ann.__enter__()
        self.t0 = time.perf_counter_ns()
        return self

    def __exit__(self, *exc):
        t1 = time.perf_counter_ns()
        if self._ann is not None:
            self._ann.__exit__(*exc)
        self.tracer._record_complete(self.name, self.t0, t1, self.args)
        return False


class Tracer:
    """Thread-safe bounded span recorder with chrome-trace export.

    ::

        tr = Tracer(capacity=65536)
        with tr.span("decode.dispatch", chunk=n):
            ...
        tr.instant("req.retire", req_id=7)
        tr.export("trace.json")
    """

    def __init__(self, capacity: int = 65536, pid: Optional[int] = None):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = int(capacity)
        self.pid = os.getpid() if pid is None else int(pid)
        self._lock = threading.Lock()
        self._events = deque(maxlen=self.capacity)
        self._thread_names = {}  # tid -> name (metadata, never evicted)
        self.dropped = 0         # spans the ring buffer evicted
        self.n_recorded = 0

    # -- recording -----------------------------------------------------
    def span(self, name: str, device: bool = False, **args) -> _SpanHandle:
        """Context manager recording a complete ("X") span on this
        thread's track. `device=True` additionally enters
        `jax.profiler.TraceAnnotation(name)` so the span shows up in a
        live XPlane device trace."""
        return _SpanHandle(self, name, args, device)

    def step_span(self, name: str, step: int) -> _SpanHandle:
        """Span for one training/serving step, bridged to
        `jax.profiler.StepTraceAnnotation` (the annotation XProf's step
        views key on) when a device trace is live."""
        h = _SpanHandle(self, name, {"step": int(step)}, device=False)
        try:
            import jax

            h._ann = jax.profiler.StepTraceAnnotation(name, step_num=step)
        except Exception:  # pragma: no cover
            h._ann = None
        return h

    def instant(self, name: str, **args) -> None:
        """Structured point-in-time event ("i" phase, thread scope)."""
        if _under_jit():
            raise TraceUnderJitError(
                f"instant {name!r} emitted while jax is tracing a "
                "program (lint rule TPU602)")
        ev = {"name": name, "ph": "i", "s": "t",
              "ts": time.perf_counter_ns() / 1e3,
              "pid": self.pid, "tid": threading.get_ident()}
        if args:
            ev["args"] = args
        self._push(ev)

    def counter(self, name: str, value) -> None:
        """Counter-series sample ("C" phase) — Perfetto renders these as
        a stacked value track."""
        if _under_jit():
            raise TraceUnderJitError(
                f"counter {name!r} sampled while jax is tracing a "
                "program (lint rule TPU602): it would record ONE "
                "trace-time point, never a per-execution series")
        self._push({"name": name, "ph": "C",
                    "ts": time.perf_counter_ns() / 1e3, "pid": self.pid,
                    "tid": threading.get_ident(),
                    "args": {"value": float(value)}})

    def complete(self, name: str, t0_ns: int, t1_ns: int, **args) -> None:
        """Record an already-measured interval retroactively (the
        engine's sync-wait is timed anyway; this avoids a second pair
        of clock reads)."""
        if _under_jit():
            raise TraceUnderJitError(
                f"complete {name!r} recorded while jax is tracing a "
                "program (lint rule TPU602)")
        self._record_complete(name, t0_ns, t1_ns, args)

    def set_thread_name(self, name: str, tid: Optional[int] = None) -> None:
        with self._lock:
            self._thread_names[tid if tid is not None
                               else threading.get_ident()] = str(name)

    # -- internals -----------------------------------------------------
    def _record_complete(self, name, t0_ns, t1_ns, args):
        ev = {"name": name, "ph": "X", "ts": t0_ns / 1e3,
              "dur": max(t1_ns - t0_ns, 0) / 1e3,
              "pid": self.pid, "tid": threading.get_ident()}
        if args:
            ev["args"] = args
        self._push(ev)

    def _push(self, ev):
        with self._lock:
            if len(self._events) == self.capacity:
                self.dropped += 1
            self._events.append(ev)
            self.n_recorded += 1

    # -- export --------------------------------------------------------
    def events(self) -> list:
        """Snapshot of buffered events (metadata rows first)."""
        with self._lock:
            meta = [{"name": "thread_name", "ph": "M", "pid": self.pid,
                     "tid": tid, "args": {"name": nm}}
                    for tid, nm in sorted(self._thread_names.items())]
            return meta + list(self._events)

    def clear(self) -> None:
        with self._lock:
            self._events.clear()
            self.dropped = 0

    def export(self, path: str, metadata: Optional[dict] = None) -> str:
        md = {"n_recorded": self.n_recorded, "dropped": self.dropped}
        if metadata:
            md.update(metadata)
        return write_chrome_trace(self.events(), path, metadata=md,
                                  display_time_unit="ms")


# -- global tracer, armed by FLAGS_trace / PADDLE_TPU_TRACE=<path> -----
_global: Optional[Tracer] = None
_global_path: Optional[str] = None
_resolved = False
_atexit_armed = False


def _resolve_from_flags():
    try:
        from ..framework.flags import flag

        path = str(flag("trace")).strip()
    except Exception:
        path = os.environ.get("PADDLE_TPU_TRACE", "").strip()
    if path:
        enable(path)


def enable(path: Optional[str] = None, capacity: int = 65536) -> Tracer:
    """Arm the global tracer (programmatic equivalent of
    PADDLE_TPU_TRACE=<path>); `path` is where `export_global` lands."""
    global _global, _global_path, _resolved, _atexit_armed
    _resolved = True
    _global = Tracer(capacity=capacity)
    _global_path = path
    if path and not _atexit_armed:
        import atexit

        atexit.register(export_global)
        _atexit_armed = True
    return _global


def disable() -> None:
    global _global, _global_path, _resolved
    _global, _global_path, _resolved = None, None, True


def get_tracer() -> Optional[Tracer]:
    """The armed global tracer, or None (THE disabled fast path: every
    instrumentation site holds this result and does one `is None`
    check per event). The flag is re-read on every unarmed call — a
    registry dict lookup — so `set_flags({'trace': ...})` AFTER some
    earlier instrumented call still arms tracing; only an explicit
    `enable()`/`disable()` latches the decision (`_resolved`)."""
    if _global is None and not _resolved:
        _resolve_from_flags()
    return _global


class _NullSpan:
    """Singleton no-op context manager — `span()` with tracing off
    returns this one shared object, allocating nothing."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


def span(name: str, **args):
    """Module-level span against the global tracer; a shared no-op when
    tracing is off."""
    tr = get_tracer()
    return _NULL_SPAN if tr is None else tr.span(name, **args)


def instant(name: str, **args) -> None:
    tr = get_tracer()
    if tr is not None:
        tr.instant(name, **args)


def export_global(path: Optional[str] = None) -> Optional[str]:
    """Write the global tracer's buffer to `path` (default: the
    FLAGS_trace path). No-op when tracing is off."""
    tr = get_tracer()
    if tr is None:
        return None
    p = path or _global_path
    return tr.export(p) if p else None
