"""paddle_tpu.observability: unified tracing + metrics (ISSUE 8).

Two halves, one activation story:

- `trace` — thread-safe monotonic-clock span recorder (bounded ring
  buffer, nested spans on per-thread tracks, instant/counter events)
  with Perfetto/chrome://tracing export and `jax.profiler` bridging.
  Armed by `FLAGS_trace` / ``PADDLE_TPU_TRACE=<path>`` (the export
  path); `trace.enable(path)` programmatically.
- `metrics` — registry of counters / gauges / bucketed histograms
  (TTFT, time-per-output-token, queue wait, prefill/decode chunk time,
  sync wait) plus a bounded structured-event log that folds the
  resilience telemetry (RetryStats give-ups, chaos firings, watchdog
  retirements, preemptions) into one place. `snapshot()` for dicts,
  `emit_jsonl()` for logging, `prometheus_text()` for scraping. Armed
  by `FLAGS_metrics` / ``PADDLE_TPU_METRICS=1``; `metrics.enable()`
  programmatically.

Both are OFF by default with a compiled-out-style fast path: every
instrumentation site resolves `get_tracer()` / `get_metrics()` once
and does a single ``is None`` check per event — disabled overhead is
unmeasurable (< 2% tokens/s on `bench_continuous`, asserted by its
``--trace`` summary line). Emitting a span while jax is TRACING raises
`TraceUnderJitError` (lint rule TPU602) — tracing must never compile
into a program.

Instrumented out of the box: the serving engine's full request
lifecycle (enqueue → admit → prefill dispatch/commit → handoff →
per-chunk decode → retire, eviction + watchdog retirement + stall
spans), `hapi.Model.fit` step phases (data fetch, step dispatch,
checkpoint save), and the resilience seams. See README.md here for
the span taxonomy and the Perfetto workflow.
"""
from __future__ import annotations

from . import metrics, trace  # noqa: F401
from .metrics import (Counter, Gauge, Histogram,  # noqa: F401
                      MetricsRegistry, get_metrics)
from .trace import (Tracer, TraceUnderJitError,  # noqa: F401
                    get_tracer, merge_chrome_traces, write_chrome_trace)

__all__ = ["trace", "metrics", "Tracer", "TraceUnderJitError",
           "MetricsRegistry", "Counter", "Gauge", "Histogram",
           "get_tracer", "get_metrics", "record_event",
           "write_chrome_trace", "merge_chrome_traces"]


def record_event(name: str, **fields) -> None:
    """Fire-and-forget structured event into BOTH armed sinks (metrics
    event log + trace instant). The one-liner the resilience modules
    call from their hot paths — a no-op (two None checks) when
    observability is off, and never raises: telemetry must not take
    down the step it observes (except under jax tracing, where the
    TPU602 guard in `trace.instant` must propagate)."""
    m = metrics.get_metrics()
    if m is not None:
        try:
            m.event(name, **fields)
        except Exception:  # pragma: no cover - defensive
            pass
    t = trace.get_tracer()
    if t is not None:
        t.instant(name, **fields)
