"""paddle_tpu: a TPU-native deep-learning framework with PaddlePaddle's
capabilities, built from scratch on JAX/XLA/Pallas/pjit.

Public surface mirrors `import paddle` (reference:
python/paddle/__init__.py): tensor ops at top level, plus nn / optimizer /
autograd / amp / io / jit / static / distributed / vision / ... subpackages.
"""
from __future__ import annotations

__version__ = "0.1.0"

# dtypes (paddle.float32 etc.)
from .framework.dtype import (  # noqa: F401
    bfloat16, float16, float32, float64, float8_e4m3fn, float8_e5m2,
    int8, int16, int32, int64, uint8, bool_ as bool8, complex64, complex128,
)
from .framework.dtype import bool_  # noqa: F401
uint16 = __import__("numpy").dtype("uint16")

from .framework import (  # noqa: F401
    seed, get_rng_state, set_rng_state, set_default_dtype, get_default_dtype,
    set_flags, get_flags, iinfo, finfo,
)
from .core import (  # noqa: F401
    Tensor, Parameter, to_tensor, no_grad, enable_grad, set_grad_enabled,
    grad_enabled,
)

# every tensor op into the top-level namespace (paddle.add, paddle.matmul, ...)
from .ops import *  # noqa: F401,F403
from .ops import __all__ as _ops_all
from . import ops  # noqa: F401

from . import autograd  # noqa: F401
from . import nn  # noqa: F401
from . import optimizer  # noqa: F401
from . import amp  # noqa: F401
from . import io  # noqa: F401
from . import device  # noqa: F401
from . import jit  # noqa: F401
from . import static  # noqa: F401
from . import framework  # noqa: F401
from . import parallel  # noqa: F401
from . import parallel as distributed  # noqa: F401
from . import incubate  # noqa: F401
from . import resilience  # noqa: F401
from . import kernels  # noqa: F401
from . import vision  # noqa: F401
from . import metric  # noqa: F401
from . import hapi  # noqa: F401
from . import distribution  # noqa: F401
from . import observability  # noqa: F401
from . import profiler  # noqa: F401
from . import sparse  # noqa: F401
from . import quantization  # noqa: F401
from . import audio  # noqa: F401
from . import models  # noqa: F401
from . import inference  # noqa: F401
from . import text  # noqa: F401
from . import onnx  # noqa: F401
from . import utils  # noqa: F401
from . import cost_model  # noqa: F401
from . import callbacks  # noqa: F401
from . import hub  # noqa: F401
from . import regularizer  # noqa: F401
from . import sysconfig  # noqa: F401
from . import version  # noqa: F401
from . import geometric  # noqa: F401
from . import fft  # noqa: F401
from . import signal  # noqa: F401

# public-surface aliases (reference top-level __all__ parity)
from .nn.initializer import ParamAttr  # noqa: F401
from .autograd import grad  # noqa: F401
from .framework import get_rng_state as get_cuda_rng_state  # noqa: F401
from .framework import set_rng_state as set_cuda_rng_state  # noqa: F401
bool = bool_  # noqa: F821  (paddle.bool dtype alias)
dtype = __import__("numpy").dtype


def create_parameter(shape, dtype="float32", name=None, attr=None,
                     is_bias=False, default_initializer=None):
    """reference: paddle.create_parameter (static nn helper)."""
    from .nn.initializer import Constant, XavierNormal, _resolve_param_attr

    attr = _resolve_param_attr(attr)
    init = (attr.initializer if attr and attr.initializer else
            default_initializer or (Constant(0.0) if is_bias
                                    else XavierNormal()))
    arr = init(tuple(int(s) for s in shape),
               __import__("numpy").dtype(dtype))
    return Parameter(arr, dtype=dtype, name=name or (attr.name if attr
                                                     else None))


class LazyGuard:
    """reference: paddle.LazyGuard — delayed parameter initialization.
    Eager TPU init is cheap (arrays materialize lazily in XLA), so this is
    a no-op context for API parity."""

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


def batch(reader, batch_size, drop_last=False):
    """reference: paddle.batch (legacy reader decorator)."""
    def gen():
        buf = []
        for item in reader():
            buf.append(item)
            if len(buf) == batch_size:
                yield buf
                buf = []
        if buf and not drop_last:
            yield buf

    return gen
from .hapi import Model, summary  # noqa: F401
from .hapi.flops import flops  # noqa: F401
import sys as _sys0
# alias paddle_tpu.distributed (and every submodule) to paddle_tpu.parallel
# so both import paths resolve to the SAME module objects
for _k in [k for k in list(_sys0.modules) if k.startswith(__name__ + ".parallel")]:
    _sys0.modules[_k.replace(".parallel", ".distributed", 1)] = _sys0.modules[_k]
_sys0.modules[__name__ + ".distributed"] = distributed
from .parallel.data_parallel import DataParallel  # noqa: F401
from . import linalg_ns as linalg  # noqa: F401
from .framework.io import save, load  # noqa: F401
from .device import set_device, get_device, is_compiled_with_cuda, is_compiled_with_xpu, is_compiled_with_tpu  # noqa: F401
from .jit.api import to_static  # noqa: F401

import sys as _sys


def in_dynamic_mode() -> bool:
    """Always true: the framework is eager-first; `to_static` jits functions
    without a global static mode (reference: paddle.in_dynamic_mode)."""
    return not static._static_mode[0]


def enable_static():
    static._static_mode[0] = True


def disable_static():
    static._static_mode[0] = False


def is_grad_enabled():
    return grad_enabled()


def disable_signal_handler():  # paddle API parity; no-op
    return None


def CUDAPinnedPlace(*a, **k):  # compat shims: places are strings on TPU
    return "cpu"


def CPUPlace(*a, **k):
    return "cpu"


def TPUPlace(idx=0):
    return f"tpu:{idx}"


CUDAPlace = TPUPlace

__all__ = (
    ["Tensor", "Parameter", "to_tensor", "no_grad", "enable_grad", "seed",
     "save", "load", "set_device", "get_device", "to_static",
     "in_dynamic_mode", "enable_static", "disable_static"]
    + list(_ops_all)
)


def _patch_remaining_tensor_methods():
    """Bind the rest of the reference's tensor_method_func list
    (python/paddle/tensor/__init__.py:282) onto Tensor. Like the
    reference's monkey-patch, each method IS the namesake free function
    with the tensor as first argument; names live in the top-level,
    linalg, signal, or static namespaces."""
    from .core.tensor import Tensor

    names = [
        "create_parameter", "create_tensor", "ormqr", "cholesky_inverse",
        "histogram_bin_edges", "histogramdd", "householder_product",
        "pca_lowrank", "svd_lowrank", "eigvalsh", "logit", "increment",
        "multiplex", "sinc", "reduce_as", "multigammaln", "hypot",
        "block_diag", "add_n", "isneginf", "isposinf", "isreal",
        "broadcast_shape", "gammaincc", "gammainc", "is_empty",
        "not_equal_", "is_tensor", "concat", "reverse", "scatter_nd",
        "shard_index", "slice", "slice_scatter", "tensor_split", "hsplit",
        "dsplit", "vsplit", "stack", "unstack", "top_p_sampling",
        "is_complex", "is_integer", "rank", "real", "imag",
        "is_floating_point", "gammaln", "broadcast_tensors", "multi_dot",
        "lu_unpack", "cdist", "as_complex", "as_real", "select_scatter",
        "put_along_axis_", "take", "sgn", "frexp", "ldexp", "trapezoid",
        "cumulative_trapezoid", "polar", "vander", "nextafter",
        "unflatten", "as_strided", "i0", "i0e", "i1", "i1e", "polygamma",
        "multinomial", "renorm", "stft", "istft", "copysign",
        "bitwise_left_shift", "bitwise_right_shift", "index_fill_",
        "atleast_1d", "atleast_2d", "atleast_3d", "diagonal_scatter",
        "signbit",
    ]
    namespaces = [globals(), vars(linalg), vars(signal), vars(fft),
                  vars(static)]
    for name in names:
        if hasattr(Tensor, name):
            continue
        for ns in namespaces:
            fn = ns.get(name)
            if callable(fn):
                setattr(Tensor, name, fn)
                break


def _define_tensor_method_stragglers():
    """The five names with no existing free-function form."""
    import jax.numpy as _jnp
    import numpy as _np

    from .core.tensor import Tensor

    def create_tensor(self, dtype="float32", name=None, persistable=False):
        # reference: tensor/creation.py create_tensor — an empty typed var
        return Tensor(_jnp.zeros((0,), _np.dtype(dtype)))

    def histogram_bin_edges(self, bins=100, min=0, max=0, name=None):
        a = _np.asarray(self.numpy())
        rng = None if (min == 0 and max == 0) else (min, max)
        return Tensor(_jnp.asarray(
            _np.histogram_bin_edges(a, bins=bins, range=rng)
            .astype(_np.float32)))

    def _inplace_of(fn_name):
        def method(self, *a, **k):
            out = getattr(__import__("paddle_tpu"), fn_name)(self, *a, **k)
            return self._replace(out._array, out._node, out._out_idx)
        return method

    Tensor.create_tensor = create_tensor
    Tensor.histogram_bin_edges = histogram_bin_edges
    Tensor.not_equal_ = _inplace_of("not_equal")
    Tensor.put_along_axis_ = _inplace_of("put_along_axis")
    Tensor.index_fill_ = _inplace_of("index_fill")


_patch_remaining_tensor_methods()
_define_tensor_method_stragglers()
