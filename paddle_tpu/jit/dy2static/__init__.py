"""paddle.jit.dy2static (reference: python/paddle/jit/dy2static/__init__.py).

The reference converts Python source via AST + bytecode (SOT). The TPU
analog traces with jax and specializes per control-flow path on graph
breaks (jit/api.py StaticFunction); these names adapt that machinery."""
from ..api import StaticFunction, to_static  # noqa: F401

__all__ = ["StaticFunction", "to_static"]


class Call:
    """reference: dy2static/convert_call_func.py — conversion is implicit
    under tracing; kept callable for generated-code parity."""

    def __init__(self, fn):
        self.fn = fn

    def __call__(self, *args, **kwargs):
        return self.fn(*args, **kwargs)
