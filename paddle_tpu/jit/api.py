"""paddle.jit.to_static — the TPU-native jit story.

Reference: python/paddle/jit/api.py:182 (`to_static`) with two front-ends:
AST transform (dy2static/program_translator.py:783) and the SOT bytecode
tracer (jit/sot/). On TPU neither is needed: because *every* op funnels
through the pure-jnp dispatch layer, plain `jax.jit` tracing of the user
function is the graph capture. What we keep from SOT is its *contract* —
guard-based re-specialisation and a compiled-program cache
(jit/sot/opcode_translator/executor/guard.py, executor_cache.py): the cache
key ("guard") is the treedef + shape/dtype of tensor args plus the values of
plain-Python args, and a miss re-traces instead of graph-breaking.

Training is supported: the traced callable is routed through core dispatch,
so `jax.vjp` of the jitted function records on the eager tape and
`loss.backward()` works across a to_static boundary. Layer buffers (e.g.
BatchNorm running stats) are threaded as extra outputs and written back.
"""
from __future__ import annotations

import functools
import inspect
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor, dispatch, unwrap
from ..core import tape as _tape
from ..framework import random as _random


def _guard_key(args, kwargs):
    """Build the specialisation key (SOT guard analog)."""

    def leaf_key(x):
        if isinstance(x, Tensor):
            return ("T", tuple(x.shape), str(x.dtype), x.stop_gradient)
        if isinstance(x, (jax.Array, np.ndarray)):
            return ("A", tuple(x.shape), str(x.dtype))
        if isinstance(x, (int, float, bool, str, bytes, type(None))):
            return ("P", x)
        if isinstance(x, (list, tuple)):
            return (type(x).__name__, tuple(leaf_key(i) for i in x))
        if isinstance(x, dict):
            return ("D", tuple(sorted((k, leaf_key(v)) for k, v in x.items())))
        return ("O", id(type(x)))

    return (tuple(leaf_key(a) for a in args), leaf_key(kwargs))


_TO_STATIC_ENABLED = True


def enable_to_static(enable: bool = True):
    """Global switch (reference: jit/api.py `enable_to_static`): when off,
    every StaticFunction runs its original eager python body."""
    global _TO_STATIC_ENABLED
    _TO_STATIC_ENABLED = bool(enable)


class StaticFunction:
    """Compiled-function wrapper (reference:
    python/paddle/jit/dy2static/program_translator.py:711
    `SymbolicStaticFunction.__call__`)."""

    def __init__(self, fn: Callable, input_spec=None, build_strategy=None, full_graph=True, layer=None):
        self._fn = fn
        self._layer = layer
        self._input_spec = input_spec
        self._full_graph = full_graph
        self._fallback_keys = set()  # guard keys that graph-broke
        self._cache = {}  # guard key -> (jitted, n_params, n_buffers, out_treedef)
        functools.update_wrapper(self, fn)

    @property
    def layer(self):
        if self._layer is not None:
            return self._layer
        # bound method of a Layer?
        self_obj = getattr(self._fn, "__self__", None)
        from ..nn.layer.layers import Layer

        if isinstance(self_obj, Layer):
            return self_obj
        return None

    def _collect_state(self):
        layer = self.layer
        if layer is None:
            return [], []
        params = list(layer.parameters(include_sublayers=True))
        buffers = [b for _, b in layer.named_buffers()]
        return params, buffers

    def __call__(self, *args, **kwargs):
        if not _TO_STATIC_ENABLED:
            return self._fn(*args, **kwargs)
        params, buffers = self._collect_state()
        key = _guard_key(args, kwargs)
        if key in self._fallback_keys:
            return self._fn(*args, **kwargs)
        entry = self._cache.get(key)
        if entry is None:
            try:
                entry = self._trace(params, buffers, args, kwargs)
            except (jax.errors.TracerBoolConversionError,
                    jax.errors.ConcretizationTypeError,
                    jax.errors.TracerArrayConversionError,
                    jax.errors.TracerIntegerConversionError) as e:
                # SOT graph-break contract: untraceable python (data-
                # dependent control flow, .numpy() mid-graph) falls back
                # to eager for this guard instead of erroring
                if self._full_graph:
                    raise
                import warnings
                warnings.warn(
                    f"to_static: graph break in {self._fn.__name__} "
                    f"({type(e).__name__}); running this specialisation "
                    "eagerly")
                self._fallback_keys.add(key)
                return self._fn(*args, **kwargs)
            self._cache[key] = entry
        jitted, out_treedef, n_out = entry

        flat_args, _ = jax.tree.flatten(
            (args, kwargs), is_leaf=lambda x: isinstance(x, Tensor)
        )
        tensor_args = [a for a in flat_args if isinstance(a, Tensor)]

        # thread a fresh PRNG key so dropout etc. varies between calls without
        # retracing (keys-as-generator; see framework/random.py)
        all_inputs = [_random.next_key()] + params + tensor_args + buffers

        try:
            outs = dispatch(f"to_static:{self._fn.__name__}", jitted,
                            tuple(all_inputs))
        except jax.errors.JaxRuntimeError as e:
            # some PJRT runtimes (e.g. tunneled single-chip dev backends)
            # reject host callbacks inside compiled programs; treat that as
            # a graph break rather than a hard failure
            if "does not support host send/recv" not in str(e):
                raise
            if self._full_graph:
                raise
            import warnings
            warnings.warn(
                f"to_static: graph break in {self._fn.__name__} (backend "
                "does not support host callbacks under jit); running this "
                "specialisation eagerly")
            self._fallback_keys.add(key)
            self._cache.pop(key, None)
            return self._fn(*args, **kwargs)
        if not isinstance(outs, tuple):
            outs = (outs,)
        # write back updated buffers
        new_buf = outs[n_out:]
        for b, nb in zip(buffers, new_buf):
            b._replace(nb._array)
        result = jax.tree.unflatten(out_treedef, list(outs[:n_out]))
        return result

    def _trace(self, params, buffers, args, kwargs):
        fn = self._fn
        n_p, n_b = len(params), len(buffers)
        flat_args, args_treedef = jax.tree.flatten(
            (args, kwargs), is_leaf=lambda x: isinstance(x, Tensor)
        )
        tensor_pos = [i for i, a in enumerate(flat_args) if isinstance(a, Tensor)]
        const_args = [a if not isinstance(a, Tensor) else None for a in flat_args]

        out_info = {}

        def pure(key, *arrays):
            p_arr = arrays[:n_p]
            t_arr = arrays[n_p : n_p + len(tensor_pos)]
            b_arr = arrays[n_p + len(tensor_pos) :]
            # bind state
            saved_p = [p._array for p in params]
            saved_b = [b._array for b in buffers]
            for p, a in zip(params, p_arr):
                p._array = a
            for b, a in zip(buffers, b_arr):
                b._array = a
            flat = list(const_args)
            for pos, a in zip(tensor_pos, t_arr):
                t = Tensor(a)
                t.stop_gradient = flat_args[pos].stop_gradient
                flat[pos] = t
            call_args, call_kwargs = jax.tree.unflatten(args_treedef, flat)
            try:
                with _tape.no_grad(), _random.rng_scope(key):
                    out = fn(*call_args, **call_kwargs)
            finally:
                new_b = [b._array for b in buffers]
                for p, a in zip(params, saved_p):
                    p._array = a
                for b, a in zip(buffers, saved_b):
                    b._array = a
            out_leaves, out_treedef = jax.tree.flatten(
                out, is_leaf=lambda x: isinstance(x, Tensor)
            )
            out_info["treedef"] = out_treedef
            out_info["n"] = len(out_leaves)
            return tuple(unwrap(o) for o in out_leaves) + tuple(new_b)

        jitted = jax.jit(pure)
        # prime: trace once at aval level (no execution) to learn out structure
        jax.eval_shape(
            pure,
            _random.next_key(),
            *[unwrap(p) for p in params],
            *[unwrap(flat_args[i]) for i in tensor_pos],
            *[unwrap(b) for b in buffers],
        )
        return jitted, out_info["treedef"], out_info["n"]

    # paddle parity helpers
    @property
    def code(self):
        return inspect.getsource(self._fn)

    def concrete_program_specify_input_spec(self, *a, **k):
        return None


def to_static(function=None, input_spec=None, build_strategy=None, backend=None, **kwargs):
    """paddle.jit.to_static (ref: python/paddle/jit/api.py:182).
    `full_graph=False` (the default, like the reference's SOT front-end)
    permits graph breaks: specialisations that cannot trace run eagerly."""
    full_graph = kwargs.pop("full_graph", False)

    def decorate(fn):
        from ..nn.layer.layers import Layer

        if isinstance(fn, Layer):
            layer = fn
            sf = StaticFunction(layer.forward, input_spec=input_spec,
                                full_graph=full_graph, layer=layer)
            layer.forward = sf
            return layer
        return StaticFunction(fn, input_spec=input_spec,
                              full_graph=full_graph)

    if function is not None:
        return decorate(function)
    return decorate


def not_to_static(fn):
    fn._not_to_static = True
    return fn


def ignore_module(modules):
    return None


# ---------------------------------------------------------------------------
# jit.save / jit.load — deployment artifacts via StableHLO export
# ---------------------------------------------------------------------------


def save(layer, path, input_spec=None, **configs):
    """paddle.jit.save (ref: python/paddle/jit/api.py, TranslatedLayer
    artifacts). Serialises params (pickle) + a StableHLO export of the
    forward function when input_spec is given."""
    import pickle
    from ..framework.io import save as fsave

    fsave(layer.state_dict(), path + ".pdiparams")
    meta = {"class": type(layer).__name__}
    if input_spec:
        try:
            from jax import export as jexport

            params = [unwrap(p) for p in layer.parameters()]

            def pure(params_arr, *xs):
                saved = [p._array for p in layer.parameters()]
                for p, a in zip(layer.parameters(), params_arr):
                    p._array = a
                try:
                    with _tape.no_grad():
                        out = layer(*[Tensor(x) for x in xs])
                finally:
                    for p, a in zip(layer.parameters(), saved):
                        p._array = a
                return unwrap(out)

            specs = [
                jax.ShapeDtypeStruct(tuple(s.shape), s.dtype) for s in input_spec
            ]
            # multi-platform artifact: the deployment shell (native/
            # predictor_capi.cpp) may serve on a different backend than
            # the one that exported
            exported = jexport.export(
                jax.jit(pure), platforms=("cpu", "tpu"))(
                [jax.ShapeDtypeStruct(p.shape, p.dtype) for p in params], *specs
            )
            with open(path + ".pdmodel", "wb") as f:
                f.write(exported.serialize())
            meta["stablehlo"] = True
        except Exception as e:  # pragma: no cover
            meta["stablehlo"] = False
            meta["export_error"] = repr(e)
    with open(path + ".pdmeta", "wb") as f:
        pickle.dump(meta, f)


class TranslatedLayer:
    """Loaded inference artifact (ref: python/paddle/jit/translated_layer.py)."""

    def __init__(self, exported, state_dict):
        self._exported = exported
        self._state = state_dict

    def __call__(self, *xs):
        params = [unwrap(v) for v in self._state.values()]
        out = self._exported.call(params, *[unwrap(x) for x in xs])
        return Tensor(out) if not isinstance(out, (tuple, list)) else tuple(Tensor(o) for o in out)

    def state_dict(self):
        return self._state


def load(path, **configs):
    import pickle
    from ..framework.io import load as fload

    state = fload(path + ".pdiparams")
    with open(path + ".pdmeta", "rb") as f:
        meta = pickle.load(f)
    if meta.get("stablehlo"):
        from jax import export as jexport

        with open(path + ".pdmodel", "rb") as f:
            exported = jexport.deserialize(f.read())
        return TranslatedLayer(exported, state)
    raise ValueError(f"no serialized program at {path}.pdmodel; re-save with input_spec")
