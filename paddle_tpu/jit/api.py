"""paddle.jit.to_static — the TPU-native jit story.

Reference: python/paddle/jit/api.py:182 (`to_static`) with two front-ends:
AST transform (dy2static/program_translator.py:783) and the SOT bytecode
tracer (jit/sot/). On TPU neither is needed: because *every* op funnels
through the pure-jnp dispatch layer, plain `jax.jit` tracing of the user
function is the graph capture. What we keep from SOT is its *contract* —
guard-based re-specialisation and a compiled-program cache
(jit/sot/opcode_translator/executor/guard.py, executor_cache.py): the cache
key ("guard") is the treedef + shape/dtype of tensor args plus the values of
plain-Python args, and a miss re-traces instead of graph-breaking.

Training is supported: the traced callable is routed through core dispatch,
so `jax.vjp` of the jitted function records on the eager tape and
`loss.backward()` works across a to_static boundary. Layer buffers (e.g.
BatchNorm running stats) are threaded as extra outputs and written back.
"""
from __future__ import annotations

import functools
import inspect
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor, dispatch, unwrap
from ..core import tape as _tape
from ..framework import random as _random


def _guard_key(args, kwargs):
    """Build the specialisation key (SOT guard analog)."""

    def leaf_key(x):
        if isinstance(x, Tensor):
            return ("T", tuple(x.shape), str(x.dtype), x.stop_gradient)
        if isinstance(x, (jax.Array, np.ndarray)):
            return ("A", tuple(x.shape), str(x.dtype))
        if isinstance(x, (int, float, bool, str, bytes, type(None))):
            return ("P", x)
        if isinstance(x, (list, tuple)):
            return (type(x).__name__, tuple(leaf_key(i) for i in x))
        if isinstance(x, dict):
            return ("D", tuple(sorted((k, leaf_key(v)) for k, v in x.items())))
        return ("O", id(type(x)))

    return (tuple(leaf_key(a) for a in args), leaf_key(kwargs))


_TO_STATIC_ENABLED = True


def enable_to_static(enable: bool = True):
    """Global switch (reference: jit/api.py `enable_to_static`): when off,
    every StaticFunction runs its original eager python body."""
    global _TO_STATIC_ENABLED
    _TO_STATIC_ENABLED = bool(enable)


class StaticFunction:
    """Compiled-function wrapper (reference:
    python/paddle/jit/dy2static/program_translator.py:711
    `SymbolicStaticFunction.__call__`)."""

    def __init__(self, fn: Callable, input_spec=None, build_strategy=None,
                 full_graph=True, layer=None, lint=None):
        self._fn = fn
        self._layer = layer
        self._input_spec = input_spec
        self._full_graph = full_graph
        # None = follow FLAGS_tpu_lint / PADDLE_TPU_LINT; True/False force
        self._lint = lint
        self._fallback_keys = set()  # guard keys that stay eager
        self._break_keys = set()     # guard keys that cannot trace whole
        self._cache = {}  # guard key -> (jitted, n_params, n_buffers, out_treedef)
        # guard key -> list of compiled PATHS (SOT sub-graph analog):
        # each entry replays one recorded control-flow path with value
        # guards re-checked on device outputs
        self._paths = {}
        self._capture_counts = {}
        functools.update_wrapper(self, fn)

    _MAX_PATHS = 8

    @property
    def layer(self):
        if self._layer is not None:
            return self._layer
        # bound method of a Layer?
        self_obj = getattr(self._fn, "__self__", None)
        from ..nn.layer.layers import Layer

        if isinstance(self_obj, Layer):
            return self_obj
        return None

    def _collect_state(self):
        layer = self.layer
        if layer is None:
            return [], []
        params = list(layer.parameters(include_sublayers=True))
        buffers = [b for _, b in layer.named_buffers()]
        return params, buffers

    def __call__(self, *args, **kwargs):
        if not _TO_STATIC_ENABLED:
            return self._fn(*args, **kwargs)
        params, buffers = self._collect_state()
        key = _guard_key(args, kwargs)
        if key in self._fallback_keys:
            return self._fn(*args, **kwargs)
        if key in self._break_keys:
            return self._path_call(key, params, buffers, args, kwargs,
                                   None)
        entry = self._cache.get(key)
        if entry is None:
            try:
                entry = self._trace(params, buffers, args, kwargs)
            except (jax.errors.TracerBoolConversionError,
                    jax.errors.ConcretizationTypeError,
                    jax.errors.TracerArrayConversionError,
                    jax.errors.TracerIntegerConversionError) as e:
                # SOT graph-break contract: data-dependent control flow
                # can't trace whole. Instead of staying eager, compile
                # per-PATH: record the executed op sequence + the scalar
                # values that steered python, replay it jitted, and
                # re-validate those values on every call (value guards).
                if self._full_graph:
                    raise
                if self._lint_enabled():
                    import warnings
                    warnings.warn(
                        f"to_static lint: {self._fn.__name__} "
                        "graph-breaks (data-dependent control flow); "
                        "path-compiled specialisations are NOT linted")
                self._break_keys.add(key)
                return self._path_call(key, params, buffers, args, kwargs,
                                       e)
            self._cache[key] = entry
        jitted, out_treedef, n_out = entry

        flat_args, _ = jax.tree.flatten(
            (args, kwargs), is_leaf=lambda x: isinstance(x, Tensor)
        )
        tensor_args = [a for a in flat_args if isinstance(a, Tensor)]

        # thread a fresh PRNG key so dropout etc. varies between calls without
        # retracing (keys-as-generator; see framework/random.py)
        all_inputs = [_random.next_key()] + params + tensor_args + buffers

        try:
            outs = dispatch(f"to_static:{self._fn.__name__}", jitted,
                            tuple(all_inputs))
        except jax.errors.JaxRuntimeError as e:
            # some PJRT runtimes (e.g. tunneled single-chip dev backends)
            # reject host callbacks inside compiled programs; treat that as
            # a graph break rather than a hard failure
            if "does not support host send/recv" not in str(e):
                raise
            if self._full_graph:
                raise
            import warnings
            warnings.warn(
                f"to_static: graph break in {self._fn.__name__} (backend "
                "does not support host callbacks under jit); running this "
                "specialisation eagerly")
            self._fallback_keys.add(key)
            self._cache.pop(key, None)
            return self._fn(*args, **kwargs)
        if not isinstance(outs, tuple):
            outs = (outs,)
        # write back updated buffers
        new_buf = outs[n_out:]
        for b, nb in zip(buffers, new_buf):
            b._replace(nb._array)
        result = jax.tree.unflatten(out_treedef, list(outs[:n_out]))
        return result

    # ------------------------------------------------------------------
    # path specialisation (the SOT sub-graph analog): one compiled replay
    # per executed control-flow path, guarded by the scalar values that
    # steered python during capture
    # ------------------------------------------------------------------
    def _flat_feed(self, params, buffers, args, kwargs):
        """Tensor leaves of the call, in stable order. Raw ndarray leaves
        are rejected (None): the capture keys placeholders by array object
        identity, which dispatch only preserves for Tensor._array."""
        flat_args, _ = jax.tree.flatten(
            (args, kwargs), is_leaf=lambda x: isinstance(x, Tensor))
        tensors = []
        for a in flat_args:
            if isinstance(a, Tensor):
                tensors.append(a)
            elif isinstance(a, (jax.Array, np.ndarray)):
                return None
        return tensors + list(params) + list(buffers)

    def _run_entry(self, entry, feed, buffers):
        """Run one compiled path; returns the unflattened result when its
        value guards hold, else None."""
        (replay, ctrl_vals, out_treedef, n_out, n_buf, extra_refs, _,
         mut_spec) = entry
        extra = []
        for ref in extra_refs:
            t = ref()
            if t is None:
                return None  # a closure tensor died; path unusable
            extra.append(t)
        try:
            outs = dispatch(f"to_static_path:{self._fn.__name__}", replay,
                            tuple(feed) + tuple(extra))
        except Exception:
            return None  # backend rejected the replay; falls to capture
        outs = outs if isinstance(outs, tuple) else (outs,)
        n_mut = len(mut_spec)
        got = [np.asarray(unwrap(o)).reshape(()).item()
               for o in outs[n_out + n_buf + n_mut:]]
        if got != ctrl_vals:
            return None
        for b, nb in zip(buffers, outs[n_out:n_out + n_buf]):
            b._replace(unwrap(nb))
        for (kind, idx), nv in zip(mut_spec,
                                   outs[n_out + n_buf:
                                        n_out + n_buf + n_mut]):
            tgt = feed[idx] if kind == "feed" else extra[idx]
            tgt._replace(unwrap(nv))
        return (jax.tree.unflatten(out_treedef, list(outs[:n_out])),)

    def _path_call(self, key, params, buffers, args, kwargs, err=None):
        if key in self._fallback_keys:
            return self._fn(*args, **kwargs)
        feed = self._flat_feed(params, buffers, args, kwargs)
        if feed is None:
            self._fallback_keys.add(key)
            return self._fn(*args, **kwargs)
        paths = self._paths.setdefault(key, [])
        # speculative replay, most-recently-hit first: run the compiled
        # path, then check its recorded control values still hold
        for i, entry in enumerate(paths):
            hit = self._run_entry(entry, feed, buffers)
            if hit is not None:
                if i:
                    paths.insert(0, paths.pop(i))
                return hit[0]
        # re-capture churn cap: exact-value guards (item()/float() reads
        # that change every batch, e.g. loss logging) would otherwise pay
        # capture + compile on EVERY call
        n_cap = self._capture_counts.get(key, 0)
        if n_cap >= self._MAX_PATHS:
            import warnings

            warnings.warn(
                f"to_static: {self._fn.__name__} keeps taking new paths "
                "(value guards never stabilize); this specialisation "
                "stays eager")
            self._fallback_keys.add(key)
            self._paths.pop(key, None)
            return self._fn(*args, **kwargs)
        self._capture_counts[key] = n_cap + 1
        # snapshot feed arrays: the capture run applies any in-place
        # effects, and the replay below must start from PRE-call state or
        # those effects double-apply on this call
        pre = [t._array for t in feed]
        entry, result = self._capture_path(key, params, buffers, args,
                                           kwargs, feed)
        if entry is None:
            # impure capture: the capture run itself was a valid eager
            # execution (with tape) — return it, do NOT run fn twice
            return result
        for t, a in zip(feed, pre):
            t._array = a
        for ref, a in zip(entry[5], entry[6]):
            if ref() is not None:
                ref()._array = a
        paths.insert(0, entry)
        if len(paths) > self._MAX_PATHS:
            paths.pop()
        hit = self._run_entry(entry, feed, buffers)
        if hit is None:  # pragma: no cover — replay must match itself
            self._fallback_keys.add(key)
            return result
        return hit[0]

    def _capture_path(self, key, params, buffers, args, kwargs, feed):
        """Run the fn eagerly under a Program capture; build a jitted
        replay of (outputs, new buffers, control scalars). Returns
        (path entry or None, this run's result) — the capture run keeps
        the tape, so when the capture turns out impure its result is a
        full eager execution the caller can return directly."""
        from ..core import tensor as _ct
        from ..static import Program

        prog = Program()
        pre_feed = [t._array for t in feed]  # pre-capture values
        for i, t in enumerate(feed):
            prog._register_placeholder(f"in{i}", t._array)
        prev = _ct._static_capture[0]
        _ct._static_capture[0] = prog
        try:
            result = self._fn(*args, **kwargs)
        finally:
            _ct._static_capture[0] = prev

        out_leaves, out_treedef = jax.tree.flatten(
            result, is_leaf=lambda x: isinstance(x, Tensor))
        out_keys = []
        for leaf in out_leaves:
            arr = unwrap(leaf) if isinstance(leaf, Tensor) else leaf
            k = prog.key_of(arr) if hasattr(arr, "shape") else None
            if k is None:
                prog._mark_impure("output produced outside dispatch")
                break
            out_keys.append(k)
        buf_keys = [prog.key_of(b._array) for b in buffers]
        if any(k is None for k in buf_keys):
            prog._mark_impure("buffer updated outside dispatch")
        if prog._impure is not None:
            import warnings

            warnings.warn(
                f"to_static: graph break in {self._fn.__name__} is not "
                f"path-compilable ({prog._impure}); this specialisation "
                "stays eager")
            self._fallback_keys.add(key)
            return None, result

        ctrl_keys = [k for k, _ in prog._controls]
        feed_keys = [prog._placeholders[f"in{i}"] for i in range(len(feed))]
        nodes = list(prog._nodes)
        literals = dict(prog._literals)
        # promote Tensor-owned literals (closure-layer params/buffers the
        # guard's layer introspection didn't see) to LIVE-fed inputs:
        # frozen copies would go stale after optimizer updates and block
        # autograd
        extra_refs = []
        extra_pre = []
        for k, ref in prog._literal_owner.items():
            if ref() is not None and k in literals:
                extra_pre.append(literals.pop(k))
                feed_keys.append(k)
                extra_refs.append(ref)
        # in-place mutations: any fed/closure tensor whose array changed
        # during the capture must have its NEW value among the replay
        # outputs, written back per call (counter.add_() and friends)
        mut_spec = []
        mut_keys = []
        for i, (t, a) in enumerate(zip(feed, pre_feed)):
            if t._array is not a:
                k = prog.key_of(t._array)
                if k is None:
                    prog._mark_impure("input mutated outside dispatch")
                    break
                mut_spec.append(("feed", i))
                mut_keys.append(k)
        for j, (ref, a) in enumerate(zip(extra_refs, extra_pre)):
            t = ref()
            if t is not None and t._array is not a:
                k = prog.key_of(t._array)
                if k is None:
                    prog._mark_impure("closure tensor mutated outside "
                                      "dispatch")
                    break
                mut_spec.append(("extra", j))
                mut_keys.append(k)
        if prog._impure is not None:
            import warnings

            warnings.warn(
                f"to_static: graph break in {self._fn.__name__} is not "
                f"path-compilable ({prog._impure}); this specialisation "
                "stays eager")
            self._fallback_keys.add(key)
            return None, result
        all_out = out_keys + buf_keys + mut_keys + ctrl_keys

        def replay(*vals):
            env = dict(literals)
            for k, v in zip(feed_keys, vals):
                env[k] = v
            for fn_, in_keys, out_ks in nodes:
                res = fn_(*[None if k is None else env[k]
                            for k in in_keys])
                if not isinstance(res, tuple):
                    res = (res,)
                for k, o in zip(out_ks, res):
                    env[k] = o
            return tuple(env[k] for k in all_out)

        replay = jax.jit(replay)
        # guard values must come from the COMPILED replay (fusion can
        # shift float scalars a ulp vs the eager capture; an eager-valued
        # guard would miss forever and re-capture every call). The feed
        # uses PRE-capture arrays: the capture run may have mutated them.
        try:
            outs0 = replay(*(pre_feed + extra_pre))
        except Exception as e:
            import warnings

            warnings.warn(
                f"to_static: compiled path for {self._fn.__name__} failed "
                f"({type(e).__name__}: {str(e)[:120]}); this "
                "specialisation stays eager")
            self._fallback_keys.add(key)
            return None, result
        ctrl_vals = [np.asarray(o).reshape(()).item()
                     for o in outs0[len(out_keys) + len(buf_keys)
                                    + len(mut_keys):]]
        return (replay, ctrl_vals, out_treedef, len(out_keys),
                len(buf_keys), extra_refs, extra_pre, mut_spec), result

    def _trace(self, params, buffers, args, kwargs):
        fn = self._fn
        n_p, n_b = len(params), len(buffers)
        flat_args, args_treedef = jax.tree.flatten(
            (args, kwargs), is_leaf=lambda x: isinstance(x, Tensor)
        )
        tensor_pos = [i for i, a in enumerate(flat_args) if isinstance(a, Tensor)]
        const_args = [a if not isinstance(a, Tensor) else None for a in flat_args]

        out_info = {}

        def pure(key, *arrays):
            p_arr = arrays[:n_p]
            t_arr = arrays[n_p : n_p + len(tensor_pos)]
            b_arr = arrays[n_p + len(tensor_pos) :]
            # bind state
            saved_p = [p._array for p in params]
            saved_b = [b._array for b in buffers]
            for p, a in zip(params, p_arr):
                p._array = a
            for b, a in zip(buffers, b_arr):
                b._array = a
            flat = list(const_args)
            for pos, a in zip(tensor_pos, t_arr):
                t = Tensor(a)
                t.stop_gradient = flat_args[pos].stop_gradient
                flat[pos] = t
            call_args, call_kwargs = jax.tree.unflatten(args_treedef, flat)
            try:
                with _tape.no_grad(), _random.rng_scope(key):
                    out = fn(*call_args, **call_kwargs)
            finally:
                new_b = [b._array for b in buffers]
                for p, a in zip(params, saved_p):
                    p._array = a
                for b, a in zip(buffers, saved_b):
                    b._array = a
            out_leaves, out_treedef = jax.tree.flatten(
                out, is_leaf=lambda x: isinstance(x, Tensor)
            )
            out_info["treedef"] = out_treedef
            out_info["n"] = len(out_leaves)
            return tuple(unwrap(o) for o in out_leaves) + tuple(new_b)

        jitted = jax.jit(pure)
        # prime: trace once at aval level (no execution) to learn out structure
        jax.eval_shape(
            pure,
            _random.next_key(),
            *[unwrap(p) for p in params],
            *[unwrap(flat_args[i]) for i in tensor_pos],
            *[unwrap(b) for b in buffers],
        )
        self._maybe_lint(pure, params, buffers, flat_args, tensor_pos)
        return jitted, out_info["treedef"], out_info["n"]

    def _lint_enabled(self) -> bool:
        """lint=True/False forces; None follows FLAGS_tpu_lint
        (PADDLE_TPU_LINT)."""
        if self._lint is not None:
            return bool(self._lint)
        from ..framework import flags as _flags

        try:
            return bool(_flags.flag("tpu_lint"))
        except KeyError:  # pragma: no cover
            return False

    def _maybe_lint(self, pure, params, buffers, flat_args, tensor_pos):
        """Opt-in trace-time lint (paddle_tpu.analysis): runs the rule
        pipeline over the SAME pure function jax.jit compiles, so what
        is linted is exactly what runs. Enabled per-function with
        `to_static(fn, lint=True)` or globally with PADDLE_TPU_LINT=1;
        severity policy from FLAGS_tpu_lint_fail_on."""
        from ..framework import flags as _flags

        if not self._lint_enabled():
            return
        from ..analysis import Severity, analyze

        # the user-level python scalars are baked into `pure`'s closure
        # (they are part of the guard key): hand them to the recompile
        # rule explicitly, labelled by their position in the call
        scalar_args = []
        for i, a in enumerate(flat_args):
            if isinstance(a, (int, float)) and not isinstance(a, bool):
                scalar_args.append((a, f"arg[{i}]"))
        # spec of the PRNG key WITHOUT consuming one: lint must not
        # shift the global key stream (seed-for-seed reproducibility)
        key_state = _random.get_rng_state()
        key_spec = jax.ShapeDtypeStruct(key_state.shape, key_state.dtype)
        report = analyze(
            pure,
            key_spec,
            *[unwrap(p) for p in params],
            *[unwrap(flat_args[i]) for i in tensor_pos],
            *[unwrap(b) for b in buffers],
            name=f"to_static:{self._fn.__name__}",
            scalar_args=scalar_args,
        )
        fail_on = str(_flags.flag("tpu_lint_fail_on")).lower()
        if fail_on == "never":
            fail = Severity.ERROR + 1  # nothing reaches it
        else:
            try:
                fail = Severity[fail_on.upper()]
            except KeyError:
                raise ValueError(
                    f"invalid FLAGS_tpu_lint_fail_on {fail_on!r}; "
                    "expected error|warning|info|never") from None
        report.raise_or_warn(fail_on=fail)

    # paddle parity helpers
    @property
    def code(self):
        return inspect.getsource(self._fn)

    def concrete_program_specify_input_spec(self, *a, **k):
        return None


def to_static(function=None, input_spec=None, build_strategy=None, backend=None, **kwargs):
    """paddle.jit.to_static (ref: python/paddle/jit/api.py:182).
    `full_graph=False` (the default, like the reference's SOT front-end)
    permits graph breaks: specialisations that cannot trace run eagerly.
    `lint=True` runs the paddle_tpu.analysis rule pipeline at trace time
    (default: follow the PADDLE_TPU_LINT env flag)."""
    full_graph = kwargs.pop("full_graph", False)
    lint = kwargs.pop("lint", None)

    def decorate(fn):
        from ..nn.layer.layers import Layer

        if isinstance(fn, Layer):
            layer = fn
            sf = StaticFunction(layer.forward, input_spec=input_spec,
                                full_graph=full_graph, layer=layer,
                                lint=lint)
            layer.forward = sf
            return layer
        return StaticFunction(fn, input_spec=input_spec,
                              full_graph=full_graph, lint=lint)

    if function is not None:
        return decorate(function)
    return decorate


def not_to_static(fn):
    fn._not_to_static = True
    return fn


def ignore_module(modules):
    return None


# ---------------------------------------------------------------------------
# jit.save / jit.load — deployment artifacts via StableHLO export
# ---------------------------------------------------------------------------


def save(layer, path, input_spec=None, **configs):
    """paddle.jit.save (ref: python/paddle/jit/api.py, TranslatedLayer
    artifacts). Serialises params (pickle) + a StableHLO export of the
    forward function when input_spec is given."""
    import pickle
    from ..framework.io import save as fsave

    fsave(layer.state_dict(), path + ".pdiparams")
    meta = {"class": type(layer).__name__}
    if input_spec:
        try:
            from jax import export as jexport

            params = [unwrap(p) for p in layer.parameters()]

            def pure(params_arr, *xs):
                saved = [p._array for p in layer.parameters()]
                for p, a in zip(layer.parameters(), params_arr):
                    p._array = a
                try:
                    with _tape.no_grad():
                        out = layer(*[Tensor(x) for x in xs])
                finally:
                    for p, a in zip(layer.parameters(), saved):
                        p._array = a
                return unwrap(out)

            specs = [
                jax.ShapeDtypeStruct(tuple(s.shape), s.dtype) for s in input_spec
            ]
            # multi-platform artifact: the deployment shell (native/
            # predictor_capi.cpp) may serve on a different backend than
            # the one that exported. A trace that took a TPU-only Pallas
            # fast path (Mosaic custom calls) cannot lower for "cpu" —
            # fall back to a single-platform export rather than failing
            # the save outright.
            pspecs = [jax.ShapeDtypeStruct(p.shape, p.dtype) for p in params]
            try:
                exported = jexport.export(
                    jax.jit(pure), platforms=("cpu", "tpu"))(pspecs, *specs)
                meta["platforms"] = ["cpu", "tpu"]
            except Exception:
                exported = jexport.export(jax.jit(pure))(pspecs, *specs)
                meta["platforms"] = [jax.default_backend()]
            with open(path + ".pdmodel", "wb") as f:
                f.write(exported.serialize())
            meta["stablehlo"] = True
        except Exception as e:  # pragma: no cover
            meta["stablehlo"] = False
            meta["export_error"] = repr(e)
    with open(path + ".pdmeta", "wb") as f:
        pickle.dump(meta, f)


class TranslatedLayer:
    """Loaded inference artifact (ref: python/paddle/jit/translated_layer.py)."""

    def __init__(self, exported, state_dict):
        self._exported = exported
        self._state = state_dict

    def __call__(self, *xs):
        params = [unwrap(v) for v in self._state.values()]
        out = self._exported.call(params, *[unwrap(x) for x in xs])
        return Tensor(out) if not isinstance(out, (tuple, list)) else tuple(Tensor(o) for o in out)

    def state_dict(self):
        return self._state


def load(path, **configs):
    import pickle
    from ..framework.io import load as fload

    state = fload(path + ".pdiparams")
    with open(path + ".pdmeta", "rb") as f:
        meta = pickle.load(f)
    if meta.get("stablehlo"):
        from jax import export as jexport

        with open(path + ".pdmodel", "rb") as f:
            exported = jexport.deserialize(f.read())
        return TranslatedLayer(exported, state)
    raise ValueError(f"no serialized program at {path}.pdmodel; re-save with input_spec")
