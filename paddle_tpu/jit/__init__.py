from .api import (to_static, not_to_static, ignore_module, save, load,
                  TranslatedLayer, enable_to_static)

__all__ = ["to_static", "not_to_static", "ignore_module", "save", "load",
           "TranslatedLayer", "enable_to_static"]
