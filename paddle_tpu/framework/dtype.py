"""Dtype system for paddle_tpu.

TPU-native counterpart of the reference's `phi::DataType` / `paddle.dtype`
(reference: paddle/phi/common/data_type.h, python/paddle/framework/dtype.py).
We standardise on `numpy.dtype` objects (which JAX consumes directly) plus
JAX's bfloat16 extension type, and keep paddle's public names.
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp
import ml_dtypes

# Canonical dtype objects. np.dtype instances are hashable, comparable and
# accepted everywhere by jax.numpy.
bfloat16 = np.dtype(ml_dtypes.bfloat16)
float16 = np.dtype("float16")
float32 = np.dtype("float32")
float64 = np.dtype("float64")
float8_e4m3fn = np.dtype(ml_dtypes.float8_e4m3fn)
float8_e5m2 = np.dtype(ml_dtypes.float8_e5m2)
int8 = np.dtype("int8")
int16 = np.dtype("int16")
int32 = np.dtype("int32")
int64 = np.dtype("int64")
uint8 = np.dtype("uint8")
uint16 = np.dtype("uint16")
uint32 = np.dtype("uint32")
uint64 = np.dtype("uint64")
bool_ = np.dtype("bool")
complex64 = np.dtype("complex64")
complex128 = np.dtype("complex128")

_ALIASES = {
    "bool": bool_,
    "bfloat16": bfloat16,
    "bf16": bfloat16,
    "float16": float16,
    "fp16": float16,
    "half": float16,
    "float32": float32,
    "fp32": float32,
    "float": float32,
    "float64": float64,
    "fp64": float64,
    "double": float64,
    "float8_e4m3fn": float8_e4m3fn,
    "float8_e5m2": float8_e5m2,
    "int8": int8,
    "int16": int16,
    "int32": int32,
    "int64": int64,
    "int": int32,
    "long": int64,
    "uint8": uint8,
    "uint16": uint16,
    "uint32": uint32,
    "uint64": uint64,
    "complex64": complex64,
    "complex128": complex128,
}

_DEFAULT_DTYPE = [float32]


def convert_dtype(dtype) -> np.dtype:
    """Normalise any dtype spec (str / np.dtype / python type / jnp dtype)
    to a canonical np.dtype. Mirrors paddle.base.data_feeder.convert_dtype."""
    if dtype is None:
        return None
    if isinstance(dtype, str):
        key = dtype.replace("paddle.", "")
        if key in _ALIASES:
            return _ALIASES[key]
        return np.dtype(key)
    if dtype is bool:
        return bool_
    if dtype is int:
        return int64
    if dtype is float:
        return float32
    return np.dtype(dtype)


def set_default_dtype(dtype):
    """paddle.set_default_dtype (python/paddle/framework/framework.py)."""
    d = convert_dtype(dtype)
    if d not in (float16, float32, float64, bfloat16):
        raise TypeError(
            f"set_default_dtype only supports float16/bfloat16/float32/float64, got {d}"
        )
    _DEFAULT_DTYPE[0] = d


def get_default_dtype() -> np.dtype:
    return _DEFAULT_DTYPE[0]


def is_floating_point(dtype) -> bool:
    return jnp.issubdtype(convert_dtype(dtype), jnp.floating)


def is_integer(dtype) -> bool:
    d = convert_dtype(dtype)
    return jnp.issubdtype(d, jnp.integer) or d == bool_


def is_complex(dtype) -> bool:
    return jnp.issubdtype(convert_dtype(dtype), jnp.complexfloating)


def is_inexact(dtype) -> bool:
    """Differentiable dtypes (float or complex, incl. bf16/fp8)."""
    return jnp.issubdtype(convert_dtype(dtype), jnp.inexact)


#: dtype promotion follows jax/numpy rules (jnp.promote_types), which matches
#: the reference's phi promotion table for the common cases.
promote_types = jnp.promote_types

iinfo = jnp.iinfo
finfo = jnp.finfo
