"""paddle.save / paddle.load.

Reference: python/paddle/framework/io.py:740 (save) / :982 (load) — pickle
protocol with tensors stored as numpy payloads. We keep the same user model
(nested state_dict of Tensors <-> file) with a numpy-npz-in-pickle format.
Distributed sharded checkpointing lives in distributed/checkpoint.
"""
from __future__ import annotations

import os
import pickle
from typing import Any

import numpy as np


def _pack(obj):
    from ..core.tensor import Tensor

    if isinstance(obj, Tensor):
        return ("__tensor__", np.asarray(obj._array), str(obj.dtype), not obj.stop_gradient)
    if isinstance(obj, dict):
        return {k: _pack(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        t = type(obj)
        return t(_pack(v) for v in obj)
    return obj


def _unpack(obj, return_numpy=False):
    from ..core.tensor import Tensor
    import jax.numpy as jnp

    if isinstance(obj, tuple) and len(obj) == 4 and obj[0] == "__tensor__":
        _, arr, dtype, trainable = obj
        if return_numpy:
            return arr
        return Tensor(jnp.asarray(arr), stop_gradient=not trainable)
    if isinstance(obj, dict):
        return {k: _unpack(v, return_numpy) for k, v in obj.items()}
    if isinstance(obj, list):
        return [_unpack(v, return_numpy) for v in obj]
    if isinstance(obj, tuple):
        return tuple(_unpack(v, return_numpy) for v in obj)
    return obj


def save(obj: Any, path: str, protocol: int = 4, **configs):
    """paddle.save (ref: python/paddle/framework/io.py:740)."""
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "wb") as f:
        pickle.dump(_pack(obj), f, protocol=protocol)


def load(path: str, return_numpy: bool = False, **configs) -> Any:
    """paddle.load (ref: python/paddle/framework/io.py:982)."""
    with open(path, "rb") as f:
        obj = pickle.load(f)
    return _unpack(obj, return_numpy=return_numpy)
