from . import dtype as _dtype_mod
from .dtype import (
    convert_dtype, set_default_dtype, get_default_dtype, promote_types,
    iinfo, finfo,
)
from .flags import set_flags, get_flags, define_flag, flag
from .random import seed, get_rng_state, set_rng_state, default_generator, rng_scope, next_key

__all__ = [
    "convert_dtype", "set_default_dtype", "get_default_dtype", "promote_types",
    "iinfo", "finfo", "set_flags", "get_flags", "define_flag", "flag", "seed",
    "get_rng_state", "set_rng_state", "default_generator", "rng_scope", "next_key",
]
