"""RNG state: keys-as-generator.

The reference carries a per-device `phi::Generator` (paddle/phi/core/generator.h)
with a seed + offset counter. The TPU-native design keeps a global splittable
JAX PRNG key; every random op folds in a fresh subkey. A scoped key can be
installed (``rng_scope``) so that jitted functional code receives randomness as
a traced argument — the idiomatic JAX pattern — while eager code keeps
paddle-style implicit state.
"""
from __future__ import annotations

import contextlib
import threading

import jax

_state = threading.local()


class Generator:
    """Splittable-key generator (reference: paddle/phi/core/generator.h)."""

    def __init__(self, seed: int = 0):
        self._seed = seed
        # key creation is LAZY: touching jax.random at import time would
        # initialize the backend in every process that merely imports the
        # package (e.g. DataLoader workers, which must stay host-only)
        self._key = None

    def _ensure(self):
        if self._key is None:
            self._key = jax.random.key(self._seed)
        return self._key

    def manual_seed(self, seed: int):
        self._seed = seed
        self._key = jax.random.key(seed)
        return self

    def initial_seed(self) -> int:
        return self._seed

    def split(self):
        """Return a fresh subkey, advancing internal state."""
        self._ensure()
        self._key, sub = jax.random.split(self._key)
        return sub

    def get_state(self):
        return self._ensure()

    def set_state(self, key):
        self._key = key


_default_generator = Generator(0)


def default_generator() -> Generator:
    return _default_generator


def seed(value: int):
    """paddle.seed (python/paddle/framework/random.py)."""
    _default_generator.manual_seed(int(value))
    return _default_generator


def get_rng_state():
    return _default_generator.get_state()


def set_rng_state(state):
    _default_generator.set_state(state)


@contextlib.contextmanager
def rng_scope(key):
    """Install a (possibly traced) PRNG key for random ops in this scope.

    Inside `jax.jit`-traced code, random ops must derive from a traced key to
    vary between steps; the functional trainer wraps model application in
    ``rng_scope(step_key)``.
    """
    prev = getattr(_state, "scope_key", None)
    prev_n = getattr(_state, "scope_n", 0)
    _state.scope_key = key
    _state.scope_n = 0
    try:
        yield
    finally:
        _state.scope_key = prev
        _state.scope_n = prev_n


def next_key():
    """Fresh subkey: from the active rng_scope if present, else the global
    generator."""
    from ..core import tensor as _ct

    if _ct._static_capture[0] is not None:
        # a replayed capture would freeze this randomness as a constant
        _ct._static_capture[0]._mark_impure("rng consumed during capture")
    key = getattr(_state, "scope_key", None)
    if key is not None:
        n = getattr(_state, "scope_n", 0)
        _state.scope_n = n + 1
        return jax.random.fold_in(key, n)
    return _default_generator.split()


def in_rng_scope() -> bool:
    return getattr(_state, "scope_key", None) is not None
