"""Global runtime flag registry.

TPU-native equivalent of the reference's exported-flag registry
(paddle/common/flags.h:336 `ExportedFlagInfoMap`, paddle/common/flags.cc which
defines ~176 FLAGS_*). Flags are plain Python values, overridable from the
environment (``FLAGS_check_nan_inf=1 python ...``) and via
``paddle_tpu.set_flags``.
"""
from __future__ import annotations

import os
from typing import Any, Dict

_REGISTRY: Dict[str, Any] = {}


def _coerce(default, raw: str):
    if isinstance(default, bool):
        return raw.lower() in ("1", "true", "yes", "on")
    if isinstance(default, int):
        return int(raw)
    if isinstance(default, float):
        return float(raw)
    return raw


def define_flag(name: str, default, doc: str = "", env_aliases=()):
    """Register a flag; `env_aliases` are extra environment variable
    names honoured besides FLAGS_<name> (first set one wins) — used for
    user-facing switches like PADDLE_TPU_LINT."""
    if not name.startswith("FLAGS_"):
        name = "FLAGS_" + name
    env = os.environ.get(name)
    for alias in env_aliases:
        if env is not None:
            break
        env = os.environ.get(alias)
    _REGISTRY[name] = _coerce(default, env) if env is not None else default
    return _REGISTRY[name]


def set_flags(flags: Dict[str, Any]):
    """paddle.set_flags equivalent (python/paddle/base/framework.py)."""
    for k, v in flags.items():
        if not k.startswith("FLAGS_"):
            k = "FLAGS_" + k
        if k not in _REGISTRY:
            raise KeyError(f"unknown flag {k}; known: {sorted(_REGISTRY)}")
        _REGISTRY[k] = v


def get_flags(flags=None) -> Dict[str, Any]:
    if flags is None:
        return dict(_REGISTRY)
    if isinstance(flags, str):
        flags = [flags]
    out = {}
    for k in flags:
        if not k.startswith("FLAGS_"):
            k = "FLAGS_" + k
        out[k] = _REGISTRY[k]
    return out


def flag(name: str):
    if not name.startswith("FLAGS_"):
        name = "FLAGS_" + name
    return _REGISTRY[name]


# --- core flags (subset of paddle/common/flags.cc, TPU-relevant) ---
define_flag("check_nan_inf", False, "check every op output for NaN/Inf (reference: flags.cc:72)")
define_flag("check_nan_inf_level", 0, "0: raise on NaN/Inf, >0: log only")
define_flag("benchmark", False, "synchronous op execution for timing")
define_flag("use_deterministic_ops", False, "prefer deterministic lowering")
define_flag("eager_delete_tensor_gb", 0.0, "no-op on TPU (XLA owns buffers)")
define_flag("allocator_strategy", "xla", "allocation is owned by the XLA runtime")
define_flag("tpu_matmul_precision", "default", "jax default_matmul_precision for fp32 matmuls")
define_flag("enable_pallas_kernels", True, "use Pallas kernels for fused ops when on TPU")
define_flag("log_level", 0, "VLOG-style verbosity")

# --- analysis / lint (paddle_tpu.analysis) ---
define_flag("tpu_lint", False,
            "run the jaxpr lint pipeline on every to_static trace "
            "(also: PADDLE_TPU_LINT=1)", env_aliases=("PADDLE_TPU_LINT",))
define_flag("tpu_lint_fail_on", "error",
            "severity that aborts the trace when tpu_lint is on: "
            "error|warning|info|never "
            "(also: PADDLE_TPU_LINT_FAIL_ON)",
            env_aliases=("PADDLE_TPU_LINT_FAIL_ON",))
define_flag("audit_memory", False,
            "run the static memory auditor (analysis/memory.py: jaxpr "
            "liveness peak-HBM estimate + donation analysis) at the "
            "audit hooks — ContinuousBatchingEngine.warm() over every "
            "cached program and Model.fit over the forward pass. "
            "PADDLE_TPU_LINT=1 implies it (the hooks compose with the "
            "lint switch) (also: PADDLE_TPU_AUDIT_MEMORY)",
            env_aliases=("PADDLE_TPU_AUDIT_MEMORY",))
define_flag("audit_comms", False,
            "run the static communication auditor (analysis/comms.py: "
            "jaxpr bytes-on-wire pass + per-chip collective cost "
            "model) at the audit hooks — "
            "ContinuousBatchingEngine.warm() over every cached program "
            "and Model.fit over the training step. PADDLE_TPU_LINT=1 "
            "implies it (the hooks compose with the lint switch) "
            "(also: PADDLE_TPU_AUDIT_COMMS)",
            env_aliases=("PADDLE_TPU_AUDIT_COMMS",))
define_flag("audit_roofline", False,
            "run the static roofline auditor (analysis/roofline.py: "
            "jaxpr FLOPs/bytes pass against the device-spec table -> "
            "predicted step latency, bound class, MFU) at the audit "
            "hooks — ContinuousBatchingEngine.warm() over every cached "
            "program and Model.fit over the training step. "
            "PADDLE_TPU_LINT=1 implies it (the hooks compose with the "
            "lint switch) (also: PADDLE_TPU_AUDIT_ROOFLINE)",
            env_aliases=("PADDLE_TPU_AUDIT_ROOFLINE",))

# --- serving kernels ---
define_flag("prefix_prefill_kernel", True,
            "serve cached-prefix suffix prefills through the ragged "
            "paged Pallas kernel (kernels/prefix_prefill.py); off = "
            "masked-softmax gather fallback. Read when the prefill "
            "program is BUILT, so flip it before constructing (or "
            "warming) an engine "
            "(also: PADDLE_TPU_PREFIX_PREFILL_KERNEL)",
            env_aliases=("PADDLE_TPU_PREFIX_PREFILL_KERNEL",))

define_flag("kv_cache_dtype", "bf16",
            "element type of the PAGED serving KV pools: 'bf16' "
            "(default) or 'int8' (symmetric per-(page, kv-head) absmax "
            "quantization — halves the HBM bytes every decode / "
            "prefix-prefill step streams AND doubles the pages a byte "
            "budget holds before LRU eviction). Read when a paged "
            "program / engine is BUILT, so flip it before constructing "
            "(or warming) an engine "
            "(also: PADDLE_TPU_KV_CACHE_DTYPE)",
            env_aliases=("PADDLE_TPU_KV_CACHE_DTYPE",))

define_flag("decode_megakernel", "off",
            "fusion rung of the paged decode step "
            "(kernels/decode_megakernel.py), a ladder: 'off' (default) "
            "= the multi-kernel oracle path; 'attn' = rms + QKV + "
            "rotary + paged attention + in-kernel KV commit + o-proj "
            "in ONE Pallas call per layer; 'full' = 'attn' plus the "
            "MLP half (post-attention rms + gate/up + silu*mul + down "
            "+ residual) fused into the same per-layer call; 'scan' = "
            "the whole decode step as ONE Pallas call whose outermost "
            "grid axis walks every layer over stacked weights and "
            "stacked K/V pools. Legacy booleans map onto the ladder "
            "(False/'0' -> off, True/'1' -> attn). Unsupported shapes "
            "fall back one rung at a time with a build-time warning. "
            "Read when a paged program / engine is BUILT (the rung "
            "joins every program key), so flip it before constructing "
            "(or warming) an engine "
            "(also: PADDLE_TPU_DECODE_MEGAKERNEL)",
            env_aliases=("PADDLE_TPU_DECODE_MEGAKERNEL",))

define_flag("unified_step", "auto",
            "serve mixed prefill+decode traffic through the UNIFIED "
            "ragged step (ISSUE 14): the engine's program zoo (cold + "
            "prefix prefill keyed over suffix bucket x batch x "
            "prefix-width rung) collapses to ONE chunked-prefill+decode "
            "program over the ragged_paged_attention kernel, admission "
            "becomes token-budget packing, and long prompts prefill in "
            "chunks so decode latency is immune to prefill bursts. "
            "'auto' (default) = on off-TPU (interpret-mode parity is "
            "cheap; silicon default flips with the gated ragged_step "
            "OPBENCH row), '1'/'0' force. The split-program path stays "
            "the oracle. Read when the engine is BUILT "
            "(also: PADDLE_TPU_UNIFIED_STEP)",
            env_aliases=("PADDLE_TPU_UNIFIED_STEP",))

define_flag("serving_mp", 1,
            "tensor-parallel degree of the PAGED serving stack: the "
            "engine's K/V pools (and their int8 scale sidecars) shard "
            "by kv head across an `mp` mesh of this many devices, the "
            "decode / prefill / prefix-prefill programs run under "
            "shard_map with each shard streaming only its local kv "
            "heads, and the sole per-layer cross-chip traffic is the "
            "all-gather of the per-shard o-proj activations. 1 "
            "(default) = today's single-chip path, byte-identical. "
            "Read when a paged program / engine is BUILT (it joins "
            "every program key), so flip it before constructing (or "
            "warming) an engine (also: PADDLE_TPU_SERVING_MP)",
            env_aliases=("PADDLE_TPU_SERVING_MP",))

define_flag("serving_cp", 1,
            "context-parallel degree of the PAGED serving stack: the "
            "engine's K/V pools shard by PAGE across a `cp` mesh axis "
            "of this many devices (composable with serving_mp as a 2-D "
            "cp x mp serving mesh), each shard streams only its LOCAL "
            "pages of a request through the attention programs and "
            "emits online-softmax partials (m, l, acc), and a small "
            "cross-chip merge of those stats — never the KV pages — "
            "applies the kernel's own rescale recurrence one level up "
            "(ServingTP.merge_attn_partials). Lifts the per-request "
            "context ceiling to cp x one chip's pool. 1 (default) = "
            "today's page-replicated path, byte-identical. Read when a "
            "paged program / engine is BUILT (it joins every program "
            "key), so flip it before constructing (or warming) an "
            "engine (also: PADDLE_TPU_SERVING_CP)",
            env_aliases=("PADDLE_TPU_SERVING_CP",))

define_flag("quantized_collectives", False,
            "ship the hot cross-chip payloads as absmax-scaled int8 "
            "with an f32 scale sidecar (parallel/collectives.py, "
            "EQuARX-style — the int8 KV pools' proven scheme): the "
            "per-layer o-proj activation all-gather at serving_mp > 1 "
            "(and the megakernel path's partial-sum psum), and the dp "
            "gradient psum in Model.fit (reduce-scatter on int8 "
            "shards + f32 dequant-accumulate + all-gather). ~0.5x the "
            "bf16 wire bytes, ~0.25x f32. Off (default) = every wire "
            "byte-identical to today. Read at program-BUILD time like "
            "every serving flag (it joins the jit program keys; "
            "warm() covers it), so flip it before constructing (or "
            "warming) an engine or calling fit "
            "(also: PADDLE_TPU_QUANTIZED_COLLECTIVES)",
            env_aliases=("PADDLE_TPU_QUANTIZED_COLLECTIVES",))

define_flag("speculative", "off",
            "speculative decoding policy of the serving engine "
            "(serving/speculative.py): 'ngram' drafts k tokens per "
            "slot host-side by prompt-lookup (match the last n "
            "generated tokens against the request's own prompt + "
            "history and propose the continuation — no draft model), "
            "'draft' runs a small draft llama on its own tiny paged "
            "pools; either way the target model verifies all k "
            "drafts + the pending token as ONE ragged window "
            "(new_len=k+1) through the same paged attention kernel, "
            "greedy acceptance keeps the longest matching prefix "
            "plus one corrected token, and rejection is pure length "
            "bookkeeping. 'off' (default) = today's one-token-per-"
            "step path, byte-identical. Read when a paged program / "
            "engine is BUILT (spec_k joins every program key; "
            "warm() covers it), so flip it before constructing (or "
            "warming) an engine (also: PADDLE_TPU_SPECULATIVE)",
            env_aliases=("PADDLE_TPU_SPECULATIVE",))
define_flag("spec_k", 4,
            "tokens drafted per slot per speculative step (the "
            "verify window is spec_k+1 rows). Read at engine BUILD "
            "time alongside `speculative` (also: PADDLE_TPU_SPEC_K)",
            env_aliases=("PADDLE_TPU_SPEC_K",))
define_flag("spec_adaptive", False,
            "acceptance-adaptive speculative draft depth: a pure HOST "
            "policy (serving/speculative.py AdaptiveSpecPolicy) that "
            "shrinks the active draft window when the measured "
            "acceptance_rate says drafts are being wasted and grows "
            "it back when acceptance recovers. The verify program is "
            "ragged over new_lens, so every effective k <= spec_k "
            "rides the ONE already-warmed window program — no new "
            "compiles ever (spec_k_effective in engine.metrics() "
            "reports the live depth). Off (default) = fixed spec_k. "
            "Read at engine BUILD time "
            "(also: PADDLE_TPU_SPEC_ADAPTIVE)",
            env_aliases=("PADDLE_TPU_SPEC_ADAPTIVE",))

define_flag("compile_cache", "",
            "persistent XLA compile-cache directory for the serving "
            "engine (serving/compile_cache.py): non-empty enables "
            "jax's compilation cache there at engine build, so a "
            "fleet restart / elastic scale-out serves warm()'s "
            "program zoo from disk instead of recompiling "
            "(warm_compile_stats in engine.metrics() reports cold vs "
            "warm counts). Empty (default) = off "
            "(also: PADDLE_TPU_COMPILE_CACHE)",
            env_aliases=("PADDLE_TPU_COMPILE_CACHE",))
define_flag("tuned_config", "",
            "path of a persisted TunedConfig artifact "
            "(analysis/tuner.py, .paddle_tpu_tune.json; a directory "
            "means <dir>/.paddle_tpu_tune.json): non-empty makes "
            "ContinuousBatchingEngine default its build-time knobs "
            "(kv_cache_dtype, decode_megakernel, unified_step, "
            "serving_mp, quantized_collectives, token_budget, "
            "block_size) from the autotuner's winner; explicit "
            "engine kwargs still win per knob. A stale artifact "
            "(schema/model mismatch) is ignored with a warning. "
            "Empty (default) = off "
            "(also: PADDLE_TPU_TUNED_CONFIG)",
            env_aliases=("PADDLE_TPU_TUNED_CONFIG",))
define_flag("fleet_heartbeat_s", 0.25,
            "decode-fleet worker heartbeat interval in seconds "
            "(serving/fleet.py): each worker renews a TTL lease in the "
            "fleet store every interval; a lease older than 4x the "
            "interval marks the worker dead and triggers fencing + "
            "in-flight request recovery "
            "(also: PADDLE_TPU_FLEET_HEARTBEAT_S)",
            env_aliases=("PADDLE_TPU_FLEET_HEARTBEAT_S",))
define_flag("router_max_queue", 64,
            "SLO router queue-depth bound (serving/router.py): the "
            "admission cap for LOW-priority requests; normal gets 2x, "
            "high 4x. Beyond its class cap a request is shed with a "
            "structured Rejected(reason='overloaded', retry_after_s) "
            "instead of growing an unbounded backlog "
            "(also: PADDLE_TPU_ROUTER_MAX_QUEUE)",
            env_aliases=("PADDLE_TPU_ROUTER_MAX_QUEUE",))

# --- observability (paddle_tpu.observability) ---
define_flag("trace", "",
            "host span tracing: a non-empty value arms the global "
            "observability tracer and is the chrome-trace/Perfetto "
            "JSON export path (written at exit, or via "
            "observability.trace.export_global()). Empty (default) = "
            "off with a no-allocation fast path "
            "(also: PADDLE_TPU_TRACE)",
            env_aliases=("PADDLE_TPU_TRACE",))
define_flag("metrics", False,
            "arm the global observability metrics registry (TTFT / "
            "TPOT / queue-wait / chunk-time histograms, resilience "
            "event log; snapshot()/emit_jsonl()/prometheus_text()). "
            "Off (default) = a single is-None check per site "
            "(also: PADDLE_TPU_METRICS)",
            env_aliases=("PADDLE_TPU_METRICS",))

# --- resilience (paddle_tpu.resilience) ---
define_flag("tpu_chaos", "",
            "fault-injection spec, e.g. 'io_error:0.1,preempt_at:200,"
            "hang:decode' (also: PADDLE_TPU_CHAOS; see resilience/chaos.py)",
            env_aliases=("PADDLE_TPU_CHAOS",))
define_flag("tpu_chaos_seed", 0,
            "seed of the deterministic chaos schedule "
            "(also: PADDLE_TPU_CHAOS_SEED)",
            env_aliases=("PADDLE_TPU_CHAOS_SEED",))
define_flag("io_retry_attempts", 3,
            "attempts for transient-IOError retry at the io seams "
            "(shard reads, DataLoader fetch); 1 disables retrying "
            "(also: PADDLE_TPU_IO_RETRIES)",
            env_aliases=("PADDLE_TPU_IO_RETRIES",))
define_flag("io_retry_base_delay_s", 0.05,
            "first backoff delay of the io RetryPolicy (doubles per "
            "retry, jittered)")
define_flag("step_timeout_s", 0.0,
            "default wall-clock watchdog deadline per serving-engine "
            "step; 0 disables (also: PADDLE_TPU_STEP_TIMEOUT_S)",
            env_aliases=("PADDLE_TPU_STEP_TIMEOUT_S",))
define_flag("barrier_timeout_s", 60.0,
            "default deadline of a gang coordination barrier "
            "(resilience/coordination.py): how long a host waits for "
            "its peers at a checkpoint stage/commit or generation "
            "agreement before raising a structured BarrierTimeout "
            "naming the missing ranks (also: "
            "PADDLE_TPU_BARRIER_TIMEOUT_S)",
            env_aliases=("PADDLE_TPU_BARRIER_TIMEOUT_S",))
