// Stable C ABI for out-of-tree custom ops (reference: paddle/phi/capi +
// PD_BUILD_OP, paddle/utils/cpp_extension). A custom op is an extern "C"
// symbol:  void <name>(const PTTensor* ins, int n_in,
//                      PTTensor* outs, int n_out);
// Tensors are dense host buffers; outputs are pre-allocated by the caller
// from the python-side infer_meta function.
#pragma once
#include <cstdint>

extern "C" {

enum PTDtype : int32_t {
  PT_FLOAT32 = 0,
  PT_FLOAT64 = 1,
  PT_INT32 = 2,
  PT_INT64 = 3,
  PT_BOOL = 4,
};

typedef struct {
  void* data;
  int64_t ndim;
  int64_t shape[8];
  int32_t dtype;
} PTTensor;

}  // extern "C"

#define PT_EXPORT extern "C" __attribute__((visibility("default")))

static inline int64_t pt_numel(const PTTensor* t) {
  int64_t n = 1;
  for (int64_t i = 0; i < t->ndim; ++i) n *= t->shape[i];
  return n;
}
