"""paddle.utils equivalent — the pieces with user-facing API surface
(reference: python/paddle/utils: cpp_extension build system, try_import,
unique_name). The reference's C++ utility types (variant/optional/
small_vector) are Python natives here."""
from . import cpp_extension  # noqa: F401

_UNIQUE_COUNTERS = {}


def unique_name(prefix="tmp"):
    """reference: python/paddle/utils/unique_name.py generate()."""
    n = _UNIQUE_COUNTERS.get(prefix, 0)
    _UNIQUE_COUNTERS[prefix] = n + 1
    return f"{prefix}_{n}"


def try_import(module_name, err_msg=None):
    """reference: python/paddle/utils/lazy_import.py try_import."""
    import importlib
    try:
        return importlib.import_module(module_name)
    except ImportError:
        raise ImportError(
            err_msg or f"{module_name} is required but not installed")
