"""Out-of-tree custom C++ op build system.

Reference: python/paddle/utils/cpp_extension/cpp_extension.py — `setup()`
(`:86`) and JIT `load()` (`:806`) compile user C++/CUDA against installed
headers and auto-generate python wrappers for `PD_BUILD_OP` ops.

TPU-native form: custom C++ runs on the HOST (there is no user-ISA path
onto the TPU core; the reference's CUDA kernels have no TPU analog —
device-side custom kernels are written in Pallas instead, see
paddle_tpu/kernels). The build chain is g++ -shared -fPIC against the
stable C ABI in ext_api.h, bound with ctypes (no pybind dependency), and
each op is exposed to the compute path through `jax.pure_callback`, so it
composes with jit / vmap-free graphs and works when the tensors live on a
TPU device (XLA stages the host round-trip).
"""
from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import tempfile
from typing import Callable, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor, dispatch, unwrap

__all__ = ["load", "get_build_directory", "CustomOpModule", "CppExtension",
           "setup"]

_MAX_NDIM = 8
_DTYPES = {  # ext_api.h PTDtype codes
    np.dtype(np.float32): 0, np.dtype(np.float64): 1,
    np.dtype(np.int32): 2, np.dtype(np.int64): 3, np.dtype(np.bool_): 4,
}


class _PTTensor(ctypes.Structure):
    _fields_ = [("data", ctypes.c_void_p),
                ("ndim", ctypes.c_int64),
                ("shape", ctypes.c_int64 * _MAX_NDIM),
                ("dtype", ctypes.c_int32)]


def get_build_directory() -> str:
    d = os.environ.get("PADDLE_EXTENSION_DIR") or os.path.join(
        tempfile.gettempdir(), "paddle_tpu_extensions")
    os.makedirs(d, exist_ok=True)
    return d


def _include_dir() -> str:
    return os.path.dirname(os.path.abspath(__file__))


_COMPILER_VERSION = None


def _compiler_version() -> bytes:
    global _COMPILER_VERSION
    if _COMPILER_VERSION is None:
        try:
            _COMPILER_VERSION = subprocess.run(
                ["g++", "--version"], capture_output=True).stdout
        except FileNotFoundError:
            # no compiler on this host: cache hits still work, a cache
            # miss fails later in the g++ invocation with a clear error
            _COMPILER_VERSION = b"g++-absent"
    return _COMPILER_VERSION


def _compile(name: str, sources: Sequence[str], extra_cflags, extra_ldflags,
             build_directory: Optional[str], verbose: bool) -> str:
    build_dir = build_directory or get_build_directory()
    os.makedirs(build_dir, exist_ok=True)
    tag = hashlib.sha256()
    for s in sources:
        with open(s, "rb") as f:
            tag.update(f.read())
    # the ABI header and compiler version are part of the binary contract
    with open(os.path.join(_include_dir(), "ext_api.h"), "rb") as f:
        tag.update(f.read())
    tag.update(_compiler_version())
    tag.update(" ".join(list(extra_cflags) + list(extra_ldflags)).encode())
    so_path = os.path.join(build_dir, f"{name}_{tag.hexdigest()[:12]}.so")
    if os.path.exists(so_path):
        return so_path
    cmd = (["g++", "-O2", "-shared", "-fPIC", "-std=c++17",
            f"-I{_include_dir()}"]
           + list(extra_cflags) + list(sources)
           + ["-o", so_path] + list(extra_ldflags))
    if verbose:
        print("cpp_extension:", " ".join(cmd))
    proc = subprocess.run(cmd, capture_output=True, text=True)
    if proc.returncode != 0:
        raise RuntimeError(
            f"compilation of custom op {name!r} failed:\n{proc.stderr}")
    return so_path


def _to_struct(arr: np.ndarray) -> _PTTensor:
    t = _PTTensor()
    t.data = arr.ctypes.data_as(ctypes.c_void_p)
    t.ndim = arr.ndim
    for i, s in enumerate(arr.shape):
        t.shape[i] = s
    t.dtype = _DTYPES[arr.dtype]
    return t


class CustomOp:
    """One bound C symbol, callable on Tensors; under jit it becomes a
    pure_callback (the XLA custom-call analog of the reference's custom
    OpKernel)."""

    def __init__(self, cfunc, name: str, infer_meta: Callable):
        self._cfunc = cfunc
        self._name = name
        self._infer_meta = infer_meta

    def _host_call(self, *arrays):
        arrays = [np.ascontiguousarray(np.asarray(a)) for a in arrays]
        if any(a.ndim > _MAX_NDIM for a in arrays):
            raise ValueError(f"custom op {self._name}: ndim > {_MAX_NDIM}")
        metas = self._infer_meta(*[(a.shape, a.dtype) for a in arrays])
        if not isinstance(metas, list):
            metas = [metas]
        outs = [np.empty(shape, dtype) for shape, dtype in metas]
        ins = (_PTTensor * len(arrays))(*[_to_struct(a) for a in arrays])
        outp = (_PTTensor * len(outs))(*[_to_struct(o) for o in outs])
        self._cfunc(ins, len(arrays), outp, len(outs))
        return tuple(outs) if len(outs) > 1 else outs[0]

    def __call__(self, *xs):
        def impl(*arrays):
            if not any(isinstance(a, jax.core.Tracer) for a in arrays):
                # eager: call the C symbol directly (device arrays round-
                # trip through host; no callback machinery, so this also
                # works on PJRT runtimes without send/recv support)
                out = self._host_call(*arrays)
                return tuple(jnp.asarray(o) for o in out) \
                    if isinstance(out, tuple) else jnp.asarray(out)
            metas = self._infer_meta(
                *[(tuple(a.shape), np.dtype(str(a.dtype))) for a in arrays])
            if not isinstance(metas, list):
                metas = [metas]
            result_shape = [jax.ShapeDtypeStruct(s, d) for s, d in metas]
            if len(result_shape) == 1:
                result_shape = result_shape[0]
            return jax.pure_callback(self._host_call, result_shape, *arrays)

        return dispatch(f"custom_op:{self._name}", impl, tuple(xs))


class CustomOpModule:
    """Namespace of the ops exported by one compiled extension."""

    def __init__(self, so_path: str, ops: Dict[str, CustomOp]):
        self.so_path = so_path
        self._ops = ops
        for k, v in ops.items():
            setattr(self, k, v)

    def op_names(self) -> List[str]:
        return list(self._ops)


def load(name: str, sources: Sequence[str],
         functions: Optional[Dict[str, Callable]] = None,
         extra_cflags: Sequence[str] = (), extra_ldflags: Sequence[str] = (),
         build_directory: Optional[str] = None, verbose: bool = False,
         **kwargs) -> CustomOpModule:
    """JIT-compile and bind a custom-op extension (reference:
    cpp_extension.py:806 `load`).

    `functions` maps exported symbol name -> infer_meta callable, the
    shape/dtype inference the reference declares via PD_BUILD_OP's
    InferShapeFn/InferDtypeFn: it receives one (shape, dtype) pair per
    input and returns one (shape, dtype) [or a list of them] per output.
    """
    if isinstance(sources, str):
        sources = [sources]
    if not functions:
        raise ValueError("functions={symbol: infer_meta} is required")
    so_path = _compile(name, sources, extra_cflags, extra_ldflags,
                       build_directory, verbose)
    lib = ctypes.CDLL(so_path)
    ops = {}
    for sym, infer_meta in functions.items():
        cfunc = getattr(lib, sym)
        cfunc.restype = None
        cfunc.argtypes = [ctypes.POINTER(_PTTensor), ctypes.c_int,
                          ctypes.POINTER(_PTTensor), ctypes.c_int]
        ops[sym] = CustomOp(cfunc, sym, infer_meta)
    return CustomOpModule(so_path, ops)


class CppExtension:
    """setup()-style extension description (reference:
    cpp_extension.py:86)."""

    def __init__(self, sources: Sequence[str], *args, **kwargs):
        self.sources = list(sources)
        self.extra_compile_args = kwargs.get("extra_compile_args", [])
        self.extra_link_args = kwargs.get("extra_link_args", [])


def setup(name: str, ext_modules, functions=None, **kwargs):
    """Eager-build analog of the reference's setuptools `setup`: compiles
    the extension into the build directory and returns the bound module."""
    if isinstance(ext_modules, CppExtension):
        ext_modules = [ext_modules]
    ext = ext_modules[0]
    return load(name, ext.sources, functions=functions,
                extra_cflags=ext.extra_compile_args,
                extra_ldflags=ext.extra_link_args, **kwargs)
