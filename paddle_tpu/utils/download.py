"""Weight-file resolution (reference: python/paddle/utils/download.py
get_weights_path_from_url / get_path_from_url).

This deployment has no network egress, so resolution is CACHE-ONLY: a url
maps to $PADDLE_TPU_HOME/weights/<basename> (default ~/.cache/paddle_tpu).
Users place files there (scp, bake into the image, ...) and every
`pretrained=True` path finds them; a missing file raises an actionable
error instead of attempting a download.
"""
from __future__ import annotations

import os

__all__ = ["get_weights_path_from_url", "weights_home"]


def weights_home() -> str:
    root = os.environ.get(
        "PADDLE_TPU_HOME",
        os.path.join(os.path.expanduser("~"), ".cache", "paddle_tpu"))
    return os.path.join(root, "weights")


def get_weights_path_from_url(url: str, md5sum=None) -> str:
    """reference: download.py get_weights_path_from_url — resolves into the
    local weights cache; offline, so the file must already be there."""
    fname = os.path.basename(url)
    path = os.path.join(weights_home(), fname)
    if not os.path.exists(path):
        raise FileNotFoundError(
            f"weight file {fname!r} not found in {weights_home()!r} and "
            "this environment has no network egress — place the file "
            "there manually (torch-format .pth checkpoints are converted "
            "automatically by paddle_tpu.vision.models.load_pretrained)")
    return path
