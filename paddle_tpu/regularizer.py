"""paddle.regularizer equivalent (reference: python/paddle/regularizer.py
L1Decay / L2Decay attached via Optimizer(weight_decay=...) or per-param
`ParamAttr.regularizer`).

TPU-native: decay folds into the jitted optimizer update (L2 as decoupled
weight decay; L1 as a sign penalty added to the gradient) instead of the
reference's separate regularization ops appended to the graph.
"""
from __future__ import annotations

__all__ = ["L1Decay", "L2Decay"]


class WeightDecayRegularizer:
    def __init__(self, coeff=0.0):
        self._coeff = float(coeff)

    @property
    def coeff(self):
        return self._coeff

    def __float__(self):
        return self._coeff

    def __repr__(self):
        return f"{type(self).__name__}(coeff={self._coeff})"


class L2Decay(WeightDecayRegularizer):
    """reference: regularizer.py L2Decay — coeff * ||w||^2 penalty,
    realised as weight decay in the fused update."""


class L1Decay(WeightDecayRegularizer):
    """reference: regularizer.py L1Decay — coeff * ||w||_1; the optimizer
    adds coeff * sign(w) to the gradient before the update."""
