"""paddle.static.nn (reference: python/paddle/static/nn/__init__.py).

Static-graph layer functions: each call creates its parameters (the
LayerHelper pattern) and computes through the same dispatch ops the
dynamic layers use, so `program_guard` capture + `Executor.run` replay see
them like any other op. Control-flow ops map to jax.lax primitives.

The `sequence_*` family operates on LoDTensors — variable-length rows
carried in lod metadata. LoD is a declared non-goal (the io/data path is
padded+mask based, SURVEY §7.4), so those entry points raise with that
explanation rather than silently mis-computing on padded data.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ...core.tensor import Parameter, Tensor, dispatch, unwrap
from ...nn import functional as F
from ...nn import initializer as I

__all__ = [
    "fc", "batch_norm", "bilinear_tensor_product", "embedding", "case",
    "cond", "static_pylayer", "conv2d", "conv2d_transpose", "conv3d",
    "conv3d_transpose", "data_norm", "deform_conv2d", "group_norm",
    "instance_norm", "layer_norm", "nce", "prelu", "py_func", "row_conv",
    "spectral_norm", "switch_case", "while_loop", "sparse_embedding",
    "sequence_conv", "sequence_softmax", "sequence_pool",
    "sequence_first_step", "sequence_last_step", "sequence_slice",
    "sequence_expand", "sequence_expand_as", "sequence_pad",
    "sequence_unpad", "sequence_reshape", "sequence_scatter",
    "sequence_enumerate",
]


def _make_param(shape, attr=None, is_bias=False, default=None, dtype="float32"):
    init = None
    a = I._resolve_param_attr(attr)
    if a is not None and a.initializer is not None:
        init = a.initializer
    if init is None:
        init = default or (I.Constant(0.0) if is_bias else I.XavierNormal())
    arr = init(tuple(int(s) for s in shape), dtype)
    return Parameter(arr, trainable=(a.trainable if a is not None else True))


def fc(x, size, num_flatten_dims=1, weight_attr=None, bias_attr=None,
       activation=None, name=None):
    """reference: static/nn/common.py:48 — XW+b over flattened trailing dims,
    summing over a list of inputs."""
    xs = x if isinstance(x, (list, tuple)) else [x]
    out = None
    for xi in xs:
        shape = tuple(xi.shape)
        nfd = num_flatten_dims if num_flatten_dims >= 0 else len(shape) - 1
        in_dim = int(np.prod(shape[nfd:]))
        w = _make_param((in_dim, size), weight_attr)

        def impl(a, wa):
            flat = a.reshape(a.shape[:nfd] + (-1,))
            return flat @ wa

        y = dispatch("static_fc", impl, (xi, w))
        out = y if out is None else out + y
    if bias_attr is not False:
        b = _make_param((size,), bias_attr, is_bias=True)
        out = dispatch("static_fc_bias", lambda a, ba: a + ba, (out, b))
    if activation is not None:
        out = getattr(F, activation)(out)
    return out


def embedding(input, size, is_sparse=False, is_distributed=False,
              padding_idx=None, param_attr=None, dtype="float32"):
    """reference: static/nn/common.py:3686."""
    w = _make_param(tuple(size), param_attr, dtype=dtype)
    return F.embedding(input, w, padding_idx=padding_idx, sparse=is_sparse)


def sparse_embedding(input, size, padding_idx=None, is_test=False,
                     entry=None, table_class="MemorySparseTable",
                     param_attr=None, dtype="float32", slot=None):
    """reference: static/nn/common.py:3838 — the PS sparse table is a
    non-goal; dense embedding has identical numerics."""
    return embedding(input, size, padding_idx=padding_idx,
                     param_attr=param_attr, dtype=dtype)


def batch_norm(input, act=None, is_test=False, momentum=0.9, epsilon=1e-5,
               param_attr=None, bias_attr=None, data_layout="NCHW",
               in_place=False, name=None, moving_mean_name=None,
               moving_variance_name=None, do_model_average_for_mean_and_var=True,
               use_global_stats=False):
    """reference: static/nn/common.py:2612."""
    from ...nn import BatchNorm as _BN

    c = input.shape[1] if data_layout == "NCHW" else input.shape[-1]
    layer = _BN(int(c), momentum=momentum, epsilon=epsilon,
                param_attr=param_attr, bias_attr=bias_attr,
                data_layout=data_layout)
    if is_test or use_global_stats:
        layer.eval()
    out = layer(input)
    return getattr(F, act)(out) if act else out


def layer_norm(input, scale=True, shift=True, begin_norm_axis=1,
               epsilon=1e-5, param_attr=None, bias_attr=None, act=None,
               name=None):
    """reference: static/nn/common.py:3550."""
    shape = tuple(int(s) for s in input.shape[begin_norm_axis:])
    w = _make_param(shape, param_attr, default=I.Constant(1.0)) if scale else None
    b = _make_param(shape, bias_attr, is_bias=True) if shift else None
    out = F.layer_norm(input, shape, weight=w, bias=b, epsilon=epsilon)
    return getattr(F, act)(out) if act else out


def group_norm(input, groups, epsilon=1e-5, param_attr=None, bias_attr=None,
               act=None, data_layout="NCHW", name=None):
    """reference: static/nn/common.py:667."""
    c = int(input.shape[1] if data_layout == "NCHW" else input.shape[-1])
    w = _make_param((c,), param_attr, default=I.Constant(1.0))
    b = _make_param((c,), bias_attr, is_bias=True)
    out = F.group_norm(input, groups, epsilon=epsilon, weight=w, bias=b,
                       data_format=data_layout)
    return getattr(F, act)(out) if act else out


def instance_norm(input, epsilon=1e-5, param_attr=None, bias_attr=None,
                  name=None):
    """reference: static/nn/common.py:271."""
    c = int(input.shape[1])
    w = _make_param((c,), param_attr, default=I.Constant(1.0))
    b = _make_param((c,), bias_attr, is_bias=True)
    return F.instance_norm(input, weight=w, bias=b, eps=epsilon)


def data_norm(input, act=None, epsilon=1e-5, param_attr=None,
              data_layout="NCHW", in_place=False, name=None, is_test=False,
              slot_dim=-1, summary_decay_0dot9999=None, sync_stats=False,
              enable_scale_and_shift=False, **kwargs):
    """reference: static/nn/common.py:460 — normalization by accumulated
    batch statistics (size/sum/square-sum summaries)."""
    c = int(input.shape[-1])
    size = _make_param((c,), None, default=I.Constant(1e4))
    ssum = _make_param((c,), None, default=I.Constant(0.0))
    sqsum = _make_param((c,), None, default=I.Constant(1e4))

    def impl(a, n, s, sq):
        mean = s / n
        return (a - mean) * jax.lax.rsqrt(jnp.maximum(sq / n - mean * mean, epsilon))

    out = dispatch("data_norm", impl, (input, size, ssum, sqsum))
    return getattr(F, act)(out) if act else out


def conv2d(input, num_filters, filter_size, stride=1, padding=0, dilation=1,
           groups=None, param_attr=None, bias_attr=None, use_cudnn=True,
           act=None, name=None, data_format="NCHW"):
    """reference: static/nn/common.py:779."""
    groups = groups or 1
    cin = int(input.shape[1] if data_format == "NCHW" else input.shape[-1])
    ks = (filter_size,) * 2 if isinstance(filter_size, int) else tuple(filter_size)
    w = _make_param((num_filters, cin // groups) + ks, param_attr)
    b = None if bias_attr is False else _make_param((num_filters,), bias_attr, is_bias=True)
    out = F.conv2d(input, w, b, stride=stride, padding=padding,
                   dilation=dilation, groups=groups, data_format=data_format)
    return getattr(F, act)(out) if act else out


def conv3d(input, num_filters, filter_size, stride=1, padding=0, dilation=1,
           groups=None, param_attr=None, bias_attr=None, use_cudnn=True,
           act=None, name=None, data_format="NCDHW"):
    """reference: static/nn/common.py:1087."""
    groups = groups or 1
    cin = int(input.shape[1] if data_format == "NCDHW" else input.shape[-1])
    ks = (filter_size,) * 3 if isinstance(filter_size, int) else tuple(filter_size)
    w = _make_param((num_filters, cin // groups) + ks, param_attr)
    b = None if bias_attr is False else _make_param((num_filters,), bias_attr, is_bias=True)
    out = F.conv3d(input, w, b, stride=stride, padding=padding,
                   dilation=dilation, groups=groups, data_format=data_format)
    return getattr(F, act)(out) if act else out


def conv2d_transpose(input, num_filters, output_size=None, filter_size=None,
                     padding=0, stride=1, dilation=1, groups=None,
                     param_attr=None, bias_attr=None, use_cudnn=True,
                     act=None, name=None, data_format="NCHW"):
    """reference: static/nn/common.py conv2d_transpose."""
    groups = groups or 1
    cin = int(input.shape[1] if data_format == "NCHW" else input.shape[-1])
    ks = (filter_size,) * 2 if isinstance(filter_size, int) else tuple(filter_size)
    w = _make_param((cin, num_filters // groups) + ks, param_attr)
    b = None if bias_attr is False else _make_param((num_filters,), bias_attr, is_bias=True)
    out = F.conv2d_transpose(input, w, b, stride=stride, padding=padding,
                             dilation=dilation, groups=groups,
                             output_size=output_size, data_format=data_format)
    return getattr(F, act)(out) if act else out


def conv3d_transpose(input, num_filters, output_size=None, filter_size=None,
                     padding=0, stride=1, dilation=1, groups=None,
                     param_attr=None, bias_attr=None, use_cudnn=True,
                     act=None, name=None, data_format="NCDHW"):
    """reference: static/nn/common.py conv3d_transpose."""
    groups = groups or 1
    cin = int(input.shape[1] if data_format == "NCDHW" else input.shape[-1])
    ks = (filter_size,) * 3 if isinstance(filter_size, int) else tuple(filter_size)
    w = _make_param((cin, num_filters // groups) + ks, param_attr)
    b = None if bias_attr is False else _make_param((num_filters,), bias_attr, is_bias=True)
    out = F.conv3d_transpose(input, w, b, stride=stride, padding=padding,
                             dilation=dilation, groups=groups,
                             output_size=output_size, data_format=data_format)
    return getattr(F, act)(out) if act else out


def deform_conv2d(x, offset, mask, num_filters, filter_size, stride=1,
                  padding=0, dilation=1, groups=1, deformable_groups=1,
                  im2col_step=1, param_attr=None, bias_attr=None, name=None):
    """reference: static/nn/common.py deform_conv2d."""
    from ...vision.ops import deform_conv2d as _dc

    cin = int(x.shape[1])
    ks = (filter_size,) * 2 if isinstance(filter_size, int) else tuple(filter_size)
    w = _make_param((num_filters, cin // groups) + ks, param_attr)
    b = None if bias_attr is False else _make_param((num_filters,), bias_attr, is_bias=True)
    return _dc(x, offset, w, bias=b, stride=stride, padding=padding,
               dilation=dilation, deformable_groups=deformable_groups,
               groups=groups, mask=mask)


def bilinear_tensor_product(x, y, size, act=None, name=None,
                            param_attr=None, bias_attr=None):
    """reference: static/nn/common.py:2537 — out_k = x W_k y^T + b."""
    w = _make_param((size, int(x.shape[-1]), int(y.shape[-1])), param_attr)
    b = None if bias_attr is False else _make_param((size,), bias_attr, is_bias=True)
    out = F.bilinear(x, y, w, b)
    return getattr(F, act)(out) if act else out


def prelu(x, mode, param_attr=None, data_format="NCHW", name=None):
    """reference: static/nn/common.py:2936 — modes all/channel/element."""
    if mode == "all":
        shape = (1,)
    elif mode == "channel":
        shape = (int(x.shape[1] if data_format == "NCHW" else x.shape[-1]),)
    elif mode == "element":
        shape = tuple(int(s) for s in x.shape[1:])
    else:
        raise ValueError(f"prelu mode must be all/channel/element, got {mode}")
    alpha = _make_param(shape, param_attr, default=I.Constant(0.25))
    return F.prelu(x, alpha, data_format=data_format)


def row_conv(input, future_context_size, param_attr=None, act=None):
    """reference: static/nn/common.py:3329 — lookahead row convolution:
    out[t] = sum_{i=0..k} w[i] * in[t+i]."""
    k = int(future_context_size)
    d = int(input.shape[-1])
    w = _make_param((k + 1, d), param_attr)

    def impl(a, wa):
        t_axis = a.ndim - 2
        pads = [(0, 0)] * a.ndim
        pads[t_axis] = (0, k)
        ap = jnp.pad(a, pads)
        out = jnp.zeros_like(a)
        for i in range(k + 1):
            sl = [slice(None)] * a.ndim
            sl[t_axis] = slice(i, i + a.shape[t_axis])
            out = out + ap[tuple(sl)] * wa[i]
        return out

    out = dispatch("row_conv", impl, (input, w))
    return getattr(F, act)(out) if act else out


def spectral_norm(weight, dim=0, power_iters=1, eps=1e-12, name=None):
    """reference: static/nn/common.py:3412 — normalize weight by its top
    singular value estimated with power iteration (stateless form)."""

    def impl(w):
        if dim != 0:
            perm = [dim] + [d for d in range(w.ndim) if d != dim]
            mat = jnp.transpose(w, perm).reshape(w.shape[dim], -1)
        else:
            mat = w.reshape(w.shape[0], -1)
        key = jax.random.PRNGKey(0)
        u = jax.random.normal(key, (mat.shape[0],), dtype=w.dtype)
        for _ in range(max(power_iters, 1)):
            v = mat.T @ u
            v = v / jnp.maximum(jnp.linalg.norm(v), eps)
            u = mat @ v
            u = u / jnp.maximum(jnp.linalg.norm(u), eps)
        sigma = u @ (mat @ v)
        return w / sigma

    return dispatch("static_spectral_norm", impl, (weight,))


def nce(input, label, num_total_classes, sample_weight=None, param_attr=None,
        bias_attr=None, num_neg_samples=None, name=None, sampler="uniform",
        custom_dist=None, seed=0, is_sparse=False):
    """reference: static/nn/common.py nce — noise-contrastive estimation
    loss with uniform negative sampling."""
    num_neg = int(num_neg_samples or 10)
    d = int(input.shape[-1])
    w = _make_param((num_total_classes, d), param_attr)
    b = _make_param((num_total_classes,), bias_attr, is_bias=True)

    def impl(x, lab, wa, ba):
        bsz = x.shape[0]
        lab = lab.reshape(bsz).astype(jnp.int32)
        pos_logit = jnp.sum(x * wa[lab], -1) + ba[lab]
        key = jax.random.PRNGKey(seed)
        neg = jax.random.randint(key, (bsz, num_neg), 0, num_total_classes)
        neg_logit = jnp.einsum("bd,bnd->bn", x, wa[neg]) + ba[neg]
        p_noise = 1.0 / num_total_classes
        pos_loss = -jax.nn.log_sigmoid(pos_logit - jnp.log(num_neg * p_noise))
        neg_loss = -jnp.sum(
            jax.nn.log_sigmoid(-(neg_logit - jnp.log(num_neg * p_noise))), -1)
        return (pos_loss + neg_loss).reshape(bsz, 1)

    return dispatch("nce", impl, (input, label, w, b))


# ---------------------------------------------------------------------------
# control flow (jax.lax mappings)
# ---------------------------------------------------------------------------
def cond(pred, true_fn=None, false_fn=None, name=None, return_names=None):
    """reference: static/nn/control_flow.py cond → lax.cond semantics.
    Executed eagerly here (host bool), matching dygraph behavior; under jit
    the tracer stages it through lax.cond via the dispatch layer."""
    p = unwrap(pred)
    if hasattr(p, "item"):
        p = bool(np.asarray(p).item()) if np.asarray(p).shape == () else bool(np.asarray(p).any())
    if p:
        return true_fn() if true_fn is not None else None
    return false_fn() if false_fn is not None else None


def case(pred_fn_pairs, default=None, name=None):
    """reference: static/nn/control_flow.py case."""
    for pred, fn in pred_fn_pairs:
        p = np.asarray(unwrap(pred))
        if bool(p.item() if p.shape == () else p.any()):
            return fn()
    if default is not None:
        return default()
    return pred_fn_pairs[-1][1]()


def switch_case(branch_index, branch_fns, default=None, name=None):
    """reference: static/nn/control_flow.py switch_case."""
    idx = int(np.asarray(unwrap(branch_index)).item())
    fns = dict(branch_fns) if not isinstance(branch_fns, dict) else branch_fns
    if idx in fns:
        return fns[idx]()
    if default is not None:
        return default()
    return fns[max(fns)]()


def while_loop(cond_fn, body, loop_vars, is_test=False, name=None):
    """reference: static/nn/control_flow.py while_loop."""
    vars_ = list(loop_vars)
    while bool(np.asarray(unwrap(cond_fn(*vars_))).item()):
        out = body(*vars_)
        vars_ = list(out) if isinstance(out, (list, tuple)) else [out]
    return vars_


def static_pylayer(forward_fn, inputs, backward_fn=None, name=None):
    """reference: static/nn/static_pylayer.py — custom fwd/bwd pair."""
    from ...autograd import PyLayer

    class _P(PyLayer):
        @staticmethod
        def forward(ctx, *args):
            return forward_fn(*args)

        @staticmethod
        def backward(ctx, *grads):
            if backward_fn is None:
                return grads
            return backward_fn(*grads)

    return _P.apply(*inputs)


def py_func(func, x, out, backward_func=None, skip_vars_in_backward_input=None):
    """reference: static/nn/common.py py_func — host python op."""
    xs = x if isinstance(x, (list, tuple)) else [x]
    res = func(*xs)
    return res if res is not None else out


# ---------------------------------------------------------------------------
# sequence ops — LoD-dependent (declared non-goal)
# ---------------------------------------------------------------------------
def _lod_refusal(opname):
    raise NotImplementedError(
        f"paddle.static.nn.{opname} consumes LoDTensors (row-level variable "
        "lengths). The TPU-native data path is padded+mask based (static "
        "shapes for XLA); LoD is a declared non-goal — express variable "
        "lengths with sequence_mask + the dense op instead.")


def _make_sequence_stub(opname):
    def op(*args, **kwargs):
        _lod_refusal(opname)

    op.__name__ = opname
    op.__doc__ = (f"reference: static/nn/sequence_lod.py {opname} — see "
                  "_lod_refusal for why this raises on TPU.")
    return op


sequence_conv = _make_sequence_stub("sequence_conv")
sequence_softmax = _make_sequence_stub("sequence_softmax")
sequence_pool = _make_sequence_stub("sequence_pool")
sequence_first_step = _make_sequence_stub("sequence_first_step")
sequence_last_step = _make_sequence_stub("sequence_last_step")
sequence_slice = _make_sequence_stub("sequence_slice")
sequence_expand = _make_sequence_stub("sequence_expand")
sequence_expand_as = _make_sequence_stub("sequence_expand_as")
sequence_pad = _make_sequence_stub("sequence_pad")
sequence_unpad = _make_sequence_stub("sequence_unpad")
sequence_reshape = _make_sequence_stub("sequence_reshape")
sequence_scatter = _make_sequence_stub("sequence_scatter")
sequence_enumerate = _make_sequence_stub("sequence_enumerate")
