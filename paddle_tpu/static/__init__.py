"""Static-graph compatibility layer.

The reference's static graph (python/paddle/static: Program/Executor,
paddle.enable_static) is subsumed on TPU by jax.jit tracing: `to_static`
produces a compiled, cached callable, and `InputSpec` describes traced
arguments. We keep a thin `Program`/`Executor` facade so code written against
the static API keeps running (it executes eagerly under the hood, with jit
around user `main_program` bodies left to `to_static`).
"""
from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

_static_mode = [False]


class InputSpec:
    """paddle.static.InputSpec (reference:
    python/paddle/static/input.py)."""

    def __init__(self, shape, dtype="float32", name=None, stop_gradient=True):
        from ..framework.dtype import convert_dtype

        self.shape = tuple(shape)
        self.dtype = convert_dtype(dtype)
        self.name = name
        self.stop_gradient = stop_gradient

    def __repr__(self):
        return f"InputSpec(shape={self.shape}, dtype={self.dtype}, name={self.name})"

    @classmethod
    def from_tensor(cls, tensor, name=None):
        return cls(tensor.shape, tensor.dtype, name or tensor.name)

    @classmethod
    def from_numpy(cls, ndarray, name=None):
        return cls(ndarray.shape, ndarray.dtype, name)


_MAX_CAPTURED_NODES = 50_000


class Program:
    """Recorded forward graph (reference: python/paddle/base/framework.py:5840
    Program/ProgramDesc).

    TPU-native: while a program_guard is active, every dispatch()-routed op
    ALSO records (pure_fn, input slots, output slots) here as it executes
    eagerly. Executor.run then replays the ancestors of the fetches as one
    jitted function of the feeds — define-by-run capture, jit-compiled
    re-execution, the role ProgramDesc + the new executor play in the
    reference. Ops that bypass dispatch (plain numpy on host) are
    capture-time constants."""

    def __init__(self):
        self._nodes = []          # (fn, in_keys, out_keys)
        self._placeholders = {}   # name -> slot key
        self._literals = {}       # key -> array (captured constants)
        self._key_of = {}         # id(array) -> key
        self._keepalive = []      # arrays must outlive the capture
        self._next_key = 0
        self._exec_cache = {}
        # host-read bookkeeping (SOT value-guard analog; consumed by
        # jit.api's path specialisation): scalar reads that steered python
        # control flow, and exports that make the capture unreplayable
        self._controls = []       # (slot key, concrete value at capture)
        self._impure = None       # reason string, or None
        # literal slot -> weakref of the owning Tensor, when known: lets
        # the path replay feed LIVE values for closure params/buffers
        # instead of baking capture-time arrays (stale after optimizer
        # steps, and opaque to autograd)
        self._literal_owner = {}

    # -- capture ----------------------------------------------------------
    def _new_key(self, arr) -> int:
        k = self._next_key
        self._next_key += 1
        self._key_of[id(arr)] = k
        self._keepalive.append(arr)
        return k

    def _key_for_input(self, arr, owner=None) -> int:
        k = self._key_of.get(id(arr))
        if k is None:
            k = self._new_key(arr)
            self._literals[k] = arr   # first seen as an input: a constant
            if owner is not None:
                import weakref

                try:
                    self._literal_owner[k] = weakref.ref(owner)
                except TypeError:
                    pass
        return k

    def _record(self, fn, in_arrs, out_arrs, tensor_args=None):
        from ..core.tensor import Tensor

        # past the node cap, stop recording AND stop pinning — nothing may
        # be appended to _nodes/_keepalive/_literals, or a training loop
        # inside one guard leaks arrays without bound. (Other impurity
        # kinds keep recording: they only gate the jit-replay path.)
        if len(self._nodes) >= _MAX_CAPTURED_NODES:
            self._mark_impure(
                f"capture exceeded {_MAX_CAPTURED_NODES} ops - "
                "program_guard must scope a single iteration's graph")
            return

        in_keys = []
        for i, a in enumerate(in_arrs):
            if a is None:
                in_keys.append(None)
                continue
            owner = None
            if tensor_args is not None and i < len(tensor_args) \
                    and isinstance(tensor_args[i], Tensor) \
                    and tensor_args[i]._array is a:  # not an AMP cast copy
                owner = tensor_args[i]
            in_keys.append(self._key_for_input(a, owner))
        out_keys = [self._new_key(o) for o in out_arrs]
        self._nodes.append((fn, in_keys, out_keys))
        self._exec_cache.clear()

    def _register_placeholder(self, name, arr):
        self._placeholders[name] = self._new_key(arr)

    def key_of(self, arr):
        return self._key_of.get(id(arr))

    def _mark_impure(self, why: str):
        if self._impure is None:
            self._impure = why

    def _control_read(self, arr):
        """A scalar left the device to steer host control flow: remember
        which slot and what value it had, so a replay can re-check the
        decision (an array never seen by capture registers as a literal —
        safe, because any host-derived data would have tripped
        _mark_impure on its way out)."""
        a = np.asarray(arr)
        if a.size != 1:
            self._mark_impure("non-scalar host read")
            return
        if len(self._controls) >= 4096:
            # a long-lived guard logging scalars every step would grow
            # this list (and pin arrays) without bound
            self._mark_impure("too many host scalar reads")
            return
        key = self._key_of.get(id(arr))
        if key is None:
            key = self._key_for_input(arr)
        self._controls.append((key, a.reshape(()).item()))

    # -- facade -----------------------------------------------------------
    def global_block(self):
        return self

    def clone(self, for_test=False):
        return self

    @property
    def _ops(self):
        return self._nodes


def default_main_program():
    return _MAIN


def default_startup_program():
    return _STARTUP


_MAIN = Program()
_STARTUP = Program()


class Executor:
    """paddle.static.Executor (reference:
    python/paddle/base/executor.py:1172 + the new executor's program
    interpretation): replays the recorded Program for the requested
    fetches as ONE jitted function of the feed values (cached per
    feed-shape signature, so each batch shape compiles once)."""

    def __init__(self, place=None):
        self.place = place

    def run(self, program=None, feed=None, fetch_list=None, **kwargs):
        import jax

        from ..core.tensor import Tensor, unwrap

        prog = program if isinstance(program, Program) else _MAIN
        feed = feed or {}
        fetch_list = fetch_list or []
        outs = []
        jit_jobs = []   # (out_index, fetch_key)
        for i, f in enumerate(fetch_list):
            if callable(f) and not isinstance(f, Tensor):
                outs.append(np.asarray(f(**feed)))  # legacy callable path
                continue
            arr = unwrap(f) if isinstance(f, Tensor) else f
            key = prog.key_of(arr)
            if key is None:
                outs.append(np.asarray(arr))  # not captured: a constant
                continue
            outs.append(None)
            jit_jobs.append((i, key))
        if not jit_jobs:
            return outs

        feed_keys = {}
        feed_vals = []
        for name, val in feed.items():
            if name in prog._placeholders:
                feed_keys[prog._placeholders[name]] = len(feed_vals)
                feed_vals.append(np.asarray(val))
        fetch_keys = tuple(k for _, k in jit_jobs)
        # the key->position mapping must be part of the cache signature: a
        # different feed-dict ordering with identical shapes would
        # otherwise reuse a runner that swaps the feeds
        sig = (fetch_keys, tuple(sorted(feed_keys.items())),
               tuple((v.shape, str(v.dtype)) for v in feed_vals))
        runner = prog._exec_cache.get(sig)
        if runner is None:
            # prune to the ancestors of the fetches
            needed = set(fetch_keys)
            chosen = []
            for fn, in_keys, out_keys in reversed(prog._nodes):
                if any(k in needed for k in out_keys):
                    chosen.append((fn, in_keys, out_keys))
                    needed.update(k for k in in_keys if k is not None)
            chosen.reverse()
            # every needed placeholder must be fed (actionable error
            # instead of an integer KeyError from inside the jit trace)
            reachable = set(prog._literals) | set(feed_keys)
            for fn, in_keys, out_keys in chosen:
                reachable.update(out_keys)
            missing_keys = set()
            for fn, in_keys, _ in chosen:
                missing_keys.update(
                    k for k in in_keys
                    if k is not None and k not in reachable)
            if missing_keys:
                names = [n for n, k in prog._placeholders.items()
                         if k in missing_keys]
                raise ValueError(
                    f"Executor.run: missing feed for placeholder(s) "
                    f"{names or sorted(missing_keys)}")

            def replay(*vals):
                env = {k: v for k, v in prog._literals.items()}
                for key, idx in feed_keys.items():
                    env[key] = vals[idx]
                for fn, in_keys, out_keys in chosen:
                    res = fn(*[None if k is None else env[k]
                               for k in in_keys])
                    if not isinstance(res, tuple):
                        res = (res,)
                    for k, o in zip(out_keys, res):
                        env[k] = o
                return tuple(env[k] for k in fetch_keys)

            runner = jax.jit(replay)
            prog._exec_cache[sig] = runner
        results = runner(*feed_vals)
        for (i, _), r in zip(jit_jobs, results):
            outs[i] = np.asarray(r)
        return outs


def name_scope(prefix=None):
    import contextlib

    @contextlib.contextmanager
    def _scope():
        yield

    return _scope()


# ---------------------------------------------------------------------------
# program_guard / data / nn — the remaining static-graph surface
# (reference: python/paddle/static/{__init__,input,nn/common}.py). Eager-
# backed like Executor above: `data` returns a named placeholder Tensor and
# static.nn layers execute immediately; deferred compilation is to_static's
# job (SURVEY §7.1 maps ProgramDesc onto jax tracing).
# ---------------------------------------------------------------------------
import contextlib as _contextlib


@_contextlib.contextmanager
def program_guard(main_program, startup_program=None):
    """reference: static/program.py program_guard — scopes the default
    programs AND activates op capture: every dispatch()-routed op executed
    inside the guard is recorded into `main_program` for Executor.run's
    jitted replay."""
    from ..core import tensor as _ct

    global _MAIN, _STARTUP
    prev = (_MAIN, _STARTUP)
    prev_cap = _ct._static_capture[0]
    _MAIN = main_program
    if startup_program is not None:
        _STARTUP = startup_program
    _ct._static_capture[0] = main_program \
        if isinstance(main_program, Program) else None
    try:
        yield
    finally:
        _MAIN, _STARTUP = prev
        _ct._static_capture[0] = prev_cap


def data(name, shape, dtype="float32", lod_level=0):
    """reference: static/input.py data — a named placeholder.

    The returned zero Tensor (None dims -> 1) feeds static.nn builders
    immediately (define-by-run capture); inside a program_guard it is also
    registered as a FEEDABLE slot, so Executor.run(feed={name: batch})
    replays the captured graph against real batches (each new feed shape
    compiles once)."""
    import numpy as _np

    from ..core.tensor import Tensor

    concrete = tuple(1 if s is None or s < 0 else int(s) for s in shape)
    t = Tensor(_np.zeros(concrete, _np.dtype(dtype) if dtype != "float32"
                         else _np.float32))
    t.name = name
    t.stop_gradient = False
    from ..core import tensor as _ct

    prog = _ct._static_capture[0] or (_MAIN if isinstance(_MAIN, Program)
                                      else None)
    if prog is not None:
        prog._register_placeholder(name, t._array)
    return t


class _StaticNN:
    """static.nn namespace (reference: python/paddle/static/nn) — eager
    functional forms of the legacy layer builders."""

    @staticmethod
    def fc(x, size, num_flatten_dims=1, activation=None, name=None):
        import numpy as _np

        from ..core.tensor import Tensor, unwrap
        from .. import nn as _nn

        arr = unwrap(x)
        in_f = int(_np.prod(arr.shape[num_flatten_dims:]))
        layer = _nn.Linear(in_f, size)
        flat = arr.reshape(arr.shape[:num_flatten_dims] + (in_f,))
        out = layer(Tensor(flat))
        if activation:
            import paddle_tpu.nn.functional as F
            out = getattr(F, activation)(out)
        return out

    @staticmethod
    def embedding(input, size, is_sparse=False, padding_idx=None,
                  param_attr=None, dtype="float32"):
        from .. import nn as _nn

        return _nn.Embedding(size[0], size[1],
                             padding_idx=padding_idx)(input)

    @staticmethod
    def batch_norm(input, **kwargs):
        from .. import nn as _nn

        c = input.shape[1]
        return _nn.BatchNorm2D(c)(input) if input.ndim == 4 else \
            _nn.BatchNorm1D(c)(input)

    @staticmethod
    def conv2d(input, num_filters, filter_size, stride=1, padding=0,
               **kwargs):
        from .. import nn as _nn

        return _nn.Conv2D(input.shape[1], num_filters, filter_size,
                          stride=stride, padding=padding)(input)


nn = _StaticNN()


# ---------------------------------------------------------------------------
# remaining static-graph __all__ surface (reference:
# python/paddle/static/__init__.py). Everything executes eagerly per this
# facade's design; program/state (de)serialization rides the framework
# save/load machinery.
# ---------------------------------------------------------------------------
import pickle as _pickle

from ..core.tensor import Parameter, Tensor
from ..nn.initializer import ParamAttr


Variable = Tensor  # reference: base/framework.py Variable ≙ Tensor here


class Scope:
    """reference: paddle/fluid/framework/scope.h — name -> variable map."""

    def __init__(self):
        self._vars = {}

    def var(self, name):
        return self._vars.setdefault(name, None)

    def find_var(self, name):
        return self._vars.get(name)

    def set_var(self, name, value):
        self._vars[name] = value


_GLOBAL_SCOPE = Scope()
_SCOPE_STACK = [_GLOBAL_SCOPE]


def global_scope():
    return _SCOPE_STACK[-1]


@_contextlib.contextmanager
def scope_guard(scope):
    _SCOPE_STACK.append(scope)
    try:
        yield
    finally:
        _SCOPE_STACK.pop()


class BuildStrategy:
    """reference: details/build_strategy.h — knobs are accepted and kept
    for introspection; XLA owns the corresponding decisions."""

    def __init__(self):
        self.enable_inplace = True
        self.fuse_elewise_add_act_ops = False
        self.fuse_bn_act_ops = False
        self.memory_optimize = True
        self.enable_addto = False


class CompiledProgram:
    """reference: compiler.py CompiledProgram — pass-through (jit is the
    compiler)."""

    def __init__(self, program, build_strategy=None):
        self._program = program
        self._build_strategy = build_strategy or BuildStrategy()

    def __getattr__(self, item):
        return getattr(self._program, item)


def append_backward(loss, parameter_list=None, no_grad_set=None,
                    callbacks=None, checkpoints=None):
    """reference: base/backward.py append_backward — eagerly runs the
    backward pass and returns (param, grad) pairs."""
    loss.backward()
    params = parameter_list
    if params is None:
        params = []
    out = []
    for p in params:
        g = getattr(p, "grad", None)
        out.append((p, g))
    return out


def gradients(targets, inputs, target_gradients=None, no_grad_set=None):
    """reference: base/backward.py gradients."""
    import paddle_tpu as _p

    return _p.grad(targets, inputs, grad_outputs=target_gradients,
                   allow_unused=True)


def Print(input, first_n=-1, message=None, summarize=20,
          print_tensor_name=True, print_tensor_type=True,
          print_tensor_shape=True, print_tensor_layout=True,
          print_tensor_lod=True, print_phase="both"):
    """reference: static/nn/common.py Print — eager print, identity."""
    vals = np.asarray(input.numpy()).reshape(-1)[:summarize]
    head = (message + " ") if message else ""
    print(f"{head}{getattr(input, 'name', '')} shape={list(input.shape)} "
          f"values={vals}")
    return input


def py_func(func, x, out, backward_func=None, skip_vars_in_backward_input=None):
    """reference: static/nn/common.py py_func — eager call-through."""
    xs = x if isinstance(x, (list, tuple)) else [x]
    result = func(*xs)
    return result


class WeightNormParamAttr(ParamAttr):
    """reference: base/param_attr.py WeightNormParamAttr."""

    def __init__(self, dim=None, name=None, initializer=None,
                 learning_rate=1.0, regularizer=None, trainable=True,
                 do_model_average=False, need_clip=True):
        super().__init__(name=name, initializer=initializer,
                         learning_rate=learning_rate,
                         trainable=trainable)
        self.dim = dim


class ExponentialMovingAverage:
    """reference: static/ema.py ExponentialMovingAverage — shadow
    variables updated as s = decay*s + (1-decay)*p, with apply/restore
    swapping."""

    def __init__(self, decay=0.999, thres_steps=None, name=None):
        self._decay = decay
        self._shadow = {}
        self._backup = {}
        self._params = []
        self._step = 0

    def register(self, parameters):
        self._params = list(parameters)
        for p in self._params:
            self._shadow[id(p)] = np.asarray(p.numpy()).copy()

    def update(self, parameters=None):
        if parameters is not None and not self._params:
            self.register(parameters)
        self._step += 1
        d = min(self._decay, (1 + self._step) / (10 + self._step))
        for p in self._params:
            s = self._shadow[id(p)]
            self._shadow[id(p)] = d * s + (1 - d) * np.asarray(p.numpy())

    @_contextlib.contextmanager
    def apply(self, executor=None, need_restore=True):
        import jax.numpy as _jnp

        for p in self._params:
            self._backup[id(p)] = p._array
            p._array = _jnp.asarray(self._shadow[id(p)])
        try:
            yield
        finally:
            if need_restore:
                self.restore()

    def restore(self, executor=None):
        for p in self._params:
            if id(p) in self._backup:
                p._array = self._backup.pop(id(p))


def save(program, model_path, protocol=4):
    """reference: static/io.py save — persist a trained state."""
    state = getattr(program, "state_dict", lambda: {})()
    with open(model_path + ".pdparams", "wb") as f:
        _pickle.dump({k: np.asarray(v.numpy() if hasattr(v, "numpy")
                                    else v) for k, v in state.items()}, f,
                     protocol=protocol)


def load(program, model_path, executor=None, var_list=None):
    with open(model_path + ".pdparams", "rb") as f:
        state = _pickle.load(f)
    set_program_state(program, state)
    return state


def serialize_program(feed_vars, fetch_vars, **kwargs):
    return _pickle.dumps({"feed": [getattr(v, "name", None)
                                   for v in feed_vars],
                          "fetch": [getattr(v, "name", None)
                                    for v in fetch_vars]})


def serialize_persistables(feed_vars, fetch_vars, executor=None, **kwargs):
    return _pickle.dumps({})


def save_to_file(path, content):
    with open(path, "wb") as f:
        f.write(content)


def deserialize_program(data):
    return _pickle.loads(data)


def deserialize_persistables(program, data, executor=None):
    return _pickle.loads(data)


def load_from_file(path):
    with open(path, "rb") as f:
        return f.read()


def normalize_program(program, feed_vars, fetch_vars, **kwargs):
    return program


def save_inference_model(path_prefix, feed_vars, fetch_vars, executor=None,
                         **kwargs):
    """reference: static/io.py save_inference_model — deployment artifact.
    The TPU-native artifact is jit.save's StableHLO bundle; here the
    feed/fetch signature is persisted alongside."""
    save_to_file(path_prefix + ".pdmodel",
                 serialize_program(feed_vars, fetch_vars))


def load_inference_model(path_prefix, executor=None, **kwargs):
    meta = deserialize_program(load_from_file(path_prefix + ".pdmodel"))
    return [meta, meta.get("feed", []), meta.get("fetch", [])]


def load_program_state(model_path, var_list=None):
    with open(model_path + ".pdparams", "rb") as f:
        return _pickle.load(f)


def set_program_state(program, state):
    st = getattr(program, "set_state_dict", None)
    if st:
        st(state)
    return program


class _Place:
    def __init__(self, kind, idx=0):
        self.kind, self.idx = kind, idx

    def __repr__(self):
        return f"Place({self.kind}:{self.idx})"


def cpu_places(device_count=None):
    n = device_count or 1
    return [_Place("cpu", i) for i in range(n)]


def cuda_places(device_ids=None):
    return []  # no CUDA on TPU builds


def xpu_places(device_ids=None):
    return []


def create_global_var(shape, value, dtype, persistable=False,
                      force_cpu=False, name=None):
    t = Tensor(np.full(shape, value, np.dtype(dtype)))
    t.name = name
    global_scope().set_var(name or f"gvar_{id(t)}", t)
    return t


def create_parameter(shape, dtype, name=None, attr=None, is_bias=False,
                     default_initializer=None):
    arr = (default_initializer(tuple(shape), dtype)
           if callable(default_initializer)
           else np.zeros(shape, np.dtype(dtype)))
    return Parameter(arr, name=name)


def accuracy(input, label, k=1, correct=None, total=None, name=None):
    from ..metric import accuracy as _acc

    return _acc(input, label, k=k)


def auc(input, label, curve="ROC", num_thresholds=4095, topk=1,
        slide_steps=1, ins_tag_weight=None):
    from ..metric import Auc

    m = Auc(curve=curve, num_thresholds=min(num_thresholds, 4095))
    m.update(np.asarray(input.numpy()), np.asarray(label.numpy()))
    from ..core.tensor import Tensor as _T

    import jax.numpy as _jnp

    return (_T(_jnp.asarray(m.accumulate())), None, None, None, None, None)


@_contextlib.contextmanager
def device_guard(device=None):
    """reference: base/framework.py device_guard — placement is XLA's;
    no-op scope."""
    yield


@_contextlib.contextmanager
def ipu_shard_guard(index=-1, stage=-1):
    """IPU support is a non-goal (SURVEY §7.4); accepted for API parity."""
    yield


def set_ipu_shard(call_func, index=-1, stage=-1):
    return call_func


class IpuStrategy:
    def __init__(self):
        raise NotImplementedError("IPU support is a non-goal on TPU")


class IpuCompiledProgram:
    def __init__(self, *a, **k):
        raise NotImplementedError("IPU support is a non-goal on TPU")


def ctr_metric_bundle(input, label, ins_tag_weight=None):
    raise NotImplementedError(
        "ctr_metric_bundle belongs to the parameter-server stack "
        "(non-goal, SURVEY §7.4)")

from . import amp  # noqa: E402,F401
from . import nn  # noqa: E402,F401
from . import quantization  # noqa: E402,F401
