"""Static-graph compatibility layer.

The reference's static graph (python/paddle/static: Program/Executor,
paddle.enable_static) is subsumed on TPU by jax.jit tracing: `to_static`
produces a compiled, cached callable, and `InputSpec` describes traced
arguments. We keep a thin `Program`/`Executor` facade so code written against
the static API keeps running (it executes eagerly under the hood, with jit
around user `main_program` bodies left to `to_static`).
"""
from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

_static_mode = [False]


class InputSpec:
    """paddle.static.InputSpec (reference:
    python/paddle/static/input.py)."""

    def __init__(self, shape, dtype="float32", name=None, stop_gradient=True):
        from ..framework.dtype import convert_dtype

        self.shape = tuple(shape)
        self.dtype = convert_dtype(dtype)
        self.name = name
        self.stop_gradient = stop_gradient

    def __repr__(self):
        return f"InputSpec(shape={self.shape}, dtype={self.dtype}, name={self.name})"

    @classmethod
    def from_tensor(cls, tensor, name=None):
        return cls(tensor.shape, tensor.dtype, name or tensor.name)

    @classmethod
    def from_numpy(cls, ndarray, name=None):
        return cls(ndarray.shape, ndarray.dtype, name)


class Program:
    """Minimal Program facade (reference: python/paddle/base/framework.py:5840)."""

    def __init__(self):
        self._ops = []

    def global_block(self):
        return self

    def clone(self, for_test=False):
        return self


def default_main_program():
    return _MAIN


def default_startup_program():
    return _STARTUP


_MAIN = Program()
_STARTUP = Program()


class Executor:
    """Eager-executing stand-in for paddle.static.Executor
    (python/paddle/base/executor.py:1172)."""

    def __init__(self, place=None):
        self.place = place

    def run(self, program=None, feed=None, fetch_list=None, **kwargs):
        outs = []
        for f in fetch_list or []:
            if callable(f):
                outs.append(np.asarray(f(**(feed or {}))))
            else:
                outs.append(f)
        return outs


def name_scope(prefix=None):
    import contextlib

    @contextlib.contextmanager
    def _scope():
        yield

    return _scope()


# ---------------------------------------------------------------------------
# program_guard / data / nn — the remaining static-graph surface
# (reference: python/paddle/static/{__init__,input,nn/common}.py). Eager-
# backed like Executor above: `data` returns a named placeholder Tensor and
# static.nn layers execute immediately; deferred compilation is to_static's
# job (SURVEY §7.1 maps ProgramDesc onto jax tracing).
# ---------------------------------------------------------------------------
import contextlib as _contextlib


@_contextlib.contextmanager
def program_guard(main_program, startup_program=None):
    """reference: static/program.py program_guard — scopes the default
    programs."""
    global _MAIN, _STARTUP
    prev = (_MAIN, _STARTUP)
    _MAIN = main_program
    if startup_program is not None:
        _STARTUP = startup_program
    try:
        yield
    finally:
        _MAIN, _STARTUP = prev


def data(name, shape, dtype="float32", lod_level=0):
    """reference: static/input.py data — a named placeholder. This facade
    executes eagerly: the returned zero Tensor (None dims -> 1) feeds
    static.nn builders immediately, giving shape/dtype checking and layer
    construction. Deferred feed/fetch execution is to_static's job — wrap
    the model body in paddle.jit.to_static (or pass callables in
    Executor.run's fetch_list) to run against real batches."""
    import numpy as _np

    from ..core.tensor import Tensor

    concrete = tuple(1 if s is None or s < 0 else int(s) for s in shape)
    t = Tensor(_np.zeros(concrete, _np.dtype(dtype) if dtype != "float32"
                         else _np.float32))
    t.name = name
    t.stop_gradient = False
    return t


class _StaticNN:
    """static.nn namespace (reference: python/paddle/static/nn) — eager
    functional forms of the legacy layer builders."""

    @staticmethod
    def fc(x, size, num_flatten_dims=1, activation=None, name=None):
        import numpy as _np

        from ..core.tensor import Tensor, unwrap
        from .. import nn as _nn

        arr = unwrap(x)
        in_f = int(_np.prod(arr.shape[num_flatten_dims:]))
        layer = _nn.Linear(in_f, size)
        flat = arr.reshape(arr.shape[:num_flatten_dims] + (in_f,))
        out = layer(Tensor(flat))
        if activation:
            import paddle_tpu.nn.functional as F
            out = getattr(F, activation)(out)
        return out

    @staticmethod
    def embedding(input, size, is_sparse=False, padding_idx=None,
                  param_attr=None, dtype="float32"):
        from .. import nn as _nn

        return _nn.Embedding(size[0], size[1],
                             padding_idx=padding_idx)(input)

    @staticmethod
    def batch_norm(input, **kwargs):
        from .. import nn as _nn

        c = input.shape[1]
        return _nn.BatchNorm2D(c)(input) if input.ndim == 4 else \
            _nn.BatchNorm1D(c)(input)

    @staticmethod
    def conv2d(input, num_filters, filter_size, stride=1, padding=0,
               **kwargs):
        from .. import nn as _nn

        return _nn.Conv2D(input.shape[1], num_filters, filter_size,
                          stride=stride, padding=padding)(input)


nn = _StaticNN()
