"""Static-graph compatibility layer.

The reference's static graph (python/paddle/static: Program/Executor,
paddle.enable_static) is subsumed on TPU by jax.jit tracing: `to_static`
produces a compiled, cached callable, and `InputSpec` describes traced
arguments. We keep a thin `Program`/`Executor` facade so code written against
the static API keeps running (it executes eagerly under the hood, with jit
around user `main_program` bodies left to `to_static`).
"""
from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

_static_mode = [False]


class InputSpec:
    """paddle.static.InputSpec (reference:
    python/paddle/static/input.py)."""

    def __init__(self, shape, dtype="float32", name=None, stop_gradient=True):
        from ..framework.dtype import convert_dtype

        self.shape = tuple(shape)
        self.dtype = convert_dtype(dtype)
        self.name = name
        self.stop_gradient = stop_gradient

    def __repr__(self):
        return f"InputSpec(shape={self.shape}, dtype={self.dtype}, name={self.name})"

    @classmethod
    def from_tensor(cls, tensor, name=None):
        return cls(tensor.shape, tensor.dtype, name or tensor.name)

    @classmethod
    def from_numpy(cls, ndarray, name=None):
        return cls(ndarray.shape, ndarray.dtype, name)


class Program:
    """Minimal Program facade (reference: python/paddle/base/framework.py:5840)."""

    def __init__(self):
        self._ops = []

    def global_block(self):
        return self

    def clone(self, for_test=False):
        return self


def default_main_program():
    return _MAIN


def default_startup_program():
    return _STARTUP


_MAIN = Program()
_STARTUP = Program()


class Executor:
    """Eager-executing stand-in for paddle.static.Executor
    (python/paddle/base/executor.py:1172)."""

    def __init__(self, place=None):
        self.place = place

    def run(self, program=None, feed=None, fetch_list=None, **kwargs):
        outs = []
        for f in fetch_list or []:
            if callable(f):
                outs.append(np.asarray(f(**(feed or {}))))
            else:
                outs.append(f)
        return outs


def name_scope(prefix=None):
    import contextlib

    @contextlib.contextmanager
    def _scope():
        yield

    return _scope()
