"""paddle.static.quantization (reference: python/paddle/static/quantization/
— the legacy static-graph quant passes). The supported quantization path is
paddle.quantization (QAT/PTQ over layers); these names adapt that to the
static API surface."""
from ...quantization import PTQ, QAT, QuantConfig  # noqa: F401
from ...quantization import quant as quantize  # noqa: F401
from ...quantization import dequant as dequantize  # noqa: F401

__all__ = ["QuantConfig", "QAT", "PTQ", "quantize", "dequantize",
           "quant_post_static", "quant_post_dynamic"]


def quant_post_static(executor, model_dir, quantize_model_path, *args, **kwargs):
    """reference: static/quantization/post_training_quantization.py —
    offline PTQ over a saved static program. The jit/StableHLO deploy path
    quantizes live layers instead (paddle.quantization.PTQ); converting
    saved legacy programs is a non-goal."""
    raise NotImplementedError(
        "quant_post_static consumes legacy static-graph programs; use "
        "paddle.quantization.PTQ on the live model, then jit.save.")


def quant_post_dynamic(model_dir, save_model_dir, *args, **kwargs):
    """reference: static/quantization/quant_post_dynamic — see
    quant_post_static."""
    raise NotImplementedError(
        "quant_post_dynamic consumes legacy static-graph programs; use "
        "paddle.quantization.PTQ / nn.quant.weight_quantize instead.")
