"""paddle.static.amp.bf16 (reference: python/paddle/static/amp/bf16/).
bf16 is the native TPU compute dtype — auto_cast(dtype='bfloat16') is the
whole mechanism; these entry points keep the reference API shape."""
import contextlib

import numpy as np

from ...amp import auto_cast, black_list, white_list

__all__ = ["AutoMixedPrecisionListsBF16", "bf16_guard",
           "cast_model_to_bf16", "cast_parameters_to_bf16",
           "convert_float_to_uint16", "rewrite_program_bf16", "decorate_bf16"]


class AutoMixedPrecisionListsBF16:
    """reference: static/amp/bf16/amp_lists.py."""

    def __init__(self, custom_bf16_list=None, custom_fp32_list=None,
                 custom_fp32_varnames=None):
        self.bf16_list = set(white_list()) | set(custom_bf16_list or ())
        self.fp32_list = (set(black_list()) | set(custom_fp32_list or ())) \
            - set(custom_bf16_list or ())
        self.fp32_varnames = set(custom_fp32_varnames or ())


@contextlib.contextmanager
def bf16_guard():
    """reference: static/amp/bf16/amp_utils.py bf16_guard."""
    with auto_cast(enable=True, dtype="bfloat16"):
        yield


def cast_model_to_bf16(program, amp_lists=None, use_bf16_guard=True, **kw):
    """Program-level cast is a trace-time dtype policy under jit."""
    return program


def cast_parameters_to_bf16(place, program, scope=None,
                            to_bf16_var_names=None, **kw):
    return None


def rewrite_program_bf16(main_prog, amp_lists=None):
    return main_prog


def convert_float_to_uint16(x):
    """reference: static/amp/bf16/amp_utils.py — reinterpret f32 as the
    bf16 bit pattern (high 16 bits)."""
    arr = np.asarray(x, dtype=np.float32)
    return (arr.view(np.uint32) >> 16).astype(np.uint16)


def decorate_bf16(optimizer, amp_lists=None, use_pure_bf16=False,
                  use_bf16_guard=None):
    """reference: static/amp/bf16/decorator.py — optimizer passthrough;
    loss scaling is unnecessary in bf16 (same exponent range as f32)."""
    return optimizer
