"""paddle.static.amp (reference: python/paddle/static/amp/__init__.py) —
static-graph AMP rides the same auto_cast/decorate machinery as paddle.amp;
the op lists are the white/black sets those use."""
from ...amp import auto_cast, black_list, decorate, white_list  # noqa: F401
from . import bf16  # noqa: F401

__all__ = ["decorate", "auto_cast", "AutoMixedPrecisionLists",
           "CustomOpLists", "bf16", "cast_model_to_fp16",
           "cast_parameters_to_fp16"]


class AutoMixedPrecisionLists:
    """reference: static/amp/fp16_lists.py AutoMixedPrecisionLists —
    white/black op-name sets consumed by auto_cast."""

    def __init__(self, custom_white_list=None, custom_black_list=None,
                 custom_black_varnames=None, dtype="float16"):
        self.white_list = set(white_list()) | set(custom_white_list or ())
        self.black_list = (set(black_list()) | set(custom_black_list or ())) \
            - set(custom_white_list or ())
        self.black_varnames = set(custom_black_varnames or ())
        self.dtype = dtype


CustomOpLists = AutoMixedPrecisionLists


def cast_model_to_fp16(program, amp_lists=None, use_fp16_guard=True, **kw):
    """reference: static/amp/fp16_utils.py — program-level cast; with jit
    tracing the dtype policy is applied at trace time by auto_cast."""
    return program


def cast_parameters_to_fp16(place, program, scope=None,
                            to_fp16_var_names=None, **kw):
    """reference: static/amp/fp16_utils.py."""
    return None
