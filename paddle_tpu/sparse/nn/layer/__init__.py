"""paddle.sparse.nn layers (reference: python/paddle/sparse/nn/layer/
{activation,conv,norm,pooling}.py)."""
import jax.numpy as jnp
from jax.experimental import sparse as jsparse

from ... import SparseCooTensor, SparseCsrTensor, _wrap_coo
from ....core.tensor import Tensor, unwrap
from ....nn.layer.layers import Layer
from .. import functional as F

__all__ = [
    "ReLU", "ReLU6", "LeakyReLU", "Softmax", "Conv2D", "Conv3D",
    "SubmConv2D", "SubmConv3D", "BatchNorm", "SyncBatchNorm", "MaxPool3D",
]


class ReLU(Layer):
    def forward(self, x):
        return F.relu(x)


class ReLU6(Layer):
    def forward(self, x):
        return F.relu6(x)


class LeakyReLU(Layer):
    def __init__(self, negative_slope=0.01, name=None):
        super().__init__()
        self._negative_slope = negative_slope

    def forward(self, x):
        return F.leaky_relu(x, self._negative_slope)


class Softmax(Layer):
    def __init__(self, axis=-1, name=None):
        super().__init__()
        self._axis = axis

    def forward(self, x):
        return F.softmax(x, self._axis)


class _ConvNd(Layer):
    _fn = None
    _ndim = 3

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format=None):
        super().__init__()
        ks = ((kernel_size,) * self._ndim if isinstance(kernel_size, int)
              else tuple(kernel_size))
        self._stride, self._padding = stride, padding
        self._dilation, self._groups = dilation, groups
        # channel-last kernels [kD..., C_in/groups, C_out] (NDHWC data)
        self.weight = self.create_parameter(
            ks + (in_channels // groups, out_channels), attr=weight_attr)
        self.bias = (self.create_parameter((out_channels,), attr=bias_attr,
                                           is_bias=True)
                     if bias_attr is not False else None)

    def forward(self, x):
        return type(self)._fn(x, self.weight, self.bias, self._stride,
                              self._padding, self._dilation, self._groups)


class Conv3D(_ConvNd):
    """reference: sparse/nn/layer/conv.py Conv3D."""
    _fn = staticmethod(F.conv3d)
    _ndim = 3


class SubmConv3D(_ConvNd):
    """reference: sparse/nn/layer/conv.py SubmConv3D."""
    _fn = staticmethod(F.subm_conv3d)
    _ndim = 3


class Conv2D(_ConvNd):
    """reference: sparse/nn/layer/conv.py Conv2D."""
    _fn = staticmethod(F.conv2d)
    _ndim = 2


class SubmConv2D(_ConvNd):
    """reference: sparse/nn/layer/conv.py SubmConv2D."""
    _fn = staticmethod(F.subm_conv2d)
    _ndim = 2


class BatchNorm(Layer):
    """BatchNorm over the channel (last) axis of a sparse activation
    (reference: sparse/nn/layer/norm.py BatchNorm — stats over nnz values).
    """

    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NDHWC",
                 use_global_stats=None, name=None):
        super().__init__()
        self._momentum, self._epsilon = momentum, epsilon
        from ....nn.initializer import Constant

        self.weight = self.create_parameter(
            (num_features,), attr=weight_attr, default_initializer=Constant(1.0))
        self.bias = self.create_parameter((num_features,), attr=bias_attr,
                                          is_bias=True)
        self.register_buffer("_mean", Tensor(jnp.zeros(num_features)))
        self.register_buffer("_variance", Tensor(jnp.ones(num_features)))

    def forward(self, x):
        dense = unwrap(x.to_dense()) if isinstance(
            x, (SparseCooTensor, SparseCsrTensor)) else unwrap(x)
        active = jnp.any(dense != 0, axis=-1)
        n_active = jnp.maximum(jnp.sum(active), 1)
        flat = dense.reshape(-1, dense.shape[-1])
        amask = active.reshape(-1, 1)
        if self.training:
            mean = jnp.sum(flat * amask, 0) / n_active
            var = jnp.sum(((flat - mean) ** 2) * amask, 0) / n_active
            m = self._momentum
            self._mean = Tensor(m * unwrap(self._mean) + (1 - m) * mean)
            self._variance = Tensor(m * unwrap(self._variance) + (1 - m) * var)
        else:
            mean, var = unwrap(self._mean), unwrap(self._variance)
        out = (dense - mean) / jnp.sqrt(var + self._epsilon)
        out = out * unwrap(self.weight) + unwrap(self.bias)
        out = jnp.where(active[..., None], out, 0.0)
        return _wrap_coo(jsparse.BCOO.fromdense(out))


class SyncBatchNorm(BatchNorm):
    """reference: sparse/nn/layer/norm.py SyncBatchNorm — under SPMD,
    batch stats are computed over the global (sharded) batch by the
    compiler, so the implementation coincides with BatchNorm."""


class MaxPool3D(Layer):
    """reference: sparse/nn/layer/pooling.py MaxPool3D."""

    def __init__(self, kernel_size, stride=None, padding=0, return_mask=False,
                 ceil_mode=False, data_format="NDHWC", name=None):
        super().__init__()
        self._args = (kernel_size, stride, padding, ceil_mode, data_format)

    def forward(self, x):
        k, s, p, cm, df = self._args
        return F.max_pool3d(x, k, stride=s, padding=p, ceil_mode=cm,
                            data_format=df)
