"""paddle.sparse.nn.functional (reference:
python/paddle/sparse/nn/functional/__init__.py).

Design note (TPU): XLA/TPU has no sparse compute units — the MXU wants
dense tiles. The reference's gather-GEMM-scatter sparse conv kernels
(paddle/phi/kernels/sparse/gpu/conv*) therefore map to densify → dense
primitive → re-sparsify here: identical semantics, and at point-cloud
densities (<99% empty) the dense conv is usually faster on TPU than a
scalar gather/scatter loop would be. ``subm_*`` masks the output back to
the input's sparsity pattern, as the submanifold definition requires.
"""
import jax
import jax.numpy as jnp
from jax.experimental import sparse as jsparse

from ... import SparseCooTensor, SparseCsrTensor, _sp, _wrap_coo
from ....core.tensor import Tensor, unwrap

__all__ = [
    "conv2d", "conv3d", "subm_conv2d", "subm_conv2d_igemm", "subm_conv3d",
    "subm_conv3d_igemm", "max_pool3d", "relu", "relu6", "leaky_relu",
    "softmax", "attention",
]


def _dense(x):
    if isinstance(x, (SparseCooTensor, SparseCsrTensor)):
        return unwrap(x.to_dense())
    return unwrap(x)


def _unary(x, fn):
    sp = _sp(x)
    if isinstance(sp, jsparse.BCOO):
        return _wrap_coo(jsparse.BCOO((fn(sp.data), sp.indices), shape=sp.shape))
    if isinstance(sp, jsparse.BCSR):
        return SparseCsrTensor(jsparse.BCSR((fn(sp.data), sp.indices, sp.indptr),
                                            shape=sp.shape))
    return Tensor(fn(unwrap(x)))


def relu(x, name=None):
    return _unary(x, lambda a: jnp.maximum(a, 0))


def relu6(x, name=None):
    return _unary(x, lambda a: jnp.clip(a, 0, 6))


def leaky_relu(x, negative_slope=0.01, name=None):
    return _unary(x, lambda a: jnp.where(a >= 0, a, negative_slope * a))


def softmax(x, axis=-1, name=None):
    """Softmax over the non-zero entries per row (reference:
    sparse/nn/functional/activation.py softmax — csr rows)."""
    sp = _sp(x)
    if isinstance(sp, jsparse.BCSR):
        dense = jnp.asarray(sp.todense())
        mask = dense != 0
        neg_inf = jnp.where(mask, dense, -jnp.inf)
        sm = jax.nn.softmax(neg_inf, axis=axis)
        sm = jnp.where(mask, sm, 0.0)
        return SparseCsrTensor(jsparse.BCSR.fromdense(sm))
    dense = _dense(x)
    mask = dense != 0
    sm = jax.nn.softmax(jnp.where(mask, dense, -jnp.inf), axis=axis)
    return _wrap_coo(jsparse.BCOO.fromdense(jnp.where(mask, sm, 0.0)))


def _convnd(x, weight, bias, stride, padding, dilation, groups, ndim, subm,
            data_format):
    xd = _dense(x)  # [N, D..., C] channel-last (NDHWC/NHWC like reference)
    w = unwrap(weight)  # [kD..., C_in/groups, C_out]
    spatial = ndim
    stride = (stride,) * spatial if isinstance(stride, int) else tuple(stride)
    dilation = (dilation,) * spatial if isinstance(dilation, int) else tuple(dilation)
    if isinstance(padding, int):
        pads = [(padding, padding)] * spatial
    elif isinstance(padding, (list, tuple)) and padding and isinstance(padding[0], int):
        pads = [(p, p) for p in padding]
    else:
        pads = [tuple(p) for p in padding]
    dn_spec = {2: ("NHWC", "HWIO", "NHWC"), 3: ("NDHWC", "DHWIO", "NDHWC")}[spatial]
    dn = jax.lax.conv_dimension_numbers(xd.shape, w.shape, dn_spec)
    out = jax.lax.conv_general_dilated(
        xd.astype(w.dtype), w, stride, pads, rhs_dilation=dilation,
        dimension_numbers=dn, feature_group_count=groups)
    if bias is not None:
        out = out + unwrap(bias)
    if subm:
        # submanifold: outputs only at input-active sites
        active = jnp.any(jnp.asarray(_dense(x)) != 0, axis=-1, keepdims=True)
        out = jnp.where(active, out, 0.0)
    return _wrap_coo(jsparse.BCOO.fromdense(out))


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NDHWC", name=None):
    """reference: sparse/nn/functional/conv.py conv3d."""
    return _convnd(x, weight, bias, stride, padding, dilation, groups, 3,
                   False, data_format)


def subm_conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1,
                groups=1, data_format="NDHWC", key=None, name=None):
    """reference: sparse/nn/functional/conv.py subm_conv3d."""
    return _convnd(x, weight, bias, stride, padding, dilation, groups, 3,
                   True, data_format)


def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NHWC", name=None):
    """reference: sparse/nn/functional/conv.py conv2d."""
    return _convnd(x, weight, bias, stride, padding, dilation, groups, 2,
                   False, data_format)


def subm_conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1,
                groups=1, data_format="NHWC", key=None, name=None):
    """reference: sparse/nn/functional/conv.py subm_conv2d."""
    return _convnd(x, weight, bias, stride, padding, dilation, groups, 2,
                   True, data_format)


def subm_conv2d_igemm(x, weight, bias=None, stride=1, padding=0, dilation=1,
                      groups=1, data_format="NHWC", name=None):
    """igemm variant — same math; algorithm choice is XLA's on TPU."""
    return subm_conv2d(x, weight, bias, stride, padding, dilation, groups,
                       data_format)


def subm_conv3d_igemm(x, weight, bias=None, stride=1, padding=0, dilation=1,
                      groups=1, data_format="NDHWC", name=None):
    """igemm variant — same math; algorithm choice is XLA's on TPU."""
    return subm_conv3d(x, weight, bias, stride, padding, dilation, groups,
                       data_format)


def max_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               data_format="NDHWC", name=None):
    """reference: sparse/nn/functional/pooling.py max_pool3d."""
    xd = _dense(x)
    ks = (kernel_size,) * 3 if isinstance(kernel_size, int) else tuple(kernel_size)
    st = ks if stride is None else ((stride,) * 3 if isinstance(stride, int) else tuple(stride))
    pd = (padding,) * 3 if isinstance(padding, int) else tuple(padding)
    window = (1,) + ks + (1,)
    strides = (1,) + st + (1,)
    pads = ((0, 0),) + tuple((p, p) for p in pd) + ((0, 0),)
    out = jax.lax.reduce_window(xd, -jnp.inf, jax.lax.max, window, strides, pads)
    return _wrap_coo(jsparse.BCOO.fromdense(out))


def attention(query, key, value, sparse_mask, key_padding_mask=None,
              attn_mask=None, name=None):
    """Sparse-mask attention (reference: sparse/nn/functional/transformer.py
    attention): scores only at sparse_mask's nonzero sites."""
    q, k, v = (_dense(t) for t in (query, key, value))
    d = q.shape[-1]
    scores = jnp.einsum("...qd,...kd->...qk", q, k) / jnp.sqrt(float(d))
    mask_dense = _dense(sparse_mask) != 0
    scores = jnp.where(mask_dense, scores, -jnp.inf)
    if key_padding_mask is not None:
        kp = unwrap(key_padding_mask)
        scores = scores + kp[:, None, None, :]
    if attn_mask is not None:
        scores = scores + unwrap(attn_mask)
    probs = jax.nn.softmax(scores, axis=-1)
    probs = jnp.where(jnp.isnan(probs), 0.0, probs)  # fully-masked rows
    return Tensor(jnp.einsum("...qk,...kd->...qd", probs, v))
