"""paddle.sparse.nn (reference: python/paddle/sparse/nn/__init__.py)."""
from . import functional  # noqa: F401
from .layer import (  # noqa: F401
    BatchNorm,
    Conv2D,
    Conv3D,
    LeakyReLU,
    MaxPool3D,
    ReLU,
    ReLU6,
    Softmax,
    SubmConv2D,
    SubmConv3D,
    SyncBatchNorm,
)

__all__ = [
    "ReLU", "ReLU6", "LeakyReLU", "Softmax", "BatchNorm", "SyncBatchNorm",
    "Conv2D", "Conv3D", "SubmConv2D", "SubmConv3D", "MaxPool3D",
]
