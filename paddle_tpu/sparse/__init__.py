"""paddle.sparse equivalent (reference: python/paddle/sparse — COO/CSR
tensors + sparse ops, 5.5k LoC).

TPU-native: backed by jax.experimental.sparse BCOO/BCSR. On TPU, XLA lowers
sparse ops to gather/scatter + dense MXU work; genuinely sparse kernels are
a CPU/GPU concept — the API surface is what matters for parity.
"""
from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import sparse as jsparse

from ..core.tensor import Tensor, unwrap

__all__ = [
    "sparse_coo_tensor", "sparse_csr_tensor", "SparseCooTensor",
    "SparseCsrTensor", "is_same_shape", "add", "subtract", "multiply",
    "divide", "matmul", "masked_matmul", "relu", "tanh", "sqrt", "sin",
    "abs", "pow", "neg", "cast", "transpose", "sum", "coalesce", "nn",
]


class SparseCooTensor(Tensor):
    """COO sparse tensor (reference: paddle/phi/core/sparse_coo_tensor.h).
    Wraps a BCOO; `.to_dense()` / `.indices()` / `.values()` parity."""

    def __init__(self, bcoo: jsparse.BCOO):
        self._sp = bcoo
        super().__init__(bcoo.todense())

    @property
    def nnz(self):
        return int(self._sp.nse)

    def indices(self) -> Tensor:
        return Tensor(jnp.swapaxes(self._sp.indices, 0, 1))

    def values(self) -> Tensor:
        return Tensor(self._sp.data)

    def to_dense(self) -> Tensor:
        return Tensor(self._sp.todense())

    def to_sparse_csr(self) -> "SparseCsrTensor":
        return SparseCsrTensor(jsparse.BCSR.from_bcoo(self._sp))

    def is_sparse(self):
        return True

    def is_sparse_coo(self):
        return True

    def coalesce(self) -> "SparseCooTensor":
        return SparseCooTensor(self._sp.sum_duplicates())


class SparseCsrTensor(Tensor):
    """CSR sparse tensor (reference: paddle/phi/core/sparse_csr_tensor.h)."""

    def __init__(self, bcsr: jsparse.BCSR):
        self._sp = bcsr
        super().__init__(bcsr.todense())

    @property
    def nnz(self):
        return int(self._sp.nse)

    def crows(self) -> Tensor:
        return Tensor(self._sp.indptr)

    def cols(self) -> Tensor:
        return Tensor(self._sp.indices)

    def values(self) -> Tensor:
        return Tensor(self._sp.data)

    def to_dense(self) -> Tensor:
        return Tensor(self._sp.todense())

    def to_sparse_coo(self, sparse_dim=None) -> SparseCooTensor:
        return SparseCooTensor(self._sp.to_bcoo())

    def is_sparse(self):
        return True

    def is_sparse_csr(self):
        return True


def sparse_coo_tensor(indices, values, shape=None, dtype=None, place=None,
                      stop_gradient=True):
    """reference: python/paddle/sparse/creation.py sparse_coo_tensor —
    indices [ndim, nnz]."""
    idx = np.asarray(unwrap(indices))
    vals = jnp.asarray(unwrap(values), dtype=dtype)
    if shape is None:
        shape = tuple(int(m) + 1 for m in idx.max(axis=1))
    bcoo = jsparse.BCOO((vals, jnp.asarray(idx.T)), shape=tuple(shape))
    return SparseCooTensor(bcoo)


def sparse_csr_tensor(crows, cols, values, shape, dtype=None, place=None,
                      stop_gradient=True):
    """reference: sparse/creation.py sparse_csr_tensor."""
    bcsr = jsparse.BCSR(
        (jnp.asarray(unwrap(values), dtype=dtype),
         jnp.asarray(unwrap(cols)), jnp.asarray(unwrap(crows))),
        shape=tuple(shape))
    return SparseCsrTensor(bcsr)


def _sp(x):
    return x._sp if isinstance(x, (SparseCooTensor, SparseCsrTensor)) else x


def _wrap_coo(b):
    return SparseCooTensor(b if isinstance(b, jsparse.BCOO)
                           else jsparse.BCOO.fromdense(b))


def is_same_shape(x, y) -> bool:
    return tuple(x.shape) == tuple(y.shape)


def _ewise(name, fn):
    def op(x, y=None, name_=None):
        if y is None:
            sp = _sp(x)
            if isinstance(sp, (jsparse.BCOO, jsparse.BCSR)):
                data = fn(sp.data)
                if isinstance(sp, jsparse.BCSR):
                    return SparseCsrTensor(jsparse.BCSR(
                        (data, sp.indices, sp.indptr), shape=sp.shape))
                return _wrap_coo(jsparse.BCOO((data, sp.indices),
                                              shape=sp.shape))
            return Tensor(fn(unwrap(x)))
        a = (x.to_dense() if isinstance(x, (SparseCooTensor, SparseCsrTensor))
             else x)
        b = (y.to_dense() if isinstance(y, (SparseCooTensor, SparseCsrTensor))
             else y)
        return _wrap_coo(jsparse.BCOO.fromdense(fn(unwrap(a), unwrap(b))))

    op.__name__ = name
    return op


add = _ewise("add", lambda a, b=None: a if b is None else a + b)
subtract = _ewise("subtract", lambda a, b: a - b)
multiply = _ewise("multiply", lambda a, b: a * b)
divide = _ewise("divide", lambda a, b: a / b)
relu = _ewise("relu", lambda a: jnp.maximum(a, 0))
tanh = _ewise("tanh", jnp.tanh)
sqrt = _ewise("sqrt", jnp.sqrt)
sin = _ewise("sin", jnp.sin)
abs = _ewise("abs", jnp.abs)
neg = _ewise("neg", jnp.negative)


def pow(x, factor, name=None):
    return _ewise("pow", lambda a: jnp.power(a, factor))(x)


def cast(x, index_dtype=None, value_dtype=None, name=None):
    sp = _sp(x)
    data = sp.data.astype(value_dtype) if value_dtype else sp.data
    idx = sp.indices.astype(index_dtype) if index_dtype else sp.indices
    if isinstance(sp, jsparse.BCSR):
        ptr = sp.indptr.astype(index_dtype) if index_dtype else sp.indptr
        return SparseCsrTensor(jsparse.BCSR((data, idx, ptr),
                                            shape=sp.shape))
    return _wrap_coo(jsparse.BCOO((data, idx), shape=sp.shape))


def matmul(x, y, name=None):
    """Sparse @ dense (reference: sparse/binary.py matmul)."""
    sp = _sp(x)
    if isinstance(sp, (jsparse.BCOO, jsparse.BCSR)):
        return Tensor(sp @ unwrap(y))
    return Tensor(unwrap(x) @ unwrap(y))


def masked_matmul(x, y, mask, name=None):
    """dense @ dense, output only at mask's nonzeros (reference:
    sparse/binary.py masked_matmul, SDDMM)."""
    dense = unwrap(x) @ unwrap(y)
    msk = _sp(mask)
    out_data = dense[tuple(msk.indices[:, i] for i in range(
        msk.indices.shape[1]))]
    return _wrap_coo(jsparse.BCOO((out_data, msk.indices), shape=msk.shape))


def transpose(x, perm, name=None):
    sp = _sp(x)
    if isinstance(sp, jsparse.BCSR):
        sp = sp.to_bcoo()
    return _wrap_coo(sp.transpose(tuple(perm)))


def sum(x, axis=None, dtype=None, keepdim=False, name=None):
    sp = _sp(x)
    dense = sp.todense() if hasattr(sp, "todense") else unwrap(x)
    return Tensor(jnp.sum(dense, axis=axis, keepdims=keepdim, dtype=dtype))


def coalesce(x, name=None):
    return x.coalesce()


# paddle.sparse.nn is a real subpackage, imported at the end of this file


# remaining unary surface (reference: sparse/unary.py)
tan = _ewise("tan", jnp.tan)
asin = _ewise("asin", jnp.arcsin)
atan = _ewise("atan", jnp.arctan)
sinh = _ewise("sinh", jnp.sinh)
asinh = _ewise("asinh", jnp.arcsinh)
atanh = _ewise("atanh", jnp.arctanh)
square = _ewise("square", jnp.square)
log1p = _ewise("log1p", jnp.log1p)
expm1 = _ewise("expm1", jnp.expm1)
deg2rad = _ewise("deg2rad", jnp.deg2rad)
rad2deg = _ewise("rad2deg", jnp.rad2deg)
isnan = _ewise("isnan", jnp.isnan)


def mv(x, vec, name=None):
    """sparse matrix x dense vector (reference: sparse/binary.py mv)."""
    sp = _sp(x)
    return Tensor(sp @ unwrap(vec))


def addmm(input, x, y, beta=1.0, alpha=1.0, name=None):
    """beta*input + alpha*(x @ y) (reference: sparse/binary.py addmm)."""
    prod = matmul(x, y)
    inp = (input.to_dense()
           if isinstance(input, (SparseCooTensor, SparseCsrTensor))
           else input)
    return Tensor(beta * unwrap(inp) + alpha * unwrap(prod))


def mask_as(x, mask, name=None):
    """Dense x filtered to mask's sparsity pattern (reference:
    sparse/unary.py mask_as)."""
    msk = _sp(mask)
    if isinstance(msk, jsparse.BCSR):
        msk = msk.to_bcoo()
    xa = unwrap(x)
    data = xa[tuple(msk.indices[:, i] for i in range(
        msk.indices.shape[1]))]
    return _wrap_coo(jsparse.BCOO((data, msk.indices), shape=msk.shape))


def reshape(x, shape, name=None):
    """reference: sparse/unary.py reshape — via dense round-trip (XLA owns
    the layout; sparse reshape has no TPU fast path)."""
    sp = _sp(x)
    dense = sp.todense() if hasattr(sp, "todense") else unwrap(x)
    return _wrap_coo(jsparse.BCOO.fromdense(dense.reshape(tuple(shape))))


def slice(x, axes, starts, ends, name=None):
    """reference: sparse/unary.py slice."""
    sp = _sp(x)
    dense = sp.todense() if hasattr(sp, "todense") else unwrap(x)
    idx = [builtins_slice(None)] * dense.ndim
    for ax, s, e in zip(axes, starts, ends):
        idx[int(ax)] = builtins_slice(int(s), int(e))
    return _wrap_coo(jsparse.BCOO.fromdense(dense[tuple(idx)]))


builtins_slice = __builtins__["slice"] if isinstance(__builtins__, dict) \
    else __builtins__.slice


def pca_lowrank(x, q=None, center=True, niter=2, name=None):
    """reference: sparse/unary.py pca_lowrank — randomized PCA of a sparse
    matrix (returns U, S, V)."""
    sp = _sp(x)
    dense = jnp.asarray(sp.todense() if hasattr(sp, "todense")
                        else unwrap(x), jnp.float32)
    m, n = dense.shape
    if q is None:
        q = min(6, m, n)
    if center:
        dense = dense - dense.mean(0, keepdims=True)
    u, s, vt = jnp.linalg.svd(dense, full_matrices=False)
    return Tensor(u[:, :q]), Tensor(s[:q]), Tensor(vt[:q].T)

from . import nn  # noqa: E402,F401
