"""AMP: automatic mixed precision (reference: python/paddle/amp).

On TPU, bf16 is the native mixed-precision dtype and needs no loss scaling —
`GradScaler` is a functional no-op kept for API parity (enabled scaling still
works for fp16 parity testing). `auto_cast` (ref: amp/auto_cast.py:1018)
installs a dtype-cast policy consulted by `dispatch` via an op allow/deny
list mirroring amp_lists (ref: python/paddle/amp/amp_lists.py).
"""
from __future__ import annotations

import contextlib
import threading

import jax.numpy as jnp

from ..core.tensor import Tensor, unwrap
from ..framework import dtype as dtypes

__all__ = ["auto_cast", "amp_guard", "GradScaler", "decorate", "is_float16_supported",
           "is_bfloat16_supported", "white_list", "black_list"]

_state = threading.local()

# reference: python/paddle/amp/amp_lists.py FP16_WHITE_LIST / FP16_BLACK_LIST
WHITE_LIST = {
    "matmul", "linear", "conv1d", "conv2d", "conv3d", "bmm", "mm", "mv",
    "einsum", "flash_attn", "flash_attn_ref", "sdpa",
}
BLACK_LIST = {
    "exp", "log", "log2", "log10", "log1p", "pow", "square", "reciprocal",
    "softmax", "log_softmax", "cross_entropy", "bce_with_logits",
    "binary_cross_entropy", "layer_norm", "rms_norm", "batch_norm",
    "instance_norm", "group_norm", "mean", "sum", "cumsum", "logsumexp",
    "softmax_with_cross_entropy", "nll_loss", "kl_div", "cosine_similarity",
}


def white_list():
    return WHITE_LIST


def black_list():
    return BLACK_LIST


def amp_state():
    return getattr(_state, "amp", None)


@contextlib.contextmanager
def auto_cast(enable=True, custom_white_list=None, custom_black_list=None, level="O1",
              dtype="bfloat16", use_promote=True):
    """paddle.amp.auto_cast (ref: python/paddle/amp/auto_cast.py:1018)."""
    prev = amp_state()
    if enable:
        wl = set(WHITE_LIST)
        bl = set(BLACK_LIST)
        if custom_white_list:
            wl |= set(custom_white_list)
            bl -= set(custom_white_list)
        if custom_black_list:
            bl |= set(custom_black_list)
            wl -= set(custom_black_list)
        _state.amp = {
            "dtype": dtypes.convert_dtype(dtype),
            "level": level,
            "white": wl,
            "black": bl,
        }
    else:
        _state.amp = None
    try:
        yield
    finally:
        _state.amp = prev


amp_guard = auto_cast


def maybe_cast_inputs(op_name: str, arrays):
    """Called from core dispatch: cast inputs per active AMP policy.

    O1: white-list ops run in low precision, black-list in fp32, others
    follow inputs. O2: everything except black-list runs in low precision.
    """
    st = amp_state()
    if st is None:
        return arrays
    low = st["dtype"]
    if op_name in st["black"]:
        tgt = jnp.float32
    elif op_name in st["white"] or st["level"] == "O2":
        tgt = low
    else:
        return arrays
    out = []
    for a in arrays:
        if hasattr(a, "dtype") and jnp.issubdtype(a.dtype, jnp.floating) and a.dtype != tgt:
            out.append(a.astype(tgt))
        else:
            out.append(a)
    return out


def decorate(models, optimizers=None, level="O1", dtype="bfloat16", master_weight=None,
             save_dtype=None, master_grad=False, excluded_layers=None):
    """paddle.amp.decorate (ref: auto_cast.py). O2 casts parameters to the
    low-precision dtype (norm layers excluded, matching the reference)."""
    from ..nn.layer.norm import _BatchNormBase, LayerNorm, GroupNorm, RMSNorm

    single = not isinstance(models, (list, tuple))
    model_list = [models] if single else list(models)
    if level == "O2":
        skip = (_BatchNormBase, LayerNorm, GroupNorm, RMSNorm)
        for m in model_list:
            for layer in m.sublayers(include_self=True):
                if isinstance(layer, skip) or (excluded_layers and isinstance(layer, tuple(excluded_layers))):
                    continue
                for p in layer._parameters.values():
                    if p is not None and jnp.issubdtype(p._array.dtype, jnp.floating):
                        p._array = p._array.astype(dtypes.convert_dtype(dtype))
    if optimizers is None:
        return models if single else model_list
    return (models if single else model_list), optimizers


class GradScaler:
    """paddle.amp.GradScaler (ref: python/paddle/amp/grad_scaler.py:645).

    bf16-on-TPU needs no scaling: with default args this is pass-through, but
    dynamic loss scaling is fully implemented for fp16 parity.
    """

    def __init__(self, enable=True, init_loss_scaling=65536.0, incr_ratio=2.0,
                 decr_ratio=0.5, incr_every_n_steps=2000, decr_every_n_nan_or_inf=1,
                 use_dynamic_loss_scaling=True):
        self._enable = enable
        self._scale = float(init_loss_scaling)
        self._incr_ratio = incr_ratio
        self._decr_ratio = decr_ratio
        self._incr_every = incr_every_n_steps
        self._decr_every = decr_every_n_nan_or_inf
        self._dynamic = use_dynamic_loss_scaling
        self._good_steps = 0
        self._bad_steps = 0
        self._found_inf = False

    def scale(self, var):
        if not self._enable:
            return var
        return var * self._scale

    def unscale_(self, optimizer):
        if not self._enable:
            return
        inv = 1.0 / self._scale
        found = False
        for group in optimizer._param_groups:
            for p in group["params"]:
                if p._grad is not None:
                    g = p._grad * inv
                    found = found or bool(jnp.any(~jnp.isfinite(g)))
                    p._grad = g
        self._found_inf = found

    def step(self, optimizer):
        if not self._enable:
            optimizer.step()
            return
        self.unscale_(optimizer)
        if not self._found_inf:
            optimizer.step()
        self.update()

    def minimize(self, optimizer, scaled_loss):
        scaled_loss.backward()
        self.step(optimizer)

    def update(self):
        if not (self._enable and self._dynamic):
            return
        if self._found_inf:
            self._bad_steps += 1
            self._good_steps = 0
            if self._bad_steps >= self._decr_every:
                self._scale = max(self._scale * self._decr_ratio, 1.0)
                self._bad_steps = 0
        else:
            self._good_steps += 1
            self._bad_steps = 0
            if self._good_steps >= self._incr_every:
                self._scale *= self._incr_ratio
                self._good_steps = 0

    def is_enable(self):
        return self._enable

    def is_use_dynamic_loss_scaling(self):
        return self._dynamic

    def get_init_loss_scaling(self):
        return self._scale

    def set_init_loss_scaling(self, v):
        self._scale = float(v)

    def state_dict(self):
        return {"scale": self._scale, "good_steps": self._good_steps, "bad_steps": self._bad_steps}

    def load_state_dict(self, sd):
        self._scale = sd.get("scale", self._scale)
        self._good_steps = sd.get("good_steps", 0)
        self._bad_steps = sd.get("bad_steps", 0)


def is_float16_supported(device=None):
    return True


def is_bfloat16_supported(device=None):
    return True

from . import debugging  # noqa: F401,E402
