"""AMP numerical debugging tools.

Reference: python/paddle/amp/debugging.py — enable_operator_stats_collection,
collect_operator_stats, enable_tensor_checker / TensorCheckerConfig,
compare_accuracy (accuracy_compare.py).

TPU-native: op invocation counts per dtype are collected at the dispatch
layer; the tensor checker rides the FLAGS_check_nan_inf sanitizer.
"""
from __future__ import annotations

import contextlib
from collections import defaultdict
from typing import Dict, Optional

import numpy as np

from ..framework import flags as _flags

__all__ = ["enable_operator_stats_collection",
           "disable_operator_stats_collection", "collect_operator_stats",
           "TensorCheckerConfig", "enable_tensor_checker",
           "disable_tensor_checker", "compare_accuracy"]

_op_stats: Optional[Dict[str, Dict[str, int]]] = None
_checker_config: Optional["TensorCheckerConfig"] = None


def _record_op(name: str, dtype):
    if _op_stats is None or dtype is None:
        return
    _op_stats[name][str(dtype)] += 1


def _should_check(op_name: str) -> bool:
    """Op filter for the NaN/Inf sanitizer (checked/skipped op lists)."""
    cfg = _checker_config
    if cfg is None:
        return True
    if cfg.skipped_op_list and op_name in cfg.skipped_op_list:
        return False
    if cfg.checked_op_list:
        return op_name in cfg.checked_op_list
    return True


def enable_operator_stats_collection():
    """reference: debugging.py enable_operator_stats_collection."""
    global _op_stats
    _op_stats = defaultdict(lambda: defaultdict(int))


def disable_operator_stats_collection():
    """Print the collected table and stop collecting."""
    global _op_stats
    stats = _op_stats
    _op_stats = None
    if not stats:
        print("<no operator stats collected>")
        return {}
    cols = sorted({d for per_op in stats.values() for d in per_op})
    head = f"{'op':<30}" + "".join(f"{c:>12}" for c in cols)
    print(head)
    print("-" * len(head))
    for op in sorted(stats):
        row = f"{op:<30}" + "".join(
            f"{stats[op].get(c, 0):>12}" for c in cols)
        print(row)
    return {k: dict(v) for k, v in stats.items()}


@contextlib.contextmanager
def collect_operator_stats():
    enable_operator_stats_collection()
    try:
        yield
    finally:
        disable_operator_stats_collection()


class TensorCheckerConfig:
    """reference: debugging.py TensorCheckerConfig — enable + op filters.
    debug_step/output_dir/stack_height_limit are accepted for parity but
    not implemented (a warning is emitted if set)."""

    def __init__(self, enable: bool = True, debug_mode=None,
                 output_dir=None, checked_op_list=None,
                 skipped_op_list=None, debug_step=None, stack_height_limit=1):
        self.enable = enable
        self.debug_mode = debug_mode
        self.checked_op_list = set(checked_op_list or [])
        self.skipped_op_list = set(skipped_op_list or [])
        if debug_step or output_dir:
            import warnings

            warnings.warn("TensorCheckerConfig: debug_step/output_dir are "
                          "not implemented; all steps are checked",
                          RuntimeWarning)


def enable_tensor_checker(config: TensorCheckerConfig):
    """Turn on per-op NaN/Inf checking (rides FLAGS_check_nan_inf; op
    filters honored via checked_op_list/skipped_op_list)."""
    global _checker_config
    if config.enable:
        _checker_config = config
        _flags.set_flags({"FLAGS_check_nan_inf": True})


def disable_tensor_checker():
    global _checker_config
    _checker_config = None
    _flags.set_flags({"FLAGS_check_nan_inf": False})


def compare_accuracy(run_fn, dtypes=("float32", "bfloat16"), atol=1e-2,
                     rtol=1e-2):
    """Run `run_fn(dtype) -> Tensor/array` under each dtype and report
    max/mean abs diff vs the first (reference: amp/accuracy_compare.py
    workflow, condensed to a functional form)."""
    from ..core.tensor import Tensor

    results = {}
    for dt in dtypes:
        out = run_fn(dt)
        results[dt] = np.asarray(out.numpy() if isinstance(out, Tensor)
                                 else out, np.float64)
    base_key = dtypes[0]
    base = results[base_key]
    report = {}
    for dt in dtypes[1:]:
        diff = np.abs(results[dt] - base)
        denom = np.maximum(np.abs(base), 1e-12)
        report[dt] = {"max_abs_diff": float(diff.max()),
                      "mean_abs_diff": float(diff.mean()),
                      "max_rel_diff": float((diff / denom).max()),
                      "within_tol": bool(np.allclose(
                          results[dt], base, atol=atol, rtol=rtol))}
        print(f"{base_key} vs {dt}: {report[dt]}")
    return report
