"""paddle.autograd equivalent.

Reference: python/paddle/autograd (backward, PyLayer at py_layer.py:270,
functional jvp/vjp/jacobian/hessian in autograd.py). The tape lives in
core/tape.py; PyLayer maps to a custom-vjp dispatch record; the functional
transforms delegate to jax.jvp/jax.vjp/jax.jacobian on the unwrapped pure
function.
"""
from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp

from ..core.tape import backward, no_grad, enable_grad, set_grad_enabled, grad_enabled
from ..core import tape as _tape
from ..core.tensor import Tensor, dispatch, unwrap

__all__ = [
    "backward", "no_grad", "enable_grad", "set_grad_enabled", "is_grad_enabled",
    "grad", "PyLayer", "PyLayerContext", "jvp", "vjp", "jacobian", "hessian",
]


def is_grad_enabled():
    return grad_enabled()


def grad(
    outputs,
    inputs,
    grad_outputs=None,
    retain_graph=None,
    create_graph=False,
    only_inputs=True,
    allow_unused=False,
    no_grad_vars=None,
):
    """paddle.grad (reference: python/paddle/base/dygraph/base.py `grad`).

    Runs the tape backward but collects cotangents for `inputs` instead of
    writing `.grad`.
    """
    outs = [outputs] if isinstance(outputs, Tensor) else list(outputs)
    ins = [inputs] if isinstance(inputs, Tensor) else list(inputs)
    # snapshot + restore .grad around a tape sweep
    saved = [t._grad for t in ins]
    for t in ins:
        t._grad = None
    # ensure inputs are treated as leaves for accumulation: temporarily mark
    prev_nodes = [t._node for t in ins]
    stops = [t.stop_gradient for t in ins]
    for t in ins:
        t.stop_gradient = False
    _tape.backward(outs, grad_outputs, retain_graph=bool(retain_graph or create_graph))
    result = []
    for t, s, pn, sv in zip(ins, stops, prev_nodes, saved):
        g = t._grad
        if g is None and not allow_unused:
            g = jnp.zeros_like(t._array)
        result.append(Tensor(g) if g is not None else None)
        t._grad = sv
        t.stop_gradient = s
    return result


class PyLayerContext:
    """ctx object passed to PyLayer.forward/backward
    (ref: python/paddle/autograd/py_layer.py)."""

    def __init__(self):
        self._saved = ()
        self.non_differentiable = ()

    def save_for_backward(self, *tensors):
        self._saved = tensors

    @property
    def saved_tensor(self):
        return self._saved

    def saved_tensors(self):
        return self._saved

    def mark_non_differentiable(self, *tensors):
        self.non_differentiable = tensors


class PyLayer:
    """Custom autograd op via subclassing (reference:
    python/paddle/autograd/py_layer.py:270). forward/backward receive a ctx;
    apply() records a TapeNode whose vjp calls the user backward."""

    @staticmethod
    def forward(ctx, *args, **kwargs):
        raise NotImplementedError

    @staticmethod
    def backward(ctx, *grads):
        raise NotImplementedError

    @classmethod
    def apply(cls, *args, **kwargs):
        ctx = PyLayerContext()
        with no_grad():
            out = cls.forward(ctx, *args, **kwargs)
        single = not isinstance(out, (tuple, list))
        outs = (out,) if single else tuple(out)

        diff_inputs = [
            a for a in args if isinstance(a, Tensor) and not a.stop_gradient
        ]
        if _tape.grad_enabled() and diff_inputs:
            tensor_args = [a for a in args if isinstance(a, Tensor)]

            def vjp_fn(cts):
                if not isinstance(cts, tuple):
                    cts = (cts,)
                ct_tensors = tuple(Tensor(c) for c in cts)
                with no_grad():
                    gin = cls.backward(ctx, *ct_tensors)
                if not isinstance(gin, (tuple, list)):
                    gin = (gin,)
                # map returned grads to the diff inputs (paddle: one grad per
                # forward tensor input, in order)
                grads_arr = []
                gi = list(gin)
                for a in tensor_args:
                    g = gi.pop(0) if gi else None
                    if a in diff_inputs:
                        grads_arr.append(unwrap(g) if g is not None else None)
                return tuple(
                    g if g is not None else jnp.zeros_like(t._array)
                    for g, t in zip(grads_arr, diff_inputs)
                )

            node = _tape.TapeNode(cls.__name__, vjp_fn, diff_inputs, len(outs))
            wrapped = []
            nd_set = {id(t) for t in ctx.non_differentiable}
            node._out_shapes = [
                (tuple(o.shape), o.dtype) for o in outs
            ]
            for i, o in enumerate(outs):
                t = o if isinstance(o, Tensor) else Tensor(o)
                if id(t) not in nd_set:
                    t.stop_gradient = False
                    t._node = node
                    t._out_idx = i
                    node.register_output(i, t)
                wrapped.append(t)
            return wrapped[0] if single else tuple(wrapped)
        return out


# ------------------------- functional transforms -------------------------


def _functionalize(func):
    def fn(*arrs):
        outs = func(*[Tensor(a) for a in arrs])
        if isinstance(outs, (tuple, list)):
            return tuple(unwrap(o) for o in outs)
        return unwrap(outs)

    return fn


def jvp(func, xs, v=None):
    """Forward-mode JVP (ref: python/paddle/autograd/autograd.py)."""
    xs_t = (xs,) if isinstance(xs, Tensor) else tuple(xs)
    arrs = tuple(unwrap(x) for x in xs_t)
    if v is None:
        tangents = tuple(jnp.ones_like(a) for a in arrs)
    else:
        v_t = (v,) if isinstance(v, Tensor) else tuple(v)
        tangents = tuple(unwrap(t) for t in v_t)
    out, tangent_out = jax.jvp(_functionalize(func), arrs, tangents)
    w = lambda o: Tensor(o)
    if isinstance(out, tuple):
        return tuple(map(w, out)), tuple(map(w, tangent_out))
    return w(out), w(tangent_out)


def vjp(func, xs, v=None):
    xs_t = (xs,) if isinstance(xs, Tensor) else tuple(xs)
    arrs = tuple(unwrap(x) for x in xs_t)
    out, vjp_fn = jax.vjp(_functionalize(func), *arrs)
    if v is None:
        cots = jnp.ones_like(out) if not isinstance(out, tuple) else tuple(jnp.ones_like(o) for o in out)
    else:
        v_t = v if isinstance(v, Tensor) else v
        cots = unwrap(v_t) if isinstance(v_t, Tensor) else tuple(unwrap(t) for t in v_t)
    grads = vjp_fn(cots)
    w = lambda o: Tensor(o)
    out_w = tuple(map(w, out)) if isinstance(out, tuple) else w(out)
    grads_w = tuple(map(w, grads))
    return out_w, grads_w[0] if len(grads_w) == 1 and isinstance(xs, Tensor) else grads_w


class Jacobian:
    """Lazy Jacobian (ref: autograd.autograd.Jacobian)."""

    def __init__(self, arr):
        self._arr = arr

    def __getitem__(self, idx):
        return Tensor(self._arr[idx])

    def numpy(self):
        import numpy as np

        return np.asarray(self._arr)

    @property
    def shape(self):
        return list(self._arr.shape)


def jacobian(func, xs, is_batched=False):
    xs_t = (xs,) if isinstance(xs, Tensor) else tuple(xs)
    arrs = tuple(unwrap(x) for x in xs_t)
    jac = jax.jacrev(_functionalize(func), argnums=tuple(range(len(arrs))))(*arrs)
    if isinstance(xs, Tensor):
        j = jac[0] if isinstance(jac, tuple) else jac
        return Jacobian(j)
    return tuple(Jacobian(j) for j in jac)


def hessian(func, xs, is_batched=False):
    xs_t = (xs,) if isinstance(xs, Tensor) else tuple(xs)
    arrs = tuple(unwrap(x) for x in xs_t)
    h = jax.hessian(_functionalize(func), argnums=tuple(range(len(arrs))))(*arrs)
    if isinstance(xs, Tensor):
        hh = h[0][0] if isinstance(h, tuple) else h
        return Jacobian(hh)
    return tuple(tuple(Jacobian(hj) for hj in hrow) for hrow in h)


class saved_tensors_hooks:
    """paddle.autograd.saved_tensors_hooks compatibility (used by recompute)."""

    def __init__(self, pack_hook, unpack_hook):
        self.pack_hook = pack_hook
        self.unpack_hook = unpack_hook

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False
