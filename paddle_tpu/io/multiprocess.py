"""Multiprocess DataLoader iterator over the native shm ring.

Reference: python/paddle/io/dataloader/dataloader_iter.py
(_DataLoaderIterMultiProcess) + worker.py — worker processes pull index
batches from a queue, materialize + collate samples, and return batches
through shared memory; the parent reorders by batch index.

Transport: batches are serialized as raw numpy buffers (zero pickling for
the tensor payload) into the native MPSC ring (native/shm_ring.cpp); a
pickle fallback covers non-array structures and oversized batches.
"""
from __future__ import annotations

import multiprocessing as mp
import os
import pickle
import queue as pyqueue
import struct
from typing import Optional

import numpy as np

from ._native import ShmRing

_MAGIC = 0x5044
_MODE_ARRAYS = 0
_MODE_PICKLE = 1


def _flatten_batch(batch):
    """Decompose a collated batch into (structure, [np arrays]) if it is a
    (nested) tuple/list/dict of Tensors/ndarrays; None if not encodable."""
    from ..core.tensor import Tensor

    arrays = []

    def rec(x):
        if isinstance(x, Tensor):
            arrays.append(np.asarray(x._array))
            return ("t", len(arrays) - 1)
        if isinstance(x, np.ndarray):
            arrays.append(x)
            return ("a", len(arrays) - 1)
        if isinstance(x, (list, tuple)):
            return ("l" if isinstance(x, list) else "u",
                    [rec(v) for v in x])
        if isinstance(x, dict):
            return ("d", {k: rec(v) for k, v in x.items()})
        raise TypeError

    try:
        return rec(batch), arrays
    except TypeError:
        return None, None


def _rebuild(node, arrays):
    from ..core.tensor import Tensor

    kind, payload = node
    if kind == "t":
        return Tensor(arrays[payload])
    if kind == "a":
        return arrays[payload]
    if kind == "l":
        return [_rebuild(v, arrays) for v in payload]
    if kind == "u":
        return tuple(_rebuild(v, arrays) for v in payload)
    if kind == "d":
        return {k: _rebuild(v, arrays) for k, v in payload.items()}
    raise ValueError(kind)


def encode_batch(batch_idx: int, batch) -> bytes:
    structure, arrays = _flatten_batch(batch)
    if structure is None:
        body = pickle.dumps(batch, protocol=pickle.HIGHEST_PROTOCOL)
        return struct.pack("<HBQ", _MAGIC, _MODE_PICKLE, batch_idx) + body
    head = struct.pack("<HBQ", _MAGIC, _MODE_ARRAYS, batch_idx)
    sbytes = pickle.dumps(structure, protocol=pickle.HIGHEST_PROTOCOL)
    parts = [head, struct.pack("<I", len(sbytes)), sbytes,
             struct.pack("<I", len(arrays))]
    for a in arrays:
        a = np.ascontiguousarray(a)
        dt = str(a.dtype).encode()
        parts.append(struct.pack("<B", len(dt)))
        parts.append(dt)
        parts.append(struct.pack("<B", a.ndim))
        parts.append(struct.pack(f"<{a.ndim}q", *a.shape))
        parts.append(struct.pack("<Q", a.nbytes))
        parts.append(a.tobytes())
    return b"".join(parts)


def decode_batch(data: bytes):
    magic, mode, batch_idx = struct.unpack_from("<HBQ", data, 0)
    off = struct.calcsize("<HBQ")
    if magic != _MAGIC:
        raise ValueError("corrupt batch message")
    if mode == _MODE_PICKLE:
        return batch_idx, pickle.loads(data[off:])
    (slen,) = struct.unpack_from("<I", data, off)
    off += 4
    structure = pickle.loads(data[off:off + slen])
    off += slen
    (n,) = struct.unpack_from("<I", data, off)
    off += 4
    arrays = []
    for _ in range(n):
        (dl,) = struct.unpack_from("<B", data, off)
        off += 1
        dt = data[off:off + dl].decode()
        off += dl
        (nd,) = struct.unpack_from("<B", data, off)
        off += 1
        shape = struct.unpack_from(f"<{nd}q", data, off)
        off += 8 * nd
        (nb,) = struct.unpack_from("<Q", data, off)
        off += 8
        arrays.append(np.frombuffer(data, dtype=dt, count=nb
                                    // np.dtype(dt).itemsize,
                                    offset=off).reshape(shape))
        off += nb
    return batch_idx, _rebuild(structure, arrays)


def _worker_loop(dataset, collate_fn, index_queue, ring_name, fallback_queue,
                 worker_id, num_workers, worker_init_fn, seed):
    """Runs in a child process (reference: io/dataloader/worker.py
    _worker_loop)."""
    # workers do host-side numpy work only; never let a worker grab the
    # parent's accelerator
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass
    from . import WorkerInfo, _worker_info

    _worker_info.info = WorkerInfo(worker_id, num_workers, dataset)
    if worker_init_fn is not None:
        worker_init_fn(worker_id)
    np.random.seed((seed + worker_id) % (2 ** 31))
    ring = ShmRing.open(ring_name)
    while True:
        item = index_queue.get()
        if item is None:
            break
        batch_idx, indices = item
        try:
            batch = collate_fn([dataset[i] for i in indices])
            msg = encode_batch(batch_idx, batch)
            if ring is not None and len(msg) <= ring.slot_size:
                if ring.push(msg, timeout_ms=-1) == 0:
                    continue
            fallback_queue.put((batch_idx, pickle.dumps(batch)))
        except Exception as e:  # surface worker errors to the parent
            fallback_queue.put((batch_idx, e))
    if ring is not None:
        ring.close()


class MultiprocessIter:
    """Ordered multiprocess prefetch iterator."""

    def __init__(self, loader, slot_mb: int = 64):
        self.loader = loader
        self.num_workers = loader.num_workers
        # spawn, not fork: the parent holds live JAX threads and a TPU
        # client; forking that process is deadlock-prone
        ctx = mp.get_context("spawn")
        self.index_queue = ctx.Queue()
        self.fallback_queue = ctx.Queue()
        ring_name = f"/pdtpu_ring_{os.getpid()}_{id(self)}"
        self.ring = ShmRing.create(ring_name, slot_mb * 1024 * 1024,
                                   max(2, 2 * self.num_workers))
        self.batches = list(loader.batch_sampler)
        self.n_batches = len(self.batches)
        self.next_submit = 0
        self.next_yield = 0
        self.reorder = {}
        self.workers = []
        seed = int.from_bytes(os.urandom(4), "little")
        # children inherit the environment at spawn: pin them to the CPU
        # backend so no worker touches the parent's accelerator
        saved_platform = os.environ.get("JAX_PLATFORMS")
        os.environ["JAX_PLATFORMS"] = "cpu"
        try:
            for w in range(self.num_workers):
                p = ctx.Process(
                    target=_worker_loop,
                    args=(loader.dataset, loader.collate_fn,
                          self.index_queue, ring_name, self.fallback_queue,
                          w, self.num_workers, loader.worker_init_fn, seed),
                    daemon=True)
                p.start()
                self.workers.append(p)
        except Exception:
            # partial start-up failure: reap already-launched workers and
            # unlink the shm segment before surfacing the error
            self.shutdown()
            raise
        finally:
            if saved_platform is None:
                os.environ.pop("JAX_PLATFORMS", None)
            else:
                os.environ["JAX_PLATFORMS"] = saved_platform
        # prefill
        for _ in range(self.num_workers * loader.prefetch_factor):
            self._submit()

    def _submit(self):
        if self.next_submit < self.n_batches:
            self.index_queue.put((self.next_submit,
                                  self.batches[self.next_submit]))
            self.next_submit += 1

    def _drain_fallback(self):
        while True:
            try:
                idx, payload = self.fallback_queue.get_nowait()
            except pyqueue.Empty:
                return
            if isinstance(payload, Exception):
                self.shutdown()
                raise payload
            self.reorder[idx] = pickle.loads(payload)

    def __iter__(self):
        return self

    def __next__(self):
        if self.next_yield >= self.n_batches:
            self.shutdown()
            raise StopIteration
        while self.next_yield not in self.reorder:
            self._drain_fallback()
            if self.next_yield in self.reorder:
                break
            if self.ring is not None:
                msg = self.ring.pop(timeout_ms=100)
                if msg is not None:
                    idx, batch = decode_batch(msg)
                    self.reorder[idx] = batch
            else:
                try:
                    idx, payload = self.fallback_queue.get(timeout=0.1)
                    if isinstance(payload, Exception):
                        self.shutdown()
                        raise payload
                    self.reorder[idx] = pickle.loads(payload)
                except pyqueue.Empty:
                    pass
            if not any(w.is_alive() for w in self.workers) \
                    and self.next_yield not in self.reorder:
                self._drain_fallback()
                if self.next_yield not in self.reorder:
                    self.shutdown()
                    raise RuntimeError("DataLoader workers exited "
                                       "unexpectedly")
        batch = self.reorder.pop(self.next_yield)
        self.next_yield += 1
        self._submit()
        return batch

    def shutdown(self):
        for _ in self.workers:
            try:
                self.index_queue.put(None)
            except Exception:
                pass
        for w in self.workers:
            w.join(timeout=2)
            if w.is_alive():
                w.terminate()
        self.workers = []
        if self.ring is not None:
            self.ring.close()
            self.ring = None

    def __del__(self):
        try:
            self.shutdown()
        except Exception:
            pass
