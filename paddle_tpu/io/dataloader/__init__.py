"""paddle.io.dataloader (reference: python/paddle/io/dataloader/__init__.py)
— internal module layout re-exported from the io package implementation."""
from .. import (  # noqa: F401
    BatchSampler,
    ChainDataset,
    ConcatDataset,
    Dataset,
    DistributedBatchSampler,
    IterableDataset,
    RandomSampler,
    Sampler,
    SequenceSampler,
    Subset,
    SubsetRandomSampler,
    TensorDataset,
    WeightedRandomSampler,
    get_worker_info,
    random_split,
)
from . import collate  # noqa: F401
