"""paddle.io.dataloader.collate (reference:
python/paddle/io/dataloader/collate.py)."""
from .. import default_collate_fn  # noqa: F401


def default_convert_fn(batch):
    """reference: dataloader/collate.py default_convert_fn — identity
    conversion for already-tensor samples."""
    return batch
