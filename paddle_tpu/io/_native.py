"""ctypes binding + on-demand build of the native shm ring
(native/shm_ring.cpp). pybind11 is deliberately avoided — a stable C ABI
via ctypes keeps the binding dependency-free (see repo environment notes).
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading

_lock = threading.Lock()
_lib = None
_tried = False

_SRC = os.path.join(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))), "native", "shm_ring.cpp")
_OUT_DIR = os.path.join(os.path.dirname(_SRC), "build")
_OUT = os.path.join(_OUT_DIR, "libshm_ring.so")


def _build() -> str | None:
    if os.path.exists(_OUT) and os.path.getmtime(_OUT) >= os.path.getmtime(
            _SRC):
        return _OUT
    os.makedirs(_OUT_DIR, exist_ok=True)
    cmd = ["g++", "-O2", "-shared", "-fPIC", "-std=c++17", _SRC, "-o", _OUT,
           "-lpthread", "-lrt"]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        return _OUT
    except Exception:
        return None


def get_lib():
    """Load (building if needed) the native library; None if unavailable."""
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        path = _build()
        if path is None:
            return None
        try:
            lib = ctypes.CDLL(path)
        except OSError:
            return None
        lib.shm_ring_create.restype = ctypes.c_void_p
        lib.shm_ring_create.argtypes = [ctypes.c_char_p, ctypes.c_uint64,
                                        ctypes.c_uint32]
        lib.shm_ring_open.restype = ctypes.c_void_p
        lib.shm_ring_open.argtypes = [ctypes.c_char_p]
        lib.shm_ring_push.restype = ctypes.c_int
        lib.shm_ring_push.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                      ctypes.c_uint64, ctypes.c_int]
        lib.shm_ring_pop.restype = ctypes.c_int64
        lib.shm_ring_pop.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                     ctypes.c_uint64, ctypes.c_int]
        lib.shm_ring_close.restype = None
        lib.shm_ring_close.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.shm_ring_slot_size.restype = ctypes.c_uint64
        lib.shm_ring_slot_size.argtypes = [ctypes.c_void_p]
        _lib = lib
        return _lib


class ShmRing:
    """Python handle over the native ring (create in parent, open in
    workers)."""

    def __init__(self, handle, lib, name: str, owner: bool):
        self._h = handle
        self._lib = lib
        self.name = name
        self._owner = owner
        self.slot_size = int(lib.shm_ring_slot_size(handle))
        # one reusable receive buffer per ring (the consumer side is
        # single-threaded by design) — allocating slot_size per pop would
        # memset tens of MB on every empty poll tick
        self._rxbuf = None

    @classmethod
    def create(cls, name: str, slot_size: int, n_slots: int):
        lib = get_lib()
        if lib is None:
            return None
        h = lib.shm_ring_create(name.encode(), slot_size, n_slots)
        if not h:
            return None
        return cls(h, lib, name, owner=True)

    @classmethod
    def open(cls, name: str):
        lib = get_lib()
        if lib is None:
            return None
        h = lib.shm_ring_open(name.encode())
        if not h:
            return None
        return cls(h, lib, name, owner=False)

    def push(self, data: bytes, timeout_ms: int = -1) -> int:
        return self._lib.shm_ring_push(self._h, data, len(data), timeout_ms)

    def pop(self, timeout_ms: int = -1):
        if self._rxbuf is None:
            self._rxbuf = ctypes.create_string_buffer(self.slot_size)
        n = self._lib.shm_ring_pop(self._h, self._rxbuf, self.slot_size,
                                   timeout_ms)
        if n < 0:
            return None
        # bytearray keeps the payload WRITABLE so np.frombuffer views over
        # it are mutable (parity with the single-process path)
        return bytearray(memoryview(self._rxbuf)[:n])

    def close(self):
        if self._h:
            self._lib.shm_ring_close(self._h, 1 if self._owner else 0)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
