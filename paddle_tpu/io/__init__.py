"""paddle.io: Dataset / DataLoader / samplers.

Reference: python/paddle/io/reader.py:266 (DataLoader with multiprocess
workers in io/dataloader/dataloader_iter.py, worker.py). TPU-native design:
the host input pipeline feeds numpy batches; `DataLoader` supports
num_workers>0 via a multiprocessing prefetch pool, and batches are converted
to device arrays on iteration (device_put overlap is handled by JAX's async
dispatch).
"""
from __future__ import annotations

import itertools
import math
import queue
import sys
import threading
from typing import Any, Iterable, List, Optional

import numpy as np

from ..core.tensor import Tensor
from ..framework.random import default_generator

__all__ = [
    "Dataset", "IterableDataset", "TensorDataset", "ComposeDataset",
    "ChainDataset", "Subset", "ConcatDataset", "random_split", "DataLoader",
    "BatchSampler", "Sampler", "SequenceSampler", "RandomSampler",
    "WeightedRandomSampler", "DistributedBatchSampler", "SubsetRandomSampler",
    "get_worker_info", "default_collate_fn",
]


class Dataset:
    def __getitem__(self, idx):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError


class IterableDataset(Dataset):
    def __iter__(self):
        raise NotImplementedError

    def __getitem__(self, idx):
        raise RuntimeError("IterableDataset does not support indexing")

    def __len__(self):
        raise RuntimeError("IterableDataset has no len()")


class TensorDataset(Dataset):
    def __init__(self, tensors):
        self.tensors = tensors

    def __getitem__(self, idx):
        return tuple(t[idx] for t in self.tensors)

    def __len__(self):
        return self.tensors[0].shape[0]


class ComposeDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)

    def __len__(self):
        return min(len(d) for d in self.datasets)

    def __getitem__(self, idx):
        out = []
        for d in self.datasets:
            sample = d[idx]
            if isinstance(sample, (list, tuple)):
                out.extend(sample)
            else:
                out.append(sample)
        return tuple(out)


class ChainDataset(IterableDataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)

    def __iter__(self):
        for d in self.datasets:
            yield from d


class ConcatDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)
        self.cum = list(itertools.accumulate(len(d) for d in self.datasets))

    def __len__(self):
        return self.cum[-1]

    def __getitem__(self, idx):
        if idx < 0:
            idx += len(self)
        import bisect

        i = bisect.bisect_right(self.cum, idx)
        prev = self.cum[i - 1] if i > 0 else 0
        return self.datasets[i][idx - prev]


class Subset(Dataset):
    def __init__(self, dataset, indices):
        self.dataset = dataset
        self.indices = list(indices)

    def __getitem__(self, idx):
        return self.dataset[self.indices[idx]]

    def __len__(self):
        return len(self.indices)


def random_split(dataset, lengths, generator=None):
    n = len(dataset)
    if all(isinstance(l, float) for l in lengths) and abs(sum(lengths) - 1.0) < 1e-6:
        lengths = [int(math.floor(n * l)) for l in lengths]
        lengths[-1] = n - sum(lengths[:-1])
    if sum(lengths) != n:
        raise ValueError("sum of lengths must equal dataset size")
    perm = np.random.permutation(n)
    out, off = [], 0
    for l in lengths:
        out.append(Subset(dataset, perm[off : off + l].tolist()))
        off += l
    return out


# ------------------------- samplers -------------------------


class Sampler:
    def __init__(self, data_source=None):
        self.data_source = data_source

    def __iter__(self):
        raise NotImplementedError

    def __len__(self):
        return len(self.data_source)


class SequenceSampler(Sampler):
    def __iter__(self):
        return iter(range(len(self.data_source)))


class RandomSampler(Sampler):
    def __init__(self, data_source, replacement=False, num_samples=None, generator=None):
        super().__init__(data_source)
        self.replacement = replacement
        self._num_samples = num_samples
        self.generator = generator

    @property
    def num_samples(self):
        return self._num_samples if self._num_samples is not None else len(self.data_source)

    def __iter__(self):
        n = len(self.data_source)
        if self.replacement:
            return iter(np.random.randint(0, n, self.num_samples).tolist())
        return iter(np.random.permutation(n)[: self.num_samples].tolist())

    def __len__(self):
        return self.num_samples


class SubsetRandomSampler(Sampler):
    def __init__(self, indices):
        self.indices = list(indices)

    def __iter__(self):
        return iter(np.random.permutation(self.indices).tolist())

    def __len__(self):
        return len(self.indices)


class WeightedRandomSampler(Sampler):
    def __init__(self, weights, num_samples, replacement=True):
        self.weights = np.asarray([float(w) for w in weights])
        self.num_samples = num_samples
        self.replacement = replacement

    def __iter__(self):
        p = self.weights / self.weights.sum()
        return iter(np.random.choice(len(self.weights), self.num_samples, replace=self.replacement, p=p).tolist())

    def __len__(self):
        return self.num_samples


class BatchSampler(Sampler):
    """Reference: python/paddle/io/dataloader/batch_sampler.py."""

    def __init__(self, dataset=None, sampler=None, shuffle=False, batch_size=1, drop_last=False):
        self.batch_size = batch_size
        self.drop_last = drop_last
        if sampler is not None:
            self.sampler = sampler
        elif shuffle:
            self.sampler = RandomSampler(dataset)
        else:
            self.sampler = SequenceSampler(dataset)

    def __iter__(self):
        batch = []
        for idx in self.sampler:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        n = len(self.sampler)
        return n // self.batch_size if self.drop_last else (n + self.batch_size - 1) // self.batch_size


class DistributedBatchSampler(BatchSampler):
    """Shards indices across data-parallel ranks (reference:
    python/paddle/io/dataloader/batch_sampler.py:DistributedBatchSampler).
    Under single-controller JAX, rank/nranks default to the dp axis of the
    active mesh (one process sees all devices, so the default is the
    whole-batch path; explicit ranks support multi-host)."""

    def __init__(self, dataset, batch_size, num_replicas=None, rank=None, shuffle=False, drop_last=False):
        self.dataset = dataset
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.drop_last = drop_last
        from ..parallel.env import get_rank, get_world_size

        self.nranks = num_replicas if num_replicas is not None else get_world_size()
        self.local_rank = rank if rank is not None else get_rank()
        self.epoch = 0
        self.num_samples = int(math.ceil(len(dataset) / self.nranks))
        self.total_size = self.num_samples * self.nranks

    def __iter__(self):
        n = len(self.dataset)
        indices = np.arange(n)
        if self.shuffle:
            rng = np.random.default_rng(self.epoch)
            rng.shuffle(indices)
        # pad to evenly divisible
        pad = self.total_size - n
        if pad > 0:
            indices = np.concatenate([indices, indices[:pad]])
        indices = indices[self.local_rank : self.total_size : self.nranks]
        batch = []
        for idx in indices.tolist():
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        return self.num_samples // self.batch_size if self.drop_last else (self.num_samples + self.batch_size - 1) // self.batch_size

    def set_epoch(self, epoch):
        self.epoch = epoch


# ------------------------- collate & worker info -------------------------


_worker_info = threading.local()


def get_worker_info():
    return getattr(_worker_info, "info", None)


class WorkerInfo:
    def __init__(self, id, num_workers, dataset):
        self.id = id
        self.num_workers = num_workers
        self.dataset = dataset


def default_collate_fn(batch):
    """Stack samples into batch arrays (reference:
    python/paddle/io/dataloader/collate.py)."""
    sample = batch[0]
    if isinstance(sample, Tensor):
        return Tensor(np.stack([np.asarray(b._array) for b in batch]))
    if isinstance(sample, np.ndarray):
        return Tensor(np.stack(batch))
    if isinstance(sample, (int, float, np.generic)):
        return Tensor(np.asarray(batch))
    if isinstance(sample, (str, bytes)):
        return batch
    if isinstance(sample, dict):
        return {k: default_collate_fn([b[k] for b in batch]) for k in sample}
    if isinstance(sample, (list, tuple)):
        return tuple(default_collate_fn(list(items)) for items in zip(*batch))
    return batch


class DataLoader:
    """paddle.io.DataLoader (reference: python/paddle/io/reader.py:266).

    num_workers>0 uses a thread prefetch pool (the heavy lifting — decode,
    augment — is numpy which releases the GIL; a C-accelerated shared-memory
    worker pool is the planned upgrade, mirroring the reference's
    _DataLoaderIterMultiProcess).
    """

    def __init__(self, dataset, feed_list=None, places=None, return_list=True,
                 batch_sampler=None, batch_size=1, shuffle=False, drop_last=False,
                 collate_fn=None, num_workers=0, use_buffer_reader=True,
                 prefetch_factor=2, use_shared_memory=True, timeout=0,
                 worker_init_fn=None, persistent_workers=False,
                 retry_policy=None):
        self.dataset = dataset
        self.collate_fn = collate_fn or default_collate_fn
        self.num_workers = num_workers
        self.prefetch_factor = prefetch_factor
        self.worker_init_fn = worker_init_fn
        self.use_shared_memory = use_shared_memory
        # transient-IOError retry around sample fetch (disk/NFS/object
        # stores flake under load); default sizes from
        # FLAGS_io_retry_attempts — see resilience/retry.py
        if retry_policy is None:
            from ..resilience.retry import default_io_policy

            retry_policy = default_io_policy()
        self.retry_policy = retry_policy
        self._iterable_mode = isinstance(dataset, IterableDataset)
        if batch_sampler is not None:
            self.batch_sampler = batch_sampler
        elif not self._iterable_mode and batch_size is not None:
            self.batch_sampler = BatchSampler(dataset, shuffle=shuffle, batch_size=batch_size, drop_last=drop_last)
        else:
            self.batch_sampler = None
        self.batch_size = batch_size
        self.drop_last = drop_last

    def __len__(self):
        if self._iterable_mode:
            raise TypeError("IterableDataset has no len()")
        if self.batch_sampler is None:
            return len(self.dataset)
        return len(self.batch_sampler)

    def _fetch(self, indices):
        from ..resilience import chaos

        injector = chaos.get_chaos()  # resolved once per batch, not per sample

        def read_one(i):
            if injector is not None:
                injector.maybe_io_error("dataloader.fetch")
            return self.dataset[i]

        # per-sample retry: one flaky read must not re-run the whole
        # batch's (potentially expensive) decode/augment work
        samples = [self.retry_policy.call(read_one, i) for i in indices]
        return self.collate_fn(samples)

    def _iter_single(self):
        if self._iterable_mode:
            batch = []
            for sample in self.dataset:
                if self.batch_size is None:
                    yield sample
                    continue
                batch.append(sample)
                if len(batch) == self.batch_size:
                    yield self.collate_fn(batch)
                    batch = []
            if batch and not self.drop_last and self.batch_size is not None:
                yield self.collate_fn(batch)
            return
        if self.batch_sampler is None:
            for i in range(len(self.dataset)):
                yield self.dataset[i]
            return
        for indices in self.batch_sampler:
            yield self._fetch(indices)

    def _iter_workers(self):
        """Thread-pool prefetch with bounded queue (ordered)."""
        from concurrent.futures import ThreadPoolExecutor

        assert self.batch_sampler is not None
        max_pending = self.num_workers * self.prefetch_factor
        with ThreadPoolExecutor(self.num_workers) as pool:
            pending = []
            it = iter(self.batch_sampler)
            try:
                for _ in range(max_pending):
                    pending.append(pool.submit(self._fetch, next(it)))
            except StopIteration:
                pass
            while pending:
                fut = pending.pop(0)
                yield fut.result()
                try:
                    pending.append(pool.submit(self._fetch, next(it)))
                except StopIteration:
                    pass

    def __iter__(self):
        if self.num_workers > 0 and not self._iterable_mode and self.batch_sampler is not None:
            if self.use_shared_memory and sys.platform.startswith("linux"):
                # native path: worker processes + shm ring transport
                # (reference: _DataLoaderIterMultiProcess)
                try:
                    from .multiprocess import MultiprocessIter

                    return MultiprocessIter(self)
                except Exception as e:
                    import warnings

                    warnings.warn(
                        f"multiprocess DataLoader unavailable ({e!r}); "
                        "falling back to thread prefetch", RuntimeWarning)
            return self._iter_workers()
        return self._iter_single()
