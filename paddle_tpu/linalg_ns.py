"""paddle.linalg namespace (reference: python/paddle/linalg.py re-exports)."""
from .ops.linalg import *  # noqa: F401,F403
from .ops.linalg import __all__  # noqa: F401
from .ops.math import trace  # noqa: F401
from .ops.linalg import inverse as inv  # noqa: F401
