"""Llama decoder family — the flagship benchmark model.

Reference anchor: test/auto_parallel/hybrid_strategy/
semi_auto_parallel_llama_model.py (the reference's own Llama used for hybrid
dp/mp/pp accuracy tests) and the fused-op family it rides
(fused_rotary_position_embedding, swiglu, rms_norm).

TPU-first design:
- weights are plain Layer parameters annotated with NamedSharding via
  logical-axis rules (`shard_llama`) — TP (mp), FSDP (sharding), and
  sequence/context parallel (sep) all come from ONE mesh; XLA SPMD inserts
  the collectives.
- attention runs the Pallas flash-attention kernel; norm runs the fused
  RMSNorm kernel; RoPE/swiglu are XLA-fused elementwise ops.
- optional per-layer rematerialisation (jax.checkpoint) trades FLOPs for
  HBM, replacing the reference's RecomputeFunction PyLayer
  (fleet/recompute/recompute.py:109).
"""
from __future__ import annotations

import dataclasses
import functools
import math
from collections import OrderedDict
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..core.tensor import Tensor, dispatch, unwrap
from ..core import tape as _tape
from ..nn import functional as F
from ..nn.layer.layers import Layer
from ..nn.layer.common import Linear, Embedding
from ..kernels.rms_norm import rms_norm as _k_rms
from ..kernels.rope import rope_freqs, apply_rotary_emb
from ..parallel import mesh as mesh_mod


@dataclasses.dataclass
class LlamaConfig:
    vocab_size: int = 32000
    hidden_size: int = 4096
    intermediate_size: int = 11008
    num_hidden_layers: int = 32
    num_attention_heads: int = 32
    num_key_value_heads: Optional[int] = None
    max_position_embeddings: int = 4096
    rms_norm_eps: float = 1e-6
    rope_theta: float = 10000.0
    tie_word_embeddings: bool = False
    use_flash_attention: bool = True
    recompute: bool = False          # per-layer remat
    # skip remat for the last K layers: their saved activations live the
    # shortest (backward frees them first), so exempting them buys back
    # recompute FLOPs at minimal peak-memory cost (analog of the
    # reference's selective recompute_interval in fleet pp_layers)
    recompute_skip: int = 0
    # remat policy: "none" saves only layer boundaries (recompute all);
    # "save_attn" additionally keeps attention outputs, skipping the flash
    # forward re-run in the backward pass (reference analog: selective
    # recompute in fleet recompute_hybrid);
    # "dots_saveable" / "dots_with_no_batch_dims_saveable" save matmul
    # outputs (jax.checkpoint_policies; measured: OOM at the bench config)
    remat_policy: str = "none"
    # remat granularity (reference: fleet/recompute/recompute.py:109 is
    # op-level, not layer-level): "layer" wraps the whole decoder layer;
    # "attn" / "mlp" checkpoint only the NAMED sub-block — that block's
    # interior activations are dropped and recomputed in backward while
    # the OTHER block's are saved — a finer memory/FLOPs point than
    # whole-layer skip counts
    remat_scope: str = "layer"
    # MLP via the fused Pallas swiglu kernel (kernels/swiglu.py): ~18%
    # slower per-op than XLA's dual-matmul at the bench shape, but its
    # custom vjp recomputes per-tile, so the two [B,S,F] gate/up
    # intermediates are never saved — an activation-memory lever that
    # can buy whole no-remat layers (single-chip knob: the pallas call
    # has no SPMD partition rule)
    fused_swiglu: bool = False
    # attention over the sep axis: "ulysses" (all-to-all seq->head reshard)
    # or "ring" (ring attention — k/v rotate with ppermute, exact blockwise
    # softmax; the long-context leapfrog the reference lacks)
    attention_impl: str = "ulysses"
    dtype: str = "float32"

    def __post_init__(self):
        if self.num_key_value_heads is None:
            self.num_key_value_heads = self.num_attention_heads

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_attention_heads

    # ------ stock sizes (any field overridable, e.g.
    # llama2_13b(num_hidden_layers=2) for a dims-faithful smoke) ------
    @staticmethod
    def _stock(defaults: dict, over: dict) -> "LlamaConfig":
        return LlamaConfig(**{**defaults, **over})

    @staticmethod
    def llama2_7b(**over) -> "LlamaConfig":
        return LlamaConfig._stock(
            dict(hidden_size=4096, intermediate_size=11008,
                 num_hidden_layers=32, num_attention_heads=32), over)

    @staticmethod
    def llama2_13b(**over) -> "LlamaConfig":
        return LlamaConfig._stock(
            dict(hidden_size=5120, intermediate_size=13824,
                 num_hidden_layers=40, num_attention_heads=40), over)

    @staticmethod
    def llama3_8b(**over) -> "LlamaConfig":
        # the modern GQA ratio (32:8) + 128k vocab + long-rope base
        return LlamaConfig._stock(
            dict(vocab_size=128256, hidden_size=4096,
                 intermediate_size=14336, num_hidden_layers=32,
                 num_attention_heads=32, num_key_value_heads=8,
                 rope_theta=500000.0), over)

    @staticmethod
    def llama_1b(**over) -> "LlamaConfig":
        return LlamaConfig._stock(
            dict(hidden_size=2048, intermediate_size=5504,
                 num_hidden_layers=16, num_attention_heads=16), over)

    @staticmethod
    def tiny(**over) -> "LlamaConfig":
        return LlamaConfig._stock(
            dict(vocab_size=128, hidden_size=64, intermediate_size=128,
                 num_hidden_layers=2, num_attention_heads=4,
                 num_key_value_heads=2, max_position_embeddings=64), over)


# ---------------------------------------------------------------------------
# activation sharding helper
# ---------------------------------------------------------------------------

def _act_spec(mesh: Optional[Mesh], shape, *dims) -> Optional[NamedSharding]:
    """Build a NamedSharding keeping only axes present in the mesh whose size
    divides the tensor dim. Each dim is None, an axis name, or a tuple of
    axis names."""
    if mesh is None:
        return None
    from ..parallel.mesh import divisible_prefix

    out = []
    for i, d in enumerate(dims):
        if d is None:
            out.append(None)
            continue
        names = (d,) if isinstance(d, str) else d
        kept = divisible_prefix(mesh, shape[i], names)
        out.append(kept if kept else None)
    return NamedSharding(mesh, P(*out))


def _constrain(x, mesh, *dims):
    sh = _act_spec(mesh, list(x.shape), *dims)
    if sh is None:
        return x
    return dispatch("shard_constraint",
                    lambda a: jax.lax.with_sharding_constraint(a, sh), (x,))


# batch dim is data-parallel over both dp and the ZeRO axis; seq dim is
# context-parallel over sep (reference: 5-D topo [data,pipe,sharding,sep,model],
# fleet/base/topology.py:188)
from ..parallel.mesh import (BATCH_AXES,  # noqa: E402 (single topology source)
                             CP_AXIS, MP_AXIS)

SEQ_AXIS = "sep"


# ---------------------------------------------------------------------------
# layers
# ---------------------------------------------------------------------------

class LlamaRMSNorm(Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.hidden_size = config.hidden_size
        self.variance_epsilon = config.rms_norm_eps
        from ..nn.initializer import Constant

        self.weight = self.create_parameter(
            [config.hidden_size], default_initializer=Constant(1.0),
            dtype=config.dtype)

    def forward(self, x):
        return dispatch(
            "rms_norm",
            lambda a, w: _k_rms(a, w, self.variance_epsilon), (x, self.weight))


class LlamaAttention(Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.config = config
        h = config.hidden_size
        nh, nkv, dh = (config.num_attention_heads, config.num_key_value_heads,
                       config.head_dim)
        self.num_heads, self.num_kv_heads, self.head_dim = nh, nkv, dh
        self.q_proj = Linear(h, nh * dh, bias_attr=False)
        self.k_proj = Linear(h, nkv * dh, bias_attr=False)
        self.v_proj = Linear(h, nkv * dh, bias_attr=False)
        self.o_proj = Linear(nh * dh, h, bias_attr=False)

    def forward(self, hidden, cos, sin, cache: Optional[Tuple] = None,
                mesh=None):
        b, s, _ = hidden.shape
        q = self.q_proj(hidden).reshape([b, s, self.num_heads, self.head_dim])
        k = self.k_proj(hidden).reshape([b, s, self.num_kv_heads, self.head_dim])
        v = self.v_proj(hidden).reshape([b, s, self.num_kv_heads, self.head_dim])
        q, k = dispatch(
            "fused_rope",
            lambda qa, ka: apply_rotary_emb(qa, ka, cos=cos, sin=sin), (q, k))
        new_cache = None
        if cache is not None:
            pk, pv = cache
            if pk is not None:
                k = Tensor(jnp.concatenate([unwrap(pk), unwrap(k)], axis=1))
                v = Tensor(jnp.concatenate([unwrap(pv), unwrap(v)], axis=1))
            new_cache = (k, v)
        causal = cache is None or k.shape[1] == s
        use_ring = (self.config.attention_impl == "ring" and cache is None
                    and mesh is not None and SEQ_AXIS in mesh.axis_names
                    and int(mesh.shape[SEQ_AXIS]) > 1)
        if use_ring:
            from ..parallel.ring_attention import ring_attention

            # GQA handled inside the ring by grouped einsum — no repeat
            out = dispatch(
                "ring_attention",
                lambda qa, ka, va: ring_attention(
                    qa, ka, va, mesh=mesh, axis=SEQ_AXIS, causal=causal),
                (q, k, v))
        else:
            from ..parallel.ulysses import seq_to_head, ulysses_available

            ulysses = (cache is None and mesh is not None
                       and ulysses_available(mesh, self.num_heads, s))
            if ulysses:
                # Ulysses: explicit all-to-all over the sep group swaps seq
                # shards for head shards (GSPMD's re-constraint lowering of
                # this swap replicates — "involuntary full remat" — so the
                # swap is a shard_map'd lax.all_to_all riding ICI; reference
                # analog: SegmentParallel sep groups,
                # fleet/base/topology.py:224)
                a2a = lambda a: seq_to_head(a, mesh)
                q = dispatch("ulysses_a2a", a2a, (q,))
                if ulysses_available(mesh, self.num_kv_heads, s):
                    k = dispatch("ulysses_a2a", a2a, (k,))
                    v = dispatch("ulysses_a2a", a2a, (v,))
                else:
                    # GQA with too few kv heads to split over mp*sep:
                    # replicate kv groups just enough to split evenly —
                    # the repeat multiplies a2a bytes, so use the minimal
                    # factor whose result still block-aligns with q's
                    # contiguous (mp, sep) head shards (kv'[j] = kv[j//r]
                    # puts q head t with kv group t*nkv/nh on each device)
                    from ..parallel.ulysses import minimal_kv_repeat

                    rep = minimal_kv_repeat(mesh, self.num_heads,
                                            self.num_kv_heads)
                    grow = lambda a: seq_to_head(
                        jnp.repeat(a, rep, axis=2), mesh)
                    k = dispatch("ulysses_a2a", grow, (k,))
                    v = dispatch("ulysses_a2a", grow, (v,))
            else:
                # heads sharded over mp (and sep when divisible): GSPMD
                # inserts the reshard from the constraint
                q = _constrain(q, mesh, BATCH_AXES, None,
                               (MP_AXIS, SEQ_AXIS), None)
                k = _constrain(k, mesh, BATCH_AXES, None,
                               (MP_AXIS, SEQ_AXIS), None)
                v = _constrain(v, mesh, BATCH_AXES, None,
                               (MP_AXIS, SEQ_AXIS), None)
            out, _ = F.flash_attention(q, k, v, causal=causal)
            if ulysses:
                from ..parallel.ulysses import head_to_seq

                out = dispatch("ulysses_a2a_back",
                               lambda a: head_to_seq(a, mesh), (out,))
        if self.config.remat_policy == "save_attn":
            from jax.ad_checkpoint import checkpoint_name

            out = dispatch("ckpt_name",
                           lambda a: checkpoint_name(a, "attn_out"), (out,))
        out = out.reshape([b, s, self.num_heads * self.head_dim])
        out = self.o_proj(out)
        if cache is not None:
            return out, new_cache
        return out


class LlamaMLP(Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        self._fused = config.fused_swiglu
        h, i = config.hidden_size, config.intermediate_size
        self.gate_proj = Linear(h, i, bias_attr=False)
        self.up_proj = Linear(h, i, bias_attr=False)
        self.down_proj = Linear(i, h, bias_attr=False)

    def forward(self, x):
        if self._fused:
            from ..kernels.swiglu import swiglu_matmul

            act = dispatch(
                "fused_swiglu",
                lambda a, g, u: swiglu_matmul(a, g, u, fused=True),
                (x, self.gate_proj.weight, self.up_proj.weight))
            return self.down_proj(act)
        return self.down_proj(F.swiglu(self.gate_proj(x), self.up_proj(x)))


class LlamaDecoderLayer(Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.config = config
        self.self_attn = LlamaAttention(config)
        self.mlp = LlamaMLP(config)
        self.input_layernorm = LlamaRMSNorm(config)
        self.post_attention_layernorm = LlamaRMSNorm(config)

    def forward(self, hidden, cos, sin, cache=None, mesh=None, remat=None):
        """remat: None, or "attn"/"mlp" — checkpoint ONLY that sub-block
        (sub-layer recompute granularity; the reference's recompute is
        op-level too, fleet/recompute/recompute.py:109)."""
        residual = hidden
        h = self.input_layernorm(hidden)
        if cache is not None:
            attn, new_cache = self.self_attn(h, cos, sin, cache=cache, mesh=mesh)
        else:
            new_cache = None
            if remat == "attn":
                def attn_fn(h_):
                    return unwrap(self.self_attn(Tensor(h_), cos, sin,
                                                 mesh=mesh))

                attn = Tensor(jax.checkpoint(attn_fn)(unwrap(h)))
            else:
                attn = self.self_attn(h, cos, sin, mesh=mesh)
        hidden = residual + attn
        residual = hidden
        h = self.post_attention_layernorm(hidden)
        if remat == "mlp" and cache is None:
            def mlp_fn(h_):
                return unwrap(self.mlp(Tensor(h_)))

            hidden = residual + Tensor(jax.checkpoint(mlp_fn)(unwrap(h)))
        else:
            hidden = residual + self.mlp(h)
        hidden = _constrain(hidden, mesh, BATCH_AXES, SEQ_AXIS, None)
        if cache is not None:
            return hidden, new_cache
        return hidden


class LlamaModel(Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.config = config
        self.embed_tokens = Embedding(config.vocab_size, config.hidden_size)
        from ..nn.layer.container import LayerList

        self.layers = LayerList(
            [LlamaDecoderLayer(config) for _ in range(config.num_hidden_layers)])
        self.norm = LlamaRMSNorm(config)
        if config.dtype != "float32":
            self.to(dtype=config.dtype)

    def forward(self, input_ids, caches=None, position_offset: int = 0):
        mesh = mesh_mod.get_global_mesh()
        s = input_ids.shape[1]
        pos = jnp.arange(position_offset, position_offset + s)
        cos, sin = rope_freqs(s, self.config.head_dim,
                              base=self.config.rope_theta, position_ids=pos)
        hidden = self.embed_tokens(input_ids)
        hidden = _constrain(hidden, mesh, BATCH_AXES, SEQ_AXIS, None)
        use_ckpt = (self.config.recompute and not _tape.grad_enabled()
                    and caches is None)
        new_caches = [] if caches is not None else None
        for li, layer in enumerate(self.layers):
            if caches is not None:
                hidden, c = layer(hidden, cos, sin, cache=caches[li], mesh=mesh)
                new_caches.append(c)
            elif use_ckpt and li < len(self.layers) - \
                    self.config.recompute_skip:
                if self.config.remat_scope in ("attn", "mlp"):
                    # sub-layer granularity: the layer itself wraps just
                    # that block; no outer whole-layer checkpoint
                    hidden = layer(hidden, cos, sin, mesh=mesh,
                                   remat=self.config.remat_scope)
                    continue

                def run(h, l=layer):
                    return unwrap(l(Tensor(h), cos, sin, mesh=mesh))

                policy = None
                if self.config.remat_policy == "save_attn":
                    policy = jax.checkpoint_policies.save_only_these_names(
                        "attn_out")
                elif self.config.remat_policy in (
                        "dots_saveable", "dots_with_no_batch_dims_saveable"):
                    policy = getattr(jax.checkpoint_policies,
                                     self.config.remat_policy)
                hidden = Tensor(jax.checkpoint(run, policy=policy)(
                    unwrap(hidden)))
            else:
                hidden = layer(hidden, cos, sin, mesh=mesh)
        hidden = self.norm(hidden)
        if caches is not None:
            return hidden, new_caches
        return hidden


class LlamaForCausalLM(Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.config = config
        self.llama = LlamaModel(config)
        if not config.tie_word_embeddings:
            self.lm_head = Linear(config.hidden_size, config.vocab_size,
                                  bias_attr=False)
            if config.dtype != "float32":
                self.lm_head.to(dtype=config.dtype)

    def forward(self, input_ids, caches=None, position_offset: int = 0):
        out = self.llama(input_ids, caches=caches,
                         position_offset=position_offset)
        hidden = out[0] if caches is not None else out
        if self.config.tie_word_embeddings:
            w = self.llama.embed_tokens.weight
            logits = dispatch("tied_lm_head",
                              lambda h, e: jnp.matmul(h, e.T), (hidden, w))
        else:
            logits = self.lm_head(hidden)
        if caches is not None:
            return logits, out[1]
        return logits

    # --------------------------------------------------------------
    def jit_generate(self, input_ids, max_new_tokens: int = 32,
                     eos_token_id: Optional[int] = None,
                     do_sample: bool = False, temperature: float = 1.0,
                     top_k: int = 0, top_p: float = 1.0,
                     seed: Optional[int] = None, bucket_size: int = 128,
                     quant: Optional[str] = None,
                     prefill_with_quant: bool = False,
                     cache_layout: str = "contiguous",
                     kv_block_size: int = 64, seq_lens=None):
        """Decode as ONE jitted program: prefill, then a lax.scan over
        decode steps against fixed-layout per-layer KV caches (reference
        analog: the fused serving generation path over
        masked_multihead_attention + top_p_sampling,
        python/paddle/tensor/search.py:1354).

        Serving features:
        - **prompt bucketing**: prompts are right-padded to a multiple of
          ``bucket_size`` and the true length enters the program as a
          traced scalar, so every prompt length in a bucket shares ONE
          compile (pad K/V slots are masked out of decode attention until
          overwritten, and the first token reads the logits at the true
          last position).
        - **sampling**: ``do_sample=True`` enables temperature / top-k /
          top-p with a threaded PRNG key; ``seed`` makes it deterministic.
          temperature and top_p are traced (no recompile when they change);
          top_k is static (it sizes a lax.top_k).
        - **weight-only int8/int4 decode** (``quant="weight_only_int8"``
          or ``"weight_only_int4"``): the decode scan reads per-channel-
          scaled int8 (or nibble-packed int4) projection weights
          (nn.quant.weight_quantize layout) — half / quarter the HBM
          traffic on the weight-bound decode path.
        - **quant-only serving** (``prefill_with_quant=True``, requires
          ``quant``): prefill ALSO reads the quantized weights
          (build_quant_generate) so no full-precision parameter set is
          ever put on device — this is how 7B-class models fit one chip.
        - **paged KV cache** (``cache_layout="paged"``): K/V live in
          [max_pages, Hkv, kv_block_size, D] pools addressed through a
          block table allocated by PagedKVManager at prefill
          (build_paged_generate; reference:
          block_multihead_attention.py:25). ``seq_lens`` (per-row true
          prompt lengths) serves a ragged batch in one program; rows
          must be right-padded to the input rectangle.
        """
        cfg = self.config
        ids_arr = unwrap(input_ids) if isinstance(input_ids, Tensor) \
            else jnp.asarray(input_ids)
        if max_new_tokens <= 0:
            return Tensor(ids_arr)
        b, s0 = ids_arr.shape
        sb = -(-s0 // bucket_size) * bucket_size  # bucketed prompt length
        padded = jnp.pad(ids_arr, ((0, 0), (0, sb - s0)))
        total = sb + max_new_tokens
        max_seq = total if total < 512 else ((total + 511) // 512) * 512
        if prefill_with_quant and quant is None:
            raise ValueError("prefill_with_quant=True requires quant=")
        if cache_layout not in ("contiguous", "paged"):
            raise ValueError(f"cache_layout must be 'contiguous' or "
                             f"'paged', got {cache_layout!r}")
        if seq_lens is not None and cache_layout != "paged":
            raise ValueError("per-row seq_lens (ragged batch) requires "
                             "cache_layout='paged'")
        params = dict(self.raw_state())
        dec_params = self._decode_params(params, quant)
        # the paged program bakes the pool dtype AND the megakernel
        # choice in at build time, so both flags join the cache key
        # (flipping either must not serve a stale compiled program)
        kv_dtype = resolve_kv_cache_dtype() if cache_layout == "paged" \
            else None
        megakernel = resolve_decode_megakernel() \
            if cache_layout == "paged" else None
        serving_mp = resolve_serving_mp() if cache_layout == "paged" \
            else None
        if cache_layout == "paged":
            from ..parallel.collectives import \
                resolve_quantized_collectives

            qcoll = resolve_quantized_collectives()
        else:
            qcoll = None
        sig = (b, sb, max_new_tokens, eos_token_id, do_sample, int(top_k),
               quant, prefill_with_quant, cache_layout, kv_block_size,
               kv_dtype, megakernel, qcoll, serving_mp)
        cache = getattr(self, "_jit_gen_cache", None)
        if cache is None:
            cache = self._jit_gen_cache = {}
        if sig not in cache:  # keep every compiled shape variant
            if cache_layout == "paged":
                fn = build_paged_generate(cfg, b, sb, max_new_tokens,
                                          kv_block_size, eos_token_id,
                                          do_sample, int(top_k),
                                          serving_mp=serving_mp)
            elif prefill_with_quant:
                fn = build_quant_generate(cfg, b, sb, max_new_tokens,
                                          max_seq, eos_token_id, do_sample,
                                          int(top_k))
            else:
                fn = _build_jit_generate(self, cfg, b, sb, max_new_tokens,
                                         max_seq, eos_token_id, do_sample,
                                         int(top_k))
            cache[sig] = jax.jit(fn)
        if seed is not None:
            key = jax.random.PRNGKey(int(seed))
        else:
            from ..framework.random import next_key

            key = next_key()
        if cache_layout == "paged":
            if seq_lens is None:
                s0_vec = jnp.full((b,), s0, jnp.int32)
            else:
                lens_np = np.asarray(seq_lens, np.int32).reshape(-1)
                if lens_np.shape[0] != b:
                    raise ValueError(f"seq_lens has {lens_np.shape[0]} "
                                     f"entries for a batch of {b}")
                if (lens_np < 1).any() or (lens_np > s0).any():
                    # out-of-range lengths would be silently clamped by
                    # the XLA gathers and decode over pad garbage
                    raise ValueError(
                        f"seq_lens must lie in [1, {s0}] (the input "
                        f"rectangle width); got {lens_np.tolist()}")
                s0_vec = jnp.asarray(lens_np)
            total = sb + max_new_tokens
            mgr = PagedKVManager(
                b * -(-total // kv_block_size), kv_block_size)
            tables, _ = mgr.tables_for_batch([total] * b)
            new_tokens = cache[sig](
                dec_params, padded, s0_vec, tables, key,
                jnp.asarray(temperature, jnp.float32),
                jnp.asarray(top_p, jnp.float32))
        else:
            args = (jnp.asarray(s0, jnp.int32), key,
                    jnp.asarray(temperature, jnp.float32),
                    jnp.asarray(top_p, jnp.float32))
            if prefill_with_quant:
                new_tokens = cache[sig](dec_params, padded, *args)
            else:
                new_tokens = cache[sig](params, dec_params, padded, *args)
        out = jnp.concatenate([ids_arr, new_tokens], axis=1)
        if eos_token_id is not None:
            # host-side trim: cut after every row has hit EOS
            toks = np.asarray(new_tokens)
            hit = (toks == eos_token_id)
            if hit.any(axis=1).all():
                last = int(hit.argmax(axis=1).max())
                out = out[:, :s0 + last + 1]
        return Tensor(out)

    def _decode_params(self, params, quant):
        """Decode-path parameter dict; with quant, the 2-D projection
        weights become (int8 [N,K], scale [N]) pairs. Quantized entries are
        cached per source array (jax arrays are immutable, so identity
        tracks staleness): a weight updated by training or set_state_dict
        is requantized on the next call, never served stale."""
        if quant is None:
            return params
        if quant not in ("weight_only_int8", "weight_only_int4"):
            raise ValueError(
                "quant must be None, 'weight_only_int8' or "
                f"'weight_only_int4', got {quant!r}")
        from ..nn.quant import weight_quantize

        qcache = getattr(self, "_decode_quant_cache", None)
        if qcache is None:
            qcache = self._decode_quant_cache = {}
        out = dict(params)
        names = [n for n in params
                 if n.endswith("_proj.weight") or n == "lm_head.weight"]
        for n in names:
            src = params[n]
            hit = qcache.get((n, quant))
            if hit is None or hit[0] is not src:
                wq, sc = weight_quantize(Tensor(src.astype(jnp.float32)),
                                         algo=quant)
                hit = (src, (unwrap(wq), unwrap(sc)))
                qcache[(n, quant)] = hit
            out[n] = hit[1]
        return out

    def generate(self, input_ids, max_new_tokens: int = 32,
                 eos_token_id: Optional[int] = None, do_sample: bool = False,
                 temperature: float = 1.0, top_k: int = 0, top_p: float = 1.0,
                 seed: Optional[int] = None):
        """Eager decode with a KV cache (reference analog: PaddleNLP
        generation; kernel family masked_multihead_attention). Supports the
        same greedy/sampled selection as jit_generate."""
        ids = input_ids if isinstance(input_ids, Tensor) else Tensor(input_ids)
        if seed is not None:
            key = jax.random.PRNGKey(int(seed))
        else:
            from ..framework.random import next_key

            key = next_key()

        def pick(logits_slice, key):
            return _sample_next(
                logits_slice.astype(jnp.float32), key, do_sample,
                jnp.asarray(temperature, jnp.float32), int(top_k),
                jnp.asarray(top_p, jnp.float32))[:, None]

        caches = [(None, None)] * self.config.num_hidden_layers
        logits, caches = self(ids, caches=caches)
        out = [ids]
        key, k0 = jax.random.split(key)
        last = pick(unwrap(logits)[:, -1], k0)
        offset = ids.shape[1]
        for step in range(max_new_tokens):
            out.append(Tensor(last))
            if eos_token_id is not None and bool(
                    jnp.all(last == eos_token_id)):
                break
            if step == max_new_tokens - 1:
                break  # the last appended token needs no further forward
            logits, caches = self(Tensor(last), caches=caches,
                                  position_offset=offset)
            offset += 1
            key, ks = jax.random.split(key)
            last = pick(unwrap(logits)[:, -1], ks)
        return Tensor(jnp.concatenate([unwrap(t) for t in out], axis=1))


def _mm(x, w):
    """Matmul against a decode weight: dense [K, N], or a
    nn.quant.weight_quantize pair — int8 [N, K] or packed int4 [N, K//2]
    (detected by the stored K) with per-channel scales [N]. The
    int→bf16 convert (and the int4 unpack) fuse into the dot, so HBM
    reads stay at the quantized width."""
    if isinstance(w, tuple):
        wq, sc = w
        if wq.shape[1] != x.shape[-1]:  # packed int4: two nibbles/byte
            # in-register Pallas dequant-matmul: the packed bytes stay
            # packed all the way into VMEM (kernels/int4_matmul.py) —
            # end-to-end decode 1.68 ms/step vs 2.79 for the XLA shift
            # form (int8 remains fastest at ~1.1-1.3; BASELINE.md)
            from ..kernels.int4_matmul import int4_matmul

            lead = x.shape[:-1]
            out = int4_matmul(x.reshape(-1, x.shape[-1]), wq, sc)
            return out.reshape(*lead, wq.shape[0]).astype(x.dtype)
        out = jnp.einsum("...k,nk->...n", x, wq.astype(x.dtype),
                         preferred_element_type=jnp.float32)
        return (out * sc).astype(x.dtype)
    return x @ w


def _sample_next(logits, key, do_sample, temperature, top_k, top_p):
    """Pick the next token from [B, V] logits: greedy, or nucleus sampling
    (the jit-safe form of ops/search.py top_p_sampling — sort, cumulative
    mass cut, categorical draw)."""
    if not do_sample:
        return jnp.argmax(logits, axis=-1)
    logits = logits.astype(jnp.float32) / jnp.maximum(temperature, 1e-6)
    if top_k > 0:
        kth = jax.lax.top_k(logits, top_k)[0][:, -1:]
        logits = jnp.where(logits < kth, -1e30, logits)
    srt = jnp.sort(logits, axis=-1)[:, ::-1]
    probs = jax.nn.softmax(srt, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    keep = (cum - probs) < top_p
    keep = keep.at[:, 0].set(True)  # the argmax survives even top_p<=0
    threshold = jnp.min(jnp.where(keep, srt, jnp.inf), axis=-1, keepdims=True)
    logits = jnp.where(logits < threshold, -1e30, logits)
    return jax.random.categorical(key, logits, axis=-1)


def _make_head_logits(cfg):
    """LM-head logits over the decode-params dict (quant-aware via _mm;
    tied embeddings stay a dense transpose-matmul)."""
    def head_logits(h, p):
        if cfg.tie_word_embeddings:
            return h @ p["llama.embed_tokens.weight"].T
        return _mm(h, p["lm_head.weight"])
    return head_logits


# ---------------------------------------------------------------------------
# stacked decode-layer parameters (FLAGS_decode_megakernel='scan'): a
# build-time re-layout putting every per-layer weight on a leading layer
# axis so the layer-scanned megakernel streams them per grid step
# ---------------------------------------------------------------------------

STACKED_PREFIX = "llama.layers.stacked."

# the per-layer weights the scan megakernel streams — the re-layout
# stacks exactly these (call order of decode_layers_megakernel)
STACKED_LAYER_NAMES = (
    "input_layernorm.weight",
    "post_attention_layernorm.weight",
    "self_attn.q_proj.weight",
    "self_attn.k_proj.weight",
    "self_attn.v_proj.weight",
    "self_attn.o_proj.weight",
    "mlp.gate_proj.weight",
    "mlp.up_proj.weight",
    "mlp.down_proj.weight",
)


def stack_decode_layer_params(p: dict, n_layers: int) -> dict:
    """Re-layout a `_decode_params` dict for the layer-scanned
    megakernel: every weight in `STACKED_LAYER_NAMES` moves from its
    `llama.layers.{i}.` entry into ONE ``llama.layers.stacked.<name>``
    entry stacked along a leading layer axis (quant pairs stack both
    members), and the per-layer entries are DROPPED — each weight lives
    in HBM exactly once. Runs once at engine build; every program reads
    layer slices back through `_lw`, so the multi-kernel oracle, the
    prefill/verify bodies and the scan kernel all serve the same dict."""
    out = dict(p)
    for name in STACKED_LAYER_NAMES:
        per = [out.pop(f"llama.layers.{i}.{name}")
               for i in range(n_layers)]
        if isinstance(per[0], tuple):
            out[STACKED_PREFIX + name] = (
                jnp.stack([w[0] for w in per]),
                jnp.stack([w[1] for w in per]))
        else:
            out[STACKED_PREFIX + name] = jnp.stack(per)
    return out


def _lw(p, i, name):
    """Layer `i`'s weight `name` from a decode-params dict — the flat
    per-layer entry, or (after `stack_decode_layer_params`) a slice of
    the stacked entry. The slice is a trace-time gather XLA folds into
    the consuming matmul; only the scan megakernel streams the stacked
    array whole."""
    w = p.get(f"llama.layers.{i}.{name}")
    if w is not None:
        return w
    st = p[STACKED_PREFIX + name]
    if isinstance(st, tuple):
        return (st[0][i], st[1][i])
    return st[i]


def _layer_kv(kcs, vcs, i, n_layers):
    """(kc_i, vc_i, page_off): layer `i`'s K/V pool entries. Per-layer
    lists return entry i with offset 0; the scan re-layout's length-1
    lists hold ONE layer-major stacked pool — layer i owns page rows
    [i*pp, (i+1)*pp), so readers add `page_off` to their block-table
    ids instead of slicing (a slice would copy the pool; the offset is
    one broadcast add)."""
    if len(kcs) == n_layers:
        return kcs[i], vcs[i], 0
    kc, vc = kcs[0], vcs[0]
    pool = kc[0] if isinstance(kc, tuple) else kc
    return kc, vc, i * (pool.shape[0] // n_layers)


def _make_prefill(cfg, b, sb, tp=None):
    """Shared per-layer prefill over the `_decode_params` layout (dense
    OR quantized projections, via _mm): embed -> L x (rms/attn/mlp) ->
    final rms. Returns (h_final, [(k_i, v_i)]) with rotary-applied K/V
    [b, sb, nkv, dh] per layer — the caller owns the cache layout
    (contiguous slices or page scatter).

    With `tp` (ServingTP, inside a shard_map body) the q/k/v weights
    arrive column-sharded so each shard computes only its local heads;
    the flash attention runs shard-local and the per-shard outputs
    all-gather along the head axis before the (replicated) o-proj —
    the one cross-chip collective per layer. The returned K/V carry
    the LOCAL kv heads (callers scatter into the local pool shard)."""
    from ..kernels.flash_attention import flash_attention as _flash

    nh, nkv, dh = (cfg.num_attention_heads, cfg.num_key_value_heads,
                   cfg.head_dim)
    # head counts the projections reshape at: the LOCAL shard's under
    # tp, the full model's otherwise (never the config's alone)
    nh_l = tp.nh_local if tp is not None else nh
    nkv_l = tp.nkv_local if tp is not None else nkv
    n_layers = cfg.num_hidden_layers
    eps = cfg.rms_norm_eps

    def prefill(p, ids):
        h = p["llama.embed_tokens.weight"][ids]          # [b, sb, h]
        pos_ids = jnp.arange(sb)
        kvs = []
        for i in range(n_layers):
            x = _k_rms(h, _lw(p, i, "input_layernorm.weight"), eps)
            q = _mm(x, _lw(p, i, "self_attn.q_proj.weight")).reshape(
                b, sb, nh_l, dh)
            k = _mm(x, _lw(p, i, "self_attn.k_proj.weight")).reshape(
                b, sb, nkv_l, dh)
            v = _mm(x, _lw(p, i, "self_attn.v_proj.weight")).reshape(
                b, sb, nkv_l, dh)
            q, k = apply_rotary_emb(q, k, position_ids=pos_ids,
                                    base=cfg.rope_theta)
            kvs.append((k, v))
            attn = _flash(q, k, v, causal=True)        # [b, sb, nh_l, dh]
            if tp is not None:
                attn = tp.gather_heads(attn)           # [b, sb, nh, dh]
            h = h + _mm(attn.reshape(b, sb, nh * dh),
                        _lw(p, i, "self_attn.o_proj.weight"))
            x2 = _k_rms(h, _lw(p, i, "post_attention_layernorm.weight"),
                        eps)
            gate = _mm(x2, _lw(p, i, "mlp.gate_proj.weight"))
            up = _mm(x2, _lw(p, i, "mlp.up_proj.weight"))
            h = h + _mm(jax.nn.silu(gate) * up,
                        _lw(p, i, "mlp.down_proj.weight"))
        h = _k_rms(h, p["llama.norm.weight"], eps)
        return h, kvs

    return prefill


def _make_prefill_with_prefix(cfg, b, sb, w_pre, block_size, tp=None):
    """Suffix prefill over a cached block-aligned prefix: compute hidden
    states for the `sb` UNCACHED suffix tokens only, attending over the
    prefix K/V gathered from the paged pools (already rotary-encoded at
    their absolute positions when they were first cached) plus the
    suffix itself, causally. This is the compute the prefix cache
    exists to elide — a request whose first `prefix_lens[row]` tokens
    hit the cache pays O(suffix) prefill instead of O(prompt).

    Per-row state is traced, so ONE compiled program serves any mix of
    prefix lengths (including 0) at this (suffix bucket, batch) shape:
    `prefix_tables` [b, w_pre] maps the prefix's logical blocks to pool
    pages (rows shorter than w_pre blocks pad with any valid page id —
    masked), `prefix_lens` [b] is the cached token count (a multiple of
    block_size), and suffix positions/rope offsets follow from it.

    The mixed prefix+suffix attention has two implementations:

    - **Pallas kernel** (FLAGS_prefix_prefill_kernel, default on): the
      ragged paged prefix-prefill grid (kernels/prefix_prefill.py) —
      one (kv head, page) tile streamed from the pools per step with
      online-softmax carry, like the paged decode kernel (PAPERS.md:
      Ragged Paged Attention). Bandwidth-bound: the gathered
      [b, w_pre, nkv, bs, dh] prefix tensor never exists.
    - **masked jnp softmax fallback**: exact but gather-bound — kept
      for unsupported shapes (suffix bucket not a whole number of KV
      pages, or an empty prefix table) and as the numerics oracle.

    The flag is read when this factory runs (program-build time), so a
    jitted program keeps the path it was compiled with.

    Returns prefill(p, kcs, vcs, ids, prefix_tables, prefix_lens,
    suffix_lens=None) -> (h_final [b, sb, h], [(k_i, v_i)]) with
    rotary-applied suffix K/V [b, sb, nkv, dh] per layer — the caller
    owns the page scatter. `suffix_lens` [b] (true suffix lengths) lets
    the kernel skip and zero pad query rows; the fallback ignores it
    (pad rows beyond it are don't-care either way: their K/V land past
    the decode watermark and are masked until overwritten).

    int8 pools (FLAGS_kv_cache_dtype): pass kcs/vcs entries as
    (int8 pool, f32 scale [max_pages, nkv]) tuples — both the kernel
    and the fallback dequantize against the scales (the fallback in
    f32 at the gather, the kernel inside its accumulation).

    With `tp` (ServingTP, inside a shard_map body): q/k/v weights and
    the pools arrive shard-local, the mixed prefix+suffix attention
    (kernel or fallback — both derive head counts from their OPERAND
    shapes) streams only the local kv heads' pages, and the per-shard
    outputs all-gather along the head axis before the replicated
    o-proj — same single collective per layer as the decode step."""
    nh, nkv, dh = (cfg.num_attention_heads, cfg.num_key_value_heads,
                   cfg.head_dim)
    nh_l = tp.nh_local if tp is not None else nh
    nkv_l = tp.nkv_local if tp is not None else nkv
    n_layers = cfg.num_hidden_layers
    eps = cfg.rms_norm_eps
    scale = 1.0 / math.sqrt(dh)
    from ..framework.flags import flag as _flag

    use_kernel = (bool(_flag("prefix_prefill_kernel"))
                  and sb % block_size == 0 and w_pre >= 1)

    def prefill(p, kcs, vcs, ids, prefix_tables, prefix_lens,
                suffix_lens=None):
        h = p["llama.embed_tokens.weight"][ids]          # [b, sb, h]
        pos_ids = prefix_lens[:, None] + jnp.arange(sb)[None, :]  # [b, sb]
        kvs = []
        for i in range(n_layers):
            x = _k_rms(h, _lw(p, i, "input_layernorm.weight"), eps)
            q = _mm(x, _lw(p, i, "self_attn.q_proj.weight")).reshape(
                b, sb, nh_l, dh)
            k = _mm(x, _lw(p, i, "self_attn.k_proj.weight")).reshape(
                b, sb, nkv_l, dh)
            v = _mm(x, _lw(p, i, "self_attn.v_proj.weight")).reshape(
                b, sb, nkv_l, dh)
            q, k = apply_rotary_emb(q, k, position_ids=pos_ids,
                                    base=cfg.rope_theta)
            kvs.append((k, v))
            kc_all, vc_all, poff = _layer_kv(kcs, vcs, i, n_layers)
            kc_i, ksc_i = kc_all if isinstance(kc_all, tuple) \
                else (kc_all, None)
            vc_i, vsc_i = vc_all if isinstance(vc_all, tuple) \
                else (vc_all, None)
            ptbl = prefix_tables + poff if poff else prefix_tables
            if tp is not None and tp.cp > 1:
                # context parallelism (ISSUE 18): prefix-phase partials
                # over the LOCAL pool pages, merged cross-chip; the
                # causal suffix phase is replicated (fresh K/V derive
                # from replicated activations) and folds in once
                from ..kernels.partial_attention import (
                    causal_window_partials, combine_partials,
                    cp_local_view, finalize_partials, paged_partials)

                loc, owned = cp_local_view(ptbl,
                                           kc_i.shape[0], tp.cp_axis)
                page = kc_i.shape[2]
                pos_ok = jnp.arange(loc.shape[1] * page)[None, :] \
                    < prefix_lens[:, None]
                valid = pos_ok & jnp.repeat(owned, page, axis=1)
                part = paged_partials(q, kc_i, vc_i, loc, valid,
                                      scale=scale, k_scale=ksc_i,
                                      v_scale=vsc_i)
                part = tp.merge_attn_partials(*part)
                suf = causal_window_partials(q, k, v, scale=scale)
                attn = finalize_partials(
                    *combine_partials(part, suf)).astype(h.dtype)
            elif use_kernel:
                from ..kernels.prefix_prefill import \
                    prefix_prefill_attention

                attn = prefix_prefill_attention(
                    q, k, v, kc_i, vc_i, ptbl, prefix_lens,
                    suffix_lens, scale=scale, k_scale=ksc_i,
                    v_scale=vsc_i).astype(h.dtype)
            else:
                from ..kernels.prefix_prefill import \
                    prefix_prefill_reference

                attn = prefix_prefill_reference(
                    q, k, v, kc_i, vc_i, ptbl, prefix_lens,
                    scale=scale, k_scale=ksc_i,
                    v_scale=vsc_i).astype(h.dtype)
            if tp is not None:
                attn = tp.gather_heads(attn)
            h = h + _mm(attn.reshape(b, sb, nh * dh),
                        _lw(p, i, "self_attn.o_proj.weight"))
            x2 = _k_rms(h, _lw(p, i, "post_attention_layernorm.weight"),
                        eps)
            gate = _mm(x2, _lw(p, i, "mlp.gate_proj.weight"))
            up = _mm(x2, _lw(p, i, "mlp.up_proj.weight"))
            h = h + _mm(jax.nn.silu(gate) * up,
                        _lw(p, i, "mlp.down_proj.weight"))
        h = _k_rms(h, p["llama.norm.weight"], eps)
        return h, kvs

    return prefill


def _make_chunk_prefill(cfg, tn, tp=None):
    """Chunk-lane transformer body of the UNIFIED serving step (ISSUE
    14): one ragged prefill WINDOW of `tn` tokens for ONE request,
    attending its already-committed tokens (earlier chunks, or a cached
    prefix — both are just pool pages named by the row's block table)
    plus the window itself causally, through `ragged_paged_attention`.
    A cold prompt is a window with ``cached_len 0``; a long prompt is
    several windows across engine steps (chunked prefill — the thing
    that stops a 100k-token prompt head-of-line-blocking decode).

    Per-window state is traced, so ONE compiled program serves every
    (cached_len, new_len) mix at this window shape: `chunk_table`
    [1, w] names the request's pages, `cached_len` [1] is the
    committed token count (page-aligned by the engine's chunking, but
    the kernel accepts arbitrary), `new_len` [1] the true chunk length
    (window rows beyond it are pad — zeroed by the kernel and scattered
    at the scratch page by the caller).

    Attention follows FLAGS_prefix_prefill_kernel at program-build
    time exactly like `_make_prefill_with_prefix`: the Pallas
    `ragged_paged_attention` grid by default, the
    `ragged_paged_attention_reference` masked softmax as fallback and
    oracle. int8 pools (FLAGS_kv_cache_dtype) pass kcs/vcs entries as
    (int8 pool, f32 scale) tuples — both paths dequantize against the
    scales.

    With `tp` (ServingTP, inside a shard_map body): shard-local q/k/v
    heads + pool shards, per-shard outputs all-gather (bf16 payload)
    before the replicated o-proj — the same one collective per layer
    as every other serving program.

    Returns prefill(p, kcs, vcs, ids, chunk_table, cached_len,
    new_len) -> (h_final [1, tn, hidden], [(k_i, v_i)]) with
    rotary-applied window K/V [1, tn, nkv_l, dh] per layer — the
    caller owns the page scatter."""
    nh, nkv, dh = (cfg.num_attention_heads, cfg.num_key_value_heads,
                   cfg.head_dim)
    nh_l = tp.nh_local if tp is not None else nh
    nkv_l = tp.nkv_local if tp is not None else nkv
    n_layers = cfg.num_hidden_layers
    eps = cfg.rms_norm_eps
    scale = 1.0 / math.sqrt(dh)
    from ..framework.flags import flag as _flag

    use_kernel = bool(_flag("prefix_prefill_kernel"))

    def prefill(p, kcs, vcs, ids, chunk_table, cached_len, new_len):
        from ..kernels.ragged_attention import (
            ragged_paged_attention, ragged_paged_attention_reference)

        h = p["llama.embed_tokens.weight"][ids]          # [1, tn, h]
        pos_ids = cached_len[:, None] + jnp.arange(tn)[None, :]
        kvs = []
        for i in range(n_layers):
            x = _k_rms(h, _lw(p, i, "input_layernorm.weight"), eps)
            q = _mm(x, _lw(p, i, "self_attn.q_proj.weight")).reshape(
                1, tn, nh_l, dh)
            k = _mm(x, _lw(p, i, "self_attn.k_proj.weight")).reshape(
                1, tn, nkv_l, dh)
            v = _mm(x, _lw(p, i, "self_attn.v_proj.weight")).reshape(
                1, tn, nkv_l, dh)
            q, k = apply_rotary_emb(q, k, position_ids=pos_ids,
                                    base=cfg.rope_theta)
            kvs.append((k, v))
            kc_all, vc_all, poff = _layer_kv(kcs, vcs, i, n_layers)
            kc_i, ksc_i = kc_all if isinstance(kc_all, tuple) \
                else (kc_all, None)
            vc_i, vsc_i = vc_all if isinstance(vc_all, tuple) \
                else (vc_all, None)
            ctbl = chunk_table + poff if poff else chunk_table
            if tp is not None and tp.cp > 1:
                # context parallelism (ISSUE 18): this shard holds only
                # 1/cp of the pool pages — stream the LOCAL pages as
                # online-softmax partials (position-valid AND owned),
                # merge the stats cross-chip (never the KV), then fold
                # in the replicated causal window exactly once
                from ..kernels.partial_attention import (
                    causal_window_partials, combine_partials,
                    cp_local_view, finalize_partials, paged_partials)

                loc, owned = cp_local_view(ctbl, kc_i.shape[0],
                                           tp.cp_axis)
                page = kc_i.shape[2]
                pos_ok = jnp.arange(loc.shape[1] * page)[None, :] \
                    < cached_len[:, None]
                valid = pos_ok & jnp.repeat(owned, page, axis=1)
                part = paged_partials(q, kc_i, vc_i, loc, valid,
                                      scale=scale, k_scale=ksc_i,
                                      v_scale=vsc_i)
                part = tp.merge_attn_partials(*part)
                win = causal_window_partials(q, k, v, new_len,
                                             scale=scale)
                mm_, ll_, aa_ = combine_partials(part, win)
                live = jnp.arange(tn)[None, :] < new_len[:, None]
                attn = finalize_partials(
                    mm_, ll_, aa_, live[..., None]).astype(h.dtype)
            else:
                attn_fn = ragged_paged_attention if use_kernel \
                    else ragged_paged_attention_reference
                attn = attn_fn(q, k, v, kc_i, vc_i, ctbl,
                               cached_len, new_len, scale=scale,
                               k_scale=ksc_i, v_scale=vsc_i
                               ).astype(h.dtype)
            if tp is not None:
                attn = tp.gather_heads(attn)
            h = h + _mm(attn.reshape(1, tn, nh * dh),
                        _lw(p, i, "self_attn.o_proj.weight"))
            x2 = _k_rms(h, _lw(p, i, "post_attention_layernorm.weight"),
                        eps)
            gate = _mm(x2, _lw(p, i, "mlp.gate_proj.weight"))
            up = _mm(x2, _lw(p, i, "mlp.up_proj.weight"))
            h = h + _mm(jax.nn.silu(gate) * up,
                        _lw(p, i, "mlp.down_proj.weight"))
        h = _k_rms(h, p["llama.norm.weight"], eps)
        return h, kvs

    return prefill


def _make_verify_window(cfg, b, w, tp=None):
    """Speculative-verify transformer body (ISSUE 19): the chunk lane of
    `_make_chunk_prefill`, batched over `b` slots at a FIXED window of
    `w = spec_k + 1` tokens — the slot's pending token plus its k
    drafts — through the same `ragged_paged_attention` kernel the
    unified step runs. The only new ask of the model is that logits
    come back for ALL w rows instead of the last: row j scores the
    token the target would emit AFTER window token j, which is exactly
    what greedy acceptance compares draft j+1 against.

    Per-slot state is traced so ONE compiled program serves every
    (cached_len, new_len) mix: `tables` [b, tw] are the slots' block
    tables, `cached_lens` [b] the committed counts (arbitrary, token
    granular), `new_lens` [b] the true window lengths (1 = no drafts =
    plain decode semantics; rows past new_len are pad — the kernel
    zeroes them and the caller scatters their K/V at the scratch page).

    With `tp` (ServingTP, inside a shard_map body): shard-local q/k/v
    heads + pool shards, per-shard outputs all-gather before the
    replicated o-proj — same one collective per layer as the decode
    chunk. Context parallelism (tp.cp > 1) is a follow-up; the engine
    gates it.

    Returns verify(p, kcs, vcs, ids, tables, cached_lens, new_lens) ->
    (h_final [b, w, hidden], [(k_i, v_i)]) with rotary-applied window
    K/V [b, w, nkv_l, dh] per layer — the caller owns the per-column
    page scatter and the head projection."""
    nh, nkv, dh = (cfg.num_attention_heads, cfg.num_key_value_heads,
                   cfg.head_dim)
    nh_l = tp.nh_local if tp is not None else nh
    nkv_l = tp.nkv_local if tp is not None else nkv
    if tp is not None and tp.cp > 1:
        raise NotImplementedError(
            "speculative verify windows do not compose with serving_cp "
            "yet (page-sharded partial-attention merge of a multi-row "
            "window is a ROADMAP follow-up)")
    n_layers = cfg.num_hidden_layers
    eps = cfg.rms_norm_eps
    scale = 1.0 / math.sqrt(dh)
    from ..framework.flags import flag as _flag

    use_kernel = bool(_flag("prefix_prefill_kernel"))

    def verify(p, kcs, vcs, ids, tables, cached_lens, new_lens):
        from ..kernels.ragged_attention import (
            ragged_paged_attention, ragged_paged_attention_reference)

        h = p["llama.embed_tokens.weight"][ids]          # [b, w, h]
        pos_ids = cached_lens[:, None] + jnp.arange(w)[None, :]
        kvs = []
        for i in range(n_layers):
            x = _k_rms(h, _lw(p, i, "input_layernorm.weight"), eps)
            q = _mm(x, _lw(p, i, "self_attn.q_proj.weight")).reshape(
                b, w, nh_l, dh)
            k = _mm(x, _lw(p, i, "self_attn.k_proj.weight")).reshape(
                b, w, nkv_l, dh)
            v = _mm(x, _lw(p, i, "self_attn.v_proj.weight")).reshape(
                b, w, nkv_l, dh)
            q, k = apply_rotary_emb(q, k, position_ids=pos_ids,
                                    base=cfg.rope_theta)
            kvs.append((k, v))
            kc_all, vc_all, poff = _layer_kv(kcs, vcs, i, n_layers)
            kc_i, ksc_i = kc_all if isinstance(kc_all, tuple) \
                else (kc_all, None)
            vc_i, vsc_i = vc_all if isinstance(vc_all, tuple) \
                else (vc_all, None)
            tbl = tables + poff if poff else tables
            attn_fn = ragged_paged_attention if use_kernel \
                else ragged_paged_attention_reference
            attn = attn_fn(q, k, v, kc_i, vc_i, tbl,
                           cached_lens, new_lens, scale=scale,
                           k_scale=ksc_i, v_scale=vsc_i).astype(h.dtype)
            if tp is not None:
                attn = tp.gather_heads(attn)
            h = h + _mm(attn.reshape(b, w, nh * dh),
                        _lw(p, i, "self_attn.o_proj.weight"))
            x2 = _k_rms(h, _lw(p, i, "post_attention_layernorm.weight"),
                        eps)
            gate = _mm(x2, _lw(p, i, "mlp.gate_proj.weight"))
            up = _mm(x2, _lw(p, i, "mlp.up_proj.weight"))
            h = h + _mm(jax.nn.silu(gate) * up,
                        _lw(p, i, "mlp.down_proj.weight"))
        h = _k_rms(h, p["llama.norm.weight"], eps)
        return h, kvs

    return verify


def build_quant_generate(cfg, b, sb, max_new, max_seq=None,
                         eos_token_id=None, do_sample=False, top_k=0):
    """Model-free serving program over QUANTIZED weights only: prefill AND
    decode read the nn.quant weight layout (int8 [N,K] / packed int4
    [N,K//2] + per-channel scales), dequantizing on the fly inside each
    matmul — no full-precision parameter set ever exists on device.

    This is what makes 7B-class serving fit one 16 GB chip: bf16 weights
    (13.5 GB) + an int8 copy cannot coexist, so the fp prefill path of
    `_build_jit_generate` is replaced by the same per-layer loop batched
    over the prompt (flash attention for the causal part). Prefill is
    compute-bound, so the dequant adds bandwidth it doesn't miss; decode
    stays weight-read-bound at the quantized width.

    Reference analog: the weight-only serving path of
    python/paddle/nn/quant/quantized_linear.py:180 (weight_only_linear)
    under the fused_multi_transformer generation loop
    (incubate/nn/functional/fused_multi_transformer.py).

    Returns run(dec_params, ids_padded, s0, key, temperature, top_p) ->
    new_tokens; jit it once per shape. `dec_params` is the
    `_decode_params` dict: quantized projections + fp embed/norm weights.
    """
    nkv, dh = cfg.num_key_value_heads, cfg.head_dim
    if max_seq is None:
        total = sb + max_new
        max_seq = total if total < 512 else ((total + 511) // 512) * 512

    head_logits = _make_head_logits(cfg)
    prefill = _make_prefill(cfg, b, sb)
    decode_step = _make_decode_step(cfg, b, max_seq)

    def run(p_dec, ids, s0, key, temperature, top_p):
        h, kvs = prefill(p_dec, ids)
        kcs, vcs = [], []
        for k, v in kvs:
            kc = jnp.zeros((b, nkv, max_seq, dh), h.dtype)
            kcs.append(jax.lax.dynamic_update_slice(
                kc, jnp.swapaxes(k, 1, 2).astype(h.dtype), (0, 0, 0, 0)))
            vc = jnp.zeros((b, nkv, max_seq, dh), h.dtype)
            vcs.append(jax.lax.dynamic_update_slice(
                vc, jnp.swapaxes(v, 1, 2).astype(h.dtype), (0, 0, 0, 0)))
        # logits at the TRUE last prompt position, not the padded end
        h_last = jax.lax.dynamic_index_in_dim(h, s0 - 1, axis=1,
                                              keepdims=True)
        last_logits = head_logits(h_last, p_dec)[:, -1]
        return _decode_tail(decode_step, p_dec, kcs, vcs,
                            last_logits, s0, key, temperature, top_p,
                            ids.dtype, max_new, eos_token_id, do_sample,
                            top_k, b)

    return run


def make_paged_kv_helpers(b, n_pre, nkv, dh, block_size, tables):
    """The two paged-cache plumbing pieces shared by every paged program
    (build_paged_generate and serving.engine): prefill page transpose and
    the per-token page/slot scatter, closed over the traced block table."""
    def to_pages(kv):
        """[b, n_pre*block_size, nkv, dh] -> [b, n_pre, nkv, block_size, dh]"""
        return jnp.transpose(
            kv.reshape(b, n_pre, block_size, nkv, dh), (0, 1, 3, 2, 4))

    def kv_write(kc, vc, k, v, lens):
        page = tables[jnp.arange(b), lens // block_size]
        slot = lens % block_size
        return (kc.at[page, :, slot, :].set(k[:, 0].astype(kc.dtype)),
                vc.at[page, :, slot, :].set(v[:, 0].astype(vc.dtype)))

    return to_pages, kv_write


# ---------------------------------------------------------------------------
# int8 KV cache (FLAGS_kv_cache_dtype): symmetric per-(page, kv-head)
# absmax quantization of the paged pools — quantize on the K/V page
# scatter, dequantize inside the Pallas kernels (decode_attention /
# prefix_prefill stream the int8 tiles + their scale rows)
# ---------------------------------------------------------------------------

KV_CACHE_DTYPES = ("bf16", "int8")


def resolve_kv_cache_dtype(kv_cache_dtype: Optional[str] = None) -> str:
    """'bf16' | 'int8', from the argument or FLAGS_kv_cache_dtype /
    PADDLE_TPU_KV_CACHE_DTYPE. Read at program-BUILD time (like
    FLAGS_prefix_prefill_kernel): flip it before constructing or
    warming an engine."""
    if kv_cache_dtype is None:
        from ..framework.flags import flag as _flag

        kv_cache_dtype = str(_flag("kv_cache_dtype"))
    if kv_cache_dtype not in KV_CACHE_DTYPES:
        raise ValueError(
            f"kv_cache_dtype must be one of {KV_CACHE_DTYPES}, got "
            f"{kv_cache_dtype!r}")
    return kv_cache_dtype


MEGAKERNEL_MODES = ("off", "attn", "full", "scan")


def resolve_decode_megakernel(decode_megakernel=None) -> str:
    """Fusion rung of the paged decode step — 'off' | 'attn' | 'full' |
    'scan' — from the argument or FLAGS_decode_megakernel /
    PADDLE_TPU_DECODE_MEGAKERNEL. The historical boolean maps onto the
    ladder (False -> 'off', True -> 'attn' — the rung the boolean used
    to enable), so every pre-tri-state call site keeps its meaning.
    Read at program-BUILD time (like FLAGS_prefix_prefill_kernel and
    FLAGS_kv_cache_dtype): flip it before constructing or warming an
    engine. Default OFF — the multi-kernel path is the oracle."""
    if decode_megakernel is None:
        from ..framework.flags import flag as _flag

        decode_megakernel = _flag("decode_megakernel")
    if isinstance(decode_megakernel, bool):
        return "attn" if decode_megakernel else "off"
    s = str(decode_megakernel).strip().lower()
    if s in ("1", "true", "yes", "on"):
        return "attn"
    if s in ("0", "false", "no", ""):
        return "off"
    if s not in MEGAKERNEL_MODES:
        raise ValueError(
            f"decode_megakernel must be one of {MEGAKERNEL_MODES} (or a "
            f"legacy boolean), got {decode_megakernel!r}")
    return s


def megakernel_rung_order(mode: str):
    """The fallback ladder below (and including) `mode`, strongest
    first: a refused rung steps DOWN one fusion level at a time —
    scan -> full -> attn -> off — never sideways."""
    return MEGAKERNEL_MODES[MEGAKERNEL_MODES.index(mode)::-1]


def resolve_unified_step(unified_step=None) -> bool:
    """Whether the serving engine runs the UNIFIED ragged step (ISSUE
    14) — one chunked-prefill+decode program over
    `ragged_paged_attention` instead of the split cold/prefix-prefill
    program zoo — from the argument or FLAGS_unified_step /
    PADDLE_TPU_UNIFIED_STEP. 'auto' (the default) resolves ON off-TPU,
    where interpret-mode parity is cheap; on silicon the default stays
    the split oracle until the gated `ragged_step` OPBENCH row
    confirms. Read at engine-BUILD time like every other serving
    flag."""
    if unified_step is None:
        from ..framework.flags import flag as _flag

        unified_step = _flag("unified_step")
    if isinstance(unified_step, str):
        s = unified_step.strip().lower()
        if s in ("auto", ""):
            from ..kernels.decode_attention import _on_tpu

            return not _on_tpu()
        if s in ("1", "true", "on", "yes"):
            return True
        if s in ("0", "false", "off", "no"):
            return False
        raise ValueError(
            f"unified_step must be 'auto'/'1'/'0', got {unified_step!r}")
    return bool(unified_step)


def serving_block_size_candidates(cfg, *, prompt_bucket: int,
                                  kv_cache_dtype: str = "bf16",
                                  max_candidates: int = 2) -> list:
    """KV page sizes (``block_size``) a serving engine could be built
    at for this model, ascending: the divisors of `prompt_bucket`
    (whole pages per bucket — the engine's admission invariant) whose
    per-token K+V row keeps double-buffered page blocks under the
    streaming kernels' scoped-VMEM cap. Candidates come from
    `kernels.constraints.vmem_block_candidates` — the SAME
    `fit_vmem_block` rule the decode / prefix-prefill kernels size
    their blocks with — so the static autotuner (analysis/tuner.py)
    can only propose pages the kernels would actually tile at.
    `max_candidates` keeps the largest few (big pages amortize block
    tables and scatter launches; a deep small-page tail is never
    competitive)."""
    itemsize = 1 if resolve_kv_cache_dtype(kv_cache_dtype) == "int8" \
        else 2
    row = 2 * cfg.num_key_value_heads * cfg.head_dim * itemsize
    from ..kernels.constraints import vmem_block_candidates

    return vmem_block_candidates(int(prompt_bucket), row,
                                 max_candidates=max_candidates)


SERVING_MP_FALLBACK_MSG = (
    "kv heads not divisible by serving_mp; falling back to "
    "replicated-KV head-sharded-Q (each shard streams the FULL kv "
    "pools — no per-chip KV memory win, query compute still shards)")


def resolve_serving_mp(serving_mp: Optional[int] = None) -> int:
    """Tensor-parallel degree of the paged serving stack, from the
    argument or FLAGS_serving_mp / PADDLE_TPU_SERVING_MP. Read at
    program-BUILD time (like FLAGS_kv_cache_dtype): flip it before
    constructing or warming an engine. 1 (default) = the single-chip
    path, byte-identical to a build without the flag."""
    if serving_mp is None:
        from ..framework.flags import flag as _flag

        serving_mp = int(_flag("serving_mp"))
    serving_mp = int(serving_mp)
    if serving_mp < 1:
        raise ValueError(f"serving_mp must be >= 1, got {serving_mp}")
    return serving_mp


def resolve_serving_cp(serving_cp: Optional[int] = None) -> int:
    """Context-parallel degree of the paged serving stack (pools shard
    by PAGE), from the argument or FLAGS_serving_cp /
    PADDLE_TPU_SERVING_CP. Read at program-BUILD time (like
    FLAGS_serving_mp): flip it before constructing or warming an
    engine. 1 (default) = the page-replicated path, byte-identical to
    a build without the flag."""
    if serving_cp is None:
        from ..framework.flags import flag as _flag

        serving_cp = int(_flag("serving_cp"))
    serving_cp = int(serving_cp)
    if serving_cp < 1:
        raise ValueError(f"serving_cp must be >= 1, got {serving_cp}")
    return serving_cp


class PageShardingError(ValueError):
    """A paged-pool geometry cannot shard along the PAGE axis as asked:
    the fleet page count does not split evenly across the `cp` shards.
    Named (rather than a bare ValueError) so admission / tuner /
    engine-build callers can distinguish 'this cp degree is
    geometrically impossible here' from argument typos."""


class ServingTP:
    """Head-sharding geometry of a tensor-parallel serving program.

    The sharding layout (ROADMAP: "pools+scales sharded; decode
    all-gathers only the o-proj activations"):

    - q/k/v projections COLUMN-shard by head over `mp`: shard i owns
      contiguous q heads [i*nh_local, (i+1)*nh_local) and kv heads
      [i*nkv_local, (i+1)*nkv_local) — the same contiguous blocks a
      `NamedSharding(P(..., 'mp'))` device_put produces, so GQA group
      membership is preserved per shard (group = nh/nkv is invariant).
    - the paged K/V pools (and their int8 scale sidecars) shard on the
      kv-head axis; block tables, lengths and budgets stay replicated,
      so page ids mean the same thing on every chip and "KV transfer"
      between workers is table bookkeeping, not data movement.
    - attention runs entirely shard-local (each shard streams only its
      local kv heads); the per-shard attention outputs — the o-proj
      ACTIVATIONS — are all-gathered along the head axis, and the
      o-proj itself plus everything outside the attention block (embed,
      norms, MLP, lm head, sampling) is computed replicated. That makes
      the all-gather the ONE cross-chip collective per layer, and every
      per-element computation identical to the single-chip program
      (token identity, not just closeness).

    MQA fallback (`kv heads % mp != 0`, e.g. nkv=1): kv heads cannot
    shard, so k/v projections and the pools stay REPLICATED while q
    heads still shard — each shard streams the full pools against its
    query group (`group_local = nh_local // nkv`), commits identical
    K/V on every chip, and the o-proj all-gather is unchanged. A
    build-time warning names the fallback (the per-chip KV-memory win
    is gone; the grid is still correct — satellite of ISSUE 7: group
    math derives from LOCAL head counts, never the full-model config).
    """

    def __init__(self, cfg, mp: int, axis: str = MP_AXIS,
                 quantized: Optional[bool] = None, cp: int = 1,
                 cp_axis: str = CP_AXIS):
        # quantized collectives (ISSUE 15): resolved HERE at geometry-
        # build time like every serving flag — the engine threads its
        # own resolution through so the flag joins its program keys
        from ..parallel.collectives import resolve_quantized_collectives

        self.quantized = resolve_quantized_collectives(quantized)
        self.cp = int(cp)
        self.cp_axis = cp_axis
        if self.cp < 1:
            raise ValueError(f"serving_cp must be >= 1, got {cp}")
        nh, nkv = cfg.num_attention_heads, cfg.num_key_value_heads
        if nh % mp:
            raise ValueError(
                f"serving_mp={mp} does not divide num_attention_heads "
                f"{nh}; query heads must shard evenly")
        self.mp = int(mp)
        self.axis = axis
        self.nh_local = nh // mp
        self.kv_sharded = nkv % mp == 0
        self.nkv_local = nkv // mp if self.kv_sharded else nkv
        if self.kv_sharded and self.nh_local % self.nkv_local:
            raise ValueError(
                f"serving_mp={mp} breaks the GQA grouping: {nh} q heads "
                f"/ {nkv} kv heads shard to {self.nh_local}/"
                f"{self.nkv_local} per chip")
        if not self.kv_sharded:
            if self.nh_local % nkv:
                raise ValueError(
                    f"serving_mp={mp} with {nkv} kv heads leaves "
                    f"{self.nh_local} q heads per chip — not a whole "
                    "number of kv groups; no valid replicated-KV grid")
            import warnings

            warnings.warn(
                f"serving_mp={mp} with {nkv} kv heads: "
                + SERVING_MP_FALLBACK_MSG, stacklevel=3)

    def gather_heads(self, ctx):
        """All-gather the per-shard attention outputs along the head
        axis — THE one cross-chip collective per layer (the o-proj
        activations; shard i's block lands at head offset i*nh_local,
        matching the column-sharded q projection). The payload is cast
        to bf16 BEFORE the gather (ISSUE 14 satellite: PR 11's comms
        auditor proved an f32 activation stream shipped f32 here, with
        the downcast landing at the o-proj AFTER the wire — the
        pre-cast halves the mp seam's bytes; a bf16 stream is
        untouched, so production serving numerics don't move and every
        shard applies the same rounding, keeping mp token-identical to
        itself across degrees).

        With FLAGS_quantized_collectives (ISSUE 15, the cashed EQuARX
        follow-up) the payload ships as absmax-scaled int8 blocks with
        an f32 scale sidecar (`parallel.collectives.
        quantized_all_gather` — the int8 KV pools' proven scheme):
        ~0.5x the bf16 wire bytes again, at quantization-noise
        accuracy (the serving gate is the int8-KV token-match bar, not
        identity). TPU803 goes silent on the rewritten seam by design
        (int8 payloads never fire); the comms auditor prices payload
        AND sidecar."""
        if self.mp <= 1:
            # cp-only geometry: every chip already holds all heads —
            # no head seam to gather (and no dtype cast: byte-identity
            # with the single-chip path is per-element)
            return ctx
        if ctx.dtype == jnp.float32:
            ctx = ctx.astype(jnp.bfloat16)
        if self.quantized:
            from ..parallel.collectives import quantized_all_gather

            return quantized_all_gather(ctx, self.axis,
                                        axis=ctx.ndim - 2, tiled=True)
        return jax.lax.all_gather(ctx, self.axis, axis=ctx.ndim - 2,
                                  tiled=True)

    def psum_partial(self, partial):
        """Sum per-shard PARTIAL results over the mp axis — the
        megakernel decode path's collective (the fused kernel emits the
        f32 o-proj partial contraction instead of the pre-o-proj
        activations; same wire bytes as the all-gather at f32). With
        FLAGS_quantized_collectives the sum runs as the two-hop
        quantized exchange (int8 reduce-scatter via all_to_all + f32
        dequant-accumulate + int8 all-gather,
        `parallel.collectives.quantized_psum`), composing the
        megakernel with the quantized wire."""
        if self.mp <= 1:
            return partial
        if self.quantized:
            from ..parallel.collectives import quantized_psum

            return quantized_psum(partial, self.axis)
        return jax.lax.psum(partial, self.axis)

    def merge_attn_partials(self, m, l, acc):
        """Merge per-cp-shard online-softmax partials into the global
        attention state — the context-parallel seam next to
        `gather_heads` (ISSUE 18). Each cp shard streams only its LOCAL
        pages and emits (m [rows...], l [rows...], acc [rows..., dh])
        f32 partials; this applies the SAME rescale recurrence the
        paged kernels run between page tiles, lifted one level to run
        between CHIPS:

            M     = pmax(m, cp)             # global running max
            w     = exp(m - M)              # per-shard rescale
            l_g   = psum(l * w, cp)
            acc_g = psum(acc * w[..., None], cp)

        Only the stats + weighted accumulator cross the wire — never
        the KV pages — so the merge costs O(rows * nh * dh) f32 per
        layer against the O(ctx * nkv * dh) pool stream it shards.
        Rows with no valid key anywhere carry the finite _NEG_INF
        sentinel (never true -inf), so w = exp(0) = 1 and l_g = 0 —
        the caller's finalize zeros them, and no NaN can form.

        With FLAGS_quantized_collectives the weighted accumulator —
        the only payload with real width — ships via the int8
        two-hop psum (`parallel.collectives.quantized_psum`); the
        scalar m/l stats always merge exact."""
        if self.cp <= 1:
            return m, l, acc
        m_g = jax.lax.pmax(m, self.cp_axis)
        w = jnp.exp(m - m_g)
        l_g = jax.lax.psum(l * w, self.cp_axis)
        acc_w = acc * w[..., None]
        if self.quantized:
            from ..parallel.collectives import quantized_psum

            acc_g = quantized_psum(acc_w, self.cp_axis)
        else:
            acc_g = jax.lax.psum(acc_w, self.cp_axis)
        return m_g, l_g, acc_g


def make_serving_tp(cfg, serving_mp: Optional[int] = None,
                    quantized_collectives: Optional[bool] = None,
                    serving_cp: Optional[int] = None) \
        -> Optional[ServingTP]:
    """ServingTP geometry for the resolved (mp, cp) degrees, or None at
    mp=1 and cp=1 (the single-chip path takes no TP plumbing at all).
    `quantized_collectives` (default: the flag) arms the int8
    all-gather / psum wire (ISSUE 15); `serving_cp` (default: the
    flag) the page-sharded context-parallel geometry (ISSUE 18) —
    at cp > 1 with mp == 1 the head seams (`gather_heads` /
    `psum_partial`) are identity and only `merge_attn_partials`
    crosses chips."""
    mp = resolve_serving_mp(serving_mp)
    cp = resolve_serving_cp(serving_cp)
    if mp <= 1 and cp <= 1:
        return None
    return ServingTP(cfg, mp, quantized=quantized_collectives, cp=cp)


def _tp_weight_spec(name: str, w, tp: ServingTP):
    """PartitionSpec(s) for one serving weight under ServingTP: q (and,
    when kv shards, k/v) projections shard on their OUTPUT-head axis —
    dense [in, out] on axis 1; nn.quant pairs (int8/int4-packed
    [out, in_packed], per-channel scale [out]) on axis 0 of both — and
    EVERYTHING else (o-proj included: it consumes the all-gathered
    activations) replicates. Mirrors `shard_serving_params`; both feed
    shard_map in_specs."""
    from jax.sharding import PartitionSpec as _P

    sharded = name.endswith("q_proj.weight") or (
        tp.kv_sharded and (name.endswith("k_proj.weight")
                           or name.endswith("v_proj.weight")))
    # stacked decode-layer weights (scan rung) carry a leading layer
    # axis the shard axis shifts past — same suffix naming, same
    # head-geometry sharding per layer slice
    stacked = name.startswith(STACKED_PREFIX)
    if isinstance(w, tuple):
        if sharded:
            if stacked:
                return (_P(None, tp.axis, None), _P(None, tp.axis))
            return (_P(tp.axis, None), _P(tp.axis))
        return tuple(_P(*([None] * getattr(a, "ndim", 0))) for a in w)
    if sharded:
        if stacked:
            return _P(None, None, tp.axis)
        return _P(None, tp.axis)
    return _P(*([None] * getattr(w, "ndim", 0)))


def serving_param_specs(params: dict, tp: ServingTP) -> dict:
    """{name: PartitionSpec | (spec, spec)} mirroring a `_decode_params`
    dict under ServingTP — the in_specs tree every sharded serving
    program passes to shard_map."""
    return {name: _tp_weight_spec(name, w, tp)
            for name, w in params.items()}


def shard_serving_params(params: dict, mesh, tp: ServingTP) -> dict:
    """Lay a `_decode_params` dict out on the serving mesh per
    `serving_param_specs` (one device_put per weight; sharded q/k/v
    columns, everything else replicated across the mp devices)."""
    specs = serving_param_specs(params, tp)
    out = {}
    for name, w in params.items():
        sp = specs[name]
        if isinstance(w, tuple):
            out[name] = tuple(
                jax.device_put(a, NamedSharding(mesh, s))
                for a, s in zip(w, sp))
        else:
            out[name] = jax.device_put(w, NamedSharding(mesh, sp))
    return out


def _tp_slice_o_proj(w, tp: ServingTP, spec_only: bool = False):
    """The LOCAL contraction slice of a (replicated) o-proj weight for
    the fused megakernel path: the megakernel computes o-proj in-kernel,
    so each shard multiplies its local attention heads against its own
    contraction rows and the partial sums psum outside. Dense weights
    [nh*dh, H] slice rows; nn.quant pairs (int8 [H, nh*dh], scale [H])
    slice contraction COLUMNS with the per-output scale replicated.
    `spec_only` returns ShapeDtypeStructs (megakernel_supported runs
    shape-only, outside any traced axis context)."""
    idx = None if spec_only else jax.lax.axis_index(tp.axis)
    if isinstance(w, tuple):
        wq, sc = w
        k_local = wq.shape[1] // tp.mp
        if spec_only:
            return (jax.ShapeDtypeStruct((wq.shape[0], k_local),
                                         wq.dtype), sc)
        return (jax.lax.dynamic_slice_in_dim(wq, idx * k_local, k_local,
                                             axis=1), sc)
    k_local = w.shape[0] // tp.mp
    if spec_only:
        return jax.ShapeDtypeStruct((k_local, w.shape[1]), w.dtype)
    return jax.lax.dynamic_slice_in_dim(w, idx * k_local, k_local, axis=0)


def _tp_local_weight_spec(w, tp: ServingTP):
    """Shard-local ShapeDtypeStruct of a column-sharded projection —
    what the shard_map body will see of a GLOBAL weight (the engine's
    build-time rung plan runs before shard_map exists)."""
    if isinstance(w, tuple):
        wq, sc = w
        return (jax.ShapeDtypeStruct((wq.shape[0] // tp.mp,)
                                     + wq.shape[1:], wq.dtype),
                jax.ShapeDtypeStruct((sc.shape[0] // tp.mp,), sc.dtype))
    return jax.ShapeDtypeStruct(w.shape[:-1] + (w.shape[-1] // tp.mp,),
                                w.dtype)


def _megakernel_rung_reason(rung, cfg, b, p, kcs, vcs, tables, tp=None,
                            localize_tp=False) -> Optional[str]:
    """None when fusion rung `rung` can serve this decode step's
    operands (layer-0 weights stand in for every layer —
    `_decode_params` quantizes them uniformly), else the reason this
    rung steps DOWN the ladder. Pure shape logic, runnable under trace
    and on ShapeDtypeStructs. Under ServingTP the check needs the
    SHARD-LOCAL operands: at trace time (inside shard_map) they arrive
    local already; the engine's BUILD-time plan passes global weights
    with `localize_tp=True` and the q/k/v columns are viewed at their
    local widths here."""
    from ..kernels.decode_megakernel import (megakernel_full_supported,
                                             megakernel_scan_supported,
                                             megakernel_supported)

    if rung == "off":
        return None
    if tp is not None and tp.cp > 1:
        # the fused kernel normalizes in-epilogue — it has no
        # partial-softmax (m, l, acc) emit for merge_attn_partials to
        # consume, so context parallelism serves the multi-kernel path
        return ("serving_cp > 1: the fused layer kernel cannot emit "
                "online-softmax partials for the cross-chip cp merge")
    if rung in ("full", "scan") and tp is not None:
        return (f"serving_mp > 1: the {rung} rung fuses the MLP past "
                "the per-layer o-proj psum, which must stay a "
                "cross-chip collective between the fused halves")
    kc0, vc0 = kcs[0], vcs[0]
    ksc = vsc = None
    if isinstance(kc0, tuple):
        (kc0, ksc), (vc0, vsc) = kc0, vc0
    H = cfg.hidden_size
    h_spec = jax.ShapeDtypeStruct(
        (b, 1, H), p["llama.embed_tokens.weight"].dtype)
    if rung == "scan":
        missing = [n for n in STACKED_LAYER_NAMES
                   if STACKED_PREFIX + n not in p]
        if missing:
            return ("scan needs the stacked-parameter re-layout "
                    "(stack_decode_layer_params — the serving engine "
                    "builds it at engine build)")
        return megakernel_scan_supported(
            h_spec, *(p[STACKED_PREFIX + n] for n in STACKED_LAYER_NAMES),
            kc0, vc0, tables, n_layers=cfg.num_hidden_layers,
            k_scale=ksc, v_scale=vsc)
    wq = _lw(p, 0, "self_attn.q_proj.weight")
    wk = _lw(p, 0, "self_attn.k_proj.weight")
    wv = _lw(p, 0, "self_attn.v_proj.weight")
    if tp is not None and localize_tp:
        wq = _tp_local_weight_spec(wq, tp)
        if tp.kv_sharded:
            wk = _tp_local_weight_spec(wk, tp)
            wv = _tp_local_weight_spec(wv, tp)
    wo = _lw(p, 0, "self_attn.o_proj.weight")
    if tp is not None:
        wo = _tp_slice_o_proj(wo, tp, spec_only=True)
    if rung == "full":
        return megakernel_full_supported(
            h_spec, _lw(p, 0, "input_layernorm.weight"),
            _lw(p, 0, "post_attention_layernorm.weight"),
            wq, wk, wv, wo,
            _lw(p, 0, "mlp.gate_proj.weight"),
            _lw(p, 0, "mlp.up_proj.weight"),
            _lw(p, 0, "mlp.down_proj.weight"),
            kc0, vc0, tables, k_scale=ksc, v_scale=vsc)
    return megakernel_supported(
        h_spec, _lw(p, 0, "input_layernorm.weight"),
        wq, wk, wv, wo, kc0, vc0, tables, k_scale=ksc, v_scale=vsc)


def _megakernel_reason(cfg, b, p, kcs, vcs, tables, tp=None) \
        -> Optional[str]:
    """Back-compat shim: the attn rung's support reason
    (`_megakernel_rung_reason('attn', ...)`)."""
    return _megakernel_rung_reason("attn", cfg, b, p, kcs, vcs, tables,
                                   tp=tp)


def plan_megakernel_rung(mode, cfg, b, p, kcs, vcs, tables, tp=None,
                         localize_tp=False):
    """(served_rung, refusals) for a requested FLAGS_decode_megakernel
    mode: walk the ladder strongest-first, stepping DOWN one fusion
    level per refusal. `refusals` is [(rung, reason), ...] for every
    rung that refused — the engine's once-per-build warning names each
    (ISSUE 20 satellite). 'off' always serves (the multi-kernel
    oracle)."""
    refusals = []
    for rung in megakernel_rung_order(mode):
        reason = _megakernel_rung_reason(rung, cfg, b, p, kcs, vcs,
                                         tables, tp=tp,
                                         localize_tp=localize_tp)
        if reason is None:
            return rung, refusals
        refusals.append((rung, reason))
    return "off", refusals


def _megakernel_or_fallback_step(cfg, b, tables, p, kcs, vcs, base,
                                 tp=None, mode="attn", warn=True):
    """The strongest supported fused decode step at or below `mode`,
    else `base` (the multi-kernel oracle) — the ONE fallback seam both
    `build_paged_generate` and the serving engine's decode-chunk
    builder go through (single-chip AND ServingTP-sharded). Each
    refused rung warns by NAME with its reason; `warn=False` callers
    (the engine) already warned once at BUILD time from
    `plan_megakernel_rung`, so the per-program traces stay silent."""
    rung, refusals = plan_megakernel_rung(mode, cfg, b, p, kcs, vcs,
                                          tables, tp=tp)
    if warn and refusals:
        import warnings

        down = "the multi-kernel path" if rung == "off" \
            else f"the '{rung}' rung"
        for refused, reason in refusals:
            warnings.warn(
                f"decode_megakernel rung '{refused}' unsupported here "
                f"({reason}); serving {down}", stacklevel=3)
    if rung == "off":
        return base
    return _make_decode_step_megakernel(cfg, b, tables, tp=tp, mode=rung)


def _make_decode_step_megakernel(cfg, b, tables, tp=None, mode="attn"):
    """`_make_decode_step`'s paged twin with the decode step fused into
    Pallas calls (kernels/decode_megakernel.py) at fusion rung `mode`:

    - 'attn': the whole attention block — rms_norm, QKV projection,
      rotary, paged-KV commit (int8 epilogue included), paged GQA
      attention, o-proj + residual — ONE call per layer; the MLP half
      and the lm head keep the shared `_mm`/`_k_rms` path.
    - 'full': the MLP half (post-attn rms_norm, gate/up, silu·mul,
      down projection, residual) fuses in too — still one call per
      layer, but nothing between calls except the residual handoff.
    - 'scan': ONE call for the whole decoder — the outermost grid axis
      walks the layers over stacked weights (`stack_decode_layer_params`)
      and a layer-major stacked pool; `kernels_per_step` collapses to
      the megakernel + final rms + lm head.

    Under ServingTP (attn rung only — the fused MLP would swallow the
    psum seam) each shard runs the SAME fused kernel over its local
    heads/pools with its local o-proj contraction slice and
    `residual=False` — the kernel emits the o-proj PARTIAL sum, psum'd
    over the mp axis before the residual add. With quantized
    collectives at lane-aligned shapes the kernel quantizes the partial
    IN-EPILOGUE (PR 18 packed-scale layout) and
    `quantized_psum_prequant` puts it straight on the wire — the
    partial never round-trips HBM as f32 (ISSUE 20 satellite)."""
    from ..kernels.decode_megakernel import (decode_layer_megakernel,
                                             decode_layer_megakernel_full,
                                             decode_layers_megakernel)

    n_layers = cfg.num_hidden_layers
    eps = cfg.rms_norm_eps
    H = cfg.hidden_size
    head_logits = _make_head_logits(cfg)
    # in-kernel quantize epilogue gate: bit-identity with
    # `quantized_psum` on the f32 partial needs the flat [b*H] payload
    # to split into whole 128-lane blocks per shard (no padding — the
    # packed-scale layouts then coincide)
    quantize_wire = (tp is not None and tp.mp > 1
                     and bool(tp.quantized)
                     and H % 128 == 0 and (b * H) % (tp.mp * 128) == 0)

    def _embed_lens(p, tok, pos):
        h = p["llama.embed_tokens.weight"][tok[:, 0]][:, None, :]
        if getattr(pos, "ndim", 0) == 1:
            lens = pos.astype(jnp.int32)
        else:
            lens = jnp.broadcast_to(jnp.asarray(pos, jnp.int32), (b,))
        return h, lens

    if mode == "scan":
        def decode_step(p, kcs, vcs, tok, pos):
            h, lens = _embed_lens(p, tok, pos)

            def st(name):
                return p[STACKED_PREFIX + name]

            stacked = [st(n) for n in STACKED_LAYER_NAMES]
            kc, vc = kcs[0], vcs[0]
            kw = dict(n_layers=n_layers, rope_base=cfg.rope_theta,
                      eps=eps)
            if isinstance(kc, tuple):
                (kcp, ksc), (vcp, vsc) = kc, vc
                h_out, kc_new, vc_new = decode_layers_megakernel(
                    h, lens, tables, *stacked, kcp, vcp,
                    k_scale=ksc, v_scale=vsc, **kw)
            else:
                h_out, kc_new, vc_new = decode_layers_megakernel(
                    h, lens, tables, *stacked, kc, vc, **kw)
            h = _k_rms(h_out, p["llama.norm.weight"], eps)
            return head_logits(h, p)[:, -1], [kc_new], [vc_new]

        return decode_step

    if mode == "full":
        def decode_step(p, kcs, vcs, tok, pos):
            h, lens = _embed_lens(p, tok, pos)
            new_kcs, new_vcs = [], []
            for i in range(n_layers):
                kc, vc = kcs[i], vcs[i]
                layer = [
                    _lw(p, i, n) for n in STACKED_LAYER_NAMES]
                kw = dict(rope_base=cfg.rope_theta, eps=eps)
                if isinstance(kc, tuple):
                    (kcp, ksc), (vcp, vsc) = kc, vc
                    h, kc_new, vc_new = decode_layer_megakernel_full(
                        h, lens, tables, *layer, kcp, vcp,
                        k_scale=ksc, v_scale=vsc, **kw)
                else:
                    h, kc_new, vc_new = decode_layer_megakernel_full(
                        h, lens, tables, *layer, kc, vc, **kw)
                new_kcs.append(kc_new)
                new_vcs.append(vc_new)
            h = _k_rms(h, p["llama.norm.weight"], eps)
            return head_logits(h, p)[:, -1], new_kcs, new_vcs

        return decode_step

    def decode_step(p, kcs, vcs, tok, pos):
        h, lens = _embed_lens(p, tok, pos)
        new_kcs, new_vcs = [], []
        for i in range(n_layers):
            kc, vc = kcs[i], vcs[i]
            wo = _lw(p, i, "self_attn.o_proj.weight")
            if tp is not None:
                wo = _tp_slice_o_proj(wo, tp)
            mk = functools.partial(
                decode_layer_megakernel, rope_base=cfg.rope_theta,
                eps=eps, residual=tp is None,
                quantize_out=quantize_wire)
            if isinstance(kc, tuple):
                (kcp, ksc), (vcp, vsc) = kc, vc
                h_out, kc_new, vc_new = mk(
                    h, lens, tables, _lw(p, i, "input_layernorm.weight"),
                    _lw(p, i, "self_attn.q_proj.weight"),
                    _lw(p, i, "self_attn.k_proj.weight"),
                    _lw(p, i, "self_attn.v_proj.weight"),
                    wo, kcp, vcp, k_scale=ksc, v_scale=vsc)
            else:
                h_out, kc_new, vc_new = mk(
                    h, lens, tables, _lw(p, i, "input_layernorm.weight"),
                    _lw(p, i, "self_attn.q_proj.weight"),
                    _lw(p, i, "self_attn.k_proj.weight"),
                    _lw(p, i, "self_attn.v_proj.weight"),
                    wo, kc, vc)
            if tp is None:
                h = h_out
            else:
                if quantize_wire:
                    # the kernel emitted the partial ALREADY int8 in
                    # the packed-scale layout — straight on the wire,
                    # no f32 HBM round-trip before the collective
                    from ..parallel.collectives import \
                        quantized_psum_prequant

                    q8, q8s = h_out
                    red = quantized_psum_prequant(
                        q8, q8s, tp.axis, shape=(b, 1, H),
                        dtype=jnp.float32)
                else:
                    # h_out is the f32 o-proj PARTIAL (no residual):
                    # psum over the shards' contraction slices
                    # (quantized when FLAGS_quantized_collectives is
                    # on), then residual
                    red = tp.psum_partial(h_out)
                h = (h.astype(jnp.float32) + red).astype(h.dtype)
            new_kcs.append(kc_new)
            new_vcs.append(vc_new)
            x2 = _k_rms(h, _lw(p, i, "post_attention_layernorm.weight"),
                        eps)
            gate = _mm(x2, _lw(p, i, "mlp.gate_proj.weight"))
            up = _mm(x2, _lw(p, i, "mlp.up_proj.weight"))
            h = h + _mm(jax.nn.silu(gate) * up,
                        _lw(p, i, "mlp.down_proj.weight"))
        h = _k_rms(h, p["llama.norm.weight"], eps)
        return head_logits(h, p)[:, -1], new_kcs, new_vcs

    return decode_step


def quantize_kv_pages(kv):
    """Symmetric absmax int8 quantization of whole K/V pages.

    kv: [..., block_size, dh] with the per-(page, kv-head) reduction
    over the trailing two axes (callers pass [b, n_pre, nkv, block, dh]
    page stacks). The absmax is computed in f32 BEFORE any bf16
    round-trip. Returns (int8 same shape, scale [...] f32) with
    scale = absmax / 127 — dequant is q * scale; an all-zero page keeps
    scale 0 (dequantizes to exact zeros)."""
    kf = kv.astype(jnp.float32)
    amax = jnp.max(jnp.abs(kf), axis=(-2, -1)) / 127.0
    safe = jnp.where(amax > 0, amax, 1.0)
    q = jnp.round(kf / safe[..., None, None]).astype(jnp.int8)
    return q, amax


def make_paged_kv_q8_helpers(b, n_pre, nkv, dh, block_size, tables):
    """int8 twins of `make_paged_kv_helpers`, operating on
    (pool int8 [max_pages, nkv, block, dh], scale f32 [max_pages, nkv])
    pairs:

    - `to_pages_q8(kv)` -> (int8 pages, scales): the prefill transpose
      fused with quantize-on-scatter;
    - `kv_write_q8(kct, vct, k, v, lens)` with kct/vct = (pool, scale)
      tuples: the per-token decode commit. The page's absmax scale is
      monotone — a token louder than the page's current absmax grows the
      scale and the already-stored rows rescale in the same read-modify-
      write (one page per token per layer, noise next to the full-cache
      stream each decode step already pays); `slot == 0` resets the
      scale, so a recycled page can never poison its new owner with a
      stale (possibly huge) absmax."""
    to_pages, _ = make_paged_kv_helpers(b, n_pre, nkv, dh, block_size,
                                        tables)

    def to_pages_q8(kv):
        return quantize_kv_pages(to_pages(kv))

    def _commit_token(pool, scales, tok, page, slot):
        tokf = tok.astype(jnp.float32)                     # [b, nkv, dh]
        tok_amax = jnp.max(jnp.abs(tokf), axis=-1) / 127.0  # [b, nkv]
        # fresh page (slot 0): whatever scale the page's previous owner
        # left behind is dead — start the absmax chain from this token
        old = jnp.where((slot == 0)[:, None], 0.0, scales[page])
        new = jnp.maximum(old, tok_amax)
        safe = jnp.where(new > 0, new, 1.0)
        ratio = old / safe                                  # <= 1
        pg = jnp.round(pool[page].astype(jnp.float32)
                       * ratio[..., None, None])
        q = jnp.round(tokf / safe[..., None])
        pg = pg.at[jnp.arange(b), :, slot, :].set(q)
        pool = pool.at[page].set(
            jnp.clip(pg, -127, 127).astype(jnp.int8))
        return pool, scales.at[page].set(new)

    def kv_write_q8(kct, vct, k, v, lens):
        page = tables[jnp.arange(b), lens // block_size]
        slot = lens % block_size
        kc, ksc = _commit_token(*kct, k[:, 0], page, slot)
        vc, vsc = _commit_token(*vct, v[:, 0], page, slot)
        return (kc, ksc), (vc, vsc)

    return to_pages_q8, kv_write_q8


def hash_prefix_blocks(tokens, block_size: int):
    """Chained per-block prompt hashes: hash i covers tokens
    [0, (i+1)*block_size) — a hit on hash i therefore implies the WHOLE
    prefix through block i matches, so a cached-prefix walk can stop at
    the first miss (the vLLM prefix-cache keying scheme)."""
    hashes = []
    h = block_size  # seed the chain with the geometry
    for i in range(len(tokens) // block_size):
        h = hash((h, tuple(tokens[i * block_size:(i + 1) * block_size])))
        hashes.append(h)
    return hashes


class PagedKVManager:
    """Host-side KV page allocator for the paged generation path
    (reference: the block-table management serving engines drive above
    block_multihead_attention.py:25 — allocate pages at prefill, free at
    sequence end, reuse freed pages for new requests).

    Pages are identified by integer ids into the [max_pages, H,
    block_size, D] cache pool; `alloc` hands out the lowest free ids
    (freed pages are reused before fresh ones), `free` returns them.

    Block-aligned prefix cache (refcounted): a page holding one FULL
    block of a prompt's K/V may be registered under the chained hash of
    that prefix (`insert_prefix`); later requests whose prompt starts
    with the same blocks map the cached pages into their block tables
    (`acquire_prefix`) instead of recomputing them. Every live mapping
    holds a reference; `free` is refcount-aware — it releases the
    reference and only makes the page reusable once no request maps it,
    parking refcount-0 cached pages on an LRU list that `alloc_pages`
    evicts (oldest first) when the strictly-free list runs short. A
    referenced cached page is therefore never recycled, which is what
    keeps a hung-slot retire from pulling a shared prefix out from
    under the surviving slots."""

    def __init__(self, max_pages: int, block_size: int = 64):
        self.max_pages = int(max_pages)
        self.block_size = int(block_size)
        self._free = list(range(self.max_pages - 1, -1, -1))  # pop() = min
        # prefix cache state: hash -> page; page -> [hash, refcount];
        # refcount-0 cached pages in least-recently-released order
        self._hash_to_page = {}
        self._cached = {}
        self._lru = OrderedDict()
        self.prefix_evictions = 0
        self._geometry = None  # set_pool_geometry

    # ---- pool byte accounting -------------------------------------------

    @staticmethod
    def page_bytes(block_size: int, *, n_layers: int, num_kv_heads: int,
                   head_dim: int, kv_cache_dtype: str = "bf16",
                   mp: int = 1) -> int:
        """PER-CHIP device bytes ONE page costs across all layers: K + V
        pools (2 x nkv x block x dh x itemsize per layer) plus, for
        int8, the per-(page, kv-head) f32 absmax scale rows
        (2 x nkv x 4). Under kv-head sharding (`mp` — ServingTP with
        nkv % mp == 0) each chip holds only nkv/mp heads of every page,
        so a page costs 1/mp of the replicated bytes per chip; page ids
        and page COUNTS stay global (every chip maps the same ids)."""
        mp = int(mp)
        if mp > 1:
            if num_kv_heads % mp:
                raise ValueError(
                    f"per-shard geometry needs kv heads {num_kv_heads} "
                    f"divisible by mp {mp} (the MQA fallback replicates "
                    "the pools — pass mp=1)")
            num_kv_heads //= mp
        itemsize = 1 if kv_cache_dtype == "int8" else 2
        per_layer = 2 * num_kv_heads * block_size * head_dim * itemsize
        if kv_cache_dtype == "int8":
            per_layer += 2 * num_kv_heads * 4
        return per_layer * n_layers

    @classmethod
    def pages_for_bytes(cls, budget_bytes: int, block_size: int, *,
                        n_layers: int, num_kv_heads: int, head_dim: int,
                        kv_cache_dtype: str = "bf16", mp: int = 1,
                        cp: int = 1) -> int:
        """FLEET pages a PER-CHIP device byte budget holds — the
        capacity side of the int8 win (at the same budget an int8 pool
        holds ~2x the pages) AND of both sharding axes: at mp shards a
        per-chip budget buys ~mp x the aggregate cacheable pages
        (each chip stores only its 1/mp head slice of every page), and
        at cp shards it buys cp x the PAGE COUNT outright (each chip
        stores only its 1/cp of the fleet's pages — the context-
        parallel axis, ISSUE 18). The result is divisible by cp by
        construction (per-chip count x cp), satisfying
        `set_pool_geometry`'s sharding invariant."""
        per_page = cls.page_bytes(block_size, n_layers=n_layers,
                                  num_kv_heads=num_kv_heads,
                                  head_dim=head_dim,
                                  kv_cache_dtype=kv_cache_dtype, mp=mp)
        return max(0, int(budget_bytes) // per_page) * max(1, int(cp))

    def set_pool_geometry(self, *, n_layers: int, num_kv_heads: int,
                          head_dim: int, kv_cache_dtype: str = "bf16",
                          mp: int = 1, cp: int = 1):
        """Record the pool geometry this manager's page ids index into,
        enabling `kv_pool_bytes()` (benches attribute capacity-driven
        hit-rate changes with it). `mp` is the kv-head shard count (1
        when the pools are replicated — including the MQA fallback) and
        `cp` the PAGE shard count (ISSUE 18: global page id g lives on
        cp shard g // (max_pages // cp)), so byte accounting reports
        PER-CHIP cost while page ids / capacity math stay fleet-wide.
        A fleet page count that does not split evenly across the cp
        shards raises `PageShardingError` — silent remainder pages
        would desynchronize the contiguous owner map every chip
        derives locally."""
        resolve_kv_cache_dtype(kv_cache_dtype)
        if mp > 1 and num_kv_heads % mp:
            raise ValueError(
                f"kv heads {num_kv_heads} not divisible by mp {mp}; "
                "replicated pools record mp=1")
        cp = int(cp)
        if cp < 1:
            raise ValueError(f"cp must be >= 1, got {cp}")
        if self.max_pages % cp:
            raise PageShardingError(
                f"fleet page count {self.max_pages} not divisible by "
                f"cp {cp}: the page axis shards contiguously "
                f"({self.max_pages} % {cp} == {self.max_pages % cp} "
                "pages would have no owner)")
        self._geometry = dict(n_layers=int(n_layers),
                              num_kv_heads=int(num_kv_heads),
                              head_dim=int(head_dim),
                              kv_cache_dtype=kv_cache_dtype,
                              mp=int(mp), cp=cp)

    def kv_pool_bytes(self, aggregate: bool = False) -> int:
        """Device bytes of the K/V pools (+ int8 scale arrays) this
        manager allocates pages of — PER CHIP by default (the number an
        HBM budget constrains; at cp > 1 each chip holds only
        max_pages/cp of the fleet's pages); `aggregate=True` multiplies
        both shard counts back in (the whole-fleet footprint). Requires
        `set_pool_geometry`."""
        if self._geometry is None:
            raise RuntimeError(
                "kv_pool_bytes() needs set_pool_geometry(...) first")
        geo = dict(self._geometry)
        cp = geo.pop("cp", 1)
        per_chip = (self.max_pages // cp) \
            * self.page_bytes(self.block_size, **geo)
        return per_chip * geo["mp"] * cp if aggregate else per_chip

    @property
    def n_free(self) -> int:
        """Strictly free pages (no eviction needed)."""
        return len(self._free)

    @property
    def n_available(self) -> int:
        """Pages allocatable right now: free + evictable (refcount-0
        cached). The admission bound — a referenced cached page is NOT
        available."""
        return len(self._free) + len(self._lru)

    @property
    def n_cached(self) -> int:
        """Pages currently registered in the prefix cache (any refcount)."""
        return len(self._cached)

    def pages_needed(self, n_tokens: int) -> int:
        return -(-int(n_tokens) // self.block_size)

    def alloc(self, n_tokens: int):
        return self.alloc_pages(self.pages_needed(n_tokens))

    def alloc_pages(self, n: int):
        # pool tight: evict refcount-0 cached pages, least recently
        # released first, dropping their hash mapping (future lookups
        # miss and recompute)
        evicted = False
        while len(self._free) < n and self._lru:
            page, _ = self._lru.popitem(last=False)
            h, refs = self._cached.pop(page)
            assert refs == 0, f"page {page} on LRU with refs {refs}"
            del self._hash_to_page[h]
            self._free.append(page)
            self.prefix_evictions += 1
            evicted = True
        if n > len(self._free):
            raise RuntimeError(
                f"paged KV pool exhausted: need {n} pages, "
                f"{len(self._free)} free of {self.max_pages} "
                f"({len(self._cached)} cached, {len(self._lru)} evictable)")
        if evicted:
            # only evictions append out-of-order ids (free() re-sorts)
            self._free.sort(reverse=True)
        return [self._free.pop() for _ in range(n)]

    def free(self, pages) -> None:
        """Refcount-aware release. Cached pages drop one reference and
        park on the LRU at zero (still mapped — a future prefix hit
        revives them); private pages return to the free list.

        Pages are processed in REVERSE order: a request's page list is
        block-ordered, so its deepest prefix blocks land oldest on the
        LRU and evict first — evicting block 0 before block 1 would
        orphan block 1's mapping (the chained-hash walk stops at the
        first miss and could never reach it again)."""
        for p in reversed(list(pages)):
            if not 0 <= p < self.max_pages:
                raise ValueError(f"page id {p} out of range")
            meta = self._cached.get(p)
            if meta is not None:
                if meta[1] <= 0:
                    raise ValueError(
                        f"over-release of cached page {p} (refcount 0)")
                meta[1] -= 1
                if meta[1] == 0:
                    self._lru[p] = None
                continue
            if p in self._free:
                raise ValueError(f"double free of page {p}")
            self._free.append(p)
        self._free.sort(reverse=True)

    # ---- prefix cache ---------------------------------------------------

    def prefix_lookup(self, tokens, max_blocks: Optional[int] = None,
                      hashes=None):
        """Longest cached block-aligned prefix of `tokens` WITHOUT taking
        references. Returns (n_blocks_hit, n_lru_hits) — the second
        counts hits currently refcount-0, i.e. pages that will leave the
        available pool when acquired (admission must budget for them).
        `hashes` (from hash_prefix_blocks) skips re-hashing a prompt the
        caller already hashed — the scheduler plans every waiting
        request each step, so this sits on the admission hot path."""
        hits = lru = 0
        if hashes is None:
            hashes = hash_prefix_blocks(tokens, self.block_size)
        if max_blocks is not None:
            hashes = hashes[:max_blocks]
        for h in hashes:
            page = self._hash_to_page.get(h)
            if page is None:
                break
            hits += 1
            if self._cached[page][1] == 0:
                lru += 1
        return hits, lru

    def acquire_prefix(self, tokens, max_blocks: Optional[int] = None,
                       hashes=None):
        """Walk the chained block hashes of `tokens`, taking a reference
        on every hit (pinning the page against eviction). Returns the
        cached page ids, in block order; release each with free()."""
        pages = []
        if hashes is None:
            hashes = hash_prefix_blocks(tokens, self.block_size)
        if max_blocks is not None:
            hashes = hashes[:max_blocks]
        for h in hashes:
            page = self._hash_to_page.get(h)
            if page is None:
                break
            meta = self._cached[page]
            if meta[1] == 0:
                del self._lru[page]
            meta[1] += 1
            pages.append(page)
        return pages

    def insert_prefix(self, tokens, pages, start_block: int = 0,
                      hashes=None) -> int:
        """Register `pages` — one per full block of `tokens`, starting at
        block `start_block`, already holding that block's K/V — under the
        chained prefix hashes. A hash that is already mapped is SKIPPED
        (first writer wins; the caller keeps its page as a private copy),
        so two same-prefix requests prefilled in one batch never
        double-insert. Each inserted page gains one reference owned by
        the caller — release it with free(). Returns the insert count."""
        if hashes is None:
            hashes = hash_prefix_blocks(tokens, self.block_size)
        inserted = 0
        for h, page in zip(hashes[start_block:], pages):
            if h in self._hash_to_page:
                continue
            if page in self._cached:
                raise ValueError(
                    f"page {page} already registered in the prefix cache")
            if page in self._free:
                raise ValueError(f"cannot insert free page {page}")
            self._hash_to_page[h] = page
            self._cached[page] = [h, 1]
            inserted += 1
        return inserted

    def tables_for_batch(self, seq_capacities):
        """Allocate per-sequence page lists and return (tables [B, max_n]
        int32 array, page_lists) — rows padded with their own last page
        id (never read past capacity)."""
        lists = [self.alloc(c) for c in seq_capacities]
        width = max(len(l) for l in lists)
        tbl = np.asarray([l + [l[-1]] * (width - len(l)) for l in lists],
                         np.int32)
        return jnp.asarray(tbl), lists


def build_paged_generate(cfg, b, sb, max_new, block_size: int = 64,
                         eos_token_id=None, do_sample=False, top_k=0,
                         serving_mp=None):
    """Generation over a PAGED KV cache with block tables — the vLLM-class
    serving core (reference: block_multihead_attention.py:25 + the paged
    decode kernels in paddle/phi/kernels/fusion/gpu/block_attn.h).

    Layout: per layer, key/value pools [max_pages, Hkv, block_size, D];
    a traced block table [B, pages_per_seq] maps each sequence's logical
    blocks to pool pages (any permutation — the allocator decides).
    Per-sequence true prompt lengths arrive as a traced VECTOR, so one
    compiled program serves a varying-length (ragged) batch: prefill is
    computed over the padded rectangle, per-sequence watermarks mask the
    garbage slots until overwritten, and each row's first sampled token
    reads its own last-position logits.

    Decode attention: the Pallas paged kernel
    (kernels/decode_attention.paged_decode_attention) for equal heads AND
    grouped queries — the GQA grid streams one page of one kv head per
    step and scores the whole query group in VMEM, so no path ever
    gathers pages at query width (the round-4 jnp fallback is gone).

    Weights are read through `_mm`, so the dec_params dict may hold
    dense OR nn.quant-quantized projections (int8/int4 serving composes
    with paging for free). With FLAGS_kv_cache_dtype=int8 (read when
    this factory runs — program-BUILD time) the pools are int8 +
    per-(page, kv-head) f32 absmax scales: prefill quantizes on the
    page scatter, decode commits re-quantize per token, and the Pallas
    kernels dequantize in-kernel. Returns
    run(dec_params, ids, s0_vec, tables, key, temperature, top_p).

    With FLAGS_serving_mp > 1 (or `serving_mp=`, likewise read at
    BUILD time) the whole program runs under shard_map on the serving
    mesh: pools (created inside the body) hold only the shard's local
    kv heads, q/k/v weights arrive column-sharded per
    `serving_param_specs`, and the per-layer o-proj activation
    all-gather is the one cross-chip collective. Tokens out are
    replicated — byte-identical to the single-chip program.
    """
    from ..kernels.decode_attention import paged_decode_attention

    nkv, dh = cfg.num_key_value_heads, cfg.head_dim
    n_layers = cfg.num_hidden_layers
    eps = cfg.rms_norm_eps
    if sb % block_size:
        raise ValueError(f"bucketed prompt length {sb} must be a multiple "
                         f"of block_size {block_size}")
    total = sb + max_new
    pages_per_seq = -(-total // block_size)
    n_pre = sb // block_size
    quant_kv = resolve_kv_cache_dtype() == "int8"
    use_mega = resolve_decode_megakernel()
    if use_mega == "scan":
        import warnings

        warnings.warn(
            "decode_megakernel='scan' requested, but jit_generate keeps "
            "per-layer params and pools (the stacked re-layout is the "
            "serving engine's — stack_decode_layer_params at engine "
            "build); serving the 'full' rung", stacklevel=2)
        use_mega = "full"
    tp = make_serving_tp(cfg, serving_mp)
    # the kv-head count of the pools the BODY sees (local under tp;
    # full when replicated — including the MQA fallback)
    nkv_eff = tp.nkv_local if tp is not None else nkv

    head_logits = _make_head_logits(cfg)
    base_prefill = _make_prefill(cfg, b, sb, tp=tp)

    def prefill(p, ids, tables, pools):
        to_pages, _ = make_paged_kv_helpers(b, n_pre, nkv_eff, dh,
                                            block_size, tables)
        to_pages_q8, _ = make_paged_kv_q8_helpers(b, n_pre, nkv_eff, dh,
                                                  block_size, tables)
        h, kvs = base_prefill(p, ids)
        for i, (k, v) in enumerate(kvs):
            kc, vc = pools[i]
            # scatter this layer's prefill K/V into the allocated pages
            if quant_kv:
                (kcp, ksc), (vcp, vsc) = kc, vc
                qk, sk_ = to_pages_q8(k)
                qv, sv_ = to_pages_q8(v)
                pools[i] = (
                    (kcp.at[tables[:, :n_pre]].set(qk),
                     ksc.at[tables[:, :n_pre]].set(sk_)),
                    (vcp.at[tables[:, :n_pre]].set(qv),
                     vsc.at[tables[:, :n_pre]].set(sv_)))
            else:
                pools[i] = (
                    kc.at[tables[:, :n_pre]].set(
                        to_pages(k).astype(kc.dtype)),
                    vc.at[tables[:, :n_pre]].set(
                        to_pages(v).astype(vc.dtype)))
        return h, pools

    def paged_attn(q1, kc, vc, tables, lens):
        """q1 [b, nh, dh]; lens [b] = cached positions (current token
        already written at lens[b]). The Pallas kernel covers both equal
        and grouped heads (GQA grid: one page x one kv head per step).
        int8 pools arrive as (pool, scale) tuples."""
        if isinstance(kc, tuple):
            (kcp, ksc), (vcp, vsc) = kc, vc
            return paged_decode_attention(q1, kcp, vcp, tables, lens,
                                          k_scale=ksc, v_scale=vsc)
        return paged_decode_attention(q1, kc, vc, tables, lens)

    def make_decode_step(tables):
        """The shared per-layer decode body (_make_decode_step) with the
        KV store swapped for page/slot scatter + table-indirect attention;
        `pos` is the per-sequence [b] length vector (ragged batch). With
        FLAGS_decode_megakernel (read when this factory ran — program-
        BUILD time) the whole attention block fuses into one Pallas call
        per layer; unsupported shapes fall back to this multi-kernel
        oracle path with a warning."""
        _, kv_write = make_paged_kv_helpers(b, n_pre, nkv_eff, dh,
                                            block_size, tables)
        if quant_kv:
            _, kv_write = make_paged_kv_q8_helpers(b, n_pre, nkv_eff, dh,
                                                   block_size, tables)

        def kv_attend(q1, kc, vc, lens):
            return paged_attn(q1, kc, vc, tables, lens)

        base = _make_decode_step(cfg, b, kv_write=kv_write,
                                 kv_attend=kv_attend, tp=tp)
        if use_mega == "off":
            return base

        def step(p, kcs, vcs, tok, pos):
            return _megakernel_or_fallback_step(
                cfg, b, tables, p, kcs, vcs, base,
                tp=tp, mode=use_mega)(p, kcs, vcs, tok, pos)

        return step

    def run(p_dec, ids, s0_vec, tables, key, temperature, top_p):
        dtype = p_dec["llama.embed_tokens.weight"].dtype
        max_pages = b * pages_per_seq
        if quant_kv:
            def pool():
                return (jnp.zeros((max_pages, nkv_eff, block_size, dh),
                                  jnp.int8),
                        jnp.zeros((max_pages, nkv_eff), jnp.float32))
            pools = [(pool(), pool()) for _ in range(n_layers)]
        else:
            pools = [(jnp.zeros((max_pages, nkv_eff, block_size, dh),
                                dtype),
                      jnp.zeros((max_pages, nkv_eff, block_size, dh),
                                dtype))
                     for _ in range(n_layers)]
        h, pools = prefill(p_dec, ids, tables, pools)
        # each row's own last-position logits (ragged batch)
        h_last = h[jnp.arange(b), s0_vec - 1][:, None, :]
        last_logits = head_logits(h_last, p_dec)[:, -1]
        kcs = [kv[0] for kv in pools]
        vcs = [kv[1] for kv in pools]
        return _decode_tail(make_decode_step(tables), p_dec,
                            kcs, vcs, last_logits, s0_vec, key,
                            temperature, top_p, ids.dtype, max_new,
                            eos_token_id, do_sample, top_k, b)

    if tp is None:
        return run

    from ..parallel.mesh import serving_mesh
    from ..parallel.shard_map_compat import shard_map

    mesh = serving_mesh(tp.mp)

    def run_sharded(p_dec, ids, s0_vec, tables, key, temperature, top_p):
        # in_specs are derived from the params structure at trace time
        # (quant pairs vs dense); pools never cross the boundary — they
        # are born local inside the body
        specs = serving_param_specs(p_dec, tp)
        fn = shard_map(run, mesh=mesh,
                       in_specs=(specs, P(), P(), P(), P(), P(), P()),
                       out_specs=P(), check_vma=False)
        return fn(p_dec, ids, s0_vec, tables, key, temperature, top_p)

    return run_sharded


def init_quant_serving_params(cfg, quant, seed: int = 0,
                              dtype=jnp.bfloat16):
    """Random-initialised quantized serving parameter dict in the
    `_decode_params` layout (quantized projections + fp embed/norms),
    built weight-by-weight ON DEVICE so the full-precision model never
    exists anywhere — host RAM or HBM — at once (peak transient = one
    fp32 weight). This is the 7B-on-one-16GB-chip bootstrap for serving
    benches and shape tests; real checkpoints reach the same layout via
    set_state_dict + jit_generate(..., quant=..., prefill_with_quant=True).

    Reference analog: the weight_only checkpoint conversion feeding
    python/paddle/nn/quant/quantized_linear.py weight_only_linear."""
    from ..nn.quant import weight_quantize

    key = jax.random.PRNGKey(seed)
    h, dh = cfg.hidden_size, cfg.head_dim
    nh, nkv = cfg.num_attention_heads, cfg.num_key_value_heads
    im = cfg.intermediate_size

    def nxt():
        nonlocal key
        key, k = jax.random.split(key)
        return k

    def quantized(shape):
        w = jax.random.normal(nxt(), shape, jnp.float32) * 0.02
        wq, sc = weight_quantize(Tensor(w), algo=quant)
        return (unwrap(wq), unwrap(sc))

    p = {"llama.embed_tokens.weight": (
        jax.random.normal(nxt(), (cfg.vocab_size, h), jnp.float32)
        * 0.02).astype(dtype)}
    for i in range(cfg.num_hidden_layers):
        pre = f"llama.layers.{i}."
        p[pre + "input_layernorm.weight"] = jnp.ones((h,), dtype)
        p[pre + "post_attention_layernorm.weight"] = jnp.ones((h,), dtype)
        p[pre + "self_attn.q_proj.weight"] = quantized((h, nh * dh))
        p[pre + "self_attn.k_proj.weight"] = quantized((h, nkv * dh))
        p[pre + "self_attn.v_proj.weight"] = quantized((h, nkv * dh))
        p[pre + "self_attn.o_proj.weight"] = quantized((nh * dh, h))
        p[pre + "mlp.gate_proj.weight"] = quantized((h, im))
        p[pre + "mlp.up_proj.weight"] = quantized((h, im))
        p[pre + "mlp.down_proj.weight"] = quantized((im, h))
    p["llama.norm.weight"] = jnp.ones((h,), dtype)
    if not cfg.tie_word_embeddings:
        p["lm_head.weight"] = quantized((h, cfg.vocab_size))
    return p


def _decode_tail(decode_step, p_dec, kcs, vcs, last_logits,
                 s0, key, temperature, top_p, ids_dtype, max_new,
                 eos_token_id, do_sample, top_k, b):
    """Shared post-prefill decode loop: sample the first token from the
    prompt's last logits, then scan single-token decode steps."""
    key, k0 = jax.random.split(key)
    first = _sample_next(last_logits.astype(jnp.float32), k0, do_sample,
                         temperature, top_k, top_p)
    done0 = (first == eos_token_id) if eos_token_id is not None \
        else jnp.zeros((b,), bool)

    def step(carry, _):
        tok, pos, kcs, vcs, done, key = carry
        logits, kcs, vcs = decode_step(p_dec, kcs, vcs, tok[:, None], pos)
        key, ks = jax.random.split(key)
        nxt = _sample_next(logits.astype(jnp.float32), ks, do_sample,
                           temperature, top_k, top_p)
        if eos_token_id is not None:
            nxt = jnp.where(done, eos_token_id, nxt)
            done = done | (nxt == eos_token_id)
        return (nxt, pos + 1, kcs, vcs, done, key), nxt

    toks = None
    if max_new > 1:
        _, toks = jax.lax.scan(
            step, (first, s0.astype(jnp.int32), kcs, vcs, done0, key),
            None, length=max_new - 1)
    pieces = [first[:, None]]
    if toks is not None:
        pieces.append(jnp.swapaxes(toks, 0, 1))
    return jnp.concatenate(pieces, axis=1).astype(ids_dtype)


def _make_decode_step(cfg, b, max_seq=None, kv_write=None, kv_attend=None,
                      tp=None):
    """Single-token decode step — the per-layer transformer math shared
    by EVERY generation program (fp, quant-only, paged); only the KV
    store differs, injected via two callbacks:

      kv_write(kc, vc, k, v, pos)  -> (kc, vc)   store the token's K/V
          (k/v [B, 1, Hkv, D]; pos scalar or [B] vector of cached counts)
      kv_attend(q1, kc, vc, pos)   -> ctx [B, Hq, D]

    Defaults (both None, requires max_seq): contiguous [B, Hkv, max_seq,
    D] caches with the grouped masked softmax — the
    masked_multihead_attention math.

    With `tp` (ServingTP, inside a shard_map body) the projections
    compute only the local shard's heads, kv_write/kv_attend operate on
    the local pool shard, and the per-shard context all-gathers along
    the head axis before the replicated o-proj — the ONE cross-chip
    collective per decode step per layer (the o-proj activations)."""
    nh, nkv, dh = (cfg.num_attention_heads, cfg.num_key_value_heads,
                   cfg.head_dim)
    nh_l = tp.nh_local if tp is not None else nh
    nkv_l = tp.nkv_local if tp is not None else nkv
    # GQA group from the LOCAL shard's head counts, never the full
    # model config (nh//nkv) — under the replicated-KV MQA fallback the
    # local group is nh_l // nkv, not nh // nkv (ISSUE 7 satellite)
    group = nh_l // nkv_l
    n_layers = cfg.num_hidden_layers
    eps = cfg.rms_norm_eps
    head_logits = _make_head_logits(cfg)

    if kv_write is None:
        def kv_write(kc, vc, k, v, pos):
            kc = jax.lax.dynamic_update_slice(
                kc, jnp.swapaxes(k, 1, 2).astype(kc.dtype), (0, 0, pos, 0))
            vc = jax.lax.dynamic_update_slice(
                vc, jnp.swapaxes(v, 1, 2).astype(vc.dtype), (0, 0, pos, 0))
            return kc, vc

    if kv_attend is None:
        # Pallas fused decode attention (round-5 roofline finding: the
        # old jnp einsum+softmax path read the KV cache at ~450 GB/s
        # effective and was the whole 17-20% residual above the serving
        # weight-read bound; the kernels stream it near peak)
        from ..kernels.decode_attention import (decode_attention,
                                                gqa_decode_attention)

        def kv_attend(q1, kc, vc, pos):
            lens = jnp.broadcast_to(jnp.asarray(pos, jnp.int32), (b,))
            if group == 1:
                return decode_attention(q1, kc, vc, lens)
            return gqa_decode_attention(q1, kc, vc, lens)

    def decode_step(p, kcs, vcs, tok, pos):
        """tok [B, 1] int32; pos: tokens already cached — a traced scalar
        (contiguous) or a per-sequence [B] vector (paged ragged batch;
        the [B, 1] position_ids broadcast per-example through the rope
        tables)."""
        # the embedding stays dense (it's a gather, not a matmul)
        h = p["llama.embed_tokens.weight"][tok[:, 0]][:, None, :]
        pos_ids = pos[:, None] if getattr(pos, "ndim", 0) == 1 \
            else jnp.reshape(pos, (1,))
        new_kcs, new_vcs = [], []
        for i in range(n_layers):
            x = _k_rms(h, _lw(p, i, "input_layernorm.weight"), eps)
            q = _mm(x, _lw(p, i, "self_attn.q_proj.weight")).reshape(
                b, 1, nh_l, dh)
            k = _mm(x, _lw(p, i, "self_attn.k_proj.weight")).reshape(
                b, 1, nkv_l, dh)
            v = _mm(x, _lw(p, i, "self_attn.v_proj.weight")).reshape(
                b, 1, nkv_l, dh)
            q, k = apply_rotary_emb(q, k, position_ids=pos_ids,
                                    base=cfg.rope_theta)
            kc, vc = kv_write(kcs[i], vcs[i], k, v, pos)
            new_kcs.append(kc)
            new_vcs.append(vc)
            ctx = kv_attend(q[:, 0], kc, vc, pos)       # [b, nh_l, dh]
            if tp is not None:
                ctx = tp.gather_heads(ctx)              # [b, nh, dh]
            h = h + _mm(ctx.reshape(b, 1, nh * dh),
                        _lw(p, i, "self_attn.o_proj.weight"))
            x2 = _k_rms(h, _lw(p, i, "post_attention_layernorm.weight"),
                        eps)
            gate = _mm(x2, _lw(p, i, "mlp.gate_proj.weight"))
            up = _mm(x2, _lw(p, i, "mlp.up_proj.weight"))
            h = h + _mm(jax.nn.silu(gate) * up,
                        _lw(p, i, "mlp.down_proj.weight"))
        h = _k_rms(h, p["llama.norm.weight"], eps)
        return head_logits(h, p)[:, -1], new_kcs, new_vcs

    return decode_step


def _build_jit_generate(model, cfg, b, sb, max_new, max_seq, eos_token_id,
                        do_sample, top_k):
    """Assemble the pure (params, dec_params, ids, s0, key, temperature,
    top_p) -> new_tokens generation program: prefill through the model's
    own forward (flash attention) on the bucket-padded prompt, then a scan
    of single-token decode steps over padded [B, Hkv, max_seq, D] caches
    with grouped-GQA attention (one pass over the cache per token, the
    masked_multihead_attention math). ``s0`` (true prompt length) is a
    traced scalar: pad K/V slots at [s0, sb) sit above the `pos` watermark
    so decode attention never sees them before they are overwritten."""
    nkv, dh = cfg.num_key_value_heads, cfg.head_dim
    n_layers = cfg.num_hidden_layers
    head_logits = _make_head_logits(cfg)
    decode_step = _make_decode_step(cfg, b, max_seq)

    def run(p, p_dec, ids, s0, key, temperature, top_p):
        with _tape.no_grad():
            out = model.func_call(
                p, Tensor(ids), caches=[(None, None)] * n_layers)
        logits, prefill = unwrap(out[0]), out[1]
        kcs, vcs = [], []
        for (k, v) in prefill:
            kc = jnp.zeros((b, nkv, max_seq, dh), unwrap(k).dtype)
            kcs.append(jax.lax.dynamic_update_slice(
                kc, jnp.swapaxes(unwrap(k), 1, 2), (0, 0, 0, 0)))
            vc = jnp.zeros((b, nkv, max_seq, dh), unwrap(v).dtype)
            vcs.append(jax.lax.dynamic_update_slice(
                vc, jnp.swapaxes(unwrap(v), 1, 2), (0, 0, 0, 0)))
        # logits at the TRUE last prompt position, not the padded end
        last_logits = jax.lax.dynamic_index_in_dim(
            logits, s0 - 1, axis=1, keepdims=False)
        return _decode_tail(decode_step, p_dec, kcs, vcs,
                            last_logits, s0, key, temperature, top_p,
                            ids.dtype, max_new, eos_token_id, do_sample,
                            top_k, b)

    return run


class LlamaPretrainingCriterion(Layer):
    """Shifted-token cross entropy (reference:
    semi_auto_parallel_llama_model.py LlamaPretrainingCriterion)."""

    def __init__(self, config: Optional[LlamaConfig] = None):
        super().__init__()

    def forward(self, logits, labels):
        def impl(lg, lb):
            lg32 = lg.astype(jnp.float32)
            lse = jax.scipy.special.logsumexp(lg32, axis=-1)
            picked = jnp.take_along_axis(
                lg32, lb.astype(jnp.int32)[..., None], axis=-1)[..., 0]
            return jnp.mean(lse - picked)

        return dispatch("llama_ce", impl, (logits, labels))


# ---------------------------------------------------------------------------
# sharding rules (logical-axis table; reference analog: per-op spmd_rules +
# the mp/sharding placements the fleet wrappers assign)
# ---------------------------------------------------------------------------

def llama_sharding_rules():
    """(param-name-suffix, partition dims) table. Weight layout is
    [in, out] (nn.Linear convention)."""
    return [
        # vocab over the ZeRO axis, h over mp: the lookup's gather output
        # then lands h-sharded-over-mp, which GSPMD reshards cleanly to the
        # (batch, sep)-sharded activation layout (vocab-over-mp made it log
        # "involuntary full rematerialization" on every embedding lookup)
        ("embed_tokens.weight", ("sharding", MP_AXIS)),     # [vocab, h]
        ("q_proj.weight", ("sharding", MP_AXIS)),           # [h, nh*dh]
        ("k_proj.weight", ("sharding", MP_AXIS)),
        ("v_proj.weight", ("sharding", MP_AXIS)),
        ("o_proj.weight", (MP_AXIS, "sharding")),           # [nh*dh, h]
        ("gate_proj.weight", ("sharding", MP_AXIS)),
        ("up_proj.weight", ("sharding", MP_AXIS)),
        ("down_proj.weight", (MP_AXIS, "sharding")),
        ("lm_head.weight", ("sharding", MP_AXIS)),          # [h, vocab]
        ("layernorm.weight", (None,)),
        ("norm.weight", (None,)),
    ]


def _param_sharding(mesh: Mesh, name: str, ndim: int,
                    shape) -> NamedSharding:
    for suffix, dims in llama_sharding_rules():
        if name.endswith(suffix):
            spec = []
            for i in range(ndim):
                d = dims[i] if i < len(dims) else None
                if d is not None and d in mesh.axis_names \
                        and shape[i] % int(mesh.shape[d]) == 0:
                    spec.append(d)
                else:
                    spec.append(None)
            return NamedSharding(mesh, P(*spec))
    return NamedSharding(mesh, P(*([None] * ndim)))


def shard_llama(model: Layer, mesh: Optional[Mesh] = None) -> Layer:
    """Lay every parameter out per the logical-axis rules: TP over `mp`,
    ZeRO-3/FSDP over `sharding` — one device_put per param, then XLA SPMD
    owns all collectives."""
    mesh = mesh or mesh_mod.get_global_mesh()
    if mesh is None:
        return model
    for name, p in model.named_parameters():
        sh = _param_sharding(mesh, name, p.ndim, p.shape)
        if isinstance(p._array, jax.core.Tracer):
            p._array = jax.lax.with_sharding_constraint(p._array, sh)
        else:
            p._array = jax.device_put(p._array, sh)
    return model
