"""Llama decoder family — the flagship benchmark model.

Reference anchor: test/auto_parallel/hybrid_strategy/
semi_auto_parallel_llama_model.py (the reference's own Llama used for hybrid
dp/mp/pp accuracy tests) and the fused-op family it rides
(fused_rotary_position_embedding, swiglu, rms_norm).

TPU-first design:
- weights are plain Layer parameters annotated with NamedSharding via
  logical-axis rules (`shard_llama`) — TP (mp), FSDP (sharding), and
  sequence/context parallel (sep) all come from ONE mesh; XLA SPMD inserts
  the collectives.
- attention runs the Pallas flash-attention kernel; norm runs the fused
  RMSNorm kernel; RoPE/swiglu are XLA-fused elementwise ops.
- optional per-layer rematerialisation (jax.checkpoint) trades FLOPs for
  HBM, replacing the reference's RecomputeFunction PyLayer
  (fleet/recompute/recompute.py:109).
"""
from __future__ import annotations

import dataclasses
import math
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..core.tensor import Tensor, dispatch, unwrap
from ..core import tape as _tape
from ..nn import functional as F
from ..nn.layer.layers import Layer
from ..nn.layer.common import Linear, Embedding
from ..kernels.rms_norm import rms_norm as _k_rms
from ..kernels.rope import rope_freqs, apply_rotary_emb
from ..parallel import mesh as mesh_mod


@dataclasses.dataclass
class LlamaConfig:
    vocab_size: int = 32000
    hidden_size: int = 4096
    intermediate_size: int = 11008
    num_hidden_layers: int = 32
    num_attention_heads: int = 32
    num_key_value_heads: Optional[int] = None
    max_position_embeddings: int = 4096
    rms_norm_eps: float = 1e-6
    rope_theta: float = 10000.0
    tie_word_embeddings: bool = False
    use_flash_attention: bool = True
    recompute: bool = False          # per-layer remat
    # skip remat for the last K layers: their saved activations live the
    # shortest (backward frees them first), so exempting them buys back
    # recompute FLOPs at minimal peak-memory cost (analog of the
    # reference's selective recompute_interval in fleet pp_layers)
    recompute_skip: int = 0
    # remat policy: "none" saves only layer boundaries (recompute all);
    # "save_attn" additionally keeps attention outputs, skipping the flash
    # forward re-run in the backward pass (reference analog: selective
    # recompute in fleet recompute_hybrid);
    # "dots_saveable" / "dots_with_no_batch_dims_saveable" save matmul
    # outputs (jax.checkpoint_policies; measured: OOM at the bench config)
    remat_policy: str = "none"
    # attention over the sep axis: "ulysses" (all-to-all seq->head reshard)
    # or "ring" (ring attention — k/v rotate with ppermute, exact blockwise
    # softmax; the long-context leapfrog the reference lacks)
    attention_impl: str = "ulysses"
    dtype: str = "float32"

    def __post_init__(self):
        if self.num_key_value_heads is None:
            self.num_key_value_heads = self.num_attention_heads

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_attention_heads

    # ------ stock sizes ------
    @staticmethod
    def llama2_7b(**over) -> "LlamaConfig":
        return LlamaConfig(hidden_size=4096, intermediate_size=11008,
                           num_hidden_layers=32, num_attention_heads=32,
                           **over)

    @staticmethod
    def llama_1b(**over) -> "LlamaConfig":
        return LlamaConfig(hidden_size=2048, intermediate_size=5504,
                           num_hidden_layers=16, num_attention_heads=16,
                           **over)

    @staticmethod
    def tiny(**over) -> "LlamaConfig":
        return LlamaConfig(vocab_size=128, hidden_size=64,
                           intermediate_size=128, num_hidden_layers=2,
                           num_attention_heads=4, num_key_value_heads=2,
                           max_position_embeddings=64, **over)


# ---------------------------------------------------------------------------
# activation sharding helper
# ---------------------------------------------------------------------------

def _act_spec(mesh: Optional[Mesh], shape, *dims) -> Optional[NamedSharding]:
    """Build a NamedSharding keeping only axes present in the mesh whose size
    divides the tensor dim. Each dim is None, an axis name, or a tuple of
    axis names."""
    if mesh is None:
        return None
    from ..parallel.mesh import divisible_prefix

    out = []
    for i, d in enumerate(dims):
        if d is None:
            out.append(None)
            continue
        names = (d,) if isinstance(d, str) else d
        kept = divisible_prefix(mesh, shape[i], names)
        out.append(kept if kept else None)
    return NamedSharding(mesh, P(*out))


def _constrain(x, mesh, *dims):
    sh = _act_spec(mesh, list(x.shape), *dims)
    if sh is None:
        return x
    return dispatch("shard_constraint",
                    lambda a: jax.lax.with_sharding_constraint(a, sh), (x,))


# batch dim is data-parallel over both dp and the ZeRO axis; seq dim is
# context-parallel over sep (reference: 5-D topo [data,pipe,sharding,sep,model],
# fleet/base/topology.py:188)
from ..parallel.mesh import BATCH_AXES  # noqa: E402  (single topology source)

SEQ_AXIS = "sep"
MP_AXIS = "mp"


# ---------------------------------------------------------------------------
# layers
# ---------------------------------------------------------------------------

class LlamaRMSNorm(Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.hidden_size = config.hidden_size
        self.variance_epsilon = config.rms_norm_eps
        from ..nn.initializer import Constant

        self.weight = self.create_parameter(
            [config.hidden_size], default_initializer=Constant(1.0),
            dtype=config.dtype)

    def forward(self, x):
        return dispatch(
            "rms_norm",
            lambda a, w: _k_rms(a, w, self.variance_epsilon), (x, self.weight))


class LlamaAttention(Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.config = config
        h = config.hidden_size
        nh, nkv, dh = (config.num_attention_heads, config.num_key_value_heads,
                       config.head_dim)
        self.num_heads, self.num_kv_heads, self.head_dim = nh, nkv, dh
        self.q_proj = Linear(h, nh * dh, bias_attr=False)
        self.k_proj = Linear(h, nkv * dh, bias_attr=False)
        self.v_proj = Linear(h, nkv * dh, bias_attr=False)
        self.o_proj = Linear(nh * dh, h, bias_attr=False)

    def forward(self, hidden, cos, sin, cache: Optional[Tuple] = None,
                mesh=None):
        b, s, _ = hidden.shape
        q = self.q_proj(hidden).reshape([b, s, self.num_heads, self.head_dim])
        k = self.k_proj(hidden).reshape([b, s, self.num_kv_heads, self.head_dim])
        v = self.v_proj(hidden).reshape([b, s, self.num_kv_heads, self.head_dim])
        q, k = dispatch(
            "fused_rope",
            lambda qa, ka: apply_rotary_emb(qa, ka, cos=cos, sin=sin), (q, k))
        new_cache = None
        if cache is not None:
            pk, pv = cache
            if pk is not None:
                k = Tensor(jnp.concatenate([unwrap(pk), unwrap(k)], axis=1))
                v = Tensor(jnp.concatenate([unwrap(pv), unwrap(v)], axis=1))
            new_cache = (k, v)
        causal = cache is None or k.shape[1] == s
        use_ring = (self.config.attention_impl == "ring" and cache is None
                    and mesh is not None and SEQ_AXIS in mesh.axis_names
                    and int(mesh.shape[SEQ_AXIS]) > 1)
        if use_ring:
            from ..parallel.ring_attention import ring_attention

            # GQA handled inside the ring by grouped einsum — no repeat
            out = dispatch(
                "ring_attention",
                lambda qa, ka, va: ring_attention(
                    qa, ka, va, mesh=mesh, axis=SEQ_AXIS, causal=causal),
                (q, k, v))
        else:
            from ..parallel.ulysses import seq_to_head, ulysses_available

            ulysses = (cache is None and mesh is not None
                       and ulysses_available(mesh, self.num_heads, s))
            if ulysses:
                # Ulysses: explicit all-to-all over the sep group swaps seq
                # shards for head shards (GSPMD's re-constraint lowering of
                # this swap replicates — "involuntary full remat" — so the
                # swap is a shard_map'd lax.all_to_all riding ICI; reference
                # analog: SegmentParallel sep groups,
                # fleet/base/topology.py:224)
                a2a = lambda a: seq_to_head(a, mesh)
                q = dispatch("ulysses_a2a", a2a, (q,))
                if ulysses_available(mesh, self.num_kv_heads, s):
                    k = dispatch("ulysses_a2a", a2a, (k,))
                    v = dispatch("ulysses_a2a", a2a, (v,))
                else:
                    # GQA with too few kv heads to split over mp*sep:
                    # replicate kv groups just enough to split evenly —
                    # the repeat multiplies a2a bytes, so use the minimal
                    # factor whose result still block-aligns with q's
                    # contiguous (mp, sep) head shards (kv'[j] = kv[j//r]
                    # puts q head t with kv group t*nkv/nh on each device)
                    from ..parallel.ulysses import minimal_kv_repeat

                    rep = minimal_kv_repeat(mesh, self.num_heads,
                                            self.num_kv_heads)
                    grow = lambda a: seq_to_head(
                        jnp.repeat(a, rep, axis=2), mesh)
                    k = dispatch("ulysses_a2a", grow, (k,))
                    v = dispatch("ulysses_a2a", grow, (v,))
            else:
                # heads sharded over mp (and sep when divisible): GSPMD
                # inserts the reshard from the constraint
                q = _constrain(q, mesh, BATCH_AXES, None,
                               (MP_AXIS, SEQ_AXIS), None)
                k = _constrain(k, mesh, BATCH_AXES, None,
                               (MP_AXIS, SEQ_AXIS), None)
                v = _constrain(v, mesh, BATCH_AXES, None,
                               (MP_AXIS, SEQ_AXIS), None)
            out, _ = F.flash_attention(q, k, v, causal=causal)
            if ulysses:
                from ..parallel.ulysses import head_to_seq

                out = dispatch("ulysses_a2a_back",
                               lambda a: head_to_seq(a, mesh), (out,))
        if self.config.remat_policy == "save_attn":
            from jax.ad_checkpoint import checkpoint_name

            out = dispatch("ckpt_name",
                           lambda a: checkpoint_name(a, "attn_out"), (out,))
        out = out.reshape([b, s, self.num_heads * self.head_dim])
        out = self.o_proj(out)
        if cache is not None:
            return out, new_cache
        return out


class LlamaMLP(Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        h, i = config.hidden_size, config.intermediate_size
        self.gate_proj = Linear(h, i, bias_attr=False)
        self.up_proj = Linear(h, i, bias_attr=False)
        self.down_proj = Linear(i, h, bias_attr=False)

    def forward(self, x):
        return self.down_proj(F.swiglu(self.gate_proj(x), self.up_proj(x)))


class LlamaDecoderLayer(Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.config = config
        self.self_attn = LlamaAttention(config)
        self.mlp = LlamaMLP(config)
        self.input_layernorm = LlamaRMSNorm(config)
        self.post_attention_layernorm = LlamaRMSNorm(config)

    def forward(self, hidden, cos, sin, cache=None, mesh=None):
        residual = hidden
        h = self.input_layernorm(hidden)
        if cache is not None:
            attn, new_cache = self.self_attn(h, cos, sin, cache=cache, mesh=mesh)
        else:
            attn = self.self_attn(h, cos, sin, mesh=mesh)
            new_cache = None
        hidden = residual + attn
        residual = hidden
        h = self.post_attention_layernorm(hidden)
        hidden = residual + self.mlp(h)
        hidden = _constrain(hidden, mesh, BATCH_AXES, SEQ_AXIS, None)
        if cache is not None:
            return hidden, new_cache
        return hidden


class LlamaModel(Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.config = config
        self.embed_tokens = Embedding(config.vocab_size, config.hidden_size)
        from ..nn.layer.container import LayerList

        self.layers = LayerList(
            [LlamaDecoderLayer(config) for _ in range(config.num_hidden_layers)])
        self.norm = LlamaRMSNorm(config)
        if config.dtype != "float32":
            self.to(dtype=config.dtype)

    def forward(self, input_ids, caches=None, position_offset: int = 0):
        mesh = mesh_mod.get_global_mesh()
        s = input_ids.shape[1]
        pos = jnp.arange(position_offset, position_offset + s)
        cos, sin = rope_freqs(s, self.config.head_dim,
                              base=self.config.rope_theta, position_ids=pos)
        hidden = self.embed_tokens(input_ids)
        hidden = _constrain(hidden, mesh, BATCH_AXES, SEQ_AXIS, None)
        use_ckpt = (self.config.recompute and not _tape.grad_enabled()
                    and caches is None)
        new_caches = [] if caches is not None else None
        for li, layer in enumerate(self.layers):
            if caches is not None:
                hidden, c = layer(hidden, cos, sin, cache=caches[li], mesh=mesh)
                new_caches.append(c)
            elif use_ckpt and li < len(self.layers) - \
                    self.config.recompute_skip:
                def run(h, l=layer):
                    return unwrap(l(Tensor(h), cos, sin, mesh=mesh))

                policy = None
                if self.config.remat_policy == "save_attn":
                    policy = jax.checkpoint_policies.save_only_these_names(
                        "attn_out")
                elif self.config.remat_policy in (
                        "dots_saveable", "dots_with_no_batch_dims_saveable"):
                    policy = getattr(jax.checkpoint_policies,
                                     self.config.remat_policy)
                hidden = Tensor(jax.checkpoint(run, policy=policy)(
                    unwrap(hidden)))
            else:
                hidden = layer(hidden, cos, sin, mesh=mesh)
        hidden = self.norm(hidden)
        if caches is not None:
            return hidden, new_caches
        return hidden


class LlamaForCausalLM(Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.config = config
        self.llama = LlamaModel(config)
        if not config.tie_word_embeddings:
            self.lm_head = Linear(config.hidden_size, config.vocab_size,
                                  bias_attr=False)
            if config.dtype != "float32":
                self.lm_head.to(dtype=config.dtype)

    def forward(self, input_ids, caches=None, position_offset: int = 0):
        out = self.llama(input_ids, caches=caches,
                         position_offset=position_offset)
        hidden = out[0] if caches is not None else out
        if self.config.tie_word_embeddings:
            w = self.llama.embed_tokens.weight
            logits = dispatch("tied_lm_head",
                              lambda h, e: jnp.matmul(h, e.T), (hidden, w))
        else:
            logits = self.lm_head(hidden)
        if caches is not None:
            return logits, out[1]
        return logits

    # --------------------------------------------------------------
    def jit_generate(self, input_ids, max_new_tokens: int = 32,
                     eos_token_id: Optional[int] = None,
                     do_sample: bool = False, temperature: float = 1.0,
                     top_k: int = 0, top_p: float = 1.0,
                     seed: Optional[int] = None, bucket_size: int = 128,
                     quant: Optional[str] = None):
        """Decode as ONE jitted program: prefill, then a lax.scan over
        decode steps against fixed-layout per-layer KV caches (reference
        analog: the fused serving generation path over
        masked_multihead_attention + top_p_sampling,
        python/paddle/tensor/search.py:1354).

        Serving features:
        - **prompt bucketing**: prompts are right-padded to a multiple of
          ``bucket_size`` and the true length enters the program as a
          traced scalar, so every prompt length in a bucket shares ONE
          compile (pad K/V slots are masked out of decode attention until
          overwritten, and the first token reads the logits at the true
          last position).
        - **sampling**: ``do_sample=True`` enables temperature / top-k /
          top-p with a threaded PRNG key; ``seed`` makes it deterministic.
          temperature and top_p are traced (no recompile when they change);
          top_k is static (it sizes a lax.top_k).
        - **weight-only int8/int4 decode** (``quant="weight_only_int8"``
          or ``"weight_only_int4"``): the decode scan reads per-channel-
          scaled int8 (or nibble-packed int4) projection weights
          (nn.quant.weight_quantize layout) — half / quarter the HBM
          traffic on the weight-bound decode path.
        """
        cfg = self.config
        ids_arr = unwrap(input_ids) if isinstance(input_ids, Tensor) \
            else jnp.asarray(input_ids)
        if max_new_tokens <= 0:
            return Tensor(ids_arr)
        b, s0 = ids_arr.shape
        sb = -(-s0 // bucket_size) * bucket_size  # bucketed prompt length
        padded = jnp.pad(ids_arr, ((0, 0), (0, sb - s0)))
        total = sb + max_new_tokens
        max_seq = total if total < 512 else ((total + 511) // 512) * 512
        params = dict(self.raw_state())
        dec_params = self._decode_params(params, quant)
        sig = (b, sb, max_new_tokens, eos_token_id, do_sample, int(top_k),
               quant)
        cache = getattr(self, "_jit_gen_cache", None)
        if cache is None:
            cache = self._jit_gen_cache = {}
        if sig not in cache:  # keep every compiled shape variant
            fn = _build_jit_generate(self, cfg, b, sb, max_new_tokens,
                                     max_seq, eos_token_id, do_sample,
                                     int(top_k))
            cache[sig] = jax.jit(fn)
        if seed is not None:
            key = jax.random.PRNGKey(int(seed))
        else:
            from ..framework.random import next_key

            key = next_key()
        new_tokens = cache[sig](params, dec_params, padded,
                                jnp.asarray(s0, jnp.int32), key,
                                jnp.asarray(temperature, jnp.float32),
                                jnp.asarray(top_p, jnp.float32))
        out = jnp.concatenate([ids_arr, new_tokens], axis=1)
        if eos_token_id is not None:
            # host-side trim: cut after every row has hit EOS
            toks = np.asarray(new_tokens)
            hit = (toks == eos_token_id)
            if hit.any(axis=1).all():
                last = int(hit.argmax(axis=1).max())
                out = out[:, :s0 + last + 1]
        return Tensor(out)

    def _decode_params(self, params, quant):
        """Decode-path parameter dict; with quant, the 2-D projection
        weights become (int8 [N,K], scale [N]) pairs. Quantized entries are
        cached per source array (jax arrays are immutable, so identity
        tracks staleness): a weight updated by training or set_state_dict
        is requantized on the next call, never served stale."""
        if quant is None:
            return params
        if quant not in ("weight_only_int8", "weight_only_int4"):
            raise ValueError(
                "quant must be None, 'weight_only_int8' or "
                f"'weight_only_int4', got {quant!r}")
        from ..nn.quant import weight_quantize

        qcache = getattr(self, "_decode_quant_cache", None)
        if qcache is None:
            qcache = self._decode_quant_cache = {}
        out = dict(params)
        names = [n for n in params
                 if n.endswith("_proj.weight") or n == "lm_head.weight"]
        for n in names:
            src = params[n]
            hit = qcache.get((n, quant))
            if hit is None or hit[0] is not src:
                wq, sc = weight_quantize(Tensor(src.astype(jnp.float32)),
                                         algo=quant)
                hit = (src, (unwrap(wq), unwrap(sc)))
                qcache[(n, quant)] = hit
            out[n] = hit[1]
        return out

    def generate(self, input_ids, max_new_tokens: int = 32,
                 eos_token_id: Optional[int] = None, do_sample: bool = False,
                 temperature: float = 1.0, top_k: int = 0, top_p: float = 1.0,
                 seed: Optional[int] = None):
        """Eager decode with a KV cache (reference analog: PaddleNLP
        generation; kernel family masked_multihead_attention). Supports the
        same greedy/sampled selection as jit_generate."""
        ids = input_ids if isinstance(input_ids, Tensor) else Tensor(input_ids)
        if seed is not None:
            key = jax.random.PRNGKey(int(seed))
        else:
            from ..framework.random import next_key

            key = next_key()

        def pick(logits_slice, key):
            return _sample_next(
                logits_slice.astype(jnp.float32), key, do_sample,
                jnp.asarray(temperature, jnp.float32), int(top_k),
                jnp.asarray(top_p, jnp.float32))[:, None]

        caches = [(None, None)] * self.config.num_hidden_layers
        logits, caches = self(ids, caches=caches)
        out = [ids]
        key, k0 = jax.random.split(key)
        last = pick(unwrap(logits)[:, -1], k0)
        offset = ids.shape[1]
        for step in range(max_new_tokens):
            out.append(Tensor(last))
            if eos_token_id is not None and bool(
                    jnp.all(last == eos_token_id)):
                break
            if step == max_new_tokens - 1:
                break  # the last appended token needs no further forward
            logits, caches = self(Tensor(last), caches=caches,
                                  position_offset=offset)
            offset += 1
            key, ks = jax.random.split(key)
            last = pick(unwrap(logits)[:, -1], ks)
        return Tensor(jnp.concatenate([unwrap(t) for t in out], axis=1))


def _mm(x, w):
    """Matmul against a decode weight: dense [K, N], or a
    nn.quant.weight_quantize pair — int8 [N, K] or packed int4 [N, K//2]
    (detected by the stored K) with per-channel scales [N]. The
    int→bf16 convert (and the int4 unpack) fuse into the dot, so HBM
    reads stay at the quantized width."""
    if isinstance(w, tuple):
        wq, sc = w
        if wq.shape[1] != x.shape[-1]:  # packed int4: two nibbles/byte
            # in-register Pallas dequant-matmul: the packed bytes stay
            # packed all the way into VMEM (kernels/int4_matmul.py) —
            # end-to-end decode 1.68 ms/step vs 2.79 for the XLA shift
            # form (int8 remains fastest at ~1.1-1.3; BASELINE.md)
            from ..kernels.int4_matmul import int4_matmul

            lead = x.shape[:-1]
            out = int4_matmul(x.reshape(-1, x.shape[-1]), wq, sc)
            return out.reshape(*lead, wq.shape[0]).astype(x.dtype)
        out = jnp.einsum("...k,nk->...n", x, wq.astype(x.dtype),
                         preferred_element_type=jnp.float32)
        return (out * sc).astype(x.dtype)
    return x @ w


def _sample_next(logits, key, do_sample, temperature, top_k, top_p):
    """Pick the next token from [B, V] logits: greedy, or nucleus sampling
    (the jit-safe form of ops/search.py top_p_sampling — sort, cumulative
    mass cut, categorical draw)."""
    if not do_sample:
        return jnp.argmax(logits, axis=-1)
    logits = logits.astype(jnp.float32) / jnp.maximum(temperature, 1e-6)
    if top_k > 0:
        kth = jax.lax.top_k(logits, top_k)[0][:, -1:]
        logits = jnp.where(logits < kth, -1e30, logits)
    srt = jnp.sort(logits, axis=-1)[:, ::-1]
    probs = jax.nn.softmax(srt, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    keep = (cum - probs) < top_p
    keep = keep.at[:, 0].set(True)  # the argmax survives even top_p<=0
    threshold = jnp.min(jnp.where(keep, srt, jnp.inf), axis=-1, keepdims=True)
    logits = jnp.where(logits < threshold, -1e30, logits)
    return jax.random.categorical(key, logits, axis=-1)


def _build_jit_generate(model, cfg, b, sb, max_new, max_seq, eos_token_id,
                        do_sample, top_k):
    """Assemble the pure (params, dec_params, ids, s0, key, temperature,
    top_p) -> new_tokens generation program: prefill through the model's
    own forward (flash attention) on the bucket-padded prompt, then a scan
    of single-token decode steps over padded [B, Hkv, max_seq, D] caches
    with grouped-GQA attention (one pass over the cache per token, the
    masked_multihead_attention math). ``s0`` (true prompt length) is a
    traced scalar: pad K/V slots at [s0, sb) sit above the `pos` watermark
    so decode attention never sees them before they are overwritten."""
    nh, nkv, dh = (cfg.num_attention_heads, cfg.num_key_value_heads,
                   cfg.head_dim)
    group = nh // nkv
    n_layers = cfg.num_hidden_layers
    eps = cfg.rms_norm_eps

    def head_logits(h, p):
        if cfg.tie_word_embeddings:
            return h @ p["llama.embed_tokens.weight"].T
        return _mm(h, p["lm_head.weight"])

    def decode_step(p, kcs, vcs, tok, pos):
        """tok [B, 1] int32; pos scalar int32 (tokens already cached)."""
        # the embedding stays dense (it's a gather, not a matmul)
        h = p["llama.embed_tokens.weight"][tok[:, 0]][:, None, :]
        pos_ids = jnp.reshape(pos, (1,))
        new_kcs, new_vcs = [], []
        for i in range(n_layers):
            pre = f"llama.layers.{i}."
            x = _k_rms(h, p[pre + "input_layernorm.weight"], eps)
            q = _mm(x, p[pre + "self_attn.q_proj.weight"]).reshape(
                b, 1, nh, dh)
            k = _mm(x, p[pre + "self_attn.k_proj.weight"]).reshape(
                b, 1, nkv, dh)
            v = _mm(x, p[pre + "self_attn.v_proj.weight"]).reshape(
                b, 1, nkv, dh)
            q, k = apply_rotary_emb(q, k, position_ids=pos_ids,
                                    base=cfg.rope_theta)
            kc = jax.lax.dynamic_update_slice(
                kcs[i], jnp.swapaxes(k, 1, 2).astype(kcs[i].dtype),
                (0, 0, pos, 0))
            vc = jax.lax.dynamic_update_slice(
                vcs[i], jnp.swapaxes(v, 1, 2).astype(vcs[i].dtype),
                (0, 0, pos, 0))
            new_kcs.append(kc)
            new_vcs.append(vc)
            # grouped-GQA decode attention: one masked pass over the cache
            qg = q[:, 0].reshape(b, nkv, group, dh)
            logits = jnp.einsum(
                "bkgd,bksd->bkgs", qg.astype(jnp.float32),
                kc.astype(jnp.float32)) / math.sqrt(dh)
            valid = jnp.arange(max_seq)[None, None, None, :] <= pos
            logits = jnp.where(valid, logits, -1e30)
            probs = jax.nn.softmax(logits, axis=-1)
            ctx = jnp.einsum("bkgs,bksd->bkgd", probs,
                             vc.astype(jnp.float32))
            ctx = ctx.reshape(b, 1, nh * dh).astype(h.dtype)
            h = h + _mm(ctx, p[pre + "self_attn.o_proj.weight"])
            x2 = _k_rms(h, p[pre + "post_attention_layernorm.weight"], eps)
            gate = _mm(x2, p[pre + "mlp.gate_proj.weight"])
            up = _mm(x2, p[pre + "mlp.up_proj.weight"])
            h = h + _mm(jax.nn.silu(gate) * up,
                        p[pre + "mlp.down_proj.weight"])
        h = _k_rms(h, p["llama.norm.weight"], eps)
        return head_logits(h, p)[:, -1], new_kcs, new_vcs

    def run(p, p_dec, ids, s0, key, temperature, top_p):
        with _tape.no_grad():
            out = model.func_call(
                p, Tensor(ids), caches=[(None, None)] * n_layers)
        logits, prefill = unwrap(out[0]), out[1]
        kcs, vcs = [], []
        for (k, v) in prefill:
            kc = jnp.zeros((b, nkv, max_seq, dh), unwrap(k).dtype)
            kcs.append(jax.lax.dynamic_update_slice(
                kc, jnp.swapaxes(unwrap(k), 1, 2), (0, 0, 0, 0)))
            vc = jnp.zeros((b, nkv, max_seq, dh), unwrap(v).dtype)
            vcs.append(jax.lax.dynamic_update_slice(
                vc, jnp.swapaxes(unwrap(v), 1, 2), (0, 0, 0, 0)))
        # logits at the TRUE last prompt position, not the padded end
        last_logits = jax.lax.dynamic_index_in_dim(
            logits, s0 - 1, axis=1, keepdims=False)
        key, k0 = jax.random.split(key)
        first = _sample_next(last_logits.astype(jnp.float32), k0, do_sample,
                             temperature, top_k, top_p)
        done0 = (first == eos_token_id) if eos_token_id is not None \
            else jnp.zeros((b,), bool)

        def step(carry, _):
            tok, pos, kcs, vcs, done, key = carry
            logits, kcs, vcs = decode_step(p_dec, kcs, vcs, tok[:, None], pos)
            key, ks = jax.random.split(key)
            nxt = _sample_next(logits.astype(jnp.float32), ks, do_sample,
                               temperature, top_k, top_p)
            if eos_token_id is not None:
                nxt = jnp.where(done, eos_token_id, nxt)
                done = done | (nxt == eos_token_id)
            return (nxt, pos + 1, kcs, vcs, done, key), nxt

        toks = None
        if max_new > 1:
            _, toks = jax.lax.scan(
                step, (first, s0.astype(jnp.int32), kcs, vcs, done0, key),
                None, length=max_new - 1)
        pieces = [first[:, None]]
        if toks is not None:
            pieces.append(jnp.swapaxes(toks, 0, 1))
        return jnp.concatenate(pieces, axis=1).astype(ids.dtype)

    return run


class LlamaPretrainingCriterion(Layer):
    """Shifted-token cross entropy (reference:
    semi_auto_parallel_llama_model.py LlamaPretrainingCriterion)."""

    def __init__(self, config: Optional[LlamaConfig] = None):
        super().__init__()

    def forward(self, logits, labels):
        def impl(lg, lb):
            lg32 = lg.astype(jnp.float32)
            lse = jax.scipy.special.logsumexp(lg32, axis=-1)
            picked = jnp.take_along_axis(
                lg32, lb.astype(jnp.int32)[..., None], axis=-1)[..., 0]
            return jnp.mean(lse - picked)

        return dispatch("llama_ce", impl, (logits, labels))


# ---------------------------------------------------------------------------
# sharding rules (logical-axis table; reference analog: per-op spmd_rules +
# the mp/sharding placements the fleet wrappers assign)
# ---------------------------------------------------------------------------

def llama_sharding_rules():
    """(param-name-suffix, partition dims) table. Weight layout is
    [in, out] (nn.Linear convention)."""
    return [
        # vocab over the ZeRO axis, h over mp: the lookup's gather output
        # then lands h-sharded-over-mp, which GSPMD reshards cleanly to the
        # (batch, sep)-sharded activation layout (vocab-over-mp made it log
        # "involuntary full rematerialization" on every embedding lookup)
        ("embed_tokens.weight", ("sharding", MP_AXIS)),     # [vocab, h]
        ("q_proj.weight", ("sharding", MP_AXIS)),           # [h, nh*dh]
        ("k_proj.weight", ("sharding", MP_AXIS)),
        ("v_proj.weight", ("sharding", MP_AXIS)),
        ("o_proj.weight", (MP_AXIS, "sharding")),           # [nh*dh, h]
        ("gate_proj.weight", ("sharding", MP_AXIS)),
        ("up_proj.weight", ("sharding", MP_AXIS)),
        ("down_proj.weight", (MP_AXIS, "sharding")),
        ("lm_head.weight", ("sharding", MP_AXIS)),          # [h, vocab]
        ("layernorm.weight", (None,)),
        ("norm.weight", (None,)),
    ]


def _param_sharding(mesh: Mesh, name: str, ndim: int,
                    shape) -> NamedSharding:
    for suffix, dims in llama_sharding_rules():
        if name.endswith(suffix):
            spec = []
            for i in range(ndim):
                d = dims[i] if i < len(dims) else None
                if d is not None and d in mesh.axis_names \
                        and shape[i] % int(mesh.shape[d]) == 0:
                    spec.append(d)
                else:
                    spec.append(None)
            return NamedSharding(mesh, P(*spec))
    return NamedSharding(mesh, P(*([None] * ndim)))


def shard_llama(model: Layer, mesh: Optional[Mesh] = None) -> Layer:
    """Lay every parameter out per the logical-axis rules: TP over `mp`,
    ZeRO-3/FSDP over `sharding` — one device_put per param, then XLA SPMD
    owns all collectives."""
    mesh = mesh or mesh_mod.get_global_mesh()
    if mesh is None:
        return model
    for name, p in model.named_parameters():
        sh = _param_sharding(mesh, name, p.ndim, p.shape)
        if isinstance(p._array, jax.core.Tracer):
            p._array = jax.lax.with_sharding_constraint(p._array, sh)
        else:
            p._array = jax.device_put(p._array, sh)
    return model
