"""Pipeline-parallel Llama training step.

Reference: fleet PipelineLayer + PipelineParallel.train_batch
(fleet/meta_parallel/parallel_layers/pp_layers.py:257 SegmentLayers —
partitioning decoder layers into stages — and pipeline_parallel.py 1F1B).

TPU-native: decoder layers are grouped into `pp` stages; per-stage parameter
pytrees are stacked with the stage dim sharded over the `pp` mesh axis and
the microbatch loop runs as scan+ppermute inside ONE jitted program
(parallel/pipeline_spmd.py). Embedding, final norm and the LM head run
outside the pipeline region (replicated over pp, still TP/FSDP-sharded over
the other axes) — the reference shares the embedding across first/last
stages similarly.
"""
from __future__ import annotations

from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from ..core.tensor import Tensor, unwrap
from ..core import tape as _tape
from ..kernels.rope import rope_freqs
from ..parallel import mesh as mesh_mod
from ..parallel.pipeline_spmd import (pipeline_1f1b, pipeline_eager_1f1b,
                                      pipeline_forward, pipeline_vpp_forward,
                                      pipeline_zb1f1b, stack_stage_params)
from ..parallel.trainer import adamw_update, batch_sharding, \
    init_adamw_state
from .llama import LlamaConfig, LlamaForCausalLM, LlamaPretrainingCriterion

__all__ = ["make_llama_pp_train_step", "split_llama_state",
           "chunk_llama_state", "merge_llama_chunked_state"]

def _flatten_with_path(tree):
    """jax.tree.flatten_with_path newer-API spelling, with the
    jax.tree_util fallback for 0.4.x."""
    if hasattr(jax.tree, "flatten_with_path"):
        return jax.tree.flatten_with_path(tree)[0]
    return jax.tree_util.tree_flatten_with_path(tree)[0]


_LAYER_PREFIX = "llama.layers."


def _parse_layer_state(state):
    """Split a flat raw_state into (outer, per_layer list of sub-dicts)."""
    per_layer = []
    outer = {}
    for k, v in state.items():
        if k.startswith(_LAYER_PREFIX):
            rest = k[len(_LAYER_PREFIX):]
            idx, sub = rest.split(".", 1)
            idx = int(idx)
            while len(per_layer) <= idx:
                per_layer.append({})
            per_layer[idx][sub] = v
        else:
            outer[k] = v
    return outer, per_layer


def split_llama_state(state: Dict[str, jax.Array], n_layers: int,
                      n_stages: int, mesh: Optional[Mesh] = None):
    """Split a flat raw_state into (outer_params, stacked_stage_params).

    Layer params are grouped into n_stages contiguous blocks (reference:
    SegmentLayers uniform partition), stacked [n_stages, layers_per_stage,
    ...] with the stage dim sharded over `pp`."""
    if n_layers % n_stages:
        raise ValueError(f"{n_layers} layers not divisible into "
                         f"{n_stages} stages")
    outer, per_layer = _parse_layer_state(state)
    lps = n_layers // n_stages
    per_stage = []
    for s in range(n_stages):
        block = per_layer[s * lps:(s + 1) * lps]
        per_stage.append(jax.tree.map(lambda *xs: jnp.stack(xs), *block))
    stacked = stack_stage_params(per_stage, mesh, axis="pp")
    return outer, stacked


def chunk_llama_state(state: Dict[str, jax.Array], n_layers: int,
                      n_stages: int, vpp_degree: int,
                      mesh: Optional[Mesh] = None):
    """Split a flat raw_state into (outer, chunked_stage_params) for the
    interleaved (VPP) schedule: n_stages*vpp_degree chunks of contiguous
    layers, laid out [S, V, layers_per_chunk, ...] with [r, v] = chunk
    v*S + r (Megatron interleaved assignment; reference:
    PipelineParallelWithInterleave's _build_layer_impl chunking)."""
    n_chunks = n_stages * vpp_degree
    if n_layers % n_chunks:
        raise ValueError(f"{n_layers} layers not divisible into "
                         f"{n_chunks} chunks (pp={n_stages} x V={vpp_degree})")
    outer, per_layer = _parse_layer_state(state)
    lpc = n_layers // n_chunks
    chunks = []
    for c in range(n_chunks):
        block = per_layer[c * lpc:(c + 1) * lpc]
        chunks.append(jax.tree.map(lambda *xs: jnp.stack(xs), *block))
    per_rank = []
    for r in range(n_stages):
        rank_chunks = [chunks[v * n_stages + r] for v in range(vpp_degree)]
        per_rank.append(jax.tree.map(lambda *xs: jnp.stack(xs), *rank_chunks))
    return outer, stack_stage_params(per_rank, mesh, axis="pp")


def merge_llama_chunked_state(outer: Dict, chunked, n_layers: int) -> Dict:
    """Inverse of chunk_llama_state."""
    state = dict(outer)
    leaves = jax.tree.leaves(chunked)
    n_stages, vpp = leaves[0].shape[0], leaves[0].shape[1]
    lpc = n_layers // (n_stages * vpp)
    flat = _flatten_with_path(chunked)
    for path, arr in flat:
        sub = ".".join(str(getattr(p, "key", getattr(p, "idx", p)))
                       for p in path)
        for r in range(n_stages):
            for v in range(vpp):
                c = v * n_stages + r
                for l in range(lpc):
                    state[f"{_LAYER_PREFIX}{c * lpc + l}.{sub}"] = arr[r, v, l]
    return state


def merge_llama_state(outer: Dict, stacked, n_layers: int) -> Dict:
    """Inverse of split_llama_state (for state_dict/checkpoint export)."""
    state = dict(outer)
    n_stages = jax.tree.leaves(stacked)[0].shape[0]
    lps = n_layers // n_stages
    flat = _flatten_with_path(stacked)
    for path, arr in flat:
        sub = ".".join(str(getattr(p, "key", getattr(p, "idx", p)))
                       for p in path)
        for s in range(n_stages):
            for l in range(lps):
                state[f"{_LAYER_PREFIX}{s * lps + l}.{sub}"] = arr[s, l]
    return state


def make_llama_pp_train_step(model: LlamaForCausalLM,
                             mesh: Optional[Mesh] = None,
                             n_micro: Optional[int] = None,
                             lr: float = 1e-4, weight_decay: float = 0.01,
                             grad_clip_norm: Optional[float] = 1.0,
                             schedule: Optional[str] = None, strategy=None,
                             vpp_degree: Optional[int] = None,
                             coop_head: Optional[bool] = None):
    """Build (step_fn, params, opt_state) where params =
    {"outer": ..., "stages": ...} and step_fn runs embed -> pp pipeline of
    decoder stages -> norm -> head -> CE loss -> AdamW, fully jitted.

    schedule (reference: pipeline_scheduler passes — FThenB/1F1B/VPP/ZBH1,
    distributed/passes/pipeline_scheduler_pass/):
      - "1F1B" (default): one-pass fwd+bwd schedule, loss inside the last
        stage, activations bounded at ~2*n_stages microbatch inputs
        (pipeline_spmd.pipeline_1f1b).
      - "FThenB": forward pipeline + autodiff (GPipe memory profile).
      - "VPP": interleaved virtual stages (`vpp_degree` chunks per rank,
        pipeline_spmd.pipeline_vpp_forward + autodiff) — the tick body
        dynamic-indexes ONE chunk, so interleaving pays control flow, not
        V× compute; pipeline bubble shrinks by 1/vpp_degree. Requires
        n_micro %% pp == 0 and layers %% (pp*vpp_degree) == 0.
      - "ZBH1": zero-bubble-style 1F1B — activation-grad-only ticks, all
        weight grads batched after the scan (pipeline_spmd.pipeline_zb1f1b
        documents the TPU-native cost model).
      - "Eager1F1B": 1F1B with a full tick of slack on every boundary
        exchange so XLA overlaps the collective-permute with compute, at
        the cost of more in-flight activations — the reference
        eager-1F1B's memory-for-overlap trade
        (pipeline_scheduler_pass/pipeline_eager_1f1b.py:31) in
        one-program form (pipeline_spmd.pipeline_eager_1f1b).

    coop_head (default: on for 1F1B/ZBH1 when vocab %% pp == 0): the final
    norm+LM-head+CE run COOPERATIVELY — every rank holds vocab/pp of the
    head weight and computes its shard's piece of the loss each tick
    (ParallelCrossEntropy math over the pp axis, reference:
    fleet/layers/mpu/mp_layers.py:742), so per-tick head FLOPs are 1/pp of
    a full head instead of the pp× a replicated per-rank head costs.

    `strategy`: a pipeline-scheduler pass output / Strategy whose
    `pipeline` section supplies schedule_mode and accumulate_steps
    (reference: distributed/passes/pipeline_scheduler_pass) — explicit
    `schedule`/`n_micro` arguments win over the strategy.
    """
    if strategy is not None:
        from ..parallel.trainer import _resolve_strategy

        pipe_cfg = _resolve_strategy(strategy)["pipeline"]
        if pipe_cfg.get("enable", True):
            if pipe_cfg.get("schedule_mode") and schedule is None:
                schedule = pipe_cfg["schedule_mode"]
            # accumulate_steps <= 1 is the pass's own default, not a
            # request for a degenerate one-microbatch pipeline
            if n_micro is None and int(
                    pipe_cfg.get("accumulate_steps") or 0) > 1:
                n_micro = int(pipe_cfg["accumulate_steps"])
            if pipe_cfg.get("vpp_degree") and vpp_degree is None:
                vpp_degree = int(pipe_cfg["vpp_degree"])
    if schedule is None:
        schedule = "1F1B"
    if schedule not in ("1F1B", "Eager1F1B", "FThenB", "VPP", "ZBH1"):
        raise ValueError(f"unknown pipeline schedule {schedule!r}")
    if vpp_degree is None:
        vpp_degree = 2
    mesh = mesh or mesh_mod.get_global_mesh()
    cfg = model.config
    n_stages = int(mesh.shape["pp"]) if (mesh is not None
                                         and "pp" in mesh.axis_names) else 1
    if coop_head:
        if schedule not in ("1F1B", "Eager1F1B", "ZBH1") or n_stages == 1:
            raise ValueError(
                "coop_head=True requires a 1F1B-family schedule with a "
                f"pp axis > 1 (got schedule={schedule!r}, pp={n_stages}); "
                "FThenB/VPP compute the head once per step outside the "
                "pipeline, so there is nothing to cooperate on")
        if cfg.vocab_size % n_stages != 0:
            raise ValueError(
                f"coop_head needs vocab_size ({cfg.vocab_size}) divisible "
                f"by the pp axis ({n_stages}) to shard the head")
    if schedule == "VPP" and n_stages > 1:
        outer, stacked = chunk_llama_state(
            dict(model.raw_state()), cfg.num_hidden_layers, n_stages,
            vpp_degree, mesh)
        lps = cfg.num_hidden_layers // (n_stages * vpp_degree)
    else:
        outer, stacked = split_llama_state(
            dict(model.raw_state()), cfg.num_hidden_layers, n_stages, mesh)
        lps = cfg.num_hidden_layers // n_stages
    params = {"outer": outer, "stages": stacked}
    opt_state = init_adamw_state(params)
    template = model.llama.layers[0]
    crit = LlamaPretrainingCriterion(cfg)
    if coop_head is None:
        coop_head = (schedule in ("1F1B", "Eager1F1B", "ZBH1")
                     and n_stages > 1
                     and cfg.vocab_size % n_stages == 0)

    def stage_fn(stage_params, h):
        s = h.shape[1]
        cos, sin = rope_freqs(s, cfg.head_dim, base=cfg.rope_theta)
        for i in range(lps):
            lp = jax.tree.map(lambda t, i=i: t[i], stage_params)
            with _tape.no_grad():
                # mesh=None: no explicit activation constraints inside the
                # manual-pp region (they would reference Auto-typed axes);
                # the weights' shardings still steer GSPMD on auto axes
                h = unwrap(template.func_call(lp, Tensor(h), cos, sin,
                                              mesh=None))
        return h

    def head_fn(hp, hidden, y_mb):
        """Final norm + LM head + shifted-CE for one microbatch — the last
        pipeline stage's tail (reference: shared embedding / LMHead stage
        in fleet pp_layers)."""
        from ..kernels.rms_norm import rms_norm as _k_rms

        with _tape.no_grad():
            hidden = _k_rms(hidden, hp["llama.norm.weight"],
                            cfg.rms_norm_eps)
            if cfg.tie_word_embeddings:
                logits = hidden @ hp["llama.embed_tokens.weight"].T
            else:
                logits = hidden @ hp["lm_head.weight"]
            loss = crit(Tensor(logits), Tensor(y_mb))
        return unwrap(loss).astype(jnp.float32)

    vocab_shard = cfg.vocab_size // n_stages if n_stages else cfg.vocab_size
    head_key = ("llama.embed_tokens.weight" if cfg.tie_word_embeddings
                else "lm_head.weight")

    def coop_head_fn(hp, hidden, y_mb):
        """Cooperative vocab-parallel head: this rank holds vocab/pp of
        the head weight; the shifted softmax-CE combines across the pp
        axis with pmax/psum — the ParallelCrossEntropy math
        (fleet/layers/mpu/mp_layers.py:742) laid over the pipeline axis,
        so per-tick head FLOPs are 1/pp of a full head."""
        from ..kernels.rms_norm import rms_norm as _k_rms

        h = _k_rms(hidden, hp["llama.norm.weight"], cfg.rms_norm_eps)
        w = hp[head_key]
        logits = h @ w.T if cfg.tie_word_embeddings else h @ w
        # labels arrive pre-shifted (LlamaPretrainingCriterion contract:
        # plain CE over every position)
        lg = logits.astype(jnp.float32)  # [mb, s, Vs]
        lb = y_mb
        sid = jax.lax.axis_index("pp")
        off = sid * vocab_shard
        # global max via all_gather (pmax has no autodiff rule; the max is
        # stop-gradient anyway — standard logsumexp stabilization)
        m = jax.lax.stop_gradient(jnp.max(
            jax.lax.all_gather(jnp.max(lg, axis=-1), "pp"), axis=0))
        se = jax.lax.psum(
            jnp.sum(jnp.exp(lg - m[..., None]), axis=-1), "pp")
        log_z = m + jnp.log(se)
        local = (lb >= off) & (lb < off + vocab_shard)
        idx = jnp.clip(lb - off, 0, vocab_shard - 1)
        corr = jnp.take_along_axis(lg, idx[..., None], axis=-1)[..., 0]
        corr = jax.lax.psum(jnp.where(local, corr, 0.0), "pp")
        return jnp.mean(log_z - corr).astype(jnp.float32)

    def embed(p, x):
        with _tape.no_grad():
            return unwrap(model.llama.embed_tokens.func_call(
                {"weight": p["outer"]["llama.embed_tokens.weight"]},
                Tensor(x)))

    def compute_loss(p, x, y):
        hidden = embed(p, x)
        if schedule == "VPP" and n_stages > 1:
            hidden = pipeline_vpp_forward(stage_fn, p["stages"], hidden,
                                          mesh=mesh, axis="pp",
                                          n_micro=n_micro)
        else:
            hidden = pipeline_forward(stage_fn, p["stages"], hidden,
                                      mesh=mesh, axis="pp", n_micro=n_micro)
        return head_fn(p["outer"], hidden, y)

    def loss_and_grads(p, x, y):
        if mesh is not None:
            x = jax.lax.with_sharding_constraint(
                x, batch_sharding(mesh, x.shape, (("dp", "sharding"),)))
        if schedule in ("FThenB", "VPP") or n_stages == 1:
            return jax.value_and_grad(compute_loss)(p, x, y)
        emb_w = p["outer"]["llama.embed_tokens.weight"]
        # the manual scatter-add below implements plain-gather embedding
        # semantics; a padding_idx would need its rows masked here
        assert getattr(model.llama.embed_tokens, "_padding_idx", None) \
            is None, "1F1B embed-grad closure assumes padding_idx=None"
        hidden = embed(p, x)
        # hand the pipeline only the params head_fn reads — every other
        # outer leaf would be carried (and psummed) as an f32 zero
        # accumulator through the whole scan
        head_keys = {"llama.norm.weight", head_key}
        head_p = {k: p["outer"][k] for k in head_keys}
        pipe = {"ZBH1": pipeline_zb1f1b,
                "Eager1F1B": pipeline_eager_1f1b}.get(schedule,
                                                      pipeline_1f1b)
        if coop_head:
            from jax.sharding import PartitionSpec as _P

            head_specs = {
                "llama.norm.weight": _P(),
                head_key: (_P("pp", None) if cfg.tie_word_embeddings
                           else _P(None, "pp")),
            }
            loss, d_st, d_head, d_hid = pipe(
                stage_fn, coop_head_fn, p["stages"], head_p, hidden, y,
                mesh=mesh, axis="pp", n_micro=n_micro,
                head_specs=head_specs)
        else:
            loss, d_st, d_head, d_hid = pipe(
                stage_fn, head_fn, p["stages"], head_p, hidden, y,
                mesh=mesh, axis="pp", n_micro=n_micro)
        # close the embedding lookup's gradient manually: d_emb[v] =
        # sum of d_hidden rows where input token == v (+ the tied-head
        # cotangent already present in d_head when tied)
        d_emb = jnp.zeros(emb_w.shape, jnp.float32).at[
            x.reshape(-1)].add(d_hid.reshape(-1, emb_w.shape[1]))
        d_outer = {k: jnp.zeros_like(v) for k, v in p["outer"].items()}
        d_outer.update(d_head)
        d_outer["llama.embed_tokens.weight"] = (
            d_outer["llama.embed_tokens.weight"]
            + d_emb.astype(emb_w.dtype))
        return loss, {"outer": d_outer, "stages": d_st}

    def step(p, s, x, y):
        loss, grads = loss_and_grads(p, x, y)
        new_p, new_s = adamw_update(
            p, grads, s, jnp.asarray(lr, jnp.float32),
            weight_decay=weight_decay, grad_clip_norm=grad_clip_norm)
        return loss, new_p, new_s

    jitted = jax.jit(step, donate_argnums=(0, 1))
    return jitted, params, opt_state
