"""Pipeline-parallel Llama training step.

Reference: fleet PipelineLayer + PipelineParallel.train_batch
(fleet/meta_parallel/parallel_layers/pp_layers.py:257 SegmentLayers —
partitioning decoder layers into stages — and pipeline_parallel.py 1F1B).

TPU-native: decoder layers are grouped into `pp` stages; per-stage parameter
pytrees are stacked with the stage dim sharded over the `pp` mesh axis and
the microbatch loop runs as scan+ppermute inside ONE jitted program
(parallel/pipeline_spmd.py). Embedding, final norm and the LM head run
outside the pipeline region (replicated over pp, still TP/FSDP-sharded over
the other axes) — the reference shares the embedding across first/last
stages similarly.
"""
from __future__ import annotations

from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from ..core.tensor import Tensor, unwrap
from ..core import tape as _tape
from ..kernels.rope import rope_freqs
from ..parallel import mesh as mesh_mod
from ..parallel.pipeline_spmd import (pipeline_1f1b, pipeline_forward,
                                      stack_stage_params)
from ..parallel.trainer import adamw_update, batch_sharding, \
    init_adamw_state
from .llama import LlamaConfig, LlamaForCausalLM, LlamaPretrainingCriterion

__all__ = ["make_llama_pp_train_step", "split_llama_state"]

_LAYER_PREFIX = "llama.layers."


def split_llama_state(state: Dict[str, jax.Array], n_layers: int,
                      n_stages: int, mesh: Optional[Mesh] = None):
    """Split a flat raw_state into (outer_params, stacked_stage_params).

    Layer params are grouped into n_stages contiguous blocks (reference:
    SegmentLayers uniform partition), stacked [n_stages, layers_per_stage,
    ...] with the stage dim sharded over `pp`."""
    if n_layers % n_stages:
        raise ValueError(f"{n_layers} layers not divisible into "
                         f"{n_stages} stages")
    per_layer = []
    outer = {}
    for k, v in state.items():
        if k.startswith(_LAYER_PREFIX):
            rest = k[len(_LAYER_PREFIX):]
            idx, sub = rest.split(".", 1)
            idx = int(idx)
            while len(per_layer) <= idx:
                per_layer.append({})
            per_layer[idx][sub] = v
        else:
            outer[k] = v
    lps = n_layers // n_stages
    per_stage = []
    for s in range(n_stages):
        block = per_layer[s * lps:(s + 1) * lps]
        per_stage.append(jax.tree.map(lambda *xs: jnp.stack(xs), *block))
    stacked = stack_stage_params(per_stage, mesh, axis="pp")
    return outer, stacked


def merge_llama_state(outer: Dict, stacked, n_layers: int) -> Dict:
    """Inverse of split_llama_state (for state_dict/checkpoint export)."""
    state = dict(outer)
    n_stages = jax.tree.leaves(stacked)[0].shape[0]
    lps = n_layers // n_stages
    flat = jax.tree.flatten_with_path(stacked)[0]
    for path, arr in flat:
        sub = ".".join(str(getattr(p, "key", getattr(p, "idx", p)))
                       for p in path)
        for s in range(n_stages):
            for l in range(lps):
                state[f"{_LAYER_PREFIX}{s * lps + l}.{sub}"] = arr[s, l]
    return state


def make_llama_pp_train_step(model: LlamaForCausalLM,
                             mesh: Optional[Mesh] = None,
                             n_micro: Optional[int] = None,
                             lr: float = 1e-4, weight_decay: float = 0.01,
                             grad_clip_norm: Optional[float] = 1.0,
                             schedule: Optional[str] = None, strategy=None):
    """Build (step_fn, params, opt_state) where params =
    {"outer": ..., "stages": ...} and step_fn runs embed -> pp pipeline of
    decoder stages -> norm -> head -> CE loss -> AdamW, fully jitted.

    schedule (reference: pipeline_scheduler passes):
      - "1F1B" (default): one-pass fwd+bwd schedule, loss inside the last
        stage, activations bounded at ~2*n_stages microbatch inputs
        (pipeline_spmd.pipeline_1f1b).
      - "FThenB": forward pipeline + autodiff (GPipe memory profile).
      - "VPP"/"ZBH1" are per-rank divergent schedules: in the
        single-program SPMD model every rank executes the same tick
        program, so interleaved virtual stages would pay V masked compute
        slots per tick — reserved until a multi-program executor exists.

    `strategy`: a pipeline-scheduler pass output / Strategy whose
    `pipeline` section supplies schedule_mode and accumulate_steps
    (reference: distributed/passes/pipeline_scheduler_pass) — explicit
    `schedule`/`n_micro` arguments win over the strategy.
    """
    if strategy is not None:
        from ..parallel.trainer import _resolve_strategy

        pipe_cfg = _resolve_strategy(strategy)["pipeline"]
        if pipe_cfg.get("enable", True):
            if pipe_cfg.get("schedule_mode") and schedule is None:
                schedule = pipe_cfg["schedule_mode"]
            # accumulate_steps <= 1 is the pass's own default, not a
            # request for a degenerate one-microbatch pipeline
            if n_micro is None and int(
                    pipe_cfg.get("accumulate_steps") or 0) > 1:
                n_micro = int(pipe_cfg["accumulate_steps"])
    if schedule is None:
        schedule = "1F1B"
    if schedule in ("VPP", "ZBH1"):
        raise NotImplementedError(
            f"{schedule} needs per-rank divergent tick programs; the "
            "single-program SPMD pipeline supports FThenB and 1F1B "
            "(pipeline_spmd.py) — 1F1B already bounds activations at "
            "O(n_stages)")
    if schedule not in ("1F1B", "FThenB"):
        raise ValueError(f"unknown pipeline schedule {schedule!r}")
    mesh = mesh or mesh_mod.get_global_mesh()
    cfg = model.config
    n_stages = int(mesh.shape["pp"]) if (mesh is not None
                                         and "pp" in mesh.axis_names) else 1
    outer, stacked = split_llama_state(dict(model.raw_state()),
                                       cfg.num_hidden_layers, n_stages, mesh)
    params = {"outer": outer, "stages": stacked}
    opt_state = init_adamw_state(params)
    template = model.llama.layers[0]
    crit = LlamaPretrainingCriterion(cfg)
    lps = cfg.num_hidden_layers // n_stages

    def stage_fn(stage_params, h):
        s = h.shape[1]
        cos, sin = rope_freqs(s, cfg.head_dim, base=cfg.rope_theta)
        for i in range(lps):
            lp = jax.tree.map(lambda t, i=i: t[i], stage_params)
            with _tape.no_grad():
                # mesh=None: no explicit activation constraints inside the
                # manual-pp region (they would reference Auto-typed axes);
                # the weights' shardings still steer GSPMD on auto axes
                h = unwrap(template.func_call(lp, Tensor(h), cos, sin,
                                              mesh=None))
        return h

    def head_fn(hp, hidden, y_mb):
        """Final norm + LM head + shifted-CE for one microbatch — the last
        pipeline stage's tail (reference: shared embedding / LMHead stage
        in fleet pp_layers)."""
        from ..kernels.rms_norm import rms_norm as _k_rms

        with _tape.no_grad():
            hidden = _k_rms(hidden, hp["llama.norm.weight"],
                            cfg.rms_norm_eps)
            if cfg.tie_word_embeddings:
                logits = hidden @ hp["llama.embed_tokens.weight"].T
            else:
                logits = hidden @ hp["lm_head.weight"]
            loss = crit(Tensor(logits), Tensor(y_mb))
        return unwrap(loss).astype(jnp.float32)

    def embed(p, x):
        with _tape.no_grad():
            return unwrap(model.llama.embed_tokens.func_call(
                {"weight": p["outer"]["llama.embed_tokens.weight"]},
                Tensor(x)))

    def compute_loss(p, x, y):
        hidden = embed(p, x)
        hidden = pipeline_forward(stage_fn, p["stages"], hidden,
                                  mesh=mesh, axis="pp", n_micro=n_micro)
        return head_fn(p["outer"], hidden, y)

    def loss_and_grads(p, x, y):
        if mesh is not None:
            x = jax.lax.with_sharding_constraint(
                x, batch_sharding(mesh, x.shape, (("dp", "sharding"),)))
        if schedule == "FThenB" or n_stages == 1:
            return jax.value_and_grad(compute_loss)(p, x, y)
        emb_w = p["outer"]["llama.embed_tokens.weight"]
        # the manual scatter-add below implements plain-gather embedding
        # semantics; a padding_idx would need its rows masked here
        assert getattr(model.llama.embed_tokens, "_padding_idx", None) \
            is None, "1F1B embed-grad closure assumes padding_idx=None"
        hidden = embed(p, x)
        # hand the pipeline only the params head_fn reads — every other
        # outer leaf would be carried (and psummed) as an f32 zero
        # accumulator through the whole scan
        head_keys = {"llama.norm.weight"}
        head_keys.add("llama.embed_tokens.weight"
                      if cfg.tie_word_embeddings else "lm_head.weight")
        head_p = {k: p["outer"][k] for k in head_keys}
        loss, d_st, d_head, d_hid = pipeline_1f1b(
            stage_fn, head_fn, p["stages"], head_p, hidden, y,
            mesh=mesh, axis="pp", n_micro=n_micro)
        # close the embedding lookup's gradient manually: d_emb[v] =
        # sum of d_hidden rows where input token == v (+ the tied-head
        # cotangent already present in d_head when tied)
        d_emb = jnp.zeros(emb_w.shape, jnp.float32).at[
            x.reshape(-1)].add(d_hid.reshape(-1, emb_w.shape[1]))
        d_outer = {k: jnp.zeros_like(v) for k, v in p["outer"].items()}
        d_outer.update(d_head)
        d_outer["llama.embed_tokens.weight"] = (
            d_outer["llama.embed_tokens.weight"]
            + d_emb.astype(emb_w.dtype))
        return loss, {"outer": d_outer, "stages": d_st}

    def step(p, s, x, y):
        loss, grads = loss_and_grads(p, x, y)
        new_p, new_s = adamw_update(
            p, grads, s, jnp.asarray(lr, jnp.float32),
            weight_decay=weight_decay, grad_clip_norm=grad_clip_norm)
        return loss, new_p, new_s

    jitted = jax.jit(step, donate_argnums=(0, 1))
    return jitted, params, opt_state
