"""Pipeline-parallel Llama training step.

Reference: fleet PipelineLayer + PipelineParallel.train_batch
(fleet/meta_parallel/parallel_layers/pp_layers.py:257 SegmentLayers —
partitioning decoder layers into stages — and pipeline_parallel.py 1F1B).

TPU-native: decoder layers are grouped into `pp` stages; per-stage parameter
pytrees are stacked with the stage dim sharded over the `pp` mesh axis and
the microbatch loop runs as scan+ppermute inside ONE jitted program
(parallel/pipeline_spmd.py). Embedding, final norm and the LM head run
outside the pipeline region (replicated over pp, still TP/FSDP-sharded over
the other axes) — the reference shares the embedding across first/last
stages similarly.
"""
from __future__ import annotations

from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from ..core.tensor import Tensor, unwrap
from ..core import tape as _tape
from ..kernels.rope import rope_freqs
from ..parallel import mesh as mesh_mod
from ..parallel.pipeline_spmd import pipeline_forward, stack_stage_params
from ..parallel.trainer import adamw_update, batch_sharding, \
    init_adamw_state
from .llama import LlamaConfig, LlamaForCausalLM, LlamaPretrainingCriterion

__all__ = ["make_llama_pp_train_step", "split_llama_state"]

_LAYER_PREFIX = "llama.layers."


def split_llama_state(state: Dict[str, jax.Array], n_layers: int,
                      n_stages: int, mesh: Optional[Mesh] = None):
    """Split a flat raw_state into (outer_params, stacked_stage_params).

    Layer params are grouped into n_stages contiguous blocks (reference:
    SegmentLayers uniform partition), stacked [n_stages, layers_per_stage,
    ...] with the stage dim sharded over `pp`."""
    if n_layers % n_stages:
        raise ValueError(f"{n_layers} layers not divisible into "
                         f"{n_stages} stages")
    per_layer = []
    outer = {}
    for k, v in state.items():
        if k.startswith(_LAYER_PREFIX):
            rest = k[len(_LAYER_PREFIX):]
            idx, sub = rest.split(".", 1)
            idx = int(idx)
            while len(per_layer) <= idx:
                per_layer.append({})
            per_layer[idx][sub] = v
        else:
            outer[k] = v
    lps = n_layers // n_stages
    per_stage = []
    for s in range(n_stages):
        block = per_layer[s * lps:(s + 1) * lps]
        per_stage.append(jax.tree.map(lambda *xs: jnp.stack(xs), *block))
    stacked = stack_stage_params(per_stage, mesh, axis="pp")
    return outer, stacked


def merge_llama_state(outer: Dict, stacked, n_layers: int) -> Dict:
    """Inverse of split_llama_state (for state_dict/checkpoint export)."""
    state = dict(outer)
    n_stages = jax.tree.leaves(stacked)[0].shape[0]
    lps = n_layers // n_stages
    flat = jax.tree.flatten_with_path(stacked)[0]
    for path, arr in flat:
        sub = ".".join(str(getattr(p, "key", getattr(p, "idx", p)))
                       for p in path)
        for s in range(n_stages):
            for l in range(lps):
                state[f"{_LAYER_PREFIX}{s * lps + l}.{sub}"] = arr[s, l]
    return state


def make_llama_pp_train_step(model: LlamaForCausalLM,
                             mesh: Optional[Mesh] = None,
                             n_micro: Optional[int] = None,
                             lr: float = 1e-4, weight_decay: float = 0.01,
                             grad_clip_norm: Optional[float] = 1.0):
    """Build (step_fn, params, opt_state) where params =
    {"outer": ..., "stages": ...} and step_fn runs embed -> pp pipeline of
    decoder stages -> norm -> head -> CE loss -> AdamW, fully jitted."""
    mesh = mesh or mesh_mod.get_global_mesh()
    cfg = model.config
    n_stages = int(mesh.shape["pp"]) if (mesh is not None
                                         and "pp" in mesh.axis_names) else 1
    outer, stacked = split_llama_state(dict(model.raw_state()),
                                       cfg.num_hidden_layers, n_stages, mesh)
    params = {"outer": outer, "stages": stacked}
    opt_state = init_adamw_state(params)
    template = model.llama.layers[0]
    crit = LlamaPretrainingCriterion(cfg)
    lps = cfg.num_hidden_layers // n_stages

    def stage_fn(stage_params, h):
        s = h.shape[1]
        cos, sin = rope_freqs(s, cfg.head_dim, base=cfg.rope_theta)
        for i in range(lps):
            lp = jax.tree.map(lambda t, i=i: t[i], stage_params)
            with _tape.no_grad():
                # mesh=None: no explicit activation constraints inside the
                # manual-pp region (they would reference Auto-typed axes);
                # the weights' shardings still steer GSPMD on auto axes
                h = unwrap(template.func_call(lp, Tensor(h), cos, sin,
                                              mesh=None))
        return h

    def compute_loss(p, x, y):
        if mesh is not None:
            x = jax.lax.with_sharding_constraint(
                x, batch_sharding(mesh, x.shape, (("dp", "sharding"),)))
        with _tape.no_grad():
            hidden = unwrap(model.llama.embed_tokens.func_call(
                {"weight": p["outer"]["llama.embed_tokens.weight"]},
                Tensor(x)))
        hidden = pipeline_forward(stage_fn, p["stages"], hidden,
                                  mesh=mesh, axis="pp", n_micro=n_micro)
        with _tape.no_grad():
            from ..kernels.rms_norm import rms_norm as _k_rms

            hidden = _k_rms(hidden, p["outer"]["llama.norm.weight"],
                            cfg.rms_norm_eps)
            if cfg.tie_word_embeddings:
                logits = hidden @ p["outer"][
                    "llama.embed_tokens.weight"].T
            else:
                logits = hidden @ p["outer"]["lm_head.weight"]
            loss = crit(Tensor(logits), Tensor(y))
        return unwrap(loss).astype(jnp.float32)

    def step(p, s, x, y):
        loss, grads = jax.value_and_grad(compute_loss)(p, x, y)
        new_p, new_s = adamw_update(
            p, grads, s, jnp.asarray(lr, jnp.float32),
            weight_decay=weight_decay, grad_clip_norm=grad_clip_norm)
        return loss, new_p, new_s

    jitted = jax.jit(step, donate_argnums=(0, 1))
    return jitted, params, opt_state
