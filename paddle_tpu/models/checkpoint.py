"""Streaming checkpoint -> quantized serving layout.

The 7B-on-16GB bootstrap for REAL weights (round-4 VERDICT #5): a
Llama-2-7B bf16 state dict is 13.5 GB — materializing it on host or
device before quantizing defeats the point of weight-only serving. This
converter reads one tensor at a time (safetensors are lazily sliceable,
HF sharded-index layouts included), quantizes it on device, and frees
the fp copy before touching the next — peak transient is ONE fp weight.

Reference analog: python/paddle/framework/io.py:740 (paddle.load) +
the weight-only conversion feeding
python/paddle/nn/quant/quantized_linear.py:180 (weight_only_linear).
"""
from __future__ import annotations

import difflib
import json
import os
from typing import Callable, Optional, Union

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor, unwrap
from ..resilience import chaos
from ..resilience.retry import RetryPolicy, default_io_policy


def _nearest(name: str, candidates, n: int = 3) -> str:
    close = difflib.get_close_matches(name, list(candidates), n=n,
                                      cutoff=0.4)
    return f"; nearest keys: {close}" if close else ""


def _hf_name(our_name: str) -> str:
    """Our `_decode_params` key -> HF Llama checkpoint key."""
    if our_name.startswith("llama."):
        return "model." + our_name[len("llama."):]
    return our_name


def _needs_transpose(name: str, arr) -> bool:
    """HF torch nn.Linear stores [out, in]; our Linear stores [in, out].
    Embeddings are [vocab, h] in both."""
    return arr.ndim == 2 and "embed_tokens" not in name


class _SafetensorsSource:
    """name -> np.ndarray over a safetensors file or an HF sharded dir.
    Tensors are read one at a time; nothing else is resident. Shard
    reads retry transient IOErrors through `retry` (default: the shared
    io policy, FLAGS_io_retry_attempts attempts)."""

    def __init__(self, path: str, retry: Optional[RetryPolicy] = None):
        from safetensors import safe_open

        self._safe_open = safe_open
        self._path = path
        self._retry = retry if retry is not None else default_io_policy()
        self._by_file = {}
        if os.path.isdir(path):
            idx = os.path.join(path, "model.safetensors.index.json")
            if os.path.exists(idx):
                with open(idx) as f:
                    weight_map = json.load(f)["weight_map"]
                for name, fname in weight_map.items():
                    self._by_file[name] = os.path.join(path, fname)
            else:
                files = sorted(f for f in os.listdir(path)
                               if f.endswith(".safetensors"))
                if not files:
                    raise FileNotFoundError(
                        f"no .safetensors files under {path}")
                for fname in files:
                    full = os.path.join(path, fname)
                    with safe_open(full, framework="pt") as sf:
                        for name in sf.keys():
                            self._by_file[name] = full
        else:
            with safe_open(path, framework="pt") as sf:
                for name in sf.keys():
                    self._by_file[name] = path

    def __contains__(self, name: str) -> bool:
        return name in self._by_file

    def __call__(self, name: str) -> np.ndarray:
        if name not in self._by_file:
            shards = sorted(set(self._by_file.values()))
            raise KeyError(
                f"tensor {name!r} not found in checkpoint {self._path!r} "
                f"({len(self._by_file)} tensors across "
                f"{len(shards)} shard file(s): "
                f"{[os.path.basename(s) for s in shards[:4]]}"
                f"{'...' if len(shards) > 4 else ''})"
                f"{_nearest(name, self._by_file)}")
        return self._retry.call(self._read, name)

    def _read(self, name: str) -> np.ndarray:
        # framework="pt" so bf16/fp16 checkpoints load (numpy has no
        # native bf16). The tensor ships at its STORED width — bf16
        # reinterpreted through ml_dtypes — and upcasts to fp32 on
        # device: host->device transfer is the bottleneck (tunneled
        # chips especially), and bf16->fp32 is exact, so shipping fp32
        # would double the bytes for nothing.
        import torch

        chaos.maybe_io_error("shard_read")
        with self._safe_open(self._by_file[name], framework="pt") as sf:
            t = sf.get_tensor(name)
        if t.dtype == torch.bfloat16:
            import ml_dtypes

            return t.view(torch.uint16).numpy().view(ml_dtypes.bfloat16)
        return t.numpy()


def load_quant_serving_params(cfg, source: Union[str, dict, Callable],
                              quant: Optional[str],
                              dtype=jnp.bfloat16,
                              names: str = "auto"):
    """Stream a checkpoint into the `_decode_params` serving layout.

    cfg: LlamaConfig of the checkpoint.
    source: a path to a .safetensors file / HF checkpoint dir, a
        name->array dict (e.g. the output of paddle.load), or a callable
        name->array for custom readers. Dict/callable use OUR names and
        layout ([in, out] projections); safetensors paths use HF names
        and torch layout (transposed on read).
    quant: None (dense bf16 serving), "weight_only_int8" or
        "weight_only_int4" — projection + head weights quantize ON
        DEVICE the moment they land; the fp copy is freed before the
        next tensor is read.
    names: "auto" (HF names for paths, ours otherwise), "hf", or "ours".

    Returns the dec_params dict build_quant_generate /
    build_paged_generate / serving.ContinuousBatchingEngine consume.
    """
    from ..nn.quant import weight_quantize

    if quant not in (None, "weight_only_int8", "weight_only_int4"):
        raise ValueError(f"unsupported quant {quant!r}")
    if isinstance(source, str):
        reader = _SafetensorsSource(source)
        hf_names = names in ("auto", "hf")
    elif isinstance(source, dict):
        reader = source.__getitem__
        hf_names = names == "hf"
    else:
        reader = source
        hf_names = names == "hf"

    def fetch(our_name, transpose_ok=True):
        key = _hf_name(our_name) if hf_names else our_name
        try:
            arr = np.asarray(reader(key))
        except KeyError as e:
            if isinstance(source, (str, _SafetensorsSource)):
                raise  # _SafetensorsSource already raised descriptively
            known = source.keys() if isinstance(source, dict) else ()
            raise KeyError(
                f"tensor {key!r} (for param {our_name!r}) not found in "
                f"the {type(source).__name__} checkpoint source"
                f"{_nearest(key, known)}") from e
        if hf_names and transpose_ok and _needs_transpose(key, arr):
            arr = arr.T
        return arr

    def quantized(our_name):
        # transfer at stored width, upcast to fp32 ON DEVICE (exact for
        # bf16/fp16 sources)
        w = jnp.asarray(fetch(our_name)).astype(jnp.float32)
        if quant is None:
            return w.astype(dtype)
        wq, sc = weight_quantize(Tensor(w), algo=quant)
        out = (unwrap(wq), unwrap(sc))
        del w  # the fp device copy dies here, before the next read
        return out

    p = {"llama.embed_tokens.weight":
         jnp.asarray(fetch("llama.embed_tokens.weight")).astype(dtype)}
    for i in range(cfg.num_hidden_layers):
        pre = f"llama.layers.{i}."
        for nm in ("input_layernorm.weight",
                   "post_attention_layernorm.weight"):
            p[pre + nm] = jnp.asarray(fetch(pre + nm)).astype(dtype)
        for nm in ("self_attn.q_proj.weight", "self_attn.k_proj.weight",
                   "self_attn.v_proj.weight", "self_attn.o_proj.weight",
                   "mlp.gate_proj.weight", "mlp.up_proj.weight",
                   "mlp.down_proj.weight"):
            p[pre + nm] = quantized(pre + nm)
    p["llama.norm.weight"] = jnp.asarray(
        fetch("llama.norm.weight")).astype(dtype)
    if not cfg.tie_word_embeddings:
        p["lm_head.weight"] = quantized("lm_head.weight")
    return p
