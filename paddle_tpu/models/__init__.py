"""Model zoo.

Reference scope: the reference frameworks' flagship model families live in
PaddleNLP/PaddleClas etc., but the in-repo anchor is the auto-parallel Llama
decoder used by its hybrid-strategy tests
(test/auto_parallel/hybrid_strategy/semi_auto_parallel_llama_model.py).
Here the zoo is first-class: Llama is the flagship for benchmarks.
"""
from .llama import (  # noqa: F401
    LlamaConfig, LlamaForCausalLM, LlamaModel, LlamaPretrainingCriterion,
    PagedKVManager, build_paged_generate, build_quant_generate,
    hash_prefix_blocks, init_quant_serving_params, llama_sharding_rules,
    quantize_kv_pages, resolve_decode_megakernel, resolve_kv_cache_dtype,
    serving_block_size_candidates, shard_llama,
)
from .checkpoint import load_quant_serving_params  # noqa: F401
from .gpt import GPTConfig, GPTForCausalLM, GPTModel, shard_gpt  # noqa: F401
from .unet import UNet2DConditionModel, UNetConfig  # noqa: F401
from .bert import (  # noqa: F401
    BertConfig, BertForMaskedLM, BertForSequenceClassification, BertModel,
    ErnieConfig, ErnieForMaskedLM, ErnieForSequenceClassification,
    ErnieModel,
)
