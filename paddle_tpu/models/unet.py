"""Stable-Diffusion-class conditional UNet (BASELINE config 5).

Reference anchor: the reference's diffusion stack lives in PaddleMIX/ppdiffusers
(UNet2DConditionModel); the in-repo hooks are the fused attention op family
it rides (memory_efficient_attention, ops.yaml). Architecture follows the
public SD-1.5 topology: ResBlocks with timestep injection + spatial
transformers (self-attn over HW tokens, cross-attn to text context, GEGLU
ff) at the lower resolutions.

TPU-first: attention flattens NCHW -> [B, HW, heads, dim] and rides the
flash kernel path (cross-attention uses sq != sk); convs are NCHW XLA convs
on the MXU; GroupNorm in fp32.
"""
from __future__ import annotations

import dataclasses
import math
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor, dispatch, unwrap
from .. import nn
from ..nn import functional as F


@dataclasses.dataclass
class UNetConfig:
    in_channels: int = 4
    out_channels: int = 4
    block_out_channels: Tuple[int, ...] = (320, 640, 1280, 1280)
    layers_per_block: int = 2
    attention_resolutions: Tuple[int, ...] = (0, 1, 2)  # level indices
    num_attention_heads: int = 8
    cross_attention_dim: int = 768
    norm_num_groups: int = 32
    dtype: str = "float32"

    @staticmethod
    def sd15(**over):
        return UNetConfig(**over)

    @staticmethod
    def tiny(**over):
        return UNetConfig(block_out_channels=(32, 64), layers_per_block=1,
                          attention_resolutions=(1,), num_attention_heads=2,
                          cross_attention_dim=32, norm_num_groups=8, **over)


def timestep_embedding(t, dim: int, max_period: float = 10000.0):
    """Sinusoidal timestep embedding (public DDPM formulation)."""
    half = dim // 2
    freqs = jnp.exp(-math.log(max_period)
                    * jnp.arange(half, dtype=jnp.float32) / half)
    args = jnp.asarray(t, jnp.float32)[:, None] * freqs[None]
    return jnp.concatenate([jnp.cos(args), jnp.sin(args)], axis=-1)


class ResBlock(nn.Layer):
    def __init__(self, in_ch, out_ch, temb_ch, groups):
        super().__init__()
        self.norm1 = nn.GroupNorm(groups, in_ch)
        self.conv1 = nn.Conv2D(in_ch, out_ch, 3, padding=1)
        self.time_emb_proj = nn.Linear(temb_ch, out_ch)
        self.norm2 = nn.GroupNorm(groups, out_ch)
        self.conv2 = nn.Conv2D(out_ch, out_ch, 3, padding=1)
        self.skip = (nn.Conv2D(in_ch, out_ch, 1) if in_ch != out_ch
                     else nn.Identity())

    def forward(self, x, temb):
        h = self.conv1(F.silu(self.norm1(x)))
        t = self.time_emb_proj(F.silu(temb))
        h = h + t.reshape([t.shape[0], -1, 1, 1])
        h = self.conv2(F.silu(self.norm2(h)))
        return self.skip(x) + h


class CrossAttention(nn.Layer):
    def __init__(self, query_dim, context_dim, heads, dim_head):
        super().__init__()
        inner = heads * dim_head
        self.heads = heads
        self.dim_head = dim_head
        self.to_q = nn.Linear(query_dim, inner, bias_attr=False)
        self.to_k = nn.Linear(context_dim, inner, bias_attr=False)
        self.to_v = nn.Linear(context_dim, inner, bias_attr=False)
        self.to_out = nn.Linear(inner, query_dim)

    def forward(self, x, context=None):
        ctx = x if context is None else context
        b, sq, _ = x.shape
        sk = ctx.shape[1]
        q = self.to_q(x).reshape([b, sq, self.heads, self.dim_head])
        k = self.to_k(ctx).reshape([b, sk, self.heads, self.dim_head])
        v = self.to_v(ctx).reshape([b, sk, self.heads, self.dim_head])
        out, _ = F.flash_attention(q, k, v, causal=False)
        return self.to_out(out.reshape([b, sq, self.heads * self.dim_head]))


class GEGLU(nn.Layer):
    def __init__(self, dim_in, dim_out):
        super().__init__()
        self.proj = nn.Linear(dim_in, dim_out * 2)

    def forward(self, x):
        h = self.proj(x)
        a, g = h.chunk(2, axis=-1)
        return a * F.gelu(g)


class TransformerBlock(nn.Layer):
    def __init__(self, dim, context_dim, heads, dim_head):
        super().__init__()
        self.norm1 = nn.LayerNorm(dim)
        self.attn1 = CrossAttention(dim, dim, heads, dim_head)       # self
        self.norm2 = nn.LayerNorm(dim)
        self.attn2 = CrossAttention(dim, context_dim, heads, dim_head)
        self.norm3 = nn.LayerNorm(dim)
        self.ff = nn.Sequential(GEGLU(dim, dim * 4),
                                nn.Linear(dim * 4, dim))

    def forward(self, x, context):
        x = x + self.attn1(self.norm1(x))
        x = x + self.attn2(self.norm2(x), context)
        return x + self.ff(self.norm3(x))


class SpatialTransformer(nn.Layer):
    """NCHW -> tokens -> transformer block -> NCHW (SD topology)."""

    def __init__(self, channels, context_dim, heads, groups):
        super().__init__()
        self.norm = nn.GroupNorm(groups, channels)
        self.proj_in = nn.Conv2D(channels, channels, 1)
        self.block = TransformerBlock(channels, context_dim, heads,
                                      channels // heads)
        self.proj_out = nn.Conv2D(channels, channels, 1)

    def forward(self, x, context):
        b, c, h, w = x.shape
        res = x
        y = self.proj_in(self.norm(x))
        tokens = y.reshape([b, c, h * w]).transpose([0, 2, 1])
        tokens = self.block(tokens, context)
        y = tokens.transpose([0, 2, 1]).reshape([b, c, h, w])
        return res + self.proj_out(y)


class Downsample(nn.Layer):
    def __init__(self, ch):
        super().__init__()
        self.op = nn.Conv2D(ch, ch, 3, stride=2, padding=1)

    def forward(self, x):
        return self.op(x)


class Upsample(nn.Layer):
    def __init__(self, ch):
        super().__init__()
        self.conv = nn.Conv2D(ch, ch, 3, padding=1)

    def forward(self, x):
        y = F.interpolate(x, scale_factor=2, mode="nearest")
        return self.conv(y)


class UNet2DConditionModel(nn.Layer):
    """SD-1.5-class UNet: (latents [B,4,H,W], t [B], context [B,77,768])
    -> noise prediction [B,4,H,W]."""

    def __init__(self, config: Optional[UNetConfig] = None, **over):
        super().__init__()
        config = config or UNetConfig(**over)
        self.config = config
        chs = config.block_out_channels
        temb_ch = chs[0] * 4
        g = config.norm_num_groups
        self.time_embed = nn.Sequential(nn.Linear(chs[0], temb_ch),
                                        nn.Silu(),
                                        nn.Linear(temb_ch, temb_ch))
        self.conv_in = nn.Conv2D(config.in_channels, chs[0], 3, padding=1)

        from ..nn.layer.container import LayerList

        self.down_blocks = LayerList()
        self.down_attns = LayerList()
        self.downsamples = LayerList()
        skip_chs = [chs[0]]
        ch = chs[0]
        for level, out_ch in enumerate(chs):
            for _ in range(config.layers_per_block):
                self.down_blocks.append(ResBlock(ch, out_ch, temb_ch, g))
                ch = out_ch
                self.down_attns.append(
                    SpatialTransformer(ch, config.cross_attention_dim,
                                       config.num_attention_heads, g)
                    if level in config.attention_resolutions
                    else nn.Identity())
                skip_chs.append(ch)
            if level != len(chs) - 1:
                self.downsamples.append(Downsample(ch))
                skip_chs.append(ch)
            else:
                self.downsamples.append(nn.Identity())

        self.mid_block1 = ResBlock(ch, ch, temb_ch, g)
        self.mid_attn = SpatialTransformer(ch, config.cross_attention_dim,
                                           config.num_attention_heads, g)
        self.mid_block2 = ResBlock(ch, ch, temb_ch, g)

        self.up_blocks = LayerList()
        self.up_attns = LayerList()
        self.upsamples = LayerList()
        for level, out_ch in reversed(list(enumerate(chs))):
            for _ in range(config.layers_per_block + 1):
                self.up_blocks.append(
                    ResBlock(ch + skip_chs.pop(), out_ch, temb_ch, g))
                ch = out_ch
                self.up_attns.append(
                    SpatialTransformer(ch, config.cross_attention_dim,
                                       config.num_attention_heads, g)
                    if level in config.attention_resolutions
                    else nn.Identity())
            if level != 0:
                self.upsamples.append(Upsample(ch))
            else:
                self.upsamples.append(nn.Identity())

        self.norm_out = nn.GroupNorm(g, ch)
        self.conv_out = nn.Conv2D(ch, config.out_channels, 3, padding=1)
        if config.dtype != "float32":
            self.to(dtype=config.dtype)

    def forward(self, sample, timesteps, encoder_hidden_states):
        cfg = self.config
        temb_raw = dispatch(
            "timestep_embedding",
            lambda t: timestep_embedding(t, cfg.block_out_channels[0]),
            (timesteps,))
        if self._dtype != jnp.float32:
            temb_raw = Tensor(unwrap(temb_raw).astype(self._dtype))
        temb = self.time_embed(temb_raw)

        h = self.conv_in(sample)
        skips = [h]
        i = 0
        for level in range(len(cfg.block_out_channels)):
            for _ in range(cfg.layers_per_block):
                h = self.down_blocks[i](h, temb)
                h = self._apply_attn(self.down_attns[i], h,
                                     encoder_hidden_states)
                skips.append(h)
                i += 1
            h = self.downsamples[level](h)
            if level != len(cfg.block_out_channels) - 1:
                skips.append(h)

        h = self.mid_block1(h, temb)
        h = self.mid_attn(h, encoder_hidden_states)
        h = self.mid_block2(h, temb)

        i = 0
        for level in reversed(range(len(cfg.block_out_channels))):
            for _ in range(cfg.layers_per_block + 1):
                from ..ops import manipulation as manip

                h = manip.concat([h, skips.pop()], axis=1)
                h = self.up_blocks[i](h, temb)
                h = self._apply_attn(self.up_attns[i], h,
                                     encoder_hidden_states)
                i += 1
            h = self.upsamples[len(cfg.block_out_channels) - 1 - level](h)

        return self.conv_out(F.silu(self.norm_out(h)))

    @staticmethod
    def _apply_attn(attn, h, context):
        if isinstance(attn, SpatialTransformer):
            return attn(h, context)
        return attn(h)
