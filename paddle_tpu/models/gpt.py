"""GPT-2-class decoder LM (reference anchor: the reference's fleet tests use
GPT models for hybrid parallel, e.g. test/collective/fleet/
hybrid_parallel_*; PaddleNLP gpt modeling is the upstream surface).

Learned positional embeddings + pre-LN blocks; attention rides the same
Pallas flash kernel as Llama. TP/FSDP via the shared logical-axis rules.
"""
from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..core.tensor import Tensor, dispatch, unwrap
from ..nn import functional as F
from ..nn.layer.layers import Layer
from ..nn.layer.common import Dropout, Embedding, Linear
from ..nn.layer.norm import LayerNorm
from ..parallel import mesh as mesh_mod
from .llama import _constrain, BATCH_AXES, MP_AXIS, SEQ_AXIS


@dataclasses.dataclass
class GPTConfig:
    vocab_size: int = 50304
    hidden_size: int = 768
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    intermediate_size: Optional[int] = None
    max_position_embeddings: int = 1024
    layer_norm_epsilon: float = 1e-5
    dropout: float = 0.0
    tie_word_embeddings: bool = True
    dtype: str = "float32"

    def __post_init__(self):
        if self.intermediate_size is None:
            self.intermediate_size = 4 * self.hidden_size

    @staticmethod
    def tiny(**over):
        return GPTConfig(vocab_size=128, hidden_size=64, num_hidden_layers=2,
                         num_attention_heads=4, max_position_embeddings=64,
                         **over)


class GPTAttention(Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        h = config.hidden_size
        self.num_heads = config.num_attention_heads
        self.head_dim = h // self.num_heads
        self.qkv_proj = Linear(h, 3 * h)
        self.out_proj = Linear(h, h)
        self.dropout = Dropout(config.dropout)

    def forward(self, x, mesh=None):
        b, s, h = x.shape
        qkv = self.qkv_proj(x).reshape([b, s, 3, self.num_heads,
                                        self.head_dim])
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        q = _constrain(q, mesh, BATCH_AXES, None, MP_AXIS, None)
        out, _ = F.flash_attention(q, k, v, causal=True)
        out = out.reshape([b, s, h])
        return self.dropout(self.out_proj(out))


class GPTBlock(Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        self.ln_1 = LayerNorm(config.hidden_size,
                              epsilon=config.layer_norm_epsilon)
        self.attn = GPTAttention(config)
        self.ln_2 = LayerNorm(config.hidden_size,
                              epsilon=config.layer_norm_epsilon)
        self.fc_in = Linear(config.hidden_size, config.intermediate_size)
        self.fc_out = Linear(config.intermediate_size, config.hidden_size)
        self.dropout = Dropout(config.dropout)

    def forward(self, x, mesh=None):
        x = x + self.attn(self.ln_1(x), mesh=mesh)
        # GPT-2 family convention: tanh-approximate GELU (HF gelu_new)
        m = self.fc_out(F.gelu(self.fc_in(self.ln_2(x)), approximate=True))
        x = x + self.dropout(m)
        return _constrain(x, mesh, BATCH_AXES, SEQ_AXIS, None)


class GPTModel(Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        self.config = config
        self.wte = Embedding(config.vocab_size, config.hidden_size)
        self.wpe = Embedding(config.max_position_embeddings,
                             config.hidden_size)
        from ..nn.layer.container import LayerList

        self.h = LayerList([GPTBlock(config)
                            for _ in range(config.num_hidden_layers)])
        self.ln_f = LayerNorm(config.hidden_size,
                              epsilon=config.layer_norm_epsilon)
        if config.dtype != "float32":
            self.to(dtype=config.dtype)

    def forward(self, input_ids, position_ids=None):
        mesh = mesh_mod.get_global_mesh()
        s = input_ids.shape[1]
        if position_ids is None:
            position_ids = Tensor(jnp.arange(s)[None])
        x = self.wte(input_ids) + self.wpe(position_ids)
        x = _constrain(x, mesh, BATCH_AXES, SEQ_AXIS, None)
        for block in self.h:
            x = block(x, mesh=mesh)
        return self.ln_f(x)


class GPTForCausalLM(Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        self.config = config
        self.gpt = GPTModel(config)
        if not config.tie_word_embeddings:
            self.lm_head = Linear(config.hidden_size, config.vocab_size,
                                  bias_attr=False)

    def forward(self, input_ids, position_ids=None):
        hidden = self.gpt(input_ids, position_ids)
        if self.config.tie_word_embeddings:
            w = self.gpt.wte.weight
            return dispatch("tied_lm_head",
                            lambda h, e: jnp.matmul(h, e.T), (hidden, w))
        return self.lm_head(hidden)


def shard_gpt(model: Layer, mesh: Optional[Mesh] = None) -> Layer:
    """TP over mp (qkv/fc_in column, out_proj/fc_out row), FSDP over
    sharding — same recipe as shard_llama."""
    mesh = mesh or mesh_mod.get_global_mesh()
    if mesh is None:
        return model
    rules = [
        ("wte.weight", (MP_AXIS, "sharding")),
        ("wpe.weight", (None, "sharding")),
        ("qkv_proj.weight", ("sharding", MP_AXIS)),
        ("fc_in.weight", ("sharding", MP_AXIS)),
        ("out_proj.weight", (MP_AXIS, "sharding")),
        ("fc_out.weight", (MP_AXIS, "sharding")),
        ("lm_head.weight", ("sharding", MP_AXIS)),
    ]
    for name, p in model.named_parameters():
        spec = [None] * p.ndim
        for suffix, dims in rules:
            if name.endswith(suffix):
                for i in range(p.ndim):
                    d = dims[i] if i < len(dims) else None
                    if d is not None and d in mesh.axis_names \
                            and p.shape[i] % int(mesh.shape[d]) == 0:
                        spec[i] = d
                break
        sh = NamedSharding(mesh, P(*spec))
        p._array = jax.device_put(p._array, sh)
    return model
