"""BERT/ERNIE-class bidirectional encoder (reference anchor: ERNIE-3.0 is
BASELINE config 2; the reference's in-repo encoder surface is
paddle.nn.TransformerEncoder, PaddleNLP ernie modeling upstream).

Pre-computed token+position+segment embeddings -> post-LN transformer
encoder -> pooler; heads for masked-LM pretraining and sequence
classification (the finetune benchmark path).
"""
from __future__ import annotations

import dataclasses
from typing import Optional

import jax.numpy as jnp

from ..core.tensor import Tensor, dispatch
from ..nn import functional as F
from ..nn.layer.layers import Layer
from ..nn.layer.common import Dropout, Embedding, Linear
from ..nn.layer.norm import LayerNorm
from ..nn.layer.activation import Tanh
from ..parallel import mesh as mesh_mod
from .llama import _constrain, BATCH_AXES, MP_AXIS


@dataclasses.dataclass
class BertConfig:
    vocab_size: int = 30522
    hidden_size: int = 768
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    intermediate_size: int = 3072
    hidden_act: str = "gelu"
    hidden_dropout_prob: float = 0.1
    attention_probs_dropout_prob: float = 0.1
    max_position_embeddings: int = 512
    type_vocab_size: int = 2
    layer_norm_eps: float = 1e-12
    num_labels: int = 2
    dtype: str = "float32"

    @staticmethod
    def tiny(**over):
        return BertConfig(vocab_size=128, hidden_size=64,
                          num_hidden_layers=2, num_attention_heads=4,
                          intermediate_size=128,
                          max_position_embeddings=64, **over)

    @staticmethod
    def ernie3_base(**over):
        return BertConfig(vocab_size=40000, hidden_size=768,
                          num_hidden_layers=12, num_attention_heads=12,
                          intermediate_size=3072, **over)


class BertEmbeddings(Layer):
    def __init__(self, config: BertConfig):
        super().__init__()
        self.word_embeddings = Embedding(config.vocab_size,
                                         config.hidden_size)
        self.position_embeddings = Embedding(config.max_position_embeddings,
                                             config.hidden_size)
        self.token_type_embeddings = Embedding(config.type_vocab_size,
                                               config.hidden_size)
        self.layer_norm = LayerNorm(config.hidden_size,
                                    epsilon=config.layer_norm_eps)
        self.dropout = Dropout(config.hidden_dropout_prob)

    def forward(self, input_ids, token_type_ids=None, position_ids=None):
        s = input_ids.shape[1]
        if position_ids is None:
            position_ids = Tensor(jnp.arange(s)[None])
        if token_type_ids is None:
            token_type_ids = Tensor(jnp.zeros_like(
                input_ids._array if isinstance(input_ids, Tensor)
                else input_ids))
        x = (self.word_embeddings(input_ids)
             + self.position_embeddings(position_ids)
             + self.token_type_embeddings(token_type_ids))
        return self.dropout(self.layer_norm(x))


class BertSelfAttention(Layer):
    def __init__(self, config: BertConfig):
        super().__init__()
        h = config.hidden_size
        self.num_heads = config.num_attention_heads
        self.head_dim = h // self.num_heads
        self.query = Linear(h, h)
        self.key = Linear(h, h)
        self.value = Linear(h, h)
        self.out = Linear(h, h)
        self.dropout = Dropout(config.attention_probs_dropout_prob)

    def forward(self, x, attention_mask=None, mesh=None):
        b, s, h = x.shape
        q = self.query(x).reshape([b, s, self.num_heads, self.head_dim])
        k = self.key(x).reshape([b, s, self.num_heads, self.head_dim])
        v = self.value(x).reshape([b, s, self.num_heads, self.head_dim])
        q = _constrain(q, mesh, BATCH_AXES, None, MP_AXIS, None)
        if attention_mask is not None:
            out = F.scaled_dot_product_attention(
                q, k, v, attn_mask=attention_mask)
        else:
            out, _ = F.flash_attention(q, k, v, causal=False)
        return self.dropout(self.out(out.reshape([b, s, h])))


class BertLayer(Layer):
    def __init__(self, config: BertConfig):
        super().__init__()
        self.attention = BertSelfAttention(config)
        self.attn_norm = LayerNorm(config.hidden_size,
                                   epsilon=config.layer_norm_eps)
        self.intermediate = Linear(config.hidden_size,
                                   config.intermediate_size)
        self.output = Linear(config.intermediate_size, config.hidden_size)
        self.out_norm = LayerNorm(config.hidden_size,
                                  epsilon=config.layer_norm_eps)
        self.dropout = Dropout(config.hidden_dropout_prob)
        self.act = {"gelu": F.gelu, "relu": F.relu}[config.hidden_act]

    def forward(self, x, attention_mask=None, mesh=None):
        x = self.attn_norm(x + self.attention(x, attention_mask, mesh))
        m = self.output(self.act(self.intermediate(x)))
        return self.out_norm(x + self.dropout(m))


class BertModel(Layer):
    """reference surface: paddle.nn-based BERT encoders used by the hapi
    finetune flows."""

    def __init__(self, config: BertConfig):
        super().__init__()
        self.config = config
        self.embeddings = BertEmbeddings(config)
        from ..nn.layer.container import LayerList

        self.encoder = LayerList([BertLayer(config)
                                  for _ in range(config.num_hidden_layers)])
        self.pooler = Linear(config.hidden_size, config.hidden_size)
        self.pooler_act = Tanh()
        if config.dtype != "float32":
            self.to(dtype=config.dtype)

    def forward(self, input_ids, token_type_ids=None, position_ids=None,
                attention_mask=None):
        mesh = mesh_mod.get_global_mesh()
        if attention_mask is not None and attention_mask.ndim == 2:
            # [B, S] padding mask -> additive [B, 1, 1, S]
            am = attention_mask._array if isinstance(attention_mask, Tensor) \
                else jnp.asarray(attention_mask)
            attention_mask = Tensor(
                (1.0 - am.astype(jnp.float32))[:, None, None, :] * -1e4)
        x = self.embeddings(input_ids, token_type_ids, position_ids)
        x = _constrain(x, mesh, BATCH_AXES, None, None)
        for layer in self.encoder:
            x = layer(x, attention_mask, mesh)
        pooled = self.pooler_act(self.pooler(x[:, 0]))
        return x, pooled


class BertForSequenceClassification(Layer):
    def __init__(self, config: BertConfig):
        super().__init__()
        self.bert = BertModel(config)
        self.dropout = Dropout(config.hidden_dropout_prob)
        self.classifier = Linear(config.hidden_size, config.num_labels)

    def forward(self, input_ids, token_type_ids=None, attention_mask=None):
        _, pooled = self.bert(input_ids, token_type_ids,
                              attention_mask=attention_mask)
        return self.classifier(self.dropout(pooled))


class BertForMaskedLM(Layer):
    def __init__(self, config: BertConfig):
        super().__init__()
        self.bert = BertModel(config)
        self.transform = Linear(config.hidden_size, config.hidden_size)
        self.transform_norm = LayerNorm(config.hidden_size,
                                        epsilon=config.layer_norm_eps)

    def forward(self, input_ids, token_type_ids=None, attention_mask=None):
        seq, _ = self.bert(input_ids, token_type_ids,
                           attention_mask=attention_mask)
        h = self.transform_norm(F.gelu(self.transform(seq)))
        w = self.bert.embeddings.word_embeddings.weight
        return dispatch("mlm_head", lambda a, e: jnp.matmul(a, e.T), (h, w))


ErnieConfig = BertConfig
ErnieModel = BertModel
ErnieForSequenceClassification = BertForSequenceClassification
ErnieForMaskedLM = BertForMaskedLM
