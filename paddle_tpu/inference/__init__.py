"""paddle.inference equivalent — the deployment surface.

Reference: paddle/fluid/inference AnalysisPredictor
(api/analysis_predictor.h:105; Run at analysis_predictor.cc:1643,
ZeroCopyRun :2671) + python surface paddle.inference.{Config,
create_predictor}.

TPU-native: the "analysis + optimization passes + engine subgraphs" stack
collapses into XLA — a saved StableHLO artifact (jit.save) or a live Layer
is jit-compiled once and run; Config's pass/engine knobs are accepted for
API parity and mapped where meaningful (memory_optim ≙ buffer donation,
enable_tensorrt ≙ no-op: XLA owns codegen).
"""
from __future__ import annotations

import os
from typing import Dict, List, Optional

import jax
import numpy as np

from ..core.tensor import Tensor, unwrap
from ..core import tape as _tape

__all__ = ["Config", "Predictor", "create_predictor", "PrecisionType",
           "PlaceType"]


class PrecisionType:
    Float32 = "float32"
    Half = "float16"
    Bfloat16 = "bfloat16"
    Int8 = "int8"


class PlaceType:
    CPU = "cpu"
    GPU = "gpu"
    TPU = "tpu"
    XPU = "xpu"


class Config:
    """reference: paddle.inference.Config (api/paddle_analysis_config.h)."""

    def __init__(self, prog_file: Optional[str] = None,
                 params_file: Optional[str] = None):
        if prog_file and prog_file.endswith(".pdmodel"):
            prog_file = prog_file[: -len(".pdmodel")]
        self._model_prefix = prog_file
        self._layer = None
        self._use_device = PlaceType.TPU
        self._memory_optim = True
        self._precision = PrecisionType.Float32
        self._disabled = False

    # --- model source ---
    def set_model(self, prog_file, params_file=None):
        if prog_file.endswith(".pdmodel"):
            prog_file = prog_file[: -len(".pdmodel")]
        self._model_prefix = prog_file

    def set_layer(self, layer):
        """TPU-native extension: predict a live Layer without export."""
        self._layer = layer

    def model_dir(self):
        return os.path.dirname(self._model_prefix or "")

    # --- device / precision knobs ---
    def enable_use_gpu(self, memory_pool_init_size_mb=100, device_id=0):
        self._use_device = PlaceType.GPU  # maps to default backend

    def enable_xpu(self, *a, **k):
        self._use_device = PlaceType.XPU

    def disable_gpu(self):
        self._use_device = PlaceType.CPU

    def enable_memory_optim(self, x=True):
        self._memory_optim = x

    def enable_tensorrt_engine(self, *a, **k):
        pass  # XLA owns kernel codegen on TPU

    def switch_ir_optim(self, x=True):
        pass

    def set_cpu_math_library_num_threads(self, n):
        pass

    def enable_mkldnn(self):
        pass

    def precision(self):
        return self._precision


class Predictor:
    """reference: AnalysisPredictor — named input/output handles + Run()."""

    def __init__(self, config: Config):
        self._config = config
        self._inputs: Dict[str, np.ndarray] = {}
        self._input_names: List[str] = []
        self._fn = None
        self._outputs = None
        if config._layer is not None:
            layer = config._layer
            layer.eval()

            def run(*xs):
                with _tape.no_grad():
                    out = layer(*[Tensor(x) for x in xs])
                return (tuple(unwrap(o) for o in out)
                        if isinstance(out, (tuple, list))
                        else (unwrap(out),))

            self._fn = jax.jit(run)
        elif config._model_prefix:
            from ..jit.api import load as jload

            self._translated = jload(config._model_prefix)

            def run(*xs):
                out = self._translated(*xs)
                return (tuple(unwrap(o) for o in out)
                        if isinstance(out, (tuple, list))
                        else (unwrap(out),))

            self._fn = run
        else:
            raise ValueError("Config has neither a model file nor a layer")

    # --- zero-copy style handles ---
    def get_input_names(self) -> List[str]:
        return self._input_names or [f"x{i}" for i in range(
            len(self._inputs) or 1)]

    def get_input_handle(self, name: str):
        return _IOHandle(self._inputs, name)

    def get_output_names(self) -> List[str]:
        n = len(self._outputs or [1])
        return [f"out{i}" for i in range(n)]

    def get_output_handle(self, name: str):
        idx = int(name[3:]) if name.startswith("out") else 0
        return _OutHandle(self, idx)

    def run(self, inputs: Optional[List] = None):
        """reference: AnalysisPredictor::Run / ZeroCopyRun."""
        if inputs is not None:
            xs = [unwrap(x) if isinstance(x, Tensor) else np.asarray(x)
                  for x in inputs]
        else:
            xs = [self._inputs[k] for k in sorted(self._inputs)]
        self._outputs = self._fn(*xs)
        if inputs is not None:
            return [Tensor(o) for o in self._outputs]
        return True


class _IOHandle:
    def __init__(self, store, name):
        self._store = store
        self._name = name

    def copy_from_cpu(self, arr):
        self._store[self._name] = np.asarray(arr)

    def reshape(self, shape):
        pass


class _OutHandle:
    def __init__(self, predictor, idx):
        self._p = predictor
        self._i = idx

    def copy_to_cpu(self):
        return np.asarray(self._p._outputs[self._i])


def create_predictor(config: Config) -> Predictor:
    return Predictor(config)
