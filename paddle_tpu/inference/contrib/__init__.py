"""paddle.inference.contrib (reference: python/paddle/inference/contrib/)."""
from . import utils  # noqa: F401
