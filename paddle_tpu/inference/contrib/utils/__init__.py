"""paddle.inference.contrib.utils (reference:
python/paddle/inference/contrib/utils/__init__.py — copy_tensor)."""
import numpy as np


def copy_tensor(dst, src):
    """Copy src's buffer into dst (reference: base.core copy_tensor)."""
    arr = np.asarray(getattr(src, "_array", src))
    if hasattr(dst, "_array"):
        import jax.numpy as jnp

        dst._array = jnp.asarray(arr)
        return dst
    np.copyto(dst, arr)
    return dst
