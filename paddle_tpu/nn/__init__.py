"""paddle.nn namespace (reference: python/paddle/nn/__init__.py)."""
from . import functional  # noqa: F401
from . import initializer  # noqa: F401
from . import utils  # noqa: F401
from . import quant  # noqa: F401
from .layer.layers import Layer  # noqa: F401
from .layer.container import Sequential, LayerList, ParameterList, LayerDict  # noqa: F401
from .layer.common import *  # noqa: F401,F403
from .layer.activation import *  # noqa: F401,F403
from .layer.conv import *  # noqa: F401,F403
from .layer.norm import *  # noqa: F401,F403
from .layer.pooling import *  # noqa: F401,F403
from .layer.loss import *  # noqa: F401,F403
from .layer.transformer import *  # noqa: F401,F403
from .layer.rnn import *  # noqa: F401,F403
from .clip import ClipGradByGlobalNorm, ClipGradByNorm, ClipGradByValue  # noqa: F401
from .initializer import ParamAttr  # noqa: F401

from .layer import common as _common
from .layer import activation as _activation
from .layer import conv as _conv
from .layer import norm as _norm
from .layer import pooling as _pooling
from .layer import loss as _loss
from .layer import transformer as _transformer
from .layer import rnn as _rnn

__all__ = (
    ["Layer", "Sequential", "LayerList", "ParameterList", "LayerDict",
     "ClipGradByGlobalNorm", "ClipGradByNorm", "ClipGradByValue", "ParamAttr"]
    + _common.__all__ + _activation.__all__ + _conv.__all__ + _norm.__all__
    + _pooling.__all__ + _loss.__all__ + _transformer.__all__ + _rnn.__all__
)

from .layer.extras2 import (  # noqa: E402,F401
    AdaptiveLogSoftmaxWithLoss, BeamSearchDecoder, FeatureAlphaDropout,
    FractionalMaxPool2D, FractionalMaxPool3D, HSigmoidLoss, ZeroPad1D,
    ZeroPad3D, dynamic_decode)

__all__ = [n for n in dir() if not n.startswith("_") and n[0].isupper()
           or n in ("functional", "initializer", "utils", "dynamic_decode")]
