"""Weight initializers (reference: python/paddle/nn/initializer/*).

Each initializer is a callable `(shape, dtype) -> jax.Array` drawing from the
global keys-as-generator RNG. `ParamAttr` mirrors paddle.ParamAttr.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from ...framework.random import next_key
from ...framework import dtype as dtypes

__all__ = [
    "Initializer", "Constant", "Normal", "TruncatedNormal", "Uniform",
    "XavierNormal", "XavierUniform", "KaimingNormal", "KaimingUniform",
    "Assign", "Orthogonal", "Dirac", "calculate_gain", "ParamAttr",
    "set_global_initializer",
]

_global_weight_init = None
_global_bias_init = None


def set_global_initializer(weight_init, bias_init=None):
    global _global_weight_init, _global_bias_init
    _global_weight_init = weight_init
    _global_bias_init = bias_init


def _fans(shape):
    shape = tuple(shape)
    if len(shape) < 1:
        return 1, 1
    if len(shape) == 1:
        return shape[0], shape[0]
    if len(shape) == 2:
        return shape[0], shape[1]
    # conv kernels: paddle layout [out_c, in_c, *k]
    receptive = int(np.prod(shape[2:]))
    return shape[1] * receptive, shape[0] * receptive


def calculate_gain(nonlinearity, param=None):
    gains = {
        "sigmoid": 1.0,
        "linear": 1.0,
        "conv1d": 1.0,
        "conv2d": 1.0,
        "conv3d": 1.0,
        "tanh": 5.0 / 3.0,
        "relu": math.sqrt(2.0),
        "leaky_relu": math.sqrt(2.0 / (1 + (param if param is not None else 0.01) ** 2)),
        "selu": 3.0 / 4.0,
    }
    return gains[nonlinearity]


class Initializer:
    def __call__(self, shape, dtype):
        raise NotImplementedError


class Constant(Initializer):
    def __init__(self, value=0.0):
        self.value = value

    def __call__(self, shape, dtype):
        return jnp.full(shape, self.value, dtype=dtypes.convert_dtype(dtype))


class Normal(Initializer):
    def __init__(self, mean=0.0, std=1.0):
        self.mean, self.std = mean, std

    def __call__(self, shape, dtype):
        d = dtypes.convert_dtype(dtype)
        return (self.mean + self.std * jax.random.normal(next_key(), shape)).astype(d)


class TruncatedNormal(Initializer):
    def __init__(self, mean=0.0, std=1.0, a=-2.0, b=2.0):
        self.mean, self.std, self.a, self.b = mean, std, a, b

    def __call__(self, shape, dtype):
        d = dtypes.convert_dtype(dtype)
        z = jax.random.truncated_normal(next_key(), self.a, self.b, shape)
        return (self.mean + self.std * z).astype(d)


class Uniform(Initializer):
    def __init__(self, low=-1.0, high=1.0):
        self.low, self.high = low, high

    def __call__(self, shape, dtype):
        d = dtypes.convert_dtype(dtype)
        return jax.random.uniform(next_key(), shape, minval=self.low, maxval=self.high).astype(d)


class XavierNormal(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype):
        d = dtypes.convert_dtype(dtype)
        fi, fo = _fans(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        fo = self.fan_out if self.fan_out is not None else fo
        std = self.gain * math.sqrt(2.0 / (fi + fo))
        return (std * jax.random.normal(next_key(), shape)).astype(d)


class XavierUniform(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype):
        d = dtypes.convert_dtype(dtype)
        fi, fo = _fans(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        fo = self.fan_out if self.fan_out is not None else fo
        limit = self.gain * math.sqrt(6.0 / (fi + fo))
        return jax.random.uniform(next_key(), shape, minval=-limit, maxval=limit).astype(d)


class KaimingNormal(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu"):
        self.fan_in, self.negative_slope, self.nonlinearity = fan_in, negative_slope, nonlinearity

    def __call__(self, shape, dtype):
        d = dtypes.convert_dtype(dtype)
        fi, _ = _fans(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        gain = calculate_gain(self.nonlinearity, self.negative_slope)
        std = gain / math.sqrt(fi)
        return (std * jax.random.normal(next_key(), shape)).astype(d)


class KaimingUniform(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu"):
        self.fan_in, self.negative_slope, self.nonlinearity = fan_in, negative_slope, nonlinearity

    def __call__(self, shape, dtype):
        d = dtypes.convert_dtype(dtype)
        fi, _ = _fans(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        gain = calculate_gain(self.nonlinearity, self.negative_slope)
        limit = gain * math.sqrt(3.0 / fi)
        return jax.random.uniform(next_key(), shape, minval=-limit, maxval=limit).astype(d)


class Assign(Initializer):
    def __init__(self, value):
        self.value = value

    def __call__(self, shape, dtype):
        from ...core.tensor import unwrap

        arr = jnp.asarray(unwrap(self.value))
        return arr.reshape(shape).astype(dtypes.convert_dtype(dtype))


class Orthogonal(Initializer):
    def __init__(self, gain=1.0):
        self.gain = gain

    def __call__(self, shape, dtype):
        d = dtypes.convert_dtype(dtype)
        rows, cols = shape[0], int(np.prod(shape[1:]))
        n = max(rows, cols)
        a = jax.random.normal(next_key(), (n, n))
        q, r = jnp.linalg.qr(a)
        q = q * jnp.sign(jnp.diagonal(r))
        return (self.gain * q[:rows, :cols]).reshape(shape).astype(d)


class Dirac(Initializer):
    def __init__(self, groups=1):
        self.groups = groups

    def __call__(self, shape, dtype):
        d = dtypes.convert_dtype(dtype)
        out = np.zeros(shape, dtype=np.float32)
        oc, ic = shape[0], shape[1]
        per = oc // self.groups
        centers = [s // 2 for s in shape[2:]]
        for g in range(self.groups):
            for i in range(min(per, ic)):
                idx = (g * per + i, i) + tuple(centers)
                out[idx] = 1.0
        return jnp.asarray(out).astype(d)


class ParamAttr:
    """paddle.ParamAttr (reference: python/paddle/base/param_attr.py)."""

    def __init__(
        self,
        name=None,
        initializer=None,
        learning_rate=1.0,
        regularizer=None,
        trainable=True,
        do_model_average=True,
        need_clip=True,
    ):
        self.name = name
        self.initializer = initializer
        self.learning_rate = learning_rate
        self.regularizer = regularizer
        self.trainable = trainable
        self.do_model_average = do_model_average
        self.need_clip = need_clip


def _resolve_param_attr(attr):
    """Normalise True/None/str/Initializer/ParamAttr to ParamAttr|None
    (reference: ParamAttr._to_attr)."""
    if attr is None or attr is True:
        return None
    if attr is False:
        return ParamAttr(trainable=False)  # sentinel: caller checks falsy attr
    if isinstance(attr, ParamAttr):
        return attr
    if isinstance(attr, str):
        return ParamAttr(name=attr)
    if isinstance(attr, Initializer):
        return ParamAttr(initializer=attr)
    return None
