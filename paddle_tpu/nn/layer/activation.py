"""Activation layers (reference: python/paddle/nn/layer/activation.py)."""
from __future__ import annotations

from .. import functional as F
from ..initializer import Constant
from .layers import Layer

__all__ = [
    "ReLU", "ReLU6", "ELU", "SELU", "CELU", "GELU", "Silu", "SiLU", "Swish", "Mish",
    "Sigmoid", "Hardsigmoid", "Hardswish", "Hardtanh", "Hardshrink",
    "Softshrink", "Tanhshrink", "LeakyReLU", "LogSigmoid", "LogSoftmax",
    "Softmax", "Softmax2D", "Softplus", "Softsign", "Tanh", "ThresholdedReLU",
    "Maxout", "GLU", "PReLU", "RReLU",
]


def _simple(name, fn_name, **defaults):
    class _Act(Layer):
        def __init__(self, *args, name=None, **kwargs):
            super().__init__()
            params = dict(defaults)
            keys = list(defaults.keys())
            for i, a in enumerate(args):
                params[keys[i]] = a
            params.update({k: v for k, v in kwargs.items() if k in params})
            self._params = params

        def forward(self, x):
            return getattr(F, fn_name)(x, **self._params)

    _Act.__name__ = name
    _Act.__qualname__ = name
    return _Act


ReLU = _simple("ReLU", "relu")
ReLU6 = _simple("ReLU6", "relu6")
ELU = _simple("ELU", "elu", alpha=1.0)
SELU = _simple("SELU", "selu", scale=1.0507009873554805, alpha=1.6732632423543772)
CELU = _simple("CELU", "celu", alpha=1.0)
GELU = _simple("GELU", "gelu", approximate=False)
Silu = _simple("Silu", "silu")
SiLU = Silu
Swish = _simple("Swish", "swish")
Mish = _simple("Mish", "mish")
Sigmoid = _simple("Sigmoid", "sigmoid")
Hardsigmoid = _simple("Hardsigmoid", "hardsigmoid")
Hardswish = _simple("Hardswish", "hardswish")
Hardtanh = _simple("Hardtanh", "hardtanh", min=-1.0, max=1.0)
Hardshrink = _simple("Hardshrink", "hardshrink", threshold=0.5)
Softshrink = _simple("Softshrink", "softshrink", threshold=0.5)
Tanhshrink = _simple("Tanhshrink", "tanhshrink")
LeakyReLU = _simple("LeakyReLU", "leaky_relu", negative_slope=0.01)
LogSigmoid = _simple("LogSigmoid", "log_sigmoid")
LogSoftmax = _simple("LogSoftmax", "log_softmax", axis=-1)
Softmax = _simple("Softmax", "softmax", axis=-1)
Softplus = _simple("Softplus", "softplus", beta=1.0, threshold=20.0)
Softsign = _simple("Softsign", "softsign")
Tanh = _simple("Tanh", "tanh")
ThresholdedReLU = _simple("ThresholdedReLU", "thresholded_relu", threshold=1.0, value=0.0)
GLU = _simple("GLU", "glu", axis=-1)


class Softmax2D(Layer):
    def forward(self, x):
        return F.softmax(x, axis=-3)


class Maxout(Layer):
    def __init__(self, groups, axis=1, name=None):
        super().__init__()
        self.groups = groups
        self.axis = axis

    def forward(self, x):
        return F.maxout(x, self.groups, self.axis)


class PReLU(Layer):
    def __init__(self, num_parameters=1, init=0.25, weight_attr=None, data_format="NCHW", name=None):
        super().__init__()
        self.data_format = data_format
        self.weight = self.create_parameter(
            (num_parameters,), attr=weight_attr, default_initializer=Constant(init)
        )

    def forward(self, x):
        return F.prelu(x, self.weight, data_format=self.data_format)


class RReLU(Layer):
    def __init__(self, lower=1.0 / 8.0, upper=1.0 / 3.0, name=None):
        super().__init__()
        self.lower = lower
        self.upper = upper

    def forward(self, x):
        return F.rrelu(x, self.lower, self.upper, training=self.training)
