"""Common layers (reference: python/paddle/nn/layer/common.py)."""
from __future__ import annotations

import jax.numpy as jnp

from ...core.tensor import Tensor
from .. import functional as F
from ..initializer import Constant, XavierNormal, XavierUniform, _resolve_param_attr
from .layers import Layer

__all__ = [
    "Linear", "Dropout", "Dropout2D", "Dropout3D", "AlphaDropout", "Embedding",
    "Flatten", "Unflatten", "Upsample", "UpsamplingBilinear2D", "UpsamplingNearest2D",
    "Pad1D", "Pad2D", "Pad3D", "ZeroPad2D", "CosineSimilarity", "PairwiseDistance",
    "Bilinear", "PixelShuffle", "PixelUnshuffle", "ChannelShuffle", "Identity",
    "Fold", "Unfold", "LinearCompress",
]


class Identity(Layer):
    def __init__(self, *args, **kwargs):
        super().__init__()

    def forward(self, x):
        return x


class Linear(Layer):
    """paddle.nn.Linear (ref: nn/layer/common.py:Linear). Weight layout
    [in_features, out_features]."""

    def __init__(self, in_features, out_features, weight_attr=None, bias_attr=None, name=None):
        super().__init__()
        self._in_features = in_features
        self._out_features = out_features
        self.weight = self.create_parameter(
            (in_features, out_features), attr=weight_attr, default_initializer=XavierNormal()
        )
        if bias_attr is False:
            self.bias = None
            self.add_parameter("bias", None)
        else:
            self.bias = self.create_parameter((out_features,), attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.linear(x, self.weight, self.bias)

    def extra_repr(self):
        return f"in_features={self._in_features}, out_features={self._out_features}"


LinearCompress = Linear  # quant-aware variant not needed on TPU (API parity)


class Dropout(Layer):
    def __init__(self, p=0.5, axis=None, mode="upscale_in_train", name=None):
        super().__init__()
        self.p = p
        self.axis = axis
        self.mode = mode

    def forward(self, x):
        return F.dropout(x, p=self.p, axis=self.axis, training=self.training, mode=self.mode)


class Dropout2D(Layer):
    def __init__(self, p=0.5, data_format="NCHW", name=None):
        super().__init__()
        self.p = p
        self.data_format = data_format

    def forward(self, x):
        return F.dropout2d(x, p=self.p, training=self.training, data_format=self.data_format)


class Dropout3D(Layer):
    def __init__(self, p=0.5, data_format="NCDHW", name=None):
        super().__init__()
        self.p = p
        self.data_format = data_format

    def forward(self, x):
        return F.dropout3d(x, p=self.p, training=self.training, data_format=self.data_format)


class AlphaDropout(Layer):
    def __init__(self, p=0.5, name=None):
        super().__init__()
        self.p = p

    def forward(self, x):
        return F.alpha_dropout(x, p=self.p, training=self.training)


class Embedding(Layer):
    """paddle.nn.Embedding (ref: nn/layer/common.py:Embedding)."""

    def __init__(self, num_embeddings, embedding_dim, padding_idx=None, sparse=False,
                 weight_attr=None, name=None):
        super().__init__()
        self._num_embeddings = num_embeddings
        self._embedding_dim = embedding_dim
        self._padding_idx = (
            None if padding_idx is None
            else padding_idx if padding_idx >= 0
            else num_embeddings + padding_idx
        )
        # reference default: the layer-helper Xavier initializer (an
        # explicit Normal(0,1) here inflated logits ~8x on tied heads)
        self.weight = self.create_parameter(
            (num_embeddings, embedding_dim), attr=weight_attr,
            default_initializer=XavierUniform()
        )
        if self._padding_idx is not None:
            self.weight._array = self.weight._array.at[self._padding_idx].set(0.0)

    def forward(self, x):
        return F.embedding(x, self.weight, padding_idx=self._padding_idx)

    def extra_repr(self):
        return f"num_embeddings={self._num_embeddings}, embedding_dim={self._embedding_dim}"


class Flatten(Layer):
    def __init__(self, start_axis=1, stop_axis=-1):
        super().__init__()
        self.start_axis = start_axis
        self.stop_axis = stop_axis

    def forward(self, x):
        from ...ops import flatten

        return flatten(x, self.start_axis, self.stop_axis)


class Unflatten(Layer):
    def __init__(self, axis, shape, name=None):
        super().__init__()
        self.axis = axis
        self.shape = shape

    def forward(self, x):
        from ...ops import reshape

        new_shape = list(x.shape[: self.axis]) + list(self.shape) + list(x.shape[self.axis + 1 :])
        return reshape(x, new_shape)


class Upsample(Layer):
    def __init__(self, size=None, scale_factor=None, mode="nearest", align_corners=False,
                 align_mode=0, data_format=None, name=None):
        super().__init__()
        self.size = size
        self.scale_factor = scale_factor
        self.mode = mode
        self.align_corners = align_corners
        self.align_mode = align_mode
        self.data_format = data_format

    def forward(self, x):
        df = self.data_format or ("NCHW" if x.ndim == 4 else "NCL" if x.ndim == 3 else "NCDHW")
        return F.interpolate(x, self.size, self.scale_factor, self.mode, self.align_corners, self.align_mode, df)


class UpsamplingBilinear2D(Upsample):
    def __init__(self, size=None, scale_factor=None, data_format="NCHW", name=None):
        super().__init__(size, scale_factor, "bilinear", True, 0, data_format)


class UpsamplingNearest2D(Upsample):
    def __init__(self, size=None, scale_factor=None, data_format="NCHW", name=None):
        super().__init__(size, scale_factor, "nearest", False, 0, data_format)


class _PadN(Layer):
    def __init__(self, padding, mode="constant", value=0.0, data_format="NCHW", name=None):
        super().__init__()
        self.padding = padding
        self.mode = mode
        self.value = value
        self.data_format = data_format

    def forward(self, x):
        return F.pad(x, self.padding, self.mode, self.value, self.data_format)


class Pad1D(_PadN):
    def __init__(self, padding, mode="constant", value=0.0, data_format="NCL", name=None):
        super().__init__(padding, mode, value, data_format)


class Pad2D(_PadN):
    pass


class Pad3D(_PadN):
    def __init__(self, padding, mode="constant", value=0.0, data_format="NCDHW", name=None):
        super().__init__(padding, mode, value, data_format)


class ZeroPad2D(_PadN):
    def __init__(self, padding, data_format="NCHW", name=None):
        super().__init__(padding, "constant", 0.0, data_format)


class CosineSimilarity(Layer):
    def __init__(self, axis=1, eps=1e-8):
        super().__init__()
        self.axis = axis
        self.eps = eps

    def forward(self, x1, x2):
        return F.cosine_similarity(x1, x2, axis=self.axis, eps=self.eps)


class PairwiseDistance(Layer):
    def __init__(self, p=2.0, epsilon=1e-6, keepdim=False, name=None):
        super().__init__()
        self.p = p
        self.epsilon = epsilon
        self.keepdim = keepdim

    def forward(self, x, y):
        from ...core.tensor import dispatch

        return dispatch(
            "pairwise_distance",
            lambda a, b: jnp.linalg.norm(a - b + self.epsilon, ord=self.p, axis=-1, keepdims=self.keepdim),
            (x, y),
        )


class Bilinear(Layer):
    def __init__(self, in1_features, in2_features, out_features, weight_attr=None, bias_attr=None, name=None):
        super().__init__()
        self.weight = self.create_parameter((out_features, in1_features, in2_features), attr=weight_attr)
        if bias_attr is False:
            self.bias = None
            self.add_parameter("bias", None)
        else:
            self.bias = self.create_parameter((1, out_features), attr=bias_attr, is_bias=True)

    def forward(self, x1, x2):
        return F.bilinear(x1, x2, self.weight, self.bias)


class PixelShuffle(Layer):
    def __init__(self, upscale_factor, data_format="NCHW", name=None):
        super().__init__()
        self.factor = upscale_factor
        self.data_format = data_format

    def forward(self, x):
        return F.pixel_shuffle(x, self.factor, self.data_format)


class PixelUnshuffle(Layer):
    def __init__(self, downscale_factor, data_format="NCHW", name=None):
        super().__init__()
        self.factor = downscale_factor
        self.data_format = data_format

    def forward(self, x):
        return F.pixel_unshuffle(x, self.factor, self.data_format)


class ChannelShuffle(Layer):
    def __init__(self, groups, data_format="NCHW", name=None):
        super().__init__()
        self.groups = groups
        self.data_format = data_format

    def forward(self, x):
        return F.channel_shuffle(x, self.groups, self.data_format)


class Fold(Layer):
    def __init__(self, output_sizes, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
        super().__init__()
        self.args = (output_sizes, kernel_sizes, strides, paddings, dilations)

    def forward(self, x):
        return F.fold(x, *self.args)


class Unfold(Layer):
    def __init__(self, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
        super().__init__()
        self.args = (kernel_sizes, strides, paddings, dilations)

    def forward(self, x):
        return F.unfold(x, *self.args)
