"""Pooling layers (reference: python/paddle/nn/layer/pooling.py)."""
from __future__ import annotations

from .. import functional as F
from .layers import Layer

__all__ = [
    "AvgPool1D", "AvgPool2D", "AvgPool3D", "MaxPool1D", "MaxPool2D", "MaxPool3D",
    "AdaptiveAvgPool1D", "AdaptiveAvgPool2D", "AdaptiveAvgPool3D",
    "AdaptiveMaxPool1D", "AdaptiveMaxPool2D", "AdaptiveMaxPool3D",
    "MaxUnPool1D", "MaxUnPool2D", "MaxUnPool3D", "LPPool1D", "LPPool2D",
]


def _make_pool_layer(name, fn, has_mask=False):
    class _Pool(Layer):
        def __init__(self, kernel_size, stride=None, padding=0, ceil_mode=False,
                     exclusive=True, divisor_override=None, return_mask=False,
                     data_format=None, name=None):
            super().__init__()
            self.kw = dict(stride=stride, padding=padding, ceil_mode=ceil_mode,
                           data_format=data_format)
            self.kernel_size = kernel_size
            self.return_mask = return_mask
            self.exclusive = exclusive

        def forward(self, x):
            kw = dict(self.kw)
            if has_mask:
                kw["return_mask"] = self.return_mask
            else:
                kw["exclusive"] = self.exclusive
            return fn(x, self.kernel_size, **kw)

    _Pool.__name__ = name
    _Pool.__qualname__ = name
    return _Pool


AvgPool1D = _make_pool_layer("AvgPool1D", F.avg_pool1d)
AvgPool2D = _make_pool_layer("AvgPool2D", F.avg_pool2d)
AvgPool3D = _make_pool_layer("AvgPool3D", F.avg_pool3d)
MaxPool1D = _make_pool_layer("MaxPool1D", F.max_pool1d, has_mask=True)
MaxPool2D = _make_pool_layer("MaxPool2D", F.max_pool2d, has_mask=True)
MaxPool3D = _make_pool_layer("MaxPool3D", F.max_pool3d, has_mask=True)


def _make_adaptive_layer(name, fn):
    class _Pool(Layer):
        def __init__(self, output_size, data_format=None, return_mask=False, name=None):
            super().__init__()
            self.output_size = output_size
            self.data_format = data_format

        def forward(self, x):
            return fn(x, self.output_size, data_format=self.data_format)

    _Pool.__name__ = name
    _Pool.__qualname__ = name
    return _Pool


AdaptiveAvgPool1D = _make_adaptive_layer("AdaptiveAvgPool1D", F.adaptive_avg_pool1d)
AdaptiveAvgPool2D = _make_adaptive_layer("AdaptiveAvgPool2D", F.adaptive_avg_pool2d)
AdaptiveAvgPool3D = _make_adaptive_layer("AdaptiveAvgPool3D", F.adaptive_avg_pool3d)
AdaptiveMaxPool1D = _make_adaptive_layer("AdaptiveMaxPool1D", F.adaptive_max_pool1d)
AdaptiveMaxPool2D = _make_adaptive_layer("AdaptiveMaxPool2D", F.adaptive_max_pool2d)
AdaptiveMaxPool3D = _make_adaptive_layer("AdaptiveMaxPool3D", F.adaptive_max_pool3d)


def _make_unpool_layer(name, fn):
    class _Unpool(Layer):
        def __init__(self, kernel_size, stride=None, padding=0, data_format=None, output_size=None, name=None):
            super().__init__()
            self.kw = dict(stride=stride, padding=padding, data_format=data_format, output_size=output_size)
            self.kernel_size = kernel_size

        def forward(self, x, indices):
            return fn(x, indices, self.kernel_size, **self.kw)

    _Unpool.__name__ = name
    _Unpool.__qualname__ = name
    return _Unpool


MaxUnPool1D = _make_unpool_layer("MaxUnPool1D", F.max_unpool1d)
MaxUnPool2D = _make_unpool_layer("MaxUnPool2D", F.max_unpool2d)
MaxUnPool3D = _make_unpool_layer("MaxUnPool3D", F.max_unpool3d)


class LPPool1D(Layer):
    def __init__(self, norm_type, kernel_size, stride=None, padding=0, ceil_mode=False, data_format="NCL", name=None):
        super().__init__()
        self.args = (norm_type, kernel_size, stride, padding, ceil_mode, data_format)

    def forward(self, x):
        return F.lp_pool1d(x, *self.args)


class LPPool2D(Layer):
    def __init__(self, norm_type, kernel_size, stride=None, padding=0, ceil_mode=False, data_format="NCHW", name=None):
        super().__init__()
        self.args = (norm_type, kernel_size, stride, padding, ceil_mode, data_format)

    def forward(self, x):
        return F.lp_pool2d(x, *self.args)
