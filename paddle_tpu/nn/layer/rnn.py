"""RNN layers via lax.scan (reference: python/paddle/nn/layer/rnn.py).

The reference lowers RNNs to cudnn kernels; on TPU the idiomatic form is a
`lax.scan` over time — XLA pipelines the per-step matmuls onto the MXU.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from ...core.tensor import Tensor, dispatch, unwrap
from ..initializer import Uniform
from .layers import Layer

__all__ = ["SimpleRNNCell", "LSTMCell", "GRUCell", "RNN", "BiRNN", "SimpleRNN", "LSTM", "GRU", "RNNCellBase"]


class RNNCellBase(Layer):
    def get_initial_states(self, batch_ref, shape=None, dtype=None, init_value=0.0, batch_dim_idx=0):
        b = batch_ref.shape[batch_dim_idx]
        sizes = self.state_shape
        if isinstance(sizes, (list, tuple)) and isinstance(sizes[0], (list, tuple)):
            return tuple(Tensor(jnp.full((b,) + tuple(s), init_value, jnp.float32)) for s in sizes)
        return Tensor(jnp.full((b,) + tuple(sizes), init_value, jnp.float32))


class SimpleRNNCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, activation="tanh", weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None, name=None):
        super().__init__()
        std = 1.0 / math.sqrt(hidden_size)
        init = Uniform(-std, std)
        self.hidden_size = hidden_size
        self.input_size = input_size
        self.activation = activation
        self.weight_ih = self.create_parameter((hidden_size, input_size), weight_ih_attr, default_initializer=init)
        self.weight_hh = self.create_parameter((hidden_size, hidden_size), weight_hh_attr, default_initializer=init)
        self.bias_ih = None if bias_ih_attr is False else self.create_parameter((hidden_size,), bias_ih_attr, is_bias=True, default_initializer=init)
        self.bias_hh = None if bias_hh_attr is False else self.create_parameter((hidden_size,), bias_hh_attr, is_bias=True, default_initializer=init)

    @property
    def state_shape(self):
        return (self.hidden_size,)

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)
        act = jnp.tanh if self.activation == "tanh" else jax.nn.relu

        def impl(x, h, wi, wh, *biases):
            z = x @ wi.T + h @ wh.T
            for b in biases:
                z = z + b
            return act(z)

        args = [inputs, states, self.weight_ih, self.weight_hh]
        args += [b for b in (self.bias_ih, self.bias_hh) if b is not None]
        h = dispatch("simple_rnn_cell", impl, tuple(args))
        return h, h


class LSTMCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, weight_ih_attr=None, weight_hh_attr=None,
                 bias_ih_attr=None, bias_hh_attr=None, proj_size=None, name=None):
        super().__init__()
        std = 1.0 / math.sqrt(hidden_size)
        init = Uniform(-std, std)
        self.hidden_size = hidden_size
        self.input_size = input_size
        self.weight_ih = self.create_parameter((4 * hidden_size, input_size), weight_ih_attr, default_initializer=init)
        self.weight_hh = self.create_parameter((4 * hidden_size, hidden_size), weight_hh_attr, default_initializer=init)
        self.bias_ih = None if bias_ih_attr is False else self.create_parameter((4 * hidden_size,), bias_ih_attr, is_bias=True, default_initializer=init)
        self.bias_hh = None if bias_hh_attr is False else self.create_parameter((4 * hidden_size,), bias_hh_attr, is_bias=True, default_initializer=init)

    @property
    def state_shape(self):
        return ((self.hidden_size,), (self.hidden_size,))

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)
        h0, c0 = states

        def impl(x, h, c, wi, wh, *biases):
            z = x @ wi.T + h @ wh.T
            for b in biases:
                z = z + b
            i, f, g, o = jnp.split(z, 4, axis=-1)
            i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
            g = jnp.tanh(g)
            c_new = f * c + i * g
            h_new = o * jnp.tanh(c_new)
            return h_new, c_new

        args = [inputs, h0, c0, self.weight_ih, self.weight_hh]
        args += [b for b in (self.bias_ih, self.bias_hh) if b is not None]
        h, c = dispatch("lstm_cell", impl, tuple(args))
        return h, (h, c)


class GRUCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, weight_ih_attr=None, weight_hh_attr=None,
                 bias_ih_attr=None, bias_hh_attr=None, name=None):
        super().__init__()
        std = 1.0 / math.sqrt(hidden_size)
        init = Uniform(-std, std)
        self.hidden_size = hidden_size
        self.input_size = input_size
        self.weight_ih = self.create_parameter((3 * hidden_size, input_size), weight_ih_attr, default_initializer=init)
        self.weight_hh = self.create_parameter((3 * hidden_size, hidden_size), weight_hh_attr, default_initializer=init)
        self.bias_ih = None if bias_ih_attr is False else self.create_parameter((3 * hidden_size,), bias_ih_attr, is_bias=True, default_initializer=init)
        self.bias_hh = None if bias_hh_attr is False else self.create_parameter((3 * hidden_size,), bias_hh_attr, is_bias=True, default_initializer=init)

    @property
    def state_shape(self):
        return (self.hidden_size,)

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)

        has_bi = self.bias_ih is not None
        has_bh = self.bias_hh is not None

        def impl(x, h, wi, wh, *biases):
            gi = x @ wi.T
            gh = h @ wh.T
            i = 0
            if has_bi:
                gi = gi + biases[i]
                i += 1
            if has_bh:
                gh = gh + biases[i]
            ir, iz, ic = jnp.split(gi, 3, axis=-1)
            hr, hz, hc = jnp.split(gh, 3, axis=-1)
            r = jax.nn.sigmoid(ir + hr)
            z = jax.nn.sigmoid(iz + hz)
            c = jnp.tanh(ic + r * hc)
            return (1 - z) * c + z * h

        args = [inputs, states, self.weight_ih, self.weight_hh]
        args += [b for b in (self.bias_ih, self.bias_hh) if b is not None]
        h = dispatch("gru_cell", impl, tuple(args))
        return h, h


class RNN(Layer):
    """Run a cell over time (ref: nn/layer/rnn.py:RNN). Python loop keeps
    per-step hooks usable; under to_static, XLA unrolls/pipelines it."""

    def __init__(self, cell, is_reverse=False, time_major=False):
        super().__init__()
        self.cell = cell
        self.is_reverse = is_reverse
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None, **kwargs):
        from ...ops import stack

        time_axis = 0 if self.time_major else 1
        steps = inputs.shape[time_axis]
        states = initial_states if initial_states is not None else self.cell.get_initial_states(inputs, batch_dim_idx=1 if self.time_major else 0)
        outs = []
        order = range(steps - 1, -1, -1) if self.is_reverse else range(steps)
        for t in order:
            xt = inputs[:, t] if not self.time_major else inputs[t]
            out, states = self.cell(xt, states, **kwargs)
            outs.append(out)
        if self.is_reverse:
            outs = outs[::-1]
        outputs = stack(outs, axis=time_axis)
        return outputs, states


class BiRNN(Layer):
    def __init__(self, cell_fw, cell_bw, time_major=False):
        super().__init__()
        self.rnn_fw = RNN(cell_fw, False, time_major)
        self.rnn_bw = RNN(cell_bw, True, time_major)
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None, **kwargs):
        from ...ops import concat

        st_fw, st_bw = (initial_states if initial_states is not None else (None, None))
        out_fw, s_fw = self.rnn_fw(inputs, st_fw, sequence_length, **kwargs)
        out_bw, s_bw = self.rnn_bw(inputs, st_bw, sequence_length, **kwargs)
        return concat([out_fw, out_bw], axis=-1), (s_fw, s_bw)


class _RNNBase(Layer):
    """Multi-layer (bi)directional recurrent net with scan-based time loop."""

    def __init__(self, mode, input_size, hidden_size, num_layers=1, direction="forward",
                 time_major=False, dropout=0.0, weight_ih_attr=None, weight_hh_attr=None,
                 bias_ih_attr=None, bias_hh_attr=None, name=None):
        super().__init__()
        self.mode = mode
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.time_major = time_major
        self.dropout = dropout
        self.bidirect = direction in ("bidirect", "bidirectional")
        num_dir = 2 if self.bidirect else 1
        self.num_directions = num_dir
        cell_cls = {"RNN_TANH": SimpleRNNCell, "RNN_RELU": SimpleRNNCell, "LSTM": LSTMCell, "GRU": GRUCell}[mode]
        kwargs = {}
        if mode == "RNN_RELU":
            kwargs["activation"] = "relu"
        elif mode == "RNN_TANH":
            kwargs["activation"] = "tanh"
        from .container import LayerList

        self.cells = LayerList()
        for layer_i in range(num_layers):
            in_size = input_size if layer_i == 0 else hidden_size * num_dir
            for _ in range(num_dir):
                self.cells.append(cell_cls(in_size, hidden_size,
                                           weight_ih_attr=weight_ih_attr, weight_hh_attr=weight_hh_attr,
                                           bias_ih_attr=bias_ih_attr, bias_hh_attr=bias_hh_attr, **kwargs))

    def _scan_layer(self, cell, x, h0, reverse):
        """x: [B, T, I] (batch-first internal). Uses lax.scan through dispatch
        so autograd works."""
        is_lstm = self.mode == "LSTM"
        has_bi = cell.bias_ih is not None
        has_bh = cell.bias_hh is not None

        params = [cell.weight_ih, cell.weight_hh]
        params += [b for b in (cell.bias_ih, cell.bias_hh) if b is not None]

        def impl(xa, h_init_0, h_init_1, wi, wh, *biases):
            bias_sum = 0.0
            i = 0
            if has_bi:
                bias_sum = bias_sum + biases[i]
                i += 1
            if has_bh:
                bias_sum = bias_sum + biases[i]

            xs = jnp.swapaxes(xa, 0, 1)  # [T, B, I]
            if reverse:
                xs = jnp.flip(xs, 0)

            if self.mode in ("RNN_TANH", "RNN_RELU"):
                act = jnp.tanh if self.mode == "RNN_TANH" else jax.nn.relu

                def step(h, xt):
                    hn = act(xt @ wi.T + h @ wh.T + bias_sum)
                    return hn, hn

                hT, ys = jax.lax.scan(step, h_init_0, xs)
                state = (hT,)
            elif self.mode == "GRU":
                bi = biases[0] if has_bi else 0.0
                bh = biases[1 if has_bi else 0] if has_bh else 0.0

                def step(h, xt):
                    gi = xt @ wi.T + bi
                    gh = h @ wh.T + bh
                    ir, iz, ic = jnp.split(gi, 3, axis=-1)
                    hr, hz, hc = jnp.split(gh, 3, axis=-1)
                    r = jax.nn.sigmoid(ir + hr)
                    z = jax.nn.sigmoid(iz + hz)
                    c = jnp.tanh(ic + r * hc)
                    hn = (1 - z) * c + z * h
                    return hn, hn

                hT, ys = jax.lax.scan(step, h_init_0, xs)
                state = (hT,)
            else:  # LSTM

                def step(carry, xt):
                    h, c = carry
                    z = xt @ wi.T + h @ wh.T + bias_sum
                    ii, ff, gg, oo = jnp.split(z, 4, axis=-1)
                    ii, ff, oo = jax.nn.sigmoid(ii), jax.nn.sigmoid(ff), jax.nn.sigmoid(oo)
                    gg = jnp.tanh(gg)
                    cn = ff * c + ii * gg
                    hn = oo * jnp.tanh(cn)
                    return (hn, cn), hn

                (hT, cT), ys = jax.lax.scan(step, (h_init_0, h_init_1), xs)
                state = (hT, cT)
            if reverse:
                ys = jnp.flip(ys, 0)
            return (jnp.swapaxes(ys, 0, 1),) + state

        h0_0 = h0[0] if is_lstm else h0
        h0_1 = h0[1] if is_lstm else h0  # dummy for non-lstm
        out = dispatch("rnn_scan", impl, tuple([x, h0_0, h0_1] + params))
        if is_lstm:
            y, hT, cT = out
            return y, (hT, cT)
        y, hT = out[0], out[1]
        return y, hT

    def forward(self, inputs, initial_states=None, sequence_length=None):
        from ...ops import concat, stack

        x = inputs
        if self.time_major:
            from ...ops import transpose

            x = transpose(x, [1, 0, 2])
        b = x.shape[0]
        nd = self.num_directions
        is_lstm = self.mode == "LSTM"
        if initial_states is None:
            z = Tensor(jnp.zeros((self.num_layers * nd, b, self.hidden_size), jnp.float32))
            initial_states = (z, z.clone()) if is_lstm else z
        final_h, final_c = [], []
        for layer_i in range(self.num_layers):
            outs = []
            for d in range(nd):
                idx = layer_i * nd + d
                cell = self.cells[idx]
                if is_lstm:
                    h0 = (initial_states[0][idx], initial_states[1][idx])
                else:
                    h0 = initial_states[idx]
                y, st = self._scan_layer(cell, x, h0, reverse=(d == 1))
                outs.append(y)
                if is_lstm:
                    final_h.append(st[0])
                    final_c.append(st[1])
                else:
                    final_h.append(st)
            x = outs[0] if nd == 1 else concat(outs, axis=-1)
            if self.dropout > 0 and layer_i < self.num_layers - 1 and self.training:
                from .. import functional as F

                x = F.dropout(x, self.dropout, training=True)
        out = x
        if self.time_major:
            from ...ops import transpose

            out = transpose(out, [1, 0, 2])
        h_stack = stack(final_h, axis=0)
        if is_lstm:
            c_stack = stack(final_c, axis=0)
            return out, (h_stack, c_stack)
        return out, h_stack


class SimpleRNN(_RNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1, direction="forward",
                 time_major=False, dropout=0.0, activation="tanh", **kwargs):
        mode = "RNN_TANH" if activation == "tanh" else "RNN_RELU"
        super().__init__(mode, input_size, hidden_size, num_layers, direction, time_major, dropout, **kwargs)


class LSTM(_RNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1, direction="forward",
                 time_major=False, dropout=0.0, proj_size=None, **kwargs):
        super().__init__("LSTM", input_size, hidden_size, num_layers, direction, time_major, dropout, **kwargs)


class GRU(_RNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1, direction="forward",
                 time_major=False, dropout=0.0, **kwargs):
        super().__init__("GRU", input_size, hidden_size, num_layers, direction, time_major, dropout, **kwargs)
