"""Loss layers (reference: python/paddle/nn/layer/loss.py)."""
from __future__ import annotations

from .. import functional as F
from .layers import Layer

__all__ = [
    "CrossEntropyLoss", "MSELoss", "L1Loss", "NLLLoss", "BCELoss",
    "BCEWithLogitsLoss", "KLDivLoss", "SmoothL1Loss", "HuberLoss",
    "MarginRankingLoss", "CosineEmbeddingLoss", "HingeEmbeddingLoss",
    "TripletMarginLoss", "TripletMarginWithDistanceLoss",
    "MultiLabelSoftMarginLoss", "SoftMarginLoss", "CTCLoss", "RNNTLoss",
    "PoissonNLLLoss", "GaussianNLLLoss", "MultiMarginLoss",
]


class CrossEntropyLoss(Layer):
    def __init__(self, weight=None, ignore_index=-100, reduction="mean", soft_label=False,
                 axis=-1, use_softmax=True, label_smoothing=0.0, name=None):
        super().__init__()
        self.kw = dict(weight=weight, ignore_index=ignore_index, reduction=reduction,
                       soft_label=soft_label, axis=axis, use_softmax=use_softmax,
                       label_smoothing=label_smoothing)

    def forward(self, input, label):
        return F.cross_entropy(input, label, **self.kw)


def _wrap(name, fn, **defaults):
    class _Loss(Layer):
        def __init__(self, **kwargs):
            super().__init__()
            kwargs.pop("name", None)
            params = dict(defaults)
            params.update({k: v for k, v in kwargs.items() if k in params})
            self.kw = params

        def forward(self, *args):
            return fn(*args, **self.kw)

    _Loss.__name__ = name
    _Loss.__qualname__ = name
    return _Loss


MSELoss = _wrap("MSELoss", F.mse_loss, reduction="mean")
L1Loss = _wrap("L1Loss", F.l1_loss, reduction="mean")
NLLLoss = _wrap("NLLLoss", F.nll_loss, weight=None, ignore_index=-100, reduction="mean")
BCELoss = _wrap("BCELoss", F.binary_cross_entropy, weight=None, reduction="mean")
BCEWithLogitsLoss = _wrap("BCEWithLogitsLoss", F.binary_cross_entropy_with_logits,
                          weight=None, reduction="mean", pos_weight=None)
KLDivLoss = _wrap("KLDivLoss", F.kl_div, reduction="mean", log_target=False)
SmoothL1Loss = _wrap("SmoothL1Loss", F.smooth_l1_loss, reduction="mean", delta=1.0)
HuberLoss = _wrap("HuberLoss", F.huber_loss, delta=1.0, reduction="mean")
MarginRankingLoss = _wrap("MarginRankingLoss", F.margin_ranking_loss, margin=0.0, reduction="mean")
CosineEmbeddingLoss = _wrap("CosineEmbeddingLoss", F.cosine_embedding_loss, margin=0, reduction="mean")
HingeEmbeddingLoss = _wrap("HingeEmbeddingLoss", F.hinge_embedding_loss, margin=1.0, reduction="mean")
TripletMarginLoss = _wrap("TripletMarginLoss", F.triplet_margin_loss, margin=1.0, p=2.0,
                          epsilon=1e-06, swap=False, reduction="mean")
TripletMarginWithDistanceLoss = _wrap("TripletMarginWithDistanceLoss",
                                      F.triplet_margin_with_distance_loss,
                                      distance_function=None, margin=1.0, swap=False, reduction="mean")
MultiLabelSoftMarginLoss = _wrap("MultiLabelSoftMarginLoss", F.multi_label_soft_margin_loss,
                                 weight=None, reduction="mean")
SoftMarginLoss = _wrap("SoftMarginLoss", F.soft_margin_loss, reduction="mean")
PoissonNLLLoss = _wrap("PoissonNLLLoss", F.poisson_nll_loss, log_input=True, full=False,
                       epsilon=1e-8, reduction="mean")
GaussianNLLLoss = _wrap("GaussianNLLLoss", F.gaussian_nll_loss, full=False, epsilon=1e-6, reduction="mean")
MultiMarginLoss = _wrap("MultiMarginLoss", F.multi_margin_loss, p=1, margin=1.0, weight=None, reduction="mean")


class CTCLoss(Layer):
    def __init__(self, blank=0, reduction="mean"):
        super().__init__()
        self.blank = blank
        self.reduction = reduction

    def forward(self, log_probs, labels, input_lengths, label_lengths, norm_by_times=False):
        return F.ctc_loss(log_probs, labels, input_lengths, label_lengths,
                          blank=self.blank, reduction=self.reduction, norm_by_times=norm_by_times)


class RNNTLoss(Layer):
    def __init__(self, blank=0, fastemit_lambda=0.001, reduction="mean", name=None):
        super().__init__()
        self.blank = blank
        self.fastemit_lambda = fastemit_lambda
        self.reduction = reduction

    def forward(self, input, label, input_lengths, label_lengths):
        return F.rnnt_loss(input, label, input_lengths, label_lengths,
                           blank=self.blank, fastemit_lambda=self.fastemit_lambda,
                           reduction=self.reduction)
