"""Layer: the module base class.

Reference: python/paddle/nn/layer/layers.py:351 `class Layer` — parameter /
buffer / sublayer registries, hooks, state_dict, train/eval. The TPU-native
Layer keeps the exact user contract; parameters hold `jax.Array`s and the
whole tree can be flattened to a pytree for jit/pjit (`raw_state` /
`load_raw_state`), which is the functional bridge the distributed trainer
uses.
"""
from __future__ import annotations

import collections
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ...core.tensor import Parameter, Tensor, unwrap
from ...framework import dtype as dtypes


_param_auto_counter = 0


class HookRemoveHelper:
    def __init__(self, hooks, hook_id):
        self._hooks = hooks
        self._hook_id = hook_id

    def remove(self):
        self._hooks.pop(self._hook_id, None)


class Layer:
    """Base class for all neural network layers (paddle.nn.Layer)."""

    def __init__(self, name_scope=None, dtype="float32"):
        object.__setattr__(self, "_parameters", collections.OrderedDict())
        object.__setattr__(self, "_sub_layers", collections.OrderedDict())
        object.__setattr__(self, "_buffers", collections.OrderedDict())
        object.__setattr__(self, "_non_persistable_buffer_names_set", set())
        self.training = True
        self._dtype = dtypes.convert_dtype(dtype)
        self._name_scope = name_scope or type(self).__name__.lower()
        self._forward_pre_hooks = collections.OrderedDict()
        self._forward_post_hooks = collections.OrderedDict()
        self._hook_id = 0
        self._casted_by_pure_fp16 = False

    # ------------------------------------------------------------------
    # attribute magic (reference Layer.__setattr__)
    # ------------------------------------------------------------------
    def __setattr__(self, name: str, value):
        params = self.__dict__.get("_parameters")
        layers = self.__dict__.get("_sub_layers")
        buffers = self.__dict__.get("_buffers")
        if isinstance(value, Parameter):
            if params is None:
                raise RuntimeError("call Layer.__init__ before assigning parameters")
            for d in (layers, buffers):
                if d is not None:
                    d.pop(name, None)
            params[name] = value
            self.__dict__.pop(name, None)
        elif isinstance(value, Layer):
            if layers is None:
                raise RuntimeError("call Layer.__init__ before assigning sublayers")
            for d in (params, buffers):
                if d is not None:
                    d.pop(name, None)
            layers[name] = value
            self.__dict__.pop(name, None)
        elif isinstance(value, Tensor) and buffers is not None and name in buffers:
            buffers[name] = value
        else:
            if params is not None:
                params.pop(name, None)
            if layers is not None:
                layers.pop(name, None)
            object.__setattr__(self, name, value)

    def __getattr__(self, name: str):
        for store in ("_parameters", "_sub_layers", "_buffers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                return d[name]
        raise AttributeError(f"'{type(self).__name__}' object has no attribute '{name}'")

    def __delattr__(self, name):
        for store in ("_parameters", "_sub_layers", "_buffers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                del d[name]
                return
        object.__delattr__(self, name)

    def __dir__(self):
        return list(super().__dir__()) + list(self._parameters) + list(self._sub_layers) + list(self._buffers)

    # ------------------------------------------------------------------
    # forward plumbing
    # ------------------------------------------------------------------
    def forward(self, *inputs, **kwargs):
        raise NotImplementedError

    def __call__(self, *inputs, **kwargs):
        for hook in list(self._forward_pre_hooks.values()):
            out = hook(self, inputs)
            if out is not None:
                inputs = out if isinstance(out, tuple) else (out,)
        outputs = self.forward(*inputs, **kwargs)
        for hook in list(self._forward_post_hooks.values()):
            o = hook(self, inputs, outputs)
            if o is not None:
                outputs = o
        return outputs

    def register_forward_pre_hook(self, hook) -> HookRemoveHelper:
        self._hook_id += 1
        self._forward_pre_hooks[self._hook_id] = hook
        return HookRemoveHelper(self._forward_pre_hooks, self._hook_id)

    def register_forward_post_hook(self, hook) -> HookRemoveHelper:
        self._hook_id += 1
        self._forward_post_hooks[self._hook_id] = hook
        return HookRemoveHelper(self._forward_post_hooks, self._hook_id)

    # ------------------------------------------------------------------
    # parameter / buffer management
    # ------------------------------------------------------------------
    def create_parameter(
        self,
        shape,
        attr=None,
        dtype=None,
        is_bias=False,
        default_initializer=None,
    ) -> Parameter:
        """Reference: Layer.create_parameter (layers.py) — honours ParamAttr
        (initializer, trainable, name) or a default initializer."""
        from ..initializer import Constant, XavierNormal, _resolve_param_attr

        dtype = dtypes.convert_dtype(dtype) or self._dtype
        attr = _resolve_param_attr(attr)
        init = None
        trainable = True
        name = None
        lr = 1.0
        if attr is not None:
            init = attr.initializer
            trainable = attr.trainable
            name = attr.name
            lr = attr.learning_rate
        if init is None:
            init = default_initializer or (Constant(0.0) if is_bias else XavierNormal())
        arr = init(tuple(int(s) for s in shape), dtype)
        if name is None:
            # reference Parameters always carry an auto-generated unique
            # name ("linear_0.w_0", LayerHelper naming) assigned at
            # CREATION — caller-independent, so name-keyed configs
            # (apply_decay_param_fun, no-clip lists) bind identically in
            # the eager and fused optimizer paths
            global _param_auto_counter
            name = (f"{type(self).__name__.lower()}_{_param_auto_counter}"
                    f".{'b' if is_bias else 'w'}_0")
            _param_auto_counter += 1
        p = Parameter(arr, dtype=dtype, name=name, trainable=trainable)
        p.optimize_attr["learning_rate"] = lr
        return p

    def add_parameter(self, name: str, parameter: Optional[Parameter]):
        if parameter is None:
            self._parameters[name] = None
        else:
            self._parameters[name] = parameter
        return parameter

    def add_sublayer(self, name: str, sublayer: "Layer"):
        self._sub_layers[str(name)] = sublayer
        return sublayer

    def register_buffer(self, name: str, tensor: Optional[Tensor], persistable: bool = True):
        self._buffers[name] = tensor
        if not persistable:
            self._non_persistable_buffer_names_set.add(name)
        return tensor

    # ------------------------------------------------------------------
    # traversal
    # ------------------------------------------------------------------
    def named_sublayers(self, prefix="", include_self=False, layers_set=None) -> Iterator[Tuple[str, "Layer"]]:
        if layers_set is None:
            layers_set = set()
        if include_self and id(self) not in layers_set:
            layers_set.add(id(self))
            yield prefix, self
        for name, layer in self._sub_layers.items():
            if layer is None:
                continue
            sub_prefix = prefix + ("." if prefix else "") + name
            yield from layer.named_sublayers(prefix=sub_prefix, include_self=True, layers_set=layers_set)

    def sublayers(self, include_self=False) -> List["Layer"]:
        return [l for _, l in self.named_sublayers(include_self=include_self)]

    def children(self) -> Iterator["Layer"]:
        for _, l in self.named_children():
            yield l

    def named_children(self) -> Iterator[Tuple[str, "Layer"]]:
        seen = set()
        for name, l in self._sub_layers.items():
            if l is not None and id(l) not in seen:
                seen.add(id(l))
                yield name, l

    def named_parameters(self, prefix="", include_sublayers=True) -> Iterator[Tuple[str, Parameter]]:
        params_set = set()
        layers = (
            self.named_sublayers(prefix=prefix, include_self=True)
            if include_sublayers
            else [(prefix, self)]
        )
        for layer_prefix, layer in layers:
            for name, p in layer._parameters.items():
                if p is None or id(p) in params_set:
                    continue
                params_set.add(id(p))
                yield layer_prefix + ("." if layer_prefix else "") + name, p

    def parameters(self, include_sublayers=True) -> List[Parameter]:
        return [p for _, p in self.named_parameters(include_sublayers=include_sublayers)]

    def named_buffers(self, prefix="", include_sublayers=True) -> Iterator[Tuple[str, Tensor]]:
        buf_set = set()
        layers = (
            self.named_sublayers(prefix=prefix, include_self=True)
            if include_sublayers
            else [(prefix, self)]
        )
        for layer_prefix, layer in layers:
            for name, b in layer._buffers.items():
                if b is None or id(b) in buf_set:
                    continue
                buf_set.add(id(b))
                yield layer_prefix + ("." if layer_prefix else "") + name, b

    def buffers(self, include_sublayers=True) -> List[Tensor]:
        return [b for _, b in self.named_buffers(include_sublayers=include_sublayers)]

    def apply(self, fn: Callable[["Layer"], None]) -> "Layer":
        for l in self.children():
            l.apply(fn)
        fn(self)
        return self

    # ------------------------------------------------------------------
    # modes
    # ------------------------------------------------------------------
    def train(self):
        self.training = True
        for l in self.sublayers():
            l.training = True
        return self

    def eval(self):
        self.training = False
        for l in self.sublayers():
            l.training = False
        return self

    # ------------------------------------------------------------------
    # state dict
    # ------------------------------------------------------------------
    def state_dict(self, destination=None, include_sublayers=True, structured_name_prefix="", use_hook=True):
        dest = destination if destination is not None else collections.OrderedDict()
        for name, p in self.named_parameters(include_sublayers=include_sublayers):
            dest[structured_name_prefix + name] = p
        for name, b in self.named_buffers(include_sublayers=include_sublayers):
            leaf = name.rsplit(".", 1)[-1]
            # skip non-persistable buffers of any sublayer
            owner = self
            if "." in name:
                for part in name.split(".")[:-1]:
                    owner = owner._sub_layers[part]
            if leaf in owner._non_persistable_buffer_names_set:
                continue
            dest[structured_name_prefix + name] = b
        return dest

    def set_state_dict(self, state_dict, use_structured_name=True):
        """Reference: Layer.set_state_dict (layers.py). Matches by structured
        name; shape-checks each entry."""
        own = self.state_dict()
        missing, unexpected = [], []
        for k, v in state_dict.items():
            if k not in own:
                unexpected.append(k)
                continue
            tgt = own[k]
            arr = unwrap(v) if isinstance(v, Tensor) else jnp.asarray(v)
            if tuple(arr.shape) != tuple(tgt.shape):
                raise ValueError(
                    f"shape mismatch for {k}: checkpoint {tuple(arr.shape)} vs model {tuple(tgt.shape)}"
                )
            tgt._replace(arr.astype(tgt._array.dtype))
        for k in own:
            if k not in state_dict:
                missing.append(k)
        return missing, unexpected

    load_dict = set_state_dict

    # ------------------------------------------------------------------
    # functional bridge (TPU-native addition)
    # ------------------------------------------------------------------
    def raw_state(self) -> Dict[str, jax.Array]:
        """Flatten params+buffers to a dict of jax arrays (a pytree) for
        jit/pjit functional training."""
        out = {}
        for k, p in self.named_parameters():
            out[k] = p._array
        for k, b in self.named_buffers():
            out.setdefault(k, b._array)
        return out

    def load_raw_state(self, state: Dict[str, jax.Array]):
        for k, p in self.named_parameters():
            if k in state:
                p._array = state[k]
        for k, b in self.named_buffers():
            if k in state:
                b._array = state[k]
        return self

    def func_call(self, state: Dict[str, jax.Array], *args, training=None, **kwargs):
        """Run forward as a pure function of `state` (used under jit/pjit).

        Temporarily binds `state` into the parameter objects; safe under
        tracing because binding is per-call and restored in `finally`.
        """
        named_p = dict(self.named_parameters())
        named_b = dict(self.named_buffers())
        saved = {k: v._array for k, v in {**named_p, **named_b}.items()}
        prev_training = self.training
        try:
            if training is not None:
                self.train() if training else self.eval()
            for k, v in state.items():
                if k in named_p:
                    named_p[k]._array = v
                elif k in named_b:
                    named_b[k]._array = v
            return self(*args, **kwargs)
        finally:
            for k, t in {**named_p, **named_b}.items():
                t._array = saved[k]
            self.training = prev_training
            if training is not None:
                self.train() if prev_training else self.eval()

    # ------------------------------------------------------------------
    # dtype / device movement
    # ------------------------------------------------------------------
    def to(self, device=None, dtype=None, blocking=None):
        if dtype is not None:
            d = dtypes.convert_dtype(dtype)
            for p in self.parameters():
                p._array = p._array.astype(d)
            for b in self.buffers():
                if jnp.issubdtype(b._array.dtype, jnp.floating):
                    b._array = b._array.astype(d)
            for l in self.sublayers(include_self=True):
                l._dtype = d
        return self

    def astype(self, dtype):
        return self.to(dtype=dtype)

    def float(self):
        return self.to(dtype="float32")

    def half(self):
        return self.to(dtype="float16")

    def bfloat16(self):
        return self.to(dtype="bfloat16")

    def clear_gradients(self):
        for p in self.parameters():
            p.clear_grad()

    def full_name(self):
        return self._name_scope

    def extra_repr(self) -> str:
        return ""

    def __repr__(self):
        extra = self.extra_repr()
        lines = [extra] if extra else []
        for name, layer in self._sub_layers.items():
            rep = repr(layer).split("\n")
            head = f"({name}): {rep[0]}"
            lines.append(head)
            lines.extend("  " + r for r in rep[1:])
        body = "\n  ".join(lines)
        return f"{type(self).__name__}({body})" if lines else f"{type(self).__name__}()"
