"""Remaining nn layer surface (reference: python/paddle/nn/layer —
FeatureAlphaDropout, FractionalMaxPool2D/3D, ZeroPad1D/3D, HSigmoidLoss,
AdaptiveLogSoftmaxWithLoss) plus the seq2seq decoding API
(BeamSearchDecoder + dynamic_decode, reference: nn/decode.py)."""
from __future__ import annotations

import numpy as np

from ...core.tensor import Tensor, unwrap
from .. import functional as F
from .layers import Layer

__all__ = ["FeatureAlphaDropout", "FractionalMaxPool2D",
           "FractionalMaxPool3D", "ZeroPad1D", "ZeroPad3D", "HSigmoidLoss",
           "AdaptiveLogSoftmaxWithLoss", "BeamSearchDecoder",
           "dynamic_decode"]


class FeatureAlphaDropout(Layer):
    def __init__(self, p=0.5, name=None):
        super().__init__()
        self.p = p

    def forward(self, x):
        return F.feature_alpha_dropout(x, p=self.p, training=self.training)


class FractionalMaxPool2D(Layer):
    def __init__(self, output_size, kernel_size=None, random_u=None,
                 return_mask=False, name=None):
        super().__init__()
        self.output_size = output_size
        self.random_u = random_u
        self.return_mask = return_mask

    def forward(self, x):
        return F.fractional_max_pool2d(x, self.output_size,
                                       random_u=self.random_u,
                                       return_mask=self.return_mask)


class FractionalMaxPool3D(Layer):
    def __init__(self, output_size, kernel_size=None, random_u=None,
                 return_mask=False, name=None):
        super().__init__()
        self.output_size = output_size
        self.random_u = random_u
        self.return_mask = return_mask

    def forward(self, x):
        return F.fractional_max_pool3d(x, self.output_size,
                                       random_u=self.random_u,
                                       return_mask=self.return_mask)


class _ZeroPadN(Layer):
    spatial = 1
    default_format = "NCL"

    def __init__(self, padding, data_format=None, name=None):
        super().__init__()
        if isinstance(padding, int):
            padding = [padding] * (2 * self.spatial)
        self.padding = list(padding)
        self.data_format = data_format or self.default_format

    def forward(self, x):
        return F.pad(x, self.padding, mode="constant", value=0.0,
                     data_format=self.data_format)


class ZeroPad1D(_ZeroPadN):
    """reference: nn/layer/common.py ZeroPad1D — NCL (or NLC) padding."""
    spatial = 1
    default_format = "NCL"


class ZeroPad3D(_ZeroPadN):
    """reference: nn/layer/common.py ZeroPad3D — NCDHW (or NDHWC)
    padding."""
    spatial = 3
    default_format = "NCDHW"


class HSigmoidLoss(Layer):
    """reference: nn/layer/loss.py HSigmoidLoss."""

    def __init__(self, feature_size, num_classes, weight_attr=None,
                 bias_attr=None, is_custom=False, is_sparse=False,
                 name=None):
        super().__init__()
        self.num_classes = num_classes
        n_nodes = num_classes - 1
        self.weight = self.create_parameter((n_nodes, feature_size),
                                            attr=weight_attr)
        self.bias = None if bias_attr is False else self.create_parameter(
            (n_nodes, 1), attr=bias_attr, is_bias=True)

    def forward(self, input, label, path_table=None, path_code=None):
        bias = None if self.bias is None else self.bias.reshape([-1])
        return F.hsigmoid_loss(input, label, self.num_classes, self.weight,
                               bias=bias, path_table=path_table,
                               path_code=path_code)


class AdaptiveLogSoftmaxWithLoss(Layer):
    """reference: nn/layer/loss.py AdaptiveLogSoftmaxWithLoss — head over
    frequent classes + shortlist cluster tokens; tail clusters project to
    in_features / div_value**i."""

    def __init__(self, in_features, n_classes, cutoffs, div_value=4.0,
                 head_bias=False, weight_attr=None, bias_attr=None,
                 name=None):
        super().__init__()
        cutoffs = list(cutoffs)
        if any(c <= 0 or c >= n_classes for c in cutoffs) or \
                sorted(set(cutoffs)) != cutoffs:
            raise ValueError("cutoffs must be increasing, in (0, n_classes)")
        self.in_features = in_features
        self.n_classes = n_classes
        self.cutoffs = cutoffs + [n_classes]
        self.div_value = div_value
        n_clusters = len(self.cutoffs) - 1
        self.head_weight = self.create_parameter(
            (in_features, self.cutoffs[0] + n_clusters), attr=weight_attr)
        self.head_bias = self.create_parameter(
            (self.cutoffs[0] + n_clusters,), is_bias=True) \
            if head_bias else None
        self.tail_weights = []
        for i in range(n_clusters):
            proj = max(1, int(in_features / (div_value ** (i + 1))))
            sz = self.cutoffs[i + 1] - self.cutoffs[i]
            p1 = self.create_parameter((in_features, proj))
            p2 = self.create_parameter((proj, sz))
            self.add_parameter(f"tail_{i}_proj", p1)
            self.add_parameter(f"tail_{i}_out", p2)
            self.tail_weights.append([p1, p2])

    def forward(self, input, label):
        return F.adaptive_log_softmax_with_loss(
            input, label, self.head_weight, self.tail_weights,
            self.cutoffs[:-1], head_bias=self.head_bias)

    def log_prob(self, input):
        """Full [N, n_classes] log-probability table."""
        import jax
        import jax.numpy as jnp

        xa = unwrap(input)
        hw = unwrap(self.head_weight)
        logits = xa @ hw
        if self.head_bias is not None:
            logits = logits + unwrap(self.head_bias)
        head_logp = jax.nn.log_softmax(logits, axis=-1)
        shortlist = self.cutoffs[0]
        parts = [head_logp[:, :shortlist]]
        for i, (p1, p2) in enumerate(self.tail_weights):
            tail_logp = jax.nn.log_softmax(
                (xa @ unwrap(p1)) @ unwrap(p2), axis=-1)
            parts.append(head_logp[:, shortlist + i:shortlist + i + 1]
                         + tail_logp)
        return Tensor(jnp.concatenate(parts, axis=-1))

    def predict(self, input):
        lp = self.log_prob(input)
        return lp.argmax(-1)


class BeamSearchDecoder:
    """reference: nn/decode.py BeamSearchDecoder — beam expansion over an
    RNN cell; finalize backtracks with gather_tree. Runs eagerly step by
    step (the reference's dynamic-graph mode); `dynamic_decode` drives it.
    """

    def __init__(self, cell, start_token, end_token, beam_size,
                 embedding_fn=None, output_fn=None):
        self.cell = cell
        self.start_token = int(start_token)
        self.end_token = int(end_token)
        self.beam_size = int(beam_size)
        self.embedding_fn = embedding_fn
        self.output_fn = output_fn

    def _merge(self, t):
        a = np.asarray(unwrap(t))
        return a.reshape((-1,) + a.shape[2:])

    def _split(self, a, batch):
        a = np.asarray(a)
        return a.reshape((batch, self.beam_size) + a.shape[1:])

    def initialize(self, initial_cell_states):
        states = initial_cell_states
        leaves = [np.asarray(unwrap(s)) for s in _flatten(states)]
        batch = leaves[0].shape[0]
        # tile cell state across beams
        tiled = [np.repeat(a[:, None], self.beam_size, 1)
                 .reshape((-1,) + a.shape[1:]) for a in leaves]
        log_probs = np.full((batch, self.beam_size), -1e9, np.float32)
        log_probs[:, 0] = 0.0
        ids = np.full((batch, self.beam_size), self.start_token, np.int64)
        finished = np.zeros((batch, self.beam_size), bool)
        return (ids, tiled, log_probs, finished)

    def step(self, inputs, states):
        ids, cell_states, log_probs, finished = states
        batch = ids.shape[0]
        flat_ids = Tensor(ids.reshape(-1))
        emb = self.embedding_fn(flat_ids) if self.embedding_fn else flat_ids
        cell_in = [Tensor(a) for a in cell_states]
        out, new_states = self.cell(emb, cell_in[0] if len(cell_in) == 1
                                    else cell_in)
        if self.output_fn is not None:
            out = self.output_fn(out)
        logits = np.asarray(unwrap(out))
        vocab = logits.shape[-1]
        step_logp = logits - _logsumexp(logits)
        step_logp = self._split(step_logp, batch)  # [B, beam, V]
        # finished beams only extend with end_token at 0 cost
        fin_mask = np.full((vocab,), -1e9, np.float32)
        fin_mask[self.end_token] = 0.0
        step_logp = np.where(finished[..., None], fin_mask[None, None],
                             step_logp)
        total = log_probs[..., None] + step_logp  # [B, beam, V]
        flat = total.reshape(batch, -1)
        top = np.argsort(-flat, axis=-1)[:, : self.beam_size]
        new_logp = np.take_along_axis(flat, top, -1)
        parent = (top // vocab).astype(np.int64)
        token = (top % vocab).astype(np.int64)
        new_finished = np.take_along_axis(finished, parent, -1) | \
            (token == self.end_token)
        # reorder cell states by parent beam
        new_cell = []
        flat_new = _flatten(new_states)
        for a in flat_new:
            a = self._split(np.asarray(unwrap(a)), batch)
            gather = np.take_along_axis(
                a, parent.reshape(parent.shape + (1,) * (a.ndim - 2)), 1)
            new_cell.append(gather.reshape((-1,) + a.shape[2:]))
        return (token, parent, new_logp), \
            (token, new_cell, new_logp, new_finished)

    def finalize(self, step_tokens, step_parents):
        ids = Tensor(np.stack(step_tokens))      # [T, B, beam]
        parents = Tensor(np.stack(step_parents))
        return F.gather_tree(ids, parents)


def _flatten(x):
    if isinstance(x, (list, tuple)):
        out = []
        for i in x:
            out.extend(_flatten(i))
        return out
    return [x]


def _logsumexp(a):
    m = a.max(-1, keepdims=True)
    return m + np.log(np.exp(a - m).sum(-1, keepdims=True))


def dynamic_decode(decoder, inits=None, max_step_num=None, output_time_major=False,
                   impute_finished=False, is_test=False,
                   return_length=False, **kwargs):
    """reference: nn/decode.py dynamic_decode — drive a decoder until all
    beams finish or max_step_num. Returns (ids [B, T, beam] (or
    time-major), final log-probs) [+ lengths]."""
    states = decoder.initialize(inits)
    tokens, parents = [], []
    lengths = None
    max_steps = max_step_num or 100
    logp = None
    for step in range(max_steps):
        prev_finished = states[3]
        (tok, par, logp), states = decoder.step(None, states)
        tokens.append(tok)
        parents.append(par)
        finished = states[3]
        if lengths is None:
            lengths = np.zeros(finished.shape, np.int64)
        # a beam's length includes the step where it emits end_token:
        # count every step taken while it was still unfinished
        lengths = np.where(~prev_finished, step + 1, lengths)
        if finished.all():
            break
    ids = decoder.finalize(tokens, parents)  # [T, B, beam]
    out = ids if output_time_major else Tensor(
        np.asarray(unwrap(ids)).transpose(1, 0, 2))
    res = (out, Tensor(logp))
    if return_length:
        res = res + (Tensor(lengths),)
    return res
