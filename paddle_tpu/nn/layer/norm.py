"""Norm layers (reference: python/paddle/nn/layer/norm.py)."""
from __future__ import annotations

import jax.numpy as jnp

from ...core.tensor import Tensor
from .. import functional as F
from ..initializer import Constant
from .layers import Layer

__all__ = [
    "BatchNorm", "BatchNorm1D", "BatchNorm2D", "BatchNorm3D", "SyncBatchNorm",
    "LayerNorm", "GroupNorm", "InstanceNorm1D", "InstanceNorm2D", "InstanceNorm3D",
    "LocalResponseNorm", "SpectralNorm", "RMSNorm",
]


class _BatchNormBase(Layer):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-05, weight_attr=None,
                 bias_attr=None, data_format="NCHW", use_global_stats=None, name=None):
        super().__init__()
        self._num_features = num_features
        self._momentum = momentum
        self._epsilon = epsilon
        self._data_format = data_format
        self._use_global_stats = use_global_stats
        if weight_attr is False:
            self.weight = None
            self.add_parameter("weight", None)
        else:
            self.weight = self.create_parameter((num_features,), attr=weight_attr, default_initializer=Constant(1.0))
        if bias_attr is False:
            self.bias = None
            self.add_parameter("bias", None)
        else:
            self.bias = self.create_parameter((num_features,), attr=bias_attr, is_bias=True)
        self.register_buffer("_mean", Tensor(jnp.zeros(num_features, jnp.float32)))
        self.register_buffer("_variance", Tensor(jnp.ones(num_features, jnp.float32)))

    def forward(self, x):
        return F.batch_norm(
            x, self._mean, self._variance, self.weight, self.bias,
            training=self.training, momentum=self._momentum, epsilon=self._epsilon,
            data_format=self._data_format, use_global_stats=self._use_global_stats,
        )

    def extra_repr(self):
        return f"num_features={self._num_features}, momentum={self._momentum}, epsilon={self._epsilon}"


class BatchNorm(_BatchNormBase):
    pass


class BatchNorm1D(_BatchNormBase):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-05, weight_attr=None,
                 bias_attr=None, data_format="NCL", use_global_stats=None, name=None):
        super().__init__(num_features, momentum, epsilon, weight_attr, bias_attr, data_format, use_global_stats)


class BatchNorm2D(_BatchNormBase):
    pass


class BatchNorm3D(_BatchNormBase):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-05, weight_attr=None,
                 bias_attr=None, data_format="NCDHW", use_global_stats=None, name=None):
        super().__init__(num_features, momentum, epsilon, weight_attr, bias_attr, data_format, use_global_stats)


class SyncBatchNorm(_BatchNormBase):
    """Cross-replica BN (reference: nn/layer/norm.py SyncBatchNorm backed by
    sync_batch_norm kernels). Under pjit/shard_map, XLA already aggregates
    batch statistics globally when the batch axis is sharded and the reduction
    is global — so the dense-compute path is identical; an explicit psum path
    is used inside shard_map contexts."""

    @classmethod
    def convert_sync_batchnorm(cls, layer):
        if isinstance(layer, _BatchNormBase) and not isinstance(layer, SyncBatchNorm):
            new = SyncBatchNorm(layer._num_features, layer._momentum, layer._epsilon,
                                data_format=layer._data_format)
            if layer.weight is not None:
                new.weight.set_value(layer.weight)
            if layer.bias is not None:
                new.bias.set_value(layer.bias)
            new._mean.set_value(layer._mean)
            new._variance.set_value(layer._variance)
            return new
        for name, sub in list(layer._sub_layers.items()):
            layer._sub_layers[name] = cls.convert_sync_batchnorm(sub)
        return layer


class LayerNorm(Layer):
    def __init__(self, normalized_shape, epsilon=1e-05, weight_attr=None, bias_attr=None, name=None):
        super().__init__()
        ns = normalized_shape if isinstance(normalized_shape, (list, tuple)) else [normalized_shape]
        self._normalized_shape = list(ns)
        self._epsilon = epsilon
        if weight_attr is False:
            self.weight = None
            self.add_parameter("weight", None)
        else:
            self.weight = self.create_parameter(tuple(ns), attr=weight_attr, default_initializer=Constant(1.0))
        if bias_attr is False:
            self.bias = None
            self.add_parameter("bias", None)
        else:
            self.bias = self.create_parameter(tuple(ns), attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.layer_norm(x, self._normalized_shape, self.weight, self.bias, self._epsilon)

    def extra_repr(self):
        return f"normalized_shape={self._normalized_shape}, epsilon={self._epsilon}"


class RMSNorm(Layer):
    """RMSNorm layer — first-class here because it is the Llama-family norm
    (reference exposes it as incubate fused_rms_norm)."""

    def __init__(self, hidden_size, epsilon=1e-6, weight_attr=None, name=None):
        super().__init__()
        self._epsilon = epsilon
        self.weight = self.create_parameter((hidden_size,), attr=weight_attr, default_initializer=Constant(1.0))

    def forward(self, x):
        return F.rms_norm(x, self.weight, self._epsilon)


class GroupNorm(Layer):
    def __init__(self, num_groups, num_channels, epsilon=1e-05, weight_attr=None,
                 bias_attr=None, data_format="NCHW", name=None):
        super().__init__()
        self._num_groups = num_groups
        self._epsilon = epsilon
        self._data_format = data_format
        if weight_attr is False:
            self.weight = None
            self.add_parameter("weight", None)
        else:
            self.weight = self.create_parameter((num_channels,), attr=weight_attr, default_initializer=Constant(1.0))
        if bias_attr is False:
            self.bias = None
            self.add_parameter("bias", None)
        else:
            self.bias = self.create_parameter((num_channels,), attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.group_norm(x, self._num_groups, self._epsilon, self.weight, self.bias, self._data_format)


class _InstanceNormBase(Layer):
    def __init__(self, num_features, epsilon=1e-05, momentum=0.9, weight_attr=None,
                 bias_attr=None, data_format="NCHW", name=None):
        super().__init__()
        self._epsilon = epsilon
        self._data_format = data_format
        if weight_attr is False:
            self.weight = None
            self.add_parameter("weight", None)
        else:
            self.weight = self.create_parameter((num_features,), attr=weight_attr, default_initializer=Constant(1.0))
        if bias_attr is False:
            self.bias = None
            self.add_parameter("bias", None)
        else:
            self.bias = self.create_parameter((num_features,), attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.instance_norm(x, weight=self.weight, bias=self.bias, eps=self._epsilon,
                               data_format=self._data_format)


class InstanceNorm1D(_InstanceNormBase):
    pass


class InstanceNorm2D(_InstanceNormBase):
    pass


class InstanceNorm3D(_InstanceNormBase):
    pass


class LocalResponseNorm(Layer):
    def __init__(self, size, alpha=0.0001, beta=0.75, k=1.0, data_format="NCHW", name=None):
        super().__init__()
        self.args = (size, alpha, beta, k, data_format)

    def forward(self, x):
        return F.local_response_norm(x, *self.args)


class SpectralNorm(Layer):
    """Power-iteration spectral norm of a weight (reference:
    nn/layer/norm.py SpectralNorm)."""

    def __init__(self, weight_shape, dim=0, power_iters=1, epsilon=1e-12, dtype="float32"):
        super().__init__()
        self._dim = dim
        self._power_iters = power_iters
        self._epsilon = epsilon
        h = weight_shape[dim]
        w = 1
        for i, s in enumerate(weight_shape):
            if i != dim:
                w *= s
        import jax

        from ...framework.random import next_key

        self.weight_u = Tensor(jax.random.normal(next_key(), (h,), jnp.float32), stop_gradient=True)
        self.weight_v = Tensor(jax.random.normal(next_key(), (w,), jnp.float32), stop_gradient=True)

    def forward(self, weight):
        from ...core.tensor import dispatch

        dim, iters, eps = self._dim, self._power_iters, self._epsilon

        def impl(w, u0, v0):
            wm = jnp.moveaxis(w, dim, 0).reshape(w.shape[dim], -1)
            u, v = u0, v0
            for _ in range(iters):
                v = wm.T @ u
                v = v / (jnp.linalg.norm(v) + eps)
                u = wm @ v
                u = u / (jnp.linalg.norm(u) + eps)
            sigma = u @ wm @ v
            return w / sigma

        return dispatch("spectral_norm", impl, (weight, self.weight_u, self.weight_v))
