"""Conv layers (reference: python/paddle/nn/layer/conv.py)."""
from __future__ import annotations

import numpy as np

from .. import functional as F
from ..initializer import XavierNormal
from .layers import Layer

__all__ = ["Conv1D", "Conv2D", "Conv3D", "Conv1DTranspose", "Conv2DTranspose", "Conv3DTranspose"]


def _tup(v, n):
    return tuple(v) if isinstance(v, (list, tuple)) else (v,) * n


class _ConvNd(Layer):
    def __init__(self, n, in_channels, out_channels, kernel_size, stride=1, padding=0,
                 dilation=1, groups=1, padding_mode="zeros", weight_attr=None, bias_attr=None,
                 data_format=None, transposed=False, output_padding=0):
        super().__init__()
        self._n = n
        self._in_channels = in_channels
        self._out_channels = out_channels
        self._kernel_size = _tup(kernel_size, n)
        self._stride = stride
        self._padding = padding
        self._dilation = dilation
        self._groups = groups
        self._padding_mode = padding_mode
        self._data_format = data_format or ("NCL" if n == 1 else "NCHW" if n == 2 else "NCDHW")
        self._transposed = transposed
        self._output_padding = output_padding
        if transposed:
            w_shape = (in_channels, out_channels // groups) + self._kernel_size
        else:
            w_shape = (out_channels, in_channels // groups) + self._kernel_size
        self.weight = self.create_parameter(w_shape, attr=weight_attr, default_initializer=XavierNormal())
        if bias_attr is False:
            self.bias = None
            self.add_parameter("bias", None)
        else:
            self.bias = self.create_parameter((out_channels,), attr=bias_attr, is_bias=True)

    def extra_repr(self):
        return (
            f"{self._in_channels}, {self._out_channels}, kernel_size={self._kernel_size}, "
            f"stride={self._stride}, padding={self._padding}"
        )


def _make_conv_layer(n, name, transposed):
    fns = {
        (1, False): F.conv1d, (2, False): F.conv2d, (3, False): F.conv3d,
        (1, True): F.conv1d_transpose, (2, True): F.conv2d_transpose, (3, True): F.conv3d_transpose,
    }
    fn = fns[(n, transposed)]

    class _Conv(_ConvNd):
        def __init__(self, in_channels, out_channels, kernel_size, stride=1, padding=0,
                     output_padding=0, dilation=1, groups=1, padding_mode="zeros",
                     weight_attr=None, bias_attr=None, data_format=None):
            super().__init__(n, in_channels, out_channels, kernel_size, stride, padding,
                             dilation, groups, padding_mode, weight_attr, bias_attr,
                             data_format, transposed, output_padding)

        def forward(self, x, output_size=None):
            if self._transposed:
                return fn(x, self.weight, self.bias, stride=self._stride, padding=self._padding,
                          output_padding=self._output_padding, groups=self._groups,
                          dilation=self._dilation, data_format=self._data_format,
                          output_size=output_size)
            return fn(x, self.weight, self.bias, stride=self._stride, padding=self._padding,
                      dilation=self._dilation, groups=self._groups, data_format=self._data_format)

    _Conv.__name__ = name
    _Conv.__qualname__ = name
    return _Conv


Conv1D = _make_conv_layer(1, "Conv1D", False)
Conv2D = _make_conv_layer(2, "Conv2D", False)
Conv3D = _make_conv_layer(3, "Conv3D", False)
Conv1DTranspose = _make_conv_layer(1, "Conv1DTranspose", True)
Conv2DTranspose = _make_conv_layer(2, "Conv2DTranspose", True)
Conv3DTranspose = _make_conv_layer(3, "Conv3DTranspose", True)
