"""Common functionals: linear, embedding, dropout, pad, interpolate, one_hot...

Reference: python/paddle/nn/functional/{common,input,extension}.py.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ...core.tensor import Tensor, dispatch, unwrap
from ...framework.random import next_key
from ...framework import dtype as dtypes

__all__ = [
    "linear", "embedding", "one_hot", "dropout", "dropout2d", "dropout3d",
    "alpha_dropout", "pad", "zeropad2d", "cosine_similarity", "pixel_shuffle",
    "pixel_unshuffle", "channel_shuffle", "interpolate", "upsample", "unfold",
    "fold", "label_smooth", "sequence_mask", "normalize", "bilinear",
    "class_center_sample", "grid_sample", "affine_grid", "temporal_shift",
]


def linear(x, weight, bias=None, name=None):
    """y = x @ W + b; W layout [in, out] (reference:
    python/paddle/nn/functional/common.py `linear` -> matmul kernel). Kept as
    a bare jnp.matmul so XLA maps it onto the MXU and fuses the bias add."""
    if bias is None:
        return dispatch("linear", jnp.matmul, (x, weight))
    return dispatch("linear", lambda a, w, b: jnp.matmul(a, w) + b, (x, weight, bias))


def embedding(x, weight, padding_idx=None, sparse=False, name=None):
    """Lookup rows of weight (reference: nn/functional/input.py embedding).

    `sparse` is accepted for API parity; on TPU gather is already the
    efficient lowering (no SelectedRows analog needed).
    """

    def impl(ids, w):
        out = jnp.take(w, ids, axis=0)
        if padding_idx is not None:
            mask = (ids == padding_idx)[..., None]
            out = jnp.where(mask, 0.0, out)
        return out

    return dispatch("embedding", impl, (x, weight))


def one_hot(x, num_classes, name=None):
    return dispatch("one_hot", lambda a: jax.nn.one_hot(a, num_classes, dtype=jnp.float32), (x,))


def dropout(x, p=0.5, axis=None, training=True, mode="upscale_in_train", name=None):
    """Reference: nn/functional/common.py dropout; keys-as-generator RNG."""
    if not training or (isinstance(p, (int, float)) and p == 0):
        return x if isinstance(x, Tensor) else Tensor(x)
    key = next_key()
    p_val = float(unwrap(p)) if not isinstance(p, (int, float)) else float(p)

    def impl(a):
        shape = list(a.shape)
        if axis is not None:
            axes = axis if isinstance(axis, (list, tuple)) else [axis]
            shape = [s if i in [ax % a.ndim for ax in axes] else 1 for i, s in enumerate(a.shape)]
        keep = jax.random.bernoulli(key, 1.0 - p_val, tuple(shape))
        if mode == "upscale_in_train":
            return jnp.where(keep, a / (1.0 - p_val), 0.0).astype(a.dtype)
        return jnp.where(keep, a, 0.0).astype(a.dtype)

    return dispatch("dropout", impl, (x,))


def dropout2d(x, p=0.5, training=True, data_format="NCHW", name=None):
    ax = [0, 1] if data_format == "NCHW" else [0, 3]
    return dropout(x, p=p, axis=ax, training=training)


def dropout3d(x, p=0.5, training=True, data_format="NCDHW", name=None):
    ax = [0, 1] if data_format == "NCDHW" else [0, 4]
    return dropout(x, p=p, axis=ax, training=training)


def alpha_dropout(x, p=0.5, training=True, name=None):
    if not training or p == 0:
        return x
    key = next_key()
    alpha = 1.6732632423543772
    scale = 1.0507009873554805
    alpha_p = -alpha * scale

    def impl(a):
        keep = jax.random.bernoulli(key, 1.0 - p, a.shape)
        q = 1.0 - p
        a_coef = (q + alpha_p**2 * q * p) ** -0.5
        b_coef = -a_coef * alpha_p * p
        return (a_coef * jnp.where(keep, a, alpha_p) + b_coef).astype(a.dtype)

    return dispatch("alpha_dropout", impl, (x,))


def _pad_nd(a, pad_list, mode, value, data_format):
    nd = a.ndim
    if len(pad_list) == 2 * nd:
        # paddle full-form: [[before,after] per dim] flattened low-dim-first?
        pairs = [(pad_list[2 * i], pad_list[2 * i + 1]) for i in range(nd)]
    else:
        # partial form pads the last spatial dims; respect data_format
        k = len(pad_list) // 2
        pairs = [(0, 0)] * nd
        if data_format.startswith("NC"):
            spatial = list(range(2, nd))
        else:
            spatial = list(range(1, nd - 1))
        spatial = spatial[-k:] if k <= len(spatial) else spatial
        # paddle pad order: last-dim pads first in the list? It's
        # [left, right, top, bottom, front, back] => reversed spatial order
        dims = list(reversed(spatial))[:k]
        for i, d in enumerate(dims):
            pairs[d] = (pad_list[2 * i], pad_list[2 * i + 1])
    jmode = {"constant": "constant", "reflect": "reflect", "replicate": "edge", "circular": "wrap"}[mode]
    if jmode == "constant":
        return jnp.pad(a, pairs, mode="constant", constant_values=value)
    return jnp.pad(a, pairs, mode=jmode)


def pad(x, pad, mode="constant", value=0.0, data_format="NCHW", pad_from_left_axis=True, name=None):
    pl = [int(unwrap(p)) for p in (pad.tolist() if isinstance(pad, Tensor) else pad)]
    return dispatch("pad", lambda a: _pad_nd(a, pl, mode, value, data_format), (x,))


def zeropad2d(x, padding, data_format="NCHW", name=None):
    return pad(x, padding, mode="constant", value=0.0, data_format=data_format)


def cosine_similarity(x1, x2, axis=1, eps=1e-8, name=None):
    def impl(a, b):
        dot = jnp.sum(a * b, axis=axis)
        na = jnp.sqrt(jnp.sum(a * a, axis=axis))
        nb = jnp.sqrt(jnp.sum(b * b, axis=axis))
        return dot / jnp.maximum(na * nb, eps)

    return dispatch("cosine_similarity", impl, (x1, x2))


def pixel_shuffle(x, upscale_factor, data_format="NCHW", name=None):
    r = upscale_factor

    def impl(a):
        if data_format == "NCHW":
            n, c, h, w = a.shape
            a = a.reshape(n, c // (r * r), r, r, h, w)
            a = jnp.transpose(a, (0, 1, 4, 2, 5, 3))
            return a.reshape(n, c // (r * r), h * r, w * r)
        n, h, w, c = a.shape
        a = a.reshape(n, h, w, r, r, c // (r * r))
        a = jnp.transpose(a, (0, 1, 3, 2, 4, 5))
        return a.reshape(n, h * r, w * r, c // (r * r))

    return dispatch("pixel_shuffle", impl, (x,))


def pixel_unshuffle(x, downscale_factor, data_format="NCHW", name=None):
    r = downscale_factor

    def impl(a):
        if data_format == "NCHW":
            n, c, h, w = a.shape
            a = a.reshape(n, c, h // r, r, w // r, r)
            a = jnp.transpose(a, (0, 1, 3, 5, 2, 4))
            return a.reshape(n, c * r * r, h // r, w // r)
        n, h, w, c = a.shape
        a = a.reshape(n, h // r, r, w // r, r, c)
        a = jnp.transpose(a, (0, 1, 3, 2, 4, 5))
        return a.reshape(n, h // r, w // r, c * r * r)

    return dispatch("pixel_unshuffle", impl, (x,))


def channel_shuffle(x, groups, data_format="NCHW", name=None):
    def impl(a):
        if data_format == "NCHW":
            n, c, h, w = a.shape
            a = a.reshape(n, groups, c // groups, h, w)
            a = jnp.swapaxes(a, 1, 2)
            return a.reshape(n, c, h, w)
        n, h, w, c = a.shape
        a = a.reshape(n, h, w, groups, c // groups)
        a = jnp.swapaxes(a, 3, 4)
        return a.reshape(n, h, w, c)

    return dispatch("channel_shuffle", impl, (x,))


def interpolate(
    x, size=None, scale_factor=None, mode="nearest", align_corners=False,
    align_mode=0, data_format="NCHW", name=None,
):
    """Reference: nn/functional/common.py interpolate → jax.image.resize."""
    mode = mode.lower()
    method = {
        "nearest": "nearest",
        "bilinear": "linear",
        "trilinear": "linear",
        "bicubic": "cubic",
        "linear": "linear",
        "area": "linear",
    }[mode]

    def impl(a):
        nd = a.ndim
        if data_format.startswith("NC"):
            spatial = list(range(2, nd))
        else:
            spatial = list(range(1, nd - 1))
        if size is not None:
            tgt = [int(unwrap(s)) for s in (size.tolist() if isinstance(size, Tensor) else (size if isinstance(size, (list, tuple)) else [size]))]
        else:
            sf = scale_factor if isinstance(scale_factor, (list, tuple)) else [scale_factor] * len(spatial)
            tgt = [int(a.shape[d] * f) for d, f in zip(spatial, sf)]
        out_shape = list(a.shape)
        for d, s in zip(spatial, tgt):
            out_shape[d] = s
        if mode == "nearest" or not align_corners:
            return jax.image.resize(a, out_shape, method=method).astype(a.dtype)
        # align_corners path: gather with linspace indices
        out = a
        for d, s in zip(spatial, tgt):
            n_in = out.shape[d]
            if s == n_in:
                continue
            idx = jnp.linspace(0.0, n_in - 1, s)
            lo = jnp.floor(idx).astype(jnp.int32)
            hi = jnp.clip(lo + 1, 0, n_in - 1)
            w = (idx - lo).astype(out.dtype)
            shape_w = [1] * out.ndim
            shape_w[d] = s
            w = w.reshape(shape_w)
            out = jnp.take(out, lo, axis=d) * (1 - w) + jnp.take(out, hi, axis=d) * w
        return out.astype(a.dtype)

    return dispatch("interpolate", impl, (x,))


def upsample(x, size=None, scale_factor=None, mode="nearest", align_corners=False, align_mode=0, data_format="NCHW", name=None):
    return interpolate(x, size, scale_factor, mode, align_corners, align_mode, data_format)


def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    """im2col (reference: phi unfold kernel). Output [N, C*kh*kw, L]."""
    ks = kernel_sizes if isinstance(kernel_sizes, (list, tuple)) else [kernel_sizes] * 2
    st = strides if isinstance(strides, (list, tuple)) else [strides] * 2
    pd = paddings if isinstance(paddings, (list, tuple)) else [paddings] * 2
    dl = dilations if isinstance(dilations, (list, tuple)) else [dilations] * 2
    if len(pd) == 2:
        pd = [pd[0], pd[1], pd[0], pd[1]]

    def impl(a):
        n, c, h, w = a.shape
        a = jnp.pad(a, ((0, 0), (0, 0), (pd[0], pd[2]), (pd[1], pd[3])))
        oh = (a.shape[2] - (dl[0] * (ks[0] - 1) + 1)) // st[0] + 1
        ow = (a.shape[3] - (dl[1] * (ks[1] - 1) + 1)) // st[1] + 1
        patches = []
        for i in range(ks[0]):
            for j in range(ks[1]):
                sl = a[:, :, i * dl[0] : i * dl[0] + oh * st[0] : st[0], j * dl[1] : j * dl[1] + ow * st[1] : st[1]]
                patches.append(sl)
        out = jnp.stack(patches, axis=2)  # [n, c, kh*kw, oh, ow]
        return out.reshape(n, c * ks[0] * ks[1], oh * ow)

    return dispatch("unfold", impl, (x,))


def fold(x, output_sizes, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    os_ = output_sizes if isinstance(output_sizes, (list, tuple)) else [output_sizes] * 2
    ks = kernel_sizes if isinstance(kernel_sizes, (list, tuple)) else [kernel_sizes] * 2
    st = strides if isinstance(strides, (list, tuple)) else [strides] * 2
    pd = paddings if isinstance(paddings, (list, tuple)) else [paddings] * 2
    dl = dilations if isinstance(dilations, (list, tuple)) else [dilations] * 2
    if len(pd) == 2:
        pd = [pd[0], pd[1], pd[0], pd[1]]

    def impl(a):
        n, ckk, L = a.shape
        c = ckk // (ks[0] * ks[1])
        ph, pw = os_[0] + pd[0] + pd[2], os_[1] + pd[1] + pd[3]
        oh = (ph - (dl[0] * (ks[0] - 1) + 1)) // st[0] + 1
        ow = (pw - (dl[1] * (ks[1] - 1) + 1)) // st[1] + 1
        a = a.reshape(n, c, ks[0], ks[1], oh, ow)
        out = jnp.zeros((n, c, ph, pw), a.dtype)
        for i in range(ks[0]):
            for j in range(ks[1]):
                out = out.at[:, :, i * dl[0] : i * dl[0] + oh * st[0] : st[0], j * dl[1] : j * dl[1] + ow * st[1] : st[1]].add(a[:, :, i, j])
        return out[:, :, pd[0] : ph - pd[2], pd[1] : pw - pd[3]]

    return dispatch("fold", impl, (x,))


def label_smooth(label, prior_dist=None, epsilon=0.1, name=None):
    if prior_dist is not None:
        return dispatch(
            "label_smooth",
            lambda l, p: (1 - epsilon) * l + epsilon * p,
            (label, prior_dist),
        )
    return dispatch(
        "label_smooth", lambda l: (1 - epsilon) * l + epsilon / l.shape[-1], (label,)
    )


def sequence_mask(x, maxlen=None, dtype="int64", name=None):
    d = dtypes.convert_dtype(dtype)

    def impl(a):
        m = maxlen if maxlen is not None else int(jnp.max(a)) if not isinstance(a, jax.core.Tracer) else None
        if m is None:
            raise ValueError("sequence_mask requires static maxlen under jit")
        r = jnp.arange(m)
        return (r[None, :] < a[..., None]).astype(d)

    return dispatch("sequence_mask", impl, (x,))


def normalize(x, p=2, axis=1, epsilon=1e-12, name=None):
    def impl(a):
        nrm = jnp.sum(jnp.abs(a) ** p, axis=axis, keepdims=True) ** (1.0 / p)
        return a / jnp.maximum(nrm, epsilon)

    return dispatch("normalize", impl, (x,))


def bilinear(x1, x2, weight, bias=None, name=None):
    def impl(a, b, w, *rest):
        out = jnp.einsum("bi,oij,bj->bo", a, w, b)
        if rest:
            out = out + rest[0]
        return out

    args = (x1, x2, weight) + ((bias,) if bias is not None else ())
    return dispatch("bilinear", impl, args)


def class_center_sample(label, num_classes, num_samples, group=None):
    # simplified host-side sampling (reference: phi class_center_sample kernel)
    lab = np.asarray(unwrap(label))
    pos = np.unique(lab)
    extra = np.setdiff1d(np.arange(num_classes), pos)
    rng = np.random.default_rng(0)
    n_extra = max(0, num_samples - len(pos))
    sampled = np.concatenate([pos, rng.choice(extra, size=n_extra, replace=False)]) if n_extra else pos
    sampled.sort()
    remap = {c: i for i, c in enumerate(sampled)}
    new_lab = np.array([remap[int(v)] for v in lab], dtype=np.int64)
    return Tensor(jnp.asarray(new_lab)), Tensor(jnp.asarray(sampled.astype(np.int64)))


def affine_grid(theta, out_shape, align_corners=True, name=None):
    def impl(t):
        n, _, _ = t.shape
        h, w = int(out_shape[2]), int(out_shape[3])
        if align_corners:
            ys = jnp.linspace(-1.0, 1.0, h)
            xs = jnp.linspace(-1.0, 1.0, w)
        else:
            ys = (jnp.arange(h) + 0.5) * 2 / h - 1
            xs = (jnp.arange(w) + 0.5) * 2 / w - 1
        gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
        ones = jnp.ones_like(gx)
        base = jnp.stack([gx, gy, ones], axis=-1).reshape(-1, 3)  # [h*w, 3]
        out = jnp.einsum("nij,pj->npi", t, base)  # [n, h*w, 2]
        return out.reshape(n, h, w, 2)

    return dispatch("affine_grid", impl, (theta,))


def grid_sample(x, grid, mode="bilinear", padding_mode="zeros", align_corners=True, name=None):
    def impl(a, g):
        n, c, h, w = a.shape
        gx, gy = g[..., 0], g[..., 1]
        if align_corners:
            fx = (gx + 1) * (w - 1) / 2
            fy = (gy + 1) * (h - 1) / 2
        else:
            fx = ((gx + 1) * w - 1) / 2
            fy = ((gy + 1) * h - 1) / 2

        def sample(img, yy, xx):
            # img [c,h,w]; yy/xx [oh,ow]
            x0 = jnp.floor(xx).astype(jnp.int32)
            y0 = jnp.floor(yy).astype(jnp.int32)
            x1, y1 = x0 + 1, y0 + 1
            wx = xx - x0
            wy = yy - y0

            def get(yi, xi):
                valid = (yi >= 0) & (yi < h) & (xi >= 0) & (xi < w)
                yi_c = jnp.clip(yi, 0, h - 1)
                xi_c = jnp.clip(xi, 0, w - 1)
                v = img[:, yi_c, xi_c]
                if padding_mode == "zeros":
                    v = jnp.where(valid[None], v, 0.0)
                return v

            if mode == "nearest":
                return get(jnp.round(yy).astype(jnp.int32), jnp.round(xx).astype(jnp.int32))
            return (
                get(y0, x0) * ((1 - wx) * (1 - wy))[None]
                + get(y0, x1) * (wx * (1 - wy))[None]
                + get(y1, x0) * ((1 - wx) * wy)[None]
                + get(y1, x1) * (wx * wy)[None]
            )

        return jax.vmap(sample)(a, fy, fx)

    return dispatch("grid_sample", impl, (x, grid))


def temporal_shift(x, seg_num, shift_ratio=0.25, data_format="NCHW", name=None):
    def impl(a):
        if data_format == "NHWC":
            a = jnp.transpose(a, (0, 3, 1, 2))
        nt, c, h, w = a.shape
        n = nt // seg_num
        a = a.reshape(n, seg_num, c, h, w)
        fold_c = int(c * shift_ratio)
        left = jnp.concatenate([a[:, 1:, :fold_c], jnp.zeros_like(a[:, :1, :fold_c])], axis=1)
        right = jnp.concatenate([jnp.zeros_like(a[:, :1, fold_c : 2 * fold_c]), a[:, :-1, fold_c : 2 * fold_c]], axis=1)
        out = jnp.concatenate([left, right, a[:, :, 2 * fold_c :]], axis=2)
        out = out.reshape(nt, c, h, w)
        if data_format == "NHWC":
            out = jnp.transpose(out, (0, 2, 3, 1))
        return out

    return dispatch("temporal_shift", impl, (x,))
