"""paddle.nn.functional namespace (reference:
python/paddle/nn/functional/__init__.py)."""
from .activation import *  # noqa: F401,F403
from .common import *  # noqa: F401,F403
from .conv import *  # noqa: F401,F403
from .pooling import *  # noqa: F401,F403
from .norm import *  # noqa: F401,F403
from .loss import *  # noqa: F401,F403
from .attention import *  # noqa: F401,F403

from .activation import __all__ as _a
from .common import __all__ as _c
from .conv import __all__ as _cv
from .pooling import __all__ as _p
from .norm import __all__ as _n
from .loss import __all__ as _l
from .attention import __all__ as _at

__all__ = list(_a) + list(_c) + list(_cv) + list(_p) + list(_n) + list(_l) + list(_at)
