"""paddle.nn.functional namespace (reference:
python/paddle/nn/functional/__init__.py)."""
from .activation import *  # noqa: F401,F403
from .common import *  # noqa: F401,F403
from .conv import *  # noqa: F401,F403
from .pooling import *  # noqa: F401,F403
from .norm import *  # noqa: F401,F403
from .loss import *  # noqa: F401,F403
from .attention import *  # noqa: F401,F403

from .activation import __all__ as _a
from .common import __all__ as _c
from .conv import __all__ as _cv
from .pooling import __all__ as _p
from .norm import __all__ as _n
from .loss import __all__ as _l
from .attention import __all__ as _at

__all__ = list(_a) + list(_c) + list(_cv) + list(_p) + list(_n) + list(_l) + list(_at)

from .extras2 import (  # noqa: E402,F401
    adaptive_log_softmax_with_loss, feature_alpha_dropout,
    flash_attention_with_sparse_mask, flash_attn_qkvpacked,
    flash_attn_varlen_qkvpacked, fractional_max_pool2d,
    fractional_max_pool3d, gather_tree, hardtanh_, hsigmoid_loss,
    leaky_relu_, margin_cross_entropy, pairwise_distance,
    sparse_attention, thresholded_relu_)

import types as _types
__all__ = [n for n, v in list(globals().items())
           if not n.startswith("_") and not isinstance(v, _types.ModuleType)]
