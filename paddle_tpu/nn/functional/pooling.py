"""Pooling functionals via lax.reduce_window.

Reference: python/paddle/nn/functional/pooling.py → phi pool kernels.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ...core.tensor import dispatch

__all__ = [
    "avg_pool1d", "avg_pool2d", "avg_pool3d", "max_pool1d", "max_pool2d",
    "max_pool3d", "adaptive_avg_pool1d", "adaptive_avg_pool2d",
    "adaptive_avg_pool3d", "adaptive_max_pool1d", "adaptive_max_pool2d",
    "adaptive_max_pool3d", "lp_pool1d", "lp_pool2d", "max_unpool1d", "max_unpool2d", "max_unpool3d",
]


def _tup(v, n):
    if isinstance(v, (list, tuple)):
        t = list(v)
        if len(t) == 1:
            t = t * n
        return tuple(int(i) for i in t)
    return (int(v),) * n


def _pads(padding, n):
    if isinstance(padding, str):
        return padding.upper()
    if isinstance(padding, (list, tuple)):
        p = list(padding)
        if len(p) == n:
            return [(int(i), int(i)) for i in p]
        if len(p) == 2 * n:
            return [(int(p[2 * i]), int(p[2 * i + 1])) for i in range(n)]
    return [(int(padding), int(padding))] * n


def _pool(a, n, ksize, stride, padding, kind, ceil_mode=False, exclusive=True, data_format="NCHW"):
    k = _tup(ksize, n)
    s = _tup(stride if stride is not None else ksize, n)
    p = _pads(padding, n)
    nc_first = data_format.startswith("NC")
    if nc_first:
        window = (1, 1) + k
        strides = (1, 1) + s
        pad_full = [(0, 0), (0, 0)] + (p if not isinstance(p, str) else p)
    else:
        window = (1,) + k + (1,)
        strides = (1,) + s + (1,)
        pad_full = [(0, 0)] + (p if not isinstance(p, str) else p) + [(0, 0)]
    if isinstance(p, str):
        pad_full = p
    if kind == "max":
        init = -jnp.inf if jnp.issubdtype(a.dtype, jnp.floating) else jnp.iinfo(a.dtype).min
        return jax.lax.reduce_window(a, init, jax.lax.max, window, strides, pad_full)
    # avg
    summed = jax.lax.reduce_window(a, 0.0, jax.lax.add, window, strides, pad_full)
    if exclusive and not isinstance(pad_full, str):
        ones = jnp.ones_like(a)
        counts = jax.lax.reduce_window(ones, 0.0, jax.lax.add, window, strides, pad_full)
        return summed / counts
    return summed / float(np.prod(k))


def _make_pool(n, kind, name):
    def pool(x, kernel_size, stride=None, padding=0, ceil_mode=False, exclusive=True,
             divisor_override=None, data_format=None, return_mask=False, name_=None, **kw):
        df = data_format or ("NCL" if n == 1 else "NCHW" if n == 2 else "NCDHW")

        def impl(a):
            out = _pool(a, n, kernel_size, stride, padding, kind, ceil_mode, exclusive, df)
            return out.astype(a.dtype)

        out = dispatch(name, impl, (x,))
        if return_mask and kind == "max":
            idx = _max_pool_indices(x, n, kernel_size, stride, padding, df)
            return out, idx
        return out

    pool.__name__ = name
    return pool


def _max_pool_indices(x, n, ksize, stride, padding, df):
    """Flat indices of max elements (paddle return_mask contract)."""

    def impl(a):
        nc_first = df.startswith("NC")
        spatial_shape = a.shape[2:] if nc_first else a.shape[1:-1]
        flat_idx = jnp.arange(int(np.prod(spatial_shape))).reshape(spatial_shape)
        # reduce_window over (value, index) pairs
        k = _tup(ksize, n)
        s = _tup(stride if stride is not None else ksize, n)
        p = _pads(padding, n)
        if nc_first:
            window = (1, 1) + k
            strides = (1, 1) + s
            pad_full = [(0, 0), (0, 0)] + p
            idx_map = jnp.broadcast_to(flat_idx[None, None], a.shape)
        else:
            window = (1,) + k + (1,)
            strides = (1,) + s + (1,)
            pad_full = [(0, 0)] + p + [(0, 0)]
            idx_map = jnp.broadcast_to(flat_idx[None, ..., None], a.shape)

        def reducer(acc, cur):
            av, ai = acc
            cv, ci = cur
            take = cv > av
            return jnp.where(take, cv, av), jnp.where(take, ci, ai)

        init_v = jnp.asarray(-jnp.inf, a.dtype)
        init_i = jnp.asarray(-1, jnp.int64)
        _, idx = jax.lax.reduce_window(
            (a, idx_map.astype(jnp.int64)),
            (init_v, init_i),
            reducer,
            window, strides, pad_full,
        )
        return idx

    return dispatch("max_pool_indices", impl, (x,))


max_pool1d = _make_pool(1, "max", "max_pool1d")
max_pool2d = _make_pool(2, "max", "max_pool2d")
max_pool3d = _make_pool(3, "max", "max_pool3d")
avg_pool1d = _make_pool(1, "avg", "avg_pool1d")
avg_pool2d = _make_pool(2, "avg", "avg_pool2d")
avg_pool3d = _make_pool(3, "avg", "avg_pool3d")


def _adaptive(a, n, out_size, kind, df):
    nc_first = df.startswith("NC")
    spatial = list(range(2, 2 + n)) if nc_first else list(range(1, 1 + n))
    tgt = _tup(out_size, n)
    out = a
    for d, t in zip(spatial, tgt):
        if t is None:
            continue
        n_in = out.shape[d]
        # split into t nearly-even bins (paddle adaptive semantics)
        starts = (np.arange(t) * n_in) // t
        ends = ((np.arange(t) + 1) * n_in + t - 1) // t  # ceil
        slices = []
        for st, en in zip(starts, ends):
            seg = jax.lax.slice_in_dim(out, int(st), int(en), axis=d)
            red = jnp.max(seg, axis=d, keepdims=True) if kind == "max" else jnp.mean(seg, axis=d, keepdims=True)
            slices.append(red)
        out = jnp.concatenate(slices, axis=d)
    return out


def _make_adaptive(n, kind, name):
    def pool(x, output_size, data_format=None, return_mask=False, name_=None, **kw):
        df = data_format or ("NCL" if n == 1 else "NCHW" if n == 2 else "NCDHW")
        out = dispatch(name, lambda a: _adaptive(a, n, output_size, kind, df), (x,))
        if return_mask:
            # indices of max within each bin — host-computed fallback
            raise NotImplementedError("adaptive pool return_mask: use max_pool with return_mask")
        return out

    pool.__name__ = name
    return pool


adaptive_avg_pool1d = _make_adaptive(1, "avg", "adaptive_avg_pool1d")
adaptive_avg_pool2d = _make_adaptive(2, "avg", "adaptive_avg_pool2d")
adaptive_avg_pool3d = _make_adaptive(3, "avg", "adaptive_avg_pool3d")
adaptive_max_pool1d = _make_adaptive(1, "max", "adaptive_max_pool1d")
adaptive_max_pool2d = _make_adaptive(2, "max", "adaptive_max_pool2d")
adaptive_max_pool3d = _make_adaptive(3, "max", "adaptive_max_pool3d")


def lp_pool1d(x, norm_type, kernel_size, stride=None, padding=0, ceil_mode=False, data_format="NCL", name=None):
    p = float(norm_type)

    def impl(a):
        powed = jnp.abs(a) ** p
        pooled = _pool(powed, 1, kernel_size, stride, padding, "avg", ceil_mode, False, data_format)
        k = _tup(kernel_size, 1)
        return (pooled * float(np.prod(k))) ** (1.0 / p)

    return dispatch("lp_pool1d", impl, (x,))


def lp_pool2d(x, norm_type, kernel_size, stride=None, padding=0, ceil_mode=False, data_format="NCHW", name=None):
    p = float(norm_type)

    def impl(a):
        powed = jnp.abs(a) ** p
        pooled = _pool(powed, 2, kernel_size, stride, padding, "avg", ceil_mode, False, data_format)
        k = _tup(kernel_size, 2)
        return (pooled * float(np.prod(k))) ** (1.0 / p)

    return dispatch("lp_pool2d", impl, (x,))


def _make_unpool(n, name):
    def unpool(x, indices, kernel_size, stride=None, padding=0, data_format=None, output_size=None, name_=None, **kw):
        df = data_format or ("NCL" if n == 1 else "NCHW" if n == 2 else "NCDHW")
        k = _tup(kernel_size, n)
        s = _tup(stride if stride is not None else kernel_size, n)

        def impl(a, idx):
            nc_first = df.startswith("NC")
            in_spatial = a.shape[2:] if nc_first else a.shape[1:-1]
            if output_size is not None:
                out_spatial = tuple(int(i) for i in output_size)[-n:]
            else:
                out_spatial = tuple((isz - 1) * st + kk for isz, st, kk in zip(in_spatial, s, k))
            lead = a.shape[:2] if nc_first else (a.shape[0], a.shape[-1])
            flat = a.reshape(lead + (-1,)) if nc_first else jnp.moveaxis(a, -1, 1).reshape((a.shape[0], a.shape[-1], -1))
            fidx = idx.reshape(lead + (-1,)) if nc_first else jnp.moveaxis(idx, -1, 1).reshape((idx.shape[0], idx.shape[-1], -1))
            out_flat = jnp.zeros(lead + (int(np.prod(out_spatial)),), a.dtype)
            out_flat = jax.vmap(jax.vmap(lambda o, i, v: o.at[i].set(v)))(out_flat, fidx, flat)
            out = out_flat.reshape(lead + out_spatial)
            if not nc_first:
                out = jnp.moveaxis(out, 1, -1)
            return out

        return dispatch(name, impl, (x, indices))

    unpool.__name__ = name
    return unpool


max_unpool1d = _make_unpool(1, "max_unpool1d")
max_unpool2d = _make_unpool(2, "max_unpool2d")
max_unpool3d = _make_unpool(3, "max_unpool3d")
