"""Remaining nn.functional surface (reference: python/paddle/nn/functional
— pairwise_distance, fractional pooling, hierarchical/adaptive softmax
losses, margin_cross_entropy, beam-search gather_tree, sparse attention,
flash-attention packing variants, and trailing in-place aliases)."""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from ...core.tensor import Tensor, dispatch, unwrap
from ...framework import random as _random
from .activation import hardtanh, leaky_relu, thresholded_relu
from .attention import flash_attention
from .common import alpha_dropout

__all__ = [
    "pairwise_distance", "hardtanh_", "leaky_relu_", "thresholded_relu_",
    "feature_alpha_dropout", "fractional_max_pool2d",
    "fractional_max_pool3d", "hsigmoid_loss", "margin_cross_entropy",
    "gather_tree", "sparse_attention", "adaptive_log_softmax_with_loss",
    "flash_attention_with_sparse_mask", "flash_attn_qkvpacked",
    "flash_attn_varlen_qkvpacked",
]


def pairwise_distance(x, y, p=2.0, epsilon=1e-6, keepdim=False, name=None):
    """reference: nn/functional/distance.py pairwise_distance."""
    def impl(xa, ya):
        d = xa - ya + epsilon
        return jnp.linalg.norm(d, ord=p, axis=-1, keepdims=keepdim)

    return dispatch("pairwise_distance", impl, (x, y))


def hardtanh_(x, min=-1.0, max=1.0, name=None):
    out = hardtanh(x, min=min, max=max)
    return x._replace(out._array, out._node, out._out_idx)


def leaky_relu_(x, negative_slope=0.01, name=None):
    out = leaky_relu(x, negative_slope=negative_slope)
    return x._replace(out._array, out._node, out._out_idx)


def thresholded_relu_(x, threshold=1.0, value=0.0, name=None):
    out = thresholded_relu(x, threshold=threshold, value=value)
    return x._replace(out._array, out._node, out._out_idx)


def feature_alpha_dropout(x, p=0.5, training=True, name=None):
    """reference: common.py feature_alpha_dropout — alpha dropout that
    drops whole channels (dim 1), keeping SELU self-normalisation."""
    if not training or p == 0.0:
        return x

    def impl(xa):
        alpha = 1.6732632423543772 * 1.0507009873554805
        neg = -alpha
        shape = (xa.shape[0], xa.shape[1]) + (1,) * (xa.ndim - 2)
        keep = jax.random.bernoulli(_random.next_key(), 1.0 - p, shape)
        a = (1.0 / math.sqrt((1.0 - p) * (1.0 + p * neg ** 2))) \
            if p < 1.0 else 0.0
        b = -a * neg * p
        return (jnp.where(keep, xa, neg) * a + b).astype(xa.dtype)

    return dispatch("feature_alpha_dropout", impl, (x,))


def _fractional_bounds(n, m, u):
    """Pooling-region boundaries for fractional max pooling
    (Graham 2014): alpha = n/m, b_i = ceil(alpha*(i+u)) clipped so every
    region is non-empty and the last ends at n."""
    alpha = n / m
    idx = np.arange(m + 1, dtype=np.float64)
    b = np.ceil(alpha * (idx + u)).astype(np.int64) - int(np.ceil(alpha * u))
    b = np.clip(b, 0, n)
    b[0], b[-1] = 0, n
    for i in range(1, m + 1):  # enforce strictly increasing
        if b[i] <= b[i - 1]:
            b[i] = b[i - 1] + 1
    return np.minimum(b, n)


def _fractional_pool(x, output_size, random_u, spatial):
    xa = unwrap(x)
    dims = xa.shape[-spatial:]
    if isinstance(output_size, int):
        output_size = (output_size,) * spatial
    out_dims = tuple(dims[i] if output_size[i] is None else output_size[i]
                     for i in range(spatial))
    u = float(random_u) if random_u is not None else float(
        jax.random.uniform(_random.next_key(), ()))
    u = min(max(u, 1e-4), 1 - 1e-4)
    bounds = [_fractional_bounds(dims[i], out_dims[i], u)
              for i in range(spatial)]

    def pool_axis(arr, axis, bnd):
        slices = [jnp.max(jax.lax.slice_in_dim(
            arr, int(bnd[i]), int(bnd[i + 1]), axis=axis),
            axis=axis, keepdims=True) for i in range(len(bnd) - 1)]
        return jnp.concatenate(slices, axis=axis)

    out = xa
    for s in range(spatial):
        axis = out.ndim - spatial + s
        out = pool_axis(out, axis, bounds[s])
    return Tensor(out)


def fractional_max_pool2d(x, output_size, kernel_size=None, random_u=None,
                          return_mask=False, name=None):
    """reference: pooling.py fractional_max_pool2d — pseudo-random pooling
    regions (Graham, 'Fractional Max-Pooling')."""
    out = _fractional_pool(x, output_size, random_u, spatial=2)
    return (out, None) if return_mask else out


def fractional_max_pool3d(x, output_size, kernel_size=None, random_u=None,
                          return_mask=False, name=None):
    out = _fractional_pool(x, output_size, random_u, spatial=3)
    return (out, None) if return_mask else out


def hsigmoid_loss(input, label, num_classes, weight, bias=None,
                  path_table=None, path_code=None, is_sparse=False,
                  name=None):
    """reference: loss.py hsigmoid_loss — hierarchical sigmoid over a
    complete binary tree (heap numbering: leaf c = c + num_classes, parent
    n//2, code n%2; num_classes-1 internal nodes), or a custom tree via
    path_table/path_code."""
    args = [a for a in (input, label, weight, bias, path_table, path_code)
            if a is not None]

    def impl(*arrs):
        it = iter(arrs)
        xa = next(it)
        lab = next(it).reshape(-1).astype(jnp.int32)
        w = next(it)
        b = next(it) if bias is not None else None
        pt = next(it) if path_table is not None else None
        pc = next(it) if path_code is not None else None
        if pt is None:
            depth = max(1, int(math.ceil(math.log2(max(num_classes, 2)))))
            node = lab + num_classes  # heap leaf id
            codes, parents, valid = [], [], []
            for _ in range(depth):
                parent = node // 2
                codes.append((node % 2).astype(jnp.float32))
                parents.append(parent - 1)  # internal node param index
                valid.append((parent >= 1).astype(jnp.float32))
                node = parent
            pt = jnp.stack(parents, 1)  # [N, depth]
            pc = jnp.stack(codes, 1)
            vd = jnp.stack(valid, 1)
        else:
            vd = (pt >= 0).astype(jnp.float32)
        pt = jnp.clip(pt, 0, w.shape[0] - 1).astype(jnp.int32)
        wsel = w[pt]                     # [N, depth, D]
        logits = jnp.einsum("nd,nkd->nk", xa, wsel)
        if b is not None:
            logits = logits + b.reshape(-1)[pt]
        # sigmoid cross entropy against the path code bits
        ce = jnp.maximum(logits, 0) - logits * pc + \
            jnp.log1p(jnp.exp(-jnp.abs(logits)))
        return (ce * vd).sum(-1, keepdims=True)

    return dispatch("hsigmoid_loss", impl, tuple(args))


def margin_cross_entropy(logits, label, margin1=1.0, margin2=0.5,
                         margin3=0.0, scale=64.0, group=None,
                         return_softmax=False, reduction="mean", name=None):
    """reference: loss.py margin_cross_entropy — combined-margin softmax
    (cos(m1*theta + m2) - m3, ArcFace family). Logits must be cosine
    similarities in [-1, 1]."""
    def impl(lg, lab):
        lab = lab.reshape(-1).astype(jnp.int32)
        oh = jax.nn.one_hot(lab, lg.shape[-1], dtype=lg.dtype)
        cos_t = jnp.clip(lg, -1.0, 1.0)
        theta = jnp.arccos(cos_t)
        modified = jnp.cos(margin1 * theta + margin2) - margin3
        out = jnp.where(oh > 0, modified, cos_t) * scale
        logp = jax.nn.log_softmax(out, axis=-1)
        loss = -(oh * logp).sum(-1, keepdims=True)
        sm = jnp.exp(logp)
        return loss, sm

    loss, sm = dispatch("margin_cross_entropy", impl, (logits, label))
    if reduction == "mean":
        loss = loss.mean()
    elif reduction == "sum":
        loss = loss.sum()
    return (loss, sm) if return_softmax else loss


def gather_tree(ids, parents):
    """reference: nn/decode.py gather_tree — backtrack full beam paths.
    ids/parents: [max_time, batch, beam]."""
    def impl(ia, pa):
        T = ia.shape[0]

        def step(beams, t):
            # beams: [batch, beam] current beam index at time t+1
            tok = jnp.take_along_axis(ia[t], beams, axis=-1)
            prev = jnp.take_along_axis(pa[t], beams, axis=-1)
            return prev, tok

        init = jnp.broadcast_to(jnp.arange(ia.shape[2]), ia.shape[1:])
        _, toks = jax.lax.scan(step, init, jnp.arange(T - 1, -1, -1))
        return toks[::-1]

    return dispatch("gather_tree", impl, (ids, parents))


def sparse_attention(query, key, value, sparse_csr_offset,
                     sparse_csr_columns, key_padding_mask=None,
                     attn_mask=None, name=None):
    """reference: sparse_attention.py — block-sparse attention with a CSR
    pattern per head. Eager-oriented (CSR is data-dependent), matching the
    reference's dynamic-graph-only support."""
    q = np.asarray(unwrap(query))
    k = np.asarray(unwrap(key))
    v = np.asarray(unwrap(value))
    off = np.asarray(unwrap(sparse_csr_offset)
                     if isinstance(sparse_csr_offset, Tensor)
                     else sparse_csr_offset).astype(np.int64)
    cols = np.asarray(unwrap(sparse_csr_columns)
                      if isinstance(sparse_csr_columns, Tensor)
                      else sparse_csr_columns).astype(np.int64)
    kpm = None if key_padding_mask is None else np.asarray(
        unwrap(key_padding_mask) if isinstance(key_padding_mask, Tensor)
        else key_padding_mask)
    am = None if attn_mask is None else np.asarray(
        unwrap(attn_mask) if isinstance(attn_mask, Tensor) else attn_mask)
    B, H, M, D = q.shape
    out = np.zeros_like(q)
    scale = 1.0 / math.sqrt(D)
    for b in range(B):
        for h in range(H):
            for m in range(M):
                s, e = off[b, h, m], off[b, h, m + 1]
                if e <= s:
                    continue
                c = cols[b, h, s:e]
                logits = (k[b, h, c] @ q[b, h, m]) * scale
                # additive masks (0 keep / -inf drop) per the reference
                if kpm is not None:
                    logits = logits + kpm[b, c]
                if am is not None:
                    logits = logits + am[m, c]
                if np.all(np.isneginf(logits)):
                    continue
                p = np.exp(logits - logits.max())
                p /= p.sum()
                out[b, h, m] = p @ v[b, h, c]
    return Tensor(jnp.asarray(out))


def adaptive_log_softmax_with_loss(input, label, head_weight, tail_weights,
                                   cutoffs, head_bias=None, name=None):
    """reference: loss.py adaptive_log_softmax_with_loss — efficient
    softmax: a head over frequent classes + shortlists, tail clusters with
    low-rank projections. Returns (target log-prob, mean nll loss)."""
    n_clusters = len(cutoffs)  # excludes the final n_classes cutoff? no:
    # paddle convention: cutoffs excludes n_classes; tail_weights is a list
    # of [proj_in, proj_out] pairs per cluster

    args = [input, label, head_weight]
    if head_bias is not None:
        args.append(head_bias)
    flat_tails = []
    for pair in tail_weights:
        flat_tails.extend(pair)
    args.extend(flat_tails)

    def impl(*arrs):
        it = iter(arrs)
        xa = next(it)
        lab = next(it).reshape(-1).astype(jnp.int32)
        hw = next(it)
        hb = next(it) if head_bias is not None else None
        tails = []
        for _ in range(len(tail_weights)):
            tails.append((next(it), next(it)))
        head_logits = xa @ hw
        if hb is not None:
            head_logits = head_logits + hb
        head_logp = jax.nn.log_softmax(head_logits, axis=-1)
        shortlist = cutoffs[0]
        out = jnp.zeros(xa.shape[0], xa.dtype)
        # shortlist targets: direct head log-prob
        in_short = lab < shortlist
        short_lp = jnp.take_along_axis(
            head_logp, jnp.clip(lab, 0, shortlist - 1)[:, None], 1)[:, 0]
        out = jnp.where(in_short, short_lp, out)
        bounds = [shortlist] + list(cutoffs[1:]) + [None]
        for ci, (p1, p2) in enumerate(tails):
            lo = bounds[ci]
            hi = bounds[ci + 1]
            hi_v = hi if hi is not None else lo + p2.shape[-1]
            in_c = (lab >= lo) & (lab < hi_v)
            cluster_lp_head = head_logp[:, shortlist + ci]
            tail_logits = (xa @ p1) @ p2
            tail_logp = jax.nn.log_softmax(tail_logits, axis=-1)
            rel = jnp.clip(lab - lo, 0, p2.shape[-1] - 1)
            lp = cluster_lp_head + jnp.take_along_axis(
                tail_logp, rel[:, None], 1)[:, 0]
            out = jnp.where(in_c, lp, out)
        return out, -out.mean()

    return dispatch("adaptive_log_softmax_with_loss", impl, tuple(args))


def flash_attn_qkvpacked(qkv, dropout=0.0, causal=False,
                         return_softmax=False, fixed_seed_offset=None,
                         rng_name="", training=True, name=None):
    """reference: flash_attention.py flash_attn_qkvpacked — packed
    [B, S, 3, H, D] input routed to the flash path."""
    q = qkv[:, :, 0]
    k = qkv[:, :, 1]
    v = qkv[:, :, 2]
    return flash_attention(q, k, v, dropout=dropout, causal=causal,
                           return_softmax=return_softmax, training=training)


def flash_attn_varlen_qkvpacked(qkv, cu_seqlens_q, cu_seqlens_k,
                                max_seqlen_q, max_seqlen_k, scale=None,
                                dropout=0.0, causal=False,
                                return_softmax=False, training=True,
                                name=None):
    """reference: flash_attention.py flash_attn_varlen_qkvpacked —
    unpadded [total_tokens, 3, H, D] with cu_seqlens. Eager per-sequence
    (lengths are data-dependent, mirroring the varlen CUDA kernel's
    dynamic use)."""
    qkv_a = np.asarray(unwrap(qkv))
    cu = np.asarray(unwrap(cu_seqlens_q)
                    if isinstance(cu_seqlens_q, Tensor)
                    else cu_seqlens_q).reshape(-1)
    outs = np.zeros((qkv_a.shape[0],) + qkv_a.shape[2:], qkv_a.dtype)
    for i in range(len(cu) - 1):
        s, e = int(cu[i]), int(cu[i + 1])
        if e <= s:
            continue
        seg = qkv_a[s:e]
        out = flash_attention(Tensor(seg[None, :, 0]),
                              Tensor(seg[None, :, 1]),
                              Tensor(seg[None, :, 2]),
                              causal=causal, training=training)
        if isinstance(out, tuple):
            out = out[0]
        outs[s:e] = np.asarray(unwrap(out))[0]
    result = Tensor(jnp.asarray(outs))
    return (result, None) if return_softmax else result


def flash_attention_with_sparse_mask(query, key, value,
                                     attn_mask_start_row_indices,
                                     attn_mask_start_row=0, dropout_p=0.0,
                                     is_causal=True, training=True,
                                     name=None):
    """reference: flash_attention.py flash_attention_with_sparse_mask —
    causal attention where row i additionally masks keys j with
    j >= start_row_indices[..., j]: a compressed column-wise mask. Builds
    the dense additive mask and runs the standard path."""
    def impl(q, k, v, sri):
        b, s = q.shape[0], q.shape[1]
        rows = jnp.arange(s)
        causal = rows[:, None] >= rows[None, :]  # [S_q, S_k]
        # sri: [B, 1(or H), S_k] — queries at row >= sri[j] cannot see col j
        sri_b = sri.reshape(b, -1, s)
        blocked = rows[None, None, :, None] >= sri_b[:, :, None, :]
        allowed = causal[None, None] & ~blocked
        bias = jnp.where(allowed, 0.0, -jnp.inf).astype(jnp.float32)
        scale = 1.0 / jnp.sqrt(jnp.asarray(q.shape[-1], jnp.float32))
        logits = jnp.einsum("bshd,bthd->bhst", q.astype(jnp.float32),
                            k.astype(jnp.float32)) * scale
        logits = logits + bias
        probs = jax.nn.softmax(logits, axis=-1)
        probs = jnp.where(jnp.isnan(probs), 0.0, probs)
        return jnp.einsum("bhst,bthd->bshd", probs,
                          v.astype(jnp.float32)).astype(q.dtype)

    return dispatch("flash_attention_with_sparse_mask", impl,
                    (query, key, value, attn_mask_start_row_indices))
