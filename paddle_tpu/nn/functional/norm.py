"""Normalization functionals (reference: python/paddle/nn/functional/norm.py).

`rms_norm` / fused variants route to Pallas kernels on TPU when
FLAGS_enable_pallas_kernels is set (paddle_tpu/kernels/).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...core.tensor import Tensor, dispatch, unwrap

__all__ = ["layer_norm", "batch_norm", "instance_norm", "group_norm", "local_response_norm", "rms_norm"]


def layer_norm(x, normalized_shape, weight=None, bias=None, epsilon=1e-05, name=None):
    ns = normalized_shape if isinstance(normalized_shape, (list, tuple)) else [normalized_shape]
    n_axes = len(ns)

    def impl(a, *rest):
        axes = tuple(range(a.ndim - n_axes, a.ndim))
        mean = jnp.mean(a.astype(jnp.float32), axis=axes, keepdims=True)
        var = jnp.var(a.astype(jnp.float32), axis=axes, keepdims=True)
        out = (a.astype(jnp.float32) - mean) * jax.lax.rsqrt(var + epsilon)
        out = out.astype(a.dtype)
        i = 0
        if weight is not None:
            out = out * rest[i]
            i += 1
        if bias is not None:
            out = out + rest[i]
        return out

    args = (x,) + tuple(t for t in (weight, bias) if t is not None)
    return dispatch("layer_norm", impl, args)


def rms_norm(x, weight=None, epsilon=1e-6, name=None):
    """RMSNorm (reference fused op: paddle/phi/kernels/fusion rms_norm,
    python/paddle/incubate/nn/functional/fused_rms_norm)."""

    def impl(a, *rest):
        a32 = a.astype(jnp.float32)
        var = jnp.mean(a32 * a32, axis=-1, keepdims=True)
        out = (a32 * jax.lax.rsqrt(var + epsilon)).astype(a.dtype)
        if rest:
            out = out * rest[0]
        return out

    args = (x,) + ((weight,) if weight is not None else ())
    return dispatch("rms_norm", impl, args)


def batch_norm(x, running_mean, running_var, weight=None, bias=None, training=False,
               momentum=0.9, epsilon=1e-05, data_format="NCHW", use_global_stats=None, name=None):
    """Reference: nn/functional/norm.py batch_norm. Running stats are updated
    in-place on the passed tensors when training (matching paddle)."""
    ch_axis = 1 if data_format.startswith("NC") and unwrap(x).ndim > 1 else -1
    use_batch_stats = training and not use_global_stats

    xa = unwrap(x)
    reduce_axes = tuple(i for i in range(xa.ndim) if i != (ch_axis % xa.ndim))

    if use_batch_stats:
        def impl(a, *rest):
            a32 = a.astype(jnp.float32)
            mean = jnp.mean(a32, axis=reduce_axes)
            var = jnp.var(a32, axis=reduce_axes)
            shape = [1] * a.ndim
            shape[ch_axis] = a.shape[ch_axis]
            out = (a32 - mean.reshape(shape)) * jax.lax.rsqrt(var.reshape(shape) + epsilon)
            out = out.astype(a.dtype)
            i = 0
            if weight is not None:
                out = out * rest[i].reshape(shape)
                i += 1
            if bias is not None:
                out = out + rest[i].reshape(shape)
            return out, mean, var

        args = (x,) + tuple(t for t in (weight, bias) if t is not None)
        out, mean_t, var_t = dispatch("batch_norm", impl, args)
        # update running stats in place (no_grad semantics)
        m = float(momentum)
        rm, rv = unwrap(running_mean), unwrap(running_var)
        running_mean._replace((m * rm + (1 - m) * mean_t._array).astype(rm.dtype))
        running_var._replace((m * rv + (1 - m) * var_t._array).astype(rv.dtype))
        return out

    def impl_eval(a, rm, rv, *rest):
        shape = [1] * a.ndim
        shape[ch_axis] = a.shape[ch_axis]
        out = (a.astype(jnp.float32) - rm.reshape(shape)) * jax.lax.rsqrt(rv.reshape(shape) + epsilon)
        out = out.astype(a.dtype)
        i = 0
        if weight is not None:
            out = out * rest[i].reshape(shape)
            i += 1
        if bias is not None:
            out = out + rest[i].reshape(shape)
        return out

    args = (x, running_mean, running_var) + tuple(t for t in (weight, bias) if t is not None)
    return dispatch("batch_norm", impl_eval, args)


def instance_norm(x, running_mean=None, running_var=None, weight=None, bias=None,
                  use_input_stats=True, momentum=0.9, eps=1e-05, data_format="NCHW", name=None):
    def impl(a, *rest):
        # normalize over spatial dims per (n, c)
        nc_first = data_format.startswith("NC")
        axes = tuple(range(2, a.ndim)) if nc_first else tuple(range(1, a.ndim - 1))
        a32 = a.astype(jnp.float32)
        mean = jnp.mean(a32, axis=axes, keepdims=True)
        var = jnp.var(a32, axis=axes, keepdims=True)
        out = ((a32 - mean) * jax.lax.rsqrt(var + eps)).astype(a.dtype)
        i = 0
        ch_axis = 1 if nc_first else a.ndim - 1
        shape = [1] * a.ndim
        shape[ch_axis] = a.shape[ch_axis]
        if weight is not None:
            out = out * rest[i].reshape(shape)
            i += 1
        if bias is not None:
            out = out + rest[i].reshape(shape)
        return out

    args = (x,) + tuple(t for t in (weight, bias) if t is not None)
    return dispatch("instance_norm", impl, args)


def group_norm(x, num_groups, epsilon=1e-05, weight=None, bias=None, data_format="NCHW", name=None):
    def impl(a, *rest):
        nc_first = data_format.startswith("NC")
        if not nc_first:
            a_t = jnp.moveaxis(a, -1, 1)
        else:
            a_t = a
        n, c = a_t.shape[:2]
        g = num_groups
        grouped = a_t.reshape((n, g, c // g) + a_t.shape[2:])
        axes = tuple(range(2, grouped.ndim))
        a32 = grouped.astype(jnp.float32)
        mean = jnp.mean(a32, axis=axes, keepdims=True)
        var = jnp.var(a32, axis=axes, keepdims=True)
        out = ((a32 - mean) * jax.lax.rsqrt(var + epsilon)).astype(a.dtype).reshape(a_t.shape)
        shape = [1] * a_t.ndim
        shape[1] = c
        i = 0
        if weight is not None:
            out = out * rest[i].reshape(shape)
            i += 1
        if bias is not None:
            out = out + rest[i].reshape(shape)
        if not nc_first:
            out = jnp.moveaxis(out, 1, -1)
        return out

    args = (x,) + tuple(t for t in (weight, bias) if t is not None)
    return dispatch("group_norm", impl, args)


def local_response_norm(x, size, alpha=0.0001, beta=0.75, k=1.0, data_format="NCHW", name=None):
    def impl(a):
        nc_first = data_format.startswith("NC")
        ch = 1 if nc_first else a.ndim - 1
        sq = a * a
        pad_lo = (size - 1) // 2
        pad_hi = size - 1 - pad_lo
        pads = [(0, 0)] * a.ndim
        pads[ch] = (pad_lo, pad_hi)
        sq = jnp.pad(sq, pads)
        window = [1] * a.ndim
        window[ch] = size
        summed = jax.lax.reduce_window(sq, 0.0, jax.lax.add, tuple(window), (1,) * a.ndim, "valid")
        div = (k + alpha * summed / size) ** beta
        return a / div

    return dispatch("local_response_norm", impl, (x,))
