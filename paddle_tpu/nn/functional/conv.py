"""Convolution functionals via lax.conv_general_dilated.

Reference: python/paddle/nn/functional/conv.py → phi conv kernels (cudnn).
On TPU the conv maps to the MXU through XLA's convolution HLO; weight layout
follows paddle ([out_c, in_c/groups, *k]).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...core.tensor import dispatch

__all__ = ["conv1d", "conv2d", "conv3d", "conv1d_transpose", "conv2d_transpose", "conv3d_transpose"]


def _norm_tuple(v, n):
    if isinstance(v, (list, tuple)):
        out = list(v)
        if len(out) == 1:
            out = out * n
        return tuple(int(i) for i in out)
    return (int(v),) * n


def _padding(padding, n, stride, dilation, ksize):
    if isinstance(padding, str):
        return padding.upper()  # 'SAME' / 'VALID'
    if isinstance(padding, (list, tuple)):
        p = list(padding)
        if len(p) == n:
            return [(int(i), int(i)) for i in p]
        if len(p) == 2 * n:
            return [(int(p[2 * i]), int(p[2 * i + 1])) for i in range(n)]
        if all(isinstance(i, (list, tuple)) for i in p):
            # NCHW-style 4-elem list incl batch/channel dims
            sp = [i for i in p if list(i) != [0, 0]] or [(0, 0)] * n
            return [tuple(int(j) for j in i) for i in sp[-n:]]
    return [(int(padding), int(padding))] * n


def _conv(a, w, bias, stride, padding, dilation, groups, n, data_format):
    chars = "DHW"[-n:]
    if data_format in (f"NC{chars}", "NCHW", "NCL", "NCDHW"):
        lhs_spec = "NC" + chars
    else:
        lhs_spec = "N" + chars + "C"
    rhs_spec = "OI" + chars
    out_spec = lhs_spec
    dn = jax.lax.conv_dimension_numbers(a.shape, w.shape, (lhs_spec, rhs_spec, out_spec))
    out = jax.lax.conv_general_dilated(
        a, w,
        window_strides=_norm_tuple(stride, n),
        padding=padding,
        rhs_dilation=_norm_tuple(dilation, n),
        dimension_numbers=dn,
        feature_group_count=groups,
    )
    if bias is not None:
        if lhs_spec.startswith("NC"):
            out = out + bias.reshape((1, -1) + (1,) * n)
        else:
            out = out + bias
    return out


def _make_conv(n, name):
    def conv(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1, data_format=None, name_=None, **kw):
        df = data_format or ("NCL" if n == 1 else "NCHW" if n == 2 else "NCDHW")
        ks = None
        pad = padding

        def impl(a, w, *rest):
            b = rest[0] if rest else None
            p = _padding(pad, n, stride, dilation, w.shape[2:])
            return _conv(a, w, b, stride, p, dilation, groups, n, df)

        args = (x, weight) + ((bias,) if bias is not None else ())
        return dispatch(name, impl, args)

    conv.__name__ = name
    return conv


conv1d = _make_conv(1, "conv1d")
conv2d = _make_conv(2, "conv2d")
conv3d = _make_conv(3, "conv3d")


def _conv_transpose(a, w, bias, stride, padding, output_padding, dilation, groups, n, data_format):
    chars = "DHW"[-n:]
    if data_format.startswith("NC"):
        lhs_spec = "NC" + chars
    else:
        lhs_spec = "N" + chars + "C"
    # paddle conv_transpose weight layout: [in_c, out_c/groups, *k]
    rhs_spec = "IO" + chars
    out_spec = lhs_spec
    dn = jax.lax.conv_dimension_numbers(a.shape, w.shape, (lhs_spec, rhs_spec, out_spec))
    strides = _norm_tuple(stride, n)
    dil = _norm_tuple(dilation, n)
    if isinstance(padding, str):
        pad = padding.upper()
    else:
        pad = _padding(padding, n, stride, dilation, w.shape[2:])
    out = jax.lax.conv_transpose(
        a, w,
        strides=strides,
        padding=pad,
        rhs_dilation=dil,
        dimension_numbers=dn,
        transpose_kernel=True,
    )
    op = _norm_tuple(output_padding, n)
    if any(op):
        pads = [(0, 0)] * out.ndim
        spatial = range(2, 2 + n) if lhs_spec.startswith("NC") else range(1, 1 + n)
        for i, d in enumerate(spatial):
            pads[d] = (0, op[i])
        out = jnp.pad(out, pads)
    if bias is not None:
        if lhs_spec.startswith("NC"):
            out = out + bias.reshape((1, -1) + (1,) * n)
        else:
            out = out + bias
    return out


def _make_conv_transpose(n, name):
    def convt(x, weight, bias=None, stride=1, padding=0, output_padding=0, groups=1, dilation=1, data_format=None, output_size=None, name_=None, **kw):
        df = data_format or ("NCL" if n == 1 else "NCHW" if n == 2 else "NCDHW")

        def impl(a, w, *rest):
            b = rest[0] if rest else None
            if groups > 1:
                # split groups manually (lax.conv_transpose lacks group support)
                in_per = a.shape[1] // groups if df.startswith("NC") else a.shape[-1] // groups
                outs = []
                for g in range(groups):
                    if df.startswith("NC"):
                        ag = a[:, g * in_per : (g + 1) * in_per]
                    else:
                        ag = a[..., g * in_per : (g + 1) * in_per]
                    wg = w[g * in_per : (g + 1) * in_per]
                    outs.append(
                        _conv_transpose(ag, wg, None, stride, padding, output_padding, dilation, 1, n, df)
                    )
                o = jnp.concatenate(outs, axis=1 if df.startswith("NC") else -1)
                if b is not None:
                    o = o + (b.reshape((1, -1) + (1,) * n) if df.startswith("NC") else b)
                return o
            return _conv_transpose(a, w, b, stride, padding, output_padding, dilation, groups, n, df)

        args = (x, weight) + ((bias,) if bias is not None else ())
        return dispatch(name, impl, args)

    convt.__name__ = name
    return convt


conv1d_transpose = _make_conv_transpose(1, "conv1d_transpose")
conv2d_transpose = _make_conv_transpose(2, "conv2d_transpose")
conv3d_transpose = _make_conv_transpose(3, "conv3d_transpose")
