"""Activation functionals (reference: python/paddle/nn/functional/activation.py)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...core.tensor import Tensor, dispatch, unwrap
from ...framework.random import next_key

__all__ = [
    "relu", "relu_", "relu6", "elu", "elu_", "selu", "celu", "gelu", "silu",
    "swish", "mish", "sigmoid", "hardsigmoid", "hardswish", "hardtanh",
    "hardshrink", "softshrink", "tanhshrink", "leaky_relu", "log_sigmoid",
    "log_softmax", "softmax", "softmax_", "softplus", "softsign", "tanh",
    "tanh_", "thresholded_relu", "maxout", "glu", "swiglu", "prelu", "rrelu",
    "gumbel_softmax",
]


def relu(x, name=None):
    return dispatch("relu", jax.nn.relu, (x,))


def relu_(x, name=None):
    out = relu(x)
    return x._replace(out._array, out._node, out._out_idx)


def relu6(x, name=None):
    return dispatch("relu6", jax.nn.relu6, (x,))


def elu(x, alpha=1.0, name=None):
    return dispatch("elu", lambda a: jax.nn.elu(a, alpha), (x,))


def elu_(x, alpha=1.0, name=None):
    out = elu(x, alpha)
    return x._replace(out._array, out._node, out._out_idx)


def selu(x, scale=1.0507009873554805, alpha=1.6732632423543772, name=None):
    return dispatch("selu", lambda a: scale * jnp.where(a > 0, a, alpha * jnp.expm1(a)), (x,))


def celu(x, alpha=1.0, name=None):
    return dispatch("celu", lambda a: jax.nn.celu(a, alpha), (x,))


def gelu(x, approximate=False, name=None):
    return dispatch("gelu", lambda a: jax.nn.gelu(a, approximate=approximate), (x,))


def silu(x, name=None):
    return dispatch("silu", jax.nn.silu, (x,))


def swish(x, name=None):
    return silu(x)


def mish(x, name=None):
    return dispatch("mish", lambda a: a * jnp.tanh(jax.nn.softplus(a)), (x,))


def sigmoid(x, name=None):
    return dispatch("sigmoid", jax.nn.sigmoid, (x,))


def hardsigmoid(x, slope=0.1666667, offset=0.5, name=None):
    return dispatch("hardsigmoid", lambda a: jnp.clip(slope * a + offset, 0.0, 1.0), (x,))


def hardswish(x, name=None):
    return dispatch("hardswish", lambda a: a * jnp.clip(a + 3.0, 0.0, 6.0) / 6.0, (x,))


def hardtanh(x, min=-1.0, max=1.0, name=None):
    return dispatch("hardtanh", lambda a: jnp.clip(a, min, max), (x,))


def hardshrink(x, threshold=0.5, name=None):
    return dispatch("hardshrink", lambda a: jnp.where(jnp.abs(a) > threshold, a, 0.0), (x,))


def softshrink(x, threshold=0.5, name=None):
    return dispatch(
        "softshrink",
        lambda a: jnp.where(a > threshold, a - threshold, jnp.where(a < -threshold, a + threshold, 0.0)),
        (x,),
    )


def tanhshrink(x, name=None):
    return dispatch("tanhshrink", lambda a: a - jnp.tanh(a), (x,))


def leaky_relu(x, negative_slope=0.01, name=None):
    return dispatch("leaky_relu", lambda a: jax.nn.leaky_relu(a, negative_slope), (x,))


def log_sigmoid(x, name=None):
    return dispatch("log_sigmoid", jax.nn.log_sigmoid, (x,))


def log_softmax(x, axis=-1, dtype=None, name=None):
    from ...framework.dtype import convert_dtype

    d = convert_dtype(dtype)

    def impl(a):
        if d is not None:
            a = a.astype(d)
        return jax.nn.log_softmax(a, axis=axis)

    return dispatch("log_softmax", impl, (x,))


def softmax(x, axis=-1, dtype=None, name=None):
    from ...framework.dtype import convert_dtype

    d = convert_dtype(dtype)

    def impl(a):
        if d is not None:
            a = a.astype(d)
        return jax.nn.softmax(a, axis=axis)

    return dispatch("softmax", impl, (x,))


def softmax_(x, axis=-1, dtype=None, name=None):
    out = softmax(x, axis, dtype)
    return x._replace(out._array, out._node, out._out_idx)


def softplus(x, beta=1.0, threshold=20.0, name=None):
    return dispatch(
        "softplus",
        lambda a: jnp.where(beta * a > threshold, a, jax.nn.softplus(beta * a) / beta),
        (x,),
    )


def softsign(x, name=None):
    return dispatch("softsign", jax.nn.soft_sign, (x,))


def tanh(x, name=None):
    return dispatch("tanh", jnp.tanh, (x,))


def tanh_(x, name=None):
    out = tanh(x)
    return x._replace(out._array, out._node, out._out_idx)


def thresholded_relu(x, threshold=1.0, value=0.0, name=None):
    return dispatch("thresholded_relu", lambda a: jnp.where(a > threshold, a, value), (x,))


def maxout(x, groups, axis=1, name=None):
    def impl(a):
        ax = axis % a.ndim
        c = a.shape[ax]
        new_shape = a.shape[:ax] + (c // groups, groups) + a.shape[ax + 1 :]
        return jnp.max(a.reshape(new_shape), axis=ax + 1)

    return dispatch("maxout", impl, (x,))


def glu(x, axis=-1, name=None):
    return dispatch("glu", lambda a: jax.nn.glu(a, axis=axis), (x,))


def swiglu(x, y=None, name=None):
    """ref: python/paddle/incubate/nn/functional/swiglu (fused op in
    reference paddle/phi/kernels/fusion); here: silu(x) * y."""
    if y is None:
        def impl(a):
            a1, a2 = jnp.split(a, 2, axis=-1)
            return jax.nn.silu(a1) * a2

        return dispatch("swiglu", impl, (x,))
    return dispatch("swiglu", lambda a, b: jax.nn.silu(a) * b, (x, y))


def prelu(x, weight, data_format="NCHW", name=None):
    def impl(a, w):
        if w.size == 1:
            return jnp.where(a > 0, a, w.reshape(()) * a)
        ch_axis = 1 if data_format in ("NCHW", "NCL", "NCDHW") else a.ndim - 1
        shape = [1] * a.ndim
        shape[ch_axis] = w.size
        return jnp.where(a > 0, a, w.reshape(shape) * a)

    return dispatch("prelu", impl, (x, weight))


def rrelu(x, lower=0.125, upper=0.3333333333333333, training=True, name=None):
    if not training:
        return dispatch("rrelu", lambda a: jnp.where(a > 0, a, (lower + upper) / 2 * a), (x,))
    key = next_key()

    def impl(a):
        slope = jax.random.uniform(key, a.shape, minval=lower, maxval=upper).astype(a.dtype)
        return jnp.where(a > 0, a, slope * a)

    return dispatch("rrelu", impl, (x,))


def gumbel_softmax(x, temperature=1.0, hard=False, axis=-1, name=None):
    key = next_key()

    def impl(a):
        g = -jnp.log(-jnp.log(jax.random.uniform(key, a.shape) + 1e-20) + 1e-20)
        y = jax.nn.softmax((a + g) / temperature, axis=axis)
        if hard:
            idx = jnp.argmax(y, axis=axis, keepdims=True)
            y_hard = jnp.zeros_like(y)
            y_hard = jnp.put_along_axis(y_hard, idx, 1.0, axis=axis, inplace=False)
            y = jax.lax.stop_gradient(y_hard - y) + y
        return y

    return dispatch("gumbel_softmax", impl, (x,))
