"""Attention functionals.

Reference: python/paddle/nn/functional/flash_attention.py:198 (flash_attention
op family, ops.yaml:1765-1777) and scaled_dot_product_attention. On TPU the
fused path is a Pallas flash-attention kernel
(paddle_tpu/kernels/flash_attention.py); a jnp reference path covers CPU
tests and odd shapes.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...core.tensor import Tensor, dispatch, unwrap
from ...framework.flags import flag

__all__ = ["scaled_dot_product_attention", "flash_attention", "flash_attn_unpadded", "sdp_kernel"]


def _sdpa_ref(q, k, v, mask, dropout_p, causal, scale=None):
    """Reference attention in fp32 accumulation. q/k/v: [B, S, H, D] (paddle
    flash_attn layout)."""
    qt = jnp.swapaxes(q, 1, 2)  # [B,H,S,D]
    kt = jnp.swapaxes(k, 1, 2)
    vt = jnp.swapaxes(v, 1, 2)
    hq, hk = qt.shape[1], kt.shape[1]
    if hk != hq:  # GQA/MQA: repeat kv heads
        rep = hq // hk
        kt = jnp.repeat(kt, rep, axis=1)
        vt = jnp.repeat(vt, rep, axis=1)
    d = q.shape[-1]
    s = scale if scale is not None else 1.0 / jnp.sqrt(jnp.asarray(d, jnp.float32))
    logits = jnp.einsum("bhqd,bhkd->bhqk", qt, kt).astype(jnp.float32) * s
    if causal:
        ql, kl = logits.shape[-2], logits.shape[-1]
        cm = jnp.tril(jnp.ones((ql, kl), bool), k=kl - ql)
        logits = jnp.where(cm, logits, -1e30)
    if mask is not None:
        if mask.dtype == jnp.bool_:
            logits = jnp.where(mask, logits, -1e30)
        else:
            logits = logits + mask.astype(jnp.float32)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    out = jnp.einsum("bhqk,bhkd->bhqd", probs, vt)
    return jnp.swapaxes(out, 1, 2)  # back to [B,S,H,D]


def flash_attention(query, key, value, dropout=0.0, causal=False, return_softmax=False,
                    fixed_seed_offset=None, rng_name="", training=True, name=None):
    """paddle.nn.functional.flash_attention.flash_attention.

    Layout [batch, seqlen, num_heads, head_dim] (ref ops.yaml:1765 flash_attn).
    Uses the Pallas kernel on TPU for the causal/no-mask path.
    """
    use_pallas = flag("FLAGS_enable_pallas_kernels")
    if use_pallas and dropout == 0.0:
        try:
            from ...kernels.flash_attention import flash_attention_fwd

            out = dispatch(
                "flash_attn",
                lambda q, k, v: flash_attention_fwd(q, k, v, causal=causal),
                (query, key, value),
            )
            return (out, None) if return_softmax else (out, None)
        except Exception:
            pass
    out = dispatch(
        "flash_attn_ref",
        lambda q, k, v: _sdpa_ref(q, k, v, None, dropout, causal),
        (query, key, value),
    )
    return out, None


def scaled_dot_product_attention(query, key, value, attn_mask=None, dropout_p=0.0,
                                 is_causal=False, training=True, name=None):
    """paddle.nn.functional.scaled_dot_product_attention (layout [B,S,H,D])."""
    if attn_mask is None:
        out, _ = flash_attention(query, key, value, dropout=dropout_p if training else 0.0, causal=is_causal)
        return out
    return dispatch(
        "sdpa",
        lambda q, k, v, m: _sdpa_ref(q, k, v, m, dropout_p, is_causal),
        (query, key, value, attn_mask),
    )


def flash_attn_unpadded(query, key, value, cu_seqlens_q, cu_seqlens_k, max_seqlen_q,
                        max_seqlen_k, scale, dropout=0.0, causal=False, return_softmax=False,
                        fixed_seed_offset=None, rng_name="", training=True, name=None):
    """Varlen flash attention (ref: flash_attn_unpadded, ops.yaml:1779).
    Implemented by segment-masked attention over the packed sequence."""

    def impl(q, k, v, cq, ck):
        # q: [total_q, H, D]; build segment ids from cu_seqlens
        total_q = q.shape[0]
        seg_q = jnp.cumsum(jnp.zeros(total_q, jnp.int32).at[cq[1:-1]].add(1))
        total_k = k.shape[0]
        seg_k = jnp.cumsum(jnp.zeros(total_k, jnp.int32).at[ck[1:-1]].add(1))
        logits = jnp.einsum("qhd,khd->hqk", q, k).astype(jnp.float32) * scale
        mask = seg_q[:, None] == seg_k[None, :]
        if causal:
            pos_q = jnp.arange(total_q) - jnp.take(cq, seg_q)
            pos_k = jnp.arange(total_k) - jnp.take(ck, seg_k)
            mask = mask & (pos_q[:, None] >= pos_k[None, :])
        logits = jnp.where(mask[None], logits, -1e30)
        probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
        return jnp.einsum("hqk,khd->qhd", probs, v)

    out = dispatch("flash_attn_unpadded", impl, (query, key, value, cu_seqlens_q, cu_seqlens_k))
    return out, None


def sdp_kernel(*args, **kwargs):
    import contextlib

    @contextlib.contextmanager
    def _noop():
        yield

    return _noop()
