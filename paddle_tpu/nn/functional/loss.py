"""Loss functionals (reference: python/paddle/nn/functional/loss.py)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...core.tensor import Tensor, dispatch, unwrap

__all__ = [
    "cross_entropy", "softmax_with_cross_entropy", "binary_cross_entropy",
    "binary_cross_entropy_with_logits", "mse_loss", "l1_loss", "nll_loss",
    "kl_div", "smooth_l1_loss", "huber_loss", "margin_ranking_loss",
    "cosine_embedding_loss", "hinge_embedding_loss", "triplet_margin_loss",
    "triplet_margin_with_distance_loss", "multi_label_soft_margin_loss",
    "soft_margin_loss", "sigmoid_focal_loss", "square_error_cost",
    "log_loss", "ctc_loss", "poisson_nll_loss", "gaussian_nll_loss",
    "multi_margin_loss", "rnnt_loss", "dice_loss", "npair_loss",
]


def _reduce(out, reduction):
    if reduction == "mean":
        return jnp.mean(out)
    if reduction == "sum":
        return jnp.sum(out)
    return out


def cross_entropy(input, label, weight=None, ignore_index=-100, reduction="mean",
                  soft_label=False, axis=-1, use_softmax=True, label_smoothing=0.0, name=None):
    """paddle.nn.functional.cross_entropy (ref: nn/functional/loss.py).

    Computed in fp32 with log-softmax for numerical stability (same contract
    as phi softmax_with_cross_entropy kernels).
    """
    w = unwrap(weight) if weight is not None else None

    def impl(logits, lab, *rest):
        lp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=axis) if use_softmax else jnp.log(
            jnp.clip(logits.astype(jnp.float32), 1e-30, None)
        )
        n_cls = logits.shape[axis]
        if soft_label:
            soft = lab.astype(jnp.float32)
            if label_smoothing > 0:
                soft = soft * (1 - label_smoothing) + label_smoothing / n_cls
            loss = -jnp.sum(soft * lp, axis=axis)
        else:
            lab_i = lab.astype(jnp.int32)
            if lab_i.ndim == lp.ndim:
                lab_i = jnp.squeeze(lab_i, axis=axis)
            valid = lab_i != ignore_index
            safe = jnp.where(valid, lab_i, 0)
            lp_m = jnp.moveaxis(lp, axis, -1)
            picked = jnp.take_along_axis(lp_m, safe[..., None], axis=-1)[..., 0]
            if label_smoothing > 0:
                smooth_loss = -jnp.mean(lp, axis=axis)
                loss = -(1 - label_smoothing) * picked + label_smoothing * smooth_loss
            else:
                loss = -picked
            loss = jnp.where(valid, loss, 0.0)
            if w is not None:
                wv = jnp.take(w, safe)
                loss = loss * jnp.where(valid, wv, 0.0)
                if reduction == "mean":
                    denom = jnp.sum(jnp.where(valid, wv, 0.0))
                    return jnp.sum(loss) / jnp.maximum(denom, 1e-12)
            if reduction == "mean":
                denom = jnp.sum(valid.astype(jnp.float32))
                return jnp.sum(loss) / jnp.maximum(denom, 1.0)
        return _reduce(loss, reduction)

    return dispatch("cross_entropy", impl, (input, label))


def softmax_with_cross_entropy(logits, label, soft_label=False, ignore_index=-100,
                               numeric_stable_mode=True, return_softmax=False, axis=-1):
    loss = cross_entropy(logits, label, soft_label=soft_label, ignore_index=ignore_index,
                         reduction="none", axis=axis)
    from .activation import softmax as _softmax

    loss = loss.unsqueeze(axis)
    if return_softmax:
        return loss, _softmax(logits, axis=axis)
    return loss


def binary_cross_entropy(input, label, weight=None, reduction="mean", name=None):
    def impl(p, lab, *rest):
        p32 = jnp.clip(p.astype(jnp.float32), 1e-12, 1 - 1e-12)
        out = -(lab * jnp.log(p32) + (1 - lab) * jnp.log1p(-p32))
        if rest:
            out = out * rest[0]
        return _reduce(out, reduction)

    args = (input, label) + ((weight,) if weight is not None else ())
    return dispatch("binary_cross_entropy", impl, args)


def binary_cross_entropy_with_logits(logit, label, weight=None, reduction="mean", pos_weight=None, name=None):
    def impl(z, lab, *rest):
        z32 = z.astype(jnp.float32)
        lab32 = lab.astype(jnp.float32)
        # log(1+exp(-|z|)) formulation
        max_val = jnp.clip(-z32, 0, None)
        if pos_weight is not None:
            pw_t = rest[len(rest) - 1]
            log_weight = (pw_t - 1) * lab32 + 1
            loss = (1 - lab32) * z32 + log_weight * (jnp.log1p(jnp.exp(-jnp.abs(z32))) + max_val)
        else:
            loss = (1 - lab32) * z32 + max_val + jnp.log1p(jnp.exp(-jnp.abs(z32)))
        if weight is not None:
            loss = loss * rest[0]
        return _reduce(loss, reduction)

    args = (logit, label) + tuple(t for t in (weight, pos_weight) if t is not None)
    return dispatch("bce_with_logits", impl, args)


def mse_loss(input, label, reduction="mean", name=None):
    return dispatch("mse_loss", lambda a, b: _reduce((a - b) ** 2, reduction), (input, label))


def square_error_cost(input, label):
    return dispatch("square_error_cost", lambda a, b: (a - b) ** 2, (input, label))


def l1_loss(input, label, reduction="mean", name=None):
    return dispatch("l1_loss", lambda a, b: _reduce(jnp.abs(a - b), reduction), (input, label))


def nll_loss(input, label, weight=None, ignore_index=-100, reduction="mean", name=None):
    w = weight

    def impl(lp, lab, *rest):
        lab_i = lab.astype(jnp.int32)
        valid = lab_i != ignore_index
        safe = jnp.where(valid, lab_i, 0)
        if lp.ndim > 2:  # N,C,d1.. -> move C last
            lpm = jnp.moveaxis(lp, 1, -1)
        else:
            lpm = lp
        picked = jnp.take_along_axis(lpm, safe[..., None], axis=-1)[..., 0]
        loss = -jnp.where(valid, picked, 0.0)
        if rest:
            wv = jnp.take(rest[0], safe)
            loss = loss * jnp.where(valid, wv, 0.0)
            if reduction == "mean":
                return jnp.sum(loss) / jnp.maximum(jnp.sum(jnp.where(valid, wv, 0.0)), 1e-12)
        if reduction == "mean":
            return jnp.sum(loss) / jnp.maximum(jnp.sum(valid.astype(jnp.float32)), 1.0)
        return _reduce(loss, reduction)

    args = (input, label) + ((w,) if w is not None else ())
    return dispatch("nll_loss", impl, args)


def kl_div(input, label, reduction="mean", log_target=False, name=None):
    def impl(lp, t):
        if log_target:
            out = jnp.exp(t) * (t - lp)
        else:
            out = t * (jnp.log(jnp.clip(t, 1e-12, None)) - lp)
        if reduction == "batchmean":
            return jnp.sum(out) / lp.shape[0]
        return _reduce(out, reduction)

    return dispatch("kl_div", impl, (input, label))


def smooth_l1_loss(input, label, reduction="mean", delta=1.0, name=None):
    def impl(a, b):
        d = jnp.abs(a - b)
        out = jnp.where(d < delta, 0.5 * d * d / delta, d - 0.5 * delta)
        return _reduce(out, reduction)

    return dispatch("smooth_l1_loss", impl, (input, label))


def huber_loss(input, label, delta=1.0, reduction="mean", name=None):
    def impl(a, b):
        d = jnp.abs(a - b)
        out = jnp.where(d <= delta, 0.5 * d * d, delta * (d - 0.5 * delta))
        return _reduce(out, reduction)

    return dispatch("huber_loss", impl, (input, label))


def margin_ranking_loss(input, other, label, margin=0.0, reduction="mean", name=None):
    return dispatch(
        "margin_ranking_loss",
        lambda a, b, l: _reduce(jnp.clip(-l * (a - b) + margin, 0, None), reduction),
        (input, other, label),
    )


def cosine_embedding_loss(input1, input2, label, margin=0, reduction="mean", name=None):
    def impl(a, b, l):
        cos = jnp.sum(a * b, axis=-1) / (
            jnp.linalg.norm(a, axis=-1) * jnp.linalg.norm(b, axis=-1) + 1e-12
        )
        out = jnp.where(l == 1, 1 - cos, jnp.clip(cos - margin, 0, None))
        return _reduce(out, reduction)

    return dispatch("cosine_embedding_loss", impl, (input1, input2, label))


def hinge_embedding_loss(input, label, margin=1.0, reduction="mean", name=None):
    def impl(a, l):
        out = jnp.where(l == 1, a, jnp.clip(margin - a, 0, None))
        return _reduce(out, reduction)

    return dispatch("hinge_embedding_loss", impl, (input, label))


def triplet_margin_loss(input, positive, negative, margin=1.0, p=2.0, epsilon=1e-06, swap=False, reduction="mean", name=None):
    def impl(a, pos, neg):
        dp = jnp.linalg.norm(a - pos + epsilon, ord=p, axis=-1)
        dn = jnp.linalg.norm(a - neg + epsilon, ord=p, axis=-1)
        if swap:
            dsn = jnp.linalg.norm(pos - neg + epsilon, ord=p, axis=-1)
            dn = jnp.minimum(dn, dsn)
        return _reduce(jnp.clip(dp - dn + margin, 0, None), reduction)

    return dispatch("triplet_margin_loss", impl, (input, positive, negative))


def triplet_margin_with_distance_loss(input, positive, negative, distance_function=None, margin=1.0, swap=False, reduction="mean", name=None):
    if distance_function is None:
        return triplet_margin_loss(input, positive, negative, margin=margin, swap=swap, reduction=reduction)
    dp = distance_function(input, positive)
    dn = distance_function(input, negative)
    if swap:
        dsn = distance_function(positive, negative)
        from ...ops import minimum

        dn = minimum(dn, dsn)
    from ...ops import clip, mean as _mean, sum as _sum

    out = clip(dp - dn + margin, min=0)
    if reduction == "mean":
        return _mean(out)
    if reduction == "sum":
        return _sum(out)
    return out


def multi_label_soft_margin_loss(input, label, weight=None, reduction="mean", name=None):
    def impl(z, y, *rest):
        out = -(y * jax.nn.log_sigmoid(z) + (1 - y) * jax.nn.log_sigmoid(-z))
        if rest:
            out = out * rest[0]
        return _reduce(jnp.mean(out, axis=-1), reduction)

    args = (input, label) + ((weight,) if weight is not None else ())
    return dispatch("multi_label_soft_margin_loss", impl, args)


def soft_margin_loss(input, label, reduction="mean", name=None):
    return dispatch(
        "soft_margin_loss",
        lambda z, y: _reduce(jnp.log1p(jnp.exp(-y * z)), reduction),
        (input, label),
    )


def sigmoid_focal_loss(logit, label, normalizer=None, alpha=0.25, gamma=2.0, reduction="sum", name=None):
    def impl(z, y, *rest):
        p = jax.nn.sigmoid(z)
        ce = (1 - y) * z + jnp.clip(-z, 0, None) + jnp.log1p(jnp.exp(-jnp.abs(z)))
        p_t = p * y + (1 - p) * (1 - y)
        a_t = alpha * y + (1 - alpha) * (1 - y)
        loss = a_t * ((1 - p_t) ** gamma) * ce
        if rest:
            loss = loss / rest[0]
        return _reduce(loss, reduction)

    args = (logit, label) + ((normalizer,) if normalizer is not None else ())
    return dispatch("sigmoid_focal_loss", impl, args)


def log_loss(input, label, epsilon=1e-4, name=None):
    return dispatch(
        "log_loss",
        lambda p, y: -y * jnp.log(p + epsilon) - (1 - y) * jnp.log(1 - p + epsilon),
        (input, label),
    )


def dice_loss(input, label, epsilon=1e-5, name=None):
    def impl(p, y):
        y1 = jax.nn.one_hot(y[..., 0], p.shape[-1], dtype=p.dtype)
        red = tuple(range(1, p.ndim))
        inter = jnp.sum(p * y1, axis=red)
        union = jnp.sum(p, axis=red) + jnp.sum(y1, axis=red)
        return jnp.mean(1 - (2 * inter + epsilon) / (union + epsilon))

    return dispatch("dice_loss", impl, (input, label))


def npair_loss(anchor, positive, labels, l2_reg=0.002):
    def impl(a, p, l):
        sim = a @ p.T
        lab = l.reshape(-1)
        target = (lab[:, None] == lab[None, :]).astype(jnp.float32)
        target = target / jnp.sum(target, axis=1, keepdims=True)
        ce = -jnp.sum(target * jax.nn.log_softmax(sim, axis=1), axis=1)
        l2 = jnp.mean(jnp.sum(a * a, axis=1) + jnp.sum(p * p, axis=1))
        return jnp.mean(ce) + l2_reg * l2 * 0.25

    return dispatch("npair_loss", impl, (anchor, positive, labels))


def poisson_nll_loss(input, label, log_input=True, full=False, epsilon=1e-8, reduction="mean", name=None):
    def impl(z, y):
        if log_input:
            out = jnp.exp(z) - y * z
        else:
            out = z - y * jnp.log(z + epsilon)
        if full:
            stirling = y * jnp.log(y + (y == 0)) - y + 0.5 * jnp.log(2 * jnp.pi * (y + (y == 0)))
            out = out + jnp.where(y > 1, stirling, 0.0)
        return _reduce(out, reduction)

    return dispatch("poisson_nll_loss", impl, (input, label))


def gaussian_nll_loss(input, label, variance, full=False, epsilon=1e-6, reduction="mean", name=None):
    def impl(mu, y, v):
        v = jnp.clip(v, epsilon, None)
        out = 0.5 * (jnp.log(v) + (y - mu) ** 2 / v)
        if full:
            out = out + 0.5 * jnp.log(jnp.asarray(2 * jnp.pi))
        return _reduce(out, reduction)

    return dispatch("gaussian_nll_loss", impl, (input, label, variance))


def multi_margin_loss(input, label, p=1, margin=1.0, weight=None, reduction="mean", name=None):
    def impl(z, y, *rest):
        n, c = z.shape
        correct = jnp.take_along_axis(z, y[:, None].astype(jnp.int32), axis=1)
        m = jnp.clip(margin - correct + z, 0, None) ** p
        if rest:
            m = m * jnp.take(rest[0], y)[:, None]
        mask = 1 - jax.nn.one_hot(y, c, dtype=z.dtype)
        out = jnp.sum(m * mask, axis=1) / c
        return _reduce(out, reduction)

    args = (input, label) + ((weight,) if weight is not None else ())
    return dispatch("multi_margin_loss", impl, args)


def ctc_loss(log_probs, labels, input_lengths, label_lengths, blank=0, reduction="mean", norm_by_times=False):
    """CTC via dynamic-programming in log space (reference: phi
    warpctc_kernel). log_probs: [T, N, C] (paddle layout)."""

    def impl(lp, lab):
        T, N, C = lp.shape
        lp = jax.nn.log_softmax(lp.astype(jnp.float32), axis=-1)
        il = unwrap(input_lengths)
        ll = unwrap(label_lengths)
        S = lab.shape[1]
        # extended label seq with blanks: length 2S+1
        ext = jnp.full((N, 2 * S + 1), blank, dtype=jnp.int32)
        ext = ext.at[:, 1::2].set(lab.astype(jnp.int32))
        neg_inf = jnp.asarray(-1e30, jnp.float32)

        def step(alpha, lp_t):
            # alpha [N, 2S+1]
            shift1 = jnp.concatenate([jnp.full((N, 1), neg_inf), alpha[:, :-1]], axis=1)
            shift2 = jnp.concatenate([jnp.full((N, 2), neg_inf), alpha[:, :-2]], axis=1)
            ext_prev2 = jnp.concatenate([jnp.full((N, 2), -1, jnp.int32), ext[:, :-2]], axis=1)
            allow_skip = (ext != blank) & (ext != ext_prev2)
            merged = jnp.logaddexp(alpha, shift1)
            merged = jnp.where(allow_skip, jnp.logaddexp(merged, shift2), merged)
            emit = jnp.take_along_axis(lp_t, ext, axis=1)
            return merged + emit, None

        alpha0 = jnp.full((N, 2 * S + 1), neg_inf)
        alpha0 = alpha0.at[:, 0].set(lp[0, :, blank])
        first_lab = jnp.take_along_axis(lp[0], ext[:, 1:2], axis=1)[:, 0]
        alpha0 = alpha0.at[:, 1].set(first_lab)

        def scan_body(carry, t):
            alpha = carry
            new_alpha, _ = step(alpha, lp[t])
            # freeze past input_lengths
            new_alpha = jnp.where((t < il)[:, None], new_alpha, alpha)
            return new_alpha, None

        alpha, _ = jax.lax.scan(scan_body, alpha0, jnp.arange(1, T))
        # gather final positions: 2*label_len and 2*label_len-1
        idx_last = (2 * ll).astype(jnp.int32)
        a_last = jnp.take_along_axis(alpha, idx_last[:, None], axis=1)[:, 0]
        a_prev = jnp.take_along_axis(alpha, jnp.clip(idx_last - 1, 0, None)[:, None], axis=1)[:, 0]
        ll_total = jnp.logaddexp(a_last, a_prev)
        loss = -ll_total
        if reduction == "mean":
            return jnp.mean(loss / jnp.maximum(ll.astype(jnp.float32), 1.0))
        return _reduce(loss, reduction)

    return dispatch("ctc_loss", impl, (log_probs, labels))


def rnnt_loss(input, label, input_lengths, label_lengths, blank=0, fastemit_lambda=0.001, reduction="mean", name=None):
    """RNN-T loss via alpha-recursion (reference: phi warprnnt kernel)."""

    def impl(logits, lab):
        B, T, U1, C = logits.shape
        lp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        il = unwrap(input_lengths)
        ul = unwrap(label_lengths)
        neg_inf = -1e30

        def one(lp_b, lab_b, T_b, U_b):
            U = U1 - 1
            # alpha [T, U+1]
            blank_lp = lp_b[:, :, blank]  # [T, U+1]
            lab_idx = jnp.concatenate([lab_b.astype(jnp.int32), jnp.zeros((1,), jnp.int32)])
            emit_lp = jnp.take_along_axis(lp_b, jnp.broadcast_to(lab_idx[None, :, None], (T, U1, 1)), axis=2)[:, :, 0]

            def row(carry, t):
                prev = carry  # alpha[t-1, :]
                def col(c2, u):
                    cur = c2
                    from_left = jnp.where(u > 0, cur[u - 1] + emit_lp[t, u - 1], neg_inf)
                    from_down = jnp.where(t > 0, prev[u] + blank_lp[t - 1, u], neg_inf)
                    init = jnp.where((t == 0) & (u == 0), 0.0, neg_inf)
                    val = jnp.logaddexp(jnp.logaddexp(from_left, from_down), init)
                    return cur.at[u].set(val), None

                cur0 = jnp.full((U1,), neg_inf)
                cur, _ = jax.lax.scan(col, cur0, jnp.arange(U1))
                return cur, cur

            _, alphas = jax.lax.scan(row, jnp.full((U1,), neg_inf), jnp.arange(T))
            final = alphas[T_b - 1, U_b] + blank_lp[T_b - 1, U_b]
            return -final

        loss = jax.vmap(one)(lp, lab, il.astype(jnp.int32), ul.astype(jnp.int32))
        return _reduce(loss, reduction)

    return dispatch("rnnt_loss", impl, (input, label))
