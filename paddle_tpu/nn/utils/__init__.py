"""paddle.nn.utils — re-parameterization hooks and gradient utilities.

TPU-native re-implementation of the reference nn.utils package:

- weight_norm / remove_weight_norm
  (reference: python/paddle/nn/utils/weight_norm_hook.py:178,224)
- spectral_norm
  (reference: python/paddle/nn/utils/spectral_norm_hook.py:163)
- clip_grad_norm_ / clip_grad_value_
  (reference: python/paddle/nn/utils/clip_grad_norm_.py:28,
   clip_grad_value_.py:28)
- parameters_to_vector / vector_to_parameters
  (reference: python/paddle/nn/utils/transform_parameters.py:85,138)

The hooks follow the reference design — the named parameter is replaced by
derived parameters (`weight_g`/`weight_v`, or `weight_orig` + `u`/`v`
buffers) and a forward-pre-hook recomputes the effective weight through
dispatch ops, so eager autograd reaches the derived parameters.  All math is
jnp closed forms; there is no translated kernel code.
"""
from __future__ import annotations

import math

import jax.numpy as jnp

from ...core.tensor import Parameter, Tensor, dispatch, unwrap
from ...core import tape as _tape

__all__ = [
    "weight_norm",
    "remove_weight_norm",
    "spectral_norm",
    "parameters_to_vector",
    "vector_to_parameters",
    "clip_grad_norm_",
    "clip_grad_value_",
]


# ---------------------------------------------------------------------------
# weight_norm
# ---------------------------------------------------------------------------
def _norm_except_dim_arr(p, dim):
    """||p|| reduced over every axis except `dim` (dim=-1 → full norm)."""
    if dim == -1:
        return jnp.sqrt(jnp.sum(jnp.square(p)) + 1e-12)
    axes = tuple(a for a in range(p.ndim) if a != dim)
    return jnp.sqrt(jnp.sum(jnp.square(p), axis=axes) + 1e-12)


def norm_except_dim(p, dim: int) -> Tensor:
    return dispatch("norm_except_dim", lambda a: _norm_except_dim_arr(a, dim), (p,))


def _weight_norm_arr(v, g, dim):
    """w = g * v / ||v||_{except dim}, broadcasting g over the kept axis."""
    if dim == -1:
        return v * (g / jnp.sqrt(jnp.sum(jnp.square(v)) + 1e-12))
    norm = _norm_except_dim_arr(v, dim)
    shape = [1] * v.ndim
    shape[dim] = v.shape[dim]
    return v * (g / norm).reshape(shape)


class WeightNorm:
    """Forward-pre-hook: recompute `name` from `name_g` / `name_v`."""

    def __init__(self, name: str, dim: int):
        self.name = name
        self.dim = -1 if dim is None else dim

    def compute_weight(self, layer) -> Tensor:
        g = getattr(layer, self.name + "_g")
        v = getattr(layer, self.name + "_v")
        return dispatch(
            "weight_norm", lambda va, ga: _weight_norm_arr(va, ga, self.dim), (v, g)
        )

    @staticmethod
    def apply(layer, name: str, dim) -> "WeightNorm":
        for hook in layer._forward_pre_hooks.values():
            if isinstance(hook, WeightNorm) and hook.name == name:
                raise RuntimeError(
                    f"Cannot register two weight_norm hooks on the same parameter {name}"
                )
        if dim is None:
            dim = -1
        w = layer._parameters[name]
        ndim = len(w.shape)
        if not (-ndim <= dim < ndim):
            raise AssertionError(
                "dim must set between [-R, R), R means the dimension of weight."
            )
        if dim != -1:
            dim = dim % ndim

        fn = WeightNorm(name, dim)
        del layer._parameters[name]
        w_arr = unwrap(w)
        layer.add_parameter(name + "_v", Parameter(w_arr))
        layer.add_parameter(
            name + "_g", Parameter(_norm_except_dim_arr(w_arr, dim))
        )
        setattr(layer, name, fn.compute_weight(layer).detach())
        layer.register_forward_pre_hook(fn)
        return fn

    def remove(self, layer):
        w = self.compute_weight(layer).detach()
        delattr(layer, self.name)
        del layer._parameters[self.name + "_g"]
        del layer._parameters[self.name + "_v"]
        layer.add_parameter(self.name, Parameter(unwrap(w)))

    def __call__(self, layer, inputs):
        setattr(layer, self.name, self.compute_weight(layer))


def weight_norm(layer, name: str = "weight", dim: int = 0):
    """w = g * v/||v||; replaces `name` with `name_g` + `name_v` parameters.

    Reference: python/paddle/nn/utils/weight_norm_hook.py:178.
    """
    WeightNorm.apply(layer, name, dim)
    return layer


def remove_weight_norm(layer, name: str = "weight"):
    """Reference: python/paddle/nn/utils/weight_norm_hook.py:224."""
    for k, hook in list(layer._forward_pre_hooks.items()):
        if isinstance(hook, WeightNorm) and hook.name == name:
            hook.remove(layer)
            del layer._forward_pre_hooks[k]
            return layer
    raise ValueError(f"weight_norm of '{name}' not found in {type(layer).__name__}")


# ---------------------------------------------------------------------------
# spectral_norm
# ---------------------------------------------------------------------------
def _l2n(x, eps):
    return x / jnp.maximum(jnp.linalg.norm(x), eps)


class SpectralNorm:
    """Forward-pre-hook: w / sigma_max(w) via power iteration on u/v buffers.

    Reference: python/paddle/nn/utils/spectral_norm_hook.py:40.
    """

    def __init__(self, name="weight", n_power_iterations=1, dim=0, eps=1e-12):
        if n_power_iterations <= 0:
            raise ValueError(
                "Expected n_power_iterations to be positive, but "
                f"got n_power_iterations={n_power_iterations}"
            )
        self.name = name
        self.dim = dim
        self.n_power_iterations = n_power_iterations
        self.eps = eps

    def _to_matrix(self, w):
        if self.dim != 0:
            perm = [self.dim] + [d for d in range(w.ndim) if d != self.dim]
            w = jnp.transpose(w, perm)
        return w.reshape(w.shape[0], -1)

    def compute_weight(self, layer, do_power_iteration: bool) -> Tensor:
        weight = getattr(layer, self.name + "_orig")
        u_t = getattr(layer, self.name + "_u")
        v_t = getattr(layer, self.name + "_v")
        if do_power_iteration:
            w_mat = self._to_matrix(unwrap(weight))
            u, v = unwrap(u_t), unwrap(v_t)
            for _ in range(self.n_power_iterations):
                v = _l2n(w_mat.T @ u, self.eps)
                u = _l2n(w_mat @ v, self.eps)
            # persist the iterated vectors (buffers are state, not autograd)
            setattr(layer, self.name + "_u", Tensor(u))
            setattr(layer, self.name + "_v", Tensor(v))
            u_t, v_t = getattr(layer, self.name + "_u"), getattr(layer, self.name + "_v")

        def impl(w, u, v):
            sigma = u @ (self._to_matrix(w) @ v)
            return w / sigma

        return dispatch("spectral_norm", impl, (weight, u_t, v_t))

    def __call__(self, layer, inputs):
        setattr(layer, self.name, self.compute_weight(layer, layer.training))

    @staticmethod
    def apply(layer, name, n_power_iterations, dim, eps) -> "SpectralNorm":
        for hook in layer._forward_pre_hooks.values():
            if isinstance(hook, SpectralNorm) and hook.name == name:
                raise RuntimeError(
                    f"Cannot register two spectral_norm hooks on the same parameter {name}"
                )
        fn = SpectralNorm(name, n_power_iterations, dim, eps)
        weight = layer._parameters[name]
        w_mat = fn._to_matrix(unwrap(weight))
        h, w = w_mat.shape
        from ...framework.random import next_key
        import jax

        ku, kv = jax.random.split(next_key())
        u = _l2n(jax.random.normal(ku, (h,), dtype=w_mat.dtype), eps)
        v = _l2n(jax.random.normal(kv, (w,), dtype=w_mat.dtype), eps)

        del layer._parameters[name]
        layer.add_parameter(name + "_orig", weight)
        # plain attribute so inits that poke `name` keep working
        object.__setattr__(layer, name, Tensor(unwrap(weight)))
        layer.register_buffer(name + "_u", Tensor(u))
        layer.register_buffer(name + "_v", Tensor(v))
        layer.register_forward_pre_hook(fn)
        return fn


def spectral_norm(
    layer, name: str = "weight", n_power_iterations: int = 1, eps: float = 1e-12, dim=None
):
    """Reference: python/paddle/nn/utils/spectral_norm_hook.py:163."""
    if dim is None:
        # Linear / conv-transpose weights keep out_features on axis 1
        from ..layer.common import Linear as _Linear

        transpose_types = [_Linear]
        try:
            from ..layer.conv import (
                Conv1DTranspose, Conv2DTranspose, Conv3DTranspose)

            transpose_types += [Conv1DTranspose, Conv2DTranspose, Conv3DTranspose]
        except ImportError:  # pragma: no cover
            pass
        dim = 1 if isinstance(layer, tuple(transpose_types)) else 0
    SpectralNorm.apply(layer, name, n_power_iterations, dim, eps)
    return layer


# ---------------------------------------------------------------------------
# parameter <-> vector
# ---------------------------------------------------------------------------
def parameters_to_vector(parameters, name=None) -> Tensor:
    """Flatten parameters into one 1-D tensor.

    Reference: python/paddle/nn/utils/transform_parameters.py:85.
    """
    parameters = list(parameters)
    if not parameters:
        raise ValueError("parameters_to_vector got an empty parameter list")
    vec = jnp.concatenate([unwrap(p).reshape(-1) for p in parameters])
    return Tensor(vec, stop_gradient=False)


def vector_to_parameters(vec, parameters, name=None) -> None:
    """Slice a 1-D tensor back into the parameters, in place.

    Reference: python/paddle/nn/utils/transform_parameters.py:138.
    """
    parameters = list(parameters)
    arr = unwrap(vec)
    sizes = [int(math.prod(p.shape)) if p.shape else 1 for p in parameters]
    if sum(sizes) != arr.shape[0]:
        raise ValueError(
            f"vector has {arr.shape[0]} elements but parameters need {sum(sizes)}"
        )
    offset = 0
    for p, n in zip(parameters, sizes):
        chunk = arr[offset : offset + n].reshape(tuple(p.shape))
        p._array = jnp.asarray(chunk, dtype=unwrap(p).dtype)
        offset += n


# ---------------------------------------------------------------------------
# gradient clipping (in place on .grad)
# ---------------------------------------------------------------------------
def clip_grad_norm_(
    parameters, max_norm, norm_type: float = 2.0, error_if_nonfinite: bool = False
) -> Tensor:
    """Clip the global norm of the parameters' gradients, in place.

    Reference: python/paddle/nn/utils/clip_grad_norm_.py:28.
    """
    if isinstance(parameters, Tensor):
        parameters = [parameters]
    parameters = list(parameters)
    if norm_type not in (float("inf"), 0, 1, 2):
        raise ValueError("norm_type only support [inf, 0, 1, 2]")
    max_norm = float(max_norm)
    norm_type = float(norm_type)
    with _tape.no_grad():
        grads = [p._grad for p in parameters if p._grad is not None]
        if not grads:
            return Tensor(jnp.asarray(0.0))
        if norm_type == float("inf"):
            total = jnp.max(jnp.stack([jnp.max(jnp.abs(g)) for g in grads]))
        else:
            per = jnp.stack(
                [jnp.linalg.norm(g.reshape(-1), ord=norm_type) for g in grads]
            )
            total = jnp.linalg.norm(per, ord=norm_type)
        if error_if_nonfinite and not bool(jnp.isfinite(total)):
            raise RuntimeError(
                f"The total norm of {norm_type} order of the gradients from "
                "`parameters` is non-finite, so it cannot be clipped. To disable "
                "this error and scale the gradient by the non-finite norm, "
                "set `error_if_nonfinite=False`"
            )
        coef = jnp.minimum(max_norm / (total + 1e-6), 1.0)
        for p in parameters:
            if p._grad is not None:
                p._grad = p._grad * coef
        return Tensor(total)


def clip_grad_value_(parameters, clip_value) -> None:
    """Clamp every gradient element into [-clip_value, clip_value], in place.

    Reference: python/paddle/nn/utils/clip_grad_value_.py:28.
    """
    if isinstance(parameters, Tensor):
        parameters = [parameters]
    clip_value = float(clip_value)
    with _tape.no_grad():
        for p in parameters:
            if p._grad is not None:
                p._grad = jnp.clip(p._grad, -clip_value, clip_value)
