"""QAT layer wrappers (reference: python/paddle/nn/quant/qat/{linear,conv}.py)."""
from __future__ import annotations

from ..layer.layers import Layer

__all__ = ["QuantedLinear", "QuantedConv2D", "ConvertibleQuantedLayer"]


class ConvertibleQuantedLayer(Layer):
    """Base for QAT layers that can convert to deploy (quant/dequant) form."""

    def weights_to_quanters(self):
        raise NotImplementedError

    def activation_quanters(self):
        raise NotImplementedError


def _instance(factory, layer):
    if factory is None:
        return None
    if hasattr(factory, "_instance"):
        return factory._instance(layer)
    if hasattr(factory, "instance"):
        return factory.instance(layer)
    return factory


class QuantedLinear(ConvertibleQuantedLayer):
    """Linear with fake-quantized input/weight (reference qat/linear.py:22)."""

    def __init__(self, layer, q_config):
        super().__init__()
        self.weight = layer.weight
        self.bias = getattr(layer, "bias", None)
        self.name = getattr(layer, "name", None)
        self.weight_quanter = _instance(getattr(q_config, "weight", None), layer)
        self.activation_quanter = _instance(getattr(q_config, "activation", None), layer)

    def forward(self, input):
        from .. import functional as F

        q_in = self.activation_quanter(input) if self.activation_quanter else input
        q_w = self.weight_quanter(self.weight) if self.weight_quanter else self.weight
        return F.linear(q_in, q_w, self.bias)

    def weights_to_quanters(self):
        return [("weight", "weight_quanter")]

    def activation_quanters(self):
        return ["activation_quanter"]


class QuantedConv2D(ConvertibleQuantedLayer):
    """Conv2D with fake-quantized input/weight (reference qat/conv.py:23)."""

    def __init__(self, layer, q_config):
        super().__init__()
        self.weight = layer.weight
        self.bias = getattr(layer, "bias", None)
        self._conv_args = dict(
            stride=layer._stride, padding=layer._padding,
            dilation=layer._dilation, groups=layer._groups,
            data_format=getattr(layer, "_data_format", "NCHW"),
        )
        self.weight_quanter = _instance(getattr(q_config, "weight", None), layer)
        self.activation_quanter = _instance(getattr(q_config, "activation", None), layer)

    def forward(self, input):
        from .. import functional as F

        q_in = self.activation_quanter(input) if self.activation_quanter else input
        q_w = self.weight_quanter(self.weight) if self.weight_quanter else self.weight
        return F.conv2d(q_in, q_w, self.bias, **self._conv_args)

    def weights_to_quanters(self):
        return [("weight", "weight_quanter")]

    def activation_quanters(self):
        return ["activation_quanter"]
