"""Functional-op wrapper layers so tensor ops can be quantized like layers.

Reference: python/paddle/nn/quant/functional_layers.py:21.
"""
from __future__ import annotations

from ...core import tensor as _ct
from ...ops import manipulation as _manip
from ...ops import math as _math
from ..layer.layers import Layer

__all__ = [
    "FloatFunctionalLayer", "add", "subtract", "multiply", "divide",
    "reshape", "transpose", "concat", "flatten", "matmul",
]


class FloatFunctionalLayer(Layer):
    def __init__(self):
        super().__init__()


class add(FloatFunctionalLayer):
    def forward(self, x, y, name=None):
        return _math.add(x, y)


class subtract(FloatFunctionalLayer):
    def forward(self, x, y, name=None):
        return _math.subtract(x, y)


class multiply(FloatFunctionalLayer):
    def forward(self, x, y, name=None):
        return _math.multiply(x, y)


class divide(FloatFunctionalLayer):
    def forward(self, x, y, name=None):
        return _math.divide(x, y)


class reshape(FloatFunctionalLayer):
    def forward(self, x, shape, name=None):
        return _manip.reshape(x, shape)


class transpose(FloatFunctionalLayer):
    def forward(self, x, perm, name=None):
        return _manip.transpose(x, perm)


class concat(FloatFunctionalLayer):
    def forward(self, x, axis=0, name=None):
        return _manip.concat(x, axis)


class flatten(FloatFunctionalLayer):
    def forward(self, x, start_axis=0, stop_axis=-1, name=None):
        return _manip.flatten(x, start_axis, stop_axis)


class matmul(FloatFunctionalLayer):
    def forward(self, x, y, transpose_x=False, transpose_y=False, name=None):
        return _math.matmul(x, y, transpose_x, transpose_y)
