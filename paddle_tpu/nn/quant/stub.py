"""Quantization stubs (reference: python/paddle/nn/quant/stub.py:29,86)."""
from __future__ import annotations

from ..layer.layers import Layer

__all__ = ["Stub", "QuanterStub"]


class Stub(Layer):
    """Placeholder marking where an activation quanter should be inserted.

    Carries an optional observer/quanter factory; ``QuantConfig`` replaces it
    with a :class:`QuanterStub` during ``QAT.quantize``.
    """

    def __init__(self, observer=None):
        super().__init__()
        self._observer = observer

    def forward(self, input):
        return input


class QuanterStub(Layer):
    """A Stub converted for QAT: applies the configured quanter in forward."""

    def __init__(self, layer: Stub, q_config=None):
        super().__init__()
        self._quanter = None
        factory = layer._observer
        if factory is None and q_config is not None:
            factory = getattr(q_config, "activation", None)
        if factory is not None:
            self._quanter = factory.instance(layer) if hasattr(factory, "instance") else factory

    def forward(self, input):
        return self._quanter(input) if self._quanter is not None else input
