"""Fake-quant layers for QAT/PTQ simulation.

Reference: python/paddle/nn/quant/quant_layers.py (FakeQuantAbsMax:69,
FakeQuantMovingAverageAbsMax:172, FakeQuantChannelWiseAbsMax:310,
MovingAverageAbsMaxScale:424, QuantizedLinear:769, QuantizedConv2D:544,
QuantStub via stub.py). Quant math is simulated in float (fake quant) —
real int8 execution lives in quantized_linear.py.
"""
from __future__ import annotations

import jax.numpy as jnp

from ...core.tensor import Tensor, dispatch, unwrap
from ..layer.layers import Layer

__all__ = [
    "FakeQuantAbsMax",
    "FakeQuantMovingAverageAbsMax",
    "FakeQuantChannelWiseAbsMax",
    "MovingAverageAbsMaxScale",
    "QuantizedLinear",
    "QuantizedConv2D",
    "QuantStub",
]


def _ema_guard(arr, layer_name):
    """EMA scale buffers are Python-side state: updating them from a
    traced value would capture a tracer (leaked-tracer error on next use)
    and be silently wrong under vmap/grad. These layers are eager-only
    QAT simulation in training mode — refuse loudly instead of corrupting
    the buffer (round-3 advisor finding)."""
    import jax

    if isinstance(arr, jax.core.Tracer):
        raise RuntimeError(
            f"{layer_name}: the moving-average scale update runs in "
            f"training mode under a jax transform (jit/grad/vmap); the "
            f"EMA buffer write would capture a tracer. Run QAT forward "
            f"eagerly, or call .eval() to freeze the scale before "
            f"jitting.")


def _fake_quant(a, scale, qmax):
    import jax

    s = jnp.maximum(scale, 1e-8)
    out = jnp.clip(jnp.round(a / s * qmax), -qmax, qmax) * s / qmax
    # straight-through estimator: quantization noise is constant w.r.t. a,
    # so QAT gradients pass through unchanged
    return a + jax.lax.stop_gradient(out - a)


class FakeQuantAbsMax(Layer):
    """Per-tensor absmax fake quant (scale recomputed every forward)."""

    def __init__(self, name=None, quant_bits=8, dtype="float32", reduce_type=None):
        super().__init__()
        self._quant_bits = quant_bits

    def forward(self, input):
        qmax = float(2 ** (self._quant_bits - 1) - 1)

        def impl(a):
            return _fake_quant(a, jnp.max(jnp.abs(a)), qmax)

        return dispatch("fake_quant_abs_max", impl, (input,))


class FakeQuantMovingAverageAbsMax(Layer):
    """EMA-absmax fake quant; scale is a buffer updated in training mode."""

    def __init__(self, name=None, moving_rate=0.9, quant_bits=8, dtype="float32",
                 reduce_type=None):
        super().__init__()
        self._moving_rate = moving_rate
        self._quant_bits = quant_bits
        self.register_buffer("scale", Tensor(jnp.zeros((), jnp.float32)))
        self.register_buffer("state", Tensor(jnp.zeros((), jnp.float32)))

    def forward(self, input):
        qmax = float(2 ** (self._quant_bits - 1) - 1)
        if self.training:
            _ema_guard(unwrap(input), type(self).__name__)
            cur = jnp.max(jnp.abs(unwrap(input))).astype(jnp.float32)
            r = self._moving_rate
            state = unwrap(self.state) * r + 1.0
            accum = unwrap(self.scale) * unwrap(self.state) * r + cur
            scale = accum / state
            self.scale = Tensor(scale)
            self.state = Tensor(state)
        scale = unwrap(self.scale)
        return dispatch("fake_quant_ma_abs_max", lambda a: _fake_quant(a, scale, qmax), (input,))


class FakeQuantChannelWiseAbsMax(Layer):
    """Per-channel absmax fake quant along ``quant_axis``."""

    def __init__(self, name=None, channel_num=None, quant_bits=8, quant_axis=0,
                 dtype="float32", reduce_type=None):
        super().__init__()
        self._quant_bits = quant_bits
        self._quant_axis = quant_axis

    def forward(self, input):
        qmax = float(2 ** (self._quant_bits - 1) - 1)
        axis = self._quant_axis

        def impl(a):
            axes = tuple(i for i in range(a.ndim) if i != axis)
            scale = jnp.max(jnp.abs(a), axis=axes, keepdims=True)
            return _fake_quant(a, scale, qmax)

        return dispatch("fake_quant_cw_abs_max", impl, (input,))


class MovingAverageAbsMaxScale(Layer):
    """Track an EMA output scale without altering the tensor."""

    def __init__(self, name=None, moving_rate=0.9, dtype="float32", reduce_type=None):
        super().__init__()
        self._moving_rate = moving_rate
        self.register_buffer("scale", Tensor(jnp.zeros((), jnp.float32)))
        self.register_buffer("state", Tensor(jnp.zeros((), jnp.float32)))

    def forward(self, input):
        if self.training:
            _ema_guard(unwrap(input), type(self).__name__)
            cur = jnp.max(jnp.abs(unwrap(input))).astype(jnp.float32)
            r = self._moving_rate
            state = unwrap(self.state) * r + 1.0
            accum = unwrap(self.scale) * unwrap(self.state) * r + cur
            self.scale = Tensor(accum / state)
            self.state = Tensor(state)
        return input


def _get_fake_quant_type(quant_type: str, **kwargs):
    table = {
        "abs_max": FakeQuantAbsMax,
        "moving_average_abs_max": FakeQuantMovingAverageAbsMax,
        "channel_wise_abs_max": FakeQuantChannelWiseAbsMax,
    }
    if quant_type not in table:
        raise ValueError(f"unsupported weight quantize type {quant_type}")
    cls = table[quant_type]
    import inspect

    allowed = set(inspect.signature(cls.__init__).parameters)
    return cls(**{k: v for k, v in kwargs.items() if k in allowed})


class QuantizedLinear(Layer):
    """Simulated-quant Linear: fake-quant weight (+ activation), then linear."""

    def __init__(self, layer, weight_quantize_type="abs_max",
                 activation_quantize_type="moving_average_abs_max",
                 weight_bits=8, activation_bits=8, moving_rate=0.9, **kwargs):
        super().__init__()
        self.weight = layer.weight
        self.bias = getattr(layer, "bias", None)
        self.name = getattr(layer, "name", None)
        self._fake_quant_weight = _get_fake_quant_type(
            weight_quantize_type, quant_bits=weight_bits, quant_axis=1)
        self._fake_quant_input = _get_fake_quant_type(
            activation_quantize_type, quant_bits=activation_bits, moving_rate=moving_rate)

    def forward(self, input):
        from .. import functional as F

        q_input = self._fake_quant_input(input)
        q_weight = self._fake_quant_weight(self.weight)
        return F.linear(q_input, q_weight, self.bias)


class QuantizedConv2D(Layer):
    """Simulated-quant Conv2D."""

    def __init__(self, layer, weight_quantize_type="abs_max",
                 activation_quantize_type="moving_average_abs_max",
                 weight_bits=8, activation_bits=8, moving_rate=0.9, **kwargs):
        super().__init__()
        self.weight = layer.weight
        self.bias = getattr(layer, "bias", None)
        self._conv_args = dict(
            stride=layer._stride, padding=layer._padding,
            dilation=layer._dilation, groups=layer._groups,
            data_format=getattr(layer, "_data_format", "NCHW"),
        )
        self._fake_quant_weight = _get_fake_quant_type(
            weight_quantize_type, quant_bits=weight_bits, quant_axis=0)
        self._fake_quant_input = _get_fake_quant_type(
            activation_quantize_type, quant_bits=activation_bits, moving_rate=moving_rate)

    def forward(self, input):
        from .. import functional as F

        q_input = self._fake_quant_input(input)
        q_weight = self._fake_quant_weight(self.weight)
        return F.conv2d(q_input, q_weight, self.bias, **self._conv_args)


class QuantStub(Layer):
    """Marks a quantization boundary; identity until converted."""

    def __init__(self, observer=None):
        super().__init__()
        self._observer = observer

    def forward(self, input):
        return input
