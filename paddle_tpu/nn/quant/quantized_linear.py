"""Weight-only quantized linear algebra for LLM serving.

TPU-native re-implementation of the reference weight-only-quant family
(reference: python/paddle/nn/quant/quantized_linear.py:54 weight_quantize,
:120 weight_dequantize, :180 weight_only_linear, :273 llm_int8_linear,
:339 apply_per_channel_scale).

Layouts (TPU convention, documented here because it differs from the CUDA
kernels' tile-swizzled layouts):

- ``weight_quantize(x[K, N])`` returns ``(w_q, scale)`` with ``w_q`` stored
  **transposed** ``[N, K]`` like the reference. int8 keeps one value per
  byte; int4 packs two adjacent K-values per int8 byte → ``[N, K//2]``
  (low nibble = even k, high nibble = odd k).
- Per-channel (``group_size=-1``): ``scale`` is ``[N]`` float32.
  Grouped (``group_size ∈ {64, 128}``): ``scale`` is ``[ceil(K/g), N]``.

The matmul keeps weights int8 in HBM and lets XLA fuse the dequantize
convert into the dot — that is the entire win on a memory-bound decode:
half (int8) or quarter (int4) the weight bytes per step. ``arch`` is
accepted for API parity and ignored (no SM versions on TPU).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...core.tensor import Tensor, dispatch, unwrap
from ...framework import dtype as dtypes

__all__ = [
    "weight_quantize",
    "weight_dequantize",
    "weight_only_linear",
    "llm_int8_linear",
    "apply_per_channel_scale",
]

_ALGOS = ("weight_only_int8", "weight_only_int4", "llm.int8")


def _check(algo, group_size):
    if algo not in _ALGOS:
        raise ValueError(f"algo must be one of {_ALGOS}, got {algo!r}")
    if group_size not in (-1, 64, 128):
        raise ValueError(f"group_size only supports -1/64/128, got {group_size}")


def _pack_int4(q):
    """[N, K] int8 values in [-8, 7] → [N, K//2] packed bytes. K must be
    even — the packed layout carries no original-K metadata, so an odd K
    could not be recovered by weight_dequantize."""
    n, k = q.shape
    if k % 2:
        raise ValueError(
            f"weight_only_int4 requires an even input-feature dim, got K={k}")
    lo = q[:, 0::2] & 0x0F
    hi = (q[:, 1::2] & 0x0F) << 4
    return (lo | hi).astype(jnp.int8)


def _unpack_int4(w, k):
    """[N, K//2] packed bytes → [N, K] int8 values in [-8, 7]."""
    lo = (w.astype(jnp.int32) & 0x0F).astype(jnp.int8)
    hi = ((w.astype(jnp.int32) >> 4) & 0x0F).astype(jnp.int8)
    # sign-extend nibbles
    lo = jnp.where(lo >= 8, lo - 16, lo)
    hi = jnp.where(hi >= 8, hi - 16, hi)
    out = jnp.stack([lo, hi], axis=-1).reshape(w.shape[0], -1)
    return out[:, :k]


def weight_quantize(x, algo: str = "weight_only_int8", arch=None, group_size: int = -1):
    """Quantize a [K, N] weight to int8/int4 with per-channel or grouped scales.

    Returns (w_q [N, K] int8, scale float32). Reference:
    python/paddle/nn/quant/quantized_linear.py:54.
    """
    _check(algo, group_size)
    a = jnp.asarray(unwrap(x))
    if a.ndim != 2:
        raise ValueError(f"weight_quantize expects a 2-D weight, got shape {a.shape}")
    k, n = a.shape
    wt = a.T.astype(jnp.float32)  # [N, K]
    qmax = 7.0 if algo == "weight_only_int4" else 127.0
    if group_size == -1:
        scale = jnp.max(jnp.abs(wt), axis=1) / qmax  # [N]
        scale = jnp.maximum(scale, 1e-8)
        q = jnp.clip(jnp.round(wt / scale[:, None]), -qmax - 1, qmax)
    else:
        g = -(-k // group_size)
        pad = g * group_size - k
        wp = jnp.pad(wt, ((0, 0), (0, pad))).reshape(n, g, group_size)
        scale = jnp.max(jnp.abs(wp), axis=2) / qmax  # [N, G]
        scale = jnp.maximum(scale, 1e-8)
        q = jnp.clip(jnp.round(wp / scale[:, :, None]), -qmax - 1, qmax)
        q = q.reshape(n, g * group_size)[:, :k]
        scale = scale.T  # [G, N] — reference group-scale layout
    q = q.astype(jnp.int8)
    if algo == "weight_only_int4":
        q = _pack_int4(q)
    return Tensor(q), Tensor(scale.astype(jnp.float32))


def weight_dequantize(x, scale, algo: str = "weight_only_int8",
                      out_dtype="float16", group_size: int = -1):
    """Invert :func:`weight_quantize` → [K, N] float. Reference :120."""
    _check(algo, group_size)
    w = jnp.asarray(unwrap(x))
    s = jnp.asarray(unwrap(scale))
    out_dtype = dtypes.convert_dtype(out_dtype) or "float16"
    if algo == "weight_only_int4":
        k = s.shape[0] * group_size if group_size != -1 else w.shape[1] * 2
        w = _unpack_int4(w, k)
    n, k = w.shape
    if group_size == -1:
        deq = w.astype(jnp.float32) * s[:, None]
    else:
        g = s.shape[0]
        pad = g * group_size - k
        wp = jnp.pad(w, ((0, 0), (0, pad))).reshape(n, g, group_size)
        deq = (wp.astype(jnp.float32) * s.T[:, :, None]).reshape(n, g * group_size)[:, :k]
    return Tensor(deq.T.astype(out_dtype))


def _weight_only_matmul(xa, w, s, weight_dtype, group_size):
    """out[..., N] = xa[..., K] @ dequant(w).T with int8/int4 weights in HBM."""
    if weight_dtype == "int4":
        k = xa.shape[-1]
        w = _unpack_int4(w, k)
    n, k = w.shape
    if group_size == -1:
        # per-channel scale commutes with the K-contraction → scale the output;
        # the int8→bf16 convert fuses into the dot, weights stay int8 in HBM
        out = jnp.einsum("...k,nk->...n", xa, w.astype(xa.dtype),
                         preferred_element_type=jnp.float32)
        out = out * s.astype(jnp.float32)
    else:
        g = s.shape[0]
        pad = g * group_size - k
        xp = jnp.pad(xa, [(0, 0)] * (xa.ndim - 1) + [(0, pad)])
        xg = xp.reshape(*xa.shape[:-1], g, group_size)
        wp = jnp.pad(w, ((0, 0), (0, pad))).reshape(n, g, group_size)
        # contract within each group, then apply the [G, N] scales
        out = jnp.einsum("...gk,ngk->...gn", xg, wp.astype(xa.dtype),
                         preferred_element_type=jnp.float32)
        out = jnp.einsum("...gn,gn->...n", out, s.astype(jnp.float32))
    return out.astype(xa.dtype)


def weight_only_linear(x, weight, bias=None, weight_scale=None,
                       weight_dtype: str = "int8", arch=None, group_size: int = -1):
    """y = x @ dequant(weight).T + bias with int8/int4 [N, K] weights.

    Reference: python/paddle/nn/quant/quantized_linear.py:180.
    """
    if weight_dtype not in ("int8", "int4"):
        raise ValueError(f"weight_dtype must be 'int8' or 'int4', got {weight_dtype!r}")
    if group_size not in (-1, 64, 128):
        raise ValueError(f"group_size only supports -1/64/128, got {group_size}")
    if weight_scale is None:
        raise ValueError("weight_only_linear requires weight_scale")

    def impl(xa, w, s, *rest):
        out = _weight_only_matmul(xa, w, s, weight_dtype, group_size)
        if rest:
            out = out + rest[0].astype(out.dtype)
        return out

    args = (x, weight, weight_scale) + (() if bias is None else (bias,))
    return dispatch("weight_only_linear", impl, args)


def llm_int8_linear(x, weight, bias=None, weight_scale=None, threshold: float = 6.0):
    """LLM.int8() linear: int8×int8 matmul with fp outlier decomposition.

    Activation columns whose absmax ≥ ``threshold`` stay in x.dtype and hit a
    dequantized matmul; the rest are dynamically quantized per-token to int8
    so the main GEMM runs int8×int8 (int32 accumulate on the MXU). Masking
    keeps shapes static for jit. Reference:
    python/paddle/nn/quant/quantized_linear.py:273.
    """
    if weight_scale is None:
        raise ValueError("llm_int8_linear requires weight_scale")

    def impl(xa, w, s, *rest):
        xf = xa.astype(jnp.float32)
        col_amax = jnp.max(jnp.abs(xf), axis=tuple(range(xa.ndim - 1)))  # [K]
        outlier = col_amax >= threshold
        x_in = jnp.where(outlier, 0.0, xf)
        x_out = jnp.where(outlier, xf, 0.0)
        # per-token dynamic quantization of the inlier block
        tok_scale = jnp.maximum(jnp.max(jnp.abs(x_in), axis=-1, keepdims=True), 1e-8) / 127.0
        xq = jnp.clip(jnp.round(x_in / tok_scale), -128, 127).astype(jnp.int8)
        main = jax.lax.dot_general(
            xq, w,
            (((xq.ndim - 1,), (1,)), ((), ())),
            preferred_element_type=jnp.int32,
        ).astype(jnp.float32)
        main = main * tok_scale * s.astype(jnp.float32)
        outliers = jnp.einsum("...k,nk->...n", x_out, w.astype(jnp.float32) * s[:, None])
        out = (main + outliers).astype(xa.dtype)
        if rest:
            out = out + rest[0].astype(out.dtype)
        return out

    args = (x, weight, weight_scale) + (() if bias is None else (bias,))
    return dispatch("llm_int8_linear", impl, args)


def apply_per_channel_scale(x, scales):
    """Pre-scale activations per channel (smooth-quant style). Reference :339."""
    return dispatch("apply_per_channel_scale", lambda a, s: a * s.astype(a.dtype), (x, scales))
