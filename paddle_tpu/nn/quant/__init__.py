"""paddle.nn.quant — weight-only quant serving ops + QAT layers.

Reference: python/paddle/nn/quant/__init__.py.
"""
from . import qat  # noqa: F401
from .functional_layers import (  # noqa: F401
    FloatFunctionalLayer,
    add,
    concat,
    divide,
    flatten,
    matmul,
    multiply,
    reshape,
    subtract,
    transpose,
)
from .quant_layers import (  # noqa: F401
    FakeQuantAbsMax,
    FakeQuantChannelWiseAbsMax,
    FakeQuantMovingAverageAbsMax,
    MovingAverageAbsMaxScale,
    QuantizedConv2D,
    QuantizedLinear,
    QuantStub,
)
from .quantized_linear import (  # noqa: F401
    apply_per_channel_scale,
    llm_int8_linear,
    weight_dequantize,
    weight_only_linear,
    weight_quantize,
)
from .stub import Stub  # noqa: F401

__all__ = [
    "Stub",
    "weight_only_linear",
    "llm_int8_linear",
    "weight_quantize",
    "weight_dequantize",
]
