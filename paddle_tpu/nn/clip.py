"""Gradient clipping (reference: python/paddle/nn/clip.py).

Clip objects are attached to an Optimizer (grad_clip=...) and applied to the
whole (param, grad) list before the update — same contract as the reference's
ClipGradByGlobalNorm._dygraph_clip.
"""
from __future__ import annotations

import jax.numpy as jnp

__all__ = ["ClipGradByGlobalNorm", "ClipGradByNorm", "ClipGradByValue", "clip_grad_norm_", "clip_grad_value_"]


class ClipGradBase:
    def apply(self, grads):
        """grads: list of jax arrays (aligned with params); returns new list."""
        raise NotImplementedError


class ClipGradByGlobalNorm(ClipGradBase):
    def __init__(self, clip_norm=1.0, group_name="default_group", auto_skip_clip=False):
        self.clip_norm = float(clip_norm)

    def apply(self, grads):
        sq = sum(jnp.sum(jnp.square(g.astype(jnp.float32))) for g in grads)
        global_norm = jnp.sqrt(sq)
        scale = self.clip_norm / jnp.maximum(global_norm, self.clip_norm)
        return [(g * scale).astype(g.dtype) for g in grads]


class ClipGradByNorm(ClipGradBase):
    def __init__(self, clip_norm=1.0):
        self.clip_norm = float(clip_norm)

    def apply(self, grads):
        out = []
        for g in grads:
            n = jnp.sqrt(jnp.sum(jnp.square(g.astype(jnp.float32))))
            scale = self.clip_norm / jnp.maximum(n, self.clip_norm)
            out.append((g * scale).astype(g.dtype))
        return out


class ClipGradByValue(ClipGradBase):
    def __init__(self, max, min=None):
        self.max = float(max)
        self.min = float(min) if min is not None else -self.max

    def apply(self, grads):
        return [jnp.clip(g, self.min, self.max) for g in grads]


# canonical implementations live in paddle.nn.utils; keep these names
# importable from nn.clip for reference parity (python/paddle/nn/clip.py)
from .utils import clip_grad_norm_, clip_grad_value_  # noqa: E402,F401
