"""paddle.distributed.auto_parallel.dygraph (reference:
distributed/auto_parallel/dygraph/__init__.py) — the dynamic-graph
semi-auto API (shard_tensor & friends)."""
from ...api import (  # noqa: F401
    Partial,
    Replicate,
    Shard,
    dtensor_from_fn,
    reshard,
    shard_layer,
    shard_optimizer,
    shard_tensor,
)
