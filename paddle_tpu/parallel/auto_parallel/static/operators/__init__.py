"""paddle.distributed.auto_parallel.static.operators (reference:
distributed/auto_parallel/static/operators/) — per-op SPMD rules; the
runtime registry is parallel/spmd_rules.py."""
from ....spmd_rules import SpmdRule, get_spmd_rule, register_spmd_rule  # noqa: F401
