"""paddle.distributed.auto_parallel.static.engine (reference:
distributed/auto_parallel/static/engine.py)."""
from .. import Engine  # noqa: F401

__all__ = ["Engine"]
