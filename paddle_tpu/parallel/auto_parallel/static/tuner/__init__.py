"""paddle.distributed.auto_parallel.static.tuner (reference:
distributed/auto_parallel/static/tuner/) — parallel-config search; the
runtime implementation is parallel/auto_tuner.py."""
from ....auto_tuner import AutoTuner, Candidate, TunerConfig  # noqa: F401
