"""paddle.distributed.auto_parallel.static.cost (reference:
distributed/auto_parallel/static/cost/) — analytic + measured cost model
(parallel/cost_model.py)."""
from ....cost_model import comp_time, transformer_memory_gb, transformer_step_cost  # noqa: F401

__all__ = ["comp_time", "transformer_step_cost", "transformer_memory_gb"]
