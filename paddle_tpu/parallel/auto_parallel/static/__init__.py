"""paddle.distributed.auto_parallel.static (reference:
distributed/auto_parallel/static/) — the static Engine path. Under jax the
completion→partition→compile pipeline is one jitted trace
(parallel/trainer.py make_train_step); Engine adapts it."""
from .. import Engine  # noqa: F401
from . import cost  # noqa: F401
from . import operators  # noqa: F401
from . import tuner  # noqa: F401
from .engine import Engine as _Engine  # noqa: F401
