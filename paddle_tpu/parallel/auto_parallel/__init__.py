"""paddle.distributed.auto_parallel (reference:
distributed/auto_parallel/__init__.py) — semi-auto SPMD entry points."""
from ..api import (  # noqa: F401
    Partial,
    ProcessMesh,
    Replicate,
    Shard,
    dtensor_from_fn,
    reshard,
    shard_layer,
    shard_optimizer,
    shard_tensor,
)
from ..compat import DistModel, Strategy, to_static  # noqa: F401
from ..fleet_utils import recompute  # noqa: F401
from ..mesh import build_mesh, get_global_mesh, set_global_mesh  # noqa: F401

__all__ = []


def create_mesh(mesh_dims):
    """Build + install the global mesh from [(name, size), ...] dims
    (reference: auto_parallel/interface.py create_mesh)."""
    names = [d[0] for d in mesh_dims]
    shape = [int(d[1]) for d in mesh_dims]
    mesh = build_mesh(shape, names)
    set_global_mesh(mesh)
    return mesh


def get_mesh():
    """reference: auto_parallel/interface.py get_mesh."""
    return get_global_mesh()


def set_mesh(mesh):
    """reference: auto_parallel/interface.py set_mesh."""
    jm = getattr(mesh, "jax_mesh", mesh)
    set_global_mesh(jm)


def shard_op(op_fn, process_mesh=None, in_shard_specs=None, out_shard_specs=None):
    """Annotate an op call with input/output sharding constraints
    (reference: auto_parallel/interface.py shard_op). Under jax this wraps
    the op with with_sharding_constraint on its outputs."""
    from ..api import shard_constraint

    def wrapped(*args, **kwargs):
        out = op_fn(*args, **kwargs)
        if out_shard_specs is not None:
            specs = out_shard_specs if isinstance(out_shard_specs, (list, tuple)) else [out_shard_specs]
            if isinstance(out, (list, tuple)):
                out = type(out)(
                    shard_constraint(o, s, process_mesh) if s is not None else o
                    for o, s in zip(out, specs))
            elif specs and specs[0] is not None:
                out = shard_constraint(out, specs[0], process_mesh)
        return out

    return wrapped


def exclude_ops_in_recompute(run_function):
    """Mark a function's ops as not-recomputed (reference:
    auto_parallel/interface.py). The jax analog: jax.checkpoint policy
    'everything_saveable' over the wrapped region."""
    import jax

    return jax.checkpoint(run_function, policy=jax.checkpoint_policies.everything_saveable)


def fetch(tensor, name=None, logging=False):
    """reference: auto_parallel/interface.py fetch — eager jax arrays are
    already host-observable; returns the tensor."""
    return tensor


def parallel_manual_seed(seed, name=""):
    """reference: auto_parallel/random.py — deterministic per-mesh-position
    seeding; jax PRNG keys are already position-folded by the framework."""
    from ...framework import random as _random

    _random.seed(seed)


class Engine:
    """Static auto-parallel engine (reference:
    distributed/auto_parallel/static/engine.py Engine). Adapter over the
    jitted hybrid-parallel step: prepare/fit/evaluate/predict with the same
    strategy consumption as DistModel."""

    def __init__(self, model=None, loss=None, optimizer=None, metrics=None,
                 strategy=None):
        self._model = model
        self._loss = loss
        self._optimizer = optimizer
        self._metrics = metrics or []
        self._strategy = strategy
        self._dist_model = None

    def _ensure(self, loader=None):
        if self._dist_model is None:
            self._dist_model = DistModel(
                self._model, loader, loss=self._loss,
                optimizer=self._optimizer, strategy=self._strategy)
        return self._dist_model

    def prepare(self, *args, **kwargs):
        return self._ensure()

    def fit(self, train_data, epochs=1, batch_size=None, steps_per_epoch=None,
            log_freq=None, **kwargs):
        dm = self._ensure(train_data)
        dm.train()
        history = []
        for _ in range(epochs):
            for step, batch in enumerate(train_data):
                if steps_per_epoch is not None and step >= steps_per_epoch:
                    break
                batch = batch if isinstance(batch, (list, tuple)) else (batch,)
                loss = dm(*batch)
                history.append(float(loss._array if hasattr(loss, "_array") else loss))
        return history

    def evaluate(self, valid_data, steps=None, **kwargs):
        dm = self._ensure(valid_data)
        dm.eval()
        losses = []
        for step, batch in enumerate(valid_data):
            if steps is not None and step >= steps:
                break
            batch = batch if isinstance(batch, (list, tuple)) else (batch,)
            out = dm(*batch)
            losses.append(float(out._array if hasattr(out, "_array") else out))
        return {"loss": sum(losses) / max(len(losses), 1)}

    def predict(self, test_data, steps=None, **kwargs):
        dm = self._ensure(test_data)
        dm.eval()
        outs = []
        for step, batch in enumerate(test_data):
            if steps is not None and step >= steps:
                break
            batch = batch if isinstance(batch, (list, tuple)) else (batch,)
            outs.append(dm(batch[0]))
        return outs

    def state_dict(self, mode="all"):
        return self._ensure().state_dict(mode)
