"""Ulysses sequence-parallel attention reshard (explicit all-to-all).

Reference analog: the `sep` axis groups of fleet/base/topology.py:224-244 and
the reference's SegmentParallel attention (DeepSpeed-Ulysses style,
arXiv:2309.14509): activations enter attention sharded over sequence, and
attention needs full sequence per head — so the seq shards are exchanged for
head shards with one all-to-all over the sep group, and swapped back after.

GSPMD cannot lower the seq<->head re-constraint efficiently (it logs
"[SPMD] Involuntary full rematerialization" and replicates), so the swap is
done explicitly with jax.shard_map + lax.all_to_all riding ICI.
"""
from __future__ import annotations

import math
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from .mesh import BATCH_AXES, divisible_prefix as _divisible_prefix

from .shard_map_compat import shard_map


def _axes_size(mesh: Mesh, names) -> int:
    return math.prod(int(mesh.shape[n]) for n in names)


def sep_degree(mesh: Optional[Mesh], seq_axis: str = "sep") -> int:
    if mesh is None or seq_axis not in mesh.axis_names:
        return 1
    return int(mesh.shape[seq_axis])


def ulysses_available(mesh: Optional[Mesh], num_heads: int, seq_len: int,
                      seq_axis: str = "sep",
                      head_axes: Tuple[str, ...] = ("mp",)) -> bool:
    """True when the explicit a2a path applies: sep>1 and both the head and
    seq dims split evenly over their axes."""
    if sep_degree(mesh, seq_axis) <= 1:
        return False
    g = _axes_size(mesh, [a for a in head_axes if a in mesh.axis_names])
    sep = int(mesh.shape[seq_axis])
    return num_heads % (g * sep) == 0 and seq_len % sep == 0


def minimal_kv_repeat(mesh: Mesh, num_heads: int, num_kv_heads: int,
                      seq_axis: str = "sep",
                      head_axes: Tuple[str, ...] = ("mp",)) -> int:
    """Smallest per-kv-head repeat factor r so nkv*r splits evenly over
    mp*sep AND still block-aligns with q's contiguous head shards
    (num_heads % (nkv*r) == 0). Falls back to the full nh/nkv repeat when
    no smaller factor aligns."""
    g = _axes_size(mesh, [a for a in head_axes if a in mesh.axis_names])
    g *= int(mesh.shape[seq_axis])
    full = num_heads // num_kv_heads
    r = g // math.gcd(num_kv_heads, g)
    if r <= full and num_heads % (num_kv_heads * r) == 0:
        return r
    return full


def _specs(mesh, shape, seq_axis, head_axes):
    """(seq-sharded spec, head-sharded spec) for a [b, s, h, d] tensor."""
    bspec = _divisible_prefix(mesh, shape[0], BATCH_AXES)
    heads = tuple(a for a in head_axes if a in mesh.axis_names)
    seq_spec = P(bspec or None, seq_axis, heads or None, None)
    head_spec = P(bspec or None, None, (heads + (seq_axis,)) or None, None)
    return seq_spec, head_spec


def seq_to_head(x: jax.Array, mesh: Mesh, seq_axis: str = "sep",
                head_axes: Tuple[str, ...] = ("mp",)) -> jax.Array:
    """[b, s/sep, H/mp, d] -> [b, s, H/(mp*sep), d]: one tiled all-to-all
    over the sep group (split heads, concat sequence)."""
    seq_spec, head_spec = _specs(mesh, x.shape, seq_axis, head_axes)

    def swap(a):
        return jax.lax.all_to_all(a, seq_axis, split_axis=2, concat_axis=1,
                                  tiled=True)

    return shard_map(swap, mesh=mesh, in_specs=seq_spec,
                         out_specs=head_spec, check_vma=False)(x)


def head_to_seq(x: jax.Array, mesh: Mesh, seq_axis: str = "sep",
                head_axes: Tuple[str, ...] = ("mp",)) -> jax.Array:
    """[b, s, H/(mp*sep), d] -> [b, s/sep, H/mp, d]: the reverse swap."""
    seq_spec, head_spec = _specs(mesh, x.shape, seq_axis, head_axes)

    def swap(a):
        return jax.lax.all_to_all(a, seq_axis, split_axis=1, concat_axis=2,
                                  tiled=True)

    return shard_map(swap, mesh=mesh, in_specs=head_spec,
                         out_specs=seq_spec, check_vma=False)(x)
