"""Gang supervisor: spawn, watch, and relaunch a checkpoint-coordinated
worker gang (ISSUE 12).

The CPU-testable analog of a multi-host slice launcher: N subprocess
workers form a gang, rendezvous through a shared
`resilience.store.FileStore` directory, and checkpoint through the
coordinated two-phase protocol (`resilience/coordination.py`). The
supervisor's job is the RECOVERY loop the Llama-3 report credits for
its fleet availability — detect a dead worker fast, tear the survivors
down (a gang whose member vanished is blocked at its next barrier
anyway), and relaunch everyone into ``fit(resume=True)`` where
generation agreement rolls the whole gang back to one common
checkpoint:

    sup = GangSupervisor([sys.executable, "train.py"], nprocs=4,
                         store_dir="/tmp/gang-store", max_restarts=3)
    result = sup.run()      # GangResult: attempts, restarts, success

Workers read their identity from the environment the supervisor
exports — ``PADDLE_GANG_RANK``, ``PADDLE_GANG_WORLD_SIZE``,
``PADDLE_GANG_STORE``, ``PADDLE_GANG_ATTEMPT``, ``PADDLE_GANG_JOB`` —
typically via ``resilience.coordination.from_env()``. Each relaunch
bumps the ATTEMPT, which namespaces every coordination key: a dead
incarnation's barrier arrivals can never satisfy the new gang's.

Restart semantics are whole-gang (the torchrun/MPI model): ANY nonzero
worker exit fails the attempt, survivors get SIGTERM (grace) then
SIGKILL, and all N ranks relaunch. A rank that already exited 0 is
relaunched too — its resumed run restores the agreed generation and
re-drains to completion, which is idempotent by construction.
"""
from __future__ import annotations

import os
import signal
import subprocess
import sys
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Union

__all__ = ["GangResult", "GangSupervisor"]

_Argv = Union[Sequence[str], Callable[[int], Sequence[str]]]
_Env = Union[None, Dict[str, str], Callable[[int, int], Dict[str, str]]]


@dataclass
class GangResult:
    """What a supervised gang run amounted to."""

    success: bool
    attempts: int                    # launch rounds actually run
    world_size: int
    exit_codes: List[int]            # final attempt, by rank
    # every relaunch decision: (rank, attempt_it_died_in, exit_code);
    # exit_code < 0 is -signal (e.g. -9 = SIGKILLed, a host preemption)
    restarts: List[tuple] = field(default_factory=list)
    wall_s: float = 0.0
    recovery_wall_s: float = 0.0     # death detected -> gang respawned

    def as_dict(self) -> dict:
        return {"success": self.success, "attempts": self.attempts,
                "world_size": self.world_size,
                "exit_codes": self.exit_codes,
                "restarts": [list(r) for r in self.restarts],
                "wall_s": round(self.wall_s, 3),
                "recovery_wall_s": round(self.recovery_wall_s, 3)}


class GangSupervisor:
    """Spawn/monitor/relaunch an N-worker gang (module docstring).

    Parameters:
      argv: worker command line (list), or ``rank -> list`` callable.
      nprocs: gang world size.
      store_dir: FileStore directory the gang rendezvouses through
        (created; also hosts per-attempt worker logs under ``logs/``).
      max_restarts: relaunch rounds after the first (0 = one shot).
      env: extra environment — a dict, or ``(rank, attempt) -> dict``
        callable. The per-attempt form is how a one-shot chaos fault
        (``PADDLE_TPU_CHAOS=preempt_host:K@N``) is armed on attempt 0
        only: a preemption is an external event, not a property of the
        worker, and re-arming it on the resumed run would re-kill the
        relaunched rank when it replays step N.
      terminate_grace_s: SIGTERM -> SIGKILL grace for survivors of a
        failed attempt.
      poll_interval: worker liveness poll period.
    """

    def __init__(self, argv: _Argv, nprocs: int, *, store_dir: str,
                 job_id: str = "gang", max_restarts: int = 3,
                 env: _Env = None, terminate_grace_s: float = 5.0,
                 poll_interval: float = 0.05):
        if nprocs < 1:
            raise ValueError(f"nprocs must be >= 1, got {nprocs}")
        self.argv = argv
        self.nprocs = nprocs
        self.store_dir = str(store_dir)
        self.job_id = job_id
        self.max_restarts = int(max_restarts)
        self.env = env
        self.terminate_grace_s = terminate_grace_s
        self.poll_interval = poll_interval
        os.makedirs(os.path.join(self.store_dir, "logs"), exist_ok=True)

    # -- per-worker plumbing -------------------------------------------
    def _argv_for(self, rank: int) -> List[str]:
        a = self.argv(rank) if callable(self.argv) else self.argv
        return [str(x) for x in a]

    def _env_for(self, rank: int, attempt: int) -> Dict[str, str]:
        env = dict(os.environ)
        extra = (self.env(rank, attempt) or {}) if callable(self.env) \
            else (self.env or {})
        # a None value means "unset" — Popen rejects non-str env values
        env.update({k: str(v) for k, v in extra.items() if v is not None})
        for k, v in extra.items():
            if v is None:
                env.pop(k, None)
        env.update({
            "PADDLE_GANG_RANK": str(rank),
            "PADDLE_GANG_WORLD_SIZE": str(self.nprocs),
            "PADDLE_GANG_STORE": self.store_dir,
            "PADDLE_GANG_ATTEMPT": str(attempt),
            "PADDLE_GANG_JOB": self.job_id,
        })
        return env

    def log_path(self, rank: int, attempt: int) -> str:
        return os.path.join(self.store_dir, "logs",
                            f"attempt{attempt:02d}-rank{rank:02d}.log")

    def _spawn(self, rank: int, attempt: int) -> subprocess.Popen:
        log = open(self.log_path(rank, attempt), "wb")
        try:
            return subprocess.Popen(
                self._argv_for(rank), env=self._env_for(rank, attempt),
                stdout=log, stderr=subprocess.STDOUT,
                start_new_session=True)
        finally:
            log.close()  # the child holds its own fd

    @staticmethod
    def _terminate(procs: Dict[int, subprocess.Popen], grace: float):
        for p in procs.values():
            if p.poll() is None:
                try:
                    p.terminate()
                except OSError:
                    pass
        deadline = time.monotonic() + grace
        for p in procs.values():
            while p.poll() is None and time.monotonic() < deadline:
                time.sleep(0.02)
            if p.poll() is None:
                try:
                    p.kill()
                except OSError:
                    pass
                p.wait()

    # -- the recovery loop ---------------------------------------------
    def run(self, timeout: Optional[float] = None) -> GangResult:
        """Supervise until the whole gang exits 0, restarts are
        exhausted, or `timeout` (whole run, seconds) expires. Never
        raises on worker failure — inspect `GangResult.success` (and
        the per-attempt logs under ``{store_dir}/logs/``)."""
        from ...observability import record_event

        t_start = time.monotonic()
        deadline = t_start + timeout if timeout else None
        restarts: List[tuple] = []
        recovery_wall = 0.0
        t_detect = None  # of the failure that triggered this relaunch
        attempt = 0
        while True:
            procs = {r: self._spawn(r, attempt)
                     for r in range(self.nprocs)}
            if t_detect is not None:
                # death detected -> replacement gang fully respawned
                recovery_wall += time.monotonic() - t_detect
                t_detect = None
            failed_rank = None
            failed_code = 0
            while True:
                codes = {r: p.poll() for r, p in procs.items()}
                bad = {r: c for r, c in codes.items()
                       if c is not None and c != 0}
                if bad:
                    failed_rank = min(bad)
                    failed_code = bad[failed_rank]
                    break
                if all(c == 0 for c in codes.values()):
                    return GangResult(
                        True, attempt + 1, self.nprocs,
                        [codes[r] for r in range(self.nprocs)],
                        restarts, time.monotonic() - t_start,
                        recovery_wall)
                if deadline is not None and time.monotonic() > deadline:
                    self._terminate(procs, self.terminate_grace_s)
                    return GangResult(
                        False, attempt + 1, self.nprocs,
                        [procs[r].poll() if procs[r].poll() is not None
                         else -1 for r in range(self.nprocs)],
                        restarts, time.monotonic() - t_start,
                        recovery_wall)
                time.sleep(self.poll_interval)
            # a worker died (host preemption = -SIGKILL) or errored
            # (e.g. a survivor's BarrierTimeout): whole-gang restart
            t_detect = time.monotonic()
            self._terminate(procs, self.terminate_grace_s)
            if attempt >= self.max_restarts:
                return GangResult(
                    False, attempt + 1, self.nprocs,
                    [procs[r].poll() for r in range(self.nprocs)],
                    restarts, time.monotonic() - t_start, recovery_wall)
            for r in range(self.nprocs):
                restarts.append((r, attempt, procs[r].poll()))
                record_event("gang.worker_restart", rank=r,
                             attempt=attempt + 1,
                             prev_exit=procs[r].poll(),
                             failed_rank=failed_rank,
                             failed_exit=failed_code)
            attempt += 1


def _main(argv: List[str]) -> int:
    """``python -m paddle_tpu.parallel.launch.gang -n N [--store DIR]
    [--max-restarts R] -- CMD ...`` — supervise CMD as an N-worker
    gang."""
    import argparse
    import tempfile

    ap = argparse.ArgumentParser(prog="paddle_tpu.parallel.launch.gang")
    ap.add_argument("-n", "--nprocs", type=int, required=True)
    ap.add_argument("--store", default=None)
    ap.add_argument("--max-restarts", type=int, default=3)
    ap.add_argument("cmd", nargs=argparse.REMAINDER,
                    help="worker command (prefix with --)")
    args = ap.parse_args(argv)
    cmd = args.cmd[1:] if args.cmd[:1] == ["--"] else args.cmd
    if not cmd:
        ap.error("worker command required after --")
    store = args.store or tempfile.mkdtemp(prefix="ptpu-gang-")
    res = GangSupervisor(cmd, args.nprocs, store_dir=store,
                         max_restarts=args.max_restarts).run()
    print(res.as_dict())
    return 0 if res.success else 1


if __name__ == "__main__":  # pragma: no cover
    sys.exit(_main(sys.argv[1:]))
