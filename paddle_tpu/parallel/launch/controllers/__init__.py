"""paddle.distributed.launch.controllers (reference:
distributed/launch/controllers/__init__.py) — the collective controller is
the supervisor loop in launch/main.py."""
from ..main import _Supervisor as CollectiveController  # noqa: F401
from ..main import launch as init  # noqa: F401

__all__ = ["CollectiveController", "init"]
