"""Decode-fleet subprocess worker entrypoint (ISSUE 17).

The cross-process twin of `serving.fleet.FleetWorker`: one engine per
PROCESS, talking to the fleet through a `resilience.store.FileStore`
mailbox instead of an in-memory one, so workers can live in separate
processes (and, with a shared filesystem, separate hosts). Launch one
directly::

    python -m paddle_tpu.parallel.launch.serve_worker \
        --store /tmp/fleet --job f1 --worker-id w0 --index 0

or a gang of them under the PR 12 supervisor (worker id / index
default from ``PADDLE_GANG_RANK``)::

    python -m paddle_tpu.parallel.launch.gang -n 2 -- \
        python -m paddle_tpu.parallel.launch.serve_worker --store ...

Store protocol (all keys under ``fleet/<job>/``; values are JSON):

- ``info/<wid>``     worker -> fleet: engine capacities, written once
  at startup (readiness marker);
- ``hb/<wid>``       TTL heartbeat lease, renewed every
  ``--heartbeat-s`` (death = expired lease);
- ``req/<wid>/<seq>`` fleet -> worker: one dispatch
  ``{rid, prompt, max_new, priority, deadline_s}`` (deleted on
  accept);
- ``prog/<wid>/<rid>`` worker -> fleet: delivered-token stream for
  in-flight recovery;
- ``done/<wid>/<rid>`` worker -> fleet: terminal result
  ``{tokens, failed, error}``;
- ``requeue/<wid>/<rid>`` worker -> fleet: unstarted requests handed
  back by a drain;
- ``ctl/<wid>``      fleet -> worker: ``stop`` | ``drain``.

Chaos: the loop runs the same ``fleet.worker`` seam as the in-process
worker; `ChaosKilled` is translated into a real ``SIGKILL`` (no
cleanup, no flush — the `preempt_host` semantics)."""
from __future__ import annotations

import argparse
import json
import os
import time


def _parse(argv=None):
    ap = argparse.ArgumentParser(
        prog="python -m paddle_tpu.parallel.launch.serve_worker")
    ap.add_argument("--store", required=True,
                    help="FileStore root shared with the fleet")
    ap.add_argument("--job", default="fleet")
    ap.add_argument("--worker-id", default=None)
    ap.add_argument("--index", type=int, default=None)
    ap.add_argument("--lease-epoch", type=int, default=0)
    ap.add_argument("--heartbeat-s", type=float, default=0.25)
    ap.add_argument("--poll-s", type=float, default=0.01)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--model", default="tiny",
                    help="LlamaConfig classmethod name (tiny, llama_1b)")
    ap.add_argument("--slots", type=int, default=2)
    ap.add_argument("--prompt-bucket", type=int, default=8)
    ap.add_argument("--max-prompt-len", type=int, default=32)
    ap.add_argument("--max-new", type=int, default=8)
    ap.add_argument("--block-size", type=int, default=8)
    ap.add_argument("--steps-per-sync", type=int, default=2)
    return ap.parse_args(argv)


def main(argv=None) -> int:
    args = _parse(argv)
    from ...resilience import chaos
    from ...resilience.store import FileStore

    rank = chaos.gang_rank()
    wid = args.worker_id or f"w{rank if rank is not None else 0}"
    index = args.index if args.index is not None \
        else (rank if rank is not None else 0)
    store = FileStore(args.store)
    pre = f"fleet/{args.job}"
    hb_key = f"{pre}/hb/{wid}"
    ttl = 4.0 * args.heartbeat_s

    def heartbeat(step):
        store.put(hb_key, json.dumps(
            {"t": time.time(), "epoch": args.lease_epoch,
             "step": step}), ttl=ttl)

    heartbeat(0)  # lease exists before the (slow) engine build

    import dataclasses

    import paddle_tpu as paddle
    from ...models import LlamaConfig, LlamaForCausalLM
    from ...serving.engine import ContinuousBatchingEngine

    cfg = getattr(LlamaConfig, args.model)()
    if args.model == "tiny":
        cfg = dataclasses.replace(cfg, num_key_value_heads=2)
    paddle.seed(args.seed)
    params = dict(LlamaForCausalLM(cfg).raw_state())
    eng = ContinuousBatchingEngine(
        cfg, params, slots=args.slots, prompt_bucket=args.prompt_bucket,
        max_prompt_len=args.max_prompt_len, max_new_tokens=args.max_new,
        block_size=args.block_size, steps_per_sync=args.steps_per_sync)
    heartbeat(0)
    store.put(f"{pre}/info/{wid}", json.dumps(
        {"slots": eng.slots, "max_prompt_len": eng.max_prompt_len,
         "max_new": eng.max_new, "pid": os.getpid()}))

    active = {}      # engine req_id -> rid
    last_len = {}    # rid -> tokens reported
    fin_seen = 0
    state = {"steps": 0}
    draining = False

    # renew the lease from a sidecar thread: a blocking engine.step()
    # (first-step compile takes seconds) must not expire it, but a
    # SIGKILLed process takes the thread with it and the lease lapses
    import threading

    hb_stop = threading.Event()

    def _hb_loop():
        while not hb_stop.is_set():
            heartbeat(state["steps"])
            hb_stop.wait(args.heartbeat_s)

    threading.Thread(target=_hb_loop, daemon=True).start()

    def accept():
        for key in sorted(store.prefix(f"{pre}/req/{wid}/")):
            raw = store.get(key)
            store.delete(key)
            if raw is None:
                continue
            d = json.loads(raw)
            if draining:
                store.put(f"{pre}/requeue/{wid}/{d['rid']}",
                          json.dumps({"rid": d["rid"]}))
                continue
            try:
                ereq = eng.add_request(
                    d["prompt"], d["max_new"],
                    priority=d.get("priority") or "normal",
                    deadline_s=d.get("deadline_s"))
            except Exception as e:
                store.put(f"{pre}/done/{wid}/{d['rid']}", json.dumps(
                    {"tokens": [], "failed": True, "error": str(e)}))
                continue
            active[ereq.req_id] = d["rid"]
            last_len[d["rid"]] = 0

    def report():
        nonlocal fin_seen
        while fin_seen < len(eng.finished):
            ereq = eng.finished[fin_seen]
            fin_seen += 1
            rid = active.pop(ereq.req_id, None)
            if rid is None:
                continue
            last_len.pop(rid, None)
            store.delete(f"{pre}/prog/{wid}/{rid}")
            store.put(f"{pre}/done/{wid}/{rid}", json.dumps(
                {"tokens": list(ereq.tokens), "failed": ereq.failed,
                 "error": ereq.error}))
        for ereq in (eng.export_progress() if active else ()):
            rid = active.get(ereq["req_id"])
            if rid is not None and \
                    len(ereq["tokens"]) > last_len.get(rid, 0):
                last_len[rid] = len(ereq["tokens"])
                store.put(f"{pre}/prog/{wid}/{rid}",
                          json.dumps({"tokens": ereq["tokens"]}))

    try:
        while True:
            chaos.maybe_kill_worker(index, state["steps"])
            ctl = store.get(f"{pre}/ctl/{wid}")
            if ctl == "stop":
                break
            if ctl == "drain":
                draining = True
                eng.pause_admission(True)
            accept()
            if eng.n_active > 0 or eng._prefilling is not None \
                    or eng._handoff or (eng.waiting and not draining):
                eng.step()
                report()
            elif draining:
                for ereq in eng.take_waiting():
                    rid = active.pop(ereq.req_id, None)
                    if rid is not None:
                        store.put(f"{pre}/requeue/{wid}/{rid}",
                                  json.dumps({"rid": rid}))
                break
            else:
                time.sleep(args.poll_s)
            state["steps"] += 1
    except chaos.ChaosKilled:
        # a hard worker death: no flush, no lease deregistration —
        # exactly what a preempted host looks like from the outside
        import signal

        os.kill(os.getpid(), signal.SIGKILL)
    hb_stop.set()
    store.delete(hb_key)
    return 0


if __name__ == "__main__":  # pragma: no cover - subprocess entrypoint
    raise SystemExit(main())
