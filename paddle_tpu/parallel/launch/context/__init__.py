"""paddle.distributed.launch.context (reference:
distributed/launch/context/__init__.py) — launch-time environment model."""
import os
import socket

__all__ = ["Context", "Node"]


class Node:
    """reference: launch/context/node.py."""

    def __init__(self):
        self.ip = self.get_host_ip()
        self.free_ports = []

    @staticmethod
    def get_host_ip():
        try:
            return socket.gethostbyname(socket.gethostname())
        except OSError:
            return "127.0.0.1"

    @staticmethod
    def get_free_port():
        with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as s:
            s.bind(("", 0))
            return s.getsockname()[1]


class Context:
    """reference: launch/context/__init__.py Context — parsed env + args."""

    def __init__(self, enable_plugin=True):
        self.node = Node()
        self.envs = dict(os.environ)

    def get_envs(self):
        return dict(self.envs)
