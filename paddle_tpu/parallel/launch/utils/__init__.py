"""paddle.distributed.launch.utils (reference: distributed/launch/utils/)."""
from ..context import Node

__all__ = ["process_group_info", "Node"]


def process_group_info():
    from ...env import get_rank, get_world_size

    return {"rank": get_rank(), "world_size": get_world_size()}
