"""Multi-host launcher: `python -m paddle_tpu.distributed.launch`.

Reference: python/paddle/distributed/launch — controllers spawn one worker
process per device, rendezvous through a Master (HTTP/etcd), watch children
and restart up to --max_restart (controllers/watcher.py).

TPU-native: JAX is single-controller per host — ONE worker per host drives
all local chips, so the launcher starts one training process per node (or N
local processes to emulate multi-host on CPU), exports the
`jax.distributed.initialize` env (coordinator address, process count/id),
then supervises: failure detection + restart with re-rendezvous is the
elastic path (manager.py ElasticManager analog).
"""
from .gang import GangResult, GangSupervisor  # noqa: F401
from .main import launch  # noqa: F401
