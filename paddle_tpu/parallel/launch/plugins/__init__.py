"""paddle.distributed.launch.plugins (reference:
distributed/launch/plugins/__init__.py) — pre-launch environment tweaks."""
__all__ = ["enabled_plugins"]


def _log_plugin(ctx):
    return ctx


enabled_plugins = [_log_plugin]
