"""paddle.distributed.launch.job (reference: distributed/launch/job/) —
pod/container model of a launched world."""
__all__ = ["Job", "Pod", "Container"]


class Container:
    """reference: launch/job/container.py — one worker process."""

    def __init__(self, entrypoint=None, rank=-1, env=None):
        self.entrypoint = entrypoint or []
        self.rank = rank
        self.env = dict(env or {})
        self.proc = None


class Pod:
    """reference: launch/job/pod.py — containers on one node."""

    def __init__(self):
        self.containers = []
        self.rank = 0

    def add_container(self, c):
        self.containers.append(c)


class Job:
    """reference: launch/job/job.py."""

    def __init__(self, jid="default", mode="collective", nnodes="1"):
        self.id = jid
        self.mode = mode
        self.nnodes = nnodes
