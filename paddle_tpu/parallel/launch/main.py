"""Launcher implementation.

Reference surface: python -m paddle.distributed.launch --nnodes --master
--devices --log_dir --max_restart script.py args...
(launch/main.py + controllers/collective.py + job/container.py log
redirection).
"""
from __future__ import annotations

import argparse
import os
import signal
import socket
import subprocess
import sys
import time
from typing import List, Optional


def _parse(argv):
    p = argparse.ArgumentParser(
        prog="paddle_tpu.distributed.launch",
        description="TPU-native distributed launcher")
    p.add_argument("--master", default=None,
                   help="coordinator host:port (default: first node, "
                        "port 8476)")
    p.add_argument("--nnodes", default="1",
                   help="number of nodes, or min:max for elastic")
    p.add_argument("--rank", type=int,
                   default=int(os.environ.get("PADDLE_NODE_RANK", 0)),
                   help="this node's rank")
    p.add_argument("--nproc_per_node", type=int, default=1,
                   help="worker processes on this node (1 per host on real "
                        "TPU; >1 emulates multi-host on CPU)")
    p.add_argument("--log_dir", default=None, help="per-rank log directory")
    p.add_argument("--max_restart", type=int, default=3,
                   help="restarts before giving up (elastic)")
    p.add_argument("--devices", default=None,
                   help="accepted for API parity (device visibility is the "
                        "TPU runtime's job)")
    p.add_argument("--elastic_store", default=None,
                   help="shared FileStore directory enabling elastic "
                        "membership (etcd stand-in; reference: "
                        "--elastic_server)")
    p.add_argument("--job_id", default="default",
                   help="elastic job id (membership namespace)")
    p.add_argument("--host_id", default=None,
                   help="this node's registration name (default: "
                        "node-{rank})")
    p.add_argument("script", help="training script")
    p.add_argument("script_args", nargs=argparse.REMAINDER)
    return p.parse_args(argv)


def _worker_env(base: dict, master: str, nproc: int, node_rank: int,
                local_rank: int, total: int) -> dict:
    env = dict(base)
    pid = node_rank * nproc + local_rank
    env.update({
        # jax.distributed.initialize reads these (TPU-native rendezvous)
        "JAX_COORDINATOR_ADDRESS": master,
        "JAX_NUM_PROCESSES": str(total),
        "JAX_PROCESS_ID": str(pid),
        # paddle-compat env (reference: PaddleCloudRoleMaker env discovery,
        # fleet/base/role_maker.py:542)
        "PADDLE_TRAINER_ID": str(pid),
        "PADDLE_TRAINERS_NUM": str(total),
        "PADDLE_MASTER": master,
        "PADDLE_LOCAL_RANK": str(local_rank),
    })
    if nproc > 1:  # multi-host emulation on one box: keep workers on CPU
        env.setdefault("JAX_PLATFORMS", "cpu")
    return env


class _Supervisor:
    """Watch children; on failure kill the peer group and restart the job
    up to max_restart times (reference: controllers/watcher.py +
    ElasticManager signal kill, fleet/elastic/manager.py:66-83)."""

    def __init__(self, cmd: List[str], envs: List[dict],
                 log_dir: Optional[str], max_restart: int,
                 elastic=None, rebuild_envs=None):
        self.cmd = cmd
        self.envs = envs
        self.log_dir = log_dir
        self.max_restart = max_restart
        self.procs: List[subprocess.Popen] = []
        # elastic: an ElasticManager watching membership; rebuild_envs maps
        # the new member list to fresh worker envs (re-ranked world)
        self.elastic = elastic
        self.rebuild_envs = rebuild_envs

    def _spawn(self):
        self.procs = []
        for i, env in enumerate(self.envs):
            stdout = stderr = None
            if self.log_dir:
                os.makedirs(self.log_dir, exist_ok=True)
                f = open(os.path.join(self.log_dir, f"workerlog.{i}"), "ab")
                stdout = stderr = f
            self.procs.append(subprocess.Popen(
                self.cmd, env=env, stdout=stdout, stderr=stderr))

    def _kill_all(self):
        for p in self.procs:
            if p.poll() is None:
                p.send_signal(signal.SIGTERM)
        deadline = time.time() + 5
        for p in self.procs:
            try:
                p.wait(timeout=max(0.1, deadline - time.time()))
            except subprocess.TimeoutExpired:
                p.kill()

    def run(self) -> int:
        restarts = 0
        while True:
            self._spawn()
            failed = None
            rescale = False
            while failed is None and not rescale:
                if self.elastic is not None and self.elastic.need_restart:
                    rescale = True
                    break
                alive = 0
                for p in self.procs:
                    rc = p.poll()
                    if rc is None:
                        alive += 1
                    elif rc != 0:
                        failed = rc
                        break
                if failed is None and alive == 0:
                    return 0  # clean exit everywhere
                time.sleep(0.2)
            self._kill_all()
            if rescale:
                # membership change: re-rank and respawn with the new
                # world (does not count against max_restart; reference:
                # manager.py watch -> signal kill -> launcher relaunch).
                # Wait for membership to SETTLE (unchanged for a window)
                # and for min_np quorum before respawning — this is
                # best-effort convergence, not consensus: nodes observing
                # different snapshots at the same instant is still
                # possible on a slow shared store (the reference's etcd
                # watch has the same property).
                self.elastic.need_restart = False
                members = self.elastic.members()
                while True:
                    time.sleep(1.0)
                    cur = self.elastic.members()
                    if cur == members and len(cur) >= self.elastic.min_np:
                        break
                    members = cur
                self.elastic.need_restart = False
                self.envs = self.rebuild_envs(members)
                print(f"[launch] elastic rescale -> members={members}",
                      file=sys.stderr)
                continue
            restarts += 1
            if restarts > self.max_restart:
                return failed
            print(f"[launch] worker failed (rc={failed}); restart "
                  f"{restarts}/{self.max_restart}", file=sys.stderr)


def launch(argv=None) -> int:
    args = _parse(argv if argv is not None else sys.argv[1:])
    nnodes = int(str(args.nnodes).split(":")[0])
    master = args.master or "127.0.0.1:8476"
    total = nnodes * args.nproc_per_node
    cmd = [sys.executable, args.script] + list(args.script_args)
    envs = [
        _worker_env(os.environ, master, args.nproc_per_node, args.rank,
                    lr, total)
        for lr in range(args.nproc_per_node)
    ]
    elastic = rebuild = None
    if args.elastic_store:
        from ..elastic import ElasticManager, FileStore

        parts = str(args.nnodes).split(":")
        np_range = (int(parts[0]), int(parts[-1]))
        host_id = args.host_id or f"node-{args.rank}"
        elastic = ElasticManager(
            FileStore(args.elastic_store), job_id=args.job_id,
            np_range=np_range, host=host_id).register().watch(
                poll_interval=0.5)
        # advertise a coordinator endpoint this node could serve, so a
        # rescale can re-derive the master when the original master node
        # is the one that left (the primary elastic failure mode —
        # reference: the fleet elastic relaunch path re-elects rank 0)
        addr_prefix = f"/paddle_tpu/elastic/{args.job_id}/addr/"
        port = master.rsplit(":", 1)[-1]
        my_addr = master
        if args.rank != 0:
            try:
                ip = socket.gethostbyname(socket.gethostname())
                if not ip.startswith("127."):
                    my_addr = f"{ip}:{port}"
                # loopback / unresolvable hostname: advertise the original
                # master rather than an address no peer can reach — the
                # failover then degrades to round-2 behavior, never worse
            except OSError:
                pass
        elastic.store.put(addr_prefix + host_id, my_addr)

        def rebuild(members):
            if host_id not in members:
                # our own heartbeat lapsed (stall / slow shared fs):
                # re-register instead of crashing — this node is healthy
                elastic.store.put(elastic._prefix + host_id, "alive",
                                  ttl=elastic.ttl)
                members = sorted(set(members) | {host_id})
            node_rank = members.index(host_id)
            new_total = len(members) * args.nproc_per_node
            # coordinator = the advertised address of the settled world's
            # first member — NOT the launch-time master, whose node may be
            # exactly the one that departed
            new_master = elastic.store.get(addr_prefix + members[0]) \
                or master
            return [
                _worker_env(os.environ, new_master, args.nproc_per_node,
                            node_rank, lr, new_total)
                for lr in range(args.nproc_per_node)
            ]

    try:
        return _Supervisor(cmd, envs, args.log_dir, args.max_restart,
                           elastic=elastic, rebuild_envs=rebuild).run()
    finally:
        if elastic is not None:
            if args.elastic_store:
                try:  # drop the advertised coordinator endpoint
                    elastic.store.delete(
                        f"/paddle_tpu/elastic/{args.job_id}/addr/"
                        + (args.host_id or f"node-{args.rank}"))
                except OSError:
                    pass
            elastic.exit()


if __name__ == "__main__":
    sys.exit(launch())
