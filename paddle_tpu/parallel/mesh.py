"""Device-mesh topology: the TPU-native HybridCommunicateGroup.

Reference: python/paddle/distributed/fleet/base/topology.py:68
(CommunicateTopology / HybridCommunicateGroup) builds a 5-D cartesian
process topology [data, pipe, sharding, sep, model] and one NCCL group per
axis. On TPU the entire topology is ONE `jax.sharding.Mesh` whose named axes
are the parallelism axes; XLA inserts the collectives (psum/all_gather/...)
over ICI when a computation is pjit'd/shard_map'd over the mesh. No
per-group communicator bootstrap (NCCL id exchange, TCPStore) is needed —
`jax.distributed.initialize` handles multi-host rendezvous.
"""
from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

# canonical axis order mirrors the reference's
# ["data", "pipe", "sharding", "sep", "model"] (topology.py:188)
HYBRID_AXES = ("dp", "pp", "sharding", "sep", "mp")
# the axes a data batch shards over (dp + the ZeRO axis); the single source
# for model activation specs and the Ulysses shard_map specs
BATCH_AXES = ("dp", "sharding")
# the tensor-parallel axis (model weights / kv heads)
MP_AXIS = "mp"
# the context-parallel axis (paged KV pools shard by PAGE along it;
# FLAGS_serving_cp — only the serving mesh uses it today)
CP_AXIS = "cp"


def divisible_prefix(mesh, dim: int, names) -> tuple:
    """Longest prefix of `names` (those present in `mesh`) whose PRODUCT
    divides `dim` — the one pruning rule behind activation sharding specs
    (partial sharding beats full replication on non-divisible dims) and the
    Ulysses shard_map in_specs, which must agree with them."""
    kept = []
    size = 1
    for n in names:
        if n not in mesh.axis_names:
            continue
        if dim % (size * int(mesh.shape[n])) == 0:
            kept.append(n)
            size *= int(mesh.shape[n])
        else:
            break
    return tuple(kept)

_global_mesh: Optional[Mesh] = None


def build_mesh(
    shape: Dict[str, int] | Sequence[int],
    axis_names: Optional[Sequence[str]] = None,
    devices: Optional[Sequence] = None,
) -> Mesh:
    """Create a Mesh from {axis: size}. Axes of size 1 are kept so sharding
    specs can always reference every hybrid axis."""
    if isinstance(shape, dict):
        axis_names = tuple(shape.keys())
        sizes = tuple(shape.values())
    else:
        sizes = tuple(shape)
        axis_names = tuple(axis_names or HYBRID_AXES[: len(sizes)])
    devices = list(devices if devices is not None else jax.devices())
    n = int(np.prod(sizes))
    if n != len(devices):
        raise ValueError(
            f"mesh shape {dict(zip(axis_names, sizes))} needs {n} devices, "
            f"got {len(devices)}")
    dev_array = np.asarray(devices).reshape(sizes)
    return Mesh(dev_array, axis_names)


def serving_mesh(mp: int, devices: Optional[Sequence] = None,
                 cp: int = 1) -> Optional[Mesh]:
    """Serving topology mesh over the first cp*mp local devices — 1-D
    `mp` (tensor parallel, FLAGS_serving_mp) when cp == 1, 2-D
    `cp x mp` (context x tensor parallel, FLAGS_serving_cp) otherwise.
    Kept separate from the global hybrid training mesh: the serving
    engine owns its own mesh so a co-resident trainer's dp/pp axes
    never leak into the paged programs' shard_map specs. Returns None
    at cp == mp == 1 (the single-chip path takes no mesh at all); the
    cp == 1 result is byte-identical to the pre-cp 1-D mesh."""
    mp, cp = int(mp), int(cp)
    if mp <= 1 and cp <= 1:
        return None
    if devices is None:
        devices = jax.devices()
    need = cp * mp
    if need > len(devices):
        raise ValueError(
            f"serving_cp={cp} x serving_mp={mp} needs {need} devices, "
            f"found {len(devices)}")
    if cp <= 1:
        return build_mesh({MP_AXIS: mp}, devices=list(devices)[:mp])
    # size-1 axes are kept (build_mesh contract), so a cp-only mesh
    # still names `mp` and every sharding spec can reference both axes
    return build_mesh({CP_AXIS: cp, MP_AXIS: mp},
                      devices=list(devices)[:need])


def set_global_mesh(mesh: Mesh) -> None:
    global _global_mesh
    _global_mesh = mesh


def get_global_mesh() -> Optional[Mesh]:
    return _global_mesh


def auto_mesh(**degrees: int) -> Mesh:
    """Build + install a hybrid mesh, inferring the dp degree from the device
    count (reference: HybridCommunicateGroup checks
    np.prod(dims) == world_size, topology.py:178)."""
    n = jax.device_count()
    known = int(np.prod([d for d in degrees.values()]))
    shape = dict(degrees)
    if n % known != 0:
        raise ValueError(f"{degrees} does not divide device count {n}")
    if "dp" not in shape:
        shape = {"dp": n // known, **shape}
    mesh = build_mesh(shape)
    set_global_mesh(mesh)
    return mesh


@dataclasses.dataclass
class HybridParallelInfo:
    """Per-axis degree/rank view (reference: HybridCommunicateGroup's
    get_*_parallel_world_size/rank accessors, topology.py:224-344)."""

    mesh: Mesh

    def degree(self, axis: str) -> int:
        return int(self.mesh.shape[axis]) if axis in self.mesh.axis_names else 1

    # paddle-named accessors
    def get_data_parallel_world_size(self):
        return self.degree("dp")

    def get_model_parallel_world_size(self):
        return self.degree("mp")

    def get_pipe_parallel_world_size(self):
        return self.degree("pp")

    def get_sharding_parallel_world_size(self):
        return self.degree("sharding")

    def get_sep_parallel_world_size(self):
        return self.degree("sep")


class HybridCommunicateGroup(HybridParallelInfo):
    """API-parity facade over the mesh (reference: topology.py:178)."""

    def __init__(self, mesh: Optional[Mesh] = None, **degrees: int):
        if mesh is None:
            mesh = get_global_mesh() or auto_mesh(**degrees)
        super().__init__(mesh)

    @property
    def nranks(self) -> int:
        return self.mesh.size

    def topology(self) -> List[int]:
        return [self.degree(a) for a in self.mesh.axis_names]
