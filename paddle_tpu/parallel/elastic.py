"""Elastic training manager.

Reference: python/paddle/distributed/fleet/elastic/manager.py —
ElasticManager(:124) registers nodes in etcd, watches membership (:247,308),
and on change kills trainers (signal :66-83) so the launcher relaunches with
re-ranked env; scaling policy from --nnodes=min:max and --elastic_level.

TPU-native: single-controller JAX re-initializes the whole distributed
runtime on topology change (re-`jax.distributed.initialize` + checkpoint
restore), so elastic = (membership watch) + (stop) + (relaunch with new
world size) + (resume from the latest distributed checkpoint, which
reshards on load — parallel/checkpoint.py). The store is pluggable: an
in-process dict store replaces etcd for tests, mirroring the reference's
mocked-etcd unit strategy (test_fleet_elastic_manager.py).
"""
from __future__ import annotations

import json
import os
import tempfile
import threading
import time
from typing import Callable, Dict, List, Optional
from urllib.parse import quote, unquote

__all__ = ["ElasticManager", "ElasticStatus", "DictStore", "FileStore"]


class ElasticStatus:
    COMPLETED = "completed"
    ERROR = "error"
    HOLD = "hold"
    RESTART = "restart"
    EXIT = "exit"


class DictStore:
    """In-process KV store with TTL semantics (etcd stand-in)."""

    def __init__(self):
        self._kv: Dict[str, tuple] = {}
        self._lock = threading.Lock()

    def put(self, key: str, value: str, ttl: Optional[float] = None):
        with self._lock:
            exp = time.time() + ttl if ttl else None
            self._kv[key] = (value, exp)

    def get(self, key: str):
        with self._lock:
            v = self._kv.get(key)
            if v is None:
                return None
            if v[1] is not None and v[1] < time.time():
                del self._kv[key]
                return None
            return v[0]

    def delete(self, key: str):
        with self._lock:
            self._kv.pop(key, None)

    def prefix(self, pre: str) -> Dict[str, str]:
        with self._lock:
            now = time.time()
            out = {}
            for k, (v, exp) in list(self._kv.items()):
                if exp is not None and exp < now:
                    del self._kv[k]
                elif k.startswith(pre):
                    out[k] = v
            return out


class FileStore:
    """File-backed KV store with TTL, shared ACROSS PROCESSES through a
    directory (the etcd stand-in the launcher's elastic path uses;
    reference: ElasticManager's etcd registry, manager.py:124). One file
    per key (name URL-quoted), values written atomically via
    tempfile+rename so concurrent readers never see partial writes."""

    def __init__(self, root: str):
        self.root = root
        os.makedirs(root, exist_ok=True)

    def _path(self, key: str) -> str:
        return os.path.join(self.root, quote(key, safe="") + ".json")

    def put(self, key: str, value: str, ttl: Optional[float] = None):
        payload = {"v": value, "exp": time.time() + ttl if ttl else None}
        fd, tmp = tempfile.mkstemp(dir=self.root, suffix=".tmp")
        with os.fdopen(fd, "w") as f:
            json.dump(payload, f)
        os.replace(tmp, self._path(key))

    def _read(self, path: str):
        try:
            with open(path) as f:
                payload = json.load(f)
        except (OSError, ValueError):
            return None
        if payload["exp"] is not None and payload["exp"] < time.time():
            # do NOT unlink: between our read and an unlink the owner may
            # have atomically renewed the file, and we would delete the
            # fresh heartbeat (spurious membership flap). Expired files
            # are simply skipped; the owner's delete() cleans up.
            return None
        return payload["v"]

    def get(self, key: str):
        return self._read(self._path(key))

    def delete(self, key: str):
        try:
            os.unlink(self._path(key))
        except OSError:
            pass

    def prefix(self, pre: str) -> Dict[str, str]:
        out = {}
        for fn in os.listdir(self.root):
            if not fn.endswith(".json"):
                continue
            key = unquote(fn[:-len(".json")])
            if not key.startswith(pre):
                continue
            v = self._read(os.path.join(self.root, fn))
            if v is not None:
                out[key] = v
        return out


class ElasticManager:
    """reference: ElasticManager(manager.py:124)."""

    def __init__(self, store=None, job_id: str = "default",
                 np_range=(1, 1), host: str = "127.0.0.1",
                 heartbeat_ttl: float = 10.0,
                 on_change: Optional[Callable[[List[str]], None]] = None):
        self.store = store if store is not None else DictStore()
        self.job_id = job_id
        self.min_np, self.max_np = np_range
        self.host = host
        self.ttl = heartbeat_ttl
        self.on_change = on_change
        self._prefix = f"/paddle_tpu/elastic/{job_id}/nodes/"
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []
        self._last_members: List[str] = []
        self.need_restart = False

    # ------------------------------------------------------------------
    def register(self):
        """Register this node + start heartbeat (reference: manager.py
        _heartbeat thread)."""
        self.store.put(self._prefix + self.host, "alive", ttl=self.ttl)
        t = threading.Thread(target=self._heartbeat, daemon=True)
        t.start()
        self._threads.append(t)
        return self

    def _heartbeat(self):
        while not self._stop.is_set():
            self.store.put(self._prefix + self.host, "alive", ttl=self.ttl)
            self._stop.wait(self.ttl / 3)

    def watch(self, poll_interval: float = 1.0):
        """Watch membership; trigger on_change / need_restart on deltas
        (reference: manager.py :247,308). The baseline membership is
        snapshotted BEFORE this returns, so any change after the call is
        guaranteed to be observed (no thread-startup race)."""
        self._last_members = self.members()
        t = threading.Thread(target=self._watch_loop,
                             args=(poll_interval,), daemon=True)
        t.start()
        self._threads.append(t)
        return self

    def _watch_loop(self, interval):
        while not self._stop.is_set():
            cur = self.members()
            if cur != self._last_members:
                self.need_restart = True
                if self.on_change is not None:
                    self.on_change(cur)
                self._last_members = cur
            self._stop.wait(interval)

    def members(self) -> List[str]:
        return sorted(k[len(self._prefix):]
                      for k in self.store.prefix(self._prefix))

    def status(self) -> str:
        n = len(self.members())
        if n < self.min_np:
            return ElasticStatus.HOLD       # wait for quorum
        if self.need_restart:
            return ElasticStatus.RESTART
        return ElasticStatus.COMPLETED

    def rank_of(self, host: Optional[str] = None) -> int:
        """Deterministic re-ranking after a membership change."""
        m = self.members()
        return m.index(host or self.host)

    def exit(self):
        self._stop.set()
        self.store.delete(self._prefix + self.host)
        for t in self._threads:
            t.join(timeout=1)
