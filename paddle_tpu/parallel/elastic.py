"""Elastic training manager.

Reference: python/paddle/distributed/fleet/elastic/manager.py —
ElasticManager(:124) registers nodes in etcd, watches membership (:247,308),
and on change kills trainers (signal :66-83) so the launcher relaunches with
re-ranked env; scaling policy from --nnodes=min:max and --elastic_level.

TPU-native: single-controller JAX re-initializes the whole distributed
runtime on topology change (re-`jax.distributed.initialize` + checkpoint
restore), so elastic = (membership watch) + (stop) + (relaunch with new
world size) + (resume from the latest distributed checkpoint, which
reshards on load — parallel/checkpoint.py). The store is pluggable: an
in-process dict store replaces etcd for tests, mirroring the reference's
mocked-etcd unit strategy (test_fleet_elastic_manager.py).
"""
from __future__ import annotations

import threading
from typing import Callable, List, Optional

# hoisted to resilience/store.py (ISSUE 12) — the gang coordination
# layer shares the exact same store implementations; re-exported here
# so existing `from paddle_tpu.parallel.elastic import DictStore`
# imports keep working
from ..resilience.store import DictStore, FileStore  # noqa: F401

__all__ = ["ElasticManager", "ElasticStatus", "DictStore", "FileStore"]


class ElasticStatus:
    COMPLETED = "completed"
    ERROR = "error"
    HOLD = "hold"
    RESTART = "restart"
    EXIT = "exit"


class ElasticManager:
    """reference: ElasticManager(manager.py:124)."""

    def __init__(self, store=None, job_id: str = "default",
                 np_range=(1, 1), host: str = "127.0.0.1",
                 heartbeat_ttl: float = 10.0,
                 on_change: Optional[Callable[[List[str]], None]] = None):
        self.store = store if store is not None else DictStore()
        self.job_id = job_id
        self.min_np, self.max_np = np_range
        self.host = host
        self.ttl = heartbeat_ttl
        self.on_change = on_change
        self._prefix = f"/paddle_tpu/elastic/{job_id}/nodes/"
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []
        self._last_members: List[str] = []
        self.need_restart = False

    # ------------------------------------------------------------------
    def register(self):
        """Register this node + start heartbeat (reference: manager.py
        _heartbeat thread)."""
        self.store.put(self._prefix + self.host, "alive", ttl=self.ttl)
        t = threading.Thread(target=self._heartbeat, daemon=True)
        t.start()
        self._threads.append(t)
        return self

    def _heartbeat(self):
        while not self._stop.is_set():
            self.store.put(self._prefix + self.host, "alive", ttl=self.ttl)
            self._stop.wait(self.ttl / 3)

    def watch(self, poll_interval: float = 1.0):
        """Watch membership; trigger on_change / need_restart on deltas
        (reference: manager.py :247,308). The baseline membership is
        snapshotted BEFORE this returns, so any change after the call is
        guaranteed to be observed (no thread-startup race)."""
        self._last_members = self.members()
        t = threading.Thread(target=self._watch_loop,
                             args=(poll_interval,), daemon=True)
        t.start()
        self._threads.append(t)
        return self

    def _watch_loop(self, interval):
        while not self._stop.is_set():
            cur = self.members()
            if cur != self._last_members:
                self.need_restart = True
                if self.on_change is not None:
                    self.on_change(cur)
                self._last_members = cur
            self._stop.wait(interval)

    def members(self) -> List[str]:
        return sorted(k[len(self._prefix):]
                      for k in self.store.prefix(self._prefix))

    def status(self) -> str:
        n = len(self.members())
        if n < self.min_np:
            return ElasticStatus.HOLD       # wait for quorum
        if self.need_restart:
            return ElasticStatus.RESTART
        return ElasticStatus.COMPLETED

    def rank_of(self, host: Optional[str] = None) -> int:
        """Deterministic re-ranking after a membership change."""
        m = self.members()
        return m.index(host or self.host)

    def exit(self):
        self._stop.set()
        self.store.delete(self._prefix + self.host)
        for t in self._threads:
            t.join(timeout=1)
