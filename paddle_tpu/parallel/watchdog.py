"""Collective hang / desync detection.

Reference: async CommTaskManager watchdog
(paddle/phi/core/distributed/comm_task_manager.h:37,55) — a thread tracks
every NCCL task with a timeout and dumps comm state on hang; store-based
barrier checks in phi/core/distributed/check/.

TPU-native: XLA collectives cannot be tracked per-op from Python, but step
hangs can — `watch()` wraps a step boundary with a heartbeat deadline; if
the step does not complete in time, the watchdog fires a diagnostic dump
(mesh, process info, stack traces of all threads) exactly like the
reference's CommTaskManager abort path. `barrier()` gives the store-based
liveness check across hosts.
"""
from __future__ import annotations

import contextlib
import faulthandler
import sys
import threading
import time
from typing import Callable, Optional

import jax

from . import mesh as mesh_mod

__all__ = ["StepWatchdog", "watch", "barrier"]


class StepWatchdog:
    """Deadline-based hang detector for train steps (reference:
    CommTaskManager + FLAGS_enable_async_trace)."""

    def __init__(self, timeout_s: float = 600.0,
                 on_timeout: Optional[Callable[[], None]] = None,
                 dump_stacks: bool = True, raise_on_timeout: bool = False):
        self.timeout_s = timeout_s
        self.on_timeout = on_timeout
        self.dump_stacks = dump_stacks
        self.raise_on_timeout = raise_on_timeout
        self._deadline = None
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._fired = False
        self._thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------------
    def start(self):
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=1)

    def _loop(self):
        while not self._stop.wait(min(1.0, self.timeout_s / 10)):
            with self._lock:
                deadline = self._deadline
            if deadline is not None and time.monotonic() > deadline \
                    and not self._fired:
                self._fired = True
                self._dump()
                if self.on_timeout is not None:
                    self.on_timeout()

    def _dump(self):
        mesh = mesh_mod.get_global_mesh()
        print(f"[watchdog] step exceeded {self.timeout_s}s — possible "
              f"collective hang. mesh="
              f"{dict(mesh.shape) if mesh else None} "
              f"process={getattr(jax, 'process_index', lambda: 0)()}",
              file=sys.stderr)
        if self.dump_stacks:
            faulthandler.dump_traceback(file=sys.stderr)

    # ------------------------------------------------------------------
    @contextlib.contextmanager
    def step(self):
        """Arm the deadline for one step; disarm on completion."""
        with self._lock:
            self._deadline = time.monotonic() + self.timeout_s
            self._fired = False
        try:
            yield
            if self._fired and self.raise_on_timeout:
                raise TimeoutError(
                    f"step exceeded watchdog timeout {self.timeout_s}s")
        finally:
            with self._lock:
                self._deadline = None


@contextlib.contextmanager
def watch(timeout_s: float = 600.0, **kw):
    """One-shot: `with watch(30): step(...)`."""
    wd = StepWatchdog(timeout_s, **kw).start()
    try:
        with wd.step():
            yield wd
    finally:
        wd.stop()


def barrier(timeout_s: float = 300.0):
    """Cross-host liveness barrier (reference: store barrier in
    phi/core/distributed/check/). Single-controller JAX: a tiny psum over
    all devices forces every host through the same program point."""
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = mesh_mod.get_global_mesh()
    with watch(timeout_s, raise_on_timeout=True):
        if mesh is None:
            jax.block_until_ready(jnp.zeros(()) + 1)
            return
        x = jax.device_put(
            jnp.ones((mesh.size,)),
            NamedSharding(mesh, P(mesh.axis_names)))
        total = jax.jit(lambda v: v.sum())(x)
        # device_get is the reliable cross-host sync point
        assert int(jax.device_get(total)) == mesh.size
