"""Functional hybrid-parallel train step.

Reference analog: the fleet dygraph train loop
(fleet/meta_parallel/pipeline_parallel.py train_batch + HybridParallelOptimizer
step) and the semi-auto static Engine (auto_parallel/static/engine.py). On
TPU both collapse into ONE jitted pure function over the mesh:

    (params, opt_state, batch) -> (loss, params', opt_state')

Params carry NamedShardings (TP over `mp`, ZeRO over `sharding`); the batch
is constrained over (dp, sharding); XLA SPMD emits all collectives
(grad psum ≙ EagerReducer allreduce; Shard(0) states ≙ sharding stage 1/2;
Shard params ≙ stage 3 gather/release with async prefetch). Buffer donation
makes the update in-place in HBM.

The optimizer update is a pure fused AdamW over the whole pytree — the role
of the reference's multi_tensor / fused adam kernels
(paddle/phi/kernels/fusion/gpu/fused_adam_kernel.cu).
"""
from __future__ import annotations

from typing import Any, Callable, Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..core.tensor import Tensor, unwrap
from ..core import tape as _tape
from ..nn.layer.layers import Layer
from . import mesh as mesh_mod


def batch_sharding(mesh: Mesh, shape, batch_spec=None) -> NamedSharding:
    """NamedSharding for a data batch: dim i takes batch_spec[i]'s axes,
    keeping only axis groups whose PRODUCT divides the dim size."""
    dims = batch_spec or (("dp", "sharding"), "sep")
    spec = []
    for i in range(len(shape)):
        d = dims[i] if i < len(dims) else None
        names = (d,) if isinstance(d, str) else (d or ())
        names = tuple(n for n in names if n in mesh.axis_names)
        # keep the longest prefix of the axis group whose PRODUCT divides
        # the dim (partial sharding beats full replication on uneven dims)
        kept = []
        size = 1
        for n in names:
            if shape[i] % (size * int(mesh.shape[n])) == 0:
                kept.append(n)
                size *= int(mesh.shape[n])
            else:
                break
        spec.append(tuple(kept) if kept else None)
    return NamedSharding(mesh, P(*spec))


class AdamWState(NamedTuple):
    m: Any
    v: Any
    step: Any


def init_adamw_state(params: Dict[str, jax.Array]) -> AdamWState:
    """Moments inherit each param's NamedSharding via zeros_like — this IS
    sharding stage 1/2 when params are FSDP-sharded (states follow params)."""
    zeros = jax.tree.map(jnp.zeros_like, params)
    return AdamWState(m=zeros, v=jax.tree.map(jnp.zeros_like, params),
                      step=jnp.zeros((), jnp.int32))


def adamw_update(params, grads, state: AdamWState, lr, *, beta1=0.9,
                 beta2=0.999, eps=1e-8, weight_decay=0.01,
                 grad_clip_norm: Optional[float] = 1.0):
    """Pure AdamW with global-norm clipping (ClipGradByGlobalNorm analog)."""
    step = state.step + 1
    if grad_clip_norm is not None:
        gnorm = jnp.sqrt(sum(
            jnp.sum(jnp.square(g.astype(jnp.float32)))
            for g in jax.tree.leaves(grads)))
        scale = jnp.minimum(1.0, grad_clip_norm / (gnorm + 1e-6))
        grads = jax.tree.map(lambda g: g * scale.astype(g.dtype), grads)
    c1 = 1.0 - beta1 ** step.astype(jnp.float32)
    c2 = 1.0 - beta2 ** step.astype(jnp.float32)

    def upd(p, g, m, v):
        g32 = g.astype(jnp.float32)
        m_ = beta1 * m + (1 - beta1) * g32
        v_ = beta2 * v + (1 - beta2) * jnp.square(g32)
        mhat = m_ / c1
        vhat = v_ / c2
        p32 = p.astype(jnp.float32)
        p_ = p32 - lr * (mhat / (jnp.sqrt(vhat) + eps) + weight_decay * p32)
        return p_.astype(p.dtype), m_.astype(m.dtype), v_.astype(v.dtype)

    out = jax.tree.map(upd, params, grads, state.m, state.v)
    new_p = jax.tree.map(lambda t: t[0], out,
                         is_leaf=lambda t: isinstance(t, tuple))
    new_m = jax.tree.map(lambda t: t[1], out,
                         is_leaf=lambda t: isinstance(t, tuple))
    new_v = jax.tree.map(lambda t: t[2], out,
                         is_leaf=lambda t: isinstance(t, tuple))
    return new_p, AdamWState(m=new_m, v=new_v, step=step)


def make_train_step(model: Layer, loss_fn: Callable, mesh: Optional[Mesh] = None,
                    lr: float = 1e-4, weight_decay: float = 0.01,
                    grad_clip_norm: Optional[float] = 1.0,
                    batch_spec: Optional[Tuple] = None,
                    donate: bool = True):
    """Build (step_fn, params, opt_state) for `model`.

    `loss_fn(logits_or_output, *batch_rest) -> scalar Tensor`; batch is
    (input, *rest). The returned step_fn is jitted with buffer donation;
    call it as `loss, params, opt_state = step_fn(params, opt_state, *batch)`.
    """
    mesh = mesh or mesh_mod.get_global_mesh()
    params = dict(model.raw_state())
    opt_state = init_adamw_state(params)

    def batch_constraint(x):
        if mesh is None:
            return x
        return jax.lax.with_sharding_constraint(
            x, batch_sharding(mesh, x.shape, batch_spec))

    def compute_loss(p, *batch):
        inputs = batch_constraint(batch[0])
        rest = [batch_constraint(b) for b in batch[1:]]
        with _tape.no_grad():
            out = model.func_call(p, Tensor(inputs))
            loss = loss_fn(out, *(Tensor(r) for r in rest))
        return unwrap(loss).astype(jnp.float32)

    def step(p, s, *batch):
        loss, grads = jax.value_and_grad(compute_loss)(p, *batch)
        new_p, new_s = adamw_update(
            p, grads, s, jnp.asarray(lr, jnp.float32),
            weight_decay=weight_decay, grad_clip_norm=grad_clip_norm)
        return loss, new_p, new_s

    jitted = jax.jit(step, donate_argnums=(0, 1) if donate else ())

    def step_fn(p, s, *batch):
        loss, new_p, new_s = jitted(p, s, *batch)
        # keep the Layer view fresh: donation invalidated the old arrays
        # (pointer swap only, no transfer)
        model.load_raw_state(new_p)
        return loss, new_p, new_s

    return step_fn, params, opt_state


def make_eval_step(model: Layer, mesh: Optional[Mesh] = None,
                   batch_spec: Optional[Tuple] = None):
    mesh = mesh or mesh_mod.get_global_mesh()

    def fwd(p, inputs):
        if mesh is not None:
            inputs = jax.lax.with_sharding_constraint(
                inputs, batch_sharding(mesh, inputs.shape, batch_spec))
        with _tape.no_grad():
            return unwrap(model.func_call(p, Tensor(inputs), training=False))

    return jax.jit(fwd)
