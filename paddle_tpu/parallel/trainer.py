"""Functional hybrid-parallel train step.

Reference analog: the fleet dygraph train loop
(fleet/meta_parallel/pipeline_parallel.py train_batch + HybridParallelOptimizer
step) and the semi-auto static Engine (auto_parallel/static/engine.py). On
TPU both collapse into ONE jitted pure function over the mesh:

    (params, opt_state, batch) -> (loss, params', opt_state')

Params carry NamedShardings (TP over `mp`, ZeRO over `sharding`); the batch
is constrained over (dp, sharding); XLA SPMD emits all collectives
(grad psum ≙ EagerReducer allreduce; Shard(0) states ≙ sharding stage 1/2;
Shard params ≙ stage 3 gather/release with async prefetch). Buffer donation
makes the update in-place in HBM.

The optimizer update is a pure fused AdamW over the whole pytree — the role
of the reference's multi_tensor / fused adam kernels
(paddle/phi/kernels/fusion/gpu/fused_adam_kernel.cu).
"""
from __future__ import annotations

from typing import Any, Callable, Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..core.tensor import Tensor, unwrap
from ..core import tape as _tape
from ..nn.layer.layers import Layer
from . import mesh as mesh_mod


def batch_sharding(mesh: Mesh, shape, batch_spec=None) -> NamedSharding:
    """NamedSharding for a data batch: dim i takes batch_spec[i]'s axes,
    keeping only axis groups whose PRODUCT divides the dim size."""
    dims = batch_spec or (("dp", "sharding"), "sep")
    spec = []
    for i in range(len(shape)):
        d = dims[i] if i < len(dims) else None
        names = (d,) if isinstance(d, str) else (d or ())
        kept = mesh_mod.divisible_prefix(mesh, shape[i], names)
        spec.append(kept if kept else None)
    return NamedSharding(mesh, P(*spec))


class AdamWState(NamedTuple):
    m: Any
    v: Any
    step: Any


def init_adamw_state(params: Dict[str, jax.Array]) -> AdamWState:
    """Moments inherit each param's NamedSharding via zeros_like — this IS
    sharding stage 1/2 when params are FSDP-sharded (states follow params)."""
    zeros = jax.tree.map(jnp.zeros_like, params)
    return AdamWState(m=zeros, v=jax.tree.map(jnp.zeros_like, params),
                      step=jnp.zeros((), jnp.int32))


def adamw_update(params, grads, state: AdamWState, lr, *, beta1=0.9,
                 beta2=0.999, eps=1e-8, weight_decay=0.01,
                 grad_clip_norm: Optional[float] = 1.0):
    """Pure AdamW with global-norm clipping (ClipGradByGlobalNorm analog).

    Weight decay applies to params with ndim > 1 only: 1-D leaves are norm
    scales / biases, which standard AdamW configs exclude (reference:
    apply_decay_param_fun in python/paddle/optimizer/adamw.py — pass a real
    AdamW(apply_decay_param_fun=...) through make_train_step(optimizer=)
    for name-based control). Decaying RMSNorm scales was the round-2
    default-path footgun; off by default now."""
    step = state.step + 1
    if grad_clip_norm is not None:
        gnorm = jnp.sqrt(sum(
            jnp.sum(jnp.square(g.astype(jnp.float32)))
            for g in jax.tree.leaves(grads)))
        scale = jnp.minimum(1.0, grad_clip_norm / (gnorm + 1e-6))
        grads = jax.tree.map(lambda g: g * scale.astype(g.dtype), grads)
    c1 = 1.0 - beta1 ** step.astype(jnp.float32)
    c2 = 1.0 - beta2 ** step.astype(jnp.float32)

    def upd(p, g, m, v):
        g32 = g.astype(jnp.float32)
        m_ = beta1 * m + (1 - beta1) * g32
        v_ = beta2 * v + (1 - beta2) * jnp.square(g32)
        mhat = m_ / c1
        vhat = v_ / c2
        p32 = p.astype(jnp.float32)
        wd = weight_decay if p.ndim > 1 else 0.0
        p_ = p32 - lr * (mhat / (jnp.sqrt(vhat) + eps) + wd * p32)
        return p_.astype(p.dtype), m_.astype(m.dtype), v_.astype(v.dtype)

    out = jax.tree.map(upd, params, grads, state.m, state.v)
    new_p = jax.tree.map(lambda t: t[0], out,
                         is_leaf=lambda t: isinstance(t, tuple))
    new_m = jax.tree.map(lambda t: t[1], out,
                         is_leaf=lambda t: isinstance(t, tuple))
    new_v = jax.tree.map(lambda t: t[2], out,
                         is_leaf=lambda t: isinstance(t, tuple))
    return new_p, AdamWState(m=new_m, v=new_v, step=step)


def _resolve_strategy(strategy) -> Dict[str, dict]:
    """Normalize a Strategy object / pass-produced config dict / None into
    plain section dicts (reference: auto_parallel/strategy.py sections)."""
    sections = ("amp", "recompute", "sharding", "gradient_merge", "pipeline")
    out = {s: {} for s in sections}
    if strategy is None:
        return out
    for s in sections:
        val = strategy.get(s) if isinstance(strategy, dict) \
            else getattr(strategy, s, None)
        if isinstance(val, dict):
            out[s] = dict(val)
    return out


_REMAT_POLICIES = {
    None: None,
    "full": None,
    "nothing_saveable": None,
    "save_attn": "dots_saveable",
    "dots_saveable": "dots_saveable",
    "dots_with_no_batch_dims_saveable": "dots_with_no_batch_dims_saveable",
}


def _remat_policy(name):
    mapped = _REMAT_POLICIES.get(name, name)
    if mapped is None:
        return None
    return getattr(jax.checkpoint_policies, mapped)


def _shard_dim0(arr, mesh, axis):
    """Extend `arr`'s sharding spec with Shard(0) over `axis` when dim 0 is
    free and divisible; otherwise return it unchanged. The single predicate
    behind both ZeRO stage-3 params and stage-1/2 accumulator layouts."""
    if getattr(arr, "ndim", 0) == 0:
        return arr
    spec = [None] * arr.ndim
    s = getattr(arr, "sharding", None)
    if isinstance(s, NamedSharding):
        got = list(s.spec) + [None] * (arr.ndim - len(s.spec))
        spec = got[:arr.ndim]
    n = int(mesh.shape[axis])
    if spec[0] is None and arr.shape[0] % n == 0 and arr.shape[0] >= n:
        spec[0] = axis
        return jax.device_put(arr, NamedSharding(mesh, P(*spec)))
    return arr


def _zero_stage3_params(params, mesh, axis):
    """ZeRO stage 3: Shard(0) every param whose dim 0 is divisible and not
    already claimed by another mesh axis (composes with TP layouts)."""
    return {k: _shard_dim0(v, mesh, axis) for k, v in params.items()}


def _zero_shard_states(opt_state, params, mesh, axis):
    """ZeRO stage 1/2: lay optimizer accumulators out Shard(0) over the
    sharding axis (on top of whatever spec they inherited from the param)."""

    def shard_one(name, st):
        p = params[name]

        def f(arr):
            if getattr(arr, "shape", None) != p.shape:
                return arr
            return _shard_dim0(arr, mesh, axis)

        return jax.tree.map(f, st)

    if isinstance(opt_state, AdamWState):
        return AdamWState(
            m={k: shard_one(k, v) for k, v in opt_state.m.items()},
            v={k: shard_one(k, v) for k, v in opt_state.v.items()},
            step=opt_state.step)
    acc = {k: shard_one(k, v) for k, v in opt_state["acc"].items()}
    return {"step": opt_state["step"], "acc": acc}


def make_train_step(model: Layer, loss_fn: Callable, mesh: Optional[Mesh] = None,
                    lr: float = 1e-4, weight_decay: float = 0.01,
                    grad_clip_norm: Optional[float] = 1.0,
                    batch_spec: Optional[Tuple] = None,
                    donate: bool = True, optimizer=None, strategy=None):
    """Build (step_fn, params, opt_state) for `model`.

    `loss_fn(logits_or_output, *batch_rest) -> scalar Tensor`; batch is
    (input, *rest). The returned step_fn is jitted with buffer donation;
    call it as `loss, params, opt_state = step_fn(params, opt_state, *batch)`.

    `optimizer`: any paddle_tpu Optimizer with a pure update rule — its
    update math, per-group weight decay, decay-exclusion fns, grad clip and
    LR schedule run inside the jitted step (reference: the static Engine
    building the optimizer into the program, auto_parallel/static/engine.py:69).
    Without it, a fused AdamW(lr, weight_decay) is used.

    `strategy`: Strategy / pass-produced config consumed at trace time
    (reference: distributed/passes/*):
      - amp.enable[, dtype]: cast fp32 params+inputs to bf16 for fwd/bwd,
        keep fp32 master params in the update (O2 semantics).
      - recompute.enable[, remat_policy]: jax.checkpoint over the loss.
      - gradient_merge.enable + k_steps[, avg]: lax.scan microbatch
        accumulation inside the step (passes/auto_parallel_gradient_merge.py).
      - sharding.enable + stage/axis: ZeRO 1/2 (states Shard(0)) or
        3 (+params Shard(0)) over the sharding mesh axis.
    """
    from .fused_optimizer import FusedOptimizer

    mesh = mesh or mesh_mod.get_global_mesh()
    strat = _resolve_strategy(strategy)
    params = dict(model.raw_state())

    shard_cfg = strat["sharding"]
    shard_axis = shard_cfg.get("axis", "sharding")
    sharding_on = bool(shard_cfg.get("enable")) and mesh is not None \
        and shard_axis in getattr(mesh, "axis_names", ())
    if sharding_on and int(shard_cfg.get("stage", 2)) >= 3:
        params = _zero_stage3_params(params, mesh, shard_axis)

    fused = FusedOptimizer(optimizer, model) if optimizer is not None else None
    opt_state = fused.init_state(params) if fused is not None \
        else init_adamw_state(params)
    if sharding_on:
        opt_state = _zero_shard_states(opt_state, params, mesh, shard_axis)

    amp_cfg = strat["amp"]
    # bf16 is the TPU-native half type; a float16 request (fp16 pass) maps
    # onto it (same contract as FP16Pass defaulting to bfloat16)
    amp_dtype = jnp.bfloat16 if amp_cfg.get("enable") else None

    def batch_constraint(x):
        if mesh is None:
            return x
        return jax.lax.with_sharding_constraint(
            x, batch_sharding(mesh, x.shape, batch_spec))

    def compute_loss(p, *batch):
        if fused is not None:
            # frozen params / buffers contribute no cotangents
            p = {k: (v if k in fused.trainable else jax.lax.stop_gradient(v))
                 for k, v in p.items()}
        if amp_dtype is not None:
            p = {k: (v.astype(amp_dtype) if v.dtype == jnp.float32 else v)
                 for k, v in p.items()}
            batch = tuple(
                b.astype(amp_dtype) if b.dtype == jnp.float32 else b
                for b in batch)
        inputs = batch_constraint(batch[0])
        rest = [batch_constraint(b) for b in batch[1:]]
        with _tape.no_grad():
            out = model.func_call(p, Tensor(inputs))
            loss = loss_fn(out, *(Tensor(r) for r in rest))
        return unwrap(loss).astype(jnp.float32)

    if strat["recompute"].get("enable"):
        model_cfg = getattr(model, "config", None)
        if model_cfg is not None and hasattr(model_cfg, "recompute"):
            # per-layer remat via the model's own segmentation — the real
            # peak-memory reducer (reference: passes/auto_parallel_recompute
            # checkpointing segments, fleet/recompute/recompute.py:109).
            # The flip is scoped to this step's trace so the shared model
            # object keeps its own config everywhere else.
            knobs = {"recompute": True}
            for knob in ("recompute_skip", "remat_policy"):
                if strat["recompute"].get(knob) is not None:
                    knobs[knob] = strat["recompute"][knob]
            inner_loss = compute_loss

            def compute_loss(p, *batch, _inner=inner_loss, _knobs=knobs):
                saved = {k: getattr(model_cfg, k) for k in _knobs}
                try:
                    for k, v in _knobs.items():
                        setattr(model_cfg, k, v)
                    return _inner(p, *batch)
                finally:
                    for k, v in saved.items():
                        setattr(model_cfg, k, v)
        else:
            # generic fallback: whole-fn checkpoint (saves only the policy's
            # residuals between fwd and bwd; no per-segment peak reduction)
            compute_loss = jax.checkpoint(
                compute_loss,
                policy=_remat_policy(strat["recompute"].get("remat_policy")))

    gm_cfg = strat["gradient_merge"]
    k_steps = int(gm_cfg.get("k_steps", 1)) if gm_cfg.get("enable") else 1
    gm_avg = bool(gm_cfg.get("avg", True))

    def loss_and_grads(p, *batch):
        if k_steps <= 1:
            return jax.value_and_grad(compute_loss)(p, *batch)
        micro = tuple(
            b.reshape((k_steps, b.shape[0] // k_steps) + b.shape[1:])
            for b in batch)

        def acc_add(a, g):
            # integer params get float0 cotangents; nothing to accumulate
            if g.dtype == jax.dtypes.float0:
                return a
            return a + g.astype(jnp.float32)

        def body(carry, mb):
            acc_loss, acc_g = carry
            loss, grads = jax.value_and_grad(compute_loss)(p, *mb)
            return (acc_loss + loss, jax.tree.map(acc_add, acc_g, grads)), None

        zeros = jax.tree.map(
            lambda x: jnp.zeros(x.shape, jnp.float32), p)
        (loss_sum, g_sum), _ = jax.lax.scan(
            body, (jnp.zeros((), jnp.float32), zeros), micro)
        scale = 1.0 / k_steps if gm_avg else 1.0
        grads = jax.tree.map(
            lambda g, x: (g * scale).astype(
                x.dtype if jnp.issubdtype(x.dtype, jnp.floating)
                else jnp.float32), g_sum, p)
        return loss_sum / k_steps, grads

    def step(p, s, lr_, *batch):
        loss, grads = loss_and_grads(p, *batch)
        if fused is not None:
            new_p, new_s = fused.update(p, grads, s, lr_)
        else:
            new_p, new_s = adamw_update(
                p, grads, s, lr_, weight_decay=weight_decay,
                grad_clip_norm=grad_clip_norm)
        return loss, new_p, new_s

    jitted = jax.jit(step, donate_argnums=(0, 1) if donate else ())

    def step_fn(p, s, *batch):
        cur_lr = fused.host_lr() if fused is not None else lr
        loss, new_p, new_s = jitted(
            p, s, jnp.asarray(cur_lr, jnp.float32), *batch)
        # keep the Layer view fresh: donation invalidated the old arrays
        # (pointer swap only, no transfer)
        model.load_raw_state(new_p)
        if fused is not None:
            fused.latest_state = new_s  # lazily exported by state_dict()
            fused.host_tick()
        return loss, new_p, new_s

    step_fn.jitted = jitted  # for lowering/compile introspection
    if fused is not None:
        step_fn.fused_optimizer = fused
    return step_fn, params, opt_state


def make_eval_step(model: Layer, mesh: Optional[Mesh] = None,
                   batch_spec: Optional[Tuple] = None):
    mesh = mesh or mesh_mod.get_global_mesh()

    def fwd(p, inputs):
        if mesh is not None:
            inputs = jax.lax.with_sharding_constraint(
                inputs, batch_sharding(mesh, inputs.shape, batch_spec))
        with _tape.no_grad():
            return unwrap(model.func_call(p, Tensor(inputs), training=False))

    return jax.jit(fwd)
