"""paddle.distributed.fleet.utils equivalent (reference:
fleet/utils/__init__.py — the public `recompute` activation-checkpointing
entry (fleet/recompute/recompute.py:429), recompute_sequential, LocalFS,
and the HDFS client).

TPU-native form: recompute wraps the callable in `jax.checkpoint` over the
raw arrays (the reference's RecomputeFunction PyLayer re-runs forward under
saved RNG state; jax.checkpoint does the same via functional key threading),
composing with the eager tape through dispatch. HDFS is out of scope —
LocalFS covers the FS interface on one host.
"""
from __future__ import annotations

import os
import shutil

import jax

from ..core.tensor import Tensor, dispatch, unwrap

__all__ = ["recompute", "recompute_sequential", "LocalFS", "HDFSClient"]


_TENSOR_SLOT = object()


def recompute(function, *args, **kwargs):
    """reference: fleet/recompute/recompute.py:429 — run `function` without
    storing intermediates; recompute them in backward. Tensor positional
    AND keyword arguments are threaded through the checkpoint (so their
    gradients flow); plain-python arguments pass through untouched."""
    kwargs.pop("use_reentrant", True)  # parity knob
    kwargs.pop("preserve_rng_state", True)
    tensor_args = [a for a in args if isinstance(a, Tensor)]
    const_args = [_TENSOR_SLOT if isinstance(a, Tensor) else a
                  for a in args]
    kw_tensor_keys = sorted(k for k, v in kwargs.items()
                            if isinstance(v, Tensor))
    tensor_args += [kwargs[k] for k in kw_tensor_keys]
    const_kwargs = {k: v for k, v in kwargs.items()
                    if not isinstance(v, Tensor)}

    def impl(*arrs):
        def run(*xs):
            it = iter(xs)
            call = [Tensor(next(it)) if c is _TENSOR_SLOT else c
                    for c in const_args]
            kw = dict(const_kwargs)
            for k in kw_tensor_keys:
                kw[k] = Tensor(next(it))
            out = function(*call, **kw)
            return unwrap(out)

        return jax.checkpoint(run)(*arrs)

    return dispatch("fleet_recompute", impl, tuple(tensor_args))


def recompute_sequential(ctx, functions, *args, **kwargs):
    """reference: fleet/recompute/recompute.py:593 — checkpoint a
    Sequential in `segments` chunks. The first chunk receives *args; the
    rest chain on the previous chunk's (single) output."""
    segments = (ctx or {}).get("segments", 1)
    fns = list(functions)
    per = max(1, len(fns) // max(segments, 1))

    def seg_runner(chunk):
        def run(*xs):
            h = xs[0] if len(xs) == 1 else xs
            for f in chunk:
                h = f(*h) if isinstance(h, tuple) else f(h)
            return h
        return run

    out = args
    for s in range(0, len(fns), per):
        chunk = fns[s:s + per]
        out = recompute(seg_runner(chunk),
                        *(out if isinstance(out, tuple) else (out,)),
                        **kwargs)
    return out


class LocalFS:
    """reference: fleet/utils/fs.py LocalFS."""

    def ls_dir(self, fs_path):
        dirs, files = [], []
        for name in sorted(os.listdir(fs_path)):
            (dirs if os.path.isdir(os.path.join(fs_path, name))
             else files).append(name)
        return dirs, files

    def is_dir(self, fs_path):
        return os.path.isdir(fs_path)

    def is_file(self, fs_path):
        return os.path.isfile(fs_path)

    def is_exist(self, fs_path):
        return os.path.exists(fs_path)

    def mkdirs(self, fs_path):
        os.makedirs(fs_path, exist_ok=True)

    def delete(self, fs_path):
        if os.path.isdir(fs_path):
            shutil.rmtree(fs_path)
        elif os.path.exists(fs_path):
            os.remove(fs_path)

    def rename(self, src, dst):
        os.rename(src, dst)

    def mv(self, src, dst, overwrite=False, test_exists=True):
        if overwrite and os.path.exists(dst):
            self.delete(dst)
        os.rename(src, dst)

    def upload(self, local_path, fs_path, multi_processes=1, overwrite=False):
        shutil.copy(local_path, fs_path)

    def download(self, fs_path, local_path, multi_processes=1,
                 overwrite=False):
        shutil.copy(fs_path, local_path)

    def touch(self, fs_path, exist_ok=True):
        if os.path.exists(fs_path) and not exist_ok:
            raise FileExistsError(fs_path)
        open(fs_path, "a").close()

    def cat(self, fs_path):
        with open(fs_path) as f:
            return f.read()

    def list_dirs(self, fs_path):
        return self.ls_dir(fs_path)[0]


class HDFSClient:
    """reference: fleet/utils/fs.py HDFSClient — cluster FS is out of
    scope on single-controller TPU deployments (checkpoints ride GCS /
    local disks via parallel.checkpoint)."""

    def __init__(self, hadoop_home=None, configs=None, *a, **k):
        raise NotImplementedError(
            "HDFS is not available in the TPU build; use LocalFS or the "
            "sharded checkpoint API (paddle_tpu.distributed.save_state_dict)")
