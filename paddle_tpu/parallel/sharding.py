"""ZeRO-style parameter/gradient/optimizer-state sharding (stages 1-3).

Reference:
- stage 1: fleet/meta_optimizers/dygraph_optimizer/dygraph_sharding_optimizer.py:44
  (optimizer states partitioned across the sharding group; grads allreduced;
  updated params broadcast)
- stage 2: fleet/meta_parallel/sharding/group_sharded_stage2.py:46 (+ grad
  slicing with reduce-scatter semantics)
- stage 3: group_sharded_stage3.py:85 (parameter slicing, gather-on-forward /
  release-after, prefetch)
- user API: python/paddle/distributed/sharding/group_sharded.py
  group_sharded_parallel(model, optimizer, level="os"|"os_g"|"p_g_os")

TPU-native: all three stages are SHARDING SPECS over the `sharding` mesh
axis, enforced by NamedSharding on the persistent buffers:
- stage 1 ("os"):   optimizer states Shard(0); params+grads replicated.
- stage 2 ("os_g"): + gradients reduce-scattered (XLA does this when the
  param update consumes Shard(0) grads).
- stage 3 ("p_g_os"): + params Shard(0); XLA all-gathers weights just
  before use (its scheduler overlaps the gather with compute = stage-3
  prefetch) and frees the gathered copy after (= release-after-use).
No broadcast step is needed: an update of a Shard(0) param IS visible to
every future all-gather."""
from __future__ import annotations

from typing import Optional

import jax
from jax.sharding import NamedSharding, PartitionSpec

from ..core.tensor import Parameter, Tensor
from ..nn.layer.layers import Layer
from . import mesh as mesh_mod

__all__ = ["group_sharded_parallel", "save_group_sharded_model",
           "shard_params_stage3", "shard_accumulators"]


def _axis_sharding(mesh, axis: str, tensor_ndim: int, shard_dim0: bool):
    spec = [None] * tensor_ndim
    if shard_dim0 and tensor_ndim > 0:
        spec[0] = axis
    return NamedSharding(mesh, PartitionSpec(*spec))


def _shardable(arr, mesh, axis) -> bool:
    return (arr.ndim > 0 and arr.shape[0] % int(mesh.shape[axis]) == 0
            and arr.shape[0] >= int(mesh.shape[axis]))


def shard_params_stage3(model: Layer, mesh=None, axis: str = "sharding"):
    """Lay every parameter out Shard(0) over the sharding axis (stage-3 /
    FSDP semantics)."""
    mesh = mesh or mesh_mod.get_global_mesh()
    if mesh is None or axis not in mesh.axis_names:
        return model
    for p in model.parameters():
        if _shardable(p._array, mesh, axis):
            p._array = jax.device_put(
                p._array, _axis_sharding(mesh, axis, p.ndim, True))
    return model


def shard_accumulators(optimizer, mesh=None, axis: str = "sharding"):
    """Stage-1: partition optimizer moments over the sharding axis."""
    mesh = mesh or mesh_mod.get_global_mesh()
    if mesh is None or axis not in mesh.axis_names:
        return optimizer
    orig_create = optimizer._create_accumulators

    def create(p):
        state = orig_create(p)
        for k, arr in list(state.items()):
            if hasattr(arr, "ndim") and _shardable(arr, mesh, axis):
                state[k] = jax.device_put(
                    arr, _axis_sharding(mesh, axis, arr.ndim, True))
        return state

    optimizer._create_accumulators = create
    return optimizer


def group_sharded_parallel(model: Layer, optimizer, level: str = "p_g_os",
                           scaler=None, group=None, offload=False,
                           sync_buffers=False, buffer_max_size=2 ** 23,
                           segment_size=2 ** 20, sync_comm=False,
                           dp_group=None, exclude_layer=None):
    """reference: python/paddle/distributed/sharding/group_sharded.py
    group_sharded_parallel(model, optimizer, level) with
    level in {"os", "os_g", "p_g_os"}."""
    if level not in ("os", "os_g", "p_g_os"):
        raise ValueError(f"level must be os|os_g|p_g_os, got {level}")
    optimizer = shard_accumulators(optimizer)
    if level == "p_g_os":
        model = shard_params_stage3(model)
    # "os_g" grad reduce-scatter falls out of XLA partitioning the backward
    # against Shard(0) accumulators; nothing extra to install eagerly.
    if scaler is not None:
        return model, optimizer, scaler
    return model, optimizer


def save_group_sharded_model(model, output, optimizer=None):
    """reference: group_sharded.py save_group_sharded_model — gathers shards
    then saves. Our state_dict already returns global arrays (single
    controller), so this is a plain save."""
    from ..framework.io import save

    save(model.state_dict(), output + ".pdparams" if not str(output).endswith(
        ".pdparams") else output)
    if optimizer is not None:
        save(optimizer.state_dict(), str(output) + ".pdopt")
