"""paddle_tpu.parallel: the distributed stack (reference:
python/paddle/distributed). Aliased as `paddle_tpu.distributed`.

Layer map (SURVEY.md §2.3/§2.4 -> TPU):
- topology/HCG            -> mesh.py (one jax Mesh, axes dp/pp/sharding/sep/mp)
- communication/*         -> collective.py (XLA collectives facade)
- auto_parallel semi-auto -> api.py + placement.py (shard_tensor/reshard)
- fleet.layers.mpu        -> mpu.py (TP layers)
- meta_parallel sharding  -> sharding.py (ZeRO 1/2/3 as sharding specs)
- pipeline_parallel       -> pipeline.py (shard_map+ppermute scan)
- sequence_parallel/sep   -> sequence_parallel.py (SP utils + Ulysses)
- moe                     -> moe.py
- fleet facade            -> fleet.py
- env/launch              -> env.py
"""
from .env import ParallelEnv, get_rank, get_world_size  # noqa: F401
from .mesh import (  # noqa: F401
    HYBRID_AXES, HybridCommunicateGroup, auto_mesh, build_mesh,
    get_global_mesh, set_global_mesh,
)
from .placement import (  # noqa: F401
    Partial, Placement, ProcessMesh, Replicate, Shard,
)
from .api import (  # noqa: F401
    dtensor_from_fn, get_placements, reshard, shard_constraint, shard_layer,
    shard_optimizer, shard_tensor, unshard_dtensor,
)
from .spmd_rules import (  # noqa: F401
    get_spmd_rule, register_spmd_rule, shard_parameters,
    with_spmd_constraint,
)
from .collective import (  # noqa: F401
    Group, ReduceOp, all_gather, all_gather_object, all_reduce, all_to_all,
    all_to_all_single, barrier, broadcast, gather, get_group, irecv, isend,
    new_group, recv, reduce, reduce_scatter, scatter, send, stream,
)
from .data_parallel import DataParallel, scale_batch  # noqa: F401
from .sharding import (  # noqa: F401
    group_sharded_parallel, save_group_sharded_model, shard_accumulators,
    shard_params_stage3,
)
from .pipeline import (  # noqa: F401
    LayerDesc, PipelineLayer, PipelineParallel, SharedLayerDesc,
    pipeline_apply,
)
from .sequence_parallel import (  # noqa: F401
    AllGatherOp, GatherOp, ReduceScatterOp, ScatterOp, SegmentParallel,
    gather_seq, mark_as_sequence_parallel_parameter, sep_attention_context,
    split_seq, ulysses_alltoall,
)
from .moe import GShardGate, MoELayer, NaiveGate, SwitchGate, moe_dispatch  # noqa: F401
from .fleet import DistributedStrategy  # noqa: F401
from . import fleet  # noqa: F401  (module; its own `fleet` instance plus
#                      init/distributed_model are module-level, matching the
#                      reference where paddle.distributed.fleet is a module)
from . import auto_tuner  # noqa: F401
from . import checkpoint  # noqa: F401
from . import cost_model  # noqa: F401
from . import elastic  # noqa: F401
from . import pipeline_spmd  # noqa: F401
from .pipeline_spmd import pipeline_forward, stack_stage_params  # noqa: F401
from . import pipeline_viz  # noqa: F401
from .pipeline_viz import (  # noqa: F401
    pipeline_timeline, render_timeline, save_chrome_trace, timeline_stats,
)
from . import ring_attention as ring_attention_mod  # noqa: F401
from .ring_attention import ring_attention  # noqa: F401
from . import watchdog  # noqa: F401
from .watchdog import StepWatchdog, barrier  # noqa: F401
from .elastic import ElasticManager  # noqa: F401
from .checkpoint import load_state_dict, save_state_dict  # noqa: F401
from .trainer import (  # noqa: F401
    AdamWState, adamw_update, init_adamw_state, make_eval_step,
    make_train_step,
)
from . import mpu  # noqa: F401
from . import collective as communication  # noqa: F401
from . import collectives  # noqa: F401
from .collectives import (  # noqa: F401
    quantized_all_gather, quantized_psum, quantized_psum_tree,
    quantized_reduce_scatter, resolve_quantized_collectives,
)


def init_parallel_env():
    """reference: python/paddle/distributed/parallel.py:957 — NCCL/TCPStore
    bootstrap. Single-controller JAX needs no per-rank rendezvous on one
    host; multi-host uses jax.distributed.initialize (env.init_distributed)."""
    from .env import init_distributed

    init_distributed()
    return ParallelEnv()


def spawn(func, args=(), nprocs=-1, **kwargs):
    """reference: python/paddle/distributed/spawn.py. Single-controller JAX
    owns all local devices in one process — run inline (nprocs>1 has no
    per-process meaning here)."""
    if nprocs not in (-1, 1):
        import warnings

        warnings.warn(
            f"paddle_tpu.distributed.spawn: nprocs={nprocs} ignored — "
            "single-controller JAX drives all devices from one process; "
            "running func inline once.")
    func(*args)
from . import compat  # noqa: E402,F401
from .compat import (  # noqa: E402,F401
    CountFilterEntry, DistAttr, DistModel, InMemoryDataset, ParallelMode,
    ProbabilityEntry, QueueDataset, ReduceType, ShardingStage1,
    ShardingStage2, ShardingStage3, ShowClickEntry, Strategy, alltoall,
    alltoall_single, broadcast_object_list, destroy_process_group,
    get_backend, gloo_barrier, gloo_init_parallel_env, gloo_release,
    is_available, is_initialized, scatter_object_list, shard_dataloader,
    shard_scaler, split, to_static, wait)
from . import launch  # noqa: E402,F401
from . import checkpoint as io  # noqa: E402,F401
from . import passes  # noqa: E402,F401
