"""paddle_tpu.parallel: the distributed stack (reference:
python/paddle/distributed). Aliased as `paddle_tpu.distributed`."""
from .env import ParallelEnv, get_rank, get_world_size  # noqa: F401
