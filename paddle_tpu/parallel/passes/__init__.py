"""paddle.distributed.passes equivalent (reference:
python/paddle/distributed/passes/pass_base.py — PassBase/PassManager/
new_pass/register_pass, plus the auto-parallel pass zoo: amp, recompute,
sharding, gradient-merge, fuse-allreduce, pipeline schedulers).

TPU-native form: the reference's passes rewrite static Programs op by op;
here XLA owns program rewriting, so a pass is a declarative transformation
over the training-step CONFIGURATION (the `Strategy`-shaped dict that
make_train_step / DistModel consume): applying `auto_parallel_recompute`
flips the remat knobs, `auto_parallel_sharding` picks the ZeRO stage and
mesh axis, pipeline scheduler passes select the microbatch schedule for
parallel.pipeline_spmd. The pass *protocol* (registration, check/apply,
manager ordering, context bookkeeping) mirrors the reference so pass
lists written against paddle port over.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Type

__all__ = ["PassBase", "PassContext", "PassManager", "new_pass",
           "register_pass"]

_PASS_REGISTRY: Dict[str, Type["PassBase"]] = {}


def register_pass(name: str):
    """reference: pass_base.py register_pass — class decorator."""
    def deco(cls):
        cls.name = name
        _PASS_REGISTRY[name] = cls
        return cls

    return deco


def new_pass(name: str, pass_attrs: Optional[dict] = None) -> "PassBase":
    """reference: pass_base.py new_pass."""
    if name not in _PASS_REGISTRY:
        raise ValueError(
            f"no pass named {name!r}; registered: "
            f"{sorted(_PASS_REGISTRY)}")
    p = _PASS_REGISTRY[name]()
    for k, v in (pass_attrs or {}).items():
        p.set_attr(k, v)
    return p


class PassContext:
    """reference: pass_base.py PassContext — records applied passes and
    cross-pass attributes."""

    def __init__(self):
        self.passes: List[PassBase] = []
        self.attrs: Dict[str, object] = {}

    def set_attr(self, k, v):
        self.attrs[k] = v

    def get_attr(self, k, default=None):
        return self.attrs.get(k, default)


class PassBase:
    """reference: pass_base.py PassBase — check/apply protocol. `apply`
    receives the strategy-config dict (the TPU analog of main_program)
    and mutates it."""

    name = "base"

    def __init__(self):
        self._attrs: Dict[str, object] = {}

    def set_attr(self, k, v):
        self._attrs[k] = v
        return self

    def get_attr(self, k, default=None):
        return self._attrs.get(k, default)

    def _check_self(self) -> bool:
        return True

    def _check_conflict(self, other: "PassBase") -> bool:
        return True

    def apply(self, main_programs, startup_programs=None, context=None):
        if not self._check_self():
            raise ValueError(f"pass {self.name} attrs invalid: "
                             f"{self._attrs}")
        ctx = context or PassContext()
        for p in ctx.passes:
            if p is self:
                continue  # re-applying the same manager/context is fine
            # both directions, like the reference: either side may declare
            # the conflict
            if not self._check_conflict(p) or not p._check_conflict(self):
                raise ValueError(
                    f"pass {self.name} conflicts with {p.name}")
        configs = main_programs if isinstance(main_programs, list) \
            else [main_programs]
        for cfg in configs:
            self._apply_single(cfg, ctx)
        if self not in ctx.passes:
            ctx.passes.append(self)
        return ctx

    def _apply_single(self, config, context):
        raise NotImplementedError


class PassManager:
    """reference: pass_base.py PassManager — ordered application with a
    shared context."""

    def __init__(self, passes: List[PassBase]):
        self._passes = list(passes)
        self._context = PassContext()

    def apply(self, main_programs, startup_programs=None):
        for p in self._passes:
            self._context = p.apply(main_programs, startup_programs,
                                    self._context)
        return self._context

    @property
    def context(self):
        return self._context

    @property
    def names(self):
        return [p.name for p in self._passes]


@register_pass("auto_parallel_amp")
class AMPPass(PassBase):
    """reference: passes/auto_parallel_amp.py — sets the mixed-precision
    policy (on TPU: bf16 compute, fp32 params/optimizer; no loss scaler
    needed)."""

    def _apply_single(self, config, context):
        config.setdefault("amp", {})
        config["amp"]["enable"] = self.get_attr("enable", True)
        config["amp"]["dtype"] = self.get_attr("dtype", "bfloat16")
        config["amp"]["level"] = self.get_attr("level", "O2")
        context.set_attr("amp_dtype", config["amp"]["dtype"])


@register_pass("auto_parallel_fp16")
class FP16Pass(AMPPass):
    """reference: passes/auto_parallel_fp16.py — bf16 is the TPU-native
    half type; dtype attr may still request float16."""

    def _apply_single(self, config, context):
        self.set_attr("dtype", self.get_attr("dtype", "bfloat16"))
        super()._apply_single(config, context)


@register_pass("auto_parallel_recompute")
class RecomputePass(PassBase):
    """reference: passes/auto_parallel_recompute.py — turns on selective
    rematerialisation (models honor recompute/recompute_skip/
    remat_policy; see LlamaConfig)."""

    def _apply_single(self, config, context):
        config.setdefault("recompute", {})
        config["recompute"]["enable"] = self.get_attr("enable", True)
        for k in ("checkpoints", "refined_ops_patterns", "remat_policy",
                  "recompute_skip"):
            if self.get_attr(k) is not None:
                config["recompute"][k] = self.get_attr(k)


@register_pass("auto_parallel_sharding")
class ShardingPass(PassBase):
    """reference: passes/auto_parallel_sharding.py — ZeRO stage over the
    sharding mesh axis (stage 1/2/3 = optimizer / +grad / +param
    sharding specs; see parallel/sharding.py)."""

    def _apply_single(self, config, context):
        config.setdefault("sharding", {})
        config["sharding"]["enable"] = True
        config["sharding"]["stage"] = int(self.get_attr("stage", 2))
        config["sharding"]["degree"] = int(self.get_attr("degree", 1))
        config["sharding"]["axis"] = self.get_attr("axis", "sharding")

    def _check_self(self):
        return int(self.get_attr("stage", 2)) in (1, 2, 3)


@register_pass("auto_parallel_gradient_merge")
class GradientMergePass(PassBase):
    """reference: passes/auto_parallel_gradient_merge.py — microbatch
    gradient accumulation (hapi accumulate_grad_batches / pipeline
    n_micro)."""

    def _apply_single(self, config, context):
        config.setdefault("gradient_merge", {})
        config["gradient_merge"]["enable"] = True
        config["gradient_merge"]["k_steps"] = int(
            self.get_attr("k_steps", 1))
        config["gradient_merge"]["avg"] = bool(self.get_attr("avg", True))


@register_pass("fuse_all_reduce")
class FuseAllReducePass(PassBase):
    """reference: passes/fuse_all_reduce.py — bucketed allreduce fusion.
    XLA's combiner already fuses collectives; the knob records the
    bucket size for introspection."""

    def _apply_single(self, config, context):
        config.setdefault("fuse_all_reduce", {})
        config["fuse_all_reduce"]["enable"] = self.get_attr("enable", True)
        config["fuse_all_reduce"]["max_memory_size"] = self.get_attr(
            "max_memory_size", 32 << 20)


class _PipelinePassBase(PassBase):
    schedule = "FThenB"

    def _apply_single(self, config, context):
        config.setdefault("pipeline", {})
        config["pipeline"]["enable"] = True
        config["pipeline"]["schedule_mode"] = self.schedule
        config["pipeline"]["micro_batch_size"] = self.get_attr(
            "micro_batch_size", 1)
        config["pipeline"]["accumulate_steps"] = self.get_attr(
            "accumulate_steps", 1)

    def _check_conflict(self, other):
        return not isinstance(other, _PipelinePassBase)


@register_pass("pipeline_scheduler_FThenB")
class PipelineFThenBPass(_PipelinePassBase):
    """reference: pipeline_scheduler_pass/pipeline_fthenb.py."""
    schedule = "FThenB"


@register_pass("pipeline_scheduler_1F1B")
class Pipeline1F1BPass(_PipelinePassBase):
    """reference: pipeline_scheduler_pass/pipeline_1f1b.py — the schedule
    parallel/pipeline_spmd.py realises as a scan+ppermute microbatch
    loop."""
    schedule = "1F1B"


@register_pass("pipeline_scheduler_Eager1F1B")
class PipelineEager1F1BPass(_PipelinePassBase):
    """reference: pipeline_scheduler_pass/pipeline_eager_1f1b.py:31 —
    more in-flight warmup forwards so boundary sends overlap compute;
    realised one-program in pipeline_spmd.pipeline_eager_1f1b."""
    schedule = "Eager1F1B"


@register_pass("pipeline_scheduler_VPP")
class PipelineVPPPass(_PipelinePassBase):
    """reference: pipeline_scheduler_pass/pipeline_vpp.py (interleaved
    virtual stages)."""
    schedule = "VPP"


@register_pass("pipeline_scheduler_ZBH1")
class PipelineZeroBubblePass(_PipelinePassBase):
    """reference: pipeline_scheduler_pass/pipeline_zero_bubble.py."""
    schedule = "ZBH1"
