"""paddle.distributed.passes.pipeline_scheduler_pass (reference:
distributed/passes/pipeline_scheduler_pass/__init__.py) — the schedule
passes consumed by the pp train step (see tests/test_pipeline.py)."""
from .. import (  # noqa: F401
    PassContext,
    Pipeline1F1BPass,
    PipelineEager1F1BPass,
    PipelineFThenBPass,
    PipelineVPPPass,
    PipelineZeroBubblePass,
    new_pass,
)

__all__ = []

_SCHEDULES = ("FThenB", "1F1B", "Eager1F1B", "VPP", "ZBH1")


def apply_pass(main_program, startup_program, pass_name, pass_attr=None):
    """Reference: pipeline_scheduler_pass/__init__.py:27 — build + apply the
    named schedule pass and return the scheduling plan (here: the strategy
    config dict the pp train step consumes)."""
    if pass_name not in _SCHEDULES:
        raise AssertionError(
            "pipeline scheduler only support FThenB, 1F1B, Eager1F1B, VPP "
            f"and ZBH1, but receive {pass_name}")
    pipeline_pass = new_pass("pipeline_scheduler_" + pass_name,
                             pass_attr or {})
    context = PassContext()
    pipeline_pass.apply([main_program], [startup_program], context)
    return context
