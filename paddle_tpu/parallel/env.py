"""Distributed environment (reference: python/paddle/distributed/parallel.py
ParallelEnv). Under single-controller JAX, `rank` is the process index
(jax.process_index) and world_size the process count; per-device data
parallelism inside one process is handled by sharding, not ranks.
"""
from __future__ import annotations

import os

import jax


def get_rank(group=None) -> int:
    try:
        return jax.process_index()
    except RuntimeError:
        return int(os.environ.get("PADDLE_TRAINER_ID", 0))


def get_world_size(group=None) -> int:
    try:
        return jax.process_count()
    except RuntimeError:
        return int(os.environ.get("PADDLE_TRAINERS_NUM", 1))


class ParallelEnv:
    """reference: python/paddle/distributed/parallel.py ParallelEnv."""

    @property
    def rank(self):
        return get_rank()

    @property
    def local_rank(self):
        return get_rank()

    @property
    def world_size(self):
        return get_world_size()

    @property
    def nranks(self):
        return get_world_size()

    @property
    def device_id(self):
        return 0

    @property
    def current_endpoint(self):
        return os.environ.get("PADDLE_CURRENT_ENDPOINT", "127.0.0.1:6170")

    @property
    def trainer_endpoints(self):
        return os.environ.get("PADDLE_TRAINER_ENDPOINTS", "127.0.0.1:6170").split(",")


def init_distributed(coordinator_address=None, num_processes=None,
                     process_id=None):
    """Multi-host bootstrap (reference: init_parallel_env's TCPStore/NCCL-id
    exchange, parallel.py:957) -> jax.distributed.initialize, which speaks
    to the TPU coordination service over DCN."""
    try:
        from jax._src import distributed as _jd

        if _jd.global_state.client is not None:
            return  # already initialized
    except Exception:
        pass
    addr = (coordinator_address or os.environ.get("COORDINATOR_ADDRESS")
            or os.environ.get("JAX_COORDINATOR_ADDRESS"))
    if addr:
        env_np = os.environ.get("JAX_NUM_PROCESSES") or None
        env_pid = os.environ.get("JAX_PROCESS_ID") or None
        if num_processes is None and env_np:
            num_processes = int(env_np)
        if process_id is None and env_pid:
            process_id = int(env_pid)
        if (num_processes is None) != (process_id is None):
            raise ValueError(
                "init_distributed needs BOTH num_processes and process_id "
                "(args or JAX_NUM_PROCESSES/JAX_PROCESS_ID env), or "
                f"neither; got num_processes={num_processes} "
                f"process_id={process_id}")
        jax.distributed.initialize(
            coordinator_address=addr,
            num_processes=num_processes, process_id=process_id)
