"""paddle.distributed.fleet.data_generator (reference:
distributed/fleet/data_generator/) — PS data stack (non-goal, SURVEY §7.4);
the classes raise with that pointer on construction."""
from .. import MultiSlotDataGenerator, MultiSlotStringDataGenerator  # noqa: F401

DataGenerator = MultiSlotDataGenerator

__all__ = ["DataGenerator", "MultiSlotDataGenerator",
           "MultiSlotStringDataGenerator"]
