"""paddle.distributed.fleet.elastic (reference: distributed/fleet/elastic/
{manager,collective}.py) — re-exports the TPU-native elastic manager."""
from ...elastic import DictStore, ElasticManager, ElasticStatus, FileStore  # noqa: F401

__all__ = ["ElasticManager", "ElasticStatus", "DictStore", "FileStore"]
