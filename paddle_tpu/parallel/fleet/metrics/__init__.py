"""paddle.distributed.fleet.metrics (reference:
distributed/fleet/metrics/metric.py) — global metric aggregation across the
world via all-reduce. Inputs are numpy arrays or Tensors; outputs numpy.
"""
import builtins as _bi

import numpy as _np

__all__ = ["sum", "max", "min", "auc", "mae", "rmse", "mse", "acc"]


def _to_np(x):
    arr = getattr(x, "_array", x)
    return _np.asarray(arr, dtype=_np.float64)


def _world_reduce(arr, op):
    """All-reduce a host array across processes when a multi-process world
    is initialized; identity in the single-controller case."""
    from ...env import get_world_size

    if get_world_size() <= 1:
        return arr
    from ...collective import ReduceOp, all_reduce
    from ....core.tensor import Tensor

    t = Tensor(arr)
    ops = {"sum": ReduceOp.SUM, "max": ReduceOp.MAX, "min": ReduceOp.MIN}
    all_reduce(t, op=ops[op])
    return _np.asarray(t._array, dtype=_np.float64)


def sum(input, scope=None, util=None):
    """Global elementwise sum (reference: metrics/metric.py:26)."""
    return _world_reduce(_to_np(input), "sum")


def max(input, scope=None, util=None):
    """Global elementwise max (reference :67)."""
    return _world_reduce(_to_np(input), "max")


def min(input, scope=None, util=None):
    """Global elementwise min (reference :108)."""
    return _world_reduce(_to_np(input), "min")


def auc(stat_pos, stat_neg, scope=None, util=None):
    """Global AUC from per-worker positive/negative stat buckets
    (reference :149) — same bucket math over the summed histograms."""
    pos = _world_reduce(_to_np(stat_pos), "sum").ravel()
    neg = _world_reduce(_to_np(stat_neg), "sum").ravel()
    area = 0.0
    tot_pos = 0.0
    tot_neg = 0.0
    for i in range(len(pos) - 1, -1, -1):
        new_pos = tot_pos + pos[i]
        new_neg = tot_neg + neg[i]
        area += (new_neg - tot_neg) * (tot_pos + new_pos) / 2.0
        tot_pos, tot_neg = new_pos, new_neg
    if tot_pos == 0.0 or tot_neg == 0.0:
        return 0.0
    return float(area / (tot_pos * tot_neg))


def mae(abserr, total_ins_num, scope=None, util=None):
    """Global mean absolute error (reference :233)."""
    e = float(_np.sum(_world_reduce(_to_np(abserr), "sum")))
    n = float(_np.sum(_world_reduce(_to_np(total_ins_num), "sum")))
    return e / _bi.max(n, 1.0)


def rmse(sqrerr, total_ins_num, scope=None, util=None):
    """Global root mean squared error (reference :284)."""
    e = float(_np.sum(_world_reduce(_to_np(sqrerr), "sum")))
    n = float(_np.sum(_world_reduce(_to_np(total_ins_num), "sum")))
    return float(_np.sqrt(e / _bi.max(n, 1.0)))


def mse(sqrerr, total_ins_num, scope=None, util=None):
    """Global mean squared error (reference :335)."""
    e = float(_np.sum(_world_reduce(_to_np(sqrerr), "sum")))
    n = float(_np.sum(_world_reduce(_to_np(total_ins_num), "sum")))
    return e / _bi.max(n, 1.0)


def acc(correct, total, scope=None, util=None):
    """Global accuracy (reference :385)."""
    c = float(_np.sum(_world_reduce(_to_np(correct), "sum")))
    t = float(_np.sum(_world_reduce(_to_np(total), "sum")))
    return c / _bi.max(t, 1.0)
