"""paddle.distributed.fleet.utils (reference: distributed/fleet/utils/__init__.py):
recompute entry points + filesystem helpers."""
from ...fleet_utils import (  # noqa: F401
    HDFSClient,
    LocalFS,
    recompute,
    recompute_sequential,
)

__all__ = ["LocalFS", "recompute", "recompute_sequential", "HDFSClient"]
