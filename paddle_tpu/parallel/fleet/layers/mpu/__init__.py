"""paddle.distributed.fleet.layers.mpu (reference:
distributed/fleet/layers/mpu/{mp_layers,random}.py)."""
from ....mpu import (  # noqa: F401
    ColumnParallelLinear,
    ParallelCrossEntropy,
    RNGStatesTracker,
    RowParallelLinear,
    VocabParallelEmbedding,
    get_rng_state_tracker,
)

__all__ = [
    "ColumnParallelLinear", "RowParallelLinear", "VocabParallelEmbedding",
    "ParallelCrossEntropy", "RNGStatesTracker", "get_rng_state_tracker",
    "model_parallel_random_seed", "dropout",
]


def model_parallel_random_seed(seed=None):
    """Seed the tracker with distinct global/local streams per mp rank
    (reference: layers/mpu/random.py model_parallel_random_seed)."""
    import random as _pyrandom

    from ....env import get_rank

    seed = seed if seed is not None else _pyrandom.randint(0, 2**31 - 1)
    tracker = get_rng_state_tracker()
    tracker.reset()
    tracker.add("global_seed", seed)
    tracker.add("local_seed", seed + 1024 + get_rank())
    return seed


def dropout(x, p=0.5, axis=None, rng_name=None, training=True, mode="upscale_in_train", name=None):
    """Dropout drawing its randomness from a tracker stream when ``rng_name``
    is given (reference: layers/mpu/random.py dropout)."""
    from .....nn import functional as F

    if rng_name is None:
        return F.dropout(x, p=p, axis=axis, training=training, mode=mode)
    with get_rng_state_tracker().rng_state(rng_name):
        return F.dropout(x, p=p, axis=axis, training=training, mode=mode)
