"""paddle.distributed.fleet.layers — tensor-parallel layer namespace."""
from . import mpu  # noqa: F401
