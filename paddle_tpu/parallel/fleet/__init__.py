"""fleet facade: init / distributed_model / distributed_optimizer.

Reference: python/paddle/distributed/fleet/fleet.py:166 (fleet.init),
fleet/model.py:32 (distributed_model wraps per active axes),
fleet/base/distributed_strategy.py (proto-backed DistributedStrategy,
distributed_strategy.proto:359).
"""
from __future__ import annotations

from typing import Optional

from .. import mesh as mesh_mod
from ..data_parallel import DataParallel
from ..mesh import HybridCommunicateGroup, auto_mesh
from ..sharding import group_sharded_parallel, shard_accumulators

__all__ = ["DistributedStrategy", "init", "get_hybrid_communicate_group",
           "distributed_model", "distributed_optimizer", "fleet"]


class _HybridConfigs(dict):
    __getattr__ = dict.get

    def __setattr__(self, k, v):
        self[k] = v


class DistributedStrategy:
    """Knob container (reference: distributed_strategy.proto — amp/recompute/
    sharding/pipeline/mp knobs). Only the hybrid degrees drive behavior on
    TPU; the rest are stored for API parity and surfaced to passes."""

    def __init__(self):
        self.hybrid_configs = {
            "dp_degree": 1, "mp_degree": 1, "pp_degree": 1,
            "sharding_degree": 1, "sep_degree": 1,
        }
        self.amp = False
        self.amp_configs = {}
        self.recompute = False
        self.recompute_configs = {}
        self.sharding = False
        self.sharding_configs = {}
        self.pipeline = False
        self.pipeline_configs = {"accumulate_steps": 1, "micro_batch_size": 1}
        self.tensor_parallel = False
        self.tensor_parallel_configs = {}
        self.gradient_merge = False
        self.gradient_merge_configs = {}
        self.find_unused_parameters = False


class _Fleet:
    def __init__(self):
        self._hcg: Optional[HybridCommunicateGroup] = None
        self._strategy: Optional[DistributedStrategy] = None
        self._is_initialized = False

    def init(self, role_maker=None, is_collective: bool = True, strategy=None,
             log_level="INFO"):
        """Build the hybrid mesh from strategy.hybrid_configs
        (reference: fleet.py:166 + HybridCommunicateGroup ctor)."""
        strategy = strategy or DistributedStrategy()
        hc = strategy.hybrid_configs
        degrees = {}
        for axis, key in (("dp", "dp_degree"), ("pp", "pp_degree"),
                          ("sharding", "sharding_degree"),
                          ("sep", "sep_degree"), ("mp", "mp_degree")):
            d = int(hc.get(key, 1) or 1)
            if axis != "dp":
                degrees[axis] = d
        # dp_degree=1 is the strategy default and means "infer"; an explicit
        # dp_degree>1 participates in the product check inside auto_mesh
        cfg_dp = int(hc.get("dp_degree", 1) or 1)
        if cfg_dp > 1:
            degrees["dp"] = cfg_dp
        mesh = auto_mesh(**degrees)
        self._hcg = HybridCommunicateGroup(mesh)
        self._strategy = strategy
        self._is_initialized = True
        return self

    def get_hybrid_communicate_group(self) -> HybridCommunicateGroup:
        if self._hcg is None:
            self.init()
        return self._hcg

    def distributed_model(self, model):
        """Wrap per active axes (reference: fleet/model.py:32,141-160)."""
        hcg = self.get_hybrid_communicate_group()
        if hcg.get_pipe_parallel_world_size() > 1:
            from ..pipeline import PipelineParallel

            return PipelineParallel(model, hcg, self._strategy)
        if hcg.get_sharding_parallel_world_size() > 1:
            # stage selection follows the reference default (stage 1:
            # optimizer states only, applied in distributed_optimizer);
            # params are sharded here only for stage 3
            stage = int((self._strategy.sharding_configs or {}).get(
                "stage", 1)) if self._strategy is not None else 1
            if stage >= 3:
                from ..sharding import shard_params_stage3

                model = shard_params_stage3(model, hcg.mesh)
        if hcg.get_data_parallel_world_size() > 1:
            return DataParallel(model)
        return model

    def distributed_optimizer(self, optimizer, strategy=None):
        """reference: HybridParallelOptimizer
        (fleet/meta_optimizers/dygraph_optimizer/hybrid_parallel_optimizer.py:255)."""
        hcg = self.get_hybrid_communicate_group()
        if hcg.get_sharding_parallel_world_size() > 1:
            optimizer = shard_accumulators(optimizer)
        return optimizer

    # role info
    def worker_index(self):
        from ..env import get_rank

        return get_rank()

    def worker_num(self):
        from ..env import get_world_size

        return get_world_size()

    def is_first_worker(self):
        return self.worker_index() == 0

    def barrier_worker(self):
        from ..collective import barrier

        barrier()

    @property
    def is_initialized(self):
        return self._is_initialized


fleet = _Fleet()
init = fleet.init
get_hybrid_communicate_group = fleet.get_hybrid_communicate_group
distributed_model = fleet.distributed_model
distributed_optimizer = fleet.distributed_optimizer
worker_index = fleet.worker_index
worker_num = fleet.worker_num
is_first_worker = fleet.is_first_worker
barrier_worker = fleet.barrier_worker
def is_initialized():
    # the instance exposes a property; the module-level form is a callable
    # evaluated at call time (a direct alias would freeze the import-time
    # value)
    return fleet.is_initialized


# ---------------------------------------------------------------------------
# remaining fleet __all__ classes (reference:
# python/paddle/distributed/fleet/__init__.py);
# HybridCommunicateGroup is already imported from .mesh above
# ---------------------------------------------------------------------------
Fleet = _Fleet  # reference: fleet/fleet.py Fleet


class Role:
    """reference: fleet/base/role_maker.py Role constants."""
    WORKER = 1
    SERVER = 2
    HETER_WORKER = 3
    ALL = 4
    COORDINATOR = 5


class RoleMakerBase:
    """Environment discovery base (reference: role_maker.py:542
    PaddleCloudRoleMaker reads the launcher's env). On TPU the launcher
    exports the same PADDLE_* variables; single-controller JAX means one
    python process per host and every process is a WORKER."""

    def __init__(self, is_collective=True, **kwargs):
        import os

        self._is_collective = is_collective
        self._rank = int(os.environ.get("PADDLE_TRAINER_ID", 0))
        eps = os.environ.get("PADDLE_TRAINER_ENDPOINTS", "")
        self._world = len(eps.split(",")) if eps else int(
            os.environ.get("PADDLE_TRAINERS_NUM", 1))

    def _worker_index(self):
        return self._rank

    def _worker_num(self):
        return self._world

    def _role(self):
        return Role.WORKER

    def _is_worker(self):
        return True

    def _is_server(self):
        return False


class PaddleCloudRoleMaker(RoleMakerBase):
    """reference: role_maker.py PaddleCloudRoleMaker."""


class UserDefinedRoleMaker(RoleMakerBase):
    """reference: role_maker.py UserDefinedRoleMaker — explicit rank/world
    instead of env discovery."""

    def __init__(self, is_collective=True, init_gloo=False, current_id=0,
                 worker_num=1, **kwargs):
        super().__init__(is_collective=is_collective)
        self._rank = current_id
        self._world = worker_num


class CommunicateTopology:
    """reference: fleet/base/topology.py:68 CommunicateTopology — the
    named cartesian rank topology backing HybridCommunicateGroup."""

    def __init__(self, hybrid_group_names=("data", "pipe", "sharding",
                                           "sep", "model"),
                 dims=(1, 1, 1, 1, 1)):
        import itertools as _it

        self._names = list(hybrid_group_names)
        self._dims = list(dims)
        self._world = 1
        for d in self._dims:
            self._world *= d
        coords = list(_it.product(*[range(d) for d in self._dims]))
        self._coord_of_rank = {i: c for i, c in enumerate(coords)}
        self._rank_of_coord = {c: i for i, c in enumerate(coords)}

    def get_hybrid_group_names(self):
        return list(self._names)

    def get_dim(self, axis_name):
        return self._dims[self._names.index(axis_name)]

    get_dim_size = get_dim

    def world_size(self):
        return self._world

    def get_rank(self, **kwargs):
        coord = tuple(kwargs[n] for n in self._names)
        return self._rank_of_coord[coord]

    def get_coord(self, rank):
        return self._coord_of_rank[rank]

    def get_axis_list(self, axis_name, index):
        ax = self._names.index(axis_name)
        return [r for r, c in self._coord_of_rank.items()
                if c[ax] == index]

    def get_comm_list(self, axis_name):
        """All rank groups along `axis_name` (the NCCL-group sets the
        reference builds; here they parameterise mesh axis groups)."""
        ax = self._names.index(axis_name)
        groups = {}
        for r, c in self._coord_of_rank.items():
            key = c[:ax] + c[ax + 1:]
            groups.setdefault(key, []).append(r)
        return [sorted(v) for _, v in sorted(groups.items())]


class UtilBase:
    """reference: fleet/utils/fs.py-backed UtilBase — cross-rank helper
    ops over the collective API."""

    def all_reduce(self, input, mode="sum"):
        from .. import collective as _c
        from ...core.tensor import Tensor
        import numpy as _np

        t = input if isinstance(input, Tensor) else Tensor(
            _np.asarray(input))
        op = {"sum": _c.ReduceOp.SUM, "max": _c.ReduceOp.MAX,
              "min": _c.ReduceOp.MIN}[mode]
        return _c.all_reduce(t, op=op)

    def barrier(self, comm_world="worker"):
        from ..watchdog import barrier as _b

        _b()

    def all_gather(self, input, comm_world="worker"):
        from .. import collective as _c
        from ...core.tensor import Tensor
        import numpy as _np

        t = input if isinstance(input, Tensor) else Tensor(
            _np.asarray(input))
        out = []
        _c.all_gather(out, t)
        return out


def _ps_data_generator(name):
    class _Refusal:
        def __init__(self, *a, **k):
            raise NotImplementedError(
                f"{name} belongs to the parameter-server data stack "
                "(non-goal, SURVEY §7.4); use paddle_tpu.io.DataLoader")
    _Refusal.__name__ = name
    return _Refusal


MultiSlotDataGenerator = _ps_data_generator("MultiSlotDataGenerator")
MultiSlotStringDataGenerator = _ps_data_generator(
    "MultiSlotStringDataGenerator")

__all__ += ["Fleet", "Role", "PaddleCloudRoleMaker", "UserDefinedRoleMaker",
            "CommunicateTopology", "HybridCommunicateGroup", "UtilBase",
            "MultiSlotDataGenerator", "MultiSlotStringDataGenerator"]

from . import utils  # noqa: E402,F401
from . import base  # noqa: E402,F401
from . import elastic  # noqa: E402,F401
from . import layers  # noqa: E402,F401
from . import meta_optimizers  # noqa: E402,F401
from . import meta_parallel  # noqa: E402,F401
from . import metrics  # noqa: E402,F401
from . import recompute  # noqa: E402,F401
__all__ += ["utils"]
