"""paddle.distributed.fleet.meta_parallel (reference:
distributed/fleet/meta_parallel/__init__.py).

The reference's MetaParallelBase wrappers exist to broadcast parameters and
sync gradients through NCCL process groups. Under SPMD/jax, parameter
placement and gradient sync are expressed through shardings on the jitted
step, so these wrappers reduce to thin Layer adapters that mark the model's
parallel mode — kept because user code type-checks against them and calls
``model = fleet.distributed_model(model)`` style flows.
"""
from ...data_parallel import DataParallel  # noqa: F401
from ...mpu import (  # noqa: F401
    ColumnParallelLinear,
    ParallelCrossEntropy,
    RNGStatesTracker,
    RowParallelLinear,
    VocabParallelEmbedding,
    get_rng_state_tracker,
)
from ...pipeline import (  # noqa: F401
    LayerDesc,
    PipelineLayer,
    PipelineParallel,
    SharedLayerDesc,
)
from ...sequence_parallel import SegmentParallel  # noqa: F401
from ..layers.mpu import model_parallel_random_seed  # noqa: F401
from . import parallel_layers  # noqa: F401
from . import pp_utils  # noqa: F401
from . import sharding  # noqa: F401


class _MetaParallelBase:
    """Adapter: hold the wrapped layers, delegate forward."""

    def __init__(self, layers, hcg=None, strategy=None, **kwargs):
        self._layers = layers
        self._hcg = hcg
        self._strategy = strategy

    def __getattr__(self, name):
        return getattr(self.__dict__["_layers"], name)

    def __call__(self, *args, **kwargs):
        return self._layers(*args, **kwargs)

    def forward(self, *args, **kwargs):
        return self._layers(*args, **kwargs)


class TensorParallel(_MetaParallelBase):
    """reference: meta_parallel/tensor_parallel.py:28 — param broadcast is a
    sharding annotation under SPMD, so construction is the whole contract."""


class ShardingParallel(_MetaParallelBase):
    """reference: meta_parallel/sharding_parallel.py:25."""


class PipelineParallelWithInterleave(PipelineParallel):
    """reference: meta_parallel/pipeline_parallel.py:1009. The interleaved
    schedule itself lives in parallel/pipeline_spmd.py (schedule="VPP")."""

    def __init__(self, layers, hcg=None, strategy=None, **kwargs):
        super().__init__(layers, hcg=hcg, strategy=strategy, **kwargs)


class PipelineParallelWithInterleaveFthenB(PipelineParallelWithInterleave):
    """reference: meta_parallel/pipeline_parallel.py (interleave + FthenB)."""


__all__ = [
    "ColumnParallelLinear", "RowParallelLinear", "VocabParallelEmbedding",
    "ParallelCrossEntropy", "RNGStatesTracker", "get_rng_state_tracker",
    "model_parallel_random_seed", "LayerDesc", "SharedLayerDesc",
    "PipelineLayer", "PipelineParallel", "PipelineParallelWithInterleave",
    "PipelineParallelWithInterleaveFthenB", "SegmentParallel",
    "ShardingParallel", "TensorParallel", "DataParallel",
]
