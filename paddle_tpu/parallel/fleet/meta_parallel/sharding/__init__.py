"""paddle.distributed.fleet.meta_parallel.sharding (reference:
distributed/fleet/meta_parallel/sharding/__init__.py — GroupSharded*).
ZeRO staging under SPMD is a sharding annotation on optimizer/param state;
the user entry point is group_sharded_parallel."""
from ....sharding import group_sharded_parallel, shard_accumulators  # noqa: F401

__all__ = ["group_sharded_parallel", "shard_accumulators"]
