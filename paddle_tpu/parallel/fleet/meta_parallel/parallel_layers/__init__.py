"""paddle.distributed.fleet.meta_parallel.parallel_layers (reference:
distributed/fleet/meta_parallel/parallel_layers/__init__.py)."""
from ....mpu import (  # noqa: F401
    ColumnParallelLinear,
    ParallelCrossEntropy,
    RNGStatesTracker,
    RowParallelLinear,
    VocabParallelEmbedding,
    get_rng_state_tracker,
)
from ....pipeline import LayerDesc, PipelineLayer, SharedLayerDesc  # noqa: F401
from ...layers.mpu import model_parallel_random_seed  # noqa: F401
