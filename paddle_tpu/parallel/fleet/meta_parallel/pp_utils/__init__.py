"""paddle.distributed.fleet.meta_parallel.pp_utils (reference:
distributed/fleet/meta_parallel/pp_utils/__init__.py). P2P send/recv
batching is a ppermute inside the one-program pipeline under SPMD; the
micro-batch utilities remain useful."""
import numpy as _np


def get_tensor_bytes(tensor):
    """reference: pp_utils/utils.py get_tensor_bytes."""
    arr = getattr(tensor, "_array", tensor)
    return int(_np.prod(arr.shape)) * _np.dtype(str(arr.dtype).split(".")[-1]).itemsize


__all__ = ["get_tensor_bytes"]
