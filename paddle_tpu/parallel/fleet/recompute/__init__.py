"""paddle.distributed.fleet.recompute (reference:
distributed/fleet/recompute/{recompute,recompute_hybrid}.py).

``recompute_hybrid``'s mp-aware RNG bookkeeping is unnecessary under jax —
``jax.checkpoint`` replays the same PRNG key threading on the backward
rematerialization — so it shares the plain implementation.
"""
from ...fleet_utils import recompute, recompute_sequential  # noqa: F401


def recompute_hybrid(ctx, function, *args, **kwargs):
    """Hybrid-parallel recompute (mp group offload/partition hints in `ctx`
    are no-ops on TPU: remat is XLA-scheduled, not manually offloaded)."""
    return recompute(function, *args, **kwargs)


__all__ = ["recompute", "recompute_sequential", "recompute_hybrid"]
