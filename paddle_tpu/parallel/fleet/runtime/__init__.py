"""paddle.distributed.fleet.runtime (reference:
distributed/fleet/runtime/) — PS runtime plugins (non-goal, SURVEY §7.4)."""


class TheOnePSRuntime:
    def __init__(self, *a, **k):
        raise NotImplementedError(
            "TheOnePSRuntime is the parameter-server runtime "
            "(non-goal, SURVEY §7.4); collective training needs no runtime "
            "plugin under SPMD.")


__all__ = ["TheOnePSRuntime"]
