"""paddle.distributed.fleet.dataset (reference:
distributed/fleet/dataset/) — PS in-memory/queue datasets; the facades live
in parallel/compat.py (loud PS refusals; paddle.io is the data path)."""
from ...compat import InMemoryDataset, QueueDataset  # noqa: F401

DatasetBase = QueueDataset

__all__ = ["DatasetBase", "InMemoryDataset", "QueueDataset"]
