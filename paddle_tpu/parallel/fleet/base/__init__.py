"""paddle.distributed.fleet.base (reference: distributed/fleet/base/
{role_maker,topology,util_factory}.py)."""
from . import role_maker  # noqa: F401
from .. import (  # noqa: F401
    CommunicateTopology,
    PaddleCloudRoleMaker,
    Role,
    RoleMakerBase,
    UserDefinedRoleMaker,
    UtilBase,
)
from ...mesh import HybridCommunicateGroup  # noqa: F401

__all__ = [
    "Role", "RoleMakerBase", "PaddleCloudRoleMaker", "UserDefinedRoleMaker",
    "CommunicateTopology", "HybridCommunicateGroup", "UtilBase",
]
