"""paddle.distributed.fleet.base.role_maker (reference:
distributed/fleet/base/role_maker.py)."""
from .. import PaddleCloudRoleMaker, Role, RoleMakerBase, UserDefinedRoleMaker  # noqa: F401

__all__ = ["Role", "RoleMakerBase", "PaddleCloudRoleMaker", "UserDefinedRoleMaker"]
