"""paddle.distributed.fleet.meta_optimizers.sharding (reference:
distributed/fleet/meta_optimizers/sharding/ — static-graph sharding pass
helpers). The SPMD equivalents live in parallel/sharding.py."""
from ....sharding import (  # noqa: F401
    group_sharded_parallel,
    save_group_sharded_model,
    shard_accumulators,
    shard_params_stage3,
)

__all__ = [
    "group_sharded_parallel", "save_group_sharded_model",
    "shard_accumulators", "shard_params_stage3",
]
