"""paddle.distributed.fleet.meta_optimizers.dygraph_optimizer (reference:
distributed/fleet/meta_optimizers/dygraph_optimizer/__init__.py:
DygraphShardingOptimizer, HybridParallelOptimizer, HybridParallelGradScaler).

Under SPMD, gradient sync and state sharding are sharding annotations on the
jitted step; these wrappers adapt that contract to the reference's
object API (delegate to the inner optimizer, shard accumulators on demand).
"""
from ....sharding import shard_accumulators

__all__ = [
    "DygraphShardingOptimizer", "HybridParallelOptimizer",
    "HybridParallelGradScaler",
]


class _DelegatingOptimizer:
    def __init__(self, optimizer):
        self._inner_opt = optimizer

    def __getattr__(self, name):
        return getattr(self.__dict__["_inner_opt"], name)

    def step(self):
        return self._inner_opt.step()

    def clear_grad(self, *a, **k):
        return self._inner_opt.clear_grad(*a, **k)

    def minimize(self, *a, **k):
        return self._inner_opt.minimize(*a, **k)


class DygraphShardingOptimizer(_DelegatingOptimizer):
    """ZeRO-1: optimizer accumulators sharded over the sharding axis
    (reference: dygraph_sharding_optimizer.py DygraphShardingOptimizer)."""

    def __init__(self, optimizer, hcg=None):
        super().__init__(shard_accumulators(optimizer))
        self._hcg = hcg


class HybridParallelOptimizer(_DelegatingOptimizer):
    """reference: hybrid_parallel_optimizer.py:255 — grad sync across
    dp/mp/pp groups is implicit in the sharded step; sharding stage 1
    applied when the hybrid group has a sharding dimension."""

    def __init__(self, optimizer, hcg=None, strategy=None):
        if hcg is not None and getattr(hcg, "get_sharding_parallel_world_size", lambda: 1)() > 1:
            optimizer = shard_accumulators(optimizer)
        super().__init__(optimizer)
        self._hcg = hcg
        self._strategy = strategy


class HybridParallelGradScaler:
    """reference: hybrid_parallel_gradscaler.py — delegates to amp.GradScaler
    (found-inf is globally consistent under SPMD, no cross-group allreduce)."""

    def __init__(self, scaler, hcg=None):
        self._scaler = scaler
        self._hcg = hcg

    def __getattr__(self, name):
        return getattr(self.__dict__["_scaler"], name)

    def scale(self, var):
        return self._scaler.scale(var)

    def minimize(self, optimizer, *args, **kwargs):
        return self._scaler.minimize(optimizer, *args, **kwargs)
