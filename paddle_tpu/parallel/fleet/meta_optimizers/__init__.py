"""paddle.distributed.fleet.meta_optimizers (reference:
distributed/fleet/meta_optimizers/__init__.py).

Under SPMD the "meta optimizer" transformations (amp, recompute, sharding,
gradient merge) are strategy knobs consumed by the jitted train step
(parallel/trainer.py make_train_step); these classes adapt that to the
reference's wrapper-object API."""
from . import dygraph_optimizer  # noqa: F401
from . import sharding  # noqa: F401
from .dygraph_optimizer import (  # noqa: F401
    DygraphShardingOptimizer,
    HybridParallelGradScaler,
    HybridParallelOptimizer,
)

__all__ = [
    "DygraphShardingOptimizer", "HybridParallelOptimizer",
    "HybridParallelGradScaler",
]
