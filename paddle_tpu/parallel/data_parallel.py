"""DataParallel wrapper + gradient sync semantics.

Reference: python/paddle/distributed/parallel.py:207 (paddle.DataParallel)
backed by the C++ EagerReducer (fluid/distributed/collective/reducer.h:88):
bucketed grad fusion + async allreduce overlapped with backward, `no_sync`
to skip sync during gradient accumulation.

TPU-native: data parallelism is batch sharding over the `dp` mesh axis.
Params are replicated; XLA emits one fused reduce for the gradient of each
replicated param automatically during the backward of a pjit'd step — the
EagerReducer's bucketing/overlap is exactly what the XLA scheduler does with
collective-matmul overlap. The wrapper's job reduces to (a) laying out
inputs over `dp`, (b) API parity (`no_sync`, `scale_loss`)."""
from __future__ import annotations

import contextlib

from ..core.tensor import Tensor
from ..nn.layer.layers import Layer
from . import mesh as mesh_mod
from .api import shard_constraint
from .placement import Replicate, Shard

__all__ = ["DataParallel", "scale_batch"]


def scale_batch(x, axis_name: str = "dp"):
    """Annotate a batch tensor as sharded on dim 0 over `dp`."""
    mesh = mesh_mod.get_global_mesh()
    if mesh is None or axis_name not in mesh.axis_names:
        return x
    pl = [Shard(0) if a == axis_name else Replicate() for a in mesh.axis_names]
    return shard_constraint(x, pl, mesh)


class DataParallel(Layer):
    """reference: paddle.DataParallel(layers, strategy=None, comm_buffer_size,
    last_comm_buffer_size, find_unused_parameters)."""

    def __init__(self, layers: Layer, strategy=None, comm_buffer_size=25,
                 last_comm_buffer_size=1, find_unused_parameters=False,
                 group=None):
        super().__init__()
        self._layers = layers
        self._sync = True

    def forward(self, *inputs, **kwargs):
        inputs = tuple(
            scale_batch(i) if isinstance(i, Tensor) and i.ndim > 0 else i
            for i in inputs)
        return self._layers(*inputs, **kwargs)

    @contextlib.contextmanager
    def no_sync(self):
        """Gradient-accumulation window (reference: parallel.py no_sync).
        Under single-controller SPMD grads are only materialized at step
        boundaries, so nothing to suppress — parity no-op."""
        self._sync = False
        try:
            yield
        finally:
            self._sync = True

    def scale_loss(self, loss):
        return loss

    def state_dict(self, *a, **k):
        return self._layers.state_dict(*a, **k)

    def set_state_dict(self, sd, *a, **k):
        return self._layers.set_state_dict(sd, *a, **k)

    def __getattr__(self, name):
        try:
            return super().__getattr__(name)
        except AttributeError:
            return getattr(self.__dict__["_sub_layers"]["_layers"], name)
