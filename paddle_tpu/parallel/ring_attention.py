"""Ring attention: exact attention over sequence-sharded q/k/v.

Reference gap: the reference snapshot has NO ring-attention kernel — its
long-context story is the `sep` axis with all-to-all (Ulysses-class)
patterns (SURVEY.md §5.7). This module is the leapfrog: context parallelism
where each `sep` rank holds a sequence chunk of q/k/v and k/v chunks rotate
around the ring with `lax.ppermute`, combining per-chunk attention with
online-softmax statistics (the blockwise-attention recurrence of the
flash/ring-attention papers). Peak memory per chip is O(S/n * S/n) for one
score block — never the full S x S matrix — and the rotation overlaps with
compute on ICI.

Differentiable: the ring loop is a `lax.scan` of jax.checkpoint'ed steps;
autodiff replays the ring in reverse with the same collectives.
"""
from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from . import mesh as mesh_mod

from .shard_map_compat import shard_map
from .pipeline_spmd import _to_varying

__all__ = ["ring_attention"]

_NEG_INF = -1e30


def _block_attn(q, k, v, scale, q_off, k_off, causal):
    """One blockwise contribution. q: [B,Sq,Hq,D]; k/v: [B,Sk,Hk,D] with
    Hk dividing Hq (GQA via grouped einsum — no materialized repeat).
    Returns (num [B,Sq,Hq,D] f32, m [B,Sq,Hq,1] f32, l [B,Sq,Hq,1] f32) —
    unnormalized output + row stats."""
    b, sq_, hq, d = q.shape
    hk = k.shape[2]
    rep = hq // hk
    qg = q.reshape(b, sq_, hk, rep, d)
    s = jnp.einsum("bqhrd,bkhd->bhrqk", qg, k).astype(jnp.float32) * scale
    if causal:
        sk_ = k.shape[1]
        qpos = q_off + jnp.arange(sq_)
        kpos = k_off + jnp.arange(sk_)
        mask = qpos[:, None] >= kpos[None, :]
        s = jnp.where(mask[None, None, None], s, _NEG_INF)
    m = jnp.max(s, axis=-1, keepdims=True)                 # [B,Hk,rep,Sq,1]
    p = jnp.exp(s - m)
    l = jnp.sum(p, axis=-1, keepdims=True)
    num = jnp.einsum("bhrqk,bkhd->bqhrd", p.astype(v.dtype), v).astype(
        jnp.float32).reshape(b, sq_, hq, d)
    # stats to [B,Sq,Hq,1]
    m = jnp.moveaxis(m[..., 0], 3, 1).reshape(b, sq_, hq)[..., None]
    l = jnp.moveaxis(l[..., 0], 3, 1).reshape(b, sq_, hq)[..., None]
    return num, m, l


def ring_attention(q, k, v, *, mesh: Optional[Mesh] = None,
                   axis: str = "sep", causal: bool = True,
                   scale: Optional[float] = None):
    """Exact attention with q/k/v sequence-sharded over `axis`.

    q/k/v: [B, S, H, D] global arrays (S divisible by the axis size);
    returns [B, S, H, D] with the same sequence sharding.
    """
    b, s, h, d = q.shape
    if scale is None:
        scale = 1.0 / math.sqrt(d)
    mesh = mesh or mesh_mod.get_global_mesh()
    if mesh is None or axis not in mesh.axis_names \
            or int(mesh.shape[axis]) == 1:
        num, m, l = _block_attn(q, k, v, scale, 0, 0, causal)
        return (num / l).astype(q.dtype)

    n = int(mesh.shape[axis])
    if s % n:
        raise ValueError(f"seq {s} not divisible by {axis} size {n}")
    chunk = s // n

    @functools.partial(shard_map, mesh=mesh, axis_names={axis},
                       in_specs=(P(None, axis), P(None, axis),
                                 P(None, axis)),
                       out_specs=P(None, axis))
    def run(ql, kl, vl):
        idx = jax.lax.axis_index(axis)
        q_off = idx * chunk
        perm = [(i, (i + 1) % n) for i in range(n)]

        @jax.checkpoint
        def step_compute(ql, kv, r):
            kc, vc = kv
            src = (idx - r) % n          # rank that produced this kv chunk
            return _block_attn(ql, kc, vc, scale, q_off, src * chunk,
                               causal)

        def combine(acc, block):
            num, m, l = acc
            bnum, bm, bl = block
            m_new = jnp.maximum(m, bm)
            c_old = jnp.exp(m - m_new)
            c_new = jnp.exp(bm - m_new)
            return (num * c_old + bnum * c_new, m_new,
                    l * c_old + bl * c_new)

        def tick(carry, r):
            num, m, l, kv = carry
            num, m, l = combine((num, m, l), step_compute(ql, kv, r))
            kv = jax.tree.map(lambda t: jax.lax.ppermute(t, axis, perm), kv)
            return (num, m, l, kv), None

        num0 = _to_varying(jnp.zeros(ql.shape, jnp.float32), axis)
        m0 = _to_varying(jnp.full((b, chunk, h, 1), _NEG_INF, jnp.float32),
                         axis)
        l0 = _to_varying(jnp.zeros((b, chunk, h, 1), jnp.float32), axis)
        # n-1 rotating ticks, then the final block without the (wasted)
        # last rotation
        (num, m, l, kv), _ = jax.lax.scan(
            tick, (num0, m0, l0, (kl, vl)), jnp.arange(n - 1))
        num, m, l = combine((num, m, l),
                            step_compute(ql, kv, jnp.asarray(n - 1)))
        # rows with no valid key (can't happen with causal self-attention
        # of equal lengths, but guard the division)
        return (num / jnp.maximum(l, 1e-30)).astype(ql.dtype)

    return run(q, k, v)
