"""Quantized collectives: int8 all-gather / psum / reduce-scatter with
an f32 scale sidecar (EQuARX-style, ISSUE 15).

The wire is the scarce resource at the two hot seams PR 11's comms
auditor priced (TPU803 names both): the per-layer decode o-proj
activation all-gather at serving_mp > 1, and the dp gradient psum in
`Model.fit`. This module ships those payloads as absmax-scaled int8
with a tiny f32 scale sidecar — the exact proven scheme of the PR 5
int8 KV pools (per-block absmax/127, zero block -> scale 0 ->
exact-zero dequant), block-quantized along the LAST dim so the sidecar
stays ~3% of the payload at block 128:

- **quantized_all_gather**: quantize locally, all-gather ONE int8
  buffer (the f32 scales ride bitcast-int8, concatenated onto the
  payload's last axis), dequantize locally. One rounding per element;
  wire bytes ~0.5x a bf16 payload, ~0.25x an f32 one.
- **quantized_psum**: reduce-scatter on int8 shards (an `all_to_all` of
  per-destination quantized chunks, sidecar packed in), local
  dequant-ACCUMULATE in f32 (so accumulation error does NOT scale with
  world size — each contribution is rounded once, the sum is exact
  f32), then a quantized all-gather of the reduced shard. Two roundings
  per element total, independent of n.
- **quantized_reduce_scatter**: the first hop alone (the
  `lax.psum_scatter(tiled=True)` shape contract).
- **quantized_psum_tree**: the dp gradient sync — flattens a grad
  pytree into ONE f32 vector, runs one quantized psum (one collective
  pair instead of one per leaf), and unflattens at the leaves' dtypes.

Numerics guards (never silent corruption):

- an all-zero block keeps scale 0 and dequantizes to EXACT zeros (zero
  gradients survive bit-exactly);
- a block containing NaN/inf stores a NON-FINITE scale, so the whole
  block dequantizes non-finite — a poisoned payload stays VISIBLY
  poisoned instead of silently clipping to finite garbage;
- payloads that cannot be quantized at all (non-float dtypes, empty or
  0-d arrays, a gather along the block axis) fall back to the plain
  collective with a build-time warning.

Cost model note: each quantized hop issues ONE collective — the f32
sidecar is bitcast to int8 and PACKED into the payload buffer
(`_pack_scales` / `_unpack_scales`), so the launch count matches the
plain op exactly and a launch-bound tiny-payload path (the per-layer
decode gather the ROADMAP silicon note flagged) cannot lose on
dispatch. The bitcast is a free relayout on both ends; the wire sees
the identical byte count the two-collective form shipped.

Flag: FLAGS_quantized_collectives / PADDLE_TPU_QUANTIZED_COLLECTIVES,
default OFF, resolved at program-BUILD time like every serving flag
(`resolve_quantized_collectives`): it joins the serving jit program
keys and `warm()` covers it; flag OFF is byte-identical to a build
without it. `analysis/comms.py` recognizes the packed int8 buffers
(the only int8 tensors the stack ever puts on a collective) and
prices them as quantized wire; TPU803 never fires on an int8 payload
by design.
"""
from __future__ import annotations

import warnings
from typing import Optional

import jax
import jax.numpy as jnp

__all__ = [
    "QCOLL_BLOCK", "QCOLL_FALLBACK_MSG", "resolve_quantized_collectives",
    "quantize_blocks", "dequantize_blocks", "quantized_all_gather",
    "quantized_psum", "quantized_psum_tree", "quantized_reduce_scatter",
]

# lane-width blocks along the last dim: one f32 scale per 128 int8
# payload bytes keeps the sidecar ~3% of the payload (payloads narrower
# than a block use one scale per row — the block clamps to the dim)
QCOLL_BLOCK = 128

QCOLL_FALLBACK_MSG = (
    "payload cannot be block-quantized; falling back to the "
    "unquantized collective (full-width wire bytes, exact numerics)")


def resolve_quantized_collectives(quantized: Optional[bool] = None) -> bool:
    """Resolve the quantized-collectives switch from the argument or
    FLAGS_quantized_collectives / PADDLE_TPU_QUANTIZED_COLLECTIVES.
    Read at program-BUILD time (like FLAGS_kv_cache_dtype /
    FLAGS_serving_mp): flip it before constructing or warming an
    engine, or before calling Model.fit. False (default) keeps every
    wire byte-identical to a build without the flag."""
    if quantized is None:
        from ..framework.flags import flag as _flag

        quantized = _flag("quantized_collectives")
    return bool(quantized)


def _quantizable(x) -> bool:
    return (getattr(x, "ndim", 0) >= 1 and x.size > 0
            and jnp.issubdtype(jnp.asarray(x).dtype, jnp.floating))


def quantize_blocks(x, block: int = QCOLL_BLOCK):
    """Symmetric absmax int8 quantization in blocks along the LAST dim
    (the PR 5 KV-pool scheme, per wire block instead of per page).

    x [..., d] float -> (q int8 [..., nb*be], scale f32 [..., nb]) with
    be = min(block, d), nb = ceil(d / be); the last partial block pads
    with zeros (trimmed again by `dequantize_blocks(..., out_dim=)`).
    The absmax is taken in f32 BEFORE any half-precision round-trip;
    scale = absmax / 127. An all-zero block keeps scale 0 (dequantizes
    to exact zeros); a block with NaN/inf stores a NON-FINITE scale so
    the dequant is visibly poisoned, never silently finite."""
    d = int(x.shape[-1])
    be = min(int(block), d)
    nb = -(-d // be)
    xf = x.astype(jnp.float32)
    pad = nb * be - d
    if pad:
        xf = jnp.pad(xf, [(0, 0)] * (x.ndim - 1) + [(0, pad)])
    xb = xf.reshape(x.shape[:-1] + (nb, be))
    scale = jnp.max(jnp.abs(xb), axis=-1) / 127.0
    # a NaN absmax fails the > 0 test, so safe stays 1.0 and q holds
    # garbage ints — harmless, because the STORED scale is the
    # non-finite absmax and the dequant poisons the whole block
    safe = jnp.where(scale > 0, scale, 1.0)
    q = jnp.round(xb / safe[..., None]).astype(jnp.int8)
    return q.reshape(x.shape[:-1] + (nb * be,)), scale


def dequantize_blocks(q, scale, out_dim: Optional[int] = None,
                      dtype=None):
    """Inverse of `quantize_blocks`: q [..., nb*be] int8 with scale
    [..., nb] -> float [..., out_dim or nb*be]. The block width is
    derived from the operand shapes, so gathered payloads (block
    structure preserved along any non-last axis) dequantize with the
    same call."""
    nb = int(scale.shape[-1])
    be = int(q.shape[-1]) // nb
    xb = q.astype(jnp.float32).reshape(scale.shape + (be,))
    x = (xb * scale[..., None]).reshape(q.shape)
    if out_dim is not None and out_dim != x.shape[-1]:
        x = x[..., :out_dim]
    return x.astype(dtype) if dtype is not None else x


def _pack_scales(q, s):
    """ONE wire buffer per hop: bitcast the f32 sidecar to int8 (4
    bytes per scale, a free relayout) and concatenate it onto the
    payload's last axis — q [..., nb*be] + s [..., nb] -> packed
    [..., nb*be + 4*nb] int8. The collective then ships a single
    tensor, so the quantized hop's launch count matches the plain
    op's (the ROADMAP launch-bound-decode note)."""
    sb = jax.lax.bitcast_convert_type(s, jnp.int8)   # [..., nb, 4]
    return jnp.concatenate(
        [q, sb.reshape(s.shape[:-1] + (4 * s.shape[-1],))], axis=-1)


def _unpack_scales(packed, nb: int):
    """Inverse of `_pack_scales` after the collective: split the
    trailing 4*nb sidecar bytes off the last axis and bitcast them
    back to the f32 [..., nb] scale."""
    split = packed.shape[-1] - 4 * nb
    q, sb = packed[..., :split], packed[..., split:]
    s = jax.lax.bitcast_convert_type(
        sb.reshape(packed.shape[:-1] + (nb, 4)), jnp.float32)
    return q, s


def quantized_all_gather(x, axis_name: str, *, axis: int = 0,
                         tiled: bool = True, block: int = QCOLL_BLOCK):
    """`lax.all_gather` shipping an int8 payload with the f32 scale
    sidecar packed in: quantize locally (blocks along the last dim),
    gather ONE int8 buffer along `axis`, split + dequantize locally at
    x.dtype. One rounding per element, one collective per hop.
    Gathering along the block axis itself (the last dim) would
    interleave shards' blocks, so that case — like non-float or empty
    payloads — falls back to the plain collective with a warning."""
    nd = getattr(x, "ndim", 0)
    if not _quantizable(x) or axis % max(nd, 1) == nd - 1:
        warnings.warn(f"quantized_all_gather: {QCOLL_FALLBACK_MSG}",
                      stacklevel=2)
        return jax.lax.all_gather(x, axis_name, axis=axis, tiled=tiled)
    q, s = quantize_blocks(x, block)
    pg = jax.lax.all_gather(_pack_scales(q, s), axis_name, axis=axis,
                            tiled=tiled)
    qg, sg = _unpack_scales(pg, int(s.shape[-1]))
    return dequantize_blocks(qg, sg, out_dim=int(x.shape[-1]),
                             dtype=x.dtype)


def quantized_psum(x, axis_name: str, *, block: int = QCOLL_BLOCK):
    """`lax.psum` as a two-hop quantized exchange (EQuARX):

    1. each chip flattens its addend to f32, splits it into n
       per-destination chunks, quantizes each chunk and `all_to_all`s
       ONE int8 buffer per chunk (sidecar packed in) — the
       reduce-scatter hop;
    2. every chip dequantizes the n received chunks and ACCUMULATES in
       f32 — one rounding per contribution, exact summation, so the
       error does not grow with world size;
    3. the reduced shard re-quantizes and all-gathers its packed
       buffer, dequantizing back to x's shape and dtype.

    Two roundings per element total, two collectives total (exactly
    the plain-psum ring's hop count). Zero addends stay exactly zero;
    non-finite addends poison their block visibly (see module doc).
    Non-float payloads fall back to the plain psum with a warning."""
    if not _quantizable(x):
        warnings.warn(f"quantized_psum: {QCOLL_FALLBACK_MSG}",
                      stacklevel=2)
        return jax.lax.psum(x, axis_name)
    n = jax.lax.psum(1, axis_name)  # static: the axis size
    if n == 1:
        return x
    shape, dtype = x.shape, x.dtype
    flat = x.astype(jnp.float32).reshape(-1)
    chunk = -(-flat.size // (n * block)) * block
    padded = jnp.pad(flat, (0, n * chunk - flat.size))
    parts = padded.reshape(n, chunk)
    q, s = quantize_blocks(parts, block)
    px = jax.lax.all_to_all(_pack_scales(q, s), axis_name,
                            split_axis=0, concat_axis=0)
    qx, sx = _unpack_scales(px, int(s.shape[-1]))
    red = jnp.sum(dequantize_blocks(qx, sx), axis=0)        # f32 [chunk]
    q2, s2 = quantize_blocks(red, block)
    pg = jax.lax.all_gather(_pack_scales(q2, s2), axis_name, axis=0,
                            tiled=False)
    qg, sg = _unpack_scales(pg, int(s2.shape[-1]))
    out = dequantize_blocks(qg, sg).reshape(-1)[:flat.size]
    return out.reshape(shape).astype(dtype)


def quantized_psum_prequant(q, scale, axis_name: str, *, shape, dtype,
                            block: int = QCOLL_BLOCK):
    """`quantized_psum` for a payload the PRODUCER already quantized —
    the decode megakernel's in-kernel o-proj epilogue (ISSUE 20
    satellite): hop 1's quantization happened inside the kernel, so
    the f32 partial never round-trips HBM before the wire.

    `q` int8 with `scale` f32 must be the `quantize_blocks` layout of
    the row-major f32 partial (q.size == prod(shape), one scale per
    `block` consecutive flat elements — a [b, H] partial with
    H % block == 0 satisfies this per row). Requires
    q.size % (n * block) == 0 so the per-destination chunks split on
    block boundaries with no padding — the caller gates (the serving
    TP seam checks `(b * H) % (mp * 128) == 0`). Hops 2 and 3 are
    `quantized_psum`'s verbatim, so the result is BIT-IDENTICAL to
    `quantized_psum(partial_f32)` of the same partial. At axis size 1
    there is no wire: the payload just dequantizes (the caller should
    not pre-quantize in that regime — `quantized_psum` returns the f32
    partial untouched there)."""
    n = jax.lax.psum(1, axis_name)  # static: the axis size
    size = int(q.size)
    if n == 1:
        return dequantize_blocks(
            q.reshape(1, size),
            scale.astype(jnp.float32).reshape(1, -1)
        ).reshape(shape).astype(dtype)
    if size % (n * block):
        raise ValueError(
            f"quantized_psum_prequant: payload size {size} does not "
            f"split into {n} destinations of whole {block}-blocks — "
            "the caller must gate on (size %% (n * block) == 0)")
    chunk = size // n
    qp = q.reshape(n, chunk)
    sp = scale.astype(jnp.float32).reshape(n, chunk // block)
    px = jax.lax.all_to_all(_pack_scales(qp, sp), axis_name,
                            split_axis=0, concat_axis=0)
    qx, sx = _unpack_scales(px, chunk // block)
    red = jnp.sum(dequantize_blocks(qx, sx), axis=0)        # f32 [chunk]
    q2, s2 = quantize_blocks(red, block)
    pg = jax.lax.all_gather(_pack_scales(q2, s2), axis_name, axis=0,
                            tiled=False)
    qg, sg = _unpack_scales(pg, int(s2.shape[-1]))
    out = dequantize_blocks(qg, sg).reshape(-1)[:size]
    return out.reshape(shape).astype(dtype)


def quantized_reduce_scatter(x, axis_name: str, *,
                             block: int = QCOLL_BLOCK):
    """`lax.psum_scatter(..., scatter_dimension=0, tiled=True)` with an
    int8 wire: x [N, ...] (N divisible by the axis size) -> this chip's
    summed shard [N/n, ...] — the first hop of `quantized_psum` alone,
    for callers that keep working on the reduced shard (ZeRO-style
    grad sharding). Accumulation is local f32 over once-rounded int8
    contributions."""
    if not _quantizable(x):
        warnings.warn(f"quantized_reduce_scatter: {QCOLL_FALLBACK_MSG}",
                      stacklevel=2)
        return jax.lax.psum_scatter(x, axis_name, scatter_dimension=0,
                                    tiled=True)
    n = jax.lax.psum(1, axis_name)
    if n == 1:
        return x.astype(x.dtype)
    if x.shape[0] % n:
        raise ValueError(
            f"quantized_reduce_scatter: leading dim {x.shape[0]} does "
            f"not divide the '{axis_name}' axis size {n}")
    parts = x.astype(jnp.float32).reshape((n, x.shape[0] // n)
                                          + x.shape[1:])
    q, s = quantize_blocks(parts, block)
    px = jax.lax.all_to_all(_pack_scales(q, s), axis_name,
                            split_axis=0, concat_axis=0)
    qx, sx = _unpack_scales(px, int(s.shape[-1]))
    red = jnp.sum(dequantize_blocks(qx, sx,
                                    out_dim=int(x.shape[-1])), axis=0)
    return red.astype(x.dtype)


def quantized_psum_tree(tree, axis_name: str, *,
                        block: int = QCOLL_BLOCK):
    """The dp gradient sync: psum a pytree of float leaves (a grads
    dict) through ONE quantized exchange — leaves flatten-concatenate
    into a single f32 vector (so the wire sees one payload + one
    sidecar per hop, not one pair per leaf), and the summed vector
    splits back at each leaf's shape and dtype. Non-float leaves (none
    in a grads tree — guards misuse) ride a plain psum."""
    leaves, treedef = jax.tree.flatten(tree)
    qleaves = [l for l in leaves if _quantizable(l)]
    if not qleaves:
        return jax.lax.psum(tree, axis_name)
    flat = jnp.concatenate(
        [l.astype(jnp.float32).reshape(-1) for l in qleaves])
    red = quantized_psum(flat, axis_name, block=block)
    out, off = [], 0
    for l in leaves:
        if _quantizable(l):
            sz = int(l.size)
            out.append(red[off:off + sz].reshape(l.shape)
                       .astype(l.dtype))
            off += sz
        else:
            out.append(jax.lax.psum(l, axis_name))
    return jax.tree.unflatten(treedef, out)
