"""Placements + ProcessMesh + DistTensor attributes for the semi-auto API.

Reference: paddle/phi/core/distributed/auto_parallel/placement_types.h:36
(Placement/Shard/Replicate/Partial) and process_mesh.h (ProcessMesh);
python surface python/paddle/distributed/auto_parallel/api.py.

TPU-native mapping: a placement list [p_0 .. p_{k-1}] over a k-axis mesh
translates directly to a `jax.sharding.NamedSharding` PartitionSpec: mesh
axis i whose placement is Shard(j) contributes its name to spec dim j.
Partial has no first-class jax.Array representation — we track it as
metadata and materialize (all-reduce) on read, same as the reference's
reshard p→r rule (p_to_r_reshard_function.cc).
"""
from __future__ import annotations

from typing import List, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from . import mesh as mesh_mod


class Placement:
    def is_shard(self, dim: Optional[int] = None) -> bool:
        return False

    def is_replicated(self) -> bool:
        return False

    def is_partial(self) -> bool:
        return False


class Replicate(Placement):
    def is_replicated(self) -> bool:
        return True

    def __repr__(self):
        return "Replicate()"

    def __eq__(self, o):
        return isinstance(o, Replicate)

    def __hash__(self):
        return hash("r")


class Shard(Placement):
    def __init__(self, dim: int):
        self.dim = dim

    def get_dim(self) -> int:
        return self.dim

    def is_shard(self, dim: Optional[int] = None) -> bool:
        return dim is None or dim == self.dim

    def __repr__(self):
        return f"Shard(dim={self.dim})"

    def __eq__(self, o):
        return isinstance(o, Shard) and o.dim == self.dim

    def __hash__(self):
        return hash(("s", self.dim))


class Partial(Placement):
    def __init__(self, reduce_type: str = "sum"):
        self.reduce_type = reduce_type

    def is_partial(self) -> bool:
        return True

    def __repr__(self):
        return f"Partial({self.reduce_type})"

    def __eq__(self, o):
        return isinstance(o, Partial) and o.reduce_type == self.reduce_type

    def __hash__(self):
        return hash(("p", self.reduce_type))


class ProcessMesh:
    """Reference: ProcessMesh (process_mesh.h; python auto_parallel
    process_mesh.py). Wraps (or builds) a jax Mesh over the same device ids."""

    def __init__(self, mesh=None, dim_names: Optional[Sequence[str]] = None,
                 shape: Optional[Sequence[int]] = None):
        if isinstance(mesh, Mesh):
            self._jax_mesh = mesh
            self._shape = list(mesh.devices.shape)
            self._dim_names = list(mesh.axis_names)
            return
        if mesh is None and shape is not None:
            mesh = np.arange(int(np.prod(shape))).reshape(shape)
        arr = np.asarray(mesh)
        self._shape = list(arr.shape)
        self._dim_names = list(dim_names) if dim_names else [
            f"d{i}" for i in range(arr.ndim)]
        devs = jax.devices()
        dev_arr = np.empty(arr.shape, dtype=object)
        for idx, pid in np.ndenumerate(arr):
            dev_arr[idx] = devs[int(pid) % len(devs)]
        self._jax_mesh = Mesh(dev_arr, tuple(self._dim_names))

    @property
    def shape(self) -> List[int]:
        return list(self._shape)

    @property
    def dim_names(self) -> List[str]:
        return list(self._dim_names)

    @property
    def process_ids(self) -> List[int]:
        return list(range(int(np.prod(self._shape))))

    @property
    def ndim(self) -> int:
        return len(self._shape)

    @property
    def jax_mesh(self) -> Mesh:
        return self._jax_mesh

    def get_dim_size(self, name: str) -> int:
        return self._shape[self._dim_names.index(name)]

    def __eq__(self, o):
        return (isinstance(o, ProcessMesh) and o._shape == self._shape
                and o._dim_names == self._dim_names)

    def __repr__(self):
        return f"ProcessMesh(shape={self._shape}, dim_names={self._dim_names})"


def placements_to_spec(placements: Sequence[Placement], mesh: Mesh,
                       ndim: int) -> PartitionSpec:
    """[p_axis0, p_axis1, ...] -> PartitionSpec over tensor dims.

    Reference analog: TensorDistAttr dims_mapping (dist_attr.h) — here
    inverted into jax's dim-major PartitionSpec."""
    per_dim: List[List[str]] = [[] for _ in range(ndim)]
    for axis_idx, p in enumerate(placements):
        if isinstance(p, Shard):
            per_dim[p.dim].append(mesh.axis_names[axis_idx])
    return PartitionSpec(*[
        (tuple(names) if len(names) > 1 else names[0]) if names else None
        for names in per_dim
    ])


def spec_to_placements(spec: PartitionSpec, mesh: Mesh) -> List[Placement]:
    """Inverse of placements_to_spec (best-effort; Partial not expressible)."""
    placements: List[Placement] = [Replicate() for _ in mesh.axis_names]
    for dim, entry in enumerate(tuple(spec)):
        if entry is None:
            continue
        names = entry if isinstance(entry, tuple) else (entry,)
        for n in names:
            placements[mesh.axis_names.index(n)] = Shard(dim)
    return placements


def named_sharding(mesh, placements: Sequence[Placement], ndim: int) -> NamedSharding:
    jmesh = mesh.jax_mesh if isinstance(mesh, ProcessMesh) else (
        mesh or mesh_mod.get_global_mesh())
    return NamedSharding(jmesh, placements_to_spec(placements, jmesh, ndim))
