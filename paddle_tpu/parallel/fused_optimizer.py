"""Functional bridge from eager Optimizers to the jitted train step.

Reference analog: the static auto-parallel Engine building an optimizer into
the compiled program (python/paddle/distributed/auto_parallel/static/engine.py:69,
python/paddle/optimizer/optimizer.py:125 _apply_optimize). TPU-native form:
every eager optimizer already defines a pure per-array update rule
(`_update_rule_arr`), so a FusedOptimizer lifts one Optimizer instance into

    init_state(params)                    -> state pytree
    update(params, grads, state, lr)      -> (params', state')

usable inside a single jitted, buffer-donating SPMD step. Per-group weight
decay, L1Decay, apply_decay_param_fun / exclude_from_weight_decay_fn, grad
clip objects, and multi_precision master weights all carry over because the
same host-side metadata that drives Optimizer.step() is resolved statically
at trace time.
"""
from __future__ import annotations

from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ..nn.layer.layers import Layer
from ..optimizer.lr import LRScheduler
from ..optimizer.optimizer import Optimizer


class _ParamProxy:
    """Just enough of a Parameter for _create_accumulators / _apply_decay."""

    __slots__ = ("_array", "name", "stop_gradient")

    def __init__(self, array, name):
        self._array = array
        self.name = name
        self.stop_gradient = False


def _sharding_of(arr) -> Optional[NamedSharding]:
    s = getattr(arr, "sharding", None)
    return s if isinstance(s, NamedSharding) else None


def _inherit_sharding(state_arr, param_arr):
    """Lay a state array out like its parameter (ZeRO stage 1/2: states
    follow the param's TP/FSDP spec). Shape-mismatched leaves (e.g. ASGD's
    history stack) stay wherever they were created."""
    s = _sharding_of(param_arr)
    if s is None or getattr(state_arr, "shape", None) != param_arr.shape:
        return state_arr
    return jax.device_put(state_arr, s)


class FusedOptimizer:
    """Lift `optimizer` (built over `model`'s parameters) into pure fns."""

    def __init__(self, optimizer: Optimizer, model: Layer):
        if not hasattr(type(optimizer), "_update_rule_arr") or \
                type(optimizer)._update_rule_arr is Optimizer._update_rule_arr:
            raise NotImplementedError(
                f"{type(optimizer).__name__} has no pure update rule and "
                "cannot run inside the fused train step (use eager "
                "loss.backward() + optimizer.step())")
        self._opt = optimizer
        named = dict(model.named_parameters())
        by_id = {id(p): n for n, p in named.items()}
        self._proxies: Dict[str, _ParamProxy] = {}
        self._params_by_name: Dict[str, Any] = {}
        self._wd: Dict[str, float] = {}
        self._l1: Dict[str, float] = {}

        from ..regularizer import L1Decay

        for group in optimizer._param_groups:
            raw = group.get("weight_decay", optimizer._weight_decay)
            is_l1 = isinstance(raw, L1Decay)
            wd = 0.0 if is_l1 else optimizer._weight_decay_value(group)
            l1 = float(raw) if is_l1 else 0.0
            for p in group["params"]:
                name = by_id.get(id(p))
                if name is None or p.stop_gradient:
                    continue
                # Parameters carry reference-style auto names from
                # creation (layers.py create_parameter), so name-based
                # decay filters bind identically here and in eager step();
                # the structured path remains the fallback for hand-built
                # Parameters
                decay = optimizer._apply_decay(
                    p if p.name else _ParamProxy(p._array, name))
                self._wd[name] = wd if decay else 0.0
                self._l1[name] = l1 if decay else 0.0
                self._proxies[name] = _ParamProxy(p._array, p.name)
                self._params_by_name[name] = p
        # raw_state entries NOT in the optimizer (frozen params, buffers)
        # pass through the update untouched
        self.trainable = frozenset(self._proxies)
        # checkpointing bridge: optimizer.state_dict() must see the fused
        # accumulators; sync lazily (export_to blocks on device values)
        self.latest_state = None
        orig_state_dict = optimizer.state_dict

        def synced_state_dict():
            if self.latest_state is not None:
                self.export_to(self.latest_state)
            return orig_state_dict()

        optimizer.state_dict = synced_state_dict

    # ------------------------------------------------------------------
    def init_state(self, params: Dict[str, jax.Array]):
        acc = {}
        for name in self.trainable:
            proxy = self._proxies[name]
            proxy._array = params[name]  # current (possibly resharded) value
            # resume: accumulators already loaded via set_state_dict win
            existing = self._opt._accumulators.get(
                id(self._params_by_name[name]))
            st = dict(existing) if existing else \
                self._opt._create_accumulators(proxy)
            acc[name] = {k: _inherit_sharding(v, params[name])
                         for k, v in st.items()}
        return {"step": jnp.asarray(self._opt._global_step, jnp.int32),
                "acc": acc}

    def update(self, params, grads, state, lr):
        """Pure: one optimizer step over the whole tree. `lr` is a traced
        scalar so LR schedules tick without recompilation."""
        opt = self._opt
        step = state["step"] + 1
        stepf = step.astype(jnp.float32)
        names = sorted(self.trainable)
        gs = [grads[n] for n in names]
        if opt._grad_clip is not None:
            gs = opt._grad_clip.apply(gs)
        new_params = dict(params)
        new_acc = {}
        for n, g in zip(names, gs):
            l1 = self._l1.get(n, 0.0)
            if l1:
                g = g + l1 * jnp.sign(params[n].astype(g.dtype))
            new_p, new_st = opt._update_rule_arr(
                params[n], g, state["acc"][n], lr, self._wd.get(n, 0.0),
                stepf)
            new_params[n] = new_p
            new_acc[n] = new_st
        return new_params, {"step": step, "acc": new_acc}

    # ------------------------------------------------------------------
    def host_lr(self) -> float:
        return self._opt.get_lr()

    def host_tick(self):
        """Advance host-side bookkeeping after a fused step: the global step
        counter and the LR scheduler (reference: Engine calls
        optimizer._learning_rate.step() once per iteration)."""
        self._opt._global_step += 1
        sched = self._opt._learning_rate
        if isinstance(sched, LRScheduler):
            sched.step()

    def export_to(self, state) -> None:
        """Write fused accumulator state back into the eager Optimizer view
        so optimizer.state_dict()/checkpointing sees the trained values
        (params themselves are synced by Layer.load_raw_state)."""
        self._opt._global_step = int(state["step"])
        for name, p in self._params_by_name.items():
            if name in state["acc"]:
                self._opt._accumulators[id(p)] = dict(state["acc"][name])
