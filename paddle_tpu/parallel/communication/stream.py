"""paddle.distributed.communication.stream (reference:
distributed/communication/stream/__init__.py). Streams are XLA's concern on
TPU; the ops are the synchronous implementations."""
from ..collective import (  # noqa: F401
    all_gather,
    all_reduce,
    all_to_all,
    all_to_all_single,
    broadcast,
    gather,
    recv,
    reduce,
    reduce_scatter,
    scatter,
    send,
)

__all__ = [
    "all_gather", "all_reduce", "all_to_all", "all_to_all_single",
    "broadcast", "gather", "recv", "reduce", "reduce_scatter", "scatter",
    "send",
]
