"""paddle.distributed.communication (reference:
distributed/communication/__init__.py) — collective op namespace."""
from ..collective import (  # noqa: F401
    Group,
    ReduceOp,
    all_gather,
    all_gather_object,
    all_reduce,
    all_to_all,
    all_to_all_single,
    barrier,
    broadcast,
    gather,
    irecv,
    isend,
    new_group,
    recv,
    reduce,
    reduce_scatter,
    scatter,
    send,
)
from ..compat import (  # noqa: F401
    alltoall,
    alltoall_single,
    broadcast_object_list,
    scatter_object_list,
)
from . import stream  # noqa: F401


class P2POp:
    """A deferred point-to-point op for batch_isend_irecv (reference:
    communication/batch_isend_irecv.py P2POp)."""

    def __init__(self, op, tensor, peer, group=None):
        self.op = op
        self.tensor = tensor
        self.peer = peer
        self.group = group


def batch_isend_irecv(p2p_op_list):
    """Run a batch of P2POps; returns their tasks (reference:
    communication/batch_isend_irecv.py)."""
    return [op.op(op.tensor, op.peer, group=op.group) for op in p2p_op_list]
