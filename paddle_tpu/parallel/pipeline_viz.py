"""Pipeline schedule timeline visualizer.

Reference: fleet/meta_parallel/pp_utils/profiler_helper.py (merges
per-rank chrome-trace records of the pipeline schedule into one
`pipeline_profile.json` for chrome://tracing). The TPU-native pipelines
are ONE program whose schedule is a closed-form function of
(tick, rank) — see parallel/pipeline_spmd.py — so the timeline can be
rendered exactly from the schedule model, no log collection needed:

    >>> tl = pipeline_timeline("1F1B", n_stages=4, n_micro=8)
    >>> print(render_timeline(tl))
    rank 0 | F0 F1 F2 F3 F4 F5 F6 F7 ..... B0 ...
    ...
    >>> save_chrome_trace(tl, "pipeline_profile.json")

Every schedule the repo implements is covered: FThenB, 1F1B, Eager1F1B,
VPP, ZBH1. The bubble accounting (`timeline_stats`) is asserted against
the analytic model in tests/test_pipeline.py.
"""
from __future__ import annotations

from typing import Dict, List, Optional

__all__ = ["pipeline_timeline", "render_timeline", "timeline_stats",
           "save_chrome_trace"]

SCHEDULES = ("FThenB", "1F1B", "Eager1F1B", "VPP", "ZBH1")


def pipeline_timeline(schedule: str, n_stages: int, n_micro: int,
                      vpp_degree: int = 1) -> Dict:
    """Per-rank, per-tick slot occupancy of a pipeline schedule.

    Returns {"schedule", "n_stages", "n_micro", "vpp_degree", "ranks"}
    where ranks[r] is a list of per-tick dicts with keys:
      "F": microbatch id forwarded this tick (None = forward slot idle)
      "B": microbatch id backwarded this tick (None = idle / n/a)
      "W": True when a deferred weight-grad pass runs (ZBH1 post-scan)
      "chunk": VPP only — the virtual chunk index active this tick

    The tick formulas are exactly the ones the scan bodies in
    parallel/pipeline_spmd.py evaluate; a mismatch between this module
    and the runtime would be a bug in one of them.
    """
    S, M, V = int(n_stages), int(n_micro), int(vpp_degree)
    if schedule not in SCHEDULES:
        raise ValueError(f"unknown schedule {schedule!r}; one of {SCHEDULES}")
    ranks: List[List[dict]] = []

    if schedule == "FThenB":
        # pipeline_forward + autodiff-of-scan: T forward ticks, then the
        # transposed scan replays them in reverse for the backward
        T = M + S - 1
        for r in range(S):
            row = []
            for t in range(T):
                i = t - r
                row.append({"F": i if 0 <= i < M else None, "B": None})
            for t in range(T - 1, -1, -1):
                i = t - r
                row.append({"F": None, "B": i if 0 <= i < M else None})
            ranks.append(row)
    elif schedule in ("1F1B", "Eager1F1B", "ZBH1"):
        eager = schedule == "Eager1F1B"
        T = M + (4 * S - 4 if eager else 2 * S - 1)
        for r in range(S):
            f_off = 2 * r if eager else r
            b_off = (4 * S - 4 - 2 * r) if eager else (2 * S - 1 - r)
            row = []
            for t in range(T):
                i_f, i_b = t - f_off, t - b_off
                row.append({"F": i_f if 0 <= i_f < M else None,
                            "B": i_b if 0 <= i_b < M else None})
            if schedule == "ZBH1":
                # one batched post-scan weight-grad pass (all microbatches
                # in a single vmapped vjp — pipeline_zb1f1b docstring)
                row.append({"F": None, "B": None, "W": True})
            ranks.append(row)
    else:  # VPP
        SV = S * V
        T = M * V + S - 1
        for r in range(S):
            row = []
            for t in range(T):
                u = t - r
                if 0 <= u < M * V:
                    g, w = u // SV, u % SV
                    row.append({"F": g * S + (w % S), "B": None,
                                "chunk": w // S})
                else:
                    row.append({"F": None, "B": None, "chunk": None})
            # autodiff replays the forward scan reversed
            for t in range(T - 1, -1, -1):
                u = t - r
                if 0 <= u < M * V:
                    g, w = u // SV, u % SV
                    row.append({"F": None, "B": g * S + (w % S),
                                "chunk": w // S})
                else:
                    row.append({"F": None, "B": None, "chunk": None})
            ranks.append(row)
    return {"schedule": schedule, "n_stages": S, "n_micro": M,
            "vpp_degree": V, "ranks": ranks}


def _cell(slot: dict) -> str:
    if slot.get("W"):
        return " W "
    f, b = slot.get("F"), slot.get("B")
    if f is None and b is None:
        return " · "
    ftxt = f"F{f}" if f is not None else ".."
    btxt = f"B{b}" if b is not None else ".."
    return f"{ftxt}/{btxt}"


def render_timeline(tl: Dict) -> str:
    """ASCII rendering: one row per pp rank, one column per tick. `·` is
    a full bubble; `F3/..` a tick whose backward slot idles."""
    head = (f"{tl['schedule']}  S={tl['n_stages']} M={tl['n_micro']}"
            + (f" V={tl['vpp_degree']}" if tl["schedule"] == "VPP" else ""))
    lines = [head]
    width = max(len(_cell(s)) for row in tl["ranks"] for s in row)
    for r, row in enumerate(tl["ranks"]):
        cells = " ".join(f"{_cell(s):^{width}}" for s in row)
        lines.append(f"rank {r} | {cells}")
    return "\n".join(lines)


def timeline_stats(tl: Dict) -> Dict:
    """Slot accounting per rank: fwd/bwd slots filled, bubbles, peak
    in-flight microbatches (forwarded but not yet backwarded — the
    activation-memory driver the schedules trade against)."""
    out = {"per_rank": [], "total_ticks": len(tl["ranks"][0])}
    for row in tl["ranks"]:
        f_n = sum(1 for s in row if s.get("F") is not None)
        b_n = sum(1 for s in row if s.get("B") is not None)
        w_n = sum(1 for s in row if s.get("W"))
        bubbles = sum(1 for s in row
                      if s.get("F") is None and s.get("B") is None
                      and not s.get("W"))
        in_flight = peak = 0
        for s in row:
            if s.get("F") is not None:
                in_flight += 1
            # peak BETWEEN the slots: the tick's forward input is alive
            # while its backward runs (the buffer must hold both)
            peak = max(peak, in_flight)
            if s.get("B") is not None:
                in_flight -= 1
        out["per_rank"].append({"F": f_n, "B": b_n, "W": w_n,
                                "bubbles": bubbles,
                                "peak_in_flight": peak})
    return out


def save_chrome_trace(tl: Dict, path: str, tick_us: float = 1000.0,
                      stats: Optional[Dict] = None) -> None:
    """Write the timeline as chrome://tracing JSON, one track per pp rank
    — the artifact the reference's profiler_helper.py assembles from
    per-rank log files, produced here from the schedule model. Loadable
    in chrome://tracing or Perfetto alongside the profiler's host trace
    (profiler.Profiler.export)."""
    events = []
    for r, row in enumerate(tl["ranks"]):
        for t, slot in enumerate(row):
            ts = t * tick_us
            for kind in ("F", "B"):
                mb = slot.get(kind)
                if mb is not None:
                    events.append({
                        "name": f"{kind}{mb}", "ph": "X", "ts": ts,
                        "dur": tick_us, "pid": 0, "tid": r,
                        "args": {"microbatch": mb, "slot": kind,
                                 **({"chunk": slot["chunk"]}
                                    if slot.get("chunk") is not None
                                    else {})}})
            if slot.get("W"):
                events.append({"name": "W(batched)", "ph": "X", "ts": ts,
                               "dur": tick_us, "pid": 0, "tid": r,
                               "args": {"slot": "W"}})
    meta = [{"name": "process_name", "ph": "M", "pid": 0,
             "args": {"name": f"pipeline {tl['schedule']}"}}]
    meta += [{"name": "thread_name", "ph": "M", "pid": 0, "tid": r,
              "args": {"name": f"pp rank {r}"}}
             for r in range(len(tl["ranks"]))]
    # one JSON-format implementation for every chrome-trace artifact
    # (ISSUE 8): emission goes through observability.trace; this
    # module keeps only the schedule->events assembly
    from ..observability.trace import write_chrome_trace

    write_chrome_trace(meta + events, path,
                       metadata={"stats": stats or timeline_stats(tl)})
