"""paddle.distributed.metric (reference: distributed/metric/metrics.py —
init_metric/print_auc for the PS metric pipeline). The TPU-native metric
path is paddle.metric + fleet.metrics; these entry points adapt the names.
"""
from ..fleet.metrics import auc as _auc

__all__ = ["init_metric", "print_auc"]

_METRICS = {}


def init_metric(metric_ptr=None, metric_yaml_path=None, **kwargs):
    """Register metric config (the PS runtime that consumed this is a
    declared non-goal; the registry keeps the API contract)."""
    _METRICS["config"] = dict(metric_ptr=metric_ptr, yaml=metric_yaml_path, **kwargs)


def print_auc(stat_pos, stat_neg, name="auc"):
    value = _auc(stat_pos, stat_neg)
    print(f"{name}: {value}")
    return value
