"""Tensor-parallel (Megatron-style) layers over the `mp` mesh axis.

Reference: python/paddle/distributed/fleet/layers/mpu/mp_layers.py
(VocabParallelEmbedding:47, ColumnParallelLinear:334, RowParallelLinear:541,
ParallelCrossEntropy:742) and mp_ops.py (_c_identity/_c_concat/_c_split/
_mp_allreduce autograd-aware collectives).

TPU-native: instead of per-rank local weight shards + explicit NCCL calls,
each layer holds the GLOBAL weight annotated with a NamedSharding that
splits it over the `mp` axis; XLA's SPMD partitioner inserts the identical
collectives (all-gather for column-parallel output gather, reduce for
row-parallel partial sums) over ICI. The math and the communication pattern
match the reference exactly — only who inserts the collective differs.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor, dispatch
from ..nn import functional as F
from ..nn.layer.layers import Layer
from .. import ops
from ..nn.initializer import XavierNormal, XavierUniform
from . import mesh as mesh_mod
from .api import shard_constraint, shard_tensor
from .placement import Replicate, Shard

__all__ = [
    "VocabParallelEmbedding", "ColumnParallelLinear", "RowParallelLinear",
    "ParallelCrossEntropy", "get_rng_state_tracker", "RNGStatesTracker",
]


def _mp_axis(mesh=None) -> Optional[str]:
    m = mesh or mesh_mod.get_global_mesh()
    if m is None:
        return None
    return "mp" if "mp" in m.axis_names else None


class RNGStatesTracker:
    """Per-group RNG offsetting for dropout inside/outside TP regions
    (reference: mpu/random.py:34 RNGStatesTracker). On TPU, per-shard
    randomness is derived by folding the mp axis index into the key, so no
    state juggling is needed — kept for API parity."""

    def __init__(self):
        self.states_ = {}

    def add(self, name, seed):
        self.states_[name] = seed

    def reset(self):
        self.states_ = {}

    def get_states_tracker(self):
        return dict(self.states_)

    def set_states_tracker(self, states):
        self.states_ = dict(states)

    def rng_state(self, name="global_seed"):
        import contextlib

        return contextlib.nullcontext()


_RNG_STATE_TRACKER = RNGStatesTracker()


def get_rng_state_tracker():
    return _RNG_STATE_TRACKER


class ColumnParallelLinear(Layer):
    """Y = X @ W, W [in, out] sharded on out (column) over `mp`.

    Reference: mp_layers.py:334 — per-rank W shard [in, out/mp], optional
    gather_output via c_concat. Here W carries Shard(1) over mp; when
    gather_output the output constraint is Replicate (XLA all-gathers),
    otherwise the activation stays Shard(-1)."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, gather_output=True, fuse_matmul_bias=False,
                 mp_group=None, name=None):
        super().__init__()
        self.in_features = in_features
        self.out_features = out_features
        self.gather_output = gather_output
        self._name = name
        init = XavierNormal()
        self.weight = self.create_parameter(
            [in_features, out_features], attr=weight_attr, default_initializer=init)
        self.bias = self.create_parameter(
            [out_features], is_bias=True) if has_bias else None
        axis = _mp_axis()
        if axis is not None:
            mesh = mesh_mod.get_global_mesh()
            w_pl = [Shard(1) if a == axis else Replicate() for a in mesh.axis_names]
            self.weight._array = shard_tensor(self.weight, mesh, w_pl)._array
            if self.bias is not None:
                b_pl = [Shard(0) if a == axis else Replicate() for a in mesh.axis_names]
                self.bias._array = shard_tensor(self.bias, mesh, b_pl)._array

    def forward(self, x):
        out = F.linear(x, self.weight, self.bias)
        axis = _mp_axis()
        if axis is None:
            return out
        mesh = mesh_mod.get_global_mesh()
        if self.gather_output:
            pl = [Replicate()] * len(mesh.axis_names)
        else:
            pl = [Shard(out.ndim - 1) if a == axis else Replicate()
                  for a in mesh.axis_names]
        return shard_constraint(out, pl, mesh)


class RowParallelLinear(Layer):
    """Y = X @ W, W [in, out] sharded on in (row) over `mp`; partial outputs
    are summed (reference: mp_layers.py:541 — mp_allreduce after the local
    matmul; input optionally split via c_split when not parallel yet)."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, input_is_parallel=False, fuse_matmul_bias=False,
                 mp_group=None, name=None):
        super().__init__()
        self.in_features = in_features
        self.out_features = out_features
        self.input_is_parallel = input_is_parallel
        init = XavierNormal()
        self.weight = self.create_parameter(
            [in_features, out_features], attr=weight_attr, default_initializer=init)
        self.bias = self.create_parameter(
            [out_features], is_bias=True) if has_bias else None
        axis = _mp_axis()
        if axis is not None:
            mesh = mesh_mod.get_global_mesh()
            w_pl = [Shard(0) if a == axis else Replicate() for a in mesh.axis_names]
            self.weight._array = shard_tensor(self.weight, mesh, w_pl)._array

    def forward(self, x):
        axis = _mp_axis()
        if axis is not None and self.input_is_parallel:
            mesh = mesh_mod.get_global_mesh()
            pl = [Shard(x.ndim - 1) if a == axis else Replicate()
                  for a in mesh.axis_names]
            x = shard_constraint(x, pl, mesh)
        out = F.linear(x, self.weight, self.bias)
        if axis is not None:
            mesh = mesh_mod.get_global_mesh()
            out = shard_constraint(out, [Replicate()] * len(mesh.axis_names), mesh)
        return out


class VocabParallelEmbedding(Layer):
    """Embedding with the vocab dim sharded over `mp` (reference:
    mp_layers.py:47 — per-rank vocab range + masked lookup + allreduce).
    XLA partitions the gather the same way from Shard(0) on the table."""

    def __init__(self, num_embeddings, embedding_dim, weight_attr=None,
                 mp_group=None, name=None):
        super().__init__()
        self.num_embeddings = num_embeddings
        self.embedding_dim = embedding_dim
        # same default as nn.Embedding so TP and single-device builds
        # initialize from the same distribution
        self.weight = self.create_parameter(
            [num_embeddings, embedding_dim], attr=weight_attr,
            default_initializer=XavierUniform())
        axis = _mp_axis()
        if axis is not None:
            mesh = mesh_mod.get_global_mesh()
            pl = [Shard(0) if a == axis else Replicate() for a in mesh.axis_names]
            self.weight._array = shard_tensor(self.weight, mesh, pl)._array

    def forward(self, x):
        return F.embedding(x, self.weight)


class ParallelCrossEntropy(Layer):
    """Cross-entropy over mp-sharded logits (reference: mp_layers.py:742 —
    c_softmax_with_cross_entropy op computing with only local vocab logits
    + two allreduces). With XLA the same reduction structure falls out of
    the sharded logsumexp."""

    def __init__(self, mp_group=None, name=None, ignore_index=-100):
        super().__init__()
        self.ignore_index = ignore_index

    def forward(self, input, label):
        def impl(logits, lbl):
            lse = jax.scipy.special.logsumexp(logits, axis=-1, keepdims=True)
            logp = logits - lse
            lbl_ = lbl.astype(jnp.int32)
            ignored = lbl_ == self.ignore_index
            safe = jnp.where(ignored, 0, lbl_)  # avoid negative wrap-indexing
            picked = jnp.take_along_axis(logp, safe[..., None], axis=-1)[..., 0]
            loss = jnp.where(ignored, 0.0, -picked)
            return loss[..., None]

        return dispatch("parallel_cross_entropy", impl, (input, label))
