"""paddle.distributed.utils (reference: distributed/utils/__init__.py) —
MoE all-to-all helpers + logging utilities."""
from . import log_utils  # noqa: F401
from . import moe_utils  # noqa: F401
from .moe_utils import global_gather, global_scatter  # noqa: F401

__all__ = ["global_scatter", "global_gather"]
