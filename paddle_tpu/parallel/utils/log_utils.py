"""paddle.distributed.utils.log_utils (reference:
distributed/utils/log_utils.py)."""
import logging


def get_logger(log_level="INFO", name="root"):
    logger = logging.getLogger(name)
    if isinstance(log_level, str):
        log_level = getattr(logging, log_level.upper(), logging.INFO)
    logger.setLevel(log_level)
    if not logger.handlers:
        h = logging.StreamHandler()
        h.setFormatter(logging.Formatter(
            "%(asctime)s %(levelname)s %(name)s: %(message)s"))
        logger.addHandler(h)
    return logger
