"""paddle.distributed.utils.moe_utils — expert-parallel token exchange.

Reference: python/paddle/distributed/utils/moe_utils.py:20 (global_scatter),
:153 (global_gather). The reference implements these as NCCL alltoall with
per-(rank, expert) counts. The TPU-native scalable dispatch lives in
parallel/moe.py (shard_map + lax.all_to_all with capacity layout); these
functions keep the reference's eager count-based contract:

- ``local_count[i]`` tokens from x go to expert ``i % n_expert`` on rank
  ``i // n_expert``;
- ``global_count[i]`` tokens are received from rank ``i // n_expert`` for
  this rank's expert ``i % n_expert``.

Counts are data-dependent (dynamic shapes), so this is a host-driven eager
op by design — inside jit use the capacity-based dispatch instead.
"""
import numpy as np

from ...core.tensor import Tensor, unwrap
from ..env import get_world_size

__all__ = ["global_scatter", "global_gather"]


def _counts(c):
    return np.asarray(unwrap(c)).astype(np.int64).ravel()


def global_scatter(x, local_count, global_count, group=None, use_calc_stream=True):
    """Send token rows to (rank, expert) destinations by count.

    Reference: distributed/utils/moe_utils.py:20.
    """
    xa = np.asarray(unwrap(x))
    lc, gc = _counts(local_count), _counts(global_count)
    world = get_world_size()
    n_expert = len(lc) // max(world, 1)

    if world <= 1:
        # single process: the exchange is an identity repack in expert order
        out = np.concatenate([seg for seg in _split_by_counts(xa, lc)], axis=0) \
            if len(xa) else xa
        return Tensor(out)

    from ..collective import all_to_all

    # pack per-destination-rank buffers: rank r gets this rank's tokens for
    # experts r*n_expert..(r+1)*n_expert-1 (row counts from local_count)
    segs = _split_by_counts(xa, lc)
    feat = xa.shape[1:] if xa.ndim > 1 else ()
    send = []
    for r in range(world):
        parts = [segs[r * n_expert + e] for e in range(n_expert)]
        send.append(Tensor(np.concatenate(parts, axis=0) if parts else
                           np.zeros((0,) + feat, xa.dtype)))
    recv = [None] * world
    all_to_all(recv, send, group=group)
    out = np.concatenate([np.asarray(unwrap(t)) for t in recv], axis=0)
    # received blocks arrive rank-major; reorder rows to expert-major using
    # global_count (gc[i]: tokens from rank i//n_expert for expert i%n_expert)
    per_rank = [gc[r * n_expert:(r + 1) * n_expert] for r in range(world)]
    offsets, cursor = {}, 0
    for r in range(world):
        for e in range(n_expert):
            offsets[(r, e)] = cursor
            cursor += int(per_rank[r][e])
    rows = []
    for e in range(n_expert):
        for r in range(world):
            o = offsets[(r, e)]
            rows.append(out[o:o + int(per_rank[r][e])])
    return Tensor(np.concatenate(rows, axis=0) if rows else out)


def global_gather(x, local_count, global_count, group=None, use_calc_stream=True):
    """Inverse of :func:`global_scatter` — return expert outputs to their
    source ranks. Reference: distributed/utils/moe_utils.py:153.
    """
    xa = np.asarray(unwrap(x))
    lc, gc = _counts(local_count), _counts(global_count)
    world = get_world_size()
    n_expert = len(lc) // max(world, 1)

    if world <= 1:
        return Tensor(xa)

    from ..collective import all_to_all

    # x holds expert-major rows (global_count layout); repack rank-major
    per_rank = [gc[r * n_expert:(r + 1) * n_expert] for r in range(world)]
    feat = xa.shape[1:] if xa.ndim > 1 else ()
    blocks, cursor = {}, 0
    for e in range(n_expert):
        for r in range(world):
            n = int(per_rank[r][e])
            blocks[(r, e)] = xa[cursor:cursor + n]
            cursor += n
    send = []
    for r in range(world):
        parts = [blocks[(r, e)] for e in range(n_expert)]
        send.append(Tensor(np.concatenate(parts, axis=0) if parts else
                           np.zeros((0,) + feat, xa.dtype)))
    recv = [None] * world
    all_to_all(recv, send, group=group)
    out = np.concatenate([np.asarray(unwrap(t)) for t in recv], axis=0)
    return Tensor(out)


def _split_by_counts(x, counts):
    segs, off = [], 0
    for c in counts:
        segs.append(x[off:off + int(c)])
        off += int(c)
    return segs
