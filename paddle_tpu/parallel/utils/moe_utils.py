"""paddle.distributed.utils.moe_utils — expert-parallel token exchange.

Reference: python/paddle/distributed/utils/moe_utils.py:20 (global_scatter),
:153 (global_gather). The reference implements these as NCCL alltoall with
per-(rank, expert) counts. The TPU-native scalable dispatch lives in
parallel/moe.py (shard_map + lax.all_to_all with capacity layout); these
functions keep the reference's eager count-based contract:

- ``local_count[i]`` tokens from x go to expert ``i % n_expert`` on rank
  ``i // n_expert``;
- ``global_count[i]`` tokens are received from rank ``i // n_expert`` for
  this rank's expert ``i % n_expert``.

Counts are data-dependent (dynamic shapes), so this is a host-driven eager
op by design — inside jit use the capacity-based dispatch instead.
"""
import numpy as np

from ...core.tensor import Tensor, unwrap
from ..env import get_world_size

__all__ = ["global_scatter", "global_gather"]


def _counts(c):
    return np.asarray(unwrap(c)).astype(np.int64).ravel()


def _world_and_experts(lc, group, n_expert):
    """Resolve (world, n_expert) for the eager exchange, loudly.

    The exchange runs across *processes*. A count vector sized for more
    ranks than there are processes (the single-process multi-device
    topology) would silently degenerate to an identity repack, so it is
    rejected instead (round-3 advisor finding)."""
    world = get_world_size()
    if group is not None:
        gr = int(getattr(group, "nranks", world))
        if gr != world:
            raise ValueError(
                f"global_scatter/global_gather are eager cross-PROCESS "
                f"exchanges: group implies {gr} ranks but only {world} "
                f"process(es) exist. For single-process multi-device "
                f"expert parallelism use the jit capacity dispatch in "
                f"paddle_tpu.parallel.moe instead.")
    if n_expert is not None:
        if world * n_expert != len(lc):
            raise ValueError(
                f"len(local_count)={len(lc)} != n_expert({n_expert}) * "
                f"world({world}) — the count layout is (rank, expert) "
                f"row-major, one entry per (rank, expert) pair.")
    else:
        if world < 1 or len(lc) % world:
            raise ValueError(
                f"len(local_count)={len(lc)} is not divisible by the "
                f"process world {world}; pass n_expert= explicitly.")
        n_expert = len(lc) // world
    return world, n_expert


def global_scatter(x, local_count, global_count, group=None,
                   use_calc_stream=True, n_expert=None):
    """Send token rows to (rank, expert) destinations by count.

    Reference: distributed/utils/moe_utils.py:20.
    """
    xa = np.asarray(unwrap(x))
    lc, gc = _counts(local_count), _counts(global_count)
    world, n_expert = _world_and_experts(lc, group, n_expert)

    if world <= 1:
        # single process: the exchange is an identity repack in expert order
        out = np.concatenate([seg for seg in _split_by_counts(xa, lc)], axis=0) \
            if len(xa) else xa
        return Tensor(out)

    # multi-process eager exchange: allgather everyone's (x, local_count)
    # and deterministically pick the rows destined for this rank — the
    # debug/eager analog of the reference's NCCL alltoall (inside jit use
    # the capacity-based dispatch in parallel/moe.py instead)
    rank = _my_rank()
    all_x, all_lc = _allgather_rows(xa, lc, world)
    rows = []
    for e in range(n_expert):
        for src in range(world):
            segs = _split_by_counts(all_x[src], all_lc[src])
            rows.append(segs[rank * n_expert + e])
    feat = xa.shape[1:] if xa.ndim > 1 else ()
    out = np.concatenate(rows, axis=0) if rows else \
        np.zeros((0,) + feat, xa.dtype)
    return Tensor(out)


def global_gather(x, local_count, global_count, group=None,
                  use_calc_stream=True, n_expert=None):
    """Inverse of :func:`global_scatter` — return expert outputs to their
    source ranks. Reference: distributed/utils/moe_utils.py:153.
    """
    xa = np.asarray(unwrap(x))
    lc, gc = _counts(local_count), _counts(global_count)
    world, n_expert = _world_and_experts(lc, group, n_expert)

    if world <= 1:
        return Tensor(xa)

    rank = _my_rank()
    # x holds expert-major rows laid out by global_count; allgather every
    # rank's expert outputs + their global_counts, then rebuild this
    # rank's original send order from its local_count
    all_x, all_gc = _allgather_rows(xa, gc, world)
    # index each destination's buffer once: block (src, expert) -> rows
    blocks_by_dst = []
    for dst in range(world):
        off, blocks = 0, {}
        gcd = all_gc[dst]
        for ee in range(n_expert):
            for src in range(world):
                n = int(gcd[src * n_expert + ee])
                blocks[(src, ee)] = all_x[dst][off:off + n]
                off += n
        blocks_by_dst.append(blocks)
    rows = []
    for i in range(len(lc)):  # destination slot order of OUR send
        dst, e = i // n_expert, i % n_expert
        rows.append(blocks_by_dst[dst][(rank, e)])
    feat = xa.shape[1:] if xa.ndim > 1 else ()
    out = np.concatenate(rows, axis=0) if rows else \
        np.zeros((0,) + feat, xa.dtype)
    return Tensor(out)


def _my_rank() -> int:
    import jax

    try:
        return jax.process_index()
    except RuntimeError:
        return 0


def _allgather_rows(xa, counts, world):
    """Host allgather of variable-row buffers: exchange counts (fixed
    shape), pad rows to the global max, gather, unpad."""
    import jax
    from jax.experimental import multihost_utils

    counts = np.asarray(counts, np.int64)
    all_counts = np.asarray(multihost_utils.process_allgather(counts))
    n_rows = np.asarray(
        multihost_utils.process_allgather(np.asarray([xa.shape[0]])))
    max_rows = int(n_rows.max())
    feat = xa.shape[1:] if xa.ndim > 1 else ()
    padded = np.zeros((max_rows,) + feat, xa.dtype)
    padded[:xa.shape[0]] = xa
    gathered = np.asarray(multihost_utils.process_allgather(padded))
    all_x = [gathered[r][:int(n_rows[r][0])] for r in range(world)]
    return all_x, [all_counts[r] for r in range(world)]


def _split_by_counts(x, counts):
    segs, off = [], 0
    for c in counts:
        segs.append(x[off:off + int(c)])
        off += int(c)
    return segs
