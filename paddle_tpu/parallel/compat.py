"""Remaining paddle.distributed __all__ surface (reference:
python/paddle/distributed/__init__.py): object collectives, gloo bootstrap
facades, env/introspection helpers, model-parallel `split`, the
semi-auto-parallel static API (Strategy / to_static / DistModel /
shard_dataloader / shard_scaler / ShardingStage*), and loud refusals for
the parameter-server dataset entries (non-goal, SURVEY §7.4).
"""
from __future__ import annotations

import pickle
from enum import Enum

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor, unwrap
from . import collective as _coll
from . import env as _env
from .mesh import get_global_mesh

__all__ = [
    "alltoall", "alltoall_single", "wait", "scatter_object_list",
    "broadcast_object_list", "gloo_init_parallel_env", "gloo_barrier",
    "gloo_release", "is_initialized", "destroy_process_group",
    "is_available", "get_backend", "ParallelMode", "ReduceType",
    "DistAttr", "split", "shard_dataloader", "shard_scaler",
    "ShardingStage1", "ShardingStage2", "ShardingStage3", "Strategy",
    "to_static", "DistModel", "QueueDataset", "InMemoryDataset",
    "CountFilterEntry", "ShowClickEntry", "ProbabilityEntry",
]

def alltoall(in_tensor_list, out_tensor_list=None, group=None,
             sync_op=True):
    """reference: communication/all_to_all.py alltoall — paddle argument
    order (inputs first); the local collective takes (out, in)."""
    return _coll.all_to_all(out_tensor_list, in_tensor_list, group=group,
                            sync_op=sync_op)


def alltoall_single(in_tensor, out_tensor=None, in_split_sizes=None,
                    out_split_sizes=None, group=None, sync_op=True):
    """reference: communication/all_to_all.py alltoall_single."""
    return _coll.all_to_all_single(out_tensor, in_tensor,
                                   out_split_sizes=out_split_sizes,
                                   in_split_sizes=in_split_sizes,
                                   group=group, sync_op=sync_op)


def wait(tensor, group=None, use_calc_stream=True):
    """reference: communication/wait.py — XLA orders collectives per
    device; block on the value for host-visible sync."""
    jax.block_until_ready(unwrap(tensor))
    return tensor


def _object_to_tensor(obj):
    data = np.frombuffer(pickle.dumps(obj), np.uint8).copy()
    return Tensor(jnp.asarray(data)), len(data)


def _tensor_to_object(t, n):
    return pickle.loads(bytes(np.asarray(unwrap(t))[:n]))


def broadcast_object_list(object_list, src=0, group=None):
    """reference: communication/broadcast.py broadcast_object_list —
    pickle over the byte-tensor broadcast path. Single-controller JAX has
    one python process per host, so within-process this is identity; the
    tensor hop keeps the comm path exercised."""
    for i, obj in enumerate(object_list):
        t, n = _object_to_tensor(obj)
        t = _coll.broadcast(t, src=src, group=group)
        object_list[i] = _tensor_to_object(t, n)
    return object_list


def scatter_object_list(out_object_list, in_object_list=None, src=0,
                        group=None):
    """reference: communication/scatter.py scatter_object_list — rank r
    receives the r-th contiguous chunk; every object is assigned
    (np.array_split semantics)."""
    rank = _env.get_rank() if hasattr(_env, "get_rank") else 0
    world = _env.get_world_size() if hasattr(_env, "get_world_size") else 1
    if in_object_list is None:
        in_object_list = []
    # plain-list chunking (np.array_split would coerce nested sequences
    # into object ndarrays); every object lands on exactly one rank
    n = len(in_object_list)
    w = max(world, 1)
    base, extra = divmod(n, w)
    start = rank * base + min(rank, extra)
    end = start + base + (1 if rank < extra else 0)
    mine = in_object_list[start:end]
    out_object_list[:] = [pickle.loads(pickle.dumps(o)) for o in mine]
    return out_object_list


def gloo_init_parallel_env(rank_id, rank_num, server_endpoint):
    """reference: parallel_with_gloo.py — CPU rendezvous is subsumed by
    jax.distributed; accepted for API parity."""
    return None


def gloo_barrier():
    _coll.barrier()


def gloo_release():
    return None


def is_initialized():
    """reference: collective.py is_initialized."""
    return _env.is_initialized() if hasattr(_env, "is_initialized") \
        else jax.device_count() > 0


def destroy_process_group(group=None):
    """reference: collective.py destroy_process_group — XLA groups are
    compiled into programs; dropping the python handle is the analog."""
    return None


def is_available():
    return True


def get_backend(group=None):
    """reference: collective.py get_backend — the TPU comm backend is XLA
    collectives over ICI/DCN."""
    return "XCCL" if jax.default_backend() == "tpu" else "GLOO"


class ParallelMode:
    """reference: parallel.py ParallelMode."""
    DATA_PARALLEL = 0
    TENSOR_PARALLEL = 1
    PIPELINE_PARALLEL = 2
    SHARDING_PARALLEL = 3


class ReduceType(Enum):
    """reference: auto_parallel/placement_type.py ReduceType."""
    kRedSum = 0
    kRedMax = 1
    kRedMin = 2
    kRedProd = 3
    kRedAvg = 4
    kRedAny = 5
    kRedAll = 6


class DistAttr:
    """reference: auto_parallel/api.py DistAttr(mesh, sharding_specs) —
    the mesh + per-dim sharding spec pair used by shard_tensor."""

    def __init__(self, mesh, sharding_specs):
        self.process_mesh = mesh
        self.sharding_specs = list(sharding_specs)

    def placements(self):
        from .placement import Replicate, Shard

        out = []
        for dim_name in getattr(self.process_mesh, "dim_names",
                                list(getattr(self.process_mesh, "shape",
                                             {}).keys())):
            if dim_name in self.sharding_specs:
                out.append(Shard(self.sharding_specs.index(dim_name)))
            else:
                out.append(Replicate())
        return out


def split(x, size, operation, axis=0, num_partitions=1, gather_out=True,
          weight_attr=None, bias_attr=None, name=None):
    """reference: collective.py split — model-parallel embedding/linear
    over the mp axis, realised by the mpu layer family."""
    from .mpu import ColumnParallelLinear, RowParallelLinear, \
        VocabParallelEmbedding

    if operation == "embedding":
        layer = VocabParallelEmbedding(size[0], size[1])
        return layer(x)
    if operation == "linear":
        if axis == 1:
            layer = ColumnParallelLinear(size[0], size[1],
                                         gather_output=gather_out)
        else:
            layer = RowParallelLinear(size[0], size[1],
                                      input_is_parallel=False)
        return layer(x)
    raise ValueError(f"unknown split operation {operation!r}")


class ShardingStage1:
    """reference: auto_parallel/api.py ShardingStage1 — marker passed to
    shard_optimizer: shard optimizer states over the mesh axis."""

    def __init__(self, axis_name="sharding", mesh=None):
        self.axis_name = axis_name
        self.mesh = mesh
        self.stage = 1


class ShardingStage2(ShardingStage1):
    def __init__(self, axis_name="sharding", mesh=None):
        super().__init__(axis_name, mesh)
        self.stage = 2


class ShardingStage3(ShardingStage1):
    def __init__(self, axis_name="sharding", mesh=None):
        super().__init__(axis_name, mesh)
        self.stage = 3


def shard_dataloader(dataloader, meshes=None, input_keys=None,
                     shard_dims="dp", is_dataset_splitted=False):
    """reference: auto_parallel/api.py shard_dataloader — wrap a loader so
    each batch lands sharded over the mesh's data axis."""
    from .api import shard_tensor
    from .placement import Replicate, Shard

    mesh = meshes if meshes is not None else get_global_mesh()
    placements = None
    if mesh is not None and shard_dims is not None:
        # accept jax Mesh (axis_names) or ProcessMesh (dim_names)
        axis_names = list(getattr(mesh, "axis_names", None)
                          or getattr(mesh, "dim_names", []))
        if isinstance(shard_dims, int):
            if not 0 <= shard_dims < len(axis_names):
                raise ValueError(
                    f"shard_dims index {shard_dims} out of range for "
                    f"mesh axes {axis_names}")
            target = axis_names[shard_dims]
        else:
            target = shard_dims
            if target not in axis_names:
                raise ValueError(
                    f"shard_dims {target!r} not in mesh axes {axis_names}")
        # batch dim 0 shards over exactly the named mesh axis
        placements = [Shard(0) if name == target else Replicate()
                      for name in axis_names]

    class _ShardedLoader:
        def __init__(self, inner):
            self._inner = inner

        def __iter__(self):
            for batch in self._inner:
                yield jax.tree.map(
                    lambda t: shard_tensor(t, mesh, placements)
                    if isinstance(t, Tensor) and placements is not None
                    else t,
                    batch,
                    is_leaf=lambda t: isinstance(t, Tensor))

        def __len__(self):
            return len(self._inner)

    return _ShardedLoader(dataloader)


def shard_scaler(scaler):
    """reference: auto_parallel/api.py shard_scaler — GradScaler already
    reduces found-inf over the mesh through the jitted step; identity."""
    return scaler


class Strategy:
    """reference: auto_parallel/strategy.py Strategy — config bundle for
    dist.to_static."""

    class _Section(dict):
        def __getattr__(self, k):
            return self.get(k)

        def __setattr__(self, k, v):
            self[k] = v

    _KNOWN = ("sharding", "fused_passes", "gradient_merge", "pipeline",
              "amp", "recompute", "fuse_all_reduce")

    def __init__(self, config=None):
        cfg = config or {}
        # dict-valued config sections become Section attributes; scalar
        # values (e.g. {"seed": 42}) attach as-is so pass-produced and
        # hand-written configs both round-trip
        for name in set(self._KNOWN) | set(cfg):
            val = cfg.get(name)
            if val is None or isinstance(val, dict):
                setattr(self, name, self._Section(val or {}))
            else:
                setattr(self, name, val)


class DistModel:
    """reference: auto_parallel/api.py DistModel — the trained static
    engine handle returned by dist.to_static: call it for one train/eval
    step; the jitted hybrid-parallel program is built by
    parallel.trainer.make_train_step (completion -> partition -> compile
    in one trace)."""

    def __init__(self, layer, loader, loss=None, optimizer=None,
                 strategy=None, metrics=None):
        from .trainer import make_train_step

        self._layer = layer
        self._loader = loader
        self._mode = "train" if optimizer is not None else "eval"
        mesh = get_global_mesh()
        self._train_step = None
        self._opt = None
        self._strategy = strategy
        if optimizer is not None:
            # the actual optimizer's update rule, decay groups, clip and LR
            # schedule run inside the jitted step; strategy sections (amp/
            # recompute/gradient_merge/sharding) are consumed at trace time
            self._train_step, self._params, self._opt = make_train_step(
                layer, loss, mesh, optimizer=optimizer, strategy=strategy)
        else:
            self._params = dict(layer.raw_state())
        self._eval_step = self._build_eval(layer, loss)

    @staticmethod
    def _build_eval(layer, loss_fn):
        from ..core import tape as _tape

        def fwd(p, *batch):
            with _tape.no_grad():
                out = layer.func_call(p, Tensor(batch[0]))
                if loss_fn is not None and len(batch) > 1:
                    return unwrap(loss_fn(out, Tensor(batch[1])))
                return unwrap(out)

        return jax.jit(fwd)

    def train(self):
        self._mode = "train"

    def eval(self):
        self._mode = "eval"

    def __call__(self, *inputs):
        arrs = [unwrap(x) if isinstance(x, Tensor) else jnp.asarray(x)
                for x in inputs]
        if self._mode == "train" and self._train_step is not None:
            loss, self._params, self._opt = self._train_step(
                self._params, self._opt, *arrs)
            return Tensor(loss)
        out = self._eval_step(self._params, *arrs)
        return Tensor(out) if not isinstance(out, tuple) else \
            tuple(Tensor(o) for o in out)

    def state_dict(self, mode="all"):
        return {k: Tensor(v) for k, v in self._params.items()}


def to_static(layer, loader=None, loss=None, optimizer=None, strategy=None,
              metrics=None):
    """reference: auto_parallel/api.py:2343 dist.to_static."""
    return DistModel(layer, loader, loss=loss, optimizer=optimizer,
                     strategy=strategy, metrics=metrics)


def _ps_refusal(name):
    def ctor(*a, **k):
        raise NotImplementedError(
            f"{name} belongs to the parameter-server data stack "
            "(non-goal, SURVEY §7.4); use paddle_tpu.io.DataLoader")
    return ctor


QueueDataset = _ps_refusal("QueueDataset")
InMemoryDataset = _ps_refusal("InMemoryDataset")
CountFilterEntry = _ps_refusal("CountFilterEntry")
ShowClickEntry = _ps_refusal("ShowClickEntry")
ProbabilityEntry = _ps_refusal("ProbabilityEntry")
