"""Collective communication facade: paddle.distributed.* over XLA collectives.

Reference: python/paddle/distributed/communication/{all_reduce,all_gather,
all_to_all,reduce_scatter,broadcast,...}.py over ProcessGroupNCCL
(paddle/fluid/distributed/collective/process_group_nccl.h:37).

TPU-native design: a `Group` IS a mesh axis (or tuple of axes). Collectives
called under `shard_map`/`pjit` tracing lower to XLA collectives over ICI
(`lax.psum`, `lax.all_gather`, ...). Called eagerly on global (already
replicated/sharded) arrays they are the corresponding no-op/layout change —
single-controller JAX has no per-rank eager tensors, so eager collectives
exist for API parity and intra-process semantics only.
"""
from __future__ import annotations

from typing import List, Optional, Sequence, Union

import jax
import jax.numpy as jnp
from jax import lax

from ..core.tensor import Tensor, dispatch, unwrap
from . import mesh as mesh_mod

__all__ = [
    "Group", "new_group", "get_group", "all_reduce", "all_gather",
    "all_gather_object", "all_to_all", "all_to_all_single", "reduce_scatter",
    "broadcast", "reduce", "scatter", "gather", "barrier", "send", "recv",
    "isend", "irecv", "ReduceOp", "stream",
]


class ReduceOp:
    SUM = "sum"
    MAX = "max"
    MIN = "min"
    PROD = "prod"
    AVG = "avg"


class Group:
    """A communication group = one or more mesh axes.

    Reference: python/paddle/distributed/communication/group.py Group (ranks +
    backend pg). Here the membership is implicit in the mesh topology.
    """

    def __init__(self, axis: Union[str, Sequence[str]], mesh=None, id: int = 0):
        self.axes = (axis,) if isinstance(axis, str) else tuple(axis)
        self._mesh = mesh
        self.id = id

    @property
    def mesh(self):
        return self._mesh or mesh_mod.get_global_mesh()

    @property
    def nranks(self) -> int:
        m = self.mesh
        if m is None:
            return 1
        size = 1
        for a in self.axes:
            size *= int(m.shape[a])
        return size

    world_size = nranks

    @property
    def rank(self):
        try:
            return lax.axis_index(tuple(self.axes))
        except Exception:
            return 0

    @property
    def process_group(self):
        return self

    def get_group_rank(self, rank):
        return rank

    def __repr__(self):
        return f"Group(axes={self.axes}, nranks={self.nranks})"


_groups: List[Group] = []


def new_group(ranks=None, backend=None, timeout=None, axis=None) -> Group:
    """Create a group. With `axis`, binds to that mesh axis; the rank-list
    form (reference collective.py:186) has no TPU meaning — it returns the
    world group for API compatibility."""
    g = Group(axis or (mesh_mod.get_global_mesh().axis_names
                       if mesh_mod.get_global_mesh() else "dp"),
              id=len(_groups) + 1)
    _groups.append(g)
    return g


def get_group(id: int = 0) -> Optional[Group]:
    for g in _groups:
        if g.id == id:
            return g
    return _groups[-1] if _groups else None


def _axes_of(group) -> Optional[Sequence[str]]:
    if group is None:
        m = mesh_mod.get_global_mesh()
        return None if m is None else tuple(m.axis_names)
    if isinstance(group, Group):
        return group.axes
    if isinstance(group, str):
        return (group,)
    return tuple(group)


def _bound(axes) -> bool:
    """True iff the axis names are bound in the current trace (inside
    shard_map over those axes)."""
    if axes is None:
        return False
    try:
        lax.axis_index(tuple(axes))
        return True
    except Exception:
        return False


def all_reduce(tensor, op=ReduceOp.SUM, group=None, sync_op=True):
    """In shard_map: lax.psum/pmax/... over the group's axes (XLA all-reduce
    over ICI). Eagerly: identity (a global array is already the reduced
    value across the single controller)."""
    axes = _axes_of(group)
    if not _bound(axes):
        return tensor

    def impl(x):
        if op in (ReduceOp.SUM, "sum"):
            return lax.psum(x, axes)
        if op in (ReduceOp.AVG, "avg"):
            return lax.pmean(x, axes)
        if op in (ReduceOp.MAX, "max"):
            return lax.pmax(x, axes)
        if op in (ReduceOp.MIN, "min"):
            return lax.pmin(x, axes)
        if op in (ReduceOp.PROD, "prod"):
            # gather + prod: exact for zeros/negatives (log-space psum is not)
            g = lax.all_gather(x, axes, tiled=False)
            extra = g.ndim - x.ndim
            return jnp.prod(g, axis=tuple(range(extra)))
        raise ValueError(f"unknown reduce op {op}")

    out = dispatch("all_reduce", impl, (tensor,))
    if isinstance(tensor, Tensor):
        tensor._replace(out._array, out._node, out._out_idx)
        return tensor
    return out


def all_gather(tensor_list, tensor=None, group=None, sync_op=True, axis=0):
    """paddle signature: all_gather(list, tensor). Functional form: pass
    tensor only -> returns gathered Tensor (stacked on a new leading dim)."""
    if tensor is None and not isinstance(tensor_list, list):
        tensor, tensor_list = tensor_list, None
    axes = _axes_of(group)
    if not _bound(axes):
        out = tensor
        n = 1
    else:
        out = dispatch(
            "all_gather",
            lambda x: lax.all_gather(x, tuple(axes), tiled=False),
            (tensor,))
        n = Group(axes).nranks
    if tensor_list is not None:
        if n == 1:
            tensor_list.append(out if isinstance(out, Tensor) else Tensor(out))
        else:
            for i in range(n):
                tensor_list.append(out[i])
        return None
    return out


def all_gather_object(object_list, obj, group=None):
    object_list.append(obj)


def reduce_scatter(tensor, tensor_list=None, op=ReduceOp.SUM, group=None,
                   sync_op=True):
    """psum_scatter over the group axis (XLA reduce-scatter)."""
    axes = _axes_of(group)
    src = tensor_list if tensor_list is not None else tensor
    if isinstance(src, (list, tuple)):
        stacked = jnp.concatenate([unwrap(t) for t in src], axis=0)
        src_t = Tensor(stacked)
    else:
        src_t = src
    if not _bound(axes):
        out = src_t
    else:
        out = dispatch(
            "reduce_scatter",
            lambda x: lax.psum_scatter(x, tuple(axes), scatter_dimension=0,
                                       tiled=True), (src_t,))
    if tensor_list is not None and isinstance(tensor, Tensor):
        tensor._replace(out._array, out._node, out._out_idx)
        return tensor
    return out


def all_to_all(out_tensor_list, in_tensor_list=None, group=None, sync_op=True):
    """List-form all_to_all (reference: communication/all_to_all.py). Inside
    shard_map use `all_to_all_single` (the XLA-native form)."""
    if in_tensor_list is None:
        in_tensor_list = out_tensor_list
        out_tensor_list = None
    axes = _axes_of(group)
    if not _bound(axes):
        res = list(in_tensor_list)
    else:
        x = jnp.stack([unwrap(t) for t in in_tensor_list], axis=0)
        swapped = lax.all_to_all(x, tuple(axes), split_axis=0, concat_axis=0,
                                 tiled=False)
        res = [Tensor(swapped[i]) for i in range(swapped.shape[0])]
    if out_tensor_list is not None:
        out_tensor_list.clear()
        out_tensor_list.extend(res)
        return None
    return res


def all_to_all_single(out_tensor, in_tensor=None, out_split_sizes=None,
                      in_split_sizes=None, group=None, sync_op=True,
                      split_axis=0, concat_axis=0):
    """XLA-native all-to-all: split in_tensor along split_axis across the
    group, concat received chunks along concat_axis. This is the Ulysses /
    MoE-dispatch primitive (reference: alltoall op +
    distributed/utils/moe_utils.py global_scatter)."""
    if in_tensor is None:
        in_tensor, out_tensor = out_tensor, None
    axes = _axes_of(group)
    if not _bound(axes):
        out = in_tensor if isinstance(in_tensor, Tensor) else Tensor(in_tensor)
    else:
        out = dispatch(
            "all_to_all",
            lambda x: lax.all_to_all(x, tuple(axes), split_axis=split_axis,
                                     concat_axis=concat_axis, tiled=True),
            (in_tensor,))
    if out_tensor is not None and isinstance(out_tensor, Tensor):
        out_tensor._replace(out._array, out._node, out._out_idx)
        return out_tensor
    return out


def broadcast(tensor, src=0, group=None, sync_op=True):
    """Within a mesh axis every shard computes the same program — broadcast
    from rank `src` is realized by selecting src's value via ppermute when
    values may diverge; under SPMD they cannot, so this is identity inside
    traces and eagerly."""
    return tensor


def reduce(tensor, dst=0, op=ReduceOp.SUM, group=None, sync_op=True):
    return all_reduce(tensor, op=op, group=group)


def scatter(tensor, tensor_list=None, src=0, group=None, sync_op=True):
    axes = _axes_of(group)
    if not _bound(axes):
        if tensor_list:
            t0 = tensor_list[0]
            tensor._replace(unwrap(t0) if not isinstance(t0, Tensor) else t0._array)
        return tensor
    stacked = jnp.stack([unwrap(t) for t in tensor_list], axis=0)
    idx = lax.axis_index(tuple(axes))
    out = lax.dynamic_index_in_dim(stacked, idx, axis=0, keepdims=False)
    tensor._replace(out)
    return tensor


def gather(tensor, gather_list=None, dst=0, group=None, sync_op=True):
    return all_gather(gather_list, tensor, group=group)


def barrier(group=None):
    """Device-sync barrier; eager = block_until_ready on a trivial psum."""
    jax.block_until_ready(jnp.zeros(()))


def send(tensor, dst=0, group=None, sync_op=True):
    """P2P over a mesh axis = lax.ppermute (used by pipeline parallel;
    reference: pp_utils/p2p_communication.py)."""
    axes = _axes_of(group)
    if not _bound(axes):
        _p2p_buf.append(unwrap(tensor))
        return tensor
    return tensor


def recv(tensor, src=0, group=None, sync_op=True):
    axes = _axes_of(group)
    if not _bound(axes):
        if _p2p_buf:
            tensor._replace(jnp.asarray(_p2p_buf.pop(0)))
        return tensor
    return tensor


_p2p_buf: list = []


def isend(tensor, dst=0, group=None):
    send(tensor, dst, group)
    return _DoneTask()


def irecv(tensor, src=0, group=None):
    recv(tensor, src, group)
    return _DoneTask()


class _DoneTask:
    def wait(self):
        return None

    def is_completed(self):
        return True


class _StreamNS:
    """paddle.distributed.stream.* variants (reference: communication/stream/);
    on TPU streams are XLA's concern — same impls."""

    all_reduce = staticmethod(all_reduce)
    all_gather = staticmethod(all_gather)
    all_to_all = staticmethod(all_to_all)
    all_to_all_single = staticmethod(all_to_all_single)
    reduce_scatter = staticmethod(reduce_scatter)
    broadcast = staticmethod(broadcast)
    reduce = staticmethod(reduce)
    scatter = staticmethod(scatter)
    gather = staticmethod(gather)
    send = staticmethod(send)
    recv = staticmethod(recv)


stream = _StreamNS()
