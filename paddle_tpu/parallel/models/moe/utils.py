"""MoE routing helpers (reference: distributed/models/moe/utils.py:24,63,
113,136,182) — jnp closed forms over the reference's custom CUDA kernels.
All are jit-safe (static shapes, no data-dependent control flow).
"""
import jax
import jax.numpy as jnp

from ....core.tensor import Tensor, dispatch, unwrap

__all__ = [
    "_number_count", "_assign_pos", "_random_routing",
    "_limit_by_capacity", "_prune_gate_by_capacity",
]


def _number_count(numbers, upper_range):
    """Histogram of expert ids in [0, upper_range) (reference :24)."""

    def impl(n):
        return jnp.bincount(n.astype(jnp.int32).ravel(), length=int(upper_range))

    return dispatch("moe_number_count", impl, (numbers,))


def _assign_pos(x, cum_count):
    """Token positions grouped by expert: pos[j] lists indices of tokens
    routed to each expert, packed by the exclusive cumsum (reference :63)."""

    def impl(ids, cum):
        ids = ids.astype(jnp.int32).ravel()
        # stable sort by expert id reproduces the kernel's grouped order
        order = jnp.argsort(ids, stable=True)
        return order.astype(jnp.int64)

    return dispatch("moe_assign_pos", impl, (x, cum_count))


def _random_routing(topk_idx, topk_value, prob, topk: int = 2):
    """Drop the 2nd choice with prob < threshold*2 (reference :113)."""
    if topk != 2:
        raise ValueError("random routing only supports topk=2")

    def impl(idx, val, p):
        keep = p < (2.0 * val[:, 1])
        new_second = jnp.where(keep, idx[:, 1], -1)
        return jnp.stack([idx[:, 0], new_second], axis=1)

    return dispatch("moe_random_routing", impl, (topk_idx, topk_value, prob))


def _limit_by_capacity(expert_count, capacity, n_worker: int):
    """Clamp per-(worker, expert) counts so each expert's global total stays
    within capacity, greedily in worker order (reference :136)."""

    def impl(ec, cap):
        ec = ec.astype(jnp.int32).reshape(int(n_worker), -1)  # [W, E]
        cap = cap.astype(jnp.int32)

        def per_expert(counts_e, cap_e):
            def step(remaining, c):
                take = jnp.minimum(c, remaining)
                return remaining - take, take

            _, taken = jax.lax.scan(step, cap_e, counts_e)
            return taken

        out = jax.vmap(per_expert, in_axes=(1, 0), out_axes=1)(ec, cap)
        return out.reshape(-1).astype(jnp.int64)

    return dispatch("moe_limit_by_capacity", impl, (expert_count, capacity))


def _prune_gate_by_capacity(gate_idx, expert_count, n_expert: int, n_worker: int):
    """Set gate ids to -1 for tokens beyond their expert's capacity count
    (reference :182)."""

    def impl(gidx, ec):
        gidx = gidx.astype(jnp.int32).ravel()
        ec = ec.astype(jnp.int32).ravel()
        one_hot = jax.nn.one_hot(gidx, int(n_expert) * int(n_worker), dtype=jnp.int32)
        pos_in_expert = jnp.cumsum(one_hot, axis=0) * one_hot
        rank = jnp.sum(pos_in_expert, axis=1)  # 1-based arrival order
        cap_of_token = ec[gidx]
        return jnp.where(rank <= cap_of_token, gidx, -1).astype(jnp.int64)

    return dispatch("moe_prune_gate_by_capacity", impl, (gate_idx, expert_count))
