"""paddle.distributed.models.moe (reference:
distributed/models/moe/__init__.py + utils.py gate helpers)."""
from ...moe import GShardGate, MoELayer, NaiveGate, SwitchGate, moe_dispatch  # noqa: F401
from .utils import (  # noqa: F401
    _assign_pos,
    _limit_by_capacity,
    _number_count,
    _prune_gate_by_capacity,
    _random_routing,
)
