"""paddle.distributed.models (reference: distributed/models/__init__.py)."""
from . import moe  # noqa: F401
