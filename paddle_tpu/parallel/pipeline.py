"""Pipeline parallelism over the `pp` mesh axis.

Reference:
- dygraph: fleet/meta_parallel/pipeline_parallel.py (train_batch:697,
  forward_backward_pipeline 1F1B:459, interleave VPP:1009) with p2p over
  NCCL (pp_utils/p2p_communication.py:51,553);
- layer partitioning: fleet/meta_parallel/parallel_layers/pp_layers.py:257
  (PipelineLayer, LayerDesc, SegmentLayers);
- static scheds: distributed/passes/pipeline_scheduler_pass/ (FThenB, 1F1B,
  VPP, zero-bubble).

TPU-native: single-controller XLA cannot run per-rank Python schedules;
instead the schedule is a `lax.scan` inside ONE `shard_map` over the `pp`
axis. Each device holds the params of its stage (stacked layer params with
the stage dim sharded over `pp`); activations move stage->stage by
`lax.ppermute` (XLA collective-permute over ICI). Differentiating the scan
yields the reverse schedule automatically (the transpose of ppermute is the
reverse ppermute), so fwd+bwd matches GPipe/1F1B bubble structure, and XLA
overlaps the permute with compute.
"""
from __future__ import annotations

import functools
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec
from .shard_map_compat import shard_map

from ..core.tensor import Tensor
from ..nn.layer.layers import Layer
from . import mesh as mesh_mod

__all__ = ["pipeline_apply", "LayerDesc", "SharedLayerDesc", "PipelineLayer",
           "PipelineParallel"]


def pipeline_apply(block_fn: Callable, stage_params: Any, x: jnp.ndarray,
                   n_microbatches: int, mesh: Optional[Mesh] = None,
                   axis: str = "pp"):
    """Run `n_stages` stacked stages over microbatches of x (GPipe schedule).

    block_fn(params_of_one_stage, activation) -> activation. `stage_params`
    pytree leaves have leading dim n_stages (sharded over `axis`);
    x is [n_microbatches * mb, ...] (global batch). Returns y with x's shape.

    Schedule (per device, inside shard_map): T = n_micro + n_stages - 1
    steps; at step t stage s computes microbatch t - s. The activation
    buffer advances one stage per step via ppermute. This is the
    collective-permute pipeline from the scaling-book recipe — the TPU
    replacement for interceptor/actor message passing (fleet_executor) and
    batched NCCL p2p.
    """
    mesh = mesh or mesh_mod.get_global_mesh()
    n_stages = int(mesh.shape[axis])
    if n_stages == 1:
        return block_fn(jax.tree.map(lambda p: p[0], stage_params), x)
    assert x.shape[0] % n_microbatches == 0
    mb = x.shape[0] // n_microbatches
    perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

    other_axes = [a for a in mesh.axis_names if a != axis]

    def per_stage(params, xs):
        # params: this stage's params (leading stage dim stripped by shard_map)
        # xs: [n_micro, mb, ...] microbatches (replicated over pp)
        params = jax.tree.map(lambda p: p[0], params)
        stage = lax.axis_index(axis)
        n_steps = n_microbatches + n_stages - 1
        state = jnp.zeros((mb,) + xs.shape[2:], xs.dtype)
        outputs = jnp.zeros_like(xs)

        def step(carry, t):
            state, outputs = carry
            # stage 0 ingests microbatch t (when valid)
            inject = lax.dynamic_index_in_dim(
                xs, jnp.clip(t, 0, n_microbatches - 1), 0, keepdims=False)
            state = jnp.where(stage == 0, inject, state)
            out = block_fn(params, state)
            # last stage captures microbatch t - (n_stages - 1)
            out_t = t - (n_stages - 1)
            cap = jnp.logical_and(stage == n_stages - 1,
                                  jnp.logical_and(out_t >= 0,
                                                  out_t < n_microbatches))
            outputs = lax.cond(
                cap,
                lambda o: lax.dynamic_update_index_in_dim(
                    o, out, jnp.clip(out_t, 0, n_microbatches - 1), 0),
                lambda o: o, outputs)
            # rotate activations stage -> stage+1
            state = lax.ppermute(out, axis, perm)
            return (state, outputs), None

        (state, outputs), _ = lax.scan(step, (state, outputs),
                                       jnp.arange(n_steps))
        # outputs live on the last stage; broadcast to all pp ranks so the
        # result is replicated (psum of one-hot contribution)
        contrib = jnp.where(stage == n_stages - 1, 1.0, 0.0)
        outputs = lax.psum(outputs * contrib.astype(outputs.dtype), axis)
        return outputs

    xs = x.reshape((n_microbatches, mb) + x.shape[1:])
    in_param_spec = jax.tree.map(
        lambda _: PartitionSpec(axis), stage_params)
    fn = shard_map(
        per_stage, mesh=mesh,
        in_specs=(in_param_spec, PartitionSpec()),
        out_specs=PartitionSpec(),
        check_vma=False)
    ys = fn(stage_params, xs)
    return ys.reshape(x.shape)


class LayerDesc:
    """reference: pp_layers.py LayerDesc — deferred layer construction."""

    def __init__(self, layer_cls, *args, **kwargs):
        self.layer_cls = layer_cls
        self.args = args
        self.kwargs = kwargs

    def build_layer(self):
        return self.layer_cls(*self.args, **self.kwargs)


class SharedLayerDesc(LayerDesc):
    """reference: pp_layers.py SharedLayerDesc (tied embeddings)."""

    def __init__(self, key, layer_cls, forward_func=None, shared_weight_attr
                 ="weight", *args, **kwargs):
        super().__init__(layer_cls, *args, **kwargs)
        self.layer_name = key
        self.forward_func = forward_func
        self.shared_weight_attr = shared_weight_attr


class PipelineLayer(Layer):
    """reference: pp_layers.py:257 PipelineLayer(layers=[LayerDesc...],
    num_stages, topology). Builds ALL layers on every process (single
    controller owns the global model); stage segmentation is recorded for
    the scheduler."""

    def __init__(self, layers, num_stages=None, topology=None, loss_fn=None,
                 seg_method="uniform", recompute_interval=0, **kwargs):
        super().__init__()
        self._loss_fn = loss_fn
        descs = list(layers)
        built = [d.build_layer() if isinstance(d, LayerDesc) else d
                 for d in descs]
        from ..nn.layer.container import LayerList

        self.run_function = LayerList(built)
        self._num_stages = num_stages or 1
        n = len(built)
        per = max(1, n // self._num_stages)
        self.segment_parts = [min(i * per, n) for i in range(self._num_stages)] + [n]

    def forward(self, x):
        for layer in self.run_function:
            x = layer(x)
        return x

    def get_stage_layers(self, stage: int):
        lo, hi = self.segment_parts[stage], self.segment_parts[stage + 1]
        return list(self.run_function)[lo:hi]


class PipelineParallel(Layer):
    """Dygraph-API wrapper (reference: pipeline_parallel.py PipelineParallel).

    `train_batch(data, optimizer, scaler)` runs microbatched fwd/bwd +
    optimizer step. With pp_degree == 1 this is plain gradient accumulation
    over microbatches; multi-stage execution goes through `pipeline_apply`
    when the wrapped model is a uniform-stage PipelineLayer."""

    def __init__(self, layers, hcg=None, strategy=None):
        super().__init__()
        self._layers = layers
        self._hcg = hcg
        self._strategy = strategy
        cfg = (strategy.pipeline_configs if strategy is not None else {}) or {}
        self.accumulate_steps = int(cfg.get("accumulate_steps", 1))

    def forward(self, *args, **kwargs):
        return self._layers(*args, **kwargs)

    def train_batch(self, data, optimizer, lr_scheduler=None, scaler=None,
                    loss_fn=None):
        x, y = data
        n_micro = max(1, self.accumulate_steps)
        xs = x if not isinstance(x, Tensor) else x
        bsz = xs.shape[0]
        if bsz % n_micro != 0:
            raise ValueError(
                f"batch size {bsz} must be divisible by accumulate_steps "
                f"{n_micro} (reference: PipelineParallel micro-batching)")
        mb = bsz // n_micro
        total = None
        loss_fn = loss_fn or getattr(self._layers, "_loss_fn", None)
        for i in range(n_micro):
            xi = xs[i * mb:(i + 1) * mb]
            yi = y[i * mb:(i + 1) * mb]
            out = self._layers(xi)
            if loss_fn is not None:
                loss = loss_fn(out, yi)
            else:
                from ..nn import functional as F

                loss = F.cross_entropy(out, yi)
            scaled = loss.scale(1.0 / n_micro) if hasattr(loss, "scale") else loss / n_micro
            if scaler is not None:
                scaler.scale(scaled).backward()
            else:
                scaled.backward()
            total = float(loss.numpy()) if total is None else total + float(loss.numpy())
        if scaler is not None:
            scaler.step(optimizer)
            scaler.update()
        else:
            optimizer.step()
        optimizer.clear_grad()
        if lr_scheduler is not None:
            lr_scheduler.step()
        from ..core.tensor import Tensor as T

        return T(total / n_micro)

    def eval_batch(self, data, compute_loss=True):
        x, y = data
        out = self._layers(x)
        return out
