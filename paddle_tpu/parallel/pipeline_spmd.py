"""SPMD pipeline parallelism: microbatch loop over a `pp` mesh axis.

Reference: fleet/meta_parallel/pipeline_parallel.py (1F1B train_batch :697,
forward_backward_pipeline :459) and the static pipeline_scheduler passes
(FThenB/1F1B/VPP/ZB). There, stages are separate processes exchanging
activations via NCCL p2p (pp_utils/p2p_communication.py batch_isend_irecv).

TPU-native: ONE program under `jax.shard_map` over the `pp` axis. The stage
dimension of the stacked layer parameters is sharded over `pp`, so each
device holds its stage's weights. The schedule is a `lax.scan` over
T = n_micro + n_stages - 1 ticks; each tick every stage processes one
microbatch slot and the boundary activation moves to the next stage with
`lax.ppermute` — the classic collective-permute pipeline from the public
scaling playbook. Autodiff through scan+ppermute gives the backward
schedule for free (fwd-then-bwd, GPipe-equivalent bubble profile);
`pipeline_1f1b` below implements the memory-bounded 1F1B schedule
manually (one fwd + one bwd per tick, loss inside the last stage).

Because everything is one XLA program, this composes with dp/mp/sharding
axes of the same mesh: the non-pp axes partition the per-stage math.
"""
from __future__ import annotations

from functools import partial
from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .shard_map_compat import shard_map

from . import mesh as mesh_mod

__all__ = ["pipeline_forward", "pipeline_1f1b", "pipeline_eager_1f1b",
           "pipeline_vpp_forward", "pipeline_zb1f1b", "stack_stage_params",
           "unstack_stage_params"]


def _to_varying(x, axis):
    """Mark x as varying over the manual axis (scan-carry requirement)."""
    if hasattr(jax.lax, "pcast"):
        return jax.lax.pcast(x, axis, to="varying")
    if hasattr(jax.lax, "pvary"):
        return jax.lax.pvary(x, axis)
    # jax 0.4.x: the compat shim runs partial-auto shard_map with the
    # replication check off, so there is no varying-ness to mark
    return x


def stack_stage_params(per_stage_params: list, mesh: Optional[Mesh] = None,
                       axis: str = "pp"):
    """Stack a list of per-stage pytrees along a new leading stage dim and
    shard that dim over `axis` (each pp rank stores only its stage's
    weights — the pp analog of ZeRO partitioning)."""
    stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *per_stage_params)
    mesh = mesh or mesh_mod.get_global_mesh()
    if mesh is not None and axis in mesh.axis_names:
        def put(x):
            spec = [axis] + [None] * (x.ndim - 1)
            return jax.device_put(x, NamedSharding(mesh, P(*spec)))

        stacked = jax.tree.map(put, stacked)
    return stacked


def unstack_stage_params(stacked, n_stages: int):
    return [jax.tree.map(lambda x, i=i: x[i], stacked)
            for i in range(n_stages)]


def pipeline_forward(stage_fn: Callable, stacked_params, x, *,
                     mesh: Optional[Mesh] = None, axis: str = "pp",
                     n_micro: Optional[int] = None):
    """Run x through n_stages pipeline stages with microbatching.

    stage_fn(stage_params, h) -> h  (the per-stage computation; it may use
    other mesh axes internally — their sharding propagates through
    shard_map via the residual spec being Replicated on `axis` only).

    x: [batch, ...] global input activations (already embedded);
    returns [batch, ...] output of the last stage, replicated over `axis`.
    """
    mesh = mesh or mesh_mod.get_global_mesh()
    if mesh is None or axis not in mesh.axis_names \
            or int(mesh.shape[axis]) == 1:
        # degenerate: run stages sequentially in one program
        n_stages = jax.tree.leaves(stacked_params)[0].shape[0]
        h = x
        for i in range(n_stages):
            p_i = jax.tree.map(lambda t, i=i: t[i], stacked_params)
            h = stage_fn(p_i, h)
        return h

    n_stages = int(mesh.shape[axis])
    stacked_n = int(jax.tree.leaves(stacked_params)[0].shape[0])
    if stacked_n != n_stages:
        raise ValueError(
            f"stacked stage dim {stacked_n} != pp axis size {n_stages}; "
            f"group layers into exactly one block per pp rank")
    batch = x.shape[0]
    n_micro = n_micro or n_stages
    if batch % n_micro != 0:
        raise ValueError(f"batch {batch} not divisible by n_micro {n_micro}")
    mb = batch // n_micro

    # manual only over `axis`: the other mesh axes stay "auto" so TP/FSDP
    # shardings of the per-stage weights keep working inside the body
    # (on jax 0.4.x the compat shim must force the replication check OFF
    # in partial-auto mode; newer jax keeps check_vma on)
    @partial(shard_map, mesh=mesh, axis_names={axis},
             in_specs=(P(axis), P()), out_specs=P())
    def run(params_local, xg):
        # params_local: stage dim reduced to 1 on this rank
        p_stage = jax.tree.map(lambda t: t[0], params_local)
        stage_id = jax.lax.axis_index(axis)
        micro = xg.reshape((n_micro, mb) + xg.shape[1:])

        t_total = n_micro + n_stages - 1
        perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

        def tick(carry, t):
            boundary, outputs = carry
            # microbatch index this stage works on at tick t
            mb_idx = t - stage_id
            active = (mb_idx >= 0) & (mb_idx < n_micro)
            # stage 0 reads its microbatch; others read the boundary
            # activation received from the previous stage
            x_in = jnp.where(
                stage_id == 0,
                micro[jnp.clip(mb_idx, 0, n_micro - 1)],
                boundary)
            y = stage_fn(p_stage, x_in)
            y = jnp.where(active, y, jnp.zeros_like(y))
            # last stage records its finished microbatch
            outputs = jnp.where(
                (stage_id == n_stages - 1) & active,
                outputs.at[jnp.clip(mb_idx, 0, n_micro - 1)].set(y),
                outputs)
            # activation moves stage s -> s+1 for the next tick
            boundary = jax.lax.ppermute(y, axis, perm)
            return (boundary, outputs), None

        boundary0 = _to_varying(
            jnp.zeros((mb,) + xg.shape[1:], xg.dtype), axis)
        outputs0 = _to_varying(
            jnp.zeros((n_micro, mb) + xg.shape[1:], xg.dtype), axis)
        (boundary, outputs), _ = jax.lax.scan(
            tick, (boundary0, outputs0), jnp.arange(t_total))
        out = outputs.reshape((batch,) + xg.shape[1:])
        # every rank returns the same value: broadcast the last stage's
        # outputs (psum over one-hot mask keeps it differentiable)
        mask = (stage_id == n_stages - 1).astype(out.dtype)
        return jax.lax.psum(out * mask, axis)

    return run(stacked_params, x)


def pipeline_vpp_forward(chunk_fn: Callable, chunked_params, x, *,
                         mesh: Optional[Mesh] = None, axis: str = "pp",
                         n_micro: Optional[int] = None):
    """Interleaved (VPP) pipeline forward — one SPMD program.

    Reference: fleet/meta_parallel/pipeline_parallel.py:1009
    PipelineParallelWithInterleave and
    passes/pipeline_scheduler_pass/pipeline_vpp.py. There, each rank holds
    V non-contiguous model chunks and a hand-written schedule interleaves
    them; here the same interleaving is ONE scan whose tick body picks the
    rank's active chunk by a dynamic index derived from (tick, rank) — a
    gather over the rank's V chunk parameter slices, NOT V× compute (the
    round-2 punt claimed otherwise; it was wrong).

    Layout: ``chunked_params`` leaves are [S, V, ...] — element [r, v] is
    model chunk ``v*S + r`` (Megatron interleaved assignment), dim 0
    sharded over `axis`. Microbatch m flows through chunks 0..S*V-1 in
    order; every chunk boundary moves rank r → r+1 (mod S), produced at
    one tick and consumed exactly at the next, so no boundary buffering is
    needed. With the local clock u = t - r:

        g = u // (S*V);  w = u % (S*V);  v = w // S;  m = g*S + (w % S)

    T = n_micro*V + S - 1 ticks of ONE chunk's work — the interleaved
    bubble is (S-1) chunk-ticks vs (S-1) full-stage-ticks for V=1, the
    1/V bubble shrink VPP exists for. Requires n_micro % S == 0 (the same
    constraint the reference's interleaved schedule imposes).
    """
    mesh = mesh or mesh_mod.get_global_mesh()
    leaves = jax.tree.leaves(chunked_params)
    S_dim, V = int(leaves[0].shape[0]), int(leaves[0].shape[1])
    if mesh is None or axis not in mesh.axis_names \
            or int(mesh.shape[axis]) == 1:
        h = x
        for c in range(S_dim * V):
            p_c = jax.tree.map(lambda t, c=c: t[c % S_dim, c // S_dim],
                               chunked_params)
            h = chunk_fn(p_c, h)
        return h

    n_stages = int(mesh.shape[axis])
    if S_dim != n_stages:
        raise ValueError(f"chunk rank-dim {S_dim} != pp axis {n_stages}")
    batch = x.shape[0]
    n_micro = n_micro or n_stages
    if batch % n_micro != 0:
        raise ValueError(f"batch {batch} not divisible by n_micro {n_micro}")
    if n_micro % n_stages != 0:
        raise ValueError(
            f"VPP needs n_micro ({n_micro}) divisible by pp ({n_stages}) — "
            "the reference interleaved schedule has the same constraint")
    mb = batch // n_micro
    SV = n_stages * V

    @partial(shard_map, mesh=mesh, axis_names={axis},
             in_specs=(P(axis), P()), out_specs=P())
    def run(params_local, xg):
        chunks = jax.tree.map(lambda t: t[0], params_local)  # [V, ...]
        r = jax.lax.axis_index(axis)
        micro = xg.reshape((n_micro, mb) + xg.shape[1:])
        t_total = n_micro * V + n_stages - 1
        perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

        def tick(carry, t):
            boundary, outputs = carry
            u = t - r
            active = (u >= 0) & (u < n_micro * V)
            uc = jnp.clip(u, 0, n_micro * V - 1)
            g = uc // SV
            w = uc % SV
            v = w // n_stages
            m = g * n_stages + (w % n_stages)
            p_v = jax.tree.map(
                lambda t_: jax.lax.dynamic_index_in_dim(
                    t_, v, axis=0, keepdims=False), chunks)
            first_chunk = (r == 0) & (v == 0)
            x_in = jnp.where(first_chunk, micro[m], boundary)
            y = chunk_fn(p_v, x_in)
            y = jnp.where(active, y, jnp.zeros_like(y))
            last_chunk = (r == n_stages - 1) & (v == V - 1)
            outputs = jnp.where(
                last_chunk & active, outputs.at[m].set(y), outputs)
            boundary = jax.lax.ppermute(y, axis, perm)
            return (boundary, outputs), None

        boundary0 = _to_varying(
            jnp.zeros((mb,) + xg.shape[1:], xg.dtype), axis)
        outputs0 = _to_varying(
            jnp.zeros((n_micro, mb) + xg.shape[1:], xg.dtype), axis)
        (boundary, outputs), _ = jax.lax.scan(
            tick, (boundary0, outputs0), jnp.arange(t_total))
        out = outputs.reshape((batch,) + xg.shape[1:])
        mask = ((r == n_stages - 1)).astype(out.dtype)
        return jax.lax.psum(out * mask, axis)

    return run(chunked_params, x)


def pipeline_zb1f1b(stage_fn: Callable, head_fn: Callable, stacked_params,
                    head_params, x, labels, *, mesh: Optional[Mesh] = None,
                    axis: str = "pp", n_micro: Optional[int] = None,
                    head_specs=None):
    """Zero-bubble-style 1F1B: weight gradients leave the tick loop.

    Reference: distributed/passes/pipeline_scheduler_pass/
    pipeline_zero_bubble.py (ZBH1) — split each backward into B
    (activation grad, on the critical path) and W (weight grad, not), and
    schedule W into bubble slots.

    TPU-native translation: the SPMD pipeline is ONE program whose ticks
    synchronize at every ppermute, so per-rank-asynchronous W slotting (the
    GPU form) cannot shorten a tick — any tick in which SOME rank does W
    costs F+B+W for everyone. What the one-program model CAN do is take W
    out of the scan entirely: ticks run F + B only (dx via a vjp w.r.t.
    the input alone), each microbatch's (input, output-cotangent) pair is
    saved, and ALL weight gradients are computed after the scan as one
    vmapped-and-summed vjp — n_micro microbatches of weight-grad matmuls
    batched into single large MXU-friendly contractions instead of
    n_micro small ones serialized through the scan.

    Cost model vs 1F1B (T = n_micro + 2S - 1 ticks): the scan saves T
    weight-grad units; the post-pass spends n_micro recompute-forward +
    n_micro weight-grad units (batched). Net tick-FLOP saving ≈
    (2S - 1 - n_micro) weight-grad units — a win for n_micro < 2S-1, a
    wash above, with the batched W pass's better MXU utilization on top
    either way. Memory: 2·n_micro microbatch activations (x and dy
    buffers) vs 1F1B's 2S inputs — the classic zero-bubble
    compute-for-memory trade (ZB-H2 territory).
    Same contract and return values as pipeline_1f1b.
    """
    return _pipeline_1f1b_impl(stage_fn, head_fn, stacked_params,
                               head_params, x, labels, mesh=mesh, axis=axis,
                               n_micro=n_micro, defer_weight_grads=True,
                               head_specs=head_specs)


def pipeline_1f1b(stage_fn: Callable, head_fn: Callable, stacked_params,
                  head_params, x, labels, *, mesh: Optional[Mesh] = None,
                  axis: str = "pp", n_micro: Optional[int] = None,
                  head_specs=None):
    """One-pass fwd+bwd pipeline with the (eager-)1F1B memory profile.

    Reference: fleet/meta_parallel/pipeline_parallel.py:459
    forward_backward_pipeline (1F1B) and the pipeline_scheduler passes.
    There the schedule is a list of p2p send/recv + fwd/bwd calls per rank;
    here it is ONE scan under shard_map where every tick runs one stage
    forward AND one stage backward:

        fwd of microbatch i at stage s happens at tick  s + i
        bwd of microbatch i at stage s happens at tick  2S - 1 - s + i

    so the backward of microbatch 0 starts at tick S (while forwards of
    later microbatches are still streaming in) and a stage holds at most
    2S-1 in-flight microbatch INPUTS — the backward recomputes the stage
    from its saved input (recompute is how the reference runs 1F1B at scale
    too), so peak activation memory is O(n_stages * microbatch) instead of
    the O(n_micro * stage_residuals) that autodiff-of-scan (GPipe) keeps.

    stage_fn(stage_params, h) -> h
    head_fn(head_params, h, labels_mb) -> scalar mean loss of the microbatch
       (the last stage's norm/head/criterion — running the loss inside the
       pipeline is what makes an early backward possible)

    Returns (loss, d_stacked, d_head_params, d_x): mean loss over
    microbatches and gradients w.r.t. the stacked stage params, the head
    params, and the pipeline input activations.

    The head runs COOPERATIVELY when `head_specs` is passed (a pytree of
    PartitionSpecs for head_params, sharding e.g. the vocab dim over
    `axis`; see make_llama_pp_train_step): every tick, the last rank's
    recomputed stage output is broadcast and all ranks evaluate the head
    on their own vocab shard, psum-combining the CE pieces — per-tick head
    FLOPs are 1/n_stages of a full head instead of the n_stages× a
    replicated per-rank head pays. head_fn must then combine its partial
    results with collectives over `axis` itself (coop_head_fn in
    models/llama_pipe.py is the model of this contract).
    """
    return _pipeline_1f1b_impl(stage_fn, head_fn, stacked_params,
                               head_params, x, labels, mesh=mesh, axis=axis,
                               n_micro=n_micro, defer_weight_grads=False,
                               head_specs=head_specs)


def pipeline_eager_1f1b(stage_fn: Callable, head_fn: Callable,
                        stacked_params, head_params, x, labels, *,
                        mesh: Optional[Mesh] = None, axis: str = "pp",
                        n_micro: Optional[int] = None, head_specs=None):
    """Eager-1F1B: trade activation memory for guaranteed comm overlap.

    Reference: distributed/passes/pipeline_scheduler_pass/
    pipeline_eager_1f1b.py:31 — relative to 1F1B, stage s issues
    2*(S-s)-1 warmup forwards instead of S-s, holding more microbatches
    in flight so activation sends overlap with compute
    (enable_send_recv_overlap) instead of stalling the steady state.

    TPU-native translation: the one-program lockstep scan already has the
    eager in-flight *profile* (a stage cannot stall on a recv — every
    ppermute is a program-ordered collective), so "eager" here takes the
    same trade one step further in the direction the reference's schedule
    exists for: every boundary exchange gets a FULL TICK of slack.
    Forward of microbatch i runs at stage s at tick 2s+i (vs s+i) and its
    backward at tick 4S-4-2s+i (vs 2S-1-s+i); an activation produced at
    tick t is consumed at t+2, so XLA's latency-hiding scheduler can run
    the collective-permute entirely under tick t+1's compute — on a real
    ICI mesh no tick ever waits on the wire. Cost, exactly the
    reference's: more in-flight activations (a stage buffers up to
    4(S-s)-3 microbatch inputs vs 2(S-s)-1 — asserted relative to 1F1B
    in tests/test_pipeline.py) and 2S-3 extra (masked) schedule ticks.
    Same contract and return values as pipeline_1f1b.
    """
    return _pipeline_1f1b_impl(stage_fn, head_fn, stacked_params,
                               head_params, x, labels, mesh=mesh, axis=axis,
                               n_micro=n_micro, defer_weight_grads=False,
                               head_specs=head_specs, eager=True)


def _pipeline_1f1b_impl(stage_fn, head_fn, stacked_params, head_params, x,
                        labels, *, mesh, axis, n_micro, defer_weight_grads,
                        head_specs=None, eager=False):
    if eager and defer_weight_grads:
        raise ValueError("eager comm-slack scheduling composes with plain "
                         "1F1B only (ZBH1 already restructures the ticks)")
    mesh = mesh or mesh_mod.get_global_mesh()
    n_stages = int(mesh.shape[axis]) if (
        mesh is not None and axis in mesh.axis_names) else 1
    if n_stages == 1:
        n_all = jax.tree.leaves(stacked_params)[0].shape[0]

        def full_loss(stacked, hp, xx):
            h = xx
            for i in range(n_all):
                p_i = jax.tree.map(lambda t, i=i: t[i], stacked)
                h = stage_fn(p_i, h)
            return head_fn(hp, h, labels)

        loss, (d_st, d_hp, d_x) = jax.value_and_grad(
            full_loss, argnums=(0, 1, 2))(stacked_params, head_params, x)
        return loss, d_st, d_hp, d_x

    stacked_n = int(jax.tree.leaves(stacked_params)[0].shape[0])
    if stacked_n != n_stages:
        raise ValueError(
            f"stacked stage dim {stacked_n} != pp axis size {n_stages}")
    batch = x.shape[0]
    n_micro = n_micro or n_stages
    if batch % n_micro != 0:
        raise ValueError(f"batch {batch} not divisible by n_micro {n_micro}")
    mb = batch // n_micro
    # ZBH1 keeps every microbatch input for the post-scan W pass; plain
    # 1F1B only needs the 2S-1 in-flight inputs (slots reused modulo);
    # eager's slack scheduling stretches a slot's lifetime to 4(S-s)-3
    if defer_weight_grads:
        buf_n = n_micro
    elif eager:
        buf_n = min(n_micro, 4 * n_stages - 3)
    else:
        buf_n = 2 * n_stages
    inv_m = 1.0 / n_micro
    coop = head_specs is not None
    hp_specs = head_specs if coop else jax.tree.map(
        lambda _: P(), head_params)

    @partial(shard_map, mesh=mesh, axis_names={axis},
             in_specs=(P(axis), hp_specs, P(), P()),
             out_specs=(P(), P(axis), hp_specs, P()))
    def run(params_local, head_p, xg, lbg):
        p_stage = jax.tree.map(lambda t: t[0], params_local)
        # make REPLICATED head params VARYING before differentiating: the
        # cotangent of an unvaried input gets an automatic psum over the
        # manual axis, which would leak every rank's (masked-garbage)
        # head gradients into the last stage's accumulation. Leaves whose
        # spec mentions the axis arrive sharded (already varying).
        head_p = jax.tree.map(
            lambda a, s: a if axis in jax.tree.leaves(tuple(s))
            else _to_varying(a, axis), head_p, hp_specs)
        sid = jax.lax.axis_index(axis)
        is_first = sid == 0
        is_last = sid == n_stages - 1
        micro_x = xg.reshape((n_micro, mb) + xg.shape[1:])
        micro_lb = lbg.reshape((n_micro, mb) + lbg.shape[1:])
        # per-stage tick offsets of the schedule (eager doubles the
        # stride so every boundary has one tick of comm slack)
        f_off = 2 * sid if eager else sid
        b_off = (4 * n_stages - 4 - 2 * sid) if eager \
            else (2 * n_stages - 1 - sid)
        h_off = (2 * n_stages - 2) if eager else n_stages
        t_total = n_micro + (4 * n_stages - 4 if eager
                             else 2 * n_stages - 1)
        fwd_perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]
        bwd_perm = [(i, (i - 1) % n_stages) for i in range(n_stages)]

        def masked_add(acc, g, active):
            return jax.tree.map(
                lambda a, gg: a + jnp.where(active, gg, 0).astype(a.dtype),
                acc, g)

        def run_head(head_p, y2, t):
            """One head evaluation + vjp. Cooperative mode: the head's
            microbatch is the LAST rank's backward microbatch, its hidden
            is broadcast from the last rank, and every rank computes its
            own vocab shard's piece (head_fn psum-combines internally)."""
            if coop:
                i_h = t - h_off  # the last rank's i_b
                act_h = (i_h >= 0) & (i_h < n_micro)
                ih_c = jnp.clip(i_h, 0, n_micro - 1)
                h_in = jax.lax.psum(
                    jnp.where(is_last, y2, jnp.zeros_like(y2)), axis)
                lb_mb = micro_lb[ih_c]
            else:
                i_b = t - b_off
                act_h = (i_b >= 0) & (i_b < n_micro)
                ih_c = jnp.clip(i_b, 0, n_micro - 1)
                h_in = y2
                lb_mb = micro_lb[ih_c]
            loss_i, vjp_head = jax.vjp(
                lambda hp, yy: head_fn(hp, yy, lb_mb), head_p, h_in)
            dhp_i, dy_head = vjp_head(
                _to_varying(jnp.asarray(inv_m, loss_i.dtype), axis))
            if coop:
                # each rank's dy is its shard's partial: sum them
                dy_head = jax.lax.psum(dy_head, axis)
            return loss_i, dhp_i, dy_head, act_h

        def tick(carry, t):
            if eager:
                (fwd_bnd, fwd_rdy, bwd_bnd, bwd_rdy, in_buf, dy_buf, dp,
                 dhp, dx_buf, loss) = carry
            else:
                fwd_bnd, bwd_bnd, in_buf, dy_buf, dp, dhp, dx_buf, \
                    loss = carry
                fwd_rdy, bwd_rdy = fwd_bnd, bwd_bnd

            # ---- forward slot: stage `sid` forwards microbatch i_f ----
            i_f = t - f_off
            act_f = (i_f >= 0) & (i_f < n_micro)
            if_c = jnp.clip(i_f, 0, n_micro - 1)
            x_in = jnp.where(is_first, micro_x[if_c], fwd_rdy)
            y = stage_fn(p_stage, x_in)
            y = jnp.where(act_f, y, jnp.zeros_like(y))
            slot_f = if_c % buf_n
            in_buf = in_buf.at[slot_f].set(
                jnp.where(act_f, x_in, in_buf[slot_f]))

            # ---- backward slot: stage `sid` backwards microbatch i_b ----
            i_b = t - b_off
            act_b = (i_b >= 0) & (i_b < n_micro)
            ib_c = jnp.clip(i_b, 0, n_micro - 1)
            x_sv = in_buf[ib_c % buf_n]
            if defer_weight_grads:
                # ZBH1: activation-grad only — the weight part of this
                # vjp happens once, batched, after the scan
                y2, vjp_x = jax.vjp(
                    lambda xx: stage_fn(p_stage, xx), x_sv)
            else:
                y2, vjp_stage = jax.vjp(stage_fn, p_stage, x_sv)
            loss_i, dhp_i, dy_head, act_h = run_head(head_p, y2, t)
            dy_in = jnp.where(is_last, dy_head.astype(bwd_rdy.dtype),
                              bwd_rdy)
            if defer_weight_grads:
                (dx,) = vjp_x(dy_in)
                dy_buf = dy_buf.at[ib_c].set(
                    jnp.where(act_b, dy_in.astype(dy_buf.dtype),
                              dy_buf[ib_c]))
            else:
                dp_i, dx = vjp_stage(dy_in)
                dp = masked_add(dp, dp_i, act_b)
            dhp = masked_add(dhp, dhp_i,
                             act_h if coop else (act_b & is_last))
            loss = loss + jnp.where(
                (act_h if coop else act_b) & is_last,
                loss_i.astype(loss.dtype) * inv_m, 0.0)
            dx_buf = dx_buf.at[ib_c].set(
                jnp.where(act_b & is_first, dx.astype(dx_buf.dtype),
                          dx_buf[ib_c]))

            # ---- boundary exchange for the next tick ----
            fwd_new = jax.lax.ppermute(y, axis, fwd_perm)
            bwd_new = jax.lax.ppermute(
                jnp.where(act_b, dx, jnp.zeros_like(dx)), axis, bwd_perm)
            if eager:
                # received boundaries rest one tick before consumption —
                # the slack XLA overlaps the collective-permute into
                return (fwd_new, fwd_bnd, bwd_new, bwd_bnd, in_buf,
                        dy_buf, dp, dhp, dx_buf, loss), None
            return (fwd_new, bwd_new, in_buf, dy_buf, dp, dhp, dx_buf,
                    loss), None

        act_shape = (mb,) + xg.shape[1:]
        vary = lambda z: _to_varying(z, axis)
        dy_slots = buf_n if defer_weight_grads else 1  # 1: placeholder
        carry0 = (
            vary(jnp.zeros(act_shape, xg.dtype)),               # fwd_bnd
            *((vary(jnp.zeros(act_shape, xg.dtype)),)           # fwd_rdy
              if eager else ()),
            vary(jnp.zeros(act_shape, xg.dtype)),               # bwd_bnd
            *((vary(jnp.zeros(act_shape, xg.dtype)),)           # bwd_rdy
              if eager else ()),
            vary(jnp.zeros((buf_n,) + act_shape, xg.dtype)),    # in_buf
            vary(jnp.zeros((dy_slots,) + act_shape, xg.dtype)),  # dy_buf
            # ZBH1 computes dp post-scan: don't carry a param-sized zero
            vary(jnp.zeros((), jnp.float32)) if defer_weight_grads else
            jax.tree.map(
                lambda a: vary(jnp.zeros(a.shape, jnp.float32)), p_stage),
            jax.tree.map(
                lambda a: vary(jnp.zeros(a.shape, jnp.float32)), head_p),
            vary(jnp.zeros((n_micro,) + act_shape, jnp.float32)),  # dx
            vary(jnp.zeros((), jnp.float32)),                   # loss
        )
        carry, _ = jax.lax.scan(tick, carry0, jnp.arange(t_total))
        in_buf, dy_buf, dp, dhp, dx_buf, loss = carry[-6:]
        if defer_weight_grads:
            # ZBH1 W pass: all microbatches' weight grads in ONE batched
            # vjp (recompute-forward per microbatch, like the in-tick
            # backward would have done — just batched and off the
            # critical path)
            def wgrad(x_i, dy_i):
                _, vjp_p = jax.vjp(lambda pp: stage_fn(pp, x_i), p_stage)
                return vjp_p(dy_i)[0]

            dps = jax.vmap(wgrad)(in_buf, dy_buf)
            dp = jax.tree.map(
                lambda g: g.astype(jnp.float32).sum(axis=0), dps)
        d_stacked = jax.tree.map(lambda a: a[None], dp)
        if coop:
            # sharded head leaves already hold exactly their shard's grad;
            # replicated leaves (e.g. the final norm) hold partials
            d_head = jax.tree.map(
                lambda a, s: a if axis in jax.tree.leaves(tuple(s))
                else jax.lax.psum(a, axis), dhp, hp_specs)
        else:
            d_head = jax.tree.map(lambda a: jax.lax.psum(a, axis), dhp)
        d_x = jax.lax.psum(dx_buf, axis).reshape((batch,) + xg.shape[1:])
        return jax.lax.psum(loss, axis), d_stacked, d_head, d_x

    return run(stacked_params, head_params, x, labels)
