"""SPMD pipeline parallelism: microbatch loop over a `pp` mesh axis.

Reference: fleet/meta_parallel/pipeline_parallel.py (1F1B train_batch :697,
forward_backward_pipeline :459) and the static pipeline_scheduler passes
(FThenB/1F1B/VPP/ZB). There, stages are separate processes exchanging
activations via NCCL p2p (pp_utils/p2p_communication.py batch_isend_irecv).

TPU-native: ONE program under `jax.shard_map` over the `pp` axis. The stage
dimension of the stacked layer parameters is sharded over `pp`, so each
device holds its stage's weights. The schedule is a `lax.scan` over
T = n_micro + n_stages - 1 ticks; each tick every stage processes one
microbatch slot and the boundary activation moves to the next stage with
`lax.ppermute` — the classic collective-permute pipeline from the public
scaling playbook. Autodiff through scan+ppermute gives the backward
schedule for free (fwd-then-bwd, GPipe-equivalent bubble profile; the
1F1B/ZB memory refinements are schedule *passes* in the reference and are
future work here).

Because everything is one XLA program, this composes with dp/mp/sharding
axes of the same mesh: the non-pp axes partition the per-stage math.
"""
from __future__ import annotations

from functools import partial
from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from . import mesh as mesh_mod

__all__ = ["pipeline_forward", "stack_stage_params", "unstack_stage_params"]


def _to_varying(x, axis):
    """Mark x as varying over the manual axis (scan-carry requirement)."""
    if hasattr(jax.lax, "pcast"):
        return jax.lax.pcast(x, axis, to="varying")
    return jax.lax.pvary(x, axis)


def stack_stage_params(per_stage_params: list, mesh: Optional[Mesh] = None,
                       axis: str = "pp"):
    """Stack a list of per-stage pytrees along a new leading stage dim and
    shard that dim over `axis` (each pp rank stores only its stage's
    weights — the pp analog of ZeRO partitioning)."""
    stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *per_stage_params)
    mesh = mesh or mesh_mod.get_global_mesh()
    if mesh is not None and axis in mesh.axis_names:
        def put(x):
            spec = [axis] + [None] * (x.ndim - 1)
            return jax.device_put(x, NamedSharding(mesh, P(*spec)))

        stacked = jax.tree.map(put, stacked)
    return stacked


def unstack_stage_params(stacked, n_stages: int):
    return [jax.tree.map(lambda x, i=i: x[i], stacked)
            for i in range(n_stages)]


def pipeline_forward(stage_fn: Callable, stacked_params, x, *,
                     mesh: Optional[Mesh] = None, axis: str = "pp",
                     n_micro: Optional[int] = None):
    """Run x through n_stages pipeline stages with microbatching.

    stage_fn(stage_params, h) -> h  (the per-stage computation; it may use
    other mesh axes internally — their sharding propagates through
    shard_map via the residual spec being Replicated on `axis` only).

    x: [batch, ...] global input activations (already embedded);
    returns [batch, ...] output of the last stage, replicated over `axis`.
    """
    mesh = mesh or mesh_mod.get_global_mesh()
    if mesh is None or axis not in mesh.axis_names \
            or int(mesh.shape[axis]) == 1:
        # degenerate: run stages sequentially in one program
        n_stages = jax.tree.leaves(stacked_params)[0].shape[0]
        h = x
        for i in range(n_stages):
            p_i = jax.tree.map(lambda t, i=i: t[i], stacked_params)
            h = stage_fn(p_i, h)
        return h

    n_stages = int(mesh.shape[axis])
    stacked_n = int(jax.tree.leaves(stacked_params)[0].shape[0])
    if stacked_n != n_stages:
        raise ValueError(
            f"stacked stage dim {stacked_n} != pp axis size {n_stages}; "
            f"group layers into exactly one block per pp rank")
    batch = x.shape[0]
    n_micro = n_micro or n_stages
    if batch % n_micro != 0:
        raise ValueError(f"batch {batch} not divisible by n_micro {n_micro}")
    mb = batch // n_micro

    # manual only over `axis`: the other mesh axes stay "auto" so TP/FSDP
    # shardings of the per-stage weights keep working inside the body
    # (check_vma must stay on — partial-manual mode relies on it)
    @partial(jax.shard_map, mesh=mesh, axis_names={axis},
             in_specs=(P(axis), P()), out_specs=P())
    def run(params_local, xg):
        # params_local: stage dim reduced to 1 on this rank
        p_stage = jax.tree.map(lambda t: t[0], params_local)
        stage_id = jax.lax.axis_index(axis)
        micro = xg.reshape((n_micro, mb) + xg.shape[1:])

        t_total = n_micro + n_stages - 1
        perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

        def tick(carry, t):
            boundary, outputs = carry
            # microbatch index this stage works on at tick t
            mb_idx = t - stage_id
            active = (mb_idx >= 0) & (mb_idx < n_micro)
            # stage 0 reads its microbatch; others read the boundary
            # activation received from the previous stage
            x_in = jnp.where(
                stage_id == 0,
                micro[jnp.clip(mb_idx, 0, n_micro - 1)],
                boundary)
            y = stage_fn(p_stage, x_in)
            y = jnp.where(active, y, jnp.zeros_like(y))
            # last stage records its finished microbatch
            outputs = jnp.where(
                (stage_id == n_stages - 1) & active,
                outputs.at[jnp.clip(mb_idx, 0, n_micro - 1)].set(y),
                outputs)
            # activation moves stage s -> s+1 for the next tick
            boundary = jax.lax.ppermute(y, axis, perm)
            return (boundary, outputs), None

        boundary0 = _to_varying(
            jnp.zeros((mb,) + xg.shape[1:], xg.dtype), axis)
        outputs0 = _to_varying(
            jnp.zeros((n_micro, mb) + xg.shape[1:], xg.dtype), axis)
        (boundary, outputs), _ = jax.lax.scan(
            tick, (boundary0, outputs0), jnp.arange(t_total))
        out = outputs.reshape((batch,) + xg.shape[1:])
        # every rank returns the same value: broadcast the last stage's
        # outputs (psum over one-hot mask keeps it differentiable)
        mask = (stage_id == n_stages - 1).astype(out.dtype)
        return jax.lax.psum(out * mask, axis)

    return run(stacked_params, x)
